// Package loader + arena-planned inference runner (reference
// libVeles workflow_loader.cc:41, workflow.cc:73-158 roles, fresh
// implementation for the tar/contents.json package of
// veles_tpu/package.py).
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "unit.h"

namespace veles_native {

class NativeWorkflow {
 public:
  // Loads a package tar; builds units via the UUID factory.
  explicit NativeWorkflow(const std::string& path);
  ~NativeWorkflow();

  // Plans the arena for `batch` samples (idempotent per batch size).
  void Initialize(int batch);

  // Runs the chain; in has batch*input_size floats, out receives
  // batch*output_size.
  void Run(const float* in, float* out, int batch);

  int64_t input_size() const { return NumElements(input_shape_); }
  int64_t output_size() const;
  int64_t arena_size() const { return arena_size_; }
  size_t unit_count() const { return units_.size(); }
  const Shape& input_shape() const { return input_shape_; }

 private:
  std::unique_ptr<class Engine> engine_;
  std::vector<std::unique_ptr<Unit>> units_;
  std::vector<Shape> stage_shapes_;   // per-stage sample shapes
  std::vector<int64_t> offsets_;      // per-stage output offsets
  std::vector<char> arena_;
  int64_t arena_size_ = 0;
  int planned_batch_ = -1;
  Shape input_shape_;
};

}  // namespace veles_native
