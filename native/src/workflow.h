// Package loader + arena-planned inference runner (reference
// libVeles workflow_loader.cc:41,73-120 roles — general DAG with
// dependency-ordered construction — fresh implementation for the
// tar/contents.json package of veles_tpu/package.py).
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "unit.h"

namespace veles_native {

class NativeWorkflow {
 public:
  // Loads a package tar; builds units via the UUID factory.  Format 2
  // packages carry explicit unit names + input links (general DAG);
  // format 1 packages are treated as a linear chain.
  explicit NativeWorkflow(const std::string& path);
  ~NativeWorkflow();

  // Plans the arena for `batch` samples (idempotent per batch size).
  void Initialize(int batch);

  // Runs the graph; in has batch*input_size floats, out receives
  // batch*output_size.
  void Run(const float* in, float* out, int batch);

  int64_t input_size() const { return NumElements(input_shape_); }
  int64_t output_size() const;
  int64_t arena_size() const { return arena_size_; }
  size_t unit_count() const { return nodes_.size(); }
  const Shape& input_shape() const { return input_shape_; }

 private:
  struct Node {
    std::unique_ptr<Unit> unit;
    std::vector<int> inputs;  // producer node index; -1 = graph input
    Shape out_shape;          // sample shape (no batch)
    int level = 0;            // dependency wavefront index
    int last_use_level = 0;   // level of the last reader
  };

  void BuildShapes();

  std::unique_ptr<class Engine> engine_;
  std::vector<Node> nodes_;       // in topological (execution) order
  std::vector<std::vector<int>> levels_;  // dependency wavefronts
  int output_node_ = -1;
  std::vector<int64_t> offsets_;  // per-node output offset in arena
  std::vector<char> arena_;
  int64_t arena_size_ = 0;
  int planned_batch_ = -1;
  Shape input_shape_;
};

}  // namespace veles_native
