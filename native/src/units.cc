// Standard inference units: fully-connected family, conv, pooling,
// standalone activations.  Math matches veles_tpu/models exactly (same
// scaled-tanh constants, softplus RELU, ceil-mode pooling).
#include <algorithm>
#include <cmath>
#include <cstring>

#include "unit.h"

namespace veles_native {

namespace {

enum class Act { kLinear, kTanh, kRelu, kStrictRelu, kSigmoid, kSoftmax };

inline float Activate(Act act, float z) {
  switch (act) {
    case Act::kTanh:
      return 1.7159f * std::tanh(0.6666f * z);
    case Act::kRelu:
      return z > 15.0f ? z : std::log1p(std::exp(std::min(z, 15.0f)));
    case Act::kStrictRelu:
      return z > 0 ? z : 0;
    case Act::kSigmoid:
      return 1.0f / (1.0f + std::exp(-z));
    default:
      return z;
  }
}

// ---------------------------------------------------------------- all2all

class All2AllUnit : public Unit {
 public:
  explicit All2AllUnit(Act act) : act_(act) {}

  void Setup(const JsonValue& props,
             std::map<std::string, NpyArray> arrays) override {
    weights_ = std::move(arrays.at("weights"));
    include_bias_ = props.Has("include_bias") &&
                    props["include_bias"].bool_value;
    if (include_bias_) bias_ = std::move(arrays.at("bias"));
    fan_in_ = weights_.shape[0];
    fan_out_ = weights_.shape[1];
  }

  Shape OutputShape(const Shape& input_shape) const override {
    if (NumElements(input_shape) != fan_in_)
      throw Error("all2all: input size mismatch");
    return {fan_out_};
  }

  void Run(const float* in, float* out, int batch,
           const Shape&) const override {
    // blocked GEMM: out[b, o] = sum_i in[b, i] * W[i, o]
    const int64_t kBlock = 64;
    for (int b = 0; b < batch; ++b) {
      float* row = out + b * fan_out_;
      const float* x = in + b * fan_in_;
      for (int64_t o = 0; o < fan_out_; ++o)
        row[o] = include_bias_ ? bias_.data[o] : 0.0f;
      for (int64_t i0 = 0; i0 < fan_in_; i0 += kBlock) {
        int64_t i1 = std::min(i0 + kBlock, fan_in_);
        for (int64_t i = i0; i < i1; ++i) {
          float xi = x[i];
          const float* wrow = weights_.data.data() + i * fan_out_;
          for (int64_t o = 0; o < fan_out_; ++o) row[o] += xi * wrow[o];
        }
      }
      if (act_ == Act::kSoftmax) {
        float mx = row[0];
        for (int64_t o = 1; o < fan_out_; ++o) mx = std::max(mx, row[o]);
        float sum = 0;
        for (int64_t o = 0; o < fan_out_; ++o) {
          row[o] = std::exp(row[o] - mx);
          sum += row[o];
        }
        for (int64_t o = 0; o < fan_out_; ++o) row[o] /= sum;
      } else if (act_ != Act::kLinear) {
        for (int64_t o = 0; o < fan_out_; ++o)
          row[o] = Activate(act_, row[o]);
      }
    }
  }

 private:
  Act act_;
  NpyArray weights_, bias_;
  bool include_bias_ = false;
  int64_t fan_in_ = 0, fan_out_ = 0;
};

// ------------------------------------------------------------------- conv

class ConvUnit : public Unit {
 public:
  explicit ConvUnit(Act act) : act_(act) {}

  void Setup(const JsonValue& props,
             std::map<std::string, NpyArray> arrays) override {
    weights_ = std::move(arrays.at("weights"));  // HWIO
    include_bias_ = props.Has("include_bias") &&
                    props["include_bias"].bool_value;
    if (include_bias_) bias_ = std::move(arrays.at("bias"));
    ky_ = weights_.shape[0];
    kx_ = weights_.shape[1];
    in_ch_ = weights_.shape[2];
    n_kernels_ = weights_.shape[3];
    if (props.Has("sliding")) {
      sx_ = props["sliding"][0].AsInt();
      sy_ = props["sliding"][1].AsInt();
    }
    if (props.Has("padding")) {
      const auto& p = props["padding"].array;
      left_ = p[0].AsInt();
      top_ = p[1].AsInt();
      right_ = p[2].AsInt();
      bottom_ = p[3].AsInt();
    }
  }

  Shape OutputShape(const Shape& s) const override {
    int64_t h = s[0], w = s[1];
    int64_t out_h = (h + top_ + bottom_ - ky_) / sy_ + 1;
    int64_t out_w = (w + left_ + right_ - kx_) / sx_ + 1;
    return {out_h, out_w, n_kernels_};
  }

  void Run(const float* in, float* out, int batch,
           const Shape& s) const override {
    int64_t h = s[0], w = s[1];
    int64_t ch = s.size() > 2 ? s[2] : 1;
    Shape os = OutputShape(s);
    int64_t oh = os[0], ow = os[1];
    int64_t in_sample = h * w * ch, out_sample = oh * ow * n_kernels_;
    for (int b = 0; b < batch; ++b) {
      const float* img = in + b * in_sample;
      float* dst = out + b * out_sample;
      for (int64_t oy = 0; oy < oh; ++oy) {
        for (int64_t ox = 0; ox < ow; ++ox) {
          float* cell = dst + (oy * ow + ox) * n_kernels_;
          for (int64_t k = 0; k < n_kernels_; ++k)
            cell[k] = include_bias_ ? bias_.data[k] : 0.0f;
          for (int64_t fy = 0; fy < ky_; ++fy) {
            int64_t iy = oy * sy_ - top_ + fy;
            if (iy < 0 || iy >= h) continue;
            for (int64_t fx = 0; fx < kx_; ++fx) {
              int64_t ix = ox * sx_ - left_ + fx;
              if (ix < 0 || ix >= w) continue;
              const float* px = img + (iy * w + ix) * ch;
              const float* wk =
                  weights_.data.data() +
                  ((fy * kx_ + fx) * in_ch_) * n_kernels_;
              for (int64_t c = 0; c < ch; ++c)
                for (int64_t k = 0; k < n_kernels_; ++k)
                  cell[k] += px[c] * wk[c * n_kernels_ + k];
            }
          }
          for (int64_t k = 0; k < n_kernels_; ++k)
            cell[k] = Activate(act_, cell[k]);
        }
      }
    }
  }

 private:
  Act act_;
  NpyArray weights_, bias_;
  bool include_bias_ = false;
  int64_t kx_ = 1, ky_ = 1, in_ch_ = 1, n_kernels_ = 1;
  int64_t sx_ = 1, sy_ = 1;
  int64_t left_ = 0, top_ = 0, right_ = 0, bottom_ = 0;
};

// ---------------------------------------------------------------- pooling

enum class PoolKind { kMax, kAvg, kMaxAbs };

class PoolingUnit : public Unit {
 public:
  explicit PoolingUnit(PoolKind kind) : kind_(kind) {}

  void Setup(const JsonValue& props,
             std::map<std::string, NpyArray>) override {
    kx_ = props["kx"].AsInt();
    ky_ = props["ky"].AsInt();
    sx_ = kx_;
    sy_ = ky_;
    if (props.Has("sliding")) {
      sx_ = props["sliding"][0].AsInt();
      sy_ = props["sliding"][1].AsInt();
    }
  }

  static int64_t OutLen(int64_t n, int64_t k, int64_t s) {
    if (n <= k) return 1;
    return (n - k + s - 1) / s + 1;  // ceil mode, covers all input
  }

  Shape OutputShape(const Shape& s) const override {
    int64_t ch = s.size() > 2 ? s[2] : 1;
    return {OutLen(s[0], ky_, sy_), OutLen(s[1], kx_, sx_), ch};
  }

  void Run(const float* in, float* out, int batch,
           const Shape& s) const override {
    int64_t h = s[0], w = s[1], ch = s.size() > 2 ? s[2] : 1;
    Shape os = OutputShape(s);
    int64_t oh = os[0], ow = os[1];
    int64_t in_sample = h * w * ch, out_sample = oh * ow * ch;
    for (int b = 0; b < batch; ++b) {
      const float* img = in + b * in_sample;
      float* dst = out + b * out_sample;
      for (int64_t oy = 0; oy < oh; ++oy)
        for (int64_t ox = 0; ox < ow; ++ox)
          for (int64_t c = 0; c < ch; ++c) {
            float best = 0, best_abs = -1, sum = 0;
            bool first = true;
            for (int64_t fy = 0; fy < ky_; ++fy) {
              int64_t iy = oy * sy_ + fy;
              if (iy >= h) continue;
              for (int64_t fx = 0; fx < kx_; ++fx) {
                int64_t ix = ox * sx_ + fx;
                if (ix >= w) continue;
                float v = img[(iy * w + ix) * ch + c];
                sum += v;
                if (kind_ == PoolKind::kMax) {
                  if (first || v > best) best = v;
                } else if (kind_ == PoolKind::kMaxAbs) {
                  if (std::fabs(v) > best_abs) {
                    best_abs = std::fabs(v);
                    best = v;
                  }
                }
                first = false;
              }
            }
            float result;
            if (kind_ == PoolKind::kAvg)
              result = sum / static_cast<float>(kx_ * ky_);
            else
              result = best;
            dst[(oy * ow + ox) * ch + c] = result;
          }
    }
  }

 private:
  PoolKind kind_;
  int64_t kx_ = 2, ky_ = 2, sx_ = 2, sy_ = 2;
};

// ------------------------------------------------------------- activations

class ActivationUnit : public Unit {
 public:
  explicit ActivationUnit(Act act) : act_(act) {}

  void Setup(const JsonValue&, std::map<std::string, NpyArray>) override {}

  Shape OutputShape(const Shape& s) const override { return s; }

  void Run(const float* in, float* out, int batch,
           const Shape& s) const override {
    int64_t n = NumElements(s) * batch;
    for (int64_t i = 0; i < n; ++i) out[i] = Activate(act_, in[i]);
  }

 private:
  Act act_;
};

// ------------------------------------------------------------ input joiner

// Concatenates N flattened inputs along the feature axis (reference
// veles/input_joiner.py:49 role; DAG multi-input node).
class JoinUnit : public Unit {
 public:
  void Setup(const JsonValue&, std::map<std::string, NpyArray>) override {}

  Shape OutputShape(const Shape& s) const override { return s; }

  Shape OutputShapeMulti(const std::vector<Shape>& ins) const override {
    int64_t total = 0;
    for (const auto& s : ins) total += NumElements(s);
    return {total};
  }

  void Run(const float* in, float* out, int batch,
           const Shape& s) const override {
    std::memcpy(out, in, sizeof(float) * NumElements(s) * batch);
  }

  void RunMulti(const std::vector<const float*>& ins,
                const std::vector<Shape>& in_shapes, float* out,
                int batch) const override {
    int64_t out_sample = 0;
    for (const auto& s : in_shapes) out_sample += NumElements(s);
    for (int b = 0; b < batch; ++b) {
      float* dst = out + b * out_sample;
      for (size_t k = 0; k < ins.size(); ++k) {
        int64_t n = NumElements(in_shapes[k]);
        std::memcpy(dst, ins[k] + b * n, sizeof(float) * n);
        dst += n;
      }
    }
  }
};

}  // namespace

UnitFactory& UnitFactory::Instance() {
  static UnitFactory factory;
  return factory;
}

void UnitFactory::Register(const std::string& uuid, Creator creator) {
  creators_[uuid] = std::move(creator);
}

std::unique_ptr<Unit> UnitFactory::Create(const std::string& uuid) const {
  auto it = creators_.find(uuid);
  if (it == creators_.end()) throw Error("unknown unit uuid " + uuid);
  return it->second();
}

void RegisterStandardUnits() {
  static bool done = false;
  if (done) return;
  done = true;
  auto& f = UnitFactory::Instance();
  // UUIDs mirror veles_tpu/package.py UNIT_UUIDS
  auto a2a = [](Act act) {
    return [act]() -> std::unique_ptr<Unit> {
      return std::make_unique<All2AllUnit>(act);
    };
  };
  f.Register("5a51b268-0001-4000-8000-76656c6573aa", a2a(Act::kLinear));
  f.Register("5a51b268-0002-4000-8000-76656c6573aa", a2a(Act::kTanh));
  f.Register("5a51b268-0003-4000-8000-76656c6573aa", a2a(Act::kRelu));
  f.Register("5a51b268-0004-4000-8000-76656c6573aa",
             a2a(Act::kStrictRelu));
  f.Register("5a51b268-0005-4000-8000-76656c6573aa", a2a(Act::kSigmoid));
  f.Register("5a51b268-0006-4000-8000-76656c6573aa", a2a(Act::kSoftmax));
  auto conv = [](Act act) {
    return [act]() -> std::unique_ptr<Unit> {
      return std::make_unique<ConvUnit>(act);
    };
  };
  f.Register("5a51b268-0011-4000-8000-76656c6573aa", conv(Act::kLinear));
  f.Register("5a51b268-0012-4000-8000-76656c6573aa", conv(Act::kTanh));
  f.Register("5a51b268-0013-4000-8000-76656c6573aa", conv(Act::kRelu));
  f.Register("5a51b268-0014-4000-8000-76656c6573aa",
             conv(Act::kStrictRelu));
  f.Register("5a51b268-0015-4000-8000-76656c6573aa",
             conv(Act::kSigmoid));
  auto pool = [](PoolKind kind) {
    return [kind]() -> std::unique_ptr<Unit> {
      return std::make_unique<PoolingUnit>(kind);
    };
  };
  f.Register("5a51b268-0021-4000-8000-76656c6573aa", pool(PoolKind::kMax));
  f.Register("5a51b268-0022-4000-8000-76656c6573aa", pool(PoolKind::kAvg));
  f.Register("5a51b268-0023-4000-8000-76656c6573aa",
             pool(PoolKind::kMaxAbs));
  auto act_unit = [](Act act) {
    return [act]() -> std::unique_ptr<Unit> {
      return std::make_unique<ActivationUnit>(act);
    };
  };
  f.Register("5a51b268-0031-4000-8000-76656c6573aa",
             act_unit(Act::kTanh));
  f.Register("5a51b268-0032-4000-8000-76656c6573aa",
             act_unit(Act::kRelu));
  f.Register("5a51b268-0033-4000-8000-76656c6573aa",
             act_unit(Act::kStrictRelu));
  f.Register("5a51b268-0034-4000-8000-76656c6573aa",
             act_unit(Act::kSigmoid));
  f.Register("5a51b268-0041-4000-8000-76656c6573aa",
             []() -> std::unique_ptr<Unit> {
               return std::make_unique<JoinUnit>();
             });
}

}  // namespace veles_native
