// Greedy strip-packing arena planner (reference
// libVeles/src/memory_optimizer.cc:38-98 behavior, fresh
// implementation): every buffer has a byte size and a [first_use,
// last_use] step interval; buffers are placed at the lowest arena
// offset whose occupied intervals don't overlap in time, largest
// first.  Returns per-buffer offsets and the total arena size.
#pragma once

#include <cstdint>
#include <vector>

namespace veles_native {

struct BufferRequest {
  int64_t size;        // bytes
  int first_use;       // step index producing it
  int last_use;        // last step reading it
};

struct BufferPlacement {
  int64_t offset;
};

// Returns placements (same order as requests) + sets *arena_size.
std::vector<BufferPlacement> PlanArena(
    const std::vector<BufferRequest>& requests, int64_t* arena_size,
    int64_t alignment = 64);

}  // namespace veles_native
