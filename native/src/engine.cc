#include "engine.h"

#include <algorithm>
#include <atomic>

namespace veles_native {

Engine::Engine(int workers) {
  if (workers <= 0)
    workers = std::max(1u, std::thread::hardware_concurrency());
  for (int i = 0; i < workers; ++i)
    threads_.emplace_back([this] { WorkerLoop(); });
}

Engine::~Engine() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void Engine::WorkerLoop() {
  while (true) {
    std::function<void()> task;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
      if (stop_ && queue_.empty()) return;
      task = std::move(queue_.front());
      queue_.pop();
    }
    task();
  }
}

void Engine::ParallelFor(int total,
                         const std::function<void(int, int)>& fn) {
  int n = workers();
  int chunk = (total + n - 1) / n;
  std::atomic<int> remaining{0};
  std::mutex done_mutex;
  std::condition_variable done_cv;

  for (int start = 0; start < total; start += chunk) {
    int count = std::min(chunk, total - start);
    ++remaining;
    {
      std::lock_guard<std::mutex> lock(mutex_);
      queue_.push([&, start, count] {
        fn(start, count);
        if (--remaining == 0) {
          std::lock_guard<std::mutex> dl(done_mutex);
          done_cv.notify_all();
        }
      });
    }
    cv_.notify_one();
  }
  std::unique_lock<std::mutex> lock(done_mutex);
  done_cv.wait(lock, [&] { return remaining.load() == 0; });
}

}  // namespace veles_native
