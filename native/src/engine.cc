#include "engine.h"

#include <algorithm>

namespace veles_native {

Engine::Engine(int workers) {
  if (workers <= 0)
    workers = std::max(1u, std::thread::hardware_concurrency());
  for (int i = 0; i < workers; ++i)
    threads_.emplace_back([this] { WorkerLoop(); });
}

Engine::~Engine() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void Engine::WorkerLoop() {
  while (true) {
    std::function<void()> task;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
      if (stop_ && queue_.empty()) return;
      task = std::move(queue_.front());
      queue_.pop();
    }
    task();
  }
}

void Engine::RunTasks(const std::vector<std::function<void()>>& tasks) {
  if (tasks.empty()) return;
  // completion count guarded by done_mutex (not an atomic): the waiter's
  // predicate can only turn true while a worker holds the mutex, so the
  // stack-allocated sync objects cannot be destroyed out from under a
  // worker that is still about to lock them
  int remaining = static_cast<int>(tasks.size());
  std::mutex done_mutex;
  std::condition_variable done_cv;

  {
    std::lock_guard<std::mutex> lock(mutex_);
    for (const auto& task : tasks) {
      queue_.push([&remaining, &done_mutex, &done_cv, &task] {
        task();
        std::lock_guard<std::mutex> dl(done_mutex);
        if (--remaining == 0) done_cv.notify_all();
      });
    }
  }
  cv_.notify_all();
  std::unique_lock<std::mutex> lock(done_mutex);
  done_cv.wait(lock, [&] { return remaining == 0; });
}
}  // namespace veles_native
