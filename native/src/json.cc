#include "json.h"

#include <cctype>
#include <cstdlib>

#include "common.h"

namespace veles_native {

namespace {

class Parser {
 public:
  explicit Parser(const std::string& text) : text_(text) {}

  JsonValue Parse() {
    JsonValue v = ParseValue();
    SkipWs();
    if (pos_ != text_.size()) throw Error("json: trailing garbage");
    return v;
  }

 private:
  void SkipWs() {
    while (pos_ < text_.size() &&
           std::isspace(static_cast<unsigned char>(text_[pos_])))
      ++pos_;
  }

  char Peek() {
    SkipWs();
    if (pos_ >= text_.size()) throw Error("json: unexpected end");
    return text_[pos_];
  }

  char Next() {
    char c = Peek();
    ++pos_;
    return c;
  }

  void Expect(char c) {
    if (Next() != c)
      throw Error(std::string("json: expected '") + c + "'");
  }

  JsonValue ParseValue() {
    // hostile deeply-nested input must error, not smash the stack
    if (++depth_ > kMaxDepth) throw Error("json: nesting too deep");
    char c = Peek();
    JsonValue v;
    switch (c) {
      case '{': v = ParseObject(); break;
      case '[': v = ParseArray(); break;
      case '"': v = ParseString(); break;
      case 't': case 'f': v = ParseBool(); break;
      case 'n': v = ParseNull(); break;
      default:  v = ParseNumber(); break;
    }
    --depth_;
    return v;
  }

  JsonValue ParseObject() {
    JsonValue v;
    v.type = JsonValue::kObject;
    Expect('{');
    if (Peek() == '}') { ++pos_; return v; }
    while (true) {
      JsonValue key = ParseString();
      Expect(':');
      v.object[key.str] = ParseValue();
      char c = Next();
      if (c == '}') break;
      if (c != ',') throw Error("json: expected ',' in object");
    }
    return v;
  }

  JsonValue ParseArray() {
    JsonValue v;
    v.type = JsonValue::kArray;
    Expect('[');
    if (Peek() == ']') { ++pos_; return v; }
    while (true) {
      v.array.push_back(ParseValue());
      char c = Next();
      if (c == ']') break;
      if (c != ',') throw Error("json: expected ',' in array");
    }
    return v;
  }

  JsonValue ParseString() {
    JsonValue v;
    v.type = JsonValue::kString;
    Expect('"');
    while (true) {
      if (pos_ >= text_.size()) throw Error("json: unterminated string");
      char c = text_[pos_++];
      if (c == '"') break;
      if (c == '\\') {
        if (pos_ >= text_.size()) throw Error("json: bad escape");
        char e = text_[pos_++];
        switch (e) {
          case 'n': v.str += '\n'; break;
          case 't': v.str += '\t'; break;
          case 'r': v.str += '\r'; break;
          case 'b': v.str += '\b'; break;
          case 'f': v.str += '\f'; break;
          case 'u': {  // basic BMP escapes only
            if (pos_ + 4 > text_.size()) throw Error("json: bad \\u");
            unsigned code = std::strtoul(
                text_.substr(pos_, 4).c_str(), nullptr, 16);
            pos_ += 4;
            if (code < 0x80) {
              v.str += static_cast<char>(code);
            } else if (code < 0x800) {
              v.str += static_cast<char>(0xC0 | (code >> 6));
              v.str += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              v.str += static_cast<char>(0xE0 | (code >> 12));
              v.str += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              v.str += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: v.str += e;
        }
      } else {
        v.str += c;
      }
    }
    return v;
  }

  JsonValue ParseBool() {
    JsonValue v;
    v.type = JsonValue::kBool;
    if (text_.compare(pos_, 4, "true") == 0) {
      v.bool_value = true;
      pos_ += 4;
    } else if (text_.compare(pos_, 5, "false") == 0) {
      v.bool_value = false;
      pos_ += 5;
    } else {
      throw Error("json: bad literal");
    }
    return v;
  }

  JsonValue ParseNull() {
    if (text_.compare(pos_, 4, "null") != 0)
      throw Error("json: bad literal");
    pos_ += 4;
    return JsonValue();
  }

  JsonValue ParseNumber() {
    size_t start = pos_;
    while (pos_ < text_.size() &&
           (std::isdigit(static_cast<unsigned char>(text_[pos_])) ||
            text_[pos_] == '-' || text_[pos_] == '+' ||
            text_[pos_] == '.' || text_[pos_] == 'e' ||
            text_[pos_] == 'E'))
      ++pos_;
    if (start == pos_) throw Error("json: bad number");
    JsonValue v;
    v.type = JsonValue::kNumber;
    v.number = std::strtod(text_.substr(start, pos_ - start).c_str(),
                           nullptr);
    return v;
  }

  static constexpr int kMaxDepth = 256;
  const std::string& text_;
  size_t pos_ = 0;
  int depth_ = 0;
};

}  // namespace

const JsonValue& JsonValue::operator[](const std::string& key) const {
  auto it = object.find(key);
  if (it == object.end()) throw Error("json: missing key " + key);
  return it->second;
}

const JsonValue& JsonValue::operator[](size_t index) const {
  if (index >= array.size()) throw Error("json: index out of range");
  return array[index];
}

JsonValue ParseJson(const std::string& text) {
  return Parser(text).Parse();
}

}  // namespace veles_native
