#include "workflow.h"

#include <cstring>

#include "engine.h"
#include "memory_optimizer.h"
#include "tar.h"

namespace veles_native {

NativeWorkflow::~NativeWorkflow() = default;

NativeWorkflow::NativeWorkflow(const std::string& path) {
  RegisterStandardUnits();
  TarFile tar(path);
  const auto& cj = tar.Get("contents.json");
  JsonValue contents = ParseJson(std::string(cj.begin(), cj.end()));

  if (contents.Has("input_shape") && !contents["input_shape"].IsNull())
    for (const auto& d : contents["input_shape"].array)
      input_shape_.push_back(d.AsInt());

  for (const auto& uj : contents["units"].array) {
    auto unit = UnitFactory::Instance().Create(uj["uuid"].str);
    unit->set_name(uj["class"].str);
    std::map<std::string, NpyArray> arrays;
    if (uj.Has("arrays"))
      for (const auto& kv : uj["arrays"].object)
        arrays[kv.first] = LoadNpy(tar.Get(kv.second.str));
    unit->Setup(uj["properties"], std::move(arrays));
    units_.push_back(std::move(unit));
  }
  if (units_.empty()) throw Error("package has no units");

  // propagate shapes through the chain
  stage_shapes_.push_back(input_shape_);
  Shape cur = input_shape_;
  for (const auto& unit : units_) {
    cur = unit->OutputShape(cur);
    stage_shapes_.push_back(cur);
  }
}

int64_t NativeWorkflow::output_size() const {
  return NumElements(stage_shapes_.back());
}

void NativeWorkflow::Initialize(int batch) {
  if (planned_batch_ == batch) return;
  // One buffer per stage output; stage i's output is produced at step i
  // and last read at step i+1 (linear inference chain).  The planner
  // lets non-adjacent buffers share arena bytes, which is the whole
  // point of the reference's strip packing.
  std::vector<BufferRequest> requests;
  int n = static_cast<int>(units_.size());
  for (int i = 0; i < n; ++i) {
    int64_t bytes =
        NumElements(stage_shapes_[i + 1]) * batch * sizeof(float);
    requests.push_back({bytes, i, std::min(i + 1, n - 1)});
  }
  auto placements = PlanArena(requests, &arena_size_);
  offsets_.clear();
  for (const auto& p : placements) offsets_.push_back(p.offset);
  arena_.resize(static_cast<size_t>(arena_size_));
  planned_batch_ = batch;
}

void NativeWorkflow::Run(const float* in, float* out, int batch) {
  Initialize(batch);
  if (!engine_) engine_ = std::make_unique<Engine>();
  const float* cur = in;
  int n = static_cast<int>(units_.size());
  for (int i = 0; i < n; ++i) {
    float* dst =
        (i == n - 1) ? out
                     : reinterpret_cast<float*>(arena_.data() + offsets_[i]);
    const Unit* unit = units_[i].get();
    const Shape& in_shape = stage_shapes_[i];
    int64_t in_sample = NumElements(in_shape);
    int64_t out_sample = NumElements(stage_shapes_[i + 1]);
    // batch rows are independent: shard them over the engine workers
    engine_->ParallelFor(batch, [&](int start, int count) {
      unit->Run(cur + start * in_sample, dst + start * out_sample, count,
                in_shape);
    });
    cur = dst;
  }
}

}  // namespace veles_native
