#include "workflow.h"

#include <algorithm>
#include <cstring>
#include <map>

#include "engine.h"
#include "memory_optimizer.h"
#include "tar.h"

namespace veles_native {

NativeWorkflow::~NativeWorkflow() = default;

NativeWorkflow::NativeWorkflow(const std::string& path) {
  RegisterStandardUnits();
  TarFile tar(path);
  const auto& cj = tar.Get("contents.json");
  JsonValue contents = ParseJson(std::string(cj.begin(), cj.end()));

  if (contents.Has("input_shape") && !contents["input_shape"].IsNull())
    for (const auto& d : contents["input_shape"].array)
      input_shape_.push_back(d.AsInt());

  // pass 1: create every unit (flat; the factory resolves classes by
  // stable UUID), record names and declared input links
  struct Raw {
    std::unique_ptr<Unit> unit;
    std::vector<std::string> input_names;
  };
  std::vector<Raw> raw;
  std::map<std::string, int> by_name;
  int idx = 0;
  for (const auto& uj : contents["units"].array) {
    auto unit = UnitFactory::Instance().Create(uj["uuid"].str);
    unit->set_name(uj["class"].str);
    std::map<std::string, NpyArray> arrays;
    if (uj.Has("arrays"))
      for (const auto& kv : uj["arrays"].object)
        arrays[kv.first] = LoadNpy(tar.Get(kv.second.str));
    unit->Setup(uj["properties"], std::move(arrays));
    Raw r;
    r.unit = std::move(unit);
    if (uj.Has("inputs"))
      for (const auto& name : uj["inputs"].array)
        r.input_names.push_back(name.str);
    std::string name = uj.Has("name") ? uj["name"].str
                                      : std::to_string(idx);
    if (by_name.count(name))
      throw Error("duplicate unit name " + name);
    by_name[name] = idx++;
    raw.push_back(std::move(r));
  }
  if (raw.empty()) throw Error("package has no units");

  // pass 2: resolve links.  Format 1 (no "inputs") = linear chain.
  int n = static_cast<int>(raw.size());
  std::vector<std::vector<int>> inputs(n);
  for (int i = 0; i < n; ++i) {
    if (raw[i].input_names.empty()) {
      inputs[i] = {i == 0 ? -1 : i - 1};
      continue;
    }
    for (const auto& name : raw[i].input_names) {
      if (name == "__input__") {
        inputs[i].push_back(-1);
      } else {
        auto it = by_name.find(name);
        if (it == by_name.end())
          throw Error("unit input references unknown unit " + name);
        inputs[i].push_back(it->second);
      }
    }
  }

  // pass 3: topological order (iterative DFS) so shapes/buffers
  // propagate in dependency order whatever the package's unit order
  // was (reference workflow_loader.cc:73-120 behavior)
  std::vector<int> order, state(n, 0);  // 0 new, 1 visiting, 2 done
  std::vector<int> stack;
  for (int start = 0; start < n; ++start) {
    if (state[start]) continue;
    stack.push_back(start);
    while (!stack.empty()) {
      int u = stack.back();
      if (state[u] == 0) {
        state[u] = 1;
        for (int producer : inputs[u]) {
          if (producer < 0) continue;
          if (state[producer] == 1)
            throw Error("cycle in unit graph at " +
                        raw[u].unit->name());
          if (state[producer] == 0) stack.push_back(producer);
        }
      } else {
        stack.pop_back();
        if (state[u] == 1) {
          state[u] = 2;
          order.push_back(u);
        }
      }
    }
  }

  // emit nodes in topo order; remap link indices
  std::vector<int> pos(n, -1);
  for (size_t p = 0; p < order.size(); ++p)
    pos[order[p]] = static_cast<int>(p);
  nodes_.resize(n);
  for (int i = 0; i < n; ++i) {
    Node& node = nodes_[pos[i]];
    node.unit = std::move(raw[i].unit);
    for (int producer : inputs[i])
      node.inputs.push_back(producer < 0 ? -1 : pos[producer]);
  }

  // the graph output: exactly one node nobody consumes
  std::vector<bool> consumed(n, false);
  for (const auto& node : nodes_)
    for (int producer : node.inputs)
      if (producer >= 0) consumed[producer] = true;
  for (int i = 0; i < n; ++i) {
    if (consumed[i]) continue;
    if (output_node_ >= 0)
      throw Error("graph has multiple outputs (" +
                  nodes_[output_node_].unit->name() + ", " +
                  nodes_[i].unit->name() + ")");
    output_node_ = i;
  }
  if (output_node_ < 0) throw Error("graph has no output node");

  BuildShapes();
}

void NativeWorkflow::BuildShapes() {
  for (auto& node : nodes_) {
    std::vector<Shape> in_shapes;
    for (int producer : node.inputs)
      in_shapes.push_back(producer < 0 ? input_shape_
                                       : nodes_[producer].out_shape);
    node.out_shape = node.unit->OutputShapeMulti(in_shapes);
  }
  // dependency wavefronts: level(i) = 1 + max level over producers.
  // Nodes sharing a level have no path between them and run
  // concurrently on the engine (reference engine.h:43 scheduled
  // children when all parents finished; wavefronts are the static
  // equivalent for a graph known up front).
  levels_.clear();
  for (size_t i = 0; i < nodes_.size(); ++i) {
    int lvl = 0;
    for (int producer : nodes_[i].inputs)
      if (producer >= 0)
        lvl = std::max(lvl, nodes_[producer].level + 1);
    nodes_[i].level = lvl;
    if (static_cast<size_t>(lvl) >= levels_.size())
      levels_.resize(lvl + 1);
    levels_[lvl].push_back(static_cast<int>(i));
  }
  // liveness in LEVEL steps, the unit of temporal ordering under
  // wavefront execution (topo index would be wrong: two same-level
  // nodes run concurrently whatever their topo positions, so a
  // buffer must stay live through the whole last-reader level)
  for (size_t i = 0; i < nodes_.size(); ++i) {
    nodes_[i].last_use_level = nodes_[i].level;
    for (size_t j = i + 1; j < nodes_.size(); ++j)
      for (int producer : nodes_[j].inputs)
        if (producer == static_cast<int>(i))
          nodes_[i].last_use_level =
              std::max(nodes_[i].last_use_level, nodes_[j].level);
  }
}

int64_t NativeWorkflow::output_size() const {
  return NumElements(nodes_[output_node_].out_shape);
}

void NativeWorkflow::Initialize(int batch) {
  if (planned_batch_ == batch) return;
  // one buffer per node output, live [produce step, last consumer
  // step]; the strip-packing planner overlaps disjoint lifetimes —
  // the reference's memory_optimizer fed with REAL intervals from the
  // DAG instead of the linear-chain i/i+1 approximation
  std::vector<BufferRequest> requests;
  int n = static_cast<int>(nodes_.size());
  for (int i = 0; i < n; ++i) {
    int64_t bytes =
        NumElements(nodes_[i].out_shape) * batch * sizeof(float);
    if (i == output_node_) bytes = 0;  // written straight to out
    requests.push_back({bytes, nodes_[i].level, nodes_[i].last_use_level});
  }
  auto placements = PlanArena(requests, &arena_size_);
  offsets_.clear();
  for (const auto& p : placements) offsets_.push_back(p.offset);
  arena_.resize(static_cast<size_t>(arena_size_));
  planned_batch_ = batch;
}

void NativeWorkflow::Run(const float* in, float* out, int batch) {
  if (batch <= 0) return;  // empty minibatch: nothing to write
  Initialize(batch);
  if (!engine_) engine_ = std::make_unique<Engine>();
  int n = static_cast<int>(nodes_.size());

  // Per-node run context, stable across the deferred wavefront tasks.
  struct Ctx {
    std::vector<const float*> ins;
    std::vector<Shape> in_shapes;
    std::vector<int64_t> in_samples;
    float* dst = nullptr;
    int64_t out_sample = 0;
  };
  std::vector<Ctx> ctx(n);
  for (int i = 0; i < n; ++i) {
    const Node& node = nodes_[i];
    Ctx& c = ctx[i];
    c.dst = (i == output_node_)
                ? out
                : reinterpret_cast<float*>(arena_.data() + offsets_[i]);
    for (int producer : node.inputs) {
      c.ins.push_back(producer < 0
                          ? in
                          : reinterpret_cast<const float*>(
                                arena_.data() + offsets_[producer]));
      c.in_shapes.push_back(producer < 0 ? input_shape_
                                         : nodes_[producer].out_shape);
      c.in_samples.push_back(NumElements(c.in_shapes.back()));
    }
    c.out_sample = NumElements(node.out_shape);
  }

  // Two parallel axes per wavefront: every node in the level is
  // independent, and each node's batch rows are independent.  Chunk
  // rows so a level still fills the pool whatever its width.
  int workers = engine_->workers();
  for (const auto& level : levels_) {
    int width = static_cast<int>(level.size());
    int chunks_per_node =
        std::min(batch, std::max(1, (workers + width - 1) / width));
    int chunk = (batch + chunks_per_node - 1) / chunks_per_node;
    std::vector<std::function<void()>> tasks;
    for (int i : level) {
      const Node& node = nodes_[i];
      const Ctx& c = ctx[i];
      for (int start = 0; start < batch; start += chunk) {
        int count = std::min(chunk, batch - start);
        tasks.push_back([&node, &c, start, count] {
          std::vector<const float*> slice(c.ins);
          for (size_t k = 0; k < slice.size(); ++k)
            slice[k] += start * c.in_samples[k];
          node.unit->RunMulti(slice, c.in_shapes,
                              c.dst + start * c.out_sample, count);
        });
      }
    }
    engine_->RunTasks(tasks);
  }
}

}  // namespace veles_native
