#include "tar.h"

#include <cstring>
#include <fstream>

#include "common.h"

namespace veles_native {

namespace {

// ustar header is 512 bytes; fields are octal ASCII.
struct UstarHeader {
  char name[100];
  char mode[8];
  char uid[8];
  char gid[8];
  char size[12];
  char mtime[12];
  char chksum[8];
  char typeflag;
  char linkname[100];
  char magic[6];
  char version[2];
  char uname[32];
  char gname[32];
  char devmajor[8];
  char devminor[8];
  char prefix[155];
  char pad[12];
};

static_assert(sizeof(UstarHeader) == 512, "ustar header must be 512B");

int64_t ParseOctal(const char* field, size_t len) {
  int64_t value = 0;
  for (size_t i = 0; i < len && field[i]; ++i) {
    char c = field[i];
    if (c == ' ') continue;
    if (c < '0' || c > '7') break;
    value = value * 8 + (c - '0');
  }
  return value;
}

bool AllZero(const char* block) {
  for (int i = 0; i < 512; ++i)
    if (block[i]) return false;
  return true;
}

}  // namespace

TarFile::TarFile(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  if (!in) throw Error("cannot open package " + path);
  // hostile size fields must not drive multi-GB allocations: no
  // member can be larger than the archive that contains it
  in.seekg(0, std::ios::end);
  const int64_t file_size = static_cast<int64_t>(in.tellg());
  in.seekg(0, std::ios::beg);
  char block[512];
  while (in.read(block, 512)) {
    if (AllZero(block)) break;  // end-of-archive marker
    const auto* hdr = reinterpret_cast<const UstarHeader*>(block);
    int64_t size = ParseOctal(hdr->size, sizeof(hdr->size));
    if (size < 0 || size > file_size)
      throw Error("tar member size field exceeds archive size");
    std::string name(hdr->name, strnlen(hdr->name, sizeof(hdr->name)));
    if (hdr->typeflag == '0' || hdr->typeflag == '\0') {
      std::vector<char> data(static_cast<size_t>(size));
      if (size > 0 && !in.read(data.data(), size))
        throw Error("truncated tar member " + name);
      members_[name] = std::move(data);
    } else {
      in.seekg(size, std::ios::cur);  // skip non-regular members
    }
    // advance to the next 512-byte boundary
    int64_t rem = size % 512;
    if (rem) in.seekg(512 - rem, std::ios::cur);
  }
}

const std::vector<char>& TarFile::Get(const std::string& name) const {
  auto it = members_.find(name);
  if (it == members_.end()) throw Error("missing tar member " + name);
  return it->second;
}

std::vector<std::string> TarFile::Names() const {
  std::vector<std::string> out;
  for (const auto& kv : members_) out.push_back(kv.first);
  return out;
}

}  // namespace veles_native
