// Inference units + UUID factory (reference libVeles unit.h:105,
// unit_factory.cc:1-65 — reimplemented from scratch).
#pragma once

#include <functional>
#include <map>
#include <memory>
#include <string>

#include "common.h"
#include "json.h"
#include "npy.h"

namespace veles_native {

class Unit {
 public:
  virtual ~Unit() = default;

  // Configure from contents.json properties + loaded arrays.
  virtual void Setup(const JsonValue& props,
                     std::map<std::string, NpyArray> arrays) = 0;

  // Given the input sample shape (without batch), return the output
  // sample shape.
  virtual Shape OutputShape(const Shape& input_shape) const = 0;

  // Process `batch` samples: contiguous f32 in -> out.
  virtual void Run(const float* in, float* out, int batch,
                   const Shape& input_shape) const = 0;

  // Multi-input variants for DAG nodes (InputJoiner et al.); the
  // defaults delegate to the single-input methods.
  virtual Shape OutputShapeMulti(const std::vector<Shape>& ins) const {
    return OutputShape(ins.at(0));
  }
  virtual void RunMulti(const std::vector<const float*>& ins,
                        const std::vector<Shape>& in_shapes, float* out,
                        int batch) const {
    Run(ins.at(0), out, batch, in_shapes.at(0));
  }

  const std::string& name() const { return name_; }
  void set_name(const std::string& n) { name_ = n; }

 private:
  std::string name_;
};

class UnitFactory {
 public:
  using Creator = std::function<std::unique_ptr<Unit>()>;

  static UnitFactory& Instance();

  void Register(const std::string& uuid, Creator creator);
  std::unique_ptr<Unit> Create(const std::string& uuid) const;

 private:
  std::map<std::string, Creator> creators_;
};

void RegisterStandardUnits();  // idempotent

}  // namespace veles_native
