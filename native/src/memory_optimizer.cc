#include "memory_optimizer.h"

#include <algorithm>
#include <numeric>

namespace veles_native {

namespace {

struct Placed {
  int64_t offset, size;
  int first, last;
};

bool TimeOverlap(int a0, int a1, int b0, int b1) {
  return a0 <= b1 && b0 <= a1;
}

}  // namespace

std::vector<BufferPlacement> PlanArena(
    const std::vector<BufferRequest>& requests, int64_t* arena_size,
    int64_t alignment) {
  std::vector<size_t> order(requests.size());
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](size_t a, size_t b) {
    return requests[a].size > requests[b].size;
  });

  std::vector<Placed> placed;
  std::vector<BufferPlacement> result(requests.size());
  int64_t total = 0;

  for (size_t idx : order) {
    const auto& req = requests[idx];
    int64_t size = ((req.size + alignment - 1) / alignment) * alignment;
    // candidate offsets: 0 and the top of every time-overlapping block
    std::vector<int64_t> candidates = {0};
    for (const auto& p : placed)
      if (TimeOverlap(p.first, p.last, req.first_use, req.last_use))
        candidates.push_back(p.offset + p.size);
    std::sort(candidates.begin(), candidates.end());
    int64_t chosen = -1;
    for (int64_t cand : candidates) {
      bool free = true;
      for (const auto& p : placed) {
        if (!TimeOverlap(p.first, p.last, req.first_use, req.last_use))
          continue;
        if (cand < p.offset + p.size && p.offset < cand + size) {
          free = false;
          break;
        }
      }
      if (free) {
        chosen = cand;
        break;
      }
    }
    placed.push_back({chosen, size, req.first_use, req.last_use});
    result[idx].offset = chosen;
    total = std::max(total, chosen + size);
  }
  *arena_size = total;
  return result;
}

}  // namespace veles_native
