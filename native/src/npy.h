// .npy reader with fp16 -> f32 widening (parity with the reference's
// numpy_array_loader.cc including its fp16 conversion path).
#pragma once

#include <vector>

#include "common.h"

namespace veles_native {

struct NpyArray {
  Shape shape;
  std::vector<float> data;  // always widened to f32
};

NpyArray LoadNpy(const std::vector<char>& bytes);

}  // namespace veles_native
