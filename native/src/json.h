// Minimal JSON parser for contents.json (the reference vendored
// rapidjson as a submodule; this schema needs ~200 lines).
#pragma once

#include <map>
#include <memory>
#include <string>
#include <vector>

namespace veles_native {

class JsonValue {
 public:
  enum Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Type type = kNull;
  bool bool_value = false;
  double number = 0;
  std::string str;
  std::vector<JsonValue> array;
  std::map<std::string, JsonValue> object;

  bool IsNull() const { return type == kNull; }
  const JsonValue& operator[](const std::string& key) const;
  const JsonValue& operator[](size_t index) const;
  bool Has(const std::string& key) const {
    return type == kObject && object.count(key);
  }
  int64_t AsInt() const { return static_cast<int64_t>(number); }
};

// Throws Error on malformed input.
JsonValue ParseJson(const std::string& text);

}  // namespace veles_native
