// Shared bits for the native inference runtime (libVeles-equivalent,
// reference libVeles/inc/veles/*.h; written from scratch for the TPU
// framework build).
#pragma once

#include <cstddef>
#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles_native {

using Shape = std::vector<int64_t>;

inline int64_t NumElements(const Shape& s) {
  int64_t n = 1;
  for (auto d : s) n *= d;
  return n;
}

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

}  // namespace veles_native
