#include "npy.h"

#include <cstdint>
#include <cstring>

namespace veles_native {

namespace {

float HalfToFloat(uint16_t h) {
  uint32_t sign = (h >> 15) & 1;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t frac = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (frac == 0) {
      bits = sign << 31;
    } else {  // subnormal: normalize
      int shift = 0;
      while (!(frac & 0x400)) {
        frac <<= 1;
        ++shift;
      }
      frac &= 0x3FF;
      bits = (sign << 31) | ((127 - 15 - shift + 1) << 23) | (frac << 13);
    }
  } else if (exp == 0x1F) {
    bits = (sign << 31) | (0xFF << 23) | (frac << 13);  // inf/nan
  } else {
    bits = (sign << 31) | ((exp - 15 + 127) << 23) | (frac << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

// Pull "'descr': '<f4'" style fields out of the python-dict header.
std::string HeaderField(const std::string& header, const std::string& key) {
  size_t pos = header.find("'" + key + "'");
  if (pos == std::string::npos) throw Error("npy: missing " + key);
  pos = header.find(':', pos);
  size_t start = header.find_first_not_of(" ", pos + 1);
  char open = header[start];
  if (open == '\'') {
    size_t end = header.find('\'', start + 1);
    return header.substr(start + 1, end - start - 1);
  }
  if (open == '(') {
    size_t end = header.find(')', start);
    return header.substr(start, end - start + 1);
  }
  size_t end = header.find_first_of(",}", start);
  return header.substr(start, end - start);
}

}  // namespace

NpyArray LoadNpy(const std::vector<char>& bytes) {
  if (bytes.size() < 10 || std::memcmp(bytes.data(), "\x93NUMPY", 6))
    throw Error("npy: bad magic");
  uint8_t major = bytes[6];
  size_t header_len, header_off;
  if (major == 1) {
    uint16_t len;
    std::memcpy(&len, bytes.data() + 8, 2);
    header_len = len;
    header_off = 10;
  } else {
    if (bytes.size() < 12) throw Error("npy: truncated header length");
    uint32_t len;
    std::memcpy(&len, bytes.data() + 8, 4);
    header_len = len;
    header_off = 12;
  }
  if (header_len > bytes.size() - header_off)
    throw Error("npy: header overruns file");
  std::string header(bytes.data() + header_off, header_len);
  std::string descr = HeaderField(header, "descr");
  std::string order = HeaderField(header, "fortran_order");
  if (order.find("True") != std::string::npos)
    throw Error("npy: fortran order unsupported");
  std::string shape_str = HeaderField(header, "shape");

  NpyArray arr;
  // parse "(3, 4)" / "(5,)" / "()"
  for (size_t i = 1; i < shape_str.size();) {
    while (i < shape_str.size() &&
           !isdigit(static_cast<unsigned char>(shape_str[i])))
      ++i;
    if (i >= shape_str.size()) break;
    arr.shape.push_back(std::strtoll(shape_str.c_str() + i, nullptr, 10));
    while (i < shape_str.size() &&
           isdigit(static_cast<unsigned char>(shape_str[i])))
      ++i;
  }

  size_t count = static_cast<size_t>(NumElements(arr.shape));
  const char* payload = bytes.data() + header_off + header_len;
  size_t avail = bytes.size() - header_off - header_len;
  // count*8 is the largest element stride below; reject sizes that would
  // overflow the multiplication before the truncation checks run.
  if (count > SIZE_MAX / 8) throw Error("npy: element count overflow");
  arr.data.resize(count);

  if (descr == "<f4" || descr == "|f4") {
    if (avail < count * 4) throw Error("npy: truncated f4 payload");
    std::memcpy(arr.data.data(), payload, count * 4);
  } else if (descr == "<f8") {
    if (avail < count * 8) throw Error("npy: truncated f8 payload");
    const double* src = reinterpret_cast<const double*>(payload);
    for (size_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(src[i]);
  } else if (descr == "<f2") {
    if (avail < count * 2) throw Error("npy: truncated f2 payload");
    const uint16_t* src = reinterpret_cast<const uint16_t*>(payload);
    for (size_t i = 0; i < count; ++i) arr.data[i] = HalfToFloat(src[i]);
  } else if (descr == "<i4") {
    if (avail < count * 4) throw Error("npy: truncated i4 payload");
    const int32_t* src = reinterpret_cast<const int32_t*>(payload);
    for (size_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(src[i]);
  } else if (descr == "<i8") {
    if (avail < count * 8) throw Error("npy: truncated i8 payload");
    const int64_t* src = reinterpret_cast<const int64_t*>(payload);
    for (size_t i = 0; i < count; ++i)
      arr.data[i] = static_cast<float>(src[i]);
  } else {
    throw Error("npy: unsupported dtype " + descr);
  }
  return arr;
}

}  // namespace veles_native
