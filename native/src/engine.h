// Thread-pool engine for inference (the reference's engine.h:43 +
// thread_pool.h scheduled a unit DAG).  Two axes of parallelism:
// independent units of the same dependency wavefront run concurrently,
// and each unit's batch rows are sharded across workers — both axes as
// row-chunked tasks through RunTasks.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace veles_native {

class Engine {
 public:
  explicit Engine(int workers = 0);
  ~Engine();

  // Runs every task on the pool; blocks until all complete.  Callers
  // build the task list themselves: wavefront scheduling emits one
  // task per (unit, row-chunk) so both parallel axes share the pool.
  void RunTasks(const std::vector<std::function<void()>>& tasks);

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  void WorkerLoop();

  std::vector<std::thread> threads_;
  std::queue<std::function<void()>> queue_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool stop_ = false;
};

}  // namespace veles_native
