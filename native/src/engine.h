// Thread-pool engine for batch-parallel inference (the reference's
// engine.h:43 + thread_pool.h scheduled a unit DAG; an inference chain
// is linear, so the parallelism that matters is ACROSS batch rows —
// this engine shards the batch over workers).
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace veles_native {

class Engine {
 public:
  explicit Engine(int workers = 0);
  ~Engine();

  // Runs fn(start, count) over [0, total) split across workers; blocks
  // until every shard completes.
  void ParallelFor(int total,
                   const std::function<void(int, int)>& fn);

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  void WorkerLoop();

  std::vector<std::thread> threads_;
  std::queue<std::function<void()>> queue_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool stop_ = false;
};

}  // namespace veles_native
