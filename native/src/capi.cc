// C API for ctypes (the Python side binds through veles_tpu/native.py;
// pybind11 is deliberately not used — see build notes in
// native/CMakeLists.txt).
#include <cstring>
#include <string>

#include "workflow.h"

using veles_native::NativeWorkflow;

namespace {

void SetError(char* err, int errlen, const std::string& what) {
  if (err && errlen > 0) {
    std::strncpy(err, what.c_str(), errlen - 1);
    err[errlen - 1] = '\0';
  }
}

}  // namespace

extern "C" {

void* veles_workflow_load(const char* path, char* err, int errlen) {
  try {
    return new NativeWorkflow(path);
  } catch (const std::exception& e) {
    SetError(err, errlen, e.what());
    return nullptr;
  }
}

void veles_workflow_destroy(void* handle) {
  delete static_cast<NativeWorkflow*>(handle);
}

long long veles_workflow_input_size(void* handle) {
  return static_cast<NativeWorkflow*>(handle)->input_size();
}

long long veles_workflow_output_size(void* handle) {
  return static_cast<NativeWorkflow*>(handle)->output_size();
}

long long veles_workflow_unit_count(void* handle) {
  return static_cast<long long>(
      static_cast<NativeWorkflow*>(handle)->unit_count());
}

// Plans the arena for `batch` and returns its size in bytes (<0: error).
long long veles_workflow_arena_size(void* handle, int batch) {
  try {
    auto* wf = static_cast<NativeWorkflow*>(handle);
    wf->Initialize(batch);
    return wf->arena_size();
  } catch (const std::exception&) {
    return -1;
  }
}

int veles_workflow_run(void* handle, const float* in, float* out,
                       int batch, char* err, int errlen) {
  try {
    static_cast<NativeWorkflow*>(handle)->Run(in, out, batch);
    return 0;
  } catch (const std::exception& e) {
    SetError(err, errlen, e.what());
    return -1;
  }
}

}  // extern "C"
