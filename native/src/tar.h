// Minimal ustar (POSIX tar) reader: maps member name -> bytes.
// The reference runtime consumed zip via a libarchive submodule
// (libVeles/src/workflow_archive.cc); this build's package format is
// plain tar so the runtime stays dependency-free.
#pragma once

#include <map>
#include <string>
#include <vector>

namespace veles_native {

class TarFile {
 public:
  // Loads the whole archive into memory; throws Error on damage.
  explicit TarFile(const std::string& path);

  bool Has(const std::string& name) const {
    return members_.count(name) != 0;
  }
  const std::vector<char>& Get(const std::string& name) const;
  std::vector<std::string> Names() const;

 private:
  std::map<std::string, std::vector<char>> members_;
};

}  // namespace veles_native
