"""BASELINE benchmark suite (see BASELINE.md target table).

Measures, on the real chip:

- headline: autotuned Pallas tiled matmul, 3001x3001 f32, vs the
  reference's only published kernel number (0.1642 s, GTX TITAN OpenCL,
  devices/device_infos.json) — now using autotune_matmul blocks;
- the same matmul in bf16 with MXU TFLOP/s and MFU vs chip peak;
- MNIST-784 fused train step (784-100-10, batch 100): per-step time,
  samples/sec, projected whole-epoch wall-clock (600 train steps);
- AlexNet images/sec/chip, f32 and bf16, each step running the REAL
  input pipeline (Pallas gather_minibatch from an HBM-resident dataset)
  + the fused train step.

Timing method: the device may sit behind a high-latency tunnel where a
blocking fetch costs ~0.1 s regardless of compute, so every number is a
slope — two dependent chains of n1 and n2 iterations, each ended by one
scalar fetch; (t2-t1)/(n2-n1) cancels the latency.

Wall-clock budget: the driver kills long benches, and on a tunneled
chip the dominant cost is the FIRST EXECUTION of each distinct program
(~60 s server-side compile for an AlexNet-sized step; measured: the
local persistent compile cache does NOT shorten it, and concurrent
first-execs serialize server-side).  So the suite (a) prints a full
headline JSON line AFTER EVERY SECTION — the driver's tail-parse takes
the last complete line, so a kill loses only the unfinished tail, never
the whole record; (b) checks a deadline (env VELES_BENCH_DEADLINE_S,
default 480 s) before each optional section and sheds the lowest
evidence-per-second first — core sections (headline matmul, MNIST,
AlexNet bf16@256) always run, then f32@128, native, the second
headline pass, bf16@128, the level-1 true-f32 row, and f32@256 run
richest-first as time allows; (c) runs the native C++ build on a host
thread concurrently with the TPU sections.

Each printed line is the required {metric, value, unit, vs_baseline}
headline plus an "extras" dict carrying the BASELINE metrics, per-row
{median, min, max, passes} timing spreads, per-section wall times, and
the list of sections shed to fit the deadline.
"""

import functools
import json
import os
import sys
import threading
import time

import numpy

BASELINE_MATMUL_S = 0.1642  # GTX TITAN, reference devices/device_infos.json
N = 3001

# bf16 MXU peak TFLOP/s by device kind substring (public spec sheets);
# used to derive MFU context for bf16 measurements.  ONE table for the
# offline bench and the live mfu_pct gauge, so the two can never
# disagree about what "peak" means.
from veles_tpu.observe.xla_introspect import PEAK_BF16_TFLOPS  # noqa: E402

# ONE definition of the jitter-pass filter, shared with the schedule
# autotuner's fitness ranking (veles_tpu/tune/measure.py holds the
# docstring and the discard-never-clamp policy)
from veles_tpu.tune.measure import filter_passes as _filter_passes  # noqa: E402

# conservative wall-cost estimates per sheddable section (seconds,
# measured on the axon tunnel, dominated by the one-time server-side
# compile of each new program: ~60-100 s for a batch-128 AlexNet step,
# ~200 s at batch 256); a section only starts when this much time
# remains before the deadline
SECTION_EST = {
    "native_inference": 25.0,
    "matmul_pass2": 40.0,
    "alexnet_b128": 100.0,
    "alexnet_b128_bfloat16": 95.0,
    "matmul_f32_level1": 80.0,
    "alexnet_b256_float32": 230.0,
    # two small MLP programs (MNIST-784 head + an AlexNet-shaped input
    # head), each compiled once and A/B'd with the pipeline on/off
    "pipeline_ab": 90.0,
    # compile-only flat-vs-bucketed SPMD collective audit (small MLP,
    # two cheap compiles, no execution)
    "comm_bucketed": 45.0,
    # AOT serving ladder A/B (small MLP, 3-4 cheap compiles, ~2 s of
    # closed-loop measurement per leg)
    "serve_ab": 40.0,
    # backward-path A/B (docs/kernels.md): two compiles of a small
    # conv stack (autodiff vs hand-scheduled backward) + interleaved
    # slope rounds on TPU; compile+parity only on CPU
    "bwd_ab": 90.0,
    # tuned-vs-static schedule A/B: on TPU a cache-hit (or one sweep)
    # + two warm legs of interleaved slopes; on CPU a tiny compile-
    # fitness GA + cache-hit receipt
    "tune_ab": 60.0,
    # model-ranked vs compile-everything GA on the same search space:
    # three forced GA legs (baseline, side spec, model-guided) of
    # compile-only fitness on CPU; TPU swaps in measured fitness
    "tune_model_ab": 60.0,
    # f32-vs-int8 quantized engine A/B: one PTQ pass + two small AOT
    # ladders; CPU = parity + receipts, TPU adds interleaved slopes
    "quant_ab": 50.0,
    # flash-vs-stock attention A/B (docs/kernels.md): two grad
    # programs per shape; CPU = compile + parity, TPU adds the
    # interleaved pass-filtered slope rounds
    "attention_ab": 60.0,
    # multi-host hedging A/B (docs/serving.md "Multi-host tier"):
    # two small in-process serve hosts + ~2 s of closed-loop
    # measurement per leg, interleaved off/on passes
    "hedge_ab": 40.0,
    # multi-tenant QoS A/B (docs/serving.md "Multi-tenant QoS"): one
    # in-process batcher, interleaved flood legs with class-ordered
    # shedding off/on + the quiet anchor leg
    "qos_ab": 30.0,
    # request-tracing overhead A/B (docs/observability.md "Request
    # tracing"): one small AOT ladder + interleaved closed-loop legs
    # with the per-request segment stamps on vs VELES_REQTRACE=0
    "trace_overhead": 30.0,
    # fleet-telemetry-plane overhead A/B (docs/observability.md
    # "Fleet telemetry"): the same small serve harness with a series
    # ring ticking + the default alert rules sweeping vs fully off
    "telemetry_overhead": 25.0,
    # elastic-mesh reshard A/B (docs/distributed.md "Elastic mesh
    # contract"): two ZeRO-1 compiles (initial + cold shrink; the
    # grow-back is the compile-cache hit under test) + 4 small steps
    "reshard_ab": 60.0,
}

# a section whose dominant cost (the one-time server compile) loosely
# tracks an already-measured sibling can shrink its estimate from the
# sibling's actual wall time: on a quiet tunnel compiles run ~3x
# faster than the conservative caps above, and a static estimate would
# shed rows the window could actually fit.  The correlation is WEAK
# (measured sibling ratios span 1.6-4.3x), so the dynamic estimate is
# floored at 60% of the static cap and can only SHRINK it — the
# worst-case overrun past the deadline stays within the ~120 s margin
# to the driver's kill window.
DYNAMIC_EST = {
    "alexnet_b256_float32": ("alexnet_b256_bfloat16", 1.3),
    "alexnet_b128_bfloat16": ("alexnet_b128", 1.3),
}


def _headline_quadruple(value, small):
    """The required {metric, value, unit, vs_baseline} — built in one
    place so the full record line and its compact sibling can never
    disagree on the headline."""
    n = 512 if small else N
    return {"metric": "matmul_%dx%d_f32_avg_time" % (n, n),
            "value": value,
            "unit": "s",
            "vs_baseline": (round(BASELINE_MATMUL_S / value, 2)
                            if value and not small else None)}


def _compact_record(value, small, extras):
    """The sub-500-byte sibling of the full record line.

    The driver captures bench output through a byte-limited tail and
    json-parses the LAST complete line; the full record grows past
    4 KB by the final section and was captured mid-line two rounds
    running (BENCH_r03/r04 ``parsed: null``).  This line carries the
    required {metric, value, unit, vs_baseline} plus only the
    BASELINE.md-row scalars, so the machine-readable record survives
    any tail window >= ~500 bytes."""
    rec = _headline_quadruple(value, small)
    mm = extras.get("matmul") or {}
    bf = mm.get("bfloat16") or {}
    if "tflops" in bf:
        rec["bf16_tflops"] = bf["tflops"]
    lvl1 = mm.get("float32_level1") or {}
    if "tflops" in lvl1:
        rec["f32_level1_tflops"] = lvl1["tflops"]
    mn = extras.get("mnist_784_100_10") or {}
    for src, dst in (("step_seconds", "mnist_step_s"),
                     ("scan_step_seconds", "mnist_scan_step_s")):
        if src in mn:
            rec[dst] = mn[src]
    alex = extras.get("alexnet") or {}
    b256 = (alex.get("batch_256") or {}).get("bfloat16") or {}
    if "images_per_sec" in b256:
        rec["alexnet_b256_bf16_img_s"] = b256["images_per_sec"]
    if "mfu_pct" in b256:
        rec["alexnet_b256_bf16_mfu_pct"] = b256["mfu_pct"]
    nat = extras.get("native_inference") or {}
    for k in ("batch_1_rows_per_sec", "batch_256_rows_per_sec"):
        if k in nat:
            rec["native_" + k] = nat[k]
    pipe = extras.get("pipeline_ab") or {}
    for src, dst in (("mnist_784", "pipe_mnist_speedup"),
                     ("alexnet_input", "pipe_alex_in_speedup")):
        if "speedup" in (pipe.get(src) or {}):
            rec[dst] = pipe[src]["speedup"]
    bwd = extras.get("bwd_ab") or {}
    if "speedup" in bwd:
        rec["bwd_ab_speedup"] = bwd["speedup"]
    tune = extras.get("tune_ab") or {}
    if "speedup" in tune:
        rec["tune_ab_speedup"] = tune["speedup"]
    tmodel = extras.get("tune_model_ab") or {}
    if "evals_saved" in tmodel:
        rec["tune_model_evals_saved"] = tmodel["evals_saved"]
    quant = extras.get("quant_ab") or {}
    if "speedup" in quant:
        rec["quant_ab_speedup"] = quant["speedup"]
    if "top1_delta_pct" in quant:
        rec["quant_top1_delta_pct"] = quant["top1_delta_pct"]
    attn = extras.get("attention_ab") or {}
    if "speedup" in attn:
        rec["attention_ab_speedup"] = attn["speedup"]
    hedge = extras.get("hedge_ab") or {}
    if hedge.get("hedge_p99_cut_pct") is not None:
        rec["hedge_p99_cut"] = hedge["hedge_p99_cut_pct"]
    qos = extras.get("qos_ab") or {}
    if qos.get("qos_interactive_p99_guard") is not None:
        rec["qos_interactive_p99_guard"] = \
            qos["qos_interactive_p99_guard"]
    reqtrace = extras.get("trace_overhead") or {}
    if reqtrace.get("trace_overhead_pct") is not None:
        rec["trace_overhead_pct"] = reqtrace["trace_overhead_pct"]
    tele = extras.get("telemetry_overhead") or {}
    if tele.get("telemetry_overhead_pct") is not None:
        rec["telemetry_overhead_pct"] = tele["telemetry_overhead_pct"]
    reshard = extras.get("reshard_ab") or {}
    if reshard.get("reshard_bytes_saved_pct") is not None:
        rec["reshard_bytes_saved"] = reshard["reshard_bytes_saved_pct"]
    if "wall_s" in extras:
        rec["wall_s"] = extras["wall_s"]
    if extras.get("shed"):
        rec["shed"] = len(extras["shed"])
    if extras.get("section_errors"):
        rec["errors"] = len(extras["section_errors"])
    return rec


class BenchError(RuntimeError):
    """A measurement failed plausibility checks after remeasurement.

    Raised instead of publishing an impossible number (round-2 lesson:
    a floor-clamped negative slope once published 1e-9 s/step = 1e11
    samples/sec as the official MNIST record)."""


def _slope_samples(run_chain, n1, n2, repeats=5):
    """The individual (t(n2)-t(n1))/(n2-n1) slope samples."""
    slopes = []
    for _ in range(repeats):
        t1 = run_chain(n1)
        t2 = run_chain(n2)
        slopes.append((t2 - t1) / (n2 - n1))
    return slopes


def _slope(run_chain, n1, n2, repeats=5):
    """median over repeats of (t(n2)-t(n1))/(n2-n1).

    Median, not min: over a high-latency tunnel t(n1) spikes inflate
    individual diffs BOTH ways; min-of-slopes is biased low and can
    report physically impossible (> chip peak) rates.  May return a
    non-positive value when tunnel jitter swamps the chain delta —
    callers MUST validate (see _robust_slope), never clamp."""
    return float(numpy.median(_slope_samples(run_chain, n1, n2, repeats)))


# _filter_passes is imported at the top of the module: ONE definition
# of the jitter-pass filter (veles_tpu/tune/measure.py), shared with
# the schedule autotuner's fitness ranking — the discard-never-clamp
# policy and its rationale live there.


def _spread(samples):
    """{median, min, max, p50/p95/p99, passes, passes_used, slopes}
    for a list of slope samples — makes cross-round headline deltas
    readable as congestion vs regression, and records the step-time
    DISTRIBUTION (nearest-rank percentiles via the shared observe
    helper) rather than one central value per row.

    The published median/percentiles ride the jitter-filtered passes
    (``_filter_passes``); min/max stay RAW so the spread still shows
    the discarded passes' magnitude, ``passes_used`` says how many
    passes survived, and ``slopes`` keeps every per-pass slope so the
    filter's effect is auditable from the record alone."""
    from veles_tpu.observe.metrics import percentiles
    used = _filter_passes(samples)
    out = {"median": round(float(numpy.median(used)), 9),
           "min": round(float(min(samples)), 9),
           "max": round(float(max(samples)), 9),
           "passes": len(samples),
           "passes_used": len(used),
           "slopes": [round(float(s), 9) for s in samples]}
    out.update({key: round(float(value), 9)
                for key, value in percentiles(used).items()})
    return out


_DISPATCH_FLOOR = None


def dispatch_floor_seconds():
    """Measured per-dispatch overhead of a trivial jitted op.

    Every train step costs at least one Python->device dispatch, so no
    honest step-time slope can fall below this; it is the physical
    floor for plausibility checks (a fused step also does real compute,
    so flagging anything under the bare-dispatch floor is conservative).
    """
    global _DISPATCH_FLOOR
    if _DISPATCH_FLOOR is not None:
        return _DISPATCH_FLOOR
    import jax

    @jax.jit
    def bump(x):
        return x + 1.0

    x = jax.device_put(numpy.float32(0))
    float(bump(x))  # compile

    def chain(k):
        acc = x
        start = time.perf_counter()
        for _ in range(k):
            acc = bump(acc)
        float(acc)
        return time.perf_counter() - start

    per = _slope(chain, 10, 1010, repeats=3)
    # Per-op enqueue costs vary several-fold between executables (a
    # trivial scalar op measured ~3x slower per dispatch than a small
    # matmul chain on the axon tunnel), so the usable floor is a
    # FRACTION of the trivial-op slope: low enough to tolerate that
    # spread, high enough to reject the zero/negative slopes the
    # round-2 clamp papered over.  10 us minimum if even this
    # measurement drowns in noise.
    _DISPATCH_FLOOR = max(0.2 * per, 1e-5)
    return _DISPATCH_FLOOR


def _robust_slope(chain, n1, n2, floor, what, repeats=5):
    """Slope with a plausibility floor and remeasure-then-fail policy.

    A slope at or below ``floor`` (one dispatch's worth of time) is a
    measurement artifact, not a fast chip.  Retry with chains 2x and
    4x longer so the compute delta grows past tunnel jitter; if every
    attempt stays implausible, raise BenchError carrying the observed
    values so the failure is loud and diagnosable.

    The returned median rides the jitter-FILTERED passes
    (``_filter_passes``: non-positive slopes are discarded, with a
    positive majority required) so one inverted pass cannot drag the
    published center — the automation of MFU.json's weather_note,
    where a negative-slope pass contaminated a published capture.

    Returns ``(median_slope, samples)`` — the RAW samples feed the
    published spread, which records ``passes_used`` + per-pass
    ``slopes`` alongside {median, min, max, passes}.
    """
    observed = []
    for scale in (1, 2, 4):
        samples = _slope_samples(chain, n1, n2 * scale, repeats=repeats)
        used = _filter_passes(samples)
        per = float(numpy.median(used))
        observed.append(round(per, 9))
        # a positive-majority requirement backs the filter: 2 surviving
        # passes out of 5 is a jitter-swamped measurement, not a signal
        if per > floor and len(used) > len(samples) // 2:
            return per, samples
    raise BenchError(
        "%s: step-time slope implausible after remeasurement "
        "(observed %s s/step vs dispatch floor %.3g s; the tunnel "
        "is misbehaving — rerun the bench)"
        % (what, observed, floor))


def _peak_bf16(device_kind):
    kind = device_kind.lower()
    for key, peak in PEAK_BF16_TFLOPS:
        if key in kind:
            return peak
    return None


def _f32_ceiling_key():
    """Autotune-DB key for the best plausibility-checked f32 matmul
    rate measured on this chip kind (TFLOP/s) — versioned with the
    kernel algorithm, since a faster kernel makes an old ceiling a
    false upper bound that would flag every legitimate new rate."""
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    return "bench:f32_ceiling_tflops:v%d" % MATMUL_KERNEL_VERSION


def _rate_guard(info, dtype_name, peak_bf16):
    """Upper plausibility bound in TFLOP/s for one dtype, or None.

    The f32 guard is measured-ceiling * 1.25 but never above half the
    bf16 spec peak — the absolute bound keeps the ratchet from
    compounding (a noise spike that passes one guard must not loosen
    the next run's guard past physics)."""
    if dtype_name == "bfloat16":
        return peak_bf16
    hard_cap = peak_bf16 / 2 if peak_bf16 else None
    ceiling = info.get(_f32_ceiling_key())
    if ceiling:
        soft = ceiling * 1.25
        return min(soft, hard_cap) if hard_cap else soft
    return hard_cap


def _measure_matmul_row(n, dtype_name, precision_level, n1, n2, small):
    """Autotune + measure ONE matmul program; apply the chip-peak
    guard and return the published row.

    Shared by the two level-0 headline dtypes and the optional level-1
    true-f32 anchor so the chain/guard/spread logic exists once.  When
    a guard remeasure changes the published slope, the spread is
    recomputed from the samples that actually back it — a row whose
    ``seconds`` sits outside its own spread would misread as
    congestion in exactly the congested case the spread targets.
    """
    import jax

    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops import matmul
    from veles_tpu.ops.matmul import autotune_matmul

    dev = jax.devices()[0]
    info = DeviceInfo(dev.device_kind)
    dtype = getattr(jax.numpy, dtype_name)
    # tune at the benchmark size itself — tile optima don't transfer
    # between 2048 (power-of-two) and 3001 (padded) shapes
    blocks = autotune_matmul(
        info, size=n, dtype=dtype, precision_level=precision_level)
    rng = numpy.random.RandomState(0)
    scale = 0.01  # keep chained products bounded
    a = jax.device_put(
        ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32)
    ).astype(dtype)
    b = jax.device_put(
        ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32)
    ).astype(dtype)

    def mm(x, y):
        return matmul(x, y, precision_level=precision_level,
                      blocks=blocks)

    float(mm(a, b)[0, 0].astype(jax.numpy.float32))  # compile

    def chain(k):
        start = time.perf_counter()
        acc = a
        for _ in range(k):
            acc = mm(acc, b)
        float(acc[0, 0].astype(jax.numpy.float32))
        return time.perf_counter() - start

    per, samples = _robust_slope(
        chain, n1, n2, dispatch_floor_seconds(),
        "matmul_%s_pl%d" % (dtype_name, precision_level))
    # physical sanity: a rate above chip peak is a measurement
    # artifact — remeasure with a longer chain and keep the slower.
    # bf16 guards against the MXU spec peak; f32 guards against a
    # previously MEASURED f32 ceiling (+25 % headroom) persisted in
    # the autotune DB — the MXU's multi-pass f32 path has no spec
    # sheet number, so a real measurement beats the old peak/2 guess
    peak = _peak_bf16(dev.device_kind)
    guard = _rate_guard(info, dtype_name, peak)
    for _ in range(2):
        tflops = 2.0 * n * n * n / per / 1e12
        # no grace above the guard: a rate past physical peak is
        # impossible however slightly (a 2% tolerance once let
        # 199.6 TF = 101.3% MFU into the record)
        if guard is None or tflops <= guard or small:
            break
        redo = _slope_samples(chain, n1, n2 * 2)
        # same filtered-median contract as every published center
        # (_filter_passes) so row["seconds"] agrees with its spread
        redo_med = float(numpy.median(_filter_passes(redo)))
        if redo_med > per:  # slower remeasure wins; spread follows it
            per, samples = redo_med, redo
    tflops = 2.0 * n * n * n / per / 1e12
    row = {"seconds": round(per, 9),
           "tflops": round(tflops, 2),
           "blocks": list(blocks),
           "spread": _spread(samples)}
    if dtype_name == "float32":
        # self-describing precision (round-3 advice): level 0 computes
        # f32 products via a bf16x3 MXU decomposition (~5e-7 max rel
        # err vs f64; see ops/matmul.py), level 1 is the true-f32
        # 6-pass path with Kahan accumulation
        row["precision_level"] = precision_level
        row["algorithm"] = ("bf16x3" if precision_level == 0
                            else "highest+kahan")
    if not small and guard is not None and tflops > guard:
        # every remeasure still exceeded the physical bound: the
        # value is recorded for diagnosis but explicitly flagged —
        # never published as a silent >peak rate
        row["implausible"] = True
    return row


def bench_matmul(small):
    """One full headline pass: autotuned f32 + bf16 matmul rows.

    Does NOT persist the f32 ceiling — a single pass can be a noise
    spike; main() persists min-of-two-passes only (the ratchet needs
    two independent passes to agree before the guard loosens)."""
    import jax

    n = 512 if small else N
    # small shapes are dispatch-bound; long chains keep the slope
    # above timer noise
    n1, n2 = (1, 100) if small else (1, 41)
    dev = jax.devices()[0]
    out = {}
    for dtype_name in ("float32", "bfloat16"):
        out[dtype_name] = _measure_matmul_row(
            n, dtype_name, 0, n1, n2, small)
    peak = _peak_bf16(dev.device_kind)
    if peak:
        if not out["bfloat16"].get("implausible"):
            out["bfloat16"]["mfu_pct"] = round(
                100.0 * out["bfloat16"]["tflops"] / peak, 1)
        out["device_peak_bf16_tflops"] = peak
    out["device_kind"] = dev.device_kind
    return out


def bench_matmul_f32_level1(small):
    """True-f32 (precision level 1: HIGHEST products + Kahan) row at
    the headline shape, so the published level-0 bf16x3 ratio has an
    in-record true-f32 anchor to compare against."""
    n = 512 if small else N
    n1, n2 = (1, 50) if small else (1, 21)
    return _measure_matmul_row(n, "float32", 1, n1, n2, small)


def _setup_training(specs, input_shape, batch, dataset_size,
                    dtype_name, classes):
    """Plans + device-resident state/dataset/labels/order + the
    device-side duplicator, shared by the per-step and epoch-scan
    measurements."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.models.zoo import build_plans_and_state

    dtype = getattr(jnp, dtype_name)
    plans, state, _ = build_plans_and_state(specs, input_shape, seed=1)
    has_dropout = any("Dropout" in p.forward_cls.__name__
                      for p in plans)
    rng = numpy.random.RandomState(0)
    dataset = jax.device_put(
        (rng.rand(dataset_size, *input_shape) * 0.5).astype(
            numpy.float32)).astype(dtype)
    labels_all = jax.device_put(
        rng.randint(0, classes, dataset_size).astype(numpy.int32))
    order = jax.device_put(
        rng.permutation(dataset_size).astype(numpy.int32))
    state = jax.tree.map(
        lambda leaf: None if leaf is None else jnp.asarray(leaf, dtype),
        state, is_leaf=lambda x: x is None)
    # device-side duplicate (leaf + 0 forces a fresh buffer): chains
    # re-seed from this without a host->device upload, which over a
    # tunneled chip costs more than the whole measured chain
    dup = jax.jit(lambda s: jax.tree.map(
        lambda leaf: None if leaf is None else leaf + 0,
        s, is_leaf=lambda x: x is None))
    return plans, state, dataset, labels_all, order, dup, has_dropout


def _train_step_images_per_sec(specs, input_shape, batch, dataset_size,
                               dtype_name, chain_lens, classes=10,
                               setup=None):
    """Fused train step fed by the real Pallas gather from HBM.

    ``setup``: a _setup_training tuple to reuse — re-running the setup
    re-uploads the whole dataset over the tunnel, which costs more
    than the measured chains."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.compiler import build_train_step
    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    plans, state, dataset, labels_all, order, dup, has_dropout = (
        setup if setup is not None else
        _setup_training(specs, input_shape, batch, dataset_size,
                        dtype_name, classes))
    step = build_train_step(plans, donate=False)
    key = jax.random.PRNGKey(0) if has_dropout else None

    # ONE dispatch per step: gather + train step fuse into a single XLA
    # program, and donating the state pytree lets XLA update the (for
    # AlexNet, hundreds of MB of) parameters in place instead of
    # double-buffering them.  The dataset/labels/order ride as ARGUMENTS
    # — closing over them would bake hundreds of MB of constants into
    # the program, which a remote-compile service has to swallow whole.
    # compiler_options must sit on THIS top-level jit: the same
    # per-chip XLA options the product's fused trainer applies (tuned
    # scoped-VMEM entry in the device DB), so the row measures what
    # users get.
    from veles_tpu.compiler import step_compiler_options

    @functools.partial(jax.jit, donate_argnums=(0,),
                       compiler_options=step_compiler_options())
    def one(state, offset, dataset, labels_all, order):
        idx = jax.lax.dynamic_slice(order, (offset,), (batch,))
        x = gather_minibatch(dataset, idx)
        y = gather_labels(labels_all, idx)
        if key is not None:
            return step(state, x, y, numpy.float32(batch),
                        jax.random.fold_in(key, offset))
        return step(state, x, y, numpy.float32(batch))

    # warm both gather and step compilations
    state2, metrics = one(dup(state), 0, dataset, labels_all, order)
    float(metrics["loss"])
    del state2  # frees a full state-sized buffer set before the chains

    # XLA's own cost model for the whole fused program (gather + fwd +
    # bwd + update) — the honest FLOP count for MFU reporting.  Lower
    # from abstract avals: no device allocation, and the same-avals
    # compile is served by the compilation cache warmed above.
    flops = None
    try:
        def aval(leaf):
            return (None if leaf is None else
                    jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
        cost = one.lower(
            jax.tree.map(aval, state, is_leaf=lambda x: x is None),
            0, aval(dataset), aval(labels_all),
            aval(order)).compile().cost_analysis()
        if cost and cost.get("flops"):
            flops = float(cost["flops"])
    except Exception:
        pass

    steps_per_epoch = dataset_size // batch

    def chain(k):
        # fresh state copy: the previous chain's buffers were donated
        s = dup(state)
        jax.block_until_ready(jax.tree.leaves(s))
        start = time.perf_counter()
        m = None
        for i in range(k):
            s, m = one(s, (i % steps_per_epoch) * batch,
                       dataset, labels_all, order)
        float(m["loss"])
        return time.perf_counter() - start

    n1, n2 = chain_lens
    per_step, samples = _robust_slope(
        chain, n1, n2, dispatch_floor_seconds(),
        "train_step_%s_%s" % ("x".join(map(str, input_shape)),
                              dtype_name))
    return per_step, batch / per_step, flops, _spread(samples)


def _epoch_scan_per_step(batch, dataset_size, chain_lens, setup):
    """Per-step time of the one-dispatch-per-epoch scan path
    (compiler.build_train_epoch): the dispatch overhead that dominates
    small-model steps amortizes over the whole epoch.  ``setup`` is
    the _setup_training tuple shared with the per-step measurement."""
    import jax

    from veles_tpu.compiler import build_train_epoch

    plans, state, dataset, labels_all, order, dup, has_dropout = setup
    steps_per_epoch = dataset_size // batch
    epoch = build_train_epoch(plans, batch)
    key = jax.random.PRNGKey(0) if has_dropout else None

    def run_epoch(st, i):
        if key is not None:
            return epoch(st, dataset, labels_all, order,
                         jax.random.fold_in(key, i))
        return epoch(st, dataset, labels_all, order)

    st, totals = run_epoch(dup(state), 0)  # compile
    float(totals["loss_mean"])
    del st

    def chain(k):
        s = dup(state)
        jax.block_until_ready(jax.tree.leaves(s))
        start = time.perf_counter()
        t = None
        for i in range(k):
            s, t = run_epoch(s, i)
        float(t["loss_mean"])
        return time.perf_counter() - start

    n1, n2 = chain_lens
    per_epoch, samples = _robust_slope(
        chain, n1, n2, dispatch_floor_seconds(), "epoch_scan")
    per_step = per_epoch / steps_per_epoch
    return per_step, _spread(
        [s / steps_per_epoch for s in samples])


def bench_mnist(small):
    specs = [
        {"type": "all2all_tanh", "output_sample_shape": 100,
         "learning_rate": 0.1, "gradient_moment": 0.9},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": 0.1, "gradient_moment": 0.9},
    ]
    batch = 100
    dataset_size = 6000 if not small else 1000
    setup = _setup_training(specs, (784,), batch, dataset_size,
                            "float32", 10)
    # n2 >= 500: at ~1.6 ms/step the long chain runs ~0.9 s, far above
    # tunnel jitter — the round-2 failure was a 100-step delta (0.16 s)
    # drowned by latency spikes of the same magnitude
    per_step, sps, _, spread = _train_step_images_per_sec(
        specs, (784,), batch, dataset_size,
        "float32", (2, 22) if small else (10, 510), setup=setup)
    steps_per_epoch = 60000 // batch
    row = {
        "step_seconds": round(per_step, 9),
        "samples_per_sec": round(sps, 1),
        "epoch_seconds_projected": round(per_step * steps_per_epoch, 3),
        "batch": batch,
        "spread": spread,
    }
    # the one-dispatch-per-epoch turbo path (build_train_epoch):
    # dispatch-bound steps collapse to pure compute
    try:
        scan_step, scan_spread = _epoch_scan_per_step(
            batch, dataset_size, (1, 5) if small else (2, 22), setup)
        row["scan_step_seconds"] = round(scan_step, 9)
        row["scan_spread"] = scan_spread
        row["scan_samples_per_sec"] = round(batch / scan_step, 1)
        row["scan_epoch_seconds_projected"] = round(
            scan_step * steps_per_epoch, 3)
        row["scan_speedup"] = round(per_step / scan_step, 2)
    except Exception as exc:
        row["scan_error"] = repr(exc)
    return row


def _pipeline_workflow(input_shape, hidden, classes, batch, train_n,
                       valid_n, pipeline):
    """The real product path for the pipeline A/B: StandardWorkflow +
    host-resident FullBatchLoader (host fill + H2D every serve) +
    fused trainer, with the async input pipeline on or off."""
    from veles_tpu import prng
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    class SynthLoader(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, valid_n, train_n]
            self._calc_class_end_offsets()
            self.create_originals(input_shape)
            rng = numpy.random.RandomState(3)
            flat = self.original_data.mem.reshape(self.total_samples, -1)
            flat[:] = rng.rand(*flat.shape) * 0.5
            for i in range(self.total_samples):
                self.original_labels[i] = i % classes

    prng.get().seed(42)
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": hidden,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": classes,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: SynthLoader(
            w, minibatch_size=batch, on_device=False,
            prng=RandomGenerator("bench_pipe", seed=7)),
        decision_config=dict(max_epochs=10 ** 6),
    )
    sw.fuse(pipeline=pipeline)
    sw.initialize(device=Device(backend=None))
    return sw


def _pipeline_ab_row(input_shape, hidden, classes, batch, train_n,
                     valid_n, chain_lens):
    """One A/B row: per-step slope of loader.run+trainer.run with the
    pipeline off, then on, over the SAME synthetic workload.

    Besides the slope, each leg publishes its per-dispatch step-time
    distribution from the telemetry registry's ``step.train_s``
    histogram (the same series the heartbeat reports), so the row
    carries p50/p95/p99 of what the trainer actually measured."""
    from veles_tpu.observe.metrics import registry
    row = {}
    for key, pipeline in (("off", False), ("on", True)):
        sw = _pipeline_workflow(input_shape, hidden, classes, batch,
                                train_n, valid_n, pipeline)
        loader, trainer = sw.loader, sw.fused_trainer
        # warm past the whole validation class so BOTH programs (eval
        # forward + train step) compile outside the timed chains
        for _ in range(valid_n // batch + 1):
            loader.run()
            trainer.run()
        float(trainer.last_loss or 0.0)
        step_hist = registry.histogram("step.train_s")
        step_hist.reset()  # drop warmup/compile observations

        def chain(k):
            start = time.perf_counter()
            for _ in range(k):
                loader.run()
                trainer.run()
            if trainer.last_loss is not None:
                float(trainer.last_loss)
            trainer.device.sync()
            return time.perf_counter() - start

        n1, n2 = chain_lens
        per_step, samples = _robust_slope(
            chain, n1, n2, dispatch_floor_seconds(),
            "pipeline_%s_%s" % ("x".join(map(str, input_shape)), key))
        row["%s_step_s" % key] = round(per_step, 9)
        row["%s_spread" % key] = _spread(samples)
        row["%s_samples_per_sec" % key] = round(batch / per_step, 1)
        snap = step_hist.snapshot()
        if snap["count"]:
            row["%s_dispatch_hist" % key] = {
                k: (round(v, 9) if isinstance(v, float) else v)
                for k, v in snap.items() if v is not None}
        if pipeline and trainer._prefetcher is not None:
            stats = trainer._prefetcher.stats
            serves = max(1, stats["serves"])
            row["fill_s_per_serve"] = round(stats["fill_s"] / serves, 9)
            row["h2d_s_per_serve"] = round(stats["h2d_s"] / serves, 9)
            applied = max(1, stats["applied"])
            row["wait_s_per_step"] = round(stats["wait_s"] / applied, 9)
        sw.stop()  # joins the prefetch worker
    row["speedup"] = round(row["off_step_s"] / row["on_step_s"], 3)
    return row


def bench_pipeline(small):
    """A/B of the async double-buffered input pipeline: with pipeline=on
    the host fill and H2D of minibatch k+1 overlap step k, so the step
    slope should approach max(fill, h2d, compute) instead of their sum.

    Two rows through the REAL workflow path (loader unit -> fused
    trainer): the MNIST-784 head, and an AlexNet-shaped input path
    (227x227x3 images through a host fill + ~12 MB/batch H2D)."""
    rows = {}
    if small:
        rows["mnist_784"] = _pipeline_ab_row(
            (784,), 100, 10, 100, 500, 100, (2, 12))
        rows["alexnet_input"] = _pipeline_ab_row(
            (67, 67, 3), 64, 10, 32, 96, 32, (2, 8))
    else:
        rows["mnist_784"] = _pipeline_ab_row(
            (784,), 100, 10, 100, 2000, 200, (5, 105))
        rows["alexnet_input"] = _pipeline_ab_row(
            (227, 227, 3), 64, 10, 64, 192, 64, (2, 22))
    return rows


def bench_alexnet_row(batch, dtype_name, small, peak):
    """One AlexNet throughput row (one distinct program = one
    unavoidable ~60 s server-side compile on a tunneled chip)."""
    from veles_tpu.models.zoo import alexnet_layers

    size = 67 if small else 227
    dataset = 256 if small else 1024
    chain_lens = ((1, 10) if small else
                  (4, 44) if batch <= 128 else (4, 24))
    per_step, ips, flops, spread = _train_step_images_per_sec(
        alexnet_layers(classes=1000 if not small else 10),
        (size, size, 3), batch, dataset, dtype_name,
        chain_lens, classes=1000 if not small else 10)
    row = {"step_seconds": round(per_step, 9),
           "images_per_sec": round(ips, 1),
           "spread": spread}
    if flops:
        row["tflops"] = round(flops / per_step / 1e12, 2)
        if peak and dtype_name == "bfloat16":
            row["mfu_pct"] = round(
                100.0 * flops / per_step / 1e12 / peak, 1)
    return row


ALEXNET_PRECISION_NOTE = (
    "f32 rows use XLA TPU default matmul precision, which "
    "computes f32 convs/dense with one bf16 MXU pass; true "
    "f32 (precision=highest) measured 3.1x slower "
    "(36.0 ms/step at batch 128).  bf16's win over default-"
    "f32 is therefore memory traffic, not MXU rate — it "
    "reaches 1.5x at batch 256 where fixed overheads "
    "amortize.")


def bench_comm_bucketed(small):
    """Compile-only audit of the SPMD bucketed gradient all-reduce on
    this host's devices (docs/distributed.md): lower the flat and the
    bucketed data-parallel step of a small MLP, count the gradient
    all-reduce ops in the optimized HLO, and report the modeled
    overlap — the same receipt SCALING.json carries for the full
    AlexNet, cheap enough to ride every bench round.  Skipped on
    single-device hosts (no data axis to reduce over)."""
    import jax

    from veles_tpu.compiler import LayerPlan, build_train_step
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.parallel import make_mesh
    from veles_tpu.parallel.analysis import parse_collective_ops
    from veles_tpu.parallel.bucketed import overlap_model

    n = len(jax.devices())
    if n < 2:
        return {"skipped": "single device: no data axis"}
    mesh = make_mesh({"data": n})
    # small mode shrinks the model (fewer/smaller buckets, faster
    # compiles) but keeps >1 bucket so the audit still bites
    hidden, classes, fan_in = (64, 10, 196) if small else (256, 10, 784)
    hyper = {"learning_rate": 0.1, "gradient_moment": 0.9}
    plans = [LayerPlan(All2AllTanh, hyper=hyper),
             LayerPlan(All2AllSoftmax, hyper=hyper)]
    rng = numpy.random.RandomState(0)

    def layer(fi, fo):
        return {"weights": rng.rand(fi, fo).astype(numpy.float32),
                "bias": numpy.zeros(fo, numpy.float32),
                "accum_weights": numpy.zeros((fi, fo), numpy.float32),
                "accum_bias": numpy.zeros(fo, numpy.float32),
                "accum2_weights": None, "accum2_bias": None}
    state = [layer(fan_in, hidden), layer(hidden, classes)]
    grad_bytes = 4 * (fan_in * hidden + hidden +
                      hidden * classes + classes)
    batch = 8 * n
    x = jax.ShapeDtypeStruct((batch, fan_in), numpy.float32)
    y = jax.ShapeDtypeStruct((batch,), numpy.int32)
    bucket_mb = 0.02 if small else 0.25  # ~3-4 buckets either way

    def audit(mb):
        step = build_train_step(plans, mesh=mesh, grad_bucket_mb=mb,
                                donate=False)
        hlo = step.lower(state, x, y,
                         numpy.float32(batch)).compile().as_text()
        return [op["bytes"] for op in parse_collective_ops(hlo)
                if op["kind"] == "all-reduce" and op["bytes"] >= 1024]

    flat_ops = audit(float("inf"))
    bucket_ops = audit(bucket_mb)
    model = overlap_model(grad_bytes, len(bucket_ops), n,
                          step_seconds=None)
    return {
        "n_devices": n,
        "grad_bytes": grad_bytes,
        "bucket_mb": bucket_mb,
        "flat_allreduce_ops": len(flat_ops),
        "bucketed_allreduce_ops": len(bucket_ops),
        "bucketed_op_bytes": bucket_ops,
        "t_comm_ms_model": round(model["t_comm_s"] * 1e3, 4),
        "ok": (len(flat_ops) == 1
               and len(bucket_ops) > 1
               and sum(bucket_ops) == sum(flat_ops)),
    }


def bench_bwd_ab(small):
    """Backward-path A/B (docs/kernels.md): the SAME small conv stack's
    fused train step built twice — stock autodiff backward
    (``VELES_PALLAS_BWD=0``) vs the hand-scheduled backward (knob on:
    fused conv-VJP + pool select-and-scatter Pallas kernels + the
    optimization_barrier production-order chain).  Both legs compile
    and parity-check everywhere (forward losses bit-identical, updated
    states within the documented ULP band); interleaved round-robin
    timing slopes run only on real TPU backends — on CPU the kernels
    execute through the Pallas interpreter, whose wall time measures
    the interpreter, not the schedule, so the CPU row is compile+parity
    evidence only.  The interleaving (one sample per leg per round,
    like the matmul autotuner) spreads congestion drift across both
    legs equally, and the published ``weather_band`` is the per-leg
    max/median slope ratio — a speedup inside that band is weather,
    not code (MFU.json's caveat methodology)."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.compiler import build_train_step
    from veles_tpu.models.zoo import build_plans_and_state
    from veles_tpu.ops import common as _ops_common

    on_tpu = jax.default_backend() == "tpu"
    size = 12 if (small or not on_tpu) else 32
    batch = 16 if (small or not on_tpu) else 128
    specs = [
        {"type": "conv_str", "n_kernels": 8, "kx": 3, "ky": 3,
         "padding": 1, "learning_rate": 0.01, "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3,
         "padding": 1, "learning_rate": 0.01, "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": 0.01, "gradient_moment": 0.9},
    ]
    plans, state, _ = build_plans_and_state(specs, (size, size, 3),
                                            seed=3)
    rng = numpy.random.RandomState(5)
    x = jax.device_put(rng.rand(batch, size, size, 3)
                       .astype(numpy.float32))
    y = jax.device_put(rng.randint(0, 10, batch).astype(numpy.int32))
    bs = numpy.float32(batch)
    dup = jax.jit(lambda s: jax.tree.map(
        lambda leaf: None if leaf is None else leaf + 0,
        s, is_leaf=lambda v: v is None))

    saved_env = _ops_common.PALLAS_BWD_ENV
    legs = {}
    try:
        for leg, env in (("autodiff", "0"), ("pallas_bwd", "1")):
            # the knob is resolved at TRACE time (Conv.apply /
            # _build_step_fn), so it must hold through the first call
            _ops_common.PALLAS_BWD_ENV = env
            step = build_train_step(plans, donate=False)
            t0 = time.perf_counter()
            new_state, metrics = step(dup(state), x, y, bs)
            loss = float(metrics["loss"])
            compile_s = time.perf_counter() - t0
            legs[leg] = {"step": step, "state": new_state,
                         "loss": loss,
                         "row": {"compile_s": round(compile_s, 3)}}
    finally:
        _ops_common.PALLAS_BWD_ENV = saved_env

    # parity receipt: identical forward (same loss bits), updated
    # state inside the documented kernel band (docs/kernels.md)
    a, p = legs["autodiff"], legs["pallas_bwd"]
    max_rel = 0.0
    for ea, ep in zip(a["state"], p["state"]):
        for key_ in ea:
            if ea[key_] is None:
                continue
            va = numpy.asarray(ea[key_], numpy.float64)
            vp = numpy.asarray(ep[key_], numpy.float64)
            denom = max(float(numpy.abs(va).max()), 1e-9)
            max_rel = max(max_rel,
                          float(numpy.abs(va - vp).max()) / denom)
    result = {
        "model": "conv8-pool-conv8-pool-softmax", "batch": batch,
        "input": size,
        "loss_bit_identical": a["loss"] == p["loss"],
        "state_max_rel_diff": float("%.3g" % max_rel),
        "parity_ok": a["loss"] == p["loss"] and max_rel < 1e-4,
        "autodiff": a["row"], "pallas_bwd": p["row"],
    }

    if not on_tpu:
        result["note"] = ("CPU: Pallas interpreter — compile+parity "
                          "evidence only; timing rides TPU rounds")
        return result

    # interleaved slopes (TPU only): one sample per leg per round
    def make_chain(leg):
        step = legs[leg]["step"]

        def chain(k):
            s = dup(state)
            jax.block_until_ready(jax.tree.leaves(s))
            start = time.perf_counter()
            m = None
            for _ in range(k):
                s, m = step(s, x, y, bs)
            float(m["loss"])
            return time.perf_counter() - start
        return chain

    chains = {leg: make_chain(leg) for leg in ("autodiff",
                                               "pallas_bwd")}
    n1, n2 = (1, 11) if small else (4, 24)
    samples = {leg: [] for leg in chains}
    for _ in range(5):
        for leg, chain in chains.items():
            t1, t2 = chain(n1), chain(n2)
            samples[leg].append((t2 - t1) / (n2 - n1))
    band = 1.0
    for leg, slopes in samples.items():
        used = _filter_passes(slopes)
        per = float(numpy.median(used))
        legs[leg]["row"].update(
            step_seconds=round(per, 9), spread=_spread(slopes))
        band = max(band, max(used) / max(per, 1e-12))
    a_per = legs["autodiff"]["row"]["step_seconds"]
    p_per = legs["pallas_bwd"]["row"]["step_seconds"]
    result["speedup"] = round(a_per / p_per, 4)
    result["weather_band"] = round(band, 4)
    result["beats_weather"] = result["speedup"] > result["weather_band"]
    return result


def bench_attention_ab(small):
    """Flash-vs-stock-autodiff attention A/B (docs/kernels.md "The
    attention kernel"): the SAME (B, T, dh) attention gradient program
    built twice — stock jnp softmax attention under jax.grad
    (``attention_reference``, the ``VELES_PALLAS_BWD=0`` path) vs the
    tiled online-softmax Pallas forward + hand-scheduled backward pair
    (``flash_attention``'s custom_vjp).  Both legs compile and
    parity-check everywhere; interleaved round-robin slope rounds run
    only on real TPU backends through ``tune/measure.py``'s ONE
    discipline (``interleaved_slopes`` + positive-majority ``rank`` +
    ``filter_passes``) — on CPU the kernels execute through the Pallas
    interpreter, whose wall time measures the interpreter, not the
    schedule, so the CPU row is compile+parity evidence only.  The
    published ``weather_band`` is the per-leg max/median slope ratio:
    a speedup inside it is congestion, not code."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.attention import (attention_reference,
                                         flash_attention)
    from veles_tpu.tune.measure import interleaved_slopes, rank

    on_tpu = jax.default_backend() == "tpu"
    b, t, dh = (4, 128, 64) if (small or not on_tpu) else (8, 1024, 64)
    rng = numpy.random.RandomState(29)
    q = jax.device_put(rng.randn(b, t, dh).astype(numpy.float32) * 0.1)
    k = jax.device_put(rng.randn(b, t, dh).astype(numpy.float32) * 0.1)
    v = jax.device_put(rng.randn(b, t, dh).astype(numpy.float32) * 0.1)

    def grad_of(attn):
        return jax.jit(jax.grad(
            lambda q_, k_, v_: jnp.sum(attn(q_, k_, v_) ** 2),
            argnums=(0, 1, 2)))

    legs, rows = {}, {}
    for leg, attn in (("stock_autodiff",
                       lambda *a: attention_reference(*a)),
                      ("flash", lambda *a: flash_attention(*a))):
        fn = grad_of(attn)
        t0 = time.perf_counter()
        out = fn(q, k, v)
        jax.block_until_ready(out)
        rows[leg] = {"compile_s": round(time.perf_counter() - t0, 3)}
        legs[leg] = (fn, out)

    # parity receipt: outputs + all three gradients inside the
    # documented multi-tile ULP band (docs/kernels.md)
    ref_out = numpy.asarray(attention_reference(q, k, v),
                            numpy.float64)
    fl_out = numpy.asarray(flash_attention(q, k, v), numpy.float64)
    fwd_rel = float(numpy.abs(ref_out - fl_out).max() /
                    max(numpy.abs(ref_out).max(), 1e-12))
    grad_rel = 0.0
    for ga, gf in zip(legs["stock_autodiff"][1], legs["flash"][1]):
        a64 = numpy.asarray(ga, numpy.float64)
        f64 = numpy.asarray(gf, numpy.float64)
        grad_rel = max(grad_rel, float(
            numpy.abs(a64 - f64).max() /
            max(numpy.abs(a64).max(), 1e-12)))
    result = {
        "shape": {"batch_heads": b, "seq": t, "head_dim": dh},
        "fwd_max_rel_diff": float("%.3g" % fwd_rel),
        "grad_max_rel_diff": float("%.3g" % grad_rel),
        "parity_ok": fwd_rel < 1e-4 and grad_rel < 1e-4,
        "stock_autodiff": rows["stock_autodiff"],
        "flash": rows["flash"],
    }

    if not on_tpu:
        result["note"] = ("CPU: Pallas interpreter — compile+parity "
                          "evidence only; timing rides TPU rounds")
        return result

    def make_run(leg):
        fn = legs[leg][0]

        def run(count):
            out = None
            for _ in range(count):
                out = fn(q, k, v)
            jax.block_until_ready(out)
        return run

    runners = {leg: make_run(leg) for leg in rows}
    repeats = 8 if small else 24
    samples = interleaved_slopes(runners, 1, repeats + 1, rounds=5)
    meds = rank(samples)
    band = 1.0
    for leg in runners:
        used = _filter_passes(samples[leg])
        rows[leg].update(step_seconds=round(
            float(numpy.median(used)), 9), spread=_spread(samples[leg]))
        band = max(band, max(used) / max(float(numpy.median(used)),
                                         1e-12))
    if meds.get("stock_autodiff") and meds.get("flash"):
        result["speedup"] = round(
            meds["stock_autodiff"] / meds["flash"], 4)
        result["weather_band"] = round(band, 4)
        result["beats_weather"] = (result["speedup"]
                                   > result["weather_band"])
    else:
        result["note"] = ("jitter-rejected leg: no honest ranking "
                          "this round")
    return result


def bench_tune_ab(small):
    """Tuned-vs-static schedule A/B (docs/kernels.md "Autotuning").

    On TPU: ``autotune_matmul`` resolves the tuned tiles for the
    A/B size (a schedule-cache hit serves instantly; a miss runs the
    shared interleaved candidate sweep and persists), then the tuned
    and static-table schedules race under the same interleaved
    round-robin slope discipline as every other published number —
    speedup inside the weather band is congestion, not schedule.

    On CPU the kernels execute through the Pallas interpreter, whose
    wall time measures the interpreter, not the schedule — so the CPU
    row is MACHINERY evidence instead: a tiny GA tune (compile-only
    fitness) persists an entry and a second tune of the same spec
    comes back a pure cache hit with zero evaluations, which is the
    receipt BENCH picks up."""
    import jax

    from veles_tpu.ops.matmul import _DEFAULT_BLOCKS, autotune_matmul
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.measure import interleaved_slopes, rank
    from veles_tpu.tune.spec import family_for, matmul_spec

    on_tpu = jax.default_backend() == "tpu"
    result = {"device_kind": jax.devices()[0].device_kind,
              "cache_path": tune_cache.cache_for().path}

    if not on_tpu:
        from veles_tpu.prng import RandomGenerator
        from veles_tpu.tune.autotune import ScheduleTuner
        spec = matmul_spec(256, 256, 256, "float32", 0)
        rows = [
            ScheduleTuner(spec, generations=2, population=4,
                          fitness="compile",
                          rng=RandomGenerator("bench-tune",
                                              seed=11)).tune()
            for _ in range(2)]
        result.update(
            first_source=rows[0]["source"],
            second_source=rows[1]["source"],
            second_evals=rows[1]["evals"],
            schedule=rows[1].get("schedule"),
            tune_counters=tune_cache.tune_counters(),
            note="CPU: Pallas interpreter — GA + cache-hit receipt "
                 "only; schedule timing rides TPU rounds")
        return result

    size = 1024 if small else 2048
    from veles_tpu.backends import DeviceInfo
    tuned = autotune_matmul(DeviceInfo(result["device_kind"]),
                            size=size)
    spec = matmul_spec(size, size, size, "float32", 0)
    result.update(size=size, tuned_blocks=list(tuned),
                  default_blocks=list(_DEFAULT_BLOCKS),
                  provenance=tune_cache.provenance(
                      spec["op"], spec["shape"], spec["dtype"],
                      spec["precision_level"], spec["extra"]))
    if tuple(tuned) == tuple(_DEFAULT_BLOCKS):
        result["note"] = ("tuned == static default: the sweep ranked "
                          "the default tile best (or was jitter-"
                          "rejected); A/B degenerate")
        return result

    family = family_for("matmul")
    runners = {}
    for leg, blocks in (("static", _DEFAULT_BLOCKS), ("tuned", tuned)):
        warm, run = family.build_runner(spec, {"blocks": list(blocks)})
        warm()
        runners[leg] = run
    repeats = 8 if small else 24
    samples = interleaved_slopes(runners, 1, repeats + 1, rounds=5)
    meds = rank(samples)
    band = 1.0
    for leg in runners:
        result[leg] = {"spread": _spread(samples[leg])}
        used = _filter_passes(samples[leg])
        band = max(band, max(used) / max(float(numpy.median(used)),
                                         1e-12))
    if meds.get("static") and meds.get("tuned"):
        result["speedup"] = round(meds["static"] / meds["tuned"], 4)
        result["weather_band"] = round(band, 4)
        result["beats_weather"] = (result["speedup"]
                                   > result["weather_band"])
    else:
        result["note"] = ("jitter-rejected leg: no honest ranking "
                          "this round")
    return result


def bench_tune_model_ab(small):
    """Model-ranked vs compile-everything GA on the SAME search space
    (docs/kernels.md "Autotuning", cost-model mode).

    One matmul spec is force-tuned twice: leg A with every candidate
    compiled+measured (the baseline discipline), leg B with
    ``fitness="model"`` — the learned cost model ranks each
    generation and only the top decile (floor 2) compiles.  Leg A's
    measurements (plus a second spec's, so leave-one-spec-out
    validation has held-out groups) ARE the model's training data:
    the bench is the fleet story in miniature — one search's paid
    compiles make the next search cheap.

    Receipts: evals paid per leg (the ``tune.evals`` counter delta,
    i.e. compiles actually paid), wall seconds per leg, the model's
    self-reported validation error, and best-found-slope parity —
    1.0 when both legs crown the same schedule, else a head-to-head
    interleaved measurement of the two winners (never the two legs'
    own fitness numbers, which ran at different cache temperatures).
    The trust gate is opened wide here (``model_trust=2.0``) so the
    receipt always shows the model-mode eval economics; the
    validation error rides the receipt, and production keeps the
    default gate."""
    import jax

    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.measure import interleaved_slopes, rank
    from veles_tpu.tune.spec import family_for, matmul_spec

    on_tpu = jax.default_backend() == "tpu"
    base = "measure" if on_tpu else "compile"
    size = 2048 if on_tpu and not small else 1024
    generations = 3 if small else 4
    # population sized so the compile-everything leg pays well over
    # 4x the model leg's floor (2 compiles/generation): the >=4x
    # evals-saved receipt must hold even when the GA converges early
    population = 20 if small else 24
    repeats, rounds = (8, 3) if on_tpu else (2, 2)
    spec = matmul_spec(size, size, size, "float32", 0)
    side = matmul_spec(size // 2, size, size, "float32", 0)

    result = {"device_kind": jax.devices()[0].device_kind,
              "base_fitness": base, "size": size,
              "generations": generations, "population": population,
              "cache_path": tune_cache.cache_for().path}

    start = time.monotonic()
    row_a = ScheduleTuner(
        spec, generations=generations, population=population,
        fitness=base, repeats=repeats, rounds=rounds,
        rng=RandomGenerator("bench-tune-model", seed=21)) \
        .tune(force=True)
    wall_a = time.monotonic() - start
    # the side spec's triples give the model a second held-out group
    ScheduleTuner(
        side, generations=2, population=max(6, population // 2),
        fitness=base, repeats=repeats, rounds=rounds,
        rng=RandomGenerator("bench-tune-model", seed=22)) \
        .tune(force=True)

    start = time.monotonic()
    row_b = ScheduleTuner(
        spec, generations=generations, population=population,
        fitness="model", model_base=base, model_min_triples=8,
        model_trust=2.0, repeats=repeats, rounds=rounds,
        rng=RandomGenerator("bench-tune-model", seed=21)) \
        .tune(force=True)
    wall_b = time.monotonic() - start

    model_info = row_b.get("model") or {}
    result.update(
        evals_measured=row_a["evals"], evals_model=row_b["evals"],
        genomes_measured=row_a["genomes"],
        genomes_model=row_b["genomes"],
        evals_saved=row_a["evals"] - row_b["evals"],
        eval_ratio=round(row_b["evals"] / max(row_a["evals"], 1), 4),
        wall_measured_s=round(wall_a, 3),
        wall_model_s=round(wall_b, 3),
        winner_measured=row_a.get("schedule"),
        winner_model=row_b.get("schedule"),
        model={k: model_info.get(k) for k in
               ("triples", "error", "spearman", "groups", "trusted",
                "fallback", "predicted")})

    sched_a, sched_b = row_a.get("schedule"), row_b.get("schedule")
    if sched_a is None or sched_b is None:
        result["note"] = ("a leg produced no rankable winner; parity "
                          "skipped")
    elif sched_a == sched_b:
        result["parity"] = 1.0
        result["parity_method"] = "identical-winner"
    else:
        # head-to-head under ONE interleaved discipline: same chip
        # temperature for both winners, unlike the legs' own fitness
        family = family_for("matmul")
        runners = {}
        for leg, sched in (("measured", sched_a), ("model", sched_b)):
            warm, run = family.build_runner(spec, sched)
            warm()
            runners[leg] = run
        meds = rank(interleaved_slopes(runners, 1, repeats + 1,
                                       rounds=max(rounds, 3)))
        if meds.get("measured") and meds.get("model"):
            result["parity"] = round(
                meds["model"] / meds["measured"], 4)
            result["parity_method"] = "head-to-head"
        else:
            result["note"] = ("jitter-rejected head-to-head leg; no "
                              "honest parity this round")
    result["tune_counters"] = tune_cache.tune_counters()
    return result


def bench_quant_ab(small):
    """f32 vs int8 quantized engine A/B (docs/serving.md "Quantized
    ladder").

    One MLP spec is post-training-quantized (percentile calibration on
    a seeded stream) and BOTH engines stand up in one process — two
    digests, one persistent cache, the quantized ladder beside the f32
    one exactly as a serving host would run an A/B.

    On CPU the row is parity + machinery evidence (the kernels execute
    through the Pallas interpreter, whose wall time measures the
    interpreter): top-1 agreement and max|dprob| between the engines on
    a seeded stream, the int8 Pallas matmul's bit-exactness vs the
    jitted interpret-mode reference, and both compile receipts.  On
    TPU the engines race their throughput rung under the shared
    interleaved pass-filtered slope discipline — speedup, weather
    band, and the int8-vs-bf16 peak context so the row reads against
    the right ceiling."""
    import jax

    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.quant import quantize_model_spec
    from veles_tpu.serve import AOTEngine

    on_tpu = jax.default_backend() == "tpu"
    fan_in, hidden, classes = (196, 64, 10) if small else (784, 256, 10)
    rng = numpy.random.RandomState(23)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": (rng.randn(fan_in, hidden) /
                     numpy.sqrt(fan_in)).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": (rng.randn(hidden, classes) /
                     numpy.sqrt(hidden)).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    calib = rng.rand(512, fan_in).astype(numpy.float32)
    qparams, calibration = quantize_model_spec(plans, params, calib)
    rung = 32 if small else 128
    engines = {}
    for leg, p in (("f32", params), ("int8", qparams)):
        # donate=False: the timed legs re-dispatch ONE device batch;
        # on TPU the default donation would delete it at the first
        # warm run and every slope sample after would raise
        engines[leg] = AOTEngine(plans, p, (fan_in,), ladder=(rung,),
                                 device=Device(), donate=False)
        engines[leg].compile()
    result = {
        "device_kind": jax.devices()[0].device_kind,
        "rung": rung,
        "clip_fraction": round(calibration.clip_fraction, 6),
        "digests": {leg: engines[leg].digest for leg in engines},
        "compiles": {leg: engines[leg].compile_receipt["new_compiles"]
                     for leg in engines},
    }

    # parity row — the accuracy side of the receipt on every backend
    x = rng.rand(256, fan_in).astype(numpy.float32)
    y32 = engines["f32"].infer(x)
    y8 = engines["int8"].infer(x)
    result["top1_delta_pct"] = round(
        100.0 * float((y32.argmax(1) != y8.argmax(1)).mean()), 3)
    result["max_abs_dprob"] = float(numpy.abs(y32 - y8).max())

    # kernel-vs-reference bit-exactness (the QUANT.json anchor)
    import jax.numpy as jnp

    from veles_tpu.ops.matmul_int8 import (matmul_int8,
                                           matmul_int8_reference)
    qa = jnp.asarray(rng.randint(-127, 128, (64, 256)), jnp.int8)
    qb = jnp.asarray(rng.randint(-127, 128, (256, 128)), jnp.int8)
    qs = jnp.asarray(rng.rand(128).astype(numpy.float32) * 1e-2)
    result["pallas_bitexact"] = bool(
        (numpy.asarray(matmul_int8(qa, qb, qs)) ==
         numpy.asarray(jax.jit(matmul_int8_reference)(qa, qb, qs)))
        .all())

    if not on_tpu:
        result["note"] = ("CPU: Pallas interpreter — parity + compile "
                          "receipt only; the speedup row rides TPU "
                          "rounds")
        return result

    # TPU: interleaved pass-filtered throughput race on the rung
    from veles_tpu.observe.xla_introspect import (PEAK_BF16_TFLOPS,
                                                  PEAK_INT8_TFLOPS)
    from veles_tpu.tune.measure import interleaved_slopes, rank

    batch = x[:rung] if rung <= x.shape[0] else numpy.resize(x, (rung,
                                                                 fan_in))
    runners = {}
    for leg, eng in engines.items():
        x_dev = eng.device.put(numpy.ascontiguousarray(batch))

        def run(count, eng=eng, x_dev=x_dev):
            out = None
            for _ in range(count):
                out = eng.run(x_dev, rung)
            jax.block_until_ready(out)

        run(1)  # warm
        runners[leg] = run
    repeats = 8 if small else 24
    samples = interleaved_slopes(runners, 1, repeats + 1, rounds=5)
    meds = rank(samples)
    band = 1.0
    for leg in runners:
        result.setdefault("legs", {})[leg] = {
            "spread": _spread(samples[leg])}
        used = _filter_passes(samples[leg])
        band = max(band, max(used) / max(float(numpy.median(used)),
                                         1e-12))
    kind = result["device_kind"].lower()
    for table, key in ((PEAK_BF16_TFLOPS, "peak_bf16_tflops"),
                       (PEAK_INT8_TFLOPS, "peak_int8_tflops")):
        for sub, tflops in table:
            if sub in kind:
                result[key] = tflops
                break
    if meds.get("f32") and meds.get("int8"):
        result["speedup"] = round(meds["f32"] / meds["int8"], 4)
        result["weather_band"] = round(band, 4)
        result["beats_weather"] = (result["speedup"]
                                   > result["weather_band"])
    else:
        result["note"] = ("jitter-rejected leg: no honest ranking "
                          "this round")
    return result


def bench_serve_ab(small):
    """Serving-path A/B (docs/serving.md): sequential single-sample
    inference through the AOT engine vs continuous batching under a
    closed-loop client pool, percentiles at the headline (the TPU
    in-datacenter paper's framing: inference is latency-bound, so the
    tail is the number, not the mean).  Small MLP, so the cost is a few
    sub-second compiles plus ~2 s of measurement per leg; the full
    closed-loop *sweep* (offered-load knee) lives in
    scripts/serve_load.py -> BENCH_serve.json."""
    import threading as _threading

    from veles_tpu.backends import Device
    from veles_tpu.observe.metrics import percentiles as _percentiles
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.serve import AOTEngine, ContinuousBatcher

    fan_in, hidden, classes = (196, 64, 10) if small else (784, 256, 10)
    rng = numpy.random.RandomState(0)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    ladder = (1, 8, 32) if small else (1, 8, 32, 128)
    engine = AOTEngine(plans, params, (fan_in,), ladder=ladder,
                       device=Device())
    receipt = engine.compile()
    samples = rng.rand(256, fan_in).astype(numpy.float32)
    duration = 1.0 if small else 2.0

    def leg(run_one, clients):
        latencies, lock = [], _threading.Lock()
        stop_at = time.perf_counter() + duration

        def client(k):
            mine = []
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                run_one(samples[(k * 31 + len(mine)) % len(samples)])
                mine.append(time.perf_counter() - t0)
            with lock:
                latencies.extend(mine)

        threads = [_threading.Thread(target=client, args=(k,))
                   for k in range(clients)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        ps = _percentiles(latencies)
        return {"clients": clients,
                "requests": len(latencies),
                "requests_per_sec": round(len(latencies) / elapsed, 1),
                **{p: round(v * 1e3, 3) for p, v in ps.items()}}

    sequential = leg(engine.infer, clients=1)
    batcher = ContinuousBatcher(engine, max_delay_s=0.002).start()
    try:
        batched = leg(lambda s: batcher.infer(s, timeout=30.0),
                      clients=8 if small else 32)
    finally:
        batcher.stop()

    # transport A/B (docs/serving.md): the SAME engine behind the two
    # wire fronts — tornado+json text vs binary tensor frames (with
    # the same-host shm payload bypass).  The delta is pure transport;
    # it feeds the BENCH_serve.json regeneration story.
    import http.client as _http_client

    from veles_tpu.serve import BinaryTransportClient, ServeService

    svc = ServeService(engine, max_delay_s=0.002, transport_port=0)
    svc.start_background()
    local = _threading.local()
    created, created_lock = [], _threading.Lock()

    def json_one(sample):
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = local.conn = _http_client.HTTPConnection(
                "127.0.0.1", svc.port, timeout=30)
            with created_lock:
                created.append(conn)
        conn.request(
            "POST", "/infer",
            body=json.dumps({"input": sample.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()

    def binary_one(sample):
        cli = getattr(local, "cli", None)
        if cli is None:
            cli = local.cli = BinaryTransportClient(
                port=svc.transport_port)
            with created_lock:
                created.append(cli)
        cli.infer(sample)

    try:
        wire_clients = 4 if small else 8
        json_row = leg(json_one, clients=wire_clients)
        binary_row = leg(binary_one, clients=wire_clients)
    finally:
        for peer in created:
            peer.close()
        svc.stop()
    transport_ab = {
        "clients": wire_clients,
        "json": json_row,
        "binary": binary_row,
        "binary_vs_json_rps_x": round(
            binary_row["requests_per_sec"]
            / max(json_row["requests_per_sec"], 1e-9), 2),
        "json_minus_binary_p50_ms": round(
            json_row["p50"] - binary_row["p50"], 3),
    }
    return {
        "compile_receipt": receipt,
        "sequential": sequential,       # p50/p95/p99 in ms
        "batched": batched,
        "throughput_x": round(
            batched["requests_per_sec"]
            / max(sequential["requests_per_sec"], 1e-9), 2),
        "transport_ab": transport_ab,
    }


def bench_trace_overhead(small):
    """Request-tracing overhead A/B (docs/observability.md "Request
    tracing"): the SAME continuously-batched serve knee measured with
    the per-request segment stamps ON (the shipping default) vs the
    ``VELES_REQTRACE=0`` kill switch, interleaved off/on passes so
    drift hits both legs alike.  The stamps are a handful of
    ``perf_counter`` calls and tuple appends per request, so the gate
    is <= 2% rps — if this A/B ever reports more, the serve hot path
    regressed.  Span emission stays off in BOTH legs (no tracer
    active): the number isolates the always-on mark/exemplar cost
    every production request pays."""
    import threading as _threading

    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.observe import requests as reqtrace
    from veles_tpu.serve import AOTEngine, ContinuousBatcher

    fan_in, hidden, classes = (196, 64, 10) if small else (784, 256, 10)
    rng = numpy.random.RandomState(7)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    ladder = (1, 8, 32) if small else (1, 8, 32, 128)
    engine = AOTEngine(plans, params, (fan_in,), ladder=ladder,
                       device=Device())
    engine.compile()
    samples = rng.rand(256, fan_in).astype(numpy.float32)
    duration = 0.5 if small else 1.0
    clients = 8 if small else 32
    batcher = ContinuousBatcher(engine, max_delay_s=0.002).start()

    def leg():
        done, lock = [0], _threading.Lock()
        stop_at = time.perf_counter() + duration

        def client(k):
            n = 0
            while time.perf_counter() < stop_at:
                batcher.infer(
                    samples[(k * 31 + n) % len(samples)],
                    timeout=30.0)
                n += 1
            with lock:
                done[0] += n

        threads = [_threading.Thread(target=client, args=(k,))
                   for k in range(clients)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return done[0] / (time.perf_counter() - start)

    saved = reqtrace.enabled
    passes = 3
    rps = {"off": [], "on": []}
    try:
        leg()  # warm the ladder + thread pool out of the measurement
        for _ in range(passes):
            for mode in ("off", "on"):
                reqtrace.enabled = mode == "on"
                rps[mode].append(leg())
    finally:
        reqtrace.enabled = saved
        batcher.stop()
        # the A/B's own tail requests are not serving evidence
        reqtrace.exemplars.clear()

    def median(xs):
        return sorted(xs)[len(xs) // 2]

    rps_off, rps_on = median(rps["off"]), median(rps["on"])
    pct = 100.0 * (rps_off - rps_on) / max(rps_off, 1e-9)
    return {
        "clients": clients,
        "passes": passes,
        "rps_tracing_off": round(rps_off, 1),
        "rps_stamps_on": round(rps_on, 1),
        "trace_overhead_pct": round(pct, 2),
        "gate_pct": 2.0,
        "within_gate": pct <= 2.0,
    }


def bench_telemetry_overhead(small):
    """Fleet-telemetry-plane overhead A/B (docs/observability.md
    "Fleet telemetry"): the SAME continuously-batched serve knee with
    the telemetry plane running hot — a private series ring ticking at
    50 ms (40x the shipping 2 s poll cadence) with the default alert
    rules sweeping every closed bucket — vs fully off, interleaved
    passes.  One tick is a registry scan + dict folds and one alert
    sweep is a handful of digest merges per rule, all on a side
    thread, so the gate is <= 1% rps: if this A/B ever reports more,
    the rollup/alert-eval path regressed."""
    import threading as _threading

    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.observe.alerts import AlertManager, default_rules
    from veles_tpu.observe.timeseries import SeriesRing
    from veles_tpu.serve import AOTEngine, ContinuousBatcher

    fan_in, hidden, classes = (196, 64, 10) if small else (784, 256, 10)
    rng = numpy.random.RandomState(11)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": numpy.zeros(hidden, numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": numpy.zeros(classes, numpy.float32)},
    ]
    ladder = (1, 8, 32) if small else (1, 8, 32, 128)
    engine = AOTEngine(plans, params, (fan_in,), ladder=ladder,
                       device=Device())
    engine.compile()
    samples = rng.rand(256, fan_in).astype(numpy.float32)
    duration = 0.5 if small else 1.0
    clients = 8 if small else 32
    batcher = ContinuousBatcher(engine, max_delay_s=0.002).start()

    def leg(telemetry_on):
        stop = _threading.Event()
        worker = None
        if telemetry_on:
            ring = SeriesRing(interval_s=0.05)
            manager = AlertManager(default_rules())

            def sweep():
                while not stop.wait(0.01):
                    # dump=False: a (never-expected) firing must cost
                    # an eval, not a flight-recorder file write
                    if ring.maybe_tick() is not None:
                        manager.evaluate(ring.buckets(last=32),
                                         dump=False)

            worker = _threading.Thread(target=sweep, daemon=True)
            worker.start()
        done, lock = [0], _threading.Lock()
        stop_at = time.perf_counter() + duration

        def client(k):
            n = 0
            while time.perf_counter() < stop_at:
                batcher.infer(
                    samples[(k * 37 + n) % len(samples)],
                    timeout=30.0)
                n += 1
            with lock:
                done[0] += n

        threads = [_threading.Thread(target=client, args=(k,))
                   for k in range(clients)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        stop.set()
        if worker is not None:
            worker.join(timeout=5)
        return done[0] / elapsed

    passes = 5
    rps = {"off": [], "on": []}
    try:
        leg(False)  # warm the ladder + thread pool
        for _ in range(passes):
            for mode in ("off", "on"):
                rps[mode].append(leg(mode == "on"))
    finally:
        batcher.stop()

    def median(xs):
        return sorted(xs)[len(xs) // 2]

    # per-PASS paired deltas, then the median (the hedge_ab
    # discipline): closed-loop rps drifts minute to minute on a
    # shared host, and pairing each on leg with its adjacent off leg
    # cancels the drift a median-of-legs comparison would publish as
    # overhead
    pcts = [100.0 * (off - on) / max(off, 1e-9)
            for off, on in zip(rps["off"], rps["on"])]
    pct = median(pcts)
    return {
        "clients": clients,
        "passes": passes,
        "rps_telemetry_off": round(median(rps["off"]), 1),
        "rps_telemetry_on": round(median(rps["on"]), 1),
        "pass_overhead_pcts": [round(p, 2) for p in pcts],
        "telemetry_overhead_pct": round(pct, 2),
        "gate_pct": 1.0,
        "within_gate": pct <= 1.0,
    }


def bench_hedge_ab(small):
    """Multi-host hedging A/B (docs/serving.md "Multi-host tier"):
    closed-loop p50/p95/p99 through a :class:`FleetRouter` over two
    in-process serve hosts with a seeded ``serve.host.stall``
    straggler, hedging OFF vs ON — the TPU paper's p99-bound serving
    argument, measured.  Passes are INTERLEAVED (off, on, off, on, …)
    and the published p99 cut is the positive-majority median of the
    per-pass deltas — the shared tune/measure.py discipline, so a
    host-load window cannot crown either leg.  The multi-process
    SIGKILL variant (real subprocess hosts) is
    scripts/fleet_soak.py -> HEDGE.json."""
    import socket as _socket
    import threading as _threading

    from veles_tpu import chaos
    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.observe.metrics import percentiles as _percentiles
    from veles_tpu.serve import (
        AOTEngine, BinaryTransportServer, ContinuousBatcher,
        FleetRouter)
    from veles_tpu.tune.measure import positive_majority_median

    fan_in, hidden, classes = 16, 24, 4
    rng = numpy.random.RandomState(0)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": rng.rand(hidden).astype(numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": rng.rand(classes).astype(numpy.float32)},
    ]
    hosts = []
    for i in range(2):
        engine = AOTEngine(plans, params, (fan_in,), ladder=(8, 32),
                           device=Device())
        engine.compile()
        batcher = ContinuousBatcher(engine, max_delay_s=0.001,
                                    max_queue=4096).start()
        server = BinaryTransportServer(
            batcher, port=None, host_meta={"host_id": "bench-h%d" % i})
        server.start_background()
        hosts.append((engine, batcher, server))
    samples = rng.rand(64, fan_in).astype(numpy.float32)
    duration = 1.0 if small else 2.0
    passes = 3
    # the stall must DOMINATE one-process scheduling jitter (~tens of
    # ms on a small shared host): 150 ms on ~20% of the straggler's
    # frames is unambiguous; the hedge answers from the healthy
    # sibling within ~floor+service
    stall_p, stall_s = 0.2, 0.15

    def leg(hedge_on, seed):
        # a fresh seeded chaos stream per leg: both legs of a pass
        # face the same stall pattern.  The stall is HOST-SCOPED to
        # bench-h0 (the transport's point:host_id convention): ONE
        # straggler, one healthy sibling — the fleet shape hedging is
        # for (a fleet-wide stall leaves nothing to hedge to)
        chaos.install(chaos.FaultPlan(seed=seed).add(
            "serve.host.stall:bench-h0", "stall",
            probability=stall_p, param=stall_s))
        router = FleetRouter(hedge=hedge_on, hedge_factor=2.0,
                             hedge_floor_s=0.03,
                             hedge_tick_s=0.01).start()
        try:
            for _, _, server in hosts:
                ours, theirs = _socket.socketpair()
                server.serve_socket(ours)
                router.add_host(sock=theirs)
            latencies, lock = [], _threading.Lock()
            stop_at = time.perf_counter() + duration

            def client(k):
                mine, n = [], 0
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    router.infer(samples[(k * 31 + n) % len(samples)],
                                 timeout=30.0)
                    mine.append(time.perf_counter() - t0)
                    n += 1
                with lock:
                    latencies.extend(mine)

            threads = [_threading.Thread(target=client, args=(k,))
                       for k in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            router.stop()
            chaos.uninstall()
        ps = _percentiles(latencies)
        return {"requests": len(latencies),
                **{p: round(v * 1e3, 3) for p, v in ps.items()}}

    try:
        rows = {"off": [], "on": []}
        deltas = []
        for i in range(passes):
            off = leg(False, seed=100 + i)
            on = leg(True, seed=100 + i)
            rows["off"].append(off)
            rows["on"].append(on)
            deltas.append(off["p99"] - on["p99"])
    finally:
        for _, batcher, server in hosts:
            server.stop()
            batcher.stop()
    from veles_tpu.observe.metrics import registry as _reg
    med_delta = positive_majority_median(deltas)
    p99_off = float(numpy.median([r["p99"] for r in rows["off"]]))
    cut_pct = (round(100.0 * med_delta / p99_off, 2)
               if med_delta is not None and p99_off else None)
    return {
        "hosts": 2,
        "clients": 3,
        "passes": passes,
        "straggler": "serve.host.stall p%.2f %.0fms" % (
            stall_p, stall_s * 1e3),
        "off": rows["off"],
        "on": rows["on"],
        "p99_deltas_ms": [round(d, 3) for d in deltas],
        "hedges_fired": _reg.counter("serve.hedge.fired").value,
        "hedge_p99_cut_pct": cut_pct,
    }


def bench_qos_ab(small):
    """Multi-tenant QoS A/B (docs/serving.md "Multi-tenant QoS"):
    closed-loop interactive p50/p99 through one in-process batcher
    while a best-effort tenant floods the queue, class-ordered
    shedding OFF vs ON — the noisy-neighbor shape the QoS layer
    exists for.  OFF labels the flood like everything else (the
    un-classed system's behavior: FIFO equality, interactive waits
    behind the storm); ON labels it ``best_effort`` so interactive
    admissions evict flood rows.  Passes are interleaved and the
    published guard is the median per-pass p99 ratio off/on; the
    quiet leg (no flood) anchors what p99 costs when nobody floods.
    The subprocess-host soak is scripts/qos_soak.py -> QOS.json."""
    import threading as _threading

    from veles_tpu.backends import Device
    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.observe.metrics import percentiles as _percentiles
    from veles_tpu.observe.metrics import registry as _reg
    from veles_tpu.serve import (
        AOTEngine, ContinuousBatcher, ServeOverload)

    fan_in, hidden, classes = 16, 24, 4
    rng = numpy.random.RandomState(0)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": rng.rand(hidden).astype(numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": rng.rand(classes).astype(numpy.float32)},
    ]
    engine = AOTEngine(plans, params, (fan_in,), ladder=(8, 32),
                       device=Device())
    engine.compile()
    # a small bound so the flood actually saturates it: the A/B is
    # about WHO gets the queue, not how big the queue is
    batcher = ContinuousBatcher(engine, max_delay_s=0.001,
                                max_queue=64).start()
    samples = rng.rand(64, fan_in).astype(numpy.float32)
    duration = 0.8 if small else 1.5
    passes = 3

    def leg(flood_class, flood=True):
        latencies, lock = [], _threading.Lock()
        shed_int0 = _reg.counter(
            "serve.tenant.interactive.shed").value
        stop_at = time.perf_counter() + duration

        def flooder(k):
            n = 0
            while time.perf_counter() < stop_at:
                try:
                    batcher.submit(samples[(k * 17 + n) % 64],
                                   slo_class=flood_class)
                except ServeOverload:
                    pass  # the storm being shed is the point
                n += 1
                if n % 64 == 0:
                    time.sleep(0.001)  # ~flood pace, not a spin

        def client(k):
            mine, n, sheds = [], 0, 0
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                try:
                    req = batcher.submit(samples[(k * 31 + n) % 64],
                                         slo_class="interactive")
                    req.done.wait(30.0)
                    if req.error is not None:
                        raise req.error
                except ServeOverload:
                    sheds += 1
                    continue
                finally:
                    n += 1
                mine.append(time.perf_counter() - t0)
            with lock:
                latencies.extend(mine)

        threads = [_threading.Thread(target=client, args=(k,))
                   for k in range(2)]
        if flood:
            threads += [_threading.Thread(target=flooder, args=(k,))
                        for k in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ps = _percentiles(latencies)
        return {"requests": len(latencies),
                "interactive_sheds": _reg.counter(
                    "serve.tenant.interactive.shed").value - shed_int0,
                **{p: round(v * 1e3, 3) for p, v in ps.items()}}

    try:
        quiet = leg("interactive", flood=False)
        rows = {"off": [], "on": []}
        ratios = []
        for _ in range(passes):
            # OFF: the flood is indistinguishable from everyone else
            # (the pre-QoS world) -> interactive queues behind it.
            # ON: the flood is labelled best_effort -> interactive
            # admissions evict it (SHED_ORDER contract)
            off = leg("interactive")
            on = leg("best_effort")
            rows["off"].append(off)
            rows["on"].append(on)
            if on["p99"]:
                ratios.append(off["p99"] / on["p99"])
    finally:
        batcher.stop()
    guard = (round(float(numpy.median(ratios)), 2)
             if ratios else None)
    return {
        "clients": 2,
        "flooders": 3,
        "passes": passes,
        "max_queue": 64,
        "quiet": quiet,
        "off": rows["off"],
        "on": rows["on"],
        # >1 means class-ordered shedding cut the flooded interactive
        # p99 by that factor vs the unlabelled-flood world
        "qos_interactive_p99_guard": guard,
        "on_interactive_sheds": sum(
            r["interactive_sheds"] for r in rows["on"]),
    }


def bench_reshard_ab(small):
    """Elastic-mesh reshard A/B (docs/distributed.md, "Elastic mesh
    contract"): time-to-recover and bytes of train state moved for a
    live consistent-hash reshard versus the full-gather baseline
    (re-materializing all ``n_shards`` rows on every membership
    change).  Three events on one MeshManager: a cold shrink (8 -> 6,
    pays a recompile), a warm grow back to the seen 8-device set (the
    digest-keyed compile cache makes rejoin recovery cheap — the
    receipt row the rejoin story rests on), and a swap.  The seeded
    soak with the crash leg is scripts/mesh_soak.py ->
    ELASTIC_MESH.json."""
    import jax as _jax

    from veles_tpu.compiler import LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.parallel.mesh import MeshManager
    devices = sorted(_jax.devices(), key=lambda d: d.id)
    if len(devices) < 4:
        return {"skipped": "needs >= 4 devices, have %d" % len(devices)}
    fan_in, hidden, classes = 16, 48 if small else 128, 4
    rng = numpy.random.RandomState(0)
    hyper = {"learning_rate": 0.1, "gradient_moment": 0.9}
    plans = [LayerPlan(All2AllTanh, hyper=hyper),
             LayerPlan(All2AllSoftmax, hyper=hyper)]
    state = []
    for fi, fo in ((fan_in, hidden), (hidden, classes)):
        state.append({
            "weights": rng.randn(fi, fo).astype(numpy.float32) * 0.1,
            "bias": numpy.zeros(fo, numpy.float32),
            "accum_weights": numpy.zeros((fi, fo), numpy.float32),
            "accum_bias": numpy.zeros(fo, numpy.float32),
            "accum2_weights": None, "accum2_bias": None})
    n = len(devices)
    batch = n * (n - 2) * 3  # divisible by every size the A/B visits
    x = rng.randn(batch, fan_in).astype(numpy.float32)
    y = (numpy.arange(batch) % classes).astype(numpy.int32)
    mgr = MeshManager(plans, state, devices=devices, n_shards=2 * n,
                      donate=False)
    mgr.step(x, y)
    mgr.step(x, y)
    # shrink (cold compile), grow back (warm: the compile-cache hit),
    # swap to a DIFFERENT same-size subset (ownership follows device
    # identity).  reshard_s covers the state movement; the first
    # post-reshard step carries the (lazily dispatched) compile, so
    # time-to-recover is their sum.
    first_step_s = []
    for target in (devices[:n - 2], devices, devices[2:n]):
        mgr.submit_membership(target)
        t0 = time.perf_counter()
        mgr.step(x, y)
        first_step_s.append(time.perf_counter() - t0)
    rows = []
    for ev, step_s in zip(mgr.reshard_log, first_step_s):
        row = {k: ev[k] for k in (
            "from_size", "to_size", "moved_shards", "changed_fraction",
            "bytes_moved", "full_gather_bytes", "reshard_s",
            "compile_cached")}
        row["time_to_recover_s"] = round(ev["reshard_s"] + step_s, 4)
        rows.append(row)
    moved = sum(r["bytes_moved"] for r in rows)
    full = sum(r["full_gather_bytes"] for r in rows)
    warm = [r["time_to_recover_s"] for r in rows if r["compile_cached"]]
    cold = [r["time_to_recover_s"] for r in rows
            if not r["compile_cached"]]
    return {
        "devices": n,
        "n_shards": mgr.n_shards,
        "events": rows,
        "bytes_moved_total": moved,
        "full_gather_bytes_total": full,
        "reshard_bytes_saved_pct": (
            round(100.0 * (1.0 - moved / full), 1) if full else None),
        "cold_recover_s": round(max(cold), 4) if cold else None,
        "warm_recover_s": round(max(warm), 4) if warm else None,
        "warm_over_cold": (round(max(warm) / max(cold), 3)
                           if warm and cold and max(cold) else None),
    }


def _build_native():
    from veles_tpu import native
    native.build_native()


def bench_native(small, build_thread=None, wait_budget_s=120.0):
    """C++ inference runtime throughput on an exported MLP package
    (wavefront engine; host CPU, not the TPU — the runtime's job is
    chip-free serving, reference libVeles).

    The CMake build runs on a background thread started at suite
    entry; by measurement time it is normally long done.  The MLP
    package trainer runs on the numpy backend, whose unit fallbacks
    pin their jax math to the host CPU (backends.host_compute_context)
    — unpinned, the same training cost ~45 s of per-op tunnel round
    trips on a remote-TPU host instead of ~2 s."""
    import tempfile

    from veles_tpu import native
    from veles_tpu.backends import Device
    if build_thread is not None:
        build_thread.join(timeout=max(1.0, wait_budget_s))
        if build_thread.is_alive():
            raise BenchError("native build still running at deadline")
    native.build_native()  # no-op when the thread built it; else build

    from tests.test_native import _train_mlp

    sw = _train_mlp(Device(backend="numpy"), epochs=1)
    pkg = os.path.join(tempfile.mkdtemp(prefix="bench_native_"),
                       "mlp.tar")
    sw.package_export(pkg)
    wf = native.NativeWorkflow(pkg)
    rng = numpy.random.RandomState(0)
    out = {}
    for batch in (1, 256):
        x = rng.rand(batch, wf.input_size).astype(numpy.float32)
        wf.run(x)  # warm the arena plan
        n = 2000 if small else 10000
        start = time.perf_counter()
        for _ in range(max(1, n // batch)):
            wf.run(x)
        elapsed = time.perf_counter() - start
        rows = max(1, n // batch) * batch
        out["batch_%d_rows_per_sec" % batch] = round(rows / elapsed, 1)
    return out


def main():
    small = bool(os.environ.get("VELES_BENCH_SMALL"))
    deadline = time.monotonic() + float(
        os.environ.get("VELES_BENCH_DEADLINE_S", "480"))
    t_start = time.monotonic()
    # VELES_TRACE=path: record the whole bench under the span tracer
    # and close with a one-line textual digest (top spans by self
    # time) so CI logs carry a trace summary next to the numbers
    trace_path = os.environ.get("VELES_TRACE", "")
    if trace_path:
        from veles_tpu.observe.trace import tracer as _bench_tracer
        _bench_tracer.start()
        _bench_tracer.label = "bench"
    # enable JAX's persistent compile cache: it does not shorten the
    # tunnel's server-side first-exec, but it does skip client-side
    # recompiles and keeps the XLA autotune cache warm
    try:
        from veles_tpu.backends import _enable_persistent_compile_cache
        _enable_persistent_compile_cache()
    except Exception:
        pass

    extras = {"sections_s": {}, "shed": []}
    result = {"value": None}

    def remaining():
        return deadline - time.monotonic()

    def emit():
        """Print the full record line, then its compact sibling.

        The driver tail-parses the LAST complete line, so the compact
        line (< 500 bytes, always whole inside any byte-limited tail)
        is what gets machine-read; the full line right above it keeps
        every section's detail for humans.  Both reprint after every
        section, so a kill can only lose the unfinished tail."""
        full = _headline_quadruple(result["value"], small)
        full["extras"] = extras
        print(json.dumps(full), flush=True)
        print(json.dumps(_compact_record(result["value"], small,
                                         extras)), flush=True)

    def section(name, fn, always=False):
        """Run one section under the deadline policy and emit."""
        est = SECTION_EST.get(name, 30.0)
        sibling = DYNAMIC_EST.get(name)
        if sibling:
            measured = extras["sections_s"].get(sibling[0])
            # an errored sibling's wall time measures its failure, not
            # the shared compile cost — never shrink from it
            if measured and sibling[0] not in extras.get(
                    "section_errors", {}):
                est = min(est, max(0.6 * est, sibling[1] * measured))
        if not always and not small and remaining() < est:
            extras["shed"].append(name)
            return None
        t0 = time.monotonic()
        try:
            value = fn()
        except Exception as exc:  # keep the record alive
            value = {"error": repr(exc)}
            extras.setdefault("section_errors", {})[name] = repr(exc)
        extras["sections_s"][name] = round(time.monotonic() - t0, 1)
        emit()
        return value

    # the native C++ build is pure host CPU — overlap it with every
    # TPU-bound section below
    build_thread = threading.Thread(target=_build_native, daemon=True)
    build_thread.start()

    # headline pass 1: always runs (it IS the record)
    t0 = time.monotonic()
    matmul_res = bench_matmul(small)
    extras["sections_s"]["matmul_pass1"] = round(
        time.monotonic() - t0, 1)
    extras["matmul"] = matmul_res
    result["value"] = matmul_res["float32"]["seconds"]
    emit()

    mnist = section("mnist", lambda: bench_mnist(small), always=True)
    if mnist is not None:
        extras["mnist_784_100_10"] = mnist

    # async input pipeline A/B (small MLP programs, cheap compiles):
    # records the overlap win of fill/H2D/step pipelining on the MNIST
    # fused step and an AlexNet-shaped input path
    pipeline_res = section("pipeline_ab", lambda: bench_pipeline(small))
    if pipeline_res is not None:
        extras["pipeline_ab"] = pipeline_res

    # SPMD comm audit: flat vs bucketed collective op counts + modeled
    # overlap (compile-only; skipped on single-device hosts)
    comm_res = section("comm_bucketed",
                       lambda: bench_comm_bucketed(small))
    if comm_res is not None:
        extras["comm_bucketed"] = comm_res

    # serving A/B: AOT-ladder sequential vs continuously-batched, with
    # p50/p95/p99 request-latency columns (docs/serving.md)
    serve_res = section("serve_ab", lambda: bench_serve_ab(small))
    if serve_res is not None:
        extras["serve_ab"] = serve_res

    # backward-path A/B: autodiff vs the hand-scheduled Pallas
    # backward, interleaved slopes on TPU, compile+parity on CPU
    # (docs/kernels.md)
    bwd_res = section("bwd_ab", lambda: bench_bwd_ab(small))
    if bwd_res is not None:
        extras["bwd_ab"] = bwd_res

    # schedule-autotuner A/B (docs/kernels.md "Autotuning"): tuned
    # schedule-cache tiles vs the static tables, interleaved; on CPU
    # the GA + cache-hit machinery receipt
    tune_res = section("tune_ab", lambda: bench_tune_ab(small))
    if tune_res is not None:
        extras["tune_ab"] = tune_res

    # cost-model autotuner A/B (docs/kernels.md "Autotuning"): model-
    # ranked top-decile compiles vs the compile-everything GA on the
    # SAME search space — evals paid, wall clock, winner parity
    tune_model_res = section("tune_model_ab",
                             lambda: bench_tune_model_ab(small))
    if tune_model_res is not None:
        extras["tune_model_ab"] = tune_model_res

    # quantized-inference A/B (docs/serving.md "Quantized ladder"):
    # f32 vs int8 engine in one process; CPU = parity + bit-exactness
    # + compile receipts, TPU adds the interleaved speedup row against
    # the int8 peak
    quant_res = section("quant_ab", lambda: bench_quant_ab(small))
    if quant_res is not None:
        extras["quant_ab"] = quant_res

    # flash-vs-stock attention A/B (docs/kernels.md "The attention
    # kernel"): interleaved pass-filtered gradient-program slopes on
    # TPU; compile + parity receipt on CPU
    attn_res = section("attention_ab",
                       lambda: bench_attention_ab(small))
    if attn_res is not None:
        extras["attention_ab"] = attn_res

    # multi-host hedging A/B (docs/serving.md "Multi-host tier"):
    # closed-loop p99 with hedging off vs on under a seeded
    # serve.host.stall straggler, interleaved passes
    hedge_res = section("hedge_ab", lambda: bench_hedge_ab(small))
    if hedge_res is not None:
        extras["hedge_ab"] = hedge_res

    # multi-tenant QoS A/B (docs/serving.md "Multi-tenant QoS"):
    # flooded interactive p99 with class-ordered shedding off vs on,
    # plus the quiet anchor leg
    qos_res = section("qos_ab", lambda: bench_qos_ab(small))
    if qos_res is not None:
        extras["qos_ab"] = qos_res

    # request-tracing overhead A/B (docs/observability.md "Request
    # tracing"): serve rps with segment stamps on vs VELES_REQTRACE=0,
    # interleaved passes — the <= 2% gate on the always-on cost
    reqtrace_res = section("trace_overhead",
                           lambda: bench_trace_overhead(small))
    if reqtrace_res is not None:
        extras["trace_overhead"] = reqtrace_res

    # fleet-telemetry-plane overhead A/B (docs/observability.md
    # "Fleet telemetry"): serve rps with a hot series ring + default
    # alert rules sweeping vs off — the <= 1% gate on the plane's cost
    tele_res = section("telemetry_overhead",
                       lambda: bench_telemetry_overhead(small))
    if tele_res is not None:
        extras["telemetry_overhead"] = tele_res

    # elastic-mesh reshard A/B (docs/distributed.md "Elastic mesh
    # contract"): time-to-recover + bytes moved for a consistent-hash
    # live reshard vs the full-gather baseline, cold and warm legs
    reshard_res = section("reshard_ab",
                          lambda: bench_reshard_ab(small))
    if reshard_res is not None:
        extras["reshard_ab"] = reshard_res

    # AlexNet rows, one program (= one ~60-200 s server compile) each.
    # Batch 256 bf16 = the throughput/MFU sweet spot and the only
    # always-run row; batch 128 f32 = the historical comparison row
    # (what SCALING.json projects from), sheddable under congestion.
    # The remaining rows are ordered by evidence-per-second and shed
    # from the back: bf16@128 (cross-round history), the level-1
    # true-f32 matmul anchor, and f32@256 (the 1.5x partner row — its
    # conclusion is carried by precision_note when shed).
    peak = _peak_bf16(matmul_res["device_kind"])
    alexnet = {"batch": 32 if small else 128}

    def alex(batch, dtype_name):
        row = bench_alexnet_row(batch, dtype_name, small, peak)
        dest = (alexnet if batch == alexnet["batch"]
                else alexnet.setdefault("batch_256", {}))
        dest[dtype_name] = row
        if not small:
            alexnet["precision_note"] = ALEXNET_PRECISION_NOTE
        extras["alexnet"] = alexnet
        return row

    b = alexnet["batch"]
    if small:
        section("alexnet_b128", lambda: alex(b, "float32"),
                always=True)
        section("alexnet_b32_bfloat16", lambda: alex(b, "bfloat16"),
                always=True)
    else:
        # the BASELINE throughput/MFU row (b256 bf16) runs FIRST: a
        # congested run whose compiles eat the budget must lose the
        # historical b128 f32 comparison row (sheddable, and its
        # f32-vs-bf16 conclusion is carried by precision_note), never
        # the headline — a 2x-congested round-5 run spent 240 s on
        # the b128 first-exec and was killed mid-b256
        section("alexnet_b256_bfloat16",
                lambda: alex(256, "bfloat16"), always=True)
        section("alexnet_b128", lambda: alex(b, "float32"))
    # floor the build-join budget at the section's own admission
    # estimate: a section admitted under the deadline policy must get a
    # join window consistent with that policy, not a near-zero clamp
    # when the suite reaches here close to the deadline
    native_res = section(
        "native_inference",
        lambda: bench_native(
            small, build_thread,
            wait_budget_s=max(SECTION_EST["native_inference"],
                              remaining() - 30.0)))
    if native_res is not None:
        extras["native_inference"] = native_res

    # a tunneled chip's congestion varies minute to minute; measure the
    # headline twice (start + end of the suite) and keep the faster
    # plausible pass.  The f32 ceiling guard only ratchets when BOTH
    # passes agree (min of the two) — one spiked pass must not loosen
    # the next run's plausibility guard.
    def pass2():
        import jax

        from veles_tpu.backends import DeviceInfo
        second = bench_matmul(small)  # in-process jit cache: no compile
        info = DeviceInfo(jax.devices()[0].device_kind)
        # snapshot BOTH independent passes before the min-selection
        # below overwrites matmul_res: the ceiling ratchet must see
        # pass1 vs pass2, not winner vs itself
        first_f32 = matmul_res["float32"]
        for dtype_name in ("float32", "bfloat16"):
            limit = _rate_guard(info, dtype_name, peak)

            def plausible(res):
                return limit is None or res["tflops"] <= limit
            passes = (matmul_res[dtype_name], second[dtype_name])
            candidates = [r for r in passes if plausible(r)]
            if not candidates:  # both spiked: keep the slower
                candidates = [max(passes, key=lambda r: r["seconds"])]
            winner = dict(min(candidates, key=lambda r: r["seconds"]))
            # both rows publish their pass list, so the best-of choice
            # is auditable per dtype (round-4 verdict: the bf16 number
            # lacked the f32 row's defensibility)
            winner["passes"] = [round(r["seconds"], 9) for r in passes]
            matmul_res[dtype_name] = winner
        # persist the f32 ceiling from the SLOWER of two plausible
        # passes: a single congestion-free spike cannot ratchet the
        # guard, but a genuinely faster kernel (seen twice) can
        f32_rates = [r["tflops"] for r in (first_f32,
                                           second["float32"])
                     if not r.get("implausible")]
        limit = _rate_guard(info, "float32", peak)
        if (len(f32_rates) == 2 and not small
                and (limit is None or min(f32_rates) <= limit)):
            agreed = min(f32_rates)
            ceiling = info.get(_f32_ceiling_key())
            if ceiling is None or agreed > ceiling:
                cap = peak / 2 if peak else agreed
                info.put(_f32_ceiling_key(),
                         round(min(agreed, cap), 2))
        extras["matmul"] = matmul_res
        result["value"] = matmul_res["float32"]["seconds"]
        return True

    if not small:
        section("matmul_pass2", pass2)
        section("alexnet_b128_bfloat16", lambda: alex(b, "bfloat16"))
        lvl1 = section("matmul_f32_level1",
                       lambda: bench_matmul_f32_level1(small))
        if lvl1 is not None and "error" not in lvl1:
            extras["matmul"]["float32_level1"] = lvl1
        section("alexnet_b256_float32", lambda: alex(256, "float32"))

    extras["wall_s"] = round(time.monotonic() - t_start, 1)
    if trace_path:
        try:
            from veles_tpu.observe import summary as _summary
            from veles_tpu.observe.trace import tracer as _bt
            _bt.stop()
            _bt.save(trace_path)
            print(_summary.digest_line(_summary.load(trace_path)),
                  flush=True)
        except Exception as exc:
            print("trace digest unavailable: %s" % exc, flush=True)
    emit()
    return _compact_record(result["value"], small, extras)


def _load_record(path):
    """The last machine-readable JSON object in ``path``: a plain
    record file parses whole; a captured bench log falls back to the
    newest parseable line (the compact record is always last)."""
    with open(path) as fh:
        text = fh.read()
    try:
        record = json.loads(text)
        if isinstance(record, dict):
            return record
    except ValueError:
        pass
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue
        if isinstance(record, dict):
            return record
    raise BenchError("no JSON record found in %s" % path)


def _gate_main(argv):
    """``bench.py --gate [record.json]``: hold a compact bench record
    (given, or freshly measured when omitted) against the committed
    PERF_BASELINE.json via the perf-regression sentinel.  Exit 1 on a
    regression — for CI lanes that opt in, never for tier-1."""
    from veles_tpu.observe import baseline as _baseline
    paths = [a for a in argv[1:] if a != "--gate"]
    record = _load_record(paths[0]) if paths else main()
    ok, report = _baseline.gate(record)
    for line in _baseline.render_report(report):
        print(line, flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    if "--gate" in sys.argv:
        sys.exit(_gate_main(sys.argv))
    main()
