"""BASELINE benchmark suite (see BASELINE.md target table).

Measures, on the real chip:

- headline: autotuned Pallas tiled matmul, 3001x3001 f32, vs the
  reference's only published kernel number (0.1642 s, GTX TITAN OpenCL,
  devices/device_infos.json) — now using autotune_matmul blocks;
- the same matmul in bf16 with MXU TFLOP/s and MFU vs chip peak;
- MNIST-784 fused train step (784-100-10, batch 100): per-step time,
  samples/sec, projected whole-epoch wall-clock (600 train steps);
- AlexNet images/sec/chip, f32 and bf16, each step running the REAL
  input pipeline (Pallas gather_minibatch from an HBM-resident dataset)
  + the fused train step.

Timing method: the device may sit behind a high-latency tunnel where a
blocking fetch costs ~0.1 s regardless of compute, so every number is a
slope — two dependent chains of n1 and n2 iterations, each ended by one
scalar fetch; (t2-t1)/(n2-n1) cancels the latency.

Prints ONE JSON line: the required {metric, value, unit, vs_baseline}
headline plus an "extras" dict carrying the BASELINE metrics.
"""

import functools
import json
import os
import time

import numpy

BASELINE_MATMUL_S = 0.1642  # GTX TITAN, reference devices/device_infos.json
N = 3001

# bf16 MXU peak TFLOP/s by device kind substring (public spec sheets);
# used only to derive MFU context for bf16 measurements.
PEAK_BF16_TFLOPS = (
    ("v6", 918.0), ("v5p", 459.0), ("v5", 197.0), ("v4", 275.0),
    ("v3", 123.0), ("v2", 45.0),
)


class BenchError(RuntimeError):
    """A measurement failed plausibility checks after remeasurement.

    Raised instead of publishing an impossible number (round-2 lesson:
    a floor-clamped negative slope once published 1e-9 s/step = 1e11
    samples/sec as the official MNIST record)."""


def _slope(run_chain, n1, n2, repeats=5):
    """median over repeats of (t(n2)-t(n1))/(n2-n1).

    Median, not min: over a high-latency tunnel t(n1) spikes inflate
    individual diffs BOTH ways; min-of-slopes is biased low and can
    report physically impossible (> chip peak) rates.  May return a
    non-positive value when tunnel jitter swamps the chain delta —
    callers MUST validate (see _robust_slope), never clamp."""
    slopes = []
    for _ in range(repeats):
        t1 = run_chain(n1)
        t2 = run_chain(n2)
        slopes.append((t2 - t1) / (n2 - n1))
    return float(numpy.median(slopes))


_DISPATCH_FLOOR = None


def dispatch_floor_seconds():
    """Measured per-dispatch overhead of a trivial jitted op.

    Every train step costs at least one Python->device dispatch, so no
    honest step-time slope can fall below this; it is the physical
    floor for plausibility checks (a fused step also does real compute,
    so flagging anything under the bare-dispatch floor is conservative).
    """
    global _DISPATCH_FLOOR
    if _DISPATCH_FLOOR is not None:
        return _DISPATCH_FLOOR
    import jax

    @jax.jit
    def bump(x):
        return x + 1.0

    x = jax.device_put(numpy.float32(0))
    float(bump(x))  # compile

    def chain(k):
        acc = x
        start = time.perf_counter()
        for _ in range(k):
            acc = bump(acc)
        float(acc)
        return time.perf_counter() - start

    per = _slope(chain, 10, 1010, repeats=3)
    # Per-op enqueue costs vary several-fold between executables (a
    # trivial scalar op measured ~3x slower per dispatch than a small
    # matmul chain on the axon tunnel), so the usable floor is a
    # FRACTION of the trivial-op slope: low enough to tolerate that
    # spread, high enough to reject the zero/negative slopes the
    # round-2 clamp papered over.  10 us minimum if even this
    # measurement drowns in noise.
    _DISPATCH_FLOOR = max(0.2 * per, 1e-5)
    return _DISPATCH_FLOOR


def _robust_slope(chain, n1, n2, floor, what, repeats=5):
    """Slope with a plausibility floor and remeasure-then-fail policy.

    A slope at or below ``floor`` (one dispatch's worth of time) is a
    measurement artifact, not a fast chip.  Retry with chains 2x and
    4x longer so the compute delta grows past tunnel jitter; if every
    attempt stays implausible, raise BenchError carrying the observed
    values so the failure is loud and diagnosable.
    """
    observed = []
    for scale in (1, 2, 4):
        per = _slope(chain, n1, n2 * scale, repeats=repeats)
        observed.append(round(per, 9))
        if per > floor:
            return per
    raise BenchError(
        "%s: step-time slope implausible after remeasurement "
        "(observed %s s/step vs dispatch floor %.3g s; the tunnel "
        "is misbehaving — rerun the bench)"
        % (what, observed, floor))


def _peak_bf16(device_kind):
    kind = device_kind.lower()
    for key, peak in PEAK_BF16_TFLOPS:
        if key in kind:
            return peak
    return None


def _f32_ceiling_key():
    """Autotune-DB key for the best plausibility-checked f32 matmul
    rate measured on this chip kind (TFLOP/s) — versioned with the
    kernel algorithm, since a faster kernel makes an old ceiling a
    false upper bound that would flag every legitimate new rate."""
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    return "bench:f32_ceiling_tflops:v%d" % MATMUL_KERNEL_VERSION


def _rate_guard(info, dtype_name, peak_bf16):
    """Upper plausibility bound in TFLOP/s for one dtype, or None.

    The f32 guard is measured-ceiling * 1.25 but never above half the
    bf16 spec peak — the absolute bound keeps the ratchet from
    compounding (a noise spike that passes one guard must not loosen
    the next run's guard past physics)."""
    if dtype_name == "bfloat16":
        return peak_bf16
    hard_cap = peak_bf16 / 2 if peak_bf16 else None
    ceiling = info.get(_f32_ceiling_key())
    if ceiling:
        soft = ceiling * 1.25
        return min(soft, hard_cap) if hard_cap else soft
    return hard_cap


def bench_matmul(small):
    import jax

    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops import matmul
    from veles_tpu.ops.matmul import autotune_matmul

    n = 512 if small else N
    # small shapes are dispatch-bound; long chains keep the slope
    # above timer noise
    n1, n2 = (1, 100) if small else (1, 41)
    dev = jax.devices()[0]
    info = DeviceInfo(dev.device_kind)

    rng = numpy.random.RandomState(0)
    scale = 0.01  # keep chained products bounded
    out = {}
    for dtype_name in ("float32", "bfloat16"):
        dtype = getattr(jax.numpy, dtype_name)
        # tune at the benchmark size itself — tile optima don't
        # transfer between 2048 (power-of-two) and 3001 (padded) shapes
        blocks = autotune_matmul(
            info, size=n, dtype=dtype, precision_level=0)
        a = jax.device_put(
            ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32)
        ).astype(dtype)
        b = jax.device_put(
            ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32)
        ).astype(dtype)

        def mm(x, y):
            return matmul(x, y, precision_level=0, blocks=blocks)

        float(mm(a, b)[0, 0].astype(jax.numpy.float32))  # compile

        def chain(k):
            start = time.perf_counter()
            acc = a
            for _ in range(k):
                acc = mm(acc, b)
            float(acc[0, 0].astype(jax.numpy.float32))
            return time.perf_counter() - start

        per = _robust_slope(chain, n1, n2, dispatch_floor_seconds(),
                            "matmul_%s" % dtype_name)
        # physical sanity: a rate above chip peak is a measurement
        # artifact — remeasure with a longer chain and keep the slower.
        # bf16 guards against the MXU spec peak; f32 guards against a
        # previously MEASURED f32 ceiling (+25 % headroom) persisted in
        # the autotune DB — the MXU's multi-pass f32 path has no spec
        # sheet number, so a real measurement beats the old peak/2 guess
        peak = _peak_bf16(dev.device_kind)
        guard = _rate_guard(info, dtype_name, peak)
        for _ in range(2):
            tflops = 2.0 * n * n * n / per / 1e12
            # no grace above the guard: a rate past physical peak is
            # impossible however slightly (a 2% tolerance once let
            # 199.6 TF = 101.3% MFU into the record)
            if guard is None or tflops <= guard or small:
                break
            per = max(per, _slope(chain, n1, n2 * 2))
        tflops = 2.0 * n * n * n / per / 1e12
        if not small and dtype_name == "float32" and (
                guard is None or tflops <= guard):
            ceiling = info.get(_f32_ceiling_key())
            if ceiling is None or tflops > ceiling:
                # never persist past the physical cap (see _rate_guard)
                cap = peak / 2 if peak else tflops
                info.put(_f32_ceiling_key(),
                         round(min(tflops, cap), 2))
        row = {"seconds": round(per, 9),
               "tflops": round(tflops, 2),
               "blocks": list(blocks)}
        if not small and guard is not None and tflops > guard:
            # every remeasure still exceeded the physical bound: the
            # value is recorded for diagnosis but explicitly flagged —
            # never published as a silent >peak rate
            row["implausible"] = True
        out[dtype_name] = row
    peak = _peak_bf16(dev.device_kind)
    if peak:
        if not out["bfloat16"].get("implausible"):
            out["bfloat16"]["mfu_pct"] = round(
                100.0 * out["bfloat16"]["tflops"] / peak, 1)
        out["device_peak_bf16_tflops"] = peak
    out["device_kind"] = dev.device_kind
    return out


def _train_step_images_per_sec(specs, input_shape, batch, dataset_size,
                               dtype_name, chain_lens, classes=10):
    """Fused train step fed by the real Pallas gather from HBM."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.compiler import build_train_step
    from veles_tpu.models.zoo import build_plans_and_state
    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    dtype = getattr(jnp, dtype_name)
    plans, state, out_shape = build_plans_and_state(
        specs, input_shape, seed=1)
    has_dropout = any("Dropout" in p.forward_cls.__name__ for p in plans)
    rng = numpy.random.RandomState(0)
    dataset = jax.device_put(
        (rng.rand(dataset_size, *input_shape) * 0.5).astype(
            numpy.float32)).astype(dtype)
    labels_all = jax.device_put(
        rng.randint(0, classes, dataset_size).astype(numpy.int32))
    order = jax.device_put(
        rng.permutation(dataset_size).astype(numpy.int32))

    state = jax.tree.map(
        lambda leaf: None if leaf is None else jnp.asarray(leaf, dtype),
        state, is_leaf=lambda x: x is None)
    # device-side duplicate (leaf + 0 forces a fresh buffer): chains
    # re-seed from this without a host->device upload, which over a
    # tunneled chip costs more than the whole measured chain
    dup = jax.jit(lambda s: jax.tree.map(
        lambda leaf: None if leaf is None else leaf + 0,
        s, is_leaf=lambda x: x is None))
    step = build_train_step(plans, donate=False)
    key = jax.random.PRNGKey(0) if has_dropout else None

    # ONE dispatch per step: gather + train step fuse into a single XLA
    # program, and donating the state pytree lets XLA update the (for
    # AlexNet, hundreds of MB of) parameters in place instead of
    # double-buffering them.  The dataset/labels/order ride as ARGUMENTS
    # — closing over them would bake hundreds of MB of constants into
    # the program, which a remote-compile service has to swallow whole.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def one(state, offset, dataset, labels_all, order):
        idx = jax.lax.dynamic_slice(order, (offset,), (batch,))
        x = gather_minibatch(dataset, idx)
        y = gather_labels(labels_all, idx)
        if key is not None:
            return step(state, x, y, numpy.float32(batch),
                        jax.random.fold_in(key, offset))
        return step(state, x, y, numpy.float32(batch))

    # warm both gather and step compilations
    state2, metrics = one(dup(state), 0, dataset, labels_all, order)
    float(metrics["loss"])
    del state2  # frees a full state-sized buffer set before the chains

    # XLA's own cost model for the whole fused program (gather + fwd +
    # bwd + update) — the honest FLOP count for MFU reporting.  Lower
    # from abstract avals: no device allocation, and the same-avals
    # compile is served by the compilation cache warmed above.
    flops = None
    try:
        def aval(leaf):
            return (None if leaf is None else
                    jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
        cost = one.lower(
            jax.tree.map(aval, state, is_leaf=lambda x: x is None),
            0, aval(dataset), aval(labels_all),
            aval(order)).compile().cost_analysis()
        if cost and cost.get("flops"):
            flops = float(cost["flops"])
    except Exception:
        pass

    steps_per_epoch = dataset_size // batch

    def chain(k):
        # fresh state copy: the previous chain's buffers were donated
        s = dup(state)
        jax.block_until_ready(jax.tree.leaves(s))
        start = time.perf_counter()
        m = None
        for i in range(k):
            s, m = one(s, (i % steps_per_epoch) * batch,
                       dataset, labels_all, order)
        float(m["loss"])
        return time.perf_counter() - start

    n1, n2 = chain_lens
    per_step = _robust_slope(
        chain, n1, n2, dispatch_floor_seconds(),
        "train_step_%s_%s" % ("x".join(map(str, input_shape)),
                              dtype_name))
    return per_step, batch / per_step, flops


def bench_mnist(small):
    specs = [
        {"type": "all2all_tanh", "output_sample_shape": 100,
         "learning_rate": 0.1, "gradient_moment": 0.9},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": 0.1, "gradient_moment": 0.9},
    ]
    batch = 100
    # n2 >= 500: at ~1.6 ms/step the long chain runs ~0.9 s, far above
    # tunnel jitter — the round-2 failure was a 100-step delta (0.16 s)
    # drowned by latency spikes of the same magnitude
    per_step, sps, _ = _train_step_images_per_sec(
        specs, (784,), batch, 6000 if not small else 1000,
        "float32", (2, 22) if small else (10, 510))
    steps_per_epoch = 60000 // batch
    return {
        "step_seconds": round(per_step, 9),
        "samples_per_sec": round(sps, 1),
        "epoch_seconds_projected": round(per_step * steps_per_epoch, 3),
        "batch": batch,
    }


def bench_alexnet(small):
    import jax

    from veles_tpu.models.zoo import alexnet_layers

    size = 67 if small else 227
    dataset = 256 if small else 1024
    peak = _peak_bf16(jax.devices()[0].device_kind)

    def rows(batch, chain_lens):
        out = {}
        for dtype_name in ("float32", "bfloat16"):
            per_step, ips, flops = _train_step_images_per_sec(
                alexnet_layers(classes=1000 if not small else 10),
                (size, size, 3), batch, dataset, dtype_name,
                chain_lens, classes=1000 if not small else 10)
            row = {"step_seconds": round(per_step, 9),
                   "images_per_sec": round(ips, 1)}
            if flops:
                row["tflops"] = round(flops / per_step / 1e12, 2)
                if peak and dtype_name == "bfloat16":
                    row["mfu_pct"] = round(
                        100.0 * flops / per_step / 1e12 / peak, 1)
            out[dtype_name] = row
        return out

    # batch 128 = the historical comparison row (and what SCALING.json
    # projects from); batch 256 = the measured throughput sweet spot
    # (52% MFU, bf16 1.5x f32 — fixed per-step overheads dilute the
    # bf16 win at 128)
    batch = 32 if small else 128
    out = rows(batch, (1, 10) if small else (4, 44))
    out["batch"] = batch
    if not small:
        out["batch_256"] = rows(256, (2, 12))
        out["precision_note"] = (
            "f32 rows use XLA TPU default matmul precision, which "
            "computes f32 convs/dense with one bf16 MXU pass; true "
            "f32 (precision=highest) measured 3.1x slower "
            "(36.0 ms/step at batch 128).  bf16's win over default-"
            "f32 is therefore memory traffic, not MXU rate — it "
            "reaches 1.5x at batch 256 where fixed overheads "
            "amortize.")
    return out


def bench_native(small):
    """C++ inference runtime throughput on an exported MLP package
    (wavefront engine; host CPU, not the TPU — the runtime's job is
    chip-free serving, reference libVeles)."""
    import tempfile

    from veles_tpu import native
    from veles_tpu.backends import Device
    native.build_native()

    from tests.test_native import _train_mlp

    sw = _train_mlp(Device(backend="numpy"), epochs=1)
    pkg = os.path.join(tempfile.mkdtemp(prefix="bench_native_"),
                       "mlp.tar")
    sw.package_export(pkg)
    wf = native.NativeWorkflow(pkg)
    rng = numpy.random.RandomState(0)
    out = {}
    for batch in (1, 256):
        x = rng.rand(batch, wf.input_size).astype(numpy.float32)
        wf.run(x)  # warm the arena plan
        n = 2000 if small else 10000
        start = time.perf_counter()
        for _ in range(max(1, n // batch)):
            wf.run(x)
        elapsed = time.perf_counter() - start
        rows = max(1, n // batch) * batch
        out["batch_%d_rows_per_sec" % batch] = round(rows / elapsed, 1)
    return out


def main():
    small = bool(os.environ.get("VELES_BENCH_SMALL"))
    extras = {}

    matmul_res = bench_matmul(small)
    extras["matmul"] = matmul_res
    try:
        extras["mnist_784_100_10"] = bench_mnist(small)
    except Exception as exc:  # keep the headline alive
        extras["mnist_784_100_10"] = {"error": repr(exc)}
    try:
        extras["alexnet"] = bench_alexnet(small)
    except Exception as exc:
        extras["alexnet"] = {"error": repr(exc)}
    try:
        extras["native_inference"] = bench_native(small)
    except Exception as exc:
        extras["native_inference"] = {"error": repr(exc)}

    # a tunneled chip's congestion varies minute to minute; measure the
    # headline twice (start + end of the suite) and keep the faster
    # pass.  Each pass's own guard already remeasures rates above chip
    # peak, and the cap below rejects a still-impossible pass outright
    # so min-time cannot lock in a spuriously fast sample.
    if not small:
        try:
            import jax

            from veles_tpu.backends import DeviceInfo
            second = bench_matmul(small)  # tuned-table cache hit
            peak = matmul_res.get("device_peak_bf16_tflops")
            info = DeviceInfo(jax.devices()[0].device_kind)
            for dtype_name in ("float32", "bfloat16"):
                limit = _rate_guard(info, dtype_name, peak)

                def plausible(res):
                    return (limit is None
                            or res["tflops"] <= limit)
                candidates = [r for r in (matmul_res[dtype_name],
                                          second[dtype_name])
                              if plausible(r)]
                if not candidates:  # both spiked: keep the slower
                    candidates = [max((matmul_res[dtype_name],
                                       second[dtype_name]),
                                      key=lambda r: r["seconds"])]
                matmul_res[dtype_name] = min(
                    candidates, key=lambda r: r["seconds"])
        except Exception:
            pass

    per_matmul = matmul_res["float32"]["seconds"]
    n = 512 if small else N
    print(json.dumps({
        "metric": "matmul_%dx%d_f32_avg_time" % (n, n),
        "value": per_matmul,
        "unit": "s",
        "vs_baseline": (round(BASELINE_MATMUL_S / per_matmul, 2)
                        if not small else None),
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
