"""Headline benchmark vs the reference's only published kernel number.

Reference: autotuned OpenCL tiled matmul, 3001x3001 float32,
PRECISION_LEVEL 0, avg 0.1642 s on a GTX TITAN
(devices/device_infos.json — the sole quantitative entry in the repo;
see BASELINE.md).  Same shape, same dtype, our Pallas TPU matmul.

Timing method: the execution environment may put the device behind a
high-latency tunnel, where a blocking fetch costs ~0.1 s regardless of
compute.  We therefore time two DEPENDENT chains of n1 and n2 matmuls,
each ended by a scalar fetch, and report the slope
(t2 - t1) / (n2 - n1) — pure device time per matmul, latency cancelled.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
vs_baseline > 1 means faster than the reference.
"""

import json
import os
import time

import numpy

BASELINE_S = 0.1642  # GTX TITAN, devices/device_infos.json
N = 3001


def _chain_time(matmul_fn, a, b, n):
    start = time.perf_counter()
    acc = a
    for _ in range(n):
        acc = matmul_fn(acc, b)
    float(acc[0, 0])  # forces completion + round trip
    return time.perf_counter() - start


def main():
    from veles_tpu.ops import matmul

    import jax

    small = bool(os.environ.get("VELES_BENCH_SMALL"))
    n = 512 if small else N
    n1, n2 = (1, 6) if small else (1, 41)

    rng = numpy.random.RandomState(0)
    scale = 0.01  # keep chained products bounded
    a = jax.device_put(
        ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32))
    b = jax.device_put(
        ((rng.rand(n, n) - 0.5) * scale).astype(numpy.float32))

    def mm(x, y):
        return matmul(x, y, precision_level=0)

    float(mm(a, b)[0, 0])  # compile + warmup

    per_matmul = min(
        (_chain_time(mm, a, b, n2) - _chain_time(mm, a, b, n1)) / (n2 - n1)
        for _ in range(3))

    print(json.dumps({
        "metric": "matmul_%dx%d_f32_avg_time" % (n, n),
        "value": round(per_matmul, 6),
        "unit": "s",
        "vs_baseline": (round(BASELINE_S / per_matmul, 2)
                        if n == N else None),
    }))


if __name__ == "__main__":
    main()
