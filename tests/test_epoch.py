"""build_train_epoch: the one-dispatch-per-epoch scan path must
reproduce the per-step path exactly (same gathers, same solver)."""

import numpy
import pytest

import jax
import jax.numpy as jnp


def _setup(loss="softmax", with_dropout=False):
    from veles_tpu.models.zoo import build_plans_and_state

    specs = [
        {"type": "all2all_tanh", "output_sample_shape": 24,
         "learning_rate": 0.05, "gradient_moment": 0.9},
    ]
    if with_dropout:
        specs.append({"type": "dropout", "dropout_ratio": 0.3})
    if loss == "softmax":
        specs.append({"type": "softmax", "output_sample_shape": 5,
                      "learning_rate": 0.05, "gradient_moment": 0.9})
    else:
        specs.append({"type": "all2all", "output_sample_shape": 12,
                      "learning_rate": 0.05, "gradient_moment": 0.9})
    plans, state, _ = build_plans_and_state(specs, (12,), seed=3)
    rng = numpy.random.RandomState(0)
    n, batch = 96, 16
    dataset = jnp.asarray(rng.rand(n, 12).astype(numpy.float32))
    if loss == "softmax":
        targets = jnp.asarray(rng.randint(0, 5, n).astype(numpy.int32))
    else:
        targets = jnp.asarray(rng.rand(n, 12).astype(numpy.float32))
    order = jnp.asarray(rng.permutation(n).astype(numpy.int32))
    return plans, state, dataset, targets, order, batch


@pytest.mark.parametrize("loss", ["softmax", "mse"])
def test_epoch_scan_matches_stepwise(loss):
    from veles_tpu.compiler import build_train_epoch, build_train_step
    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    plans, state, dataset, targets, order, batch = _setup(loss)
    epoch = build_train_epoch(plans, batch, loss=loss, donate=False)
    new_state, totals = epoch(state, dataset, targets, order)

    step = build_train_step(plans, loss=loss, donate=False)
    st = state
    losses, n_err = [], 0
    for i in range(order.shape[0] // batch):
        idx = order[i * batch:(i + 1) * batch]
        x = gather_minibatch(dataset, idx)
        y = (gather_labels(targets, idx) if loss == "softmax"
             else gather_minibatch(targets, idx))
        st, m = step(st, x, y, numpy.float32(batch))
        losses.append(float(m["loss"]))
        n_err += int(m["n_err"])

    for got, want in zip(jax.tree.leaves(new_state),
                         jax.tree.leaves(st)):
        numpy.testing.assert_allclose(
            numpy.asarray(got), numpy.asarray(want),
            rtol=1e-5, atol=1e-6)
    numpy.testing.assert_allclose(
        float(totals["loss_mean"]), numpy.mean(losses), rtol=1e-5)
    assert int(totals["n_err"]) == n_err


def test_epoch_scan_with_dropout_trains():
    from veles_tpu.compiler import build_train_epoch

    plans, state, dataset, targets, order, batch = _setup(
        with_dropout=True)
    epoch = build_train_epoch(plans, batch, donate=False)
    key = jax.random.PRNGKey(7)
    st, t1 = epoch(state, dataset, targets, order, key)
    st, t2 = epoch(st, dataset, targets, order,
                   jax.random.fold_in(key, 1))
    assert numpy.isfinite(float(t1["loss_mean"]))
    # training progresses across scanned epochs
    assert float(t2["loss_mean"]) < float(t1["loss_mean"])


def test_epoch_scan_donation_chains():
    """donate=True (the perf default): chained epochs reuse buffers."""
    from veles_tpu.compiler import build_train_epoch

    plans, state, dataset, targets, order, batch = _setup()
    epoch = build_train_epoch(plans, batch)
    st = jax.tree.map(lambda l: None if l is None else jnp.asarray(l),
                      state, is_leaf=lambda x: x is None)
    losses = []
    for _ in range(3):
        st, totals = epoch(st, dataset, targets, order)
        losses.append(float(totals["loss_mean"]))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("loss", ["softmax", "mse"])
def test_eval_epoch_matches_direct_forward(loss):
    from veles_tpu.compiler import build_eval_epoch, build_forward

    plans, state, dataset, targets, order, batch = _setup(loss)
    params = [{"weights": s["weights"], "bias": s["bias"]}
              for s in state]
    ev = build_eval_epoch(plans, batch, loss=loss)
    got = ev(params, dataset, targets, order)

    fwd = build_forward(plans)
    n = (order.shape[0] // batch) * batch
    idx = numpy.asarray(order)[:n]
    x = numpy.asarray(dataset)[idx]
    out = numpy.asarray(fwd(params, jnp.asarray(x)))
    if loss == "softmax":
        want = int((out.argmax(-1) != numpy.asarray(targets)[idx]).sum())
        assert int(got["n_err"]) == want
    else:
        # forward output for mse plans has no softmax; match the
        # evaluator's per-sample feature-mean sum
        t = numpy.asarray(targets)[idx].reshape(n, -1)
        diff = out.reshape(n, -1) - t
        want = float((diff * diff).mean(axis=1).sum())
        numpy.testing.assert_allclose(float(got["mse_sum"]), want,
                                      rtol=1e-5)
    assert int(got["samples"]) == n


@pytest.mark.parametrize("loss", ["softmax", "mse"])
def test_epoch_scan_masked_tail_matches_stepwise(loss):
    """Non-multiple split: the tail executes as one masked step and
    must reproduce the per-step path run with a short final
    minibatch — exact N-sample coverage, no drop-last."""
    from veles_tpu.compiler import build_train_epoch, build_train_step
    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    plans, state, dataset, targets, order, batch = _setup(loss)
    n = 90  # 5 full 16-batches + a 10-sample tail
    order = order[:n]
    epoch = build_train_epoch(plans, batch, loss=loss, donate=False)
    new_state, totals = epoch(state, dataset, targets, order)

    step = build_train_step(plans, loss=loss, donate=False)
    st = state
    loss_weighted, n_err = 0.0, 0
    for start in range(0, n, batch):
        idx = order[start:start + batch]
        size = int(idx.shape[0])
        x = gather_minibatch(dataset, idx)
        y = (gather_labels(targets, idx) if loss == "softmax"
             else gather_minibatch(targets, idx))
        st, m = step(st, x, y, numpy.float32(size))
        loss_weighted += float(m["loss"]) * size
        n_err += int(m["n_err"])

    for got, want in zip(jax.tree.leaves(new_state),
                         jax.tree.leaves(st)):
        numpy.testing.assert_allclose(
            numpy.asarray(got), numpy.asarray(want),
            rtol=1e-5, atol=1e-6)
    numpy.testing.assert_allclose(
        float(totals["loss_mean"]), loss_weighted / n, rtol=1e-5)
    assert int(totals["n_err"]) == n_err


@pytest.mark.parametrize("loss", ["softmax", "mse"])
def test_eval_epoch_masked_tail_exact_coverage(loss):
    """Eval metrics must cover ALL N samples on a non-multiple split."""
    from veles_tpu.compiler import build_eval_epoch, build_forward

    plans, state, dataset, targets, order, batch = _setup(loss)
    n = 90
    order = order[:n]
    params = [{"weights": s["weights"], "bias": s["bias"]}
              for s in state]
    ev = build_eval_epoch(plans, batch, loss=loss)
    got = ev(params, dataset, targets, order)
    assert int(got["samples"]) == n

    fwd = build_forward(plans)
    idx = numpy.asarray(order)
    out = numpy.asarray(fwd(params, dataset[jnp.asarray(idx)]))
    if loss == "softmax":
        want = int((out.argmax(-1) != numpy.asarray(targets)[idx]).sum())
        assert int(got["n_err"]) == want
    else:
        t = numpy.asarray(targets)[idx].reshape(n, -1)
        diff = out.reshape(n, -1) - t
        numpy.testing.assert_allclose(
            float(got["mse_sum"]),
            float((diff * diff).mean(axis=1).sum()), rtol=1e-5)


def test_eval_epoch_samples_excludes_sentinel_labels():
    """samples counts rows that entered the metric: sentinel (-1)
    labels must not dilute n_err/samples (advisor r04)."""
    from veles_tpu.compiler import build_eval_epoch

    plans, state, dataset, targets, order, batch = _setup("softmax")
    targets = numpy.asarray(targets).copy()
    targets[:7] = -1  # 7 sentinel rows somewhere in the epoch
    params = [{"weights": s["weights"], "bias": s["bias"]}
              for s in state]
    ev = build_eval_epoch(plans, batch, loss="softmax")
    got = ev(params, dataset, jnp.asarray(targets), order)
    assert int(got["samples"]) == order.shape[0] - 7


@pytest.mark.slow
def test_digits_turbo_example_reaches_anchor_quality():
    """The runnable three-gears example (examples/digits_turbo.py)
    trains the real-digits anchor through the epoch-scan path to the
    same quality class as the unit-graph workflow."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples",
                                      "digits_turbo.py"),
         "--backend", "cpu", "--epochs", "30"],
        capture_output=True, text=True, timeout=300, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-1500:]
    last = [l for l in proc.stdout.splitlines()
            if l.startswith("best validation error")][-1]
    err = float(last.split()[3].rstrip("%"))
    assert err < 4.0, last  # unit-graph anchor reaches 1.39%
