"""Cluster-scope observability (PR 5): clock-offset estimation, trace
chunk shipping + merge, the black-box flight recorder, XLA
introspection (recompiles, step FLOPs, live MFU), and the crash-path
trace/flight preservation in the launcher."""

import json
import threading
import time

import pytest

from veles_tpu.observe.cluster import TraceCollector, estimate_offset
from veles_tpu.observe.flight import (FlightRecorder, flight,
                                      validate_flight)
from veles_tpu.observe.merge import merge_parts, merge_run, part_from_doc
from veles_tpu.observe.metrics import MetricsRegistry, registry
from veles_tpu.observe.trace import SpanTracer, validate_trace

pytestmark = pytest.mark.observe


# -- clock-offset estimator (NTP-style join handshake) ---------------------


def test_estimate_offset_symmetric_rtt_recovers_exactly():
    """Symmetric path: the classic four-timestamp formula recovers the
    true offset regardless of the RTT magnitude."""
    true_offset = 2.5       # server clock ahead by 2.5 s
    one_way = 0.02          # symmetric 20 ms each way
    samples = []
    for i in range(5):
        t0 = 100.0 + i
        t1 = t0 + one_way + true_offset
        t2 = t1
        t3 = t0 + 2 * one_way
        samples.append((t0, t1, t2, t3))
    offset, delay = estimate_offset(samples)
    assert abs(offset - true_offset) < 1e-9
    assert abs(delay - 2 * one_way) < 1e-9


def test_estimate_offset_asymmetric_prefers_min_delay_sample():
    """Asymmetric probes mis-estimate by at most delay/2; the
    estimator must pick the MINIMUM-delay sample, where that bound is
    tightest — not average the noisy ones in."""
    true_offset = 1.0
    # 0.5 s out / 0.1 s back: grossly asymmetric, delay 0.6
    noisy = (0.0, 0.5 + true_offset, 0.5 + true_offset, 0.6)
    # 10/11 ms: near-symmetric, delay 21 ms
    clean = (10.0, 10.010 + true_offset, 10.010 + true_offset, 10.021)
    offset, delay = estimate_offset([noisy, clean])
    assert abs(delay - 0.021) < 1e-9, "min-delay sample must win"
    assert abs(offset - true_offset) <= 0.021 / 2 + 1e-9
    with pytest.raises(ValueError):
        estimate_offset([])


# -- trace chunks + merge --------------------------------------------------


def _recording_tracer(label):
    """A tracer with a private (disabled) flight sink so these tests
    never touch the process-global ring."""
    tracer = SpanTracer(flight=FlightRecorder(enabled=False))
    tracer.start()
    tracer.label = label
    return tracer


def test_take_chunk_pops_bounded_and_preserves_thread_names():
    tracer = _recording_tracer("worker")
    for i in range(10):
        tracer.instant("e%d" % i)
    chunk = tracer.take_chunk(max_events=4)
    assert chunk["schema"] == 1
    assert [e["name"] for e in chunk["events"]] == \
        ["e0", "e1", "e2", "e3"]
    assert chunk["label"] == "worker"
    assert chunk["wall_epoch"] > 0
    # the names map replaces the popped thread_name metadata event
    tid = chunk["events"][0]["tid"]
    assert chunk["threads"][str(tid)] != ""
    # the rest stays recorded; a later chunk picks it up
    rest = tracer.take_chunk()
    assert [e["name"] for e in rest["events"]] == \
        ["e%d" % i for i in range(4, 10)]
    assert tracer.take_chunk() is None


def test_take_chunk_thread_scoping_separates_shared_tracer():
    """trace_scope="threads" (in-process two-node tests): only events
    recorded by the named threads ship; the rest stay."""
    tracer = _recording_tracer("shared")
    tracer.instant("main-event")
    seen = {}

    def worker():
        seen["ident"] = threading.get_ident()
        tracer.instant("worker-event")

    thread = threading.Thread(target=worker, name="chunk-worker")
    thread.start()
    thread.join()
    chunk = tracer.take_chunk(idents={seen["ident"]})
    assert [e["name"] for e in chunk["events"]] == ["worker-event"]
    remaining = {e["name"] for e in tracer.events if e["ph"] != "M"}
    assert remaining == {"main-event"}


def test_merge_two_process_traces_tracks_and_corrected_timestamps(
        tmp_path):
    """Round-trip: two synthetic per-process traces -> one merged doc
    with separate process tracks, offset-corrected, monotonic
    timestamps."""
    master = _recording_tracer("master")
    with master.span("m.outer", cat="test"):
        with master.span("m.inner", cat="test"):
            time.sleep(0.002)
        master.instant("proto.job_out", cat="proto", job="j1")
    master.stop()

    slave = _recording_tracer("slave:host:1")
    with slave.span("slave.job", cat="proto", job="j1"):
        time.sleep(0.002)
    slave.stop()
    # pretend the slave's wall clock runs 5 s behind the master's; the
    # join-time estimate (+5 s) must pull its events back into line
    slave._epoch_wall -= 5.0

    mp, sp = str(tmp_path / "m.json"), str(tmp_path / "s.json")
    master.save(mp)
    slave.save(sp)
    with open(mp) as fin:
        mdoc = json.load(fin)
    with open(sp) as fin:
        sdoc = json.load(fin)
    merged = merge_parts(
        [part_from_doc(mdoc), part_from_doc(sdoc, offset_s=5.0)],
        trace_id="tid-1")
    validate_trace(merged)
    events = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    # monotonic corrected timeline
    stamps = [e["ts"] for e in events]
    assert stamps == sorted(stamps)
    assert all(ts >= 0 for ts in stamps)
    # track separation: per-part synthetic pids + process_name metadata
    by_name = {e["name"]: e for e in events}
    assert by_name["m.outer"]["pid"] != by_name["slave.job"]["pid"]
    procs = {(e.get("args") or {}).get("name")
             for e in merged["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"master", "slave:host:1"}
    # offset correction: with +5 s applied the slave span lands within
    # the (sub-second) master recording window, not 5 s away
    span_gap = abs(by_name["slave.job"]["ts"] - by_name["m.outer"]["ts"])
    assert span_gap < 2e6, "offset correction must realign the clocks"
    assert merged["otherData"]["trace_id"] == "tid-1"


def test_trace_collector_bounds_and_labels():
    collector = TraceCollector(max_events=5)
    chunk = {"schema": 1, "pid": 1, "label": "slave:a",
             "wall_epoch": 1.0, "threads": {},
             "events": [{"ph": "i", "ts": 0.0, "name": "e",
                         "pid": 1, "tid": 1}] * 4}
    assert collector.add_chunk("a", chunk) == 4
    assert collector.add_chunk("a", chunk) == 1  # bounded
    assert collector.dropped_events == 3
    collector.add_chunk("a", {"schema": 99, "events": []})  # unknown
    collector.set_offset("a", 0.25, 0.01)
    parts = collector.parts()
    assert len(parts) == 1
    assert parts[0]["label"] == "slave:a"
    assert parts[0]["offset_s"] == 0.25
    assert sum(len(c["events"]) for c in parts[0]["chunks"]) == 5


# -- flight recorder -------------------------------------------------------


def test_flight_ring_semantics_and_dump_schema(tmp_path):
    recorder = FlightRecorder(capacity=32, enabled=True,
                              base_path=str(tmp_path / "fl"))
    for i in range(100):
        recorder.record("instant", "e%d" % i)
    assert len(recorder) == 32  # ring keeps only the most recent
    events = recorder.snapshot()
    assert events[0]["name"] == "e68"
    assert events[-1]["name"] == "e99"
    path = recorder.dump(reason="unit test")
    with open(path) as fin:
        doc = json.load(fin)
    validate_flight(doc)
    assert doc["reason"] == "unit test"
    assert len(doc["events"]) == 32
    # sequenced: a second dump never overwrites the first
    assert recorder.dump(reason="unit test") != path


def test_disabled_tracer_still_feeds_flight_ring():
    """The black box works without --trace: complete/instant/counter
    route into the flight ring even while full tracing is off."""
    ring = FlightRecorder(capacity=64, enabled=True)
    tracer = SpanTracer(flight=ring)
    assert not tracer.enabled and tracer.active
    with tracer.span("step", cat="test"):
        pass
    tracer.instant("proto.evt")
    tracer.counter("depth", 2)
    assert tracer.events == []  # the trace buffer stays empty
    kinds = [(e["kind"], e["name"]) for e in ring.snapshot()]
    assert kinds == [("span", "step"), ("instant", "proto.evt"),
                     ("counter", "depth")]
    span = ring.snapshot()[0]
    assert span["dur_s"] >= 0 and span["ts"] > 0
    # and with the ring ALSO off, nothing records anywhere
    ring.enabled = False
    assert not tracer.active
    with tracer.span("ignored"):
        pass
    assert len(ring) == 3


def test_validate_flight_rejects_malformed():
    with pytest.raises(ValueError):
        validate_flight([])
    with pytest.raises(ValueError, match="missing"):
        validate_flight({"kind": "flight"})
    good = FlightRecorder(capacity=16).document("x")
    validate_flight(good)
    bad = dict(good, schema=99)
    with pytest.raises(ValueError, match="schema"):
        validate_flight(bad)


# -- XLA introspection -----------------------------------------------------


def test_recompile_watcher_detects_forced_donated_shape_recompile():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from veles_tpu.observe.xla_introspect import CompileWatcher
    reg = MetricsRegistry()
    watcher = CompileWatcher(registry=reg, warn_after=1)
    assert watcher.install()

    step = jax.jit(lambda x: x * 3.0, donate_argnums=(0,))
    assert watcher.watch(step, "step")
    step(jnp.ones((8, 8)))
    assert watcher.poll() == {"step": 1}
    # a changed donated shape silently recompiles — the exact storm
    # signature the watcher exists to catch
    step(jnp.ones((4, 8)))
    warned = []
    sizes = watcher.poll(warn=lambda name, size:
                         warned.append((name, size)))
    assert sizes["step"] == 2
    assert warned == [("step", 2)]
    assert reg.counter("compile.recompiles").value >= 1
    # the monitoring listener counted the backend compiles globally
    assert reg.counter("compile.count").value >= 2
    assert reg.counter("compile.seconds").value > 0


def test_device_memory_gauges_census_fallback():
    pytest.importorskip("jax")
    from veles_tpu.observe.xla_introspect import device_memory_gauges
    reg = MetricsRegistry()
    out = device_memory_gauges(reg)
    # CPU backends lack memory_stats -> live-array census; either way
    # at least one gauge must land
    assert out
    assert all(isinstance(v, int) and v >= 0 for v in out.values())


def test_mfu_snapshot_pipeline(monkeypatch):
    from veles_tpu.observe import xla_introspect
    reg = MetricsRegistry()
    assert xla_introspect.mfu_snapshot(reg) is None  # nothing published
    xla_introspect.set_step_flops(2e9, reg)
    hist = reg.histogram("step.train_s")
    for _ in range(8):
        hist.observe(0.001)  # 2e9 flops / 1ms = 2 TFLOP/s achieved
    monkeypatch.setenv("VELES_PEAK_TFLOPS", "4")
    monkeypatch.setattr(xla_introspect, "_peak_cache", {})
    mfu = xla_introspect.mfu_snapshot(reg)
    assert mfu is not None and abs(mfu - 50.0) < 1.0
    assert reg.peek("xla.mfu_pct").value == mfu
    # the health surface picks it up without extra publication
    from veles_tpu.observe.metrics import health_snapshot
    assert health_snapshot(reg)["mfu_pct"] == mfu


# -- heartbeat: compile/mfu fields on the fused path -----------------------


def test_heartbeat_carries_compile_count_and_mfu_on_fused_run(
        cpu_device, tmp_path):
    """Acceptance: heartbeat JSONL lines from a fused run carry
    non-null compile.count and mfu_pct."""
    from veles_tpu.observe.profile import validate_heartbeat
    from tests.test_observe import _trace_smoke_run
    registry.reset()
    doc, lines = _trace_smoke_run(cpu_device, tmp_path, pipeline=False)
    assert lines
    final = lines[-1]
    validate_heartbeat(final)
    assert final["mono"] > 0  # schema v2: both clocks on every line
    assert final["compile"]["count"] > 0
    assert final["compile"]["seconds"] > 0
    assert final["mfu_pct"] is not None and final["mfu_pct"] > 0
    # the trace side still validates with the new anchor metadata
    validate_trace(doc)
    assert doc["otherData"]["wall_epoch"] > 0


# -- launcher crash paths --------------------------------------------------


def test_launcher_saves_trace_and_flight_on_unhandled_exception(
        cpu_device, tmp_path):
    """Satellite: --trace output (and a flight dump) must survive an
    unhandled exception, verified through a chaos kill point in the
    input pipeline worker."""
    from veles_tpu import chaos, prng
    from veles_tpu.chaos import FaultPlan
    from veles_tpu.launcher import Launcher
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from tests.test_models import BlobsLoader

    registry.reset()
    trace_path = str(tmp_path / "crash_trace.json")
    prng.get().seed(991)
    launcher = Launcher(trace=trace_path)
    StandardWorkflow(
        launcher,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=32, on_device=False,
            prng=RandomGenerator("obsc_crash", seed=3)),
        decision_config=dict(max_epochs=4),
    ).fuse(pipeline=True)
    launcher.initialize(device=cpu_device)
    chaos.install(FaultPlan().add("pipeline.serve", "exc", nth=3))
    try:
        with pytest.raises(RuntimeError, match="injected serve"):
            launcher.run()
    finally:
        chaos.uninstall()
        launcher.stop()
    # the trace survived the crash (saved on the exception exit path)
    with open(trace_path) as fin:
        doc = json.load(fin)
    validate_trace(doc)
    # the crash lands during the first (eval) minibatches — the saved
    # buffer must still hold the spans recorded up to that point
    names = {e.get("name") for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert "FusedTrainer" in names and "pipeline.fill" in names
    # ...and the flight recorder dumped next to it
    dumps = list(tmp_path.glob("crash_trace.json.flight.exception.*"))
    assert dumps, "flight dump must be emitted on the exception path"
    with open(str(dumps[0])) as fin:
        fdoc = json.load(fin)
    validate_flight(fdoc)
    assert fdoc["reason"] == "exception"
    assert any(e["kind"] == "span" for e in fdoc["events"])


# -- end-to-end: two-node chaos run -> merged trace + flight dump ----------


@pytest.mark.chaos
def test_two_node_chaos_run_merged_trace_and_quarantine_dump(
        cpu_device, tmp_path):
    """Acceptance: an in-proc master+slave run with an injected
    poisoned update produces (a) a flight dump at the quarantine, and
    (b) a merged Perfetto trace where one job id links the master's
    proto.job_out and the slave's job span on separate process tracks
    under the run's trace id."""
    from veles_tpu import chaos
    from veles_tpu.chaos import FaultPlan
    from veles_tpu.client import Client
    from veles_tpu.observe.trace import tracer
    from tests.test_network import _build, _start_server

    registry.reset()
    old_base, flight.base_path = flight.base_path, \
        str(tmp_path / "flight")
    tracer.start()
    tracer.label = "master"
    try:
        master = _build("master", "obsc_m", cpu_device)
        slave = _build("slave", "obsc_s", cpu_device)
        server, _ = _start_server(master, blacklist_ttl=0.6)
        client = Client("127.0.0.1:%d" % server.port, slave,
                        trace_scope="threads")
        plan = chaos.install(
            FaultPlan().add("net.update", "nan", nth=2))
        try:
            client.run()
        finally:
            chaos.uninstall()
        assert server._done.wait(15)
        assert plan.fired("net.update") == 1
    finally:
        tracer.stop()
        flight.base_path = old_base
    assert server.quarantined == 1
    assert bool(master.decision.complete)

    # trace context propagated through the protocol at join time
    assert client.trace_id == server.trace_id
    assert client.clock_offset is not None
    assert abs(client.clock_offset) < 1.0  # same host, same clock
    assert client.trace_chunks_sent > 0

    # (a) schema-valid flight dump emitted AT the injected failure
    dumps = sorted(tmp_path.glob("flight.quarantine.*.json"))
    assert dumps
    with open(str(dumps[0])) as fin:
        fdoc = json.load(fin)
    validate_flight(fdoc)
    assert fdoc["reason"] == "quarantine"
    assert any(e["kind"] == "instant" and
               e["name"] == "proto.quarantine"
               for e in fdoc["events"])

    # (b) merged cluster trace: master doc + shipped slave chunks
    trace_path = str(tmp_path / "master.json")
    tracer.save(trace_path)
    with open(trace_path) as fin:
        master_doc = json.load(fin)
    assert server.trace_collector.keys()
    merged = merge_run(master_doc, server.trace_collector,
                       trace_id=server.trace_id)
    validate_trace(merged)
    assert merged["otherData"]["trace_id"] == server.trace_id
    # the shared in-proc tracer must not leak the master's label onto
    # the slave's shipped chunks: two DISTINCT process names
    procs = {(e.get("args") or {}).get("name")
             for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "master" in procs
    assert any(name.startswith("slave:") for name in procs)
    events = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    stamps = [e["ts"] for e in events]
    assert stamps == sorted(stamps), "merged timeline must be monotonic"

    def jobs_of(name, ph):
        return {(e.get("args") or {}).get("job"): e["pid"]
                for e in events
                if e["name"] == name and e.get("ph") == ph}

    job_out = jobs_of("proto.job_out", "i")
    slave_spans = jobs_of("slave.job", "X")
    update_in = jobs_of("proto.update_in", "i")
    stitched = set(job_out) & set(slave_spans) & set(update_in)
    assert stitched, "one job id must link master and slave events"
    for job in stitched:
        assert job_out[job] != slave_spans[job], \
            "master and slave events must sit on separate process tracks"
        assert job_out[job] == update_in[job]
    # the slave's protocol instants carry the shared trace id
    slave_traced = [e for e in events if e["name"] == "proto.job_in"]
    assert slave_traced
    assert all((e["args"] or {}).get("trace") ==
               server.trace_id[:8] for e in slave_traced)
