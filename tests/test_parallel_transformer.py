"""Model sharding beyond data-parallel, driving the NEW transformer
blocks: tensor-parallel head/column sharding (parallel/tensor.py) and
the pipeline-parallel stage split (parallel/pipeline.py), both against
the single-device fused step over 3 chained train steps on the
8-device CPU mesh — plus pipeline_forward/moe_apply compositions over
real TransformerBlock stages (the pre-existing pipeline-MoE tests use
synthetic stages).  docs/distributed.md "Model parallelism"."""

import numpy
import pytest

pytestmark = [pytest.mark.transformer, pytest.mark.dist]

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from veles_tpu.compiler import build_train_step  # noqa: E402
from veles_tpu.models.zoo import (  # noqa: E402
    build_plans_and_state, transformer_layers)
from veles_tpu.parallel.mesh import make_mesh  # noqa: E402
from veles_tpu.parallel.pipeline import (  # noqa: E402
    build_pipeline_train_step, stack_pipeline_state,
    unstack_pipeline_state)
from veles_tpu.parallel.tensor import (  # noqa: E402
    build_tp_train_step, gather_tp_state, place_tp_state)

#: the receipted ULP bound for the model-parallel paths: the TP output
#: projection is a psum of per-shard partial contractions and the
#: microbatched pipeline accumulates per-microbatch wgrads — different
#: f32 reduction groupings than the single-device step, compounded
#: through 3 momentum steps.  Measured 1.5e-4 (TP) / 9.1e-5 (mb=2
#: pipeline) on this model; the bound gives ~6x headroom.
ULP_BOUND_3_STEPS = 1e-3


def _setup(seed=3, heads=4):
    specs = transformer_layers(blocks=2, heads=heads, hidden=16,
                               classes=10)
    plans, state, _ = build_plans_and_state(specs, (8, 8), seed=seed)
    rng = numpy.random.RandomState(5)
    x = jnp.asarray(rng.rand(16, 8, 8), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, 16), jnp.int32)
    return plans, state, x, y, numpy.float32(16)


def _run3(step, state, x, y, bs, **kw):
    losses = []
    for _ in range(3):
        state, m = step(state, x, y, bs, **kw)
        losses.append(float(m["loss"]))
    return state, losses, m


def _host(state):
    return [{k: (None if v is None else numpy.asarray(v))
             for k, v in e.items()} for e in state]


def _maxrel(ref, got):
    worst = 0.0
    for re, ge in zip(ref, got):
        for key in re:
            if re[key] is None or ge.get(key) is None:
                continue
            a = numpy.asarray(re[key], numpy.float64)
            b = numpy.asarray(ge[key], numpy.float64)
            worst = max(worst, float(
                numpy.abs(a - b).max() / max(numpy.abs(a).max(),
                                             1e-9)))
    return worst


def _assert_bit_identical(ref, got):
    for re, ge in zip(ref, got):
        for key in re:
            if re[key] is None:
                continue
            numpy.testing.assert_array_equal(
                numpy.asarray(re[key]), numpy.asarray(ge[key]),
                err_msg="leaf %r" % key)


def _reference(plans, state, x, y, bs):
    step = build_train_step(plans, donate=False)
    s = [dict(e) for e in state]
    s, losses, m = _run3(step, s, x, y, bs)
    return _host(s), losses


# -- tensor parallel --------------------------------------------------------


def test_tp_step_matches_single_device_over_3_chained_steps():
    """Acceptance: head-sharded QKV + column/row-split MLP over
    model=2, ULP-bounded (receipted) against the single-device fused
    step — loss AND weights/accumulators."""
    plans, state, x, y, bs = _setup()
    ref_state, ref_losses = _reference(plans, state, x, y, bs)

    mesh = make_mesh({"model": 2}, devices=jax.devices()[:2])
    ts = place_tp_state(mesh, plans, state)
    step = build_tp_train_step(plans, mesh=mesh, donate=False)
    ts, losses, m = _run3(step, ts, x, y, bs)
    for a, b in zip(ref_losses, losses):
        assert abs(a - b) / abs(a) < 1e-5
    measured = _maxrel(ref_state, gather_tp_state(plans, ts))
    assert measured < ULP_BOUND_3_STEPS, \
        "TP drift %.3g exceeds the receipted bound" % measured


def test_tp_single_shard_stays_in_tight_ulp_band():
    """model axis of size 1 = no partial contractions to regroup; the
    residual drift (measured 2.5e-3 rel on near-zero bias
    accumulators, ~4e-7 absolute) is pure program-structure noise —
    XLA fuses the shard_map program differently from the plain one,
    regrouping the bias-grad reductions — an order of magnitude under
    the multi-shard bound."""
    plans, state, x, y, bs = _setup(heads=2)
    ref_state, ref_losses = _reference(plans, state, x, y, bs)
    mesh = make_mesh({"model": 1}, devices=jax.devices()[:1])
    ts = place_tp_state(mesh, plans, state)
    step = build_tp_train_step(plans, mesh=mesh, donate=False)
    ts, losses, _ = _run3(step, ts, x, y, bs)
    for a, b in zip(ref_losses, losses):
        assert abs(a - b) / abs(a) < 1e-6
    got = gather_tp_state(plans, ts)
    for re, ge in zip(ref_state, got):
        for key in re:
            if re[key] is None:
                continue
            a = numpy.asarray(re[key], numpy.float64)
            b = numpy.asarray(ge[key], numpy.float64)
            assert float(numpy.abs(a - b).max()) < 1e-6, key


def test_tp_composes_with_bucketed_data_axis():
    """dp x tp on one mesh: batch shards over data, heads over model,
    gradients merge through the bucketed all-reduce — same result as
    TP alone (the data-axis merge is exact for a replicated batch
    split + psum'd metrics)."""
    plans, state, x, y, bs = _setup()
    mesh_tp = make_mesh({"model": 2}, devices=jax.devices()[:2])
    ts = place_tp_state(mesh_tp, plans, state)
    step_tp = build_tp_train_step(plans, mesh=mesh_tp, donate=False)
    ts, tp_losses, _ = _run3(step_tp, ts, x, y, bs)

    mesh = make_mesh({"data": 2, "model": 2},
                     devices=jax.devices()[:4])
    ts2 = place_tp_state(mesh, plans, state)
    step = build_tp_train_step(plans, mesh=mesh, data_axis="data",
                               grad_bucket_mb=0.001, donate=False)
    ts2, losses, m = _run3(step, ts2, x, y, bs)
    assert bool(m["finite"])
    for a, b in zip(tp_losses, losses):
        assert abs(a - b) / abs(a) < 1e-5
    assert _maxrel(gather_tp_state(plans, ts),
                   gather_tp_state(plans, ts2)) < ULP_BOUND_3_STEPS


def test_tp_poisoned_step_skips_uniformly():
    """A poisoned gradient leaves EVERY shard's state bit-identical to
    never having served the step (the guard's grad-norm is psummed
    over the model axis, so all shards see the same verdict)."""
    plans, state, x, y, bs = _setup()
    mesh = make_mesh({"model": 2}, devices=jax.devices()[:2])
    ts = place_tp_state(mesh, plans, state)
    step = build_tp_train_step(plans, mesh=mesh, donate=False)
    before = gather_tp_state(plans, ts)
    ts, m = step(ts, x, y, bs, None, numpy.float32(numpy.nan), None)
    assert int(m["skipped"]) == 1 and not bool(m["finite"])
    _assert_bit_identical(before, gather_tp_state(plans, ts))


def test_tp_step_flops_feed_mfu_attribution():
    """The TP step exposes .lower like the fused step, so the live MFU
    pipeline (xla.step_flops -> mfu_snapshot) attributes the sharded
    workload too."""
    from veles_tpu.observe import xla_introspect
    from veles_tpu.observe.metrics import MetricsRegistry
    plans, state, x, y, bs = _setup()
    mesh = make_mesh({"model": 2}, devices=jax.devices()[:2])
    ts = place_tp_state(mesh, plans, state)
    step = build_tp_train_step(plans, mesh=mesh, donate=False)
    cost = step.lower(ts, x, y, bs).cost_analysis()
    flops = (sum(float(c.get("flops", 0.0)) for c in cost
                 if isinstance(c, dict))
             if isinstance(cost, (list, tuple))
             else float((cost or {}).get("flops", 0.0)))
    assert flops > 0
    reg = MetricsRegistry()
    xla_introspect.set_step_flops(flops, reg)
    assert reg.peek("xla.step_flops").value == flops


# -- pipeline parallel ------------------------------------------------------


def test_pipeline_2_stage_split_bit_identical_over_3_steps():
    """Acceptance (satellite): the 2-stage pipeline split of the
    2-block transformer is BIT-identical to the unsplit fused step
    over 3 chained train steps (microbatches=1: every stage executes
    the single-device op sequence; discarded wavefront ticks
    contribute exact-zero gradients)."""
    plans, state, x, y, bs = _setup(heads=2)
    ref_state, ref_losses = _reference(plans, state, x, y, bs)

    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    ps, layout = stack_pipeline_state(mesh, plans, state)
    step = build_pipeline_train_step(plans, mesh=mesh, microbatches=1,
                                     donate=False)
    ps, losses, _ = _run3(step, ps, x, y, bs)
    assert losses == ref_losses, "loss must be bit-identical"
    _assert_bit_identical(ref_state, unstack_pipeline_state(ps, layout))


def test_pipeline_microbatches_ulp_bounded():
    """microbatches=2 accumulates per-microbatch wgrads (a different
    f32 grouping): receipted-ULP-bounded, not bit-equal."""
    plans, state, x, y, bs = _setup(heads=2)
    ref_state, _ = _reference(plans, state, x, y, bs)
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    ps, layout = stack_pipeline_state(mesh, plans, state)
    step = build_pipeline_train_step(plans, mesh=mesh, microbatches=2,
                                     donate=False)
    ps, _, m = _run3(step, ps, x, y, bs)
    assert bool(m["finite"])
    measured = _maxrel(ref_state, unstack_pipeline_state(ps, layout))
    assert 0 < measured < ULP_BOUND_3_STEPS


def test_pipeline_poisoned_step_skips_uniformly():
    plans, state, x, y, bs = _setup(heads=2)
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    ps, layout = stack_pipeline_state(mesh, plans, state)
    step = build_pipeline_train_step(plans, mesh=mesh, microbatches=1,
                                     donate=False)
    before = unstack_pipeline_state(ps, layout)
    ps, m = step(ps, x, y, bs, None, numpy.float32(numpy.nan), None)
    assert int(m["skipped"]) == 1
    _assert_bit_identical(before, unstack_pipeline_state(ps, layout))


def test_pipeline_prefix_layer_grads_replicate_bit_identically():
    """Regression: layers BEFORE the block run feed the wavefront only
    through stage 0's injection, so their raw cotangent is zero on
    every other rank — without the enter conjugate's psum, 'replicated'
    prefix updates silently diverge per rank (rank 0 trains, the rest
    momentum-decay) and the finiteness guard fires non-uniformly.
    With it, the prefix-bearing split stays BIT-identical to the
    unsplit step over 3 chained steps on every rank."""
    specs = ([{"type": "layer_norm", "learning_rate": 0.05,
               "gradient_moment": 0.9}] +
             transformer_layers(blocks=2, heads=2, hidden=16,
                                classes=10, lr=0.05))
    plans, state, _ = build_plans_and_state(specs, (8, 8), seed=4)
    rng = numpy.random.RandomState(6)
    x = jnp.asarray(rng.rand(16, 8, 8), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, 16), jnp.int32)
    bs = numpy.float32(16)
    ref_state, ref_losses = _reference(plans, state, x, y, bs)

    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    ps, layout = stack_pipeline_state(mesh, plans, state)
    step = build_pipeline_train_step(plans, mesh=mesh, microbatches=1,
                                     donate=False)
    ps, losses, _ = _run3(step, ps, x, y, bs)
    assert losses == ref_losses
    # the REAL uniformity check: the assembled logical array can hide a
    # divergent rank (jax picks one shard for a 'replicated' leaf), so
    # compare every rank's device buffer bit-for-bit
    for key in ("weights", "accum_weights", "bias", "accum_bias"):
        leaf = ps[0][key]
        shards = [numpy.asarray(s.data)
                  for s in leaf.addressable_shards]
        for other in shards[1:]:
            numpy.testing.assert_array_equal(shards[0], other,
                                             err_msg=key)
    got = unstack_pipeline_state(ps, layout)
    _assert_bit_identical(ref_state, got)
    # the trained prefix must actually have MOVED (a zero-grad prefix
    # that merely matched the reference would mean the reference broke)
    assert not numpy.array_equal(numpy.asarray(got[0]["weights"]),
                                 numpy.asarray(state[0]["weights"]))


def test_pipeline_rejects_uneven_or_scattered_blocks():
    plans, state, x, y, bs = _setup(heads=2)
    mesh3 = make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    with pytest.raises(ValueError):
        build_pipeline_train_step(plans, mesh=mesh3)
    no_blocks, _, _ = build_plans_and_state(
        [{"type": "softmax", "output_sample_shape": 4}], (8,), seed=0)
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError):
        build_pipeline_train_step(no_blocks, mesh=mesh)


# -- pipeline_forward / moe over REAL transformer blocks --------------------


def test_pipeline_forward_drives_transformer_block_stages():
    """pipeline_forward with TransformerBlock.apply as the stage fn
    (4 real blocks over 4 stages) vs the sequential composition."""
    from veles_tpu.models.transformer import (TransformerBlock,
                                              init_block_params)
    from veles_tpu.parallel.pipeline import (pipeline_forward,
                                             stack_stage_params,
                                             stage_param_sharding)
    rng = numpy.random.RandomState(11)
    d, hidden, n_stages = 8, 16, 4
    stages = []
    for _ in range(n_stages):
        w, b = init_block_params(d, hidden, rng)
        stages.append({"weights": jnp.asarray(w),
                       "bias": jnp.asarray(b)})
    x = jnp.asarray(rng.randn(8, 6, d), jnp.float32)

    def stage_fn(params, a):
        return TransformerBlock.apply(params, a, heads=2,
                                      hidden=hidden)

    want = x
    for s in stages:
        want = stage_fn(s, want)

    mesh = make_mesh({"pipe": n_stages}, devices=jax.devices()[:4])
    stacked = stage_param_sharding(mesh, stack_stage_params(stages))
    got = pipeline_forward(stage_fn, stacked, x, mesh, microbatches=2)
    numpy.testing.assert_allclose(numpy.asarray(got),
                                  numpy.asarray(want), rtol=1e-5,
                                  atol=1e-5)


def test_moe_ffn_drives_transformer_attention_sublayer():
    """A transformer block whose position-wise FFN is the
    expert-parallel MoE layer: attention sub-layer (real
    MultiHeadAttention math) -> LN -> moe_apply over the expert axis,
    vs the moe_reference composition."""
    from veles_tpu.models.transformer import (layer_norm,
                                              multi_head_attention)
    from veles_tpu.parallel.moe import (init_moe_params, moe_apply,
                                        moe_reference,
                                        shard_moe_params)
    rng = numpy.random.RandomState(12)
    d, heads = 8, 2
    w_qkv = jnp.asarray(rng.randn(d, 3 * d) * 0.3, jnp.float32)
    w_o = jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)
    gamma = jnp.ones((d,), jnp.float32)
    beta = jnp.zeros((d,), jnp.float32)
    x = jnp.asarray(rng.randn(6, 5, d), jnp.float32)

    h = x + multi_head_attention(layer_norm(x, gamma, beta), w_qkv,
                                 None, w_o, None, heads)
    tokens = layer_norm(h, gamma, beta).reshape(-1, d)
    moe = init_moe_params(rng, n_experts=4, features=d, hidden=16,
                          out_features=d)
    want = numpy.asarray(h) + numpy.asarray(
        moe_reference(moe, tokens, top_k=2)).reshape(h.shape)

    mesh = make_mesh({"expert": 4}, devices=jax.devices()[:4])
    sharded = shard_moe_params(mesh, moe)
    got = numpy.asarray(h) + numpy.asarray(
        moe_apply(sharded, tokens, mesh, top_k=2)).reshape(h.shape)
    numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
