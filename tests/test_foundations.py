"""Tests for config, mutable, prng, logger, cmdline, pickling
(reference analogs: test_config, test_mutable, test_random)."""

import io
import pickle

import numpy
import pytest

from veles_tpu.config import Config, root, validate_kwargs
from veles_tpu.mutable import Bool, LinkableAttribute
from veles_tpu import prng
from veles_tpu.distributable import Pickleable


class TestConfig:
    def test_autovivify(self):
        cfg = Config("test")
        cfg.a.b.c = 13
        assert cfg.a.b.c == 13
        assert isinstance(cfg.a.x, Config)

    def test_update(self):
        cfg = Config("test")
        cfg.update({"x": 1, "sub": {"y": 2}})
        assert cfg.x == 1
        assert cfg.sub.y == 2
        cfg(sub={"z": 3})
        assert cfg.sub.y == 2 and cfg.sub.z == 3

    def test_protect(self):
        cfg = Config("test")
        cfg.key = 5
        cfg.protect("key")
        with pytest.raises(AttributeError):
            cfg.key = 6
        assert cfg.key == 5

    def test_get_unset(self):
        cfg = Config("test")
        assert cfg.get("nothing", 42) == 42
        cfg.present = 1
        assert cfg.get("present") == 1

    def test_as_dict_roundtrip(self):
        cfg = Config("test")
        cfg.update({"a": 1, "b": {"c": [1, 2]}})
        restored = pickle.loads(pickle.dumps(cfg))
        assert restored.a == 1
        assert restored.b.c == [1, 2]

    def test_root_defaults(self):
        assert root.common.engine.get("precision_type") in (
            "float32", "bfloat16", "float16")

    def test_print(self):
        out = io.StringIO()
        cfg = Config("t")
        cfg.a = 1
        cfg.print_(out=out)
        assert "a: 1" in out.getvalue()

    def test_validate_kwargs_warns(self):
        with pytest.warns(UserWarning):
            validate_kwargs(object(), bad=Config("unset"))


class TestBool:
    def test_assign(self):
        flag = Bool()
        assert not flag
        flag <<= True
        assert flag

    def test_derived_or(self):
        a, b = Bool(False), Bool(False)
        c = a | b
        assert not c
        a <<= True
        assert c          # live: sees the operand change
        a <<= False
        b <<= True
        assert c

    def test_derived_and_invert_xor(self):
        a, b = Bool(True), Bool(False)
        assert not (a & b)
        b <<= True
        assert a & b
        assert not ~a
        assert a ^ Bool(False)
        assert not (a ^ b)

    def test_on_change(self):
        calls = []
        flag = Bool(False)
        flag.on_change = calls.append
        flag <<= True
        flag <<= True  # no change
        flag <<= False
        assert len(calls) == 2

    def test_pickle(self):
        a = Bool(True)
        b = pickle.loads(pickle.dumps(a))
        assert bool(b)


class _Src:
    pass


class _Dst:
    pass


class TestLinkableAttribute:
    def test_one_way(self):
        src, dst = _Src(), _Dst()
        src.value = 13
        LinkableAttribute(dst, "value", src, "value")
        assert dst.value == 13
        src.value = 14
        assert dst.value == 14
        with pytest.raises(AttributeError):
            dst.value = 15

    def test_two_way(self):
        src, dst = _Src(), _Dst()
        src.v = 1
        LinkableAttribute(dst, "v", src, "v", two_way=True)
        dst.v = 99
        assert src.v == 99

    def test_different_names(self):
        src, dst = _Src(), _Dst()
        src.output = "x"
        LinkableAttribute(dst, "input", src, "output")
        assert dst.input == "x"

    def test_independent_instances(self):
        s1, s2 = _Src(), _Src()
        d1, d2 = _Dst(), _Dst()
        s1.q, s2.q = 1, 2
        LinkableAttribute(d1, "q", s1, "q")
        LinkableAttribute(d2, "q", s2, "q")
        assert d1.q == 1 and d2.q == 2


class TestPrng:
    def test_reproducible(self):
        a = prng.RandomGenerator("t", seed=42)
        b = prng.RandomGenerator("t", seed=42)
        arr1 = numpy.zeros(16)
        arr2 = numpy.zeros(16)
        a.fill(arr1)
        b.fill(arr2)
        assert numpy.array_equal(arr1, arr2)

    def test_state_roundtrip(self):
        rng = prng.RandomGenerator("t", seed=7)
        rng.uniform(size=10)
        state = pickle.dumps(rng)
        expected = rng.uniform(size=5)
        restored = pickle.loads(state)
        assert numpy.array_equal(restored.uniform(size=5), expected)

    def test_registry(self):
        assert prng.get("k1") is prng.get("k1")
        assert prng.get("k1") is not prng.get("k2")

    def test_jax_key_stream_deterministic(self):
        a = prng.RandomGenerator("t", seed=99)
        b = prng.RandomGenerator("t", seed=99)
        import jax
        k1, k2 = a.jax_key(), a.jax_key()
        m1 = b.jax_key()
        assert jax.numpy.array_equal(k1, m1)
        assert not jax.numpy.array_equal(k1, k2)

    def test_shuffle_deterministic(self):
        a = prng.RandomGenerator("t", seed=5)
        arr = numpy.arange(100)
        a.shuffle(arr)
        b = prng.RandomGenerator("t", seed=5)
        arr2 = numpy.arange(100)
        b.shuffle(arr2)
        assert numpy.array_equal(arr, arr2)


class _Transient(Pickleable):
    def __init__(self):
        super(_Transient, self).__init__()
        self.keep = 1

    def init_unpickled(self):
        super(_Transient, self).init_unpickled()
        self.scratch_ = "recreated"


class TestPickleable:
    def test_transient_excluded(self):
        obj = _Transient()
        obj.scratch_ = "dirty"
        restored = pickle.loads(pickle.dumps(obj))
        assert restored.keep == 1
        assert restored.scratch_ == "recreated"


class TestCmdline:
    def test_registry_collects(self):
        from veles_tpu.cmdline import (CommandLineBase, build_parser)

        class Contributor(CommandLineBase):
            @classmethod
            def init_parser(cls, parser):
                parser.add_argument("--contributed-flag", default="x")
                return parser

        parser = build_parser()
        args = parser.parse_args(["--contributed-flag", "y"])
        assert args.contributed_flag == "y"


class TestLogger:
    def test_event_file(self, tmp_path):
        from veles_tpu import logger as vlog
        from veles_tpu.logger import Logger, set_event_file
        path = tmp_path / "events.jsonl"
        set_event_file(str(path))
        try:
            obj = Logger()
            obj.event("step", "begin", idx=1)
            obj.event("step", "end", idx=1)
            with pytest.raises(ValueError):
                obj.event("step", "sometimes")
        finally:
            set_event_file(None)
        import json
        lines = [json.loads(line) for line in
                 path.read_text().strip().splitlines()]
        assert len(lines) == 2
        assert lines[0]["kind"] == "begin"
        assert lines[1]["idx"] == 1
