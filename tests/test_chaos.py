"""Deterministic fault-injection (veles_tpu/chaos.py): recovery across
the checkpoint and control planes is TESTED under injected faults, not
assumed.  Acceptance bar (ISSUE 2): a mid-write snapshot crash, a
corrupted ``_current`` target, and a slave kill mid-batch all recover
automatically with bit-identical final weights vs. the fault-free run;
a corrupted frame is rejected before unpickling and the connection is
retried; ``kill -9`` of a snapshot in progress never leaves ``_current``
pointing at an unverifiable file."""

import asyncio
import os
import pickle
import signal
import subprocess
import sys
import threading
import time

import numpy
import pytest

from veles_tpu import chaos, prng
from veles_tpu.chaos import ChaosCrash, FaultPlan
from veles_tpu.client import Client
from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.network_common import (
    ProtocolError, pack_payload, read_frame, write_frame)
from veles_tpu.prng import RandomGenerator
from veles_tpu.server import Server
from veles_tpu.snapshotter import Snapshotter, SnapshotterBase
from tests.test_models import BlobsLoader

pytestmark = pytest.mark.chaos

LAYERS = [
    {"type": "all2all_tanh", "output_sample_shape": 32,
     "learning_rate": 0.05, "gradient_moment": 0.9},
    {"type": "softmax", "output_sample_shape": 4,
     "learning_rate": 0.05, "gradient_moment": 0.9},
]


def _build(mode, seed_key, device, max_epochs=3):
    prng.get().seed(4242)  # identical layer-init streams across builds
    wf = DummyWorkflow()
    wf.workflow.workflow_mode = mode
    sw = StandardWorkflow(
        wf.workflow, layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator(seed_key, seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.initialize(device=device)
    return sw


def _weights(sw):
    out = []
    for fwd in sw.forwards:
        fwd.weights.map_read()
        out.append(numpy.array(fwd.weights.mem))
    return out


# -- the harness itself --------------------------------------------------


def test_fault_plan_spec_parsing():
    plan = FaultPlan.from_spec(
        "seed=7;net.recv=corrupt:n3;snapshot.write=crash:p0.25;"
        "server.serve=stall:x2:0.01")
    assert plan.seed == 7
    # nth trigger: exactly the 3rd hit, once
    assert plan.fire("net.recv") is None
    assert plan.fire("net.recv") is None
    fault = plan.fire("net.recv")
    assert fault is not None and fault.action == "corrupt"
    assert plan.fire("net.recv") is None
    # bounded unconditional trigger with a param
    s1 = plan.fire("server.serve")
    s2 = plan.fire("server.serve")
    assert s1.action == "stall" and s1.param == 0.01
    assert s2 is not None and plan.fire("server.serve") is None
    # unknown points cost nothing and fire nothing
    assert plan.fire("no.such.point") is None
    assert plan.fired("net.recv") == 1


def test_fault_plan_probability_deterministic():
    first = FaultPlan(seed=99).add("p", "x", probability=0.5)
    pattern = [bool(first.fire("p")) for _ in range(32)]
    assert any(pattern) and not all(pattern)
    again = FaultPlan(seed=99).add("p", "x", probability=0.5)
    assert [bool(again.fire("p")) for _ in range(32)] == pattern


def test_install_from_env(monkeypatch):
    monkeypatch.setenv("VELES_CHAOS", "seed=3;client.job=die:n1")
    plan = chaos.install_from_env()
    try:
        assert chaos.plan is plan and plan.seed == 3
        assert plan.fire("client.job").action == "die"
    finally:
        chaos.uninstall()


def test_bad_spec_rejected():
    with pytest.raises(ValueError):
        FaultPlan.from_spec("not-an-entry")


# -- snapshot plane ------------------------------------------------------


def _snapshotted(device, tmp_path, max_epochs=1):
    sw = _build("standalone", "chaos_snap", device, max_epochs=max_epochs)
    sw.run()
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="c",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    return sw, snap


def test_snapshot_crash_mid_write_preserves_current(tmp_path, cpu_device):
    """Acceptance (a): a crash mid-snapshot-write leaves only a .tmp
    residue; _current still names the previous verified snapshot."""
    sw, snap = _snapshotted(cpu_device, tmp_path)
    snap.suffix = "good"
    snap.export()
    good = snap.destination
    assert SnapshotterBase.verify_snapshot(good)[0] is True

    chaos.install(FaultPlan().add("snapshot.write", "crash", nth=1))
    try:
        snap.suffix = "doomed"
        with pytest.raises(ChaosCrash):
            snap.export()
    finally:
        chaos.uninstall()

    doomed = os.path.join(str(tmp_path), "c_doomed.%d.pickle.gz" %
                          pickle.HIGHEST_PROTOCOL)
    assert not os.path.exists(doomed), "torn file at the final path"
    assert os.path.exists(doomed + ".tmp"), "crash left no residue?"
    link = os.path.join(str(tmp_path), "c_current")
    assert os.path.realpath(link) == os.path.realpath(good)
    ok, _ = SnapshotterBase.verify_snapshot(link)
    assert ok is True
    assert SnapshotterBase.import_file(link) is not None


def test_snapshot_enospc_warns_and_run_continues(tmp_path, cpu_device,
                                                 caplog):
    sw, snap = _snapshotted(cpu_device, tmp_path)
    snap.suffix = "good"
    snap.export()
    good = snap.destination

    chaos.install(FaultPlan().add("snapshot.write", "enospc", nth=1))
    try:
        snap.suffix = "full"
        snap.export()  # must NOT raise: training continues
    finally:
        chaos.uninstall()
    assert snap.destination == good, "failed write must not be adopted"
    assert any("snapshot write" in r.message and "failed" in r.message
               for r in caplog.records)
    link = os.path.join(str(tmp_path), "c_current")
    assert os.path.realpath(link) == os.path.realpath(good)
    # the disk "recovered": the next export succeeds and flips _current
    snap.suffix = "after"
    snap.export()
    assert snap.destination != good
    assert os.path.realpath(link) == os.path.realpath(snap.destination)


def test_corrupted_current_falls_back_to_previous_good(tmp_path,
                                                       cpu_device,
                                                       caplog):
    """Acceptance (b): a corrupted _current target is detected by its
    manifest BEFORE unpickling and restore falls back, with a warning,
    to the newest previous-good snapshot."""
    sw, snap = _snapshotted(cpu_device, tmp_path)
    snap.suffix = "older"
    snap.export()
    older = snap.destination
    time.sleep(0.05)
    snap.suffix = "newest"
    snap.export()
    newest = snap.destination

    with open(newest, "r+b") as fout:  # flip one byte, size unchanged
        fout.seek(os.path.getsize(newest) // 2)
        byte = fout.read(1)
        fout.seek(-1, os.SEEK_CUR)
        fout.write(bytes([byte[0] ^ 0xFF]))
    ok, reason = SnapshotterBase.verify_snapshot(newest)
    assert ok is False and "sha256" in reason

    link = os.path.join(str(tmp_path), "c_current")
    restored = SnapshotterBase.import_file(link)
    assert type(restored).__name__ == "StandardWorkflow"
    messages = [r.message for r in caplog.records]
    assert any("failed verification" in m for m in messages)
    assert any(os.path.basename(older) in m and "previous-good" in m
               for m in messages)
    # fail-fast mode still refuses
    with pytest.raises(Exception):
        SnapshotterBase.import_file(newest, fallback=False)


class NoisyBlobsLoader(BlobsLoader):
    """Overlapping blobs: with a small learning rate the validation
    error falls gradually, so EVERY epoch improves and checkpoints —
    the crash can land on any epoch's snapshot."""

    def load_data(self):
        self.class_lengths[:] = [0, 64, 256]
        self._calc_class_end_offsets()
        self.create_originals((16,))
        rng = numpy.random.RandomState(5)
        centers = rng.randn(4, 16) * 1.2
        for i in range(self.total_samples):
            label = i % 4
            self.original_data.mem[i] = (
                centers[label] + rng.randn(16) * 1.5)
            self.original_labels[i] = label


def _build_resume(parent, device=None, max_epochs=6):
    prng.get().seed(4242)
    sw = StandardWorkflow(
        parent,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.004, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.004, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: NoisyBlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("chaos_resume", seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    if device is not None:
        sw.initialize(device=device)
    return sw


def test_master_crash_mid_run_resume_auto_bit_identical(tmp_path,
                                                        cpu_device):
    """Acceptance: crash the run mid-training (a ChaosCrash during the
    third epoch's snapshot), then ``--resume auto`` from the validated
    _current target; the resumed run's final weights and epoch metrics
    are bit-identical to an uninterrupted run."""
    dir_ref = tmp_path / "ref"
    dir_crash = tmp_path / "crash"
    saved = (root.common.snapshot.get("dir"),
             root.common.snapshot.get("time_interval", 15),
             root.common.snapshot.get("resume") or "")

    def configure(directory, resume=""):
        root.common.snapshot.update({
            "dir": str(directory), "time_interval": 0,
            "resume": resume})

    try:
        # reference: uninterrupted, snapshotting at every improvement
        configure(dir_ref)
        ref = _build_resume(DummyWorkflow().workflow, cpu_device)
        assert ref.snapshotter is not None
        ref.run()
        assert bool(ref.decision.complete)
        ref_weights = _weights(ref)
        ref_metrics = list(ref.decision.epoch_metrics)

        # crashed run: same seeds, same graph, dies mid epoch 3's
        # snapshot (after epochs 1-2 checkpointed)
        configure(dir_crash)
        crashed = _build_resume(DummyWorkflow().workflow, cpu_device)
        chaos.install(FaultPlan().add("snapshot.write", "crash", nth=3))
        try:
            with pytest.raises(ChaosCrash):
                crashed.run()
        finally:
            chaos.uninstall()
        assert not bool(crashed.decision.complete)

        # resume through the real launcher path: --resume auto finds
        # the validated _current target and swaps the workflow in
        configure(dir_crash, resume="auto")
        from veles_tpu.launcher import Launcher
        launcher = Launcher()
        _build_resume(launcher)  # throwaway fresh workflow
        launcher.initialize(device=cpu_device)
        resumed = launcher.workflow
        assert resumed.restored_from_snapshot_
        launcher.run()
        assert bool(resumed.decision.complete)

        assert list(resumed.decision.epoch_metrics) == ref_metrics
        for got, want in zip(_weights(resumed), ref_weights):
            numpy.testing.assert_array_equal(got, want)

        # resuming a COMPLETED run must be a clean no-op: the one
        # minibatch the first cycle evaluates before end_point fires
        # must not mutate weights (every gd skips on complete)
        launcher2 = Launcher()
        _build_resume(launcher2)
        launcher2.initialize(device=cpu_device)
        again = launcher2.workflow
        assert again.restored_from_snapshot_
        assert bool(again.decision.complete)
        launcher2.run()
        for got, want in zip(_weights(again), ref_weights):
            numpy.testing.assert_array_equal(got, want)
    finally:
        root.common.snapshot.update({
            "dir": saved[0], "time_interval": saved[1],
            "resume": saved[2]})


# -- control plane -------------------------------------------------------


def _start_server(master_sw, **kwargs):
    server = Server("127.0.0.1:0", master_sw, **kwargs)
    master_sw.workflow.on_workflow_finished = server.on_workflow_finished
    thread = server.start_background()
    assert server.wait_listening(10)
    return server, thread


def test_slave_killed_mid_batch_bit_identical(cpu_device):
    """Acceptance (c): the slave dies on exactly its 3rd job, BEFORE
    running it; the master requeues the minibatch, the same slave
    reconnects (budget reset after its productive session) and replays
    it — final master weights bit-identical to the fault-free run."""
    # fault-free reference
    master_ref = _build("master", "chaos_net_m", cpu_device)
    slave_ref = _build("slave", "chaos_net_s", cpu_device)
    server_ref, _ = _start_server(master_ref)
    client_ref = Client("127.0.0.1:%d" % server_ref.port, slave_ref)
    client_ref.run()
    assert server_ref._done.wait(10)
    assert bool(master_ref.decision.complete)
    ref_weights = _weights(master_ref)
    ref_metrics = list(master_ref.decision.epoch_metrics)

    # chaotic run: identical seeds, die on job 3
    master = _build("master", "chaos_net_m", cpu_device)
    slave = _build("slave", "chaos_net_s", cpu_device)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    plan = chaos.install(FaultPlan().add("client.job", "die", nth=3))
    try:
        client.run()
    finally:
        chaos.uninstall()
    assert server._done.wait(10)

    assert plan.fired("client.job") == 1, "the injected death must fire"
    assert client.sessions_established == 2, "the slave must reconnect"
    assert master.loader.total_failed >= 1, "the job must requeue"
    assert bool(master.decision.complete)
    assert list(master.decision.epoch_metrics) == ref_metrics
    for got, want in zip(_weights(master), ref_weights):
        numpy.testing.assert_array_equal(got, want)


def test_server_side_conn_kill_requeues_and_recovers(cpu_device):
    """A mid-batch connection kill from the MASTER side: the reserved
    minibatch requeues and the reconnecting slave finishes the run."""
    master = _build("master", "chaos_kill_m", cpu_device)
    slave = _build("slave", "chaos_kill_s", cpu_device)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    plan = chaos.install(FaultPlan().add("server.serve", "kill", nth=4))
    try:
        client.run()
    finally:
        chaos.uninstall()
    assert server._done.wait(10)
    assert plan.fired("server.serve") == 1
    assert client.sessions_established >= 2
    assert master.loader.total_failed >= 1
    assert bool(master.decision.complete)
    assert numpy.isfinite(_weights(master)[0]).all()


def test_corrupted_frame_rejected_before_unpickling():
    """Unit-level: with a shared secret, a corrupted payload fails the
    HMAC check inside read_frame — ProtocolError BEFORE the payload
    bytes ever reach pickle."""
    secret = b"sesame"

    class _Writer(object):
        def __init__(self):
            self.data = b""

        def write(self, blob):
            self.data += blob

    writer = _Writer()
    write_frame(writer, {"type": "update", "job_id": "j1"},
                pack_payload({"x": 1}), secret)
    blob = bytearray(writer.data)
    blob[-20] ^= 0xFF  # corrupt inside payload/mac tail

    async def read_corrupt():
        reader = asyncio.StreamReader()
        reader.feed_data(bytes(blob))
        reader.feed_eof()
        return await read_frame(reader, secret)

    with pytest.raises(ProtocolError):
        asyncio.run(read_corrupt())


def test_corrupted_frame_on_wire_connection_retried(cpu_device):
    """End-to-end: chaos corrupts the slave's 5th received frame; the
    authenticated session rejects it before unpickling, reconnects,
    and the run still completes."""
    master = _build("master", "chaos_corrupt_m", cpu_device)
    slave = _build("slave", "chaos_corrupt_s", cpu_device)
    server, _ = _start_server(master, secret=b"sesame")
    client = Client("127.0.0.1:%d" % server.port, slave,
                    secret=b"sesame")
    plan = chaos.install(
        FaultPlan().add("net.recv:slave", "corrupt", nth=5))
    try:
        client.run()
    finally:
        chaos.uninstall()
    assert server._done.wait(10)
    assert plan.fired("net.recv:slave") == 1
    assert client.sessions_established >= 2, \
        "the corrupted frame must force a reconnect"
    assert client.jobs_done > 0
    assert bool(master.decision.complete)


def test_client_reconnects_twice_across_healthy_intervals():
    """Satellite: the attempt budget bounds CONSECUTIVE unproductive
    attempts; productive sessions reset it, so two blips separated by
    healthy intervals survive even reconnect_limit=1."""
    handshakes = []
    stop_after = 3

    async def handle(reader, writer):
        msg, _ = await read_frame(reader)
        assert msg["type"] == "handshake"
        handshakes.append(msg)
        write_frame(writer, {"type": "handshake_ack",
                             "id": "s%d" % len(handshakes),
                             "codec": "none"},
                    pack_payload([]))
        msg, _ = await read_frame(reader)  # job_request
        if len(handshakes) >= stop_after:
            write_frame(writer, {"type": "stop"})
            await writer.drain()
            writer.close()
            return
        write_frame(writer, {"type": "job", "job_id": "j",
                             "codec": "none"}, pack_payload(None))
        await read_frame(reader)  # the update: session was productive
        writer.close()            # ...then the "blip"

    class _StubWorkflow(object):
        checksum = "stub"

        def apply_initial_data_from_master(self, data):
            pass

        def do_job(self, data, update, callback):
            callback({"ok": True})

    started = threading.Event()
    port = [0]

    def serve():
        async def main():
            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            port[0] = server.sockets[0].getsockname()[1]
            started.set()
            async with server:
                for _ in range(200):
                    await asyncio.sleep(0.05)
                    if len(handshakes) >= stop_after:
                        await asyncio.sleep(0.5)
                        return
        asyncio.run(main())

    thread = threading.Thread(target=serve, daemon=True)
    thread.start()
    assert started.wait(5)

    client = Client("127.0.0.1:%d" % port[0], _StubWorkflow(),
                    reconnect_limit=1)
    client.run()
    assert client.sessions_established == stop_after, \
        "without the budget reset the second blip would be fatal"
    assert client.jobs_done == stop_after - 1
    thread.join(10)


# -- input pipeline ------------------------------------------------------


def test_pipeline_serve_exception_surfaces_cleanly(cpu_device):
    """A worker-thread serve failure must surface on the graph thread
    and wind the worker down (no leaked threads, no hang)."""
    from veles_tpu.models.fused import fuse_standard_workflow
    prng.get().seed(4242)
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow, layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("chaos_pipe2", seed=7)),
        decision_config=dict(max_epochs=4),
    )
    fuse_standard_workflow(sw, pipeline=True)
    sw.initialize(device=cpu_device)
    chaos.install(FaultPlan().add("pipeline.serve", "exc", nth=3))
    try:
        with pytest.raises(RuntimeError, match="injected serve"):
            sw.run()
    finally:
        chaos.uninstall()
        sw.stop()
    pf = sw.fused_trainer._prefetcher
    assert pf is None or pf._pool is None, "worker must be shut down"


# -- numerics health: nan injection, rollback, quarantine ----------------
# (docs/health.md; unit-level guard coverage lives in tests/test_health.py)


def test_nan_grad_injected_step_is_skipped_and_run_completes(cpu_device):
    """A NaN gradient at train step k: the fused step skips exactly
    that update (skip counter = 1), training continues, and the run
    finishes with finite weights and a sane validation error."""
    prng.get().seed(4242)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("chaos_nan1", seed=7)),
        decision_config=dict(max_epochs=4),
    )
    sw.fuse()
    sw.initialize(device=cpu_device)
    plan = chaos.install(FaultPlan().add("step.grad", "nan", nth=3))
    try:
        sw.run()
    finally:
        chaos.uninstall()
    assert plan.fired("step.grad") == 1
    assert bool(sw.decision.complete)
    assert int(sw.fused_trainer.skip_count) == 1
    assert int(sw.fused_trainer.consecutive_skips) == 0
    for w in _weights(sw):
        assert numpy.isfinite(w).all()
    assert sw.decision.epoch_metrics[1] < 10.0, \
        "one skipped step must not derail training"


def test_nan_grad_per_unit_path_skips_whole_chain(cpu_device):
    """The PER-UNIT gd chain has the same skip semantics: poisoning the
    last layer's err_output cascades a non-finite err_input upstream,
    so every layer skips that step — no torn half-updated state."""
    prng.get().seed(4242)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("chaos_nan2", seed=7)),
        decision_config=dict(max_epochs=4),
    )
    sw.initialize(device=cpu_device)
    # hit 1 = the FIRST gd to run on the first train step — the last
    # layer's unit, whose poisoned err_input cascades to every other
    plan = chaos.install(FaultPlan().add("step.grad", "nan", nth=1))
    try:
        sw.run()
    finally:
        chaos.uninstall()
    assert plan.fired("step.grad") == 1
    assert bool(sw.decision.complete)
    skips = [int(gd.skip_count) for gd in sw.gds]
    assert skips == [1, 1], \
        "both layers must skip the poisoned step together: %s" % skips
    for w in _weights(sw):
        assert numpy.isfinite(w).all()
    assert sw.decision.epoch_metrics[1] < 10.0


def test_sustained_nan_rolls_back_and_completes(tmp_path, cpu_device):
    """Acceptance: sustained NaN gradients trip the consecutive-skip
    budget; the run rolls back to the last VERIFIED snapshot, backs
    off the learning rate, and still completes with finite weights."""
    saved = (root.common.snapshot.get("dir"),
             root.common.snapshot.get("time_interval", 15))
    root.common.snapshot.update({"dir": str(tmp_path),
                                 "time_interval": 0})
    try:
        sw = _build_resume(DummyWorkflow().workflow, max_epochs=6)
        sw.decision.skip_budget = 4
        sw.fuse()
        sw.initialize(device=cpu_device)
        assert sw.snapshotter is not None
        lr0 = sw.gds[0].learning_rate
        # 4 train steps/epoch: epoch 1 clean (snapshot lands), epochs
        # 2-3 fully poisoned (trip + rollback each), 4-6 clean again
        chaos.install(FaultPlan().add("step.grad", "nan",
                                      after=4, times=8))
        try:
            sw.run()
        finally:
            chaos.uninstall()
        assert bool(sw.decision.complete)
        assert sw.snapshotter.rollbacks == 2
        assert sw.gds[0].learning_rate == pytest.approx(lr0 * 0.25)
        assert not bool(sw.decision.diverged)
        for w in _weights(sw):
            assert numpy.isfinite(w).all()
    finally:
        root.common.snapshot.update({"dir": saved[0],
                                     "time_interval": saved[1]})


def test_rollback_budget_exhaustion_hard_fails(tmp_path, cpu_device):
    """When divergence keeps tripping past the bounded retry budget,
    the run must die LOUDLY (RollbackExhausted), not loop forever."""
    from veles_tpu.health import RollbackExhausted
    saved = (root.common.snapshot.get("dir"),
             root.common.snapshot.get("time_interval", 15))
    root.common.snapshot.update({"dir": str(tmp_path),
                                 "time_interval": 0})
    try:
        sw = _build_resume(DummyWorkflow().workflow, max_epochs=8)
        sw.decision.skip_budget = 4
        sw.fuse()
        sw.initialize(device=cpu_device)
        sw.snapshotter.rollback_budget = 1
        # epoch 1 clean, then NaN forever: rollback 1 is allowed, the
        # second trip exhausts the budget
        chaos.install(FaultPlan().add("step.grad", "nan", after=4))
        try:
            with pytest.raises(RollbackExhausted):
                sw.run()
        finally:
            chaos.uninstall()
        assert sw.snapshotter.rollbacks == 2  # the failing attempt
        assert not bool(sw.decision.complete)
    finally:
        root.common.snapshot.update({"dir": saved[0],
                                     "time_interval": saved[1]})


def test_divergence_without_snapshotter_fails_loudly(cpu_device):
    """No snapshotter attached -> nothing to roll back to: the
    watchdog must abort the run instead of converging to garbage."""
    from veles_tpu.health import DivergenceError
    prng.get().seed(4242)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[dict(spec) for spec in LAYERS],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("chaos_nosnap", seed=7)),
        decision_config=dict(max_epochs=4, skip_budget=4),
    )
    sw.fuse()
    sw.initialize(device=cpu_device)
    assert sw.snapshotter is None
    chaos.install(FaultPlan().add("step.grad", "nan"))
    try:
        with pytest.raises(DivergenceError):
            sw.run()
    finally:
        chaos.uninstall()


def test_poisoned_slave_update_quarantined_and_run_finishes(cpu_device):
    """Acceptance: a master receiving a poisoned (all-NaN) slave update
    quarantines that slave — drop + TTL blacklist, minibatch requeued —
    instead of merging it into global weights; the slave rejoins after
    the TTL and the run finishes with finite weights."""
    master = _build("master", "chaos_poison_m", cpu_device)
    slave = _build("slave", "chaos_poison_s", cpu_device)
    server, _ = _start_server(master, blacklist_ttl=0.6)
    client = Client("127.0.0.1:%d" % server.port, slave)
    plan = chaos.install(FaultPlan().add("net.update", "nan", nth=2))
    try:
        client.run()
    finally:
        chaos.uninstall()
    assert server._done.wait(15)

    assert plan.fired("net.update") == 1
    assert server.quarantined == 1
    assert master.loader.total_failed >= 1, \
        "the poisoned job's minibatch must requeue"
    assert client.sessions_established >= 2, \
        "the quarantined slave must rejoin after the blacklist TTL"
    assert bool(master.decision.complete)
    for w in _weights(master):
        assert numpy.isfinite(w).all()
    # the poisoned update was never applied: global metrics stay sane
    assert master.decision.epoch_metrics[1] is not None
    assert numpy.isfinite(master.decision.epoch_metrics[1])


# -- kill -9 soak (slow tier) --------------------------------------------


_KILL9_CHILD = r"""
import os, sys, time
sys.path.insert(0, %(repo)r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("VELES_BACKEND", "numpy")
import numpy
from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.snapshotter import Snapshotter

wf = DummyWorkflow()
DummyUnit(wf, payload=numpy.arange(1 << 15))
snap = Snapshotter(wf, directory=%(dir)r, prefix="k", interval=1,
                   time_interval=0, compression="")
snap.initialize()
print("READY", flush=True)
i = 0
while True:
    snap.suffix = "s%%06d" %% i
    snap.export()
    i += 1
"""


@pytest.mark.slow
def test_kill9_mid_snapshot_never_corrupts_current(tmp_path):
    """Acceptance: kill -9 a process that snapshots in a tight loop, at
    arbitrary moments; the _current link must always land on a
    manifest-verified, loadable snapshot."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rounds = 3
    verified_rounds = 0
    for i in range(rounds):
        workdir = str(tmp_path / ("round%d" % i))
        os.makedirs(workdir)
        child = subprocess.Popen(
            [sys.executable, "-c",
             _KILL9_CHILD % {"repo": repo, "dir": workdir}],
            stdout=subprocess.PIPE)
        assert child.stdout.readline().strip() == b"READY"
        time.sleep(0.05 + 0.19 * i)  # kill at varied phases
        os.kill(child.pid, signal.SIGKILL)
        child.wait()
        child.stdout.close()

        link = os.path.join(workdir, "k_current")
        if not os.path.lexists(link):
            continue  # killed before the first snapshot completed: fine
        ok, detail = SnapshotterBase.verify_snapshot(link)
        # the flip happens after the manifest write, so _current may
        # briefly name a snapshot whose manifest is the only residue
        # missing — unverifiable is acceptable ONLY when loadable
        assert ok is not False, \
            "_current points at a corrupt snapshot: %s" % (detail,)
        assert SnapshotterBase.import_file(link) is not None
        verified_rounds += 1
    assert verified_rounds >= 1, \
        "every kill landed before the first snapshot — no coverage"
