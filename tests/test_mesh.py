"""Elastic device-mesh tests (docs/distributed.md, "Elastic mesh
contract"): consistent-hash shard ownership, the ZeRO-1 state layout
and its flat-all-reduce bit-identity, and the MeshManager's live
reshard — quiesce/coalesce, minimal movement, warm-rejoin compile
cache, safety-snapshot crash recovery.  The seeded soak receipt is
scripts/mesh_soak.py -> ELASTIC_MESH.json."""

import numpy
import pytest

import jax

from veles_tpu import chaos
from veles_tpu.compiler import LayerPlan, build_train_step
from veles_tpu.elastic import FleetView, movement_plan, shard_owners
from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.parallel.mesh import (
    MeshManager, auto_mesh, mesh_snapshot, unzero_state, zero_slot_table,
    zero_state)

pytestmark = pytest.mark.mesh

DEVICES = sorted(jax.devices(), key=lambda d: d.id)

FAN_IN, HIDDEN, CLASSES = 16, 32, 4


def _plans(solver="momentum"):
    hyper = {"learning_rate": 0.1, "gradient_moment": 0.9}
    return [LayerPlan(All2AllTanh, solver=solver, hyper=hyper),
            LayerPlan(All2AllSoftmax, solver=solver, hyper=hyper)]


def _state(seed=0, adadelta=False):
    rng = numpy.random.RandomState(seed)
    out = []
    for fi, fo in ((FAN_IN, HIDDEN), (HIDDEN, CLASSES)):
        entry = {
            "weights": rng.randn(fi, fo).astype(numpy.float32) * 0.1,
            "bias": numpy.zeros(fo, numpy.float32),
            "accum_weights": numpy.zeros((fi, fo), numpy.float32),
            "accum_bias": numpy.zeros(fo, numpy.float32),
            "accum2_weights": None, "accum2_bias": None}
        if adadelta:
            entry["accum2_weights"] = numpy.zeros((fi, fo),
                                                  numpy.float32)
            entry["accum2_bias"] = numpy.zeros(fo, numpy.float32)
        out.append(entry)
    return out


def _batch(seed=0, n=48):
    rng = numpy.random.RandomState(seed + 1)
    x = rng.randn(n, FAN_IN).astype(numpy.float32)
    y = (rng.randint(0, CLASSES, n)).astype(numpy.int32)
    return x, y


def _assert_states_equal(a, b):
    keys = ("weights", "bias", "accum_weights", "accum_bias",
            "accum2_weights", "accum2_bias")
    for pa, pb in zip(a, b):
        for key in keys:
            va, vb = pa.get(key), pb.get(key)
            if va is None or vb is None:
                assert va is None and vb is None
                continue
            numpy.testing.assert_array_equal(
                numpy.asarray(va), numpy.asarray(vb), err_msg=key)


# -- consistent-hash ownership (elastic.shard_owners) ---------------------


def test_shard_owners_exact_quotas_and_determinism():
    members = ["d%d" % i for i in range(5)]
    owners = shard_owners(16, members)
    assert sorted(owners) == list(range(16))
    counts = {m: 0 for m in members}
    for m in owners.values():
        counts[m] += 1
    # 16 over 5: three members own 3, two own 4 (floor/ceil quotas)
    assert sorted(counts.values()) == [3, 3, 3, 3, 4]
    assert owners == shard_owners(16, list(reversed(members)))


def test_shard_owners_leave_moves_only_departed_shards():
    members = ["d%d" % i for i in range(8)]
    before = shard_owners(16, members)
    after = shard_owners(16, members[:6], previous=before)
    departed = {s for s, m in before.items() if m in ("d6", "d7")}
    moved = {s for s in after if after[s] != before.get(s)}
    # minimal movement: ONLY the departed members' shards move...
    assert moved == departed
    # ...and the survivors' plans agree
    plan = movement_plan(before, after)
    assert plan["n_moved"] == len(departed)
    assert plan["changed_fraction"] == pytest.approx(
        len(departed) / 16.0)


def test_shard_owners_join_sheds_at_most_quota_excess():
    members = ["d%d" % i for i in range(6)]
    before = shard_owners(18, members)          # 3 each
    after = shard_owners(18, members + ["d6"], previous=before)
    counts = {}
    for m in after.values():
        counts[m] = counts.get(m, 0) + 1
    # every member lands on floor/ceil of 18/7 = 2..3
    assert set(counts.values()) <= {2, 3}
    moved = sum(1 for s in after if after[s] != before.get(s))
    # the joiner's quota is filled by shed shards only — never a
    # reshuffle among survivors
    assert moved == counts["d6"]


def test_movement_plan_counts_new_shards_as_moved():
    plan = movement_plan({}, {0: "a", 1: "b"})
    assert plan["n_moved"] == 2
    assert plan["changed_fraction"] == 1.0


# -- ZeRO-1 state layout --------------------------------------------------


def test_zero_slot_table_round_robin_and_padding():
    table = zero_slot_table(5, 2)
    # k = ceil(5/2) = 3 slots per device; pad id is n_shards (5)
    assert table.shape == (6,)
    assert table.dtype == numpy.int32
    assert sorted(t for t in table if t != 5) == [0, 1, 2, 3, 4]
    assert list(table).count(5) == 1


def test_zero_slot_table_rejects_over_capacity():
    with pytest.raises(ValueError):
        zero_slot_table(4, 2, owners={0: 0, 1: 0, 2: 0, 3: 1})


def test_zero_state_round_trip_bit_exact():
    state = _state(seed=3, adadelta=True)
    rng = numpy.random.RandomState(7)
    for entry in state:   # non-trivial accums: round-trip must move rows
        for key in ("accum_weights", "accum_bias", "accum2_weights",
                    "accum2_bias"):
            entry[key] = rng.randn(*entry[key].shape).astype(
                numpy.float32)
    packed = zero_state(state, 8, n_shards=16)
    assert all(e["zero_slots"].shape == (16,) for e in packed)
    _assert_states_equal(unzero_state(packed, 16), state)


# -- flat-vs-ZeRO bit-identity on a fixed mesh ---------------------------


@pytest.mark.dist
@pytest.mark.parametrize("solver,n_shards", [
    ("momentum", None), ("momentum", 16), ("adadelta", 16)])
def test_zero1_step_bit_identical_to_flat_allreduce(solver, n_shards):
    """The tentpole numerics gate: reduce-scatter + all-gather with
    sharded optimizer state produces bit-identical params AND accums
    to the flat all-reduce step — psum_scatter sums like psum, the
    repack moves rows, never values.  Any logical shard layout."""
    mesh = auto_mesh("data", DEVICES)
    plans = _plans(solver)
    adadelta = solver == "adadelta"
    x, y = _batch()
    flat_step = build_train_step(plans, mesh=mesh,
                                 grad_bucket_mb=float("inf"),
                                 donate=False)
    zero_step = build_train_step(plans, mesh=mesh, zero=1,
                                 zero_shards=n_shards, donate=False)
    flat = _state(adadelta=adadelta)
    packed = zero_state(_state(adadelta=adadelta), len(DEVICES),
                        n_shards=n_shards)
    m = n_shards or len(DEVICES)
    for _ in range(3):
        flat, flat_metrics = flat_step(flat, x, y, numpy.float32(48))
        packed, zero_metrics = zero_step(packed, x, y,
                                         numpy.float32(48))
    flat_host = [{k: None if v is None else numpy.asarray(v)
                  for k, v in e.items()} for e in flat]
    _assert_states_equal(unzero_state(packed, m), flat_host)
    assert float(flat_metrics["loss"]) == float(zero_metrics["loss"])
    # grad_norm may differ in last ULPs (per-shard association)
    assert float(zero_metrics["grad_norm"]) == pytest.approx(
        float(flat_metrics["grad_norm"]), rel=1e-5)


@pytest.mark.dist
def test_zero1_optimizer_state_shards_to_1_over_n():
    """The ZeRO-1 memory receipt: per-device optimizer-state bytes
    shrink ~1/N vs the replicated flat path (addressable_shards
    accounting; device_memory_gauges publishes the census gauges)."""
    from veles_tpu.observe.xla_introspect import device_memory_gauges
    mesh = auto_mesh("data", DEVICES)
    n = len(DEVICES)
    x, y = _batch()
    flat_step = build_train_step(_plans(), mesh=mesh,
                                 grad_bucket_mb=float("inf"),
                                 donate=False)
    zero_step = build_train_step(_plans(), mesh=mesh, zero=1,
                                 zero_shards=2 * n, donate=False)
    flat, _ = flat_step(_state(), x, y, numpy.float32(48))
    packed, _ = zero_step(zero_state(_state(), n, n_shards=2 * n),
                          x, y, numpy.float32(48))

    def per_device_accum_bytes(state):
        out = {d.id: 0 for d in DEVICES}
        for entry in state:
            for key in ("accum_weights", "accum_bias"):
                for shard in entry[key].addressable_shards:
                    out[shard.device.id] += int(shard.data.nbytes)
        return out

    flat_bytes = per_device_accum_bytes(flat)
    zero_bytes = per_device_accum_bytes(packed)
    # flat replicates: every device holds the full accums
    full = sum(e["accum_weights"].nbytes + e["accum_bias"].nbytes
               for e in _state())
    assert max(flat_bytes.values()) == full
    # sharded: ~1/N plus the ceil-division pad per tensor
    assert max(zero_bytes.values()) <= 1.5 * full / n
    gauges = device_memory_gauges()
    assert gauges, "memory gauges must publish on CPU too"


# -- MeshManager: live reshard -------------------------------------------


def test_mesh_manager_fixed_mesh_matches_flat_step():
    """No membership events: the manager is a plain ZeRO-1 trainer,
    bit-identical to the flat path."""
    mesh = auto_mesh("data", DEVICES)
    x, y = _batch()
    flat_step = build_train_step(_plans(), mesh=mesh,
                                 grad_bucket_mb=float("inf"),
                                 donate=False)
    flat = _state()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    for _ in range(3):
        flat, _ = flat_step(flat, x, y, numpy.float32(48))
        mgr.step(x, y)
    flat_host = [{k: None if v is None else numpy.asarray(v)
                  for k, v in e.items()} for e in flat]
    _assert_states_equal(mgr.canonical_state(), flat_host)
    assert mgr.reshard_log == []


def test_reshard_moves_only_changed_owner_bytes():
    x, y = _batch()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr.step(x, y)
    mgr.submit_membership(DEVICES[:6])
    mgr.step(x, y)
    (event,) = mgr.reshard_log
    assert event["from_size"] == 8 and event["to_size"] == 6
    # two departed devices owned 2 shards each (16 over 8)
    assert event["moved_shards"] == 4
    assert event["changed_fraction"] == pytest.approx(0.25)
    assert event["bytes_moved"] < event["full_gather_bytes"]
    assert event["bytes_moved"] == round(
        event["changed_fraction"] * event["full_gather_bytes"])


def test_reshard_convergence_within_ulp_band_and_warm_rejoin():
    """Shrink then grow back: final state stays inside the TP ULP
    contract of the fault-free run (association order changes with N;
    rows never change), and the rejoin to a seen device set hits the
    compile cache."""
    x, y = _batch()
    ref = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    for i in range(6):
        if i == 2:
            mgr.submit_membership(DEVICES[:6])
        if i == 4:
            mgr.submit_membership(DEVICES)
        ref.step(x, y)
        mgr.step(x, y)
    assert [e["to_size"] for e in mgr.reshard_log] == [6, 8]
    assert mgr.reshard_log[1]["compile_cached"], \
        "rejoining a seen device set must not recompile"
    for pa, pb in zip(mgr.canonical_state(), ref.canonical_state()):
        for key in ("weights", "bias"):
            numpy.testing.assert_allclose(
                pa[key], pb[key], rtol=1e-3, atol=1e-6)


def test_shrink_to_one_device_and_grow_past_original():
    x, y = _batch()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES[:4],
                      n_shards=16, donate=False)
    mgr.step(x, y)
    mgr.submit_membership(DEVICES[:1])
    mgr.step(x, y)
    assert mgr.size == 1
    # grow PAST the original size: 1 -> 8
    mgr.submit_membership(DEVICES)
    mgr.step(x, y)
    assert mgr.size == 8
    assert [e["to_size"] for e in mgr.reshard_log] == [1, 8]
    # every device owns at least one of the 16 shards after the grow
    assert len(set(mgr._owners.values())) == 8


def test_back_to_back_events_coalesce_into_one_reshard():
    x, y = _batch()
    before = _registry.counter("mesh.coalesced_events").value
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr.step(x, y)
    mgr.submit_membership(DEVICES[:6])
    mgr.submit_membership(DEVICES[:5])
    mgr.submit_membership(DEVICES[:4])   # newest wins, one reshard
    mgr.step(x, y)
    assert [e["to_size"] for e in mgr.reshard_log] == [4]
    assert _registry.counter("mesh.coalesced_events").value \
        == before + 2


def test_same_device_set_event_is_a_noop():
    x, y = _batch()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr.step(x, y)
    mgr.submit_membership(list(DEVICES))   # leave+rejoin of the same set
    mgr.step(x, y)
    assert mgr.reshard_log == []
    assert mgr.mesh_epoch == 0


def test_poisoned_step_skips_uniformly_across_reshard_boundary():
    """The skip-step guard (docs/health.md) must hold THROUGH a
    reshard: a poisoned gradient on the first post-reshard step leaves
    params and solver state bit-identical to never having run it —
    on the new mesh, uniformly across every device's owned shards."""
    x, y = _batch()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr.step(x, y)
    mgr.submit_membership(DEVICES[:6])
    mgr.maybe_reshard()
    before = mgr.canonical_state()
    metrics = mgr.step(x, y, grad_poison=numpy.float32(float("nan")))
    assert int(metrics["skipped"]) == 1
    _assert_states_equal(mgr.canonical_state(), before)
    # and the next clean step advances normally
    clean = mgr.step(x, y)
    assert int(clean["skipped"]) == 0


def test_crash_mid_reshard_resumes_bit_exact(tmp_path):
    """Chaos ``mesh.reshard=crash`` dies after the safety snapshot,
    before destructive movement; MeshManager.resume (the --resume auto
    path) restores from the manifest-verified snapshot and the run
    finishes bit-identical to the uninterrupted one, with every step
    applied exactly once."""
    x, y = _batch()

    def run(crash, snapdir):
        mgr = MeshManager(_plans(), _state(), devices=DEVICES,
                          n_shards=16, snapshot_dir=snapdir,
                          donate=False)
        if crash:
            chaos.install(
                chaos.FaultPlan.from_spec("mesh.reshard=crash:n1"))
        applied = []
        try:
            while mgr.applied_steps < 6:
                if mgr.applied_steps == 3 and not mgr.reshard_log:
                    mgr.submit_membership(DEVICES[:6])
                i = mgr.applied_steps
                try:
                    mgr.step(x, y)
                except chaos.ChaosCrash:
                    mgr = MeshManager.resume(snapdir, _plans(),
                                             devices=DEVICES[:6],
                                             donate=False)
                    continue
                applied.append(i)
        finally:
            if crash:
                chaos.uninstall()
        return mgr, applied

    ref, ref_applied = run(False, str(tmp_path / "ref"))
    mgr, applied = run(True, str(tmp_path / "crash"))
    assert ref_applied == applied == list(range(6)), \
        "no minibatch lost or double-applied across the crash"
    _assert_states_equal(mgr.canonical_state(), ref.canonical_state())


def test_sync_fleet_feeds_membership_from_fleet_view():
    x, y = _batch()
    fleet = FleetView()
    fleet.join("s0", 1.0)
    fleet.join("s1", 1.0)
    by_sid = {"s0": DEVICES[:4], "s1": DEVICES[4:]}
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    assert mgr.sync_fleet(fleet, lambda sid: by_sid[sid])
    # same epoch again: deduped, no new event
    assert not mgr.sync_fleet(fleet, lambda sid: by_sid[sid])
    mgr.step(x, y)
    assert mgr.reshard_log == []   # same 8-device union: no-op
    fleet.leave("s1")
    assert mgr.sync_fleet(fleet, lambda sid: by_sid[sid])
    mgr.step(x, y)
    assert [e["to_size"] for e in mgr.reshard_log] == [4]


def test_mesh_snapshot_publishes_gauges_and_histogram():
    x, y = _batch()
    mgr = MeshManager(_plans(), _state(), devices=DEVICES, n_shards=16,
                      donate=False)
    mgr.step(x, y)
    mgr.submit_membership(DEVICES[:6])
    mgr.step(x, y)
    snap = mesh_snapshot()
    assert snap["size"] == 6
    assert snap["epoch"] == mgr.mesh_epoch
    assert snap["reshards"] >= 1
    assert snap["bytes_moved"] >= mgr.reshard_log[-1]["bytes_moved"]
    assert snap["reshard_s"]["count"] >= 1


def test_batch_not_divisible_raises_helpfully():
    mgr = MeshManager(_plans(), _state(), devices=DEVICES[:5],
                      n_shards=16, donate=False)
    x, y = _batch(n=48)   # 48 % 5 != 0
    with pytest.raises(ValueError, match="divisible"):
        mgr.step(x, y)
