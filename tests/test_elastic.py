"""Elastic fleet (veles_tpu/elastic.py + server/client integration):
membership epochs, dynamic resharding on join/leave, exactly-once
update semantics (stale + speculative-duplicate rejection), the
drop-vs-apply requeue race, speculative backup dispatch lifted from
the jobfarm, and the seeded preempt/rejoin soak smoke — the elasticity
contract of docs/distributed.md, TESTED rather than assumed.

The hour-scale SIGKILL soak (subprocess slaves preempted on an
aK-style schedule, receipted in ELASTIC.json) runs under ``slow`` via
scripts/elastic_soak.py; the in-process smoke here exercises the same
master-side requeue/reshard/stale machinery with three seeded
die/rejoin cycles in tier-1 time.
"""

import asyncio
import math
import threading
import time
from collections import deque

import numpy
import pytest

from veles_tpu import chaos, elastic
from veles_tpu.chaos import FaultPlan
from veles_tpu.client import Client
from veles_tpu.elastic import (
    FleetView, POWER_SCALE_BOUND, effective_power, fleet_snapshot,
    power_shares, speculation_threshold)
from veles_tpu.jobfarm import _FarmMaster, _UNSET
from veles_tpu.network_common import pack_payload
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.server import Server, SlaveDescription
from tests.test_chaos import _build, _start_server, _weights

pytestmark = pytest.mark.elastic


# -- the shared math (degenerate-safe by contract) ------------------------


def test_effective_power_degenerate():
    assert effective_power(2.5) == 2.5
    for sick in (0.0, -3.0, float("nan"), float("inf"),
                 float("-inf"), None, "garbage", [1]):
        assert effective_power(sick) == 1.0


def test_power_shares_exact_and_deterministic():
    shares = power_shares(100, {"a": 3.0, "b": 1.0})
    assert shares == {"a": 75, "b": 25}
    # exact sum even when nothing divides evenly
    shares = power_shares(10, {"a": 1.0, "b": 1.0, "c": 1.0})
    assert sum(shares.values()) == 10
    assert sorted(shares.values()) == [3, 3, 4]
    # deterministic tie-break: same inputs, same split
    again = power_shares(10, {"a": 1.0, "b": 1.0, "c": 1.0})
    assert shares == again
    # nothing to partition
    assert power_shares(100, {}) == {}
    assert power_shares(None, {"a": 1.0}) == {}
    assert power_shares(-5, {"a": 1.0}) == {}
    assert power_shares(0, {"a": 1.0, "b": 2.0}) == {"a": 0, "b": 0}


def test_power_shares_degenerate_powers_never_divide_by_zero():
    # an all-zero (or negative, or NaN) fleet must not ZeroDivision:
    # every sick rating collapses to the neutral 1.0 -> equal split
    shares = power_shares(9, {"a": 0.0, "b": -1.0, "c": float("nan")})
    assert sum(shares.values()) == 9
    assert max(shares.values()) - min(shares.values()) <= 1
    # one sick member among healthy ones weighs as baseline
    shares = power_shares(4, {"a": 0.0, "b": 3.0})
    assert shares == {"a": 1, "b": 3}


def test_speculation_threshold_basics():
    # no fleet info: the plain MapReduce bar
    assert speculation_threshold(10.0, 2.0, 5.0) == 20.0
    # the floor keeps millisecond jobs from speculating their tail
    assert speculation_threshold(0.01, 2.0, 5.0) == 5.0
    # sick means collapse to the floor instead of exploding
    for sick in (float("nan"), -3.0, None, "x"):
        assert speculation_threshold(sick, 2.0, 5.0) == 5.0


def test_speculation_threshold_power_corrected_and_bounded():
    # a slave rated at half the fleet mean gets 2x the runway
    fleet = (1.0, 1.0, 4.0)  # mean 2.0
    t = speculation_threshold(10.0, 2.0, 0.1, owner_power=1.0,
                              fleet_powers=fleet)
    assert t == pytest.approx(40.0)
    # ...and a fast slave gets less
    t = speculation_threshold(10.0, 2.0, 0.1, owner_power=4.0,
                              fleet_powers=fleet)
    assert t == pytest.approx(10.0)
    # one absurd rating cannot make a job unspeculatable: the scale
    # clamps to POWER_SCALE_BOUND in both directions
    t = speculation_threshold(10.0, 1.0, 0.1, owner_power=1e-9,
                              fleet_powers=(1e-9, 1000.0))
    assert t <= 10.0 * POWER_SCALE_BOUND + 1e-9
    t = speculation_threshold(10.0, 1.0, 5.0, owner_power=1e9,
                              fleet_powers=(1e9, 1.0))
    assert t >= 10.0 / POWER_SCALE_BOUND


def test_speculation_threshold_degenerate_fleets():
    # zero/negative/single-member fleets: aggregates stay positive
    for fleet in ((0.0,), (-1.0, 0.0), (float("nan"),), (2.0,)):
        t = speculation_threshold(1.0, 2.0, 0.5, owner_power=0.0,
                                  fleet_powers=fleet)
        assert math.isfinite(t) and t >= 0.5
    # a single healthy member speculating its own fleet: scale == 1
    assert speculation_threshold(
        10.0, 2.0, 0.1, owner_power=3.0,
        fleet_powers=(3.0,)) == pytest.approx(20.0)


def test_fleet_view_epochs():
    fleet = FleetView()
    assert len(fleet) == 0 and fleet.membership_epoch == 0
    assert fleet.join("a", 2.0) == 1
    assert fleet.join("b", 1.0) == 2
    assert len(fleet) == 2
    assert fleet.shares(30) == {"a": 20, "b": 10}
    assert sorted(fleet.powers()) == [1.0, 2.0]
    assert fleet.leave("a") == 3
    # a double drop is not a membership change
    assert fleet.leave("a") == 3
    assert fleet.shares(30) == {"b": 30}


def test_fleet_view_throughput_ema_share_mode():
    """The serve tier's share mode: weights are MEASURED throughput
    EMAs, not static ratings — cold members read the neutral 1.0, the
    first real sample seeds the EMA directly, later ones decay in."""
    fleet = FleetView(throughput_alpha=0.5)
    fleet.join("a", 1.0)
    fleet.join("b", 1.0)
    # cold start: neutral 1.0 everywhere -> equal split
    assert fleet.throughput("a") == 1.0
    assert fleet.throughputs() == [1.0, 1.0]
    assert fleet.shares(10, by="throughput") == {"a": 5, "b": 5}
    # the FIRST observation seeds the EMA directly (no 1.0 bias that
    # would take dozens of samples to wash out of a rows/sec scale)
    assert fleet.observe_throughput("a", 300.0) == 300.0
    # decay: alpha=0.5 folds each new sample in halfway
    assert fleet.observe_throughput("a", 100.0) == pytest.approx(200.0)
    assert fleet.observe_throughput("a", 100.0) == pytest.approx(150.0)
    fleet.observe_throughput("b", 50.0)
    assert fleet.shares(100, by="throughput") == {"a": 75, "b": 25}
    # the power mode is untouched by observations
    assert fleet.shares(100) == {"a": 50, "b": 50}
    # callers that can substitute a better prior detect cold members
    assert fleet.throughput("ghost", default=None) is None


def test_fleet_view_throughput_sick_samples_neutralized():
    """A host reporting zero/negative/NaN/garbage throughput
    neutralizes to 1.0 like effective_power — one corrupt report can
    dent the EMA but never poison a fleet aggregate."""
    for sick in (0.0, -5.0, float("nan"), float("inf"), None, "junk"):
        cold = FleetView(throughput_alpha=0.5)
        cold.join("a", 1.0)
        assert cold.observe_throughput("a", sick) == 1.0
    fleet = FleetView(throughput_alpha=0.5)
    fleet.join("a", 1.0)
    fleet.observe_throughput("a", 200.0)
    ema = fleet.observe_throughput("a", float("nan"))
    assert math.isfinite(ema) and ema == pytest.approx(100.5)


def test_fleet_view_throughput_forgotten_on_leave():
    fleet = FleetView()
    fleet.join("a", 1.0)
    fleet.observe_throughput("a", 500.0)
    fleet.leave("a")
    fleet.join("a", 1.0)
    # a rejoin restarts cold: the pre-leave rate is stale evidence
    assert fleet.throughput("a") == 1.0


# -- server threshold math under degenerate stats -------------------------


class _IdleWorkflow(object):
    checksum = "idle"

    def generate_initial_data_for_slave(self, slave):
        return None

    def generate_data_for_slave(self, slave):
        return False

    def apply_data_from_slave(self, update, slave):
        return True

    def drop_slave(self, slave):
        pass


def test_timeout_threshold_degenerate_samples():
    server = Server("127.0.0.1:0", _IdleWorkflow(), job_timeout=7.0)
    # under 4 samples there is no credible sigma: the floor rules
    assert server._timeout_threshold() == 7.0
    server._all_job_times.extend([0.1, 0.1, 0.1])
    assert server._timeout_threshold() == 7.0
    # constant samples (sigma 0): mean + 3*0 < floor -> still 7
    server._all_job_times.append(0.1)
    assert server._timeout_threshold() == 7.0
    # a genuine spread lifts the threshold above the floor
    server._all_job_times.extend([30.0, 30.0, 30.0, 30.0])
    assert server._timeout_threshold() > 7.0
    assert math.isfinite(server._timeout_threshold())


def test_server_speculation_threshold_uses_fleet_powers():
    server = Server("127.0.0.1:0", _IdleWorkflow(),
                    speculation_factor=2.0, min_speculation_s=0.5)
    # degenerate fleet powers must not blow up the server's bar
    server.fleet.join("a", 0.0)
    server.fleet.join("b", -1.0)
    t = elastic.speculation_threshold(
        1.0, server.speculation_factor, server.min_speculation_s,
        owner_power=0.0, fleet_powers=server.fleet.powers())
    assert math.isfinite(t) and t == pytest.approx(2.0)


# -- jobfarm's shared threshold under degenerate powers -------------------


def _slave(sid, power=1.0):
    return SlaveDescription(sid, "mid-" + sid, 0, power)


def test_farm_speculation_survives_degenerate_powers():
    m = _FarmMaster("c", speculation_factor=1.0, min_speculation_s=0.1)
    m.reset(["a", "b"])
    e = m.epoch
    sick = _slave("s1", power=0.0)       # zero rating
    worse = _slave("s2", power=-5.0)     # negative rating
    assert m.generate_data_for_slave(sick) == (e, 0, "a")
    assert m.generate_data_for_slave(worse) == (e, 1, "b")
    m.apply_data_from_slave((e, 1, ("ok", "B")), worse)
    m._durations.clear()
    m._durations.append(0.01)
    # job 0 straggles on the zero-power slave: the power-corrected
    # threshold must stay finite and the idle slave must shadow it
    m._outstanding[0][sick.id] = time.perf_counter() - 100.0
    assert m.generate_data_for_slave(worse) == (e, 0, "a")
    m.apply_data_from_slave((e, 0, ("ok", "rescued")), worse)
    assert m.results == [("ok", "rescued"), ("ok", "B")]


def test_farm_single_slave_fleet_never_self_speculates():
    m = _FarmMaster("c", speculation_factor=1.0,
                    min_speculation_s=0.01)
    m.reset(["a"])
    only = _slave("s1", power=float("nan"))
    assert m.generate_data_for_slave(only) == (m.epoch, 0, "a")
    m._durations.append(0.01)
    m._outstanding[0][only.id] = time.perf_counter() - 100.0
    # the sole member already owns the only copy: no second copy
    assert m.generate_data_for_slave(only) is False
    assert m.results == [_UNSET]


def test_farm_drop_slave_forgets_power_rating():
    m = _FarmMaster("c")
    m.reset(["a"])
    s = _slave("s1", power=100.0)
    m.generate_data_for_slave(s)
    assert m._powers[s.id] == 100.0
    m.drop_slave(s)
    assert s.id not in m._powers


# -- e2e: membership epochs + reshard pushes ------------------------------


class _StubMaster(object):
    """Minimal master-side workflow contract with explicit job/requeue
    bookkeeping, so tests can assert EXACTLY what applied vs requeued."""

    checksum = "elastic-stub"
    update_validation = "prewalk"

    def __init__(self, jobs, remainder=None):
        self._lock = threading.Lock()
        self.pending = deque(jobs)
        self.outstanding = {}        # slave id -> [jobs]
        self.applied = []            # (job, slave id)
        self.drops = []
        self.events = []             # ordered apply/drop audit trail
        self.remainder = remainder
        self.apply_gate = None       # optional: blocks applies
        self.apply_started = threading.Event()

    def generate_initial_data_for_slave(self, slave):
        return None

    def generate_data_for_slave(self, slave):
        with self._lock:
            if not self.pending:
                return False
            job = self.pending.popleft()
            self.outstanding.setdefault(slave.id, []).append(job)
            return job

    def apply_data_from_slave(self, update, slave):
        self.apply_started.set()
        if self.apply_gate is not None:
            # generous window: the gate is a DETERMINISTIC handoff (the
            # test opens it once its assertions ran), so a long timeout
            # costs nothing when healthy but keeps full-suite load from
            # expiring the wedge mid-sequence (the PR 12/13 flake)
            assert self.apply_gate.wait(60), "apply gate never opened"
        with self._lock:
            job = update[1]
            jobs = self.outstanding.get(slave.id, [])
            if job in jobs:
                jobs.remove(job)
            self.applied.append((job, slave.id))
            self.events.append(("apply", job))
        return True

    def drop_slave(self, slave):
        with self._lock:
            self.drops.append(slave.id)
            self.events.append(("drop", slave.id))
            # requeue whatever is STILL outstanding for that slave
            for job in self.outstanding.pop(slave.id, []):
                self.pending.appendleft(job)

    def unserved_remainder(self):
        if self.remainder is not None:
            return self.remainder
        with self._lock:
            return len(self.pending) + sum(
                len(v) for v in self.outstanding.values())


class _StubSlave(object):
    """Client-side stub: returns each job payload as its result.
    Jobs in ``slow_on`` straggle — until ``gate`` is set when one is
    given (a PURE event wedge, no wall-clock cap: every gated test
    releases it in its ``finally``, so the owner can never un-wedge
    on its own under full-suite load and race the assertions — the
    last PR-9-era timing window, closed like the PR 14 deflakes),
    else for ``slow_s`` seconds."""

    checksum = "elastic-stub"

    def __init__(self, slow_on=(), slow_s=2.0, gate=None):
        self.slow_on = set(slow_on)
        self.slow_s = slow_s
        self.gate = gate
        self.reshards = []

    def apply_initial_data_from_master(self, data):
        pass

    def apply_reshard(self, info):
        self.reshards.append(dict(info))

    def do_job(self, data, update, callback):
        if data in self.slow_on:
            if self.gate is not None:
                self.gate.wait()
            else:
                time.sleep(self.slow_s)
        callback(("done", data))


class _PowerClient(Client):
    """Client reporting a FIXED power rating (deterministic shares)."""

    def __init__(self, *args, power=1.0, **kwargs):
        super(_PowerClient, self).__init__(*args, **kwargs)
        self._fixed_power = power

    @property
    def computing_power(self):
        return self._fixed_power


def _wait_for(predicate, timeout=30.0, what="condition"):
    # every caller waits on a DETERMINISTIC handoff (a push the server
    # already scheduled, a flag another thread must set), so a wide
    # default costs nothing when healthy; the old 10 s bound was the
    # PR 11/12 reshard-race flake — reshard pushes ride an executor
    # hop + the event loop, and full-suite load on a small host
    # stretched that past 10 s while solo runs land in ~0.1 s
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError("timed out waiting for " + what)


def _stub_server(master, **kwargs):
    server = Server("127.0.0.1:0", master, **kwargs)
    thread = server.start_background()
    assert server.wait_listening(10)
    return server, thread


def test_membership_epochs_and_reshard_push():
    master = _StubMaster([], remainder=100)
    server, _ = _stub_server(master)
    wf1, wf2 = _StubSlave(), _StubSlave()
    c1 = _PowerClient("127.0.0.1:%d" % server.port, wf1, power=3.0)
    c2 = _PowerClient("127.0.0.1:%d" % server.port, wf2, power=1.0)
    t1 = c1.start_background()
    t2 = None
    try:
        _wait_for(lambda: c1.member_epoch == 1, what="first join")
        # the join push hands the whole remainder to the only member
        _wait_for(lambda: wf1.reshards
                  and wf1.reshards[-1]["share"] == 100,
                  what="solo share push")
        t2 = c2.start_background()
        _wait_for(lambda: c2.member_epoch == 2, what="second join")
        # the second join REPARTITIONS without restarting anything:
        # power-weighted 3:1 split of the same remainder, both slaves
        # told, membership epoch bumped exactly once
        _wait_for(lambda: wf1.reshards
                  and wf1.reshards[-1]["epoch"] == 2,
                  what="repartition push to slave 1")
        _wait_for(lambda: wf2.reshards
                  and wf2.reshards[-1]["epoch"] == 2,
                  what="repartition push to slave 2")
        assert wf1.reshards[-1]["share"] == 75
        assert wf2.reshards[-1]["share"] == 25
        assert wf1.reshards[-1]["fleet"] == 2
        assert server.fleet.membership_epoch == 2
        assert server.reshards == 2
        # the fleet block dashboards/heartbeats read is live
        snap = fleet_snapshot()
        assert snap["membership_epoch"] == 2
        assert snap["live"] == 2
        assert _registry.peek("elastic.membership_epoch").value == 2
    finally:
        server.stop()
        server._done.wait(10)
        t1.join(10)
        if t2 is not None:
            t2.join(10)


def test_drop_requeues_reshards_and_replays(cpu_device):
    """A slave dying mid-run: requeue + leave-reshard + replay on
    rejoin — final weights bit-identical to the fault-free run."""
    master_ref = _build("master", "elastic_drop_m", cpu_device)
    slave_ref = _build("slave", "elastic_drop_s", cpu_device)
    server_ref, _ = _start_server(master_ref)
    client_ref = Client("127.0.0.1:%d" % server_ref.port, slave_ref)
    client_ref.run()
    # wide deterministic windows (the soak smoke's discipline): both
    # runs end by event; the fault-free 10 s bound was the OTHER half
    # of the PR 11/12 reshard-race flake — the die/rejoin backoff plus
    # the rejoin's reshard push stretch under full-suite load while
    # solo runs finish in ~2 s
    assert server_ref._done.wait(60)
    ref_weights = _weights(master_ref)

    master = _build("master", "elastic_drop_m", cpu_device)
    slave = _build("slave", "elastic_drop_s", cpu_device)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    plan = chaos.install(FaultPlan().add("client.job", "die", nth=2))
    try:
        client.run()
    finally:
        chaos.uninstall()
    assert server._done.wait(90)
    assert plan.fired("client.job") == 1
    assert client.sessions_established == 2
    # join, leave, rejoin: three membership changes, three reshards
    assert server.reshards >= 3
    assert server.fleet.membership_epoch >= 3
    assert master.loader.total_failed >= 1, "the job must requeue"
    assert server.stale_updates == 0, \
        "a die-before-job death leaves no in-flight update to reject"
    for got, want in zip(_weights(master), ref_weights):
        numpy.testing.assert_array_equal(got, want)


# -- the requeue race (satellite audit) -----------------------------------


def test_drop_during_apply_defers_requeue_never_doubles():
    """Regression for the drop-vs-apply race: a slave dropped AFTER its
    update was received but BEFORE check_and_apply completes must not
    have that job both requeued and applied.  The drop's requeue is
    deferred until the in-flight apply finishes; the applied job is
    then NOT among the requeued ones."""
    master = _StubMaster(["j1"])
    master.apply_gate = threading.Event()
    server, _ = _stub_server(master)
    wf = _StubSlave()
    client = Client("127.0.0.1:%d" % server.port, wf)
    thread = client.start_background()
    try:
        # the update for j1 arrives and its apply BLOCKS mid-flight
        # (deterministic handoff; wide window, same discipline as the
        # apply gate above)
        assert master.apply_started.wait(60)
        conn = list(server.slaves.values())[0]
        # the slave is dropped while the apply is still on the executor
        server._loop.call_soon_threadsafe(server._drop, conn, "test")
        # _drop flips conn.dropped BEFORE it registers the deferral
        # (the flag is the stale-update fence and must come first) —
        # wait on the deferral itself, the state the assertions read,
        # not the flag; the sleep is then purely the negative window
        # for a wrong requeue to surface
        _wait_for(lambda: conn.slave.id in server._deferred_drops,
                  what="deferred drop registered")
        assert conn.dropped
        time.sleep(0.3)
        assert master.drops == [], \
            "requeue must be DEFERRED while the update is mid-apply"
        assert server.drops_deferred == 1
        assert server._deferred_drops[conn.slave.id][1] == "test"
        # release the apply: it completes, THEN the drop finishes
        master.apply_gate.set()
        _wait_for(lambda: master.drops, what="deferred drop")
        assert master.events[0] == ("apply", "j1"), \
            "the in-flight apply must win the race"
        assert master.events[1][0] == "drop"
        assert master.applied == [("j1", conn.slave.id)]
        assert list(master.pending) == [], \
            "the APPLIED job must not also be requeued"
        # any further update from the departed slave is STALE: rejected
        # before validation, never applied
        fut = asyncio.run_coroutine_threadsafe(
            server._dispatch({"type": "update", "job_id": "whatever",
                              "codec": "none"},
                             pack_payload(("done", "j1")),
                             conn, None, None),
            server._loop)
        fut.result(10)
        assert server.stale_updates == 1
        assert master.applied == [("j1", conn.slave.id)], \
            "the stale update must never reach the workflow"
        assert _registry.peek("elastic.stale_updates").value >= 1
    finally:
        master.apply_gate.set()
        server.stop()
        server._done.wait(10)
        thread.join(10)


def test_stop_during_apply_drains_bookkeeping_before_done():
    """Regression for the server-side lost-update race behind the
    kill-during-reshard flake: a workflow that completes INSIDE
    check_and_apply (the decision latching ``complete`` on the
    executor thread) schedules the stop via call_soon_threadsafe
    BEFORE the executor future's own continuation, so _main could
    return — and asyncio.run cancel the _apply_update coroutine —
    after the weights mutated but before the bookkeeping
    (updates_applied, the ack, deferred drops) ran.  The teardown now
    drains ``_applying`` first: _done must not fire while an apply is
    mid-executor, and the counter must reflect every update the
    workflow actually absorbed."""
    master = _StubMaster(["j1"])
    master.apply_gate = threading.Event()
    server, thread = _stub_server(master)
    wf = _StubSlave()
    client = Client("127.0.0.1:%d" % server.port, wf)
    cthread = client.start_background()
    try:
        # j1's update arrives and its apply wedges on the executor
        assert master.apply_started.wait(60)
        # the stop lands while the apply is still in flight — the
        # exact scheduling the completion-inside-apply race produces
        server.stop()
        assert not server._done.wait(0.5), \
            "teardown must drain the in-flight apply, not bail"
        assert server.updates_applied == 0
        master.apply_gate.set()
        assert server._done.wait(30)
        assert master.applied and master.applied[0][0] == "j1", \
            "the wedged apply must have reached the workflow"
        assert server.updates_applied == 1, \
            "an apply that mutated the workflow must be counted"
    finally:
        master.apply_gate.set()
        server.stop()
        server._done.wait(10)
        thread.join(10)
        cthread.join(10)


# -- speculative backup dispatch (lifted from the jobfarm) ----------------


def test_server_speculation_first_result_wins():
    """The straggler path end-to-end: slave A wedges on its job, idle
    slave B is handed a backup copy of the SAME stamped job, B's
    result applies under A's reservation, and A's late duplicate is
    dropped before validation — applied exactly once.

    A's wedge is an EVENT (released only after the backup's result
    applied), not a wall-clock sleep: the old 2.5 s nap could expire
    under full-suite load before the watchdog crossed the speculation
    threshold, letting the owner win its own race and the
    ``speculated == 1`` wait time out (the PR 12/13 flake)."""
    wedge = threading.Event()
    master = _StubMaster(["seed", "slow"])
    server, _ = _stub_server(master, speculation_factor=1.0,
                             min_speculation_s=0.2)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _StubSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a)
    ta = ca.start_background()
    tb = None
    try:
        # A alone: completes "seed" (seeding the duration stats) and
        # wedges on "slow"
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: not master.pending
                  and master.outstanding.get(ca.sid), what="slow out")
        a_sid = ca.sid
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        # B idles at the sync point until the straggler crosses the
        # threshold; the watchdog tick re-evaluates and dispatches the
        # backup copy
        _wait_for(lambda: server.speculated == 1, timeout=30,
                  what="speculative dispatch")
        _wait_for(lambda: len(master.applied) == 2, timeout=30,
                  what="backup result")
        # B won, but the apply retired the OWNER's reservation
        assert master.applied[1] == ("slow", a_sid)
        # release the owner: its late duplicate is dropped before
        # validation
        wedge.set()
        _wait_for(lambda: server.duplicates_dropped == 1, timeout=30,
                  what="duplicate drop")
        assert len(master.applied) == 2, "never applied twice"
        assert _registry.peek("elastic.speculative_jobs").value >= 1
        assert server.stale_updates == 0
    finally:
        wedge.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


def test_owner_drop_during_backup_apply_defers_requeue():
    """Regression for the speculated flavor of the requeue race: the
    straggling OWNER is dropped while its backup's winning update —
    which applies under the owner's reservation — is mid-apply.  The
    drop must defer on the APPLY TARGET (not the sender's conn), so
    the job is applied once and never also requeued."""
    wedge = threading.Event()
    master = _StubMaster(["seed", "slow"])
    server, _ = _stub_server(master, speculation_factor=1.0,
                             min_speculation_s=0.2)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _StubSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a)
    ta = ca.start_background()
    tb = None
    try:
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: master.outstanding.get(ca.sid),
                  what="slow job out")
        a_sid = ca.sid
        a_conn = server.slaves[a_sid]
        # gate the NEXT apply (the backup's result) mid-flight
        master.apply_started.clear()
        master.apply_gate = threading.Event()
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        _wait_for(lambda: server.speculated == 1, timeout=15,
                  what="speculative dispatch")
        assert master.apply_started.wait(15), "backup result mid-apply"
        # drop the OWNER while the backup's update is applying under
        # the owner's reservation
        server._loop.call_soon_threadsafe(server._drop, a_conn,
                                          "owner-timeout")
        # same discipline as the drop-during-apply test above: the
        # dropped flag precedes the deferral registration, so wait on
        # the registration the assertions read
        _wait_for(lambda: a_sid in server._deferred_drops,
                  what="deferred owner drop registered")
        assert a_conn.dropped
        time.sleep(0.3)
        assert master.drops == [], \
            "the owner's requeue must defer on the apply target"
        assert server.drops_deferred == 1
        master.apply_gate.set()
        _wait_for(lambda: master.drops == [a_sid],
                  what="deferred owner drop")
        slow_apply = master.events.index(("apply", "slow"))
        assert master.events.index(("drop", a_sid)) > slow_apply, \
            "the winning apply must complete before the drop requeues"
        assert master.applied.count(("slow", a_sid)) == 1
        assert list(master.pending) == [], \
            "the applied job must not also be requeued"
    finally:
        wedge.set()
        if master.apply_gate is not None:
            master.apply_gate.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


def test_speculated_owner_request_parks_until_resolution():
    """An async (pipelining) owner asking for MORE work while its job
    is speculated — or while the backup's winning result is mid-apply
    under its reservation — must be PARKED, not served: a second
    reservation under the owner would be retired by the wrong result
    (the loader pops reservations LIFO per slave)."""
    wedge = threading.Event()
    master = _StubMaster(["seed", "slow"])
    server, _ = _stub_server(master, speculation_factor=1.0,
                             min_speculation_s=0.2)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _StubSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a, async_slave=True)
    ta = ca.start_background()
    tb = None
    try:
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: master.outstanding.get(ca.sid) == ["slow"],
                  what="slow out alone")
        # hold the NEXT apply (the backup's winning result) open so
        # both windows — speculated-unresolved and mid-apply — exist
        master.apply_started.clear()
        master.apply_gate = threading.Event()
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        _wait_for(lambda: server.speculated == 1, timeout=15,
                  what="speculative dispatch")
        # fresh work appears while the owner's job is speculated /
        # mid-apply; the parked-requester retry ticks at 0.5 s and
        # must NOT hand it to the owner
        master.pending.append("next")
        assert master.apply_started.wait(15), "backup result mid-apply"
        time.sleep(1.2)
        assert master.outstanding.get(ca.sid) == ["slow"], \
            "owner must not get a second reservation while its job " \
            "is speculated or mid-apply"
        master.apply_gate.set()
        _wait_for(lambda: ("apply", "slow") in master.events,
                  what="backup result applied")
        # resolution releases the parked owner — "next" may go to the
        # (still wedged) owner or to the idle backup; release the
        # wedge so it applies either way
        wedge.set()
        _wait_for(lambda: ("apply", "next") in master.events,
                  what="fresh work flows again after resolution")
        assert master.applied.count(("slow", ca.sid)) == 1
        _wait_for(lambda: server.duplicates_dropped == 1,
                  what="owner's late slow result dropped as duplicate")
    finally:
        wedge.set()
        if master.apply_gate is not None:
            master.apply_gate.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


def test_speculation_off_switch_inf_factor():
    """``--speculation-factor inf`` is the off-switch: nothing ever
    speculates, and the job stamps — which stay, the exactly-once
    duplicate/stale fences key on them — stop caching payloads, so
    the master does not retain a copy of every in-flight job."""
    wedge = threading.Event()
    master = _StubMaster(["seed", "slow"])
    server, _ = _stub_server(master,
                             speculation_factor=float("inf"),
                             min_speculation_s=0.0)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _StubSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a)
    ta = ca.start_background()
    tb = None
    try:
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: master.outstanding.get(ca.sid) == ["slow"],
                  what="slow job out")
        # the stamp lands on the event loop AFTER the executor-side
        # reservation the line above observes — wait for it
        _wait_for(lambda: server._inflight, what="job stamp")
        assert all(job.data is None
                   for job in server._inflight.values()), \
            "no payloads retained with speculation off"
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        time.sleep(1.5)  # several idle watchdog ticks
        assert server.speculated == 0, "inf factor never speculates"
        wedge.set()
        _wait_for(lambda: ("apply", "slow") in master.events,
                  what="owner's own result applies")
        assert master.applied.count(("slow", ca.sid)) == 1
    finally:
        wedge.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


class _PoisonSlave(_StubSlave):
    """Returns a structurally-valid but NaN update for every job —
    the finiteness quarantine must catch it before apply."""

    def do_job(self, data, update, callback):
        callback(numpy.array([float("nan")]))


def test_poisoned_backup_with_dropped_owner_not_reinstated(monkeypatch):
    """A poisoned speculative backup normally REINSTATES the job stamp
    (the owner's healthy copy is still running) — but NOT when the
    owner itself was dropped while the poisoned apply was in flight:
    its reservation was already requeued by the deferred drop, so
    reinstating would leave a phantom in-flight job with a departed
    owner, racing the legitimately requeued minibatch."""
    from veles_tpu import health
    wedge = threading.Event()
    poison_gate = threading.Event()
    real_all_finite = health.all_finite

    def gated_all_finite(obj):
        ok = real_all_finite(obj)
        if not ok:
            # hold the poisoned validation open so the owner's drop
            # deterministically lands inside the apply window.  The
            # window is generous on purpose: it starts ticking the
            # moment the backup's NaN update arrives, while the test
            # thread is still polling for the speculation/apply flags
            # — under full-suite load a short timeout expired mid-
            # sequence and the quarantine beat the deferred drop (the
            # PR 13 flake)
            assert poison_gate.wait(120), "poison gate never opened"
        return ok

    monkeypatch.setattr(health, "all_finite", gated_all_finite)
    master = _StubMaster(["seed", "slow"])
    server, _ = _stub_server(master, speculation_factor=1.0,
                             min_speculation_s=0.2)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _PoisonSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a)
    ta = ca.start_background()
    tb = None
    try:
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: master.outstanding.get(ca.sid) == ["slow"],
                  what="slow job out")
        a_sid = ca.sid
        a_conn = server.slaves[a_sid]
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        _wait_for(lambda: server.speculated == 1, timeout=30,
                  what="speculative dispatch")
        # the poisoned validation is now (about to be) wedged on the
        # executor under the OWNER's reservation; drop the owner
        _wait_for(lambda: server._applying.get(a_sid), timeout=30,
                  what="poisoned apply in flight")
        server._loop.call_soon_threadsafe(server._drop, a_conn,
                                          "owner-timeout")
        # wait for the COUNTER, not the dropped flag: _drop sets
        # conn.dropped several statements (including a log call)
        # before it bumps drops_deferred, all on the loop thread —
        # under full-suite load the test thread can observe the flag
        # and read the counter inside that window.  The deferral
        # itself is guaranteed (the apply is wedged on poison_gate),
        # so waiting loses no strictness: an immediate drop would
        # never bump the counter and still fails here.
        _wait_for(lambda: a_conn.dropped, timeout=30,
                  what="owner drop flag")
        _wait_for(lambda: server.drops_deferred == 1, timeout=30,
                  what="deferred-drop counter")
        assert server.drops_deferred == 1
        poison_gate.set()
        _wait_for(lambda: a_sid in master.drops, timeout=30,
                  what="deferred owner drop")
        _wait_for(lambda: server.quarantined == 1, timeout=30,
                  what="poisoned sender quarantined")
        assert server._inflight == {}, \
            "no phantom stamp for the departed owner"
        assert list(master.pending) == ["slow"], \
            "the owner's work requeued exactly once"
        assert master.applied == [("seed", a_sid)], \
            "the poisoned update never applied"
    finally:
        wedge.set()
        poison_gate.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


def test_failed_apply_of_speculated_copy_does_not_orphan_job():
    """Exactly-once in the applied-ZERO-times direction: when the
    first-arriving copy of a speculated job dies in a transient
    master-side apply exception, the stamp must be reinstated so a
    surviving copy's good result still applies — not dropped as a
    duplicate, which would leave the owner's reservation never
    retired and the job silently lost."""
    wedge = threading.Event()
    master = _StubMaster(["seed", "slow"])
    armed = {"fail": True}
    real_apply = master.apply_data_from_slave

    def flaky_apply(update, slave):
        if update[1] == "slow" and armed["fail"]:
            armed["fail"] = False
            raise RuntimeError("transient apply failure")
        return real_apply(update, slave)

    master.apply_data_from_slave = flaky_apply
    server, _ = _stub_server(master, speculation_factor=1.0,
                             min_speculation_s=0.2)
    wf_a = _StubSlave(slow_on=("slow",), gate=wedge)
    wf_b = _StubSlave()
    ca = Client("127.0.0.1:%d" % server.port, wf_a)
    ta = ca.start_background()
    tb = None
    try:
        _wait_for(lambda: len(master.applied) == 1, what="seed job")
        _wait_for(lambda: master.outstanding.get(ca.sid) == ["slow"],
                  what="slow job out")
        cb = Client("127.0.0.1:%d" % server.port, wf_b)
        tb = cb.start_background()
        # >=: the failed copy's job re-speculates within milliseconds,
        # so the counter can pass 1 between two polls
        _wait_for(lambda: server.speculated >= 1, timeout=15,
                  what="speculative dispatch")
        # the backup's result arrives first and its apply RAISES; a
        # surviving copy (the owner's, or a re-speculated backup) must
        # then land the job exactly once
        _wait_for(lambda: not armed["fail"], timeout=15,
                  what="transient apply failure")
        wedge.set()
        _wait_for(lambda: ("apply", "slow") in master.events,
                  timeout=15, what="surviving copy applies")
        assert master.applied.count(("slow", ca.sid)) == 1, \
            "the job applies exactly once, under the owner"
        assert server.updates_applied == 2, "seed + slow"
    finally:
        wedge.set()
        server.stop()
        server._done.wait(10)
        ta.join(10)
        if tb is not None:
            tb.join(10)


# -- seeded preempt/rejoin soak smoke (tier-1) ----------------------------


@pytest.mark.chaos
def test_soak_smoke_three_preempt_rejoin_cycles_bit_identical(
        cpu_device):
    """The 60 s smoke variant of the preemption soak
    (scripts/elastic_soak.py runs the hour-scale SIGKILL version under
    ``slow``): three seeded die/rejoin cycles while training — every
    death requeues, every rejoin reshards at a bumped membership
    epoch, and the final master weights are bit-identical to the
    fault-free run."""
    master_ref = _build("master", "elastic_soak_m", cpu_device,
                        max_epochs=4)
    slave_ref = _build("slave", "elastic_soak_s", cpu_device,
                       max_epochs=4)
    server_ref, _ = _start_server(master_ref)
    client_ref = Client("127.0.0.1:%d" % server_ref.port, slave_ref)
    client_ref.run()
    assert server_ref._done.wait(60)
    ref_weights = _weights(master_ref)
    ref_metrics = list(master_ref.decision.epoch_metrics)

    master = _build("master", "elastic_soak_m", cpu_device,
                    max_epochs=4)
    slave = _build("slave", "elastic_soak_s", cpu_device, max_epochs=4)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    plan = chaos.install(
        FaultPlan(seed=11)
        .add("client.job", "die", nth=2)
        .add("client.job", "die", nth=6)
        .add("client.job", "die", nth=11))
    try:
        client.run()
    finally:
        chaos.uninstall()
    # wide deterministic window: the run ends by event; under full-
    # suite load the fault-free 15 s bound tripped (PR 12's reshard-
    # race flake) while solo runs finish in ~3 s
    assert server._done.wait(90)

    assert plan.fired("client.job") == 3, "three seeded preemptions"
    assert client.sessions_established == 4, "three rejoins"
    assert bool(master.decision.complete)
    # 4 joins + 3 mid-run leaves = 7 membership changes, 7 reshards
    assert server.reshards >= 7
    assert server.fleet.membership_epoch >= 7
    assert master.loader.total_failed >= 3
    assert list(master.decision.epoch_metrics) == ref_metrics
    for got, want in zip(_weights(master), ref_weights):
        numpy.testing.assert_array_equal(got, want)
    snap = fleet_snapshot()
    assert snap["membership_epoch"] >= 7


@pytest.mark.chaos
def test_kill_during_reshard_never_double_applies(cpu_device):
    """Acceptance: a slave connection severed DURING a reshard push
    (the rejoin reshard after a mid-run death).  Its requeued work
    replays after the next rejoin; no update is double-applied —
    final weights bit-identical to the fault-free run."""
    master_ref = _build("master", "elastic_krr_m", cpu_device)
    slave_ref = _build("slave", "elastic_krr_s", cpu_device)
    server_ref, _ = _start_server(master_ref)
    client_ref = Client("127.0.0.1:%d" % server_ref.port, slave_ref)
    client_ref.run()
    assert server_ref._done.wait(60)
    ref_weights = _weights(master_ref)
    ref_applied = server_ref.updates_applied

    master = _build("master", "elastic_krr_m", cpu_device)
    slave = _build("slave", "elastic_krr_s", cpu_device)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    # die on job 3 -> rejoin -> the JOIN reshard push (2nd hit of the
    # per-slave push point) kills the conn mid-push -> rejoin again
    plan = chaos.install(
        FaultPlan(seed=7)
        .add("client.job", "die", nth=3)
        .add("server.reshard", "kill", nth=2))
    try:
        client.run()
    finally:
        chaos.uninstall()
    # wide deterministic window (see the soak smoke above): the rejoin
    # backoff after the mid-reshard kill stretches under suite load
    assert server._done.wait(90)

    assert plan.fired("server.reshard") == 1, \
        "the kill-during-reshard must actually fire"
    assert client.sessions_established >= 3
    assert bool(master.decision.complete)
    assert server.updates_applied == ref_applied, \
        "same number of applies as fault-free: nothing doubled, " \
        "nothing lost"
    for got, want in zip(_weights(master), ref_weights):
        numpy.testing.assert_array_equal(got, want)


# -- the hour-scale SIGKILL soak (slow tier) ------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_preemption_soak_sigkill_subprocess_receipt(tmp_path):
    """Acceptance: scripts/elastic_soak.py SIGKILLs real slave
    subprocesses on a seeded aK schedule (chaos ``slave.preempt``),
    respawns them after seeded ``slave.rejoin_after`` delays, and the
    soaked master converges bit-identically to the fault-free run
    with bounded throughput loss; the kill-during-reshard case
    double-applies nothing.  The committed ELASTIC.json is this
    driver at full size."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "ELASTIC.json"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "elastic_soak.py"),
         "--out", str(out), "--seed", "42",
         "--preempts", "5", "--max-epochs", "8"],
        cwd=repo, timeout=1800, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    receipt = json.loads(out.read_text())
    assert receipt["bit_identical"] is True
    assert receipt["events_total"] >= 10
    assert receipt["soak"]["preempts"] >= 5
    assert receipt["soak"]["reshards"] >= 10
    assert receipt["throughput"]["within_bound"] is True
    assert receipt["kill_during_reshard"]["double_applies"] == 0
    assert receipt["kill_during_reshard"]["bit_identical"] is True


# -- reshard plumbing through workflow + loader ---------------------------


def test_workflow_forwards_reshard_to_loader(cpu_device):
    sw = _build("slave", "elastic_plumb", cpu_device)
    info = {"epoch": 5, "share": 128, "fleet": 3, "remaining": 320}
    sw.apply_reshard(info)
    assert sw.fleet_info_ == info
    assert sw.loader.fleet_share == 128
    assert sw.loader.fleet_epoch == 5


def test_loader_unserved_remainder_tracks_epoch_progress(cpu_device):
    sw = _build("standalone", "elastic_remainder", cpu_device,
                max_epochs=1)
    loader = sw.loader
    total = loader.effective_total_samples
    # before anything is served: the whole class window is unserved
    assert loader.unserved_remainder() == total
    assert sw.unserved_remainder() == total
    sw.run()
    # after a run the loader sits mid-epoch (the completion cycle
    # serves into the next epoch before end_point): still a sane,
    # positive remainder within the class window
    assert 0 < loader.unserved_remainder() <= total
    # mid-epoch arithmetic (no serving needed: pure accounting)
    loader.samples_served = total + 70
    assert loader.unserved_remainder() == total - 70


# -- reshard-failure rejoin + mesh-epoch stamping -------------------------


class _HookFailSlave(_StubSlave):
    """apply_reshard raises on the FIRST push only — the stale-
    elasticity-state shape the sever-and-rejoin contract covers."""

    def __init__(self, *args, **kwargs):
        super(_HookFailSlave, self).__init__(*args, **kwargs)
        self.failures_left = 1

    def apply_reshard(self, info):
        if self.failures_left:
            self.failures_left -= 1
            raise RuntimeError("loader cannot adopt the new window")
        super(_HookFailSlave, self).apply_reshard(info)


def test_reshard_hook_failure_severs_and_rejoins_at_fresh_epoch():
    """Regression for the log-and-continue swallow: a failed
    ``apply_reshard`` hook leaves the slave on stale elasticity state,
    so the client must sever the session and rejoin at a fresh
    membership epoch — counted in ``elastic.reshard_failures``."""
    before = _registry.counter("elastic.reshard_failures").value
    master = _StubMaster([], remainder=100)
    server, _ = _stub_server(master)
    wf = _HookFailSlave()
    client = Client("127.0.0.1:%d" % server.port, wf)
    thread = client.start_background()
    try:
        # first join push fails the hook -> sever -> reconnect; the
        # rejoin bumps the epoch past the leave and the replayed push
        # lands on a hook that now works
        _wait_for(lambda: wf.reshards, what="post-rejoin reshard push")
        _wait_for(lambda: client.sessions_established >= 2,
                  what="fresh handshake after the sever")
        assert wf.failures_left == 0
        assert _registry.counter("elastic.reshard_failures").value \
            == before + 1
        # join(1) + leave(2) + rejoin(3): the recorded epoch is FRESH
        assert wf.reshards[-1]["epoch"] >= 3
        assert client.member_epoch >= 3
    finally:
        server.stop()
        server._done.wait(10)
        thread.join(10)


def test_reshard_frame_carries_mesh_epoch():
    """A master training through a MeshManager stamps its device-mesh
    epoch into reshard frames so slaves can correlate membership churn
    with the train-state reshard it produced."""

    class _MeshStub(object):
        mesh_epoch = 7

    master = _StubMaster([], remainder=100)
    server, _ = _stub_server(master)
    server.mesh_manager = _MeshStub()
    wf = _StubSlave()
    client = Client("127.0.0.1:%d" % server.port, wf)
    thread = client.start_background()
    try:
        _wait_for(lambda: wf.reshards, what="join reshard push")
        assert wf.reshards[-1]["mesh_epoch"] == 7
        assert client.mesh_epoch == 7
    finally:
        server.stop()
        server._done.wait(10)
        thread.join(10)


# -- solver-state delta shipping (momentum through respawn) ---------------


def test_gd_units_ship_solver_state_deltas(cpu_device):
    """The PR-9 caveat closed: gd units ship canonical solver
    accumulators with each job and merge the slave's accumulator
    deltas additively — the same master-slave contract params ride —
    so a respawned slave replays momentum runs bit-faithfully
    (receipted at soak scale in ELASTIC.json)."""
    from tests.test_chaos import _build as _build_chaos
    master = _build_chaos("master", "elastic_accum_m", cpu_device)
    slave = _build_chaos("slave", "elastic_accum_s", cpu_device)
    gd_m, gd_s = master.gds[0], slave.gds[0]
    gd_m.accum_weights.map_invalidate()
    gd_m.accum_weights.mem[:] = 0.25
    payload = gd_m.generate_data_for_slave()
    assert numpy.all(payload["accum_weights"] == 0.25)
    assert "accum_bias" in payload

    gd_s.apply_data_from_master(payload)
    gd_s.accum_weights.map_read()
    assert numpy.all(gd_s.accum_weights.mem == 0.25)
    # the slave trains: its accums move; the delta is what ships back
    gd_s.accum_weights.map_write()
    gd_s.accum_weights.mem += 1.0
    delta = gd_s.generate_data_for_master()
    assert numpy.allclose(delta["accum_weights"], 1.0)
    assert numpy.allclose(delta["accum_bias"], 0.0)

    gd_m.apply_data_from_slave(delta)
    gd_m.accum_weights.map_read()
    assert numpy.allclose(gd_m.accum_weights.mem, 1.25)
