"""Native inference runtime tests (reference test model:
libVeles/tests/{workflow,unit_factory,memory_optimizer,
numpy_array_loader}.cc): package export -> C++ load -> run, compared
against the JAX forward path."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator
from tests.test_models import BlobsLoader
from tests.test_conv import TinyImageLoader


@pytest.fixture(scope="module")
def native():
    from veles_tpu import native as native_mod
    try:
        native_mod.build_native()
    except Exception as exc:
        pytest.skip("native build unavailable: %s" % exc)
    return native_mod


def _train_mlp(device, epochs=3):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("nat", seed=5)),
        decision_config=dict(max_epochs=epochs),
    )
    sw.initialize(device=device)
    sw.run()
    return sw


def _jax_forward(sw, x):
    from veles_tpu.compiler import build_forward, extract_state, \
        workflow_plan
    plans = workflow_plan(sw)
    state = extract_state(sw)
    params = [{"weights": s["weights"], "bias": s["bias"]}
              for s in state]
    return numpy.asarray(build_forward(plans)(params, x))


def test_export_and_native_mlp_inference(tmp_path, native, cpu_device):
    sw = _train_mlp(cpu_device)
    pkg = str(tmp_path / "mlp.veles.tar")
    sw.package_export(pkg)

    nwf = native.NativeWorkflow(pkg)
    assert nwf.unit_count == 2
    assert nwf.input_size == 16
    assert nwf.output_size == 4

    rng = numpy.random.RandomState(0)
    x = rng.rand(32, 16).astype(numpy.float32)
    got = nwf.run(x)
    want = _jax_forward(sw, x)
    numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    numpy.testing.assert_allclose(got.sum(axis=1), 1.0, atol=1e-5)


def test_native_fp16_package(tmp_path, native, cpu_device):
    """fp16 arrays are widened on load (numpy_array_loader parity)."""
    sw = _train_mlp(cpu_device)
    pkg = str(tmp_path / "mlp16.veles.tar")
    sw.package_export(pkg, precision="float16")
    nwf = native.NativeWorkflow(pkg)
    rng = numpy.random.RandomState(1)
    x = rng.rand(8, 16).astype(numpy.float32)
    got = nwf.run(x)
    want = _jax_forward(sw, x)
    numpy.testing.assert_allclose(got, want, rtol=0.05, atol=0.02)


def test_native_conv_inference(tmp_path, native, cpu_device):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "conv_tanh", "n_kernels": 6, "kx": 3, "ky": 3,
             "padding": 1, "learning_rate": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 3,
             "learning_rate": 0.05},
        ],
        loader_factory=lambda w: TinyImageLoader(
            w, minibatch_size=48, prng=RandomGenerator("natc", seed=6)),
        decision_config=dict(max_epochs=2),
    )
    sw.initialize(device=cpu_device)
    sw.run()

    pkg = str(tmp_path / "conv.veles.tar")
    sw.package_export(pkg)
    nwf = native.NativeWorkflow(pkg)
    assert nwf.unit_count == 3

    rng = numpy.random.RandomState(2)
    x = rng.rand(8, 8, 8, 1).astype(numpy.float32)
    got = nwf.run(x)
    want = _jax_forward(sw, x).reshape(8, -1)
    numpy.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_arena_reuses_memory(tmp_path, native, cpu_device):
    """Strip packing must reuse bytes across non-adjacent stages: the
    arena must be smaller than the sum of all stage buffers for a deep
    chain (reference memory_optimizer.cc objective)."""
    wf = DummyWorkflow()
    layers = []
    for _ in range(6):
        layers.append({"type": "all2all_tanh", "output_sample_shape": 64,
                       "learning_rate": 0.05})
    layers.append({"type": "softmax", "output_sample_shape": 4,
                   "learning_rate": 0.05})
    sw = StandardWorkflow(
        wf.workflow, layers=layers,
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("nata", seed=8)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    pkg = str(tmp_path / "deep.veles.tar")
    sw.package_export(pkg)
    nwf = native.NativeWorkflow(pkg)
    batch = 64
    total_naive = sum(
        batch * 64 * 4 for _ in range(6)) + batch * 4 * 4
    arena = nwf.arena_size(batch)
    assert arena < total_naive, (arena, total_naive)
    # sanity: deep chain still computes
    out = nwf.run(numpy.random.RandomState(3).rand(4, 16))
    assert numpy.allclose(out.sum(axis=1), 1.0, atol=1e-4)


def test_native_rejects_malformed_npy(tmp_path, native, cpu_device):
    """A package whose npy header length overruns the file must fail
    cleanly (no OOB read; advisor finding, round 1)."""
    import struct
    import tarfile

    sw = _train_mlp(cpu_device, epochs=1)
    pkg = str(tmp_path / "ok.tar")
    sw.package_export(pkg)

    # corrupt every npy: claim a header length far past EOF
    evil = str(tmp_path / "evil.tar")
    with tarfile.open(pkg) as tin, tarfile.open(evil, "w") as tout:
        for member in tin.getmembers():
            data = tin.extractfile(member).read()
            if member.name.endswith(".npy"):
                data = (data[:8] + struct.pack("<H", 0xFFFF) +
                        data[10:])
            member.size = len(data)
            import io
            tout.addfile(member, io.BytesIO(data))

    with pytest.raises(RuntimeError):
        native.NativeWorkflow(evil)


def test_native_branching_dag_inference(tmp_path, native, cpu_device):
    """General DAG (reference workflow_loader.cc:73-120): two parallel
    branches from the input joined by InputJoiner, then a softmax head.
    Native inference must match the Python forward."""
    from veles_tpu.models.all2all import (
        All2AllRELU, All2AllSoftmax, All2AllTanh)
    from veles_tpu.package import export_workflow
    from veles_tpu.service_units import InputJoiner

    sw = _train_mlp(cpu_device, epochs=1)  # provides loader + checksum
    loader = sw.loader

    branch_a = All2AllTanh(sw, output_sample_shape=8,
                           learning_rate=0.1)
    branch_a.link_attrs(loader, ("input", "minibatch_data"))
    branch_a.initialize(device=cpu_device)

    branch_b = All2AllRELU(sw, output_sample_shape=12,
                           learning_rate=0.1)
    branch_b.link_attrs(loader, ("input", "minibatch_data"))
    branch_b.initialize(device=cpu_device)

    joiner = InputJoiner(sw)
    joiner.link_inputs((branch_a, "output"), (branch_b, "output"))
    joiner.initialize(device=cpu_device)

    head = All2AllSoftmax(sw, output_sample_shape=4, learning_rate=0.1)
    head.link_attrs(joiner, ("input", "output"))

    # run the python forward once to size + initialize the head
    branch_a.run()
    branch_b.run()
    joiner.run()
    head.initialize(device=cpu_device)
    head.run()

    pkg = str(tmp_path / "dag.tar")
    export_workflow(sw, pkg,
                    units=[branch_a, branch_b, joiner, head])

    loader.minibatch_data.map_read()
    x = numpy.ascontiguousarray(
        loader.minibatch_data.mem, numpy.float32)
    head.output.map_read()
    expected = numpy.asarray(head.output.mem, numpy.float32)

    wf = native.NativeWorkflow(pkg)
    assert wf.unit_count == 4
    got = wf.run(x).reshape(expected.shape)
    numpy.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_native_dag_arena_overlaps_disjoint_lifetimes(tmp_path, native,
                                                      cpu_device):
    """The arena planner packs buffers whose DAG lifetimes are disjoint
    into overlapping bytes: total arena < sum of buffer sizes for a
    deep chain."""
    sw = _train_mlp(cpu_device, epochs=1)
    pkg = str(tmp_path / "chain.tar")
    sw.package_export(pkg)
    wf = native.NativeWorkflow(pkg)
    batch = 16
    arena = wf.arena_size(batch)
    # chain of 2 units: 32-feature hidden + 4-class head; with real
    # intervals the head output (written to out) costs nothing and the
    # hidden buffer alone bounds the arena
    assert arena <= 32 * batch * 4 + 4096


def test_native_wavefront_wide_graph_batch1(tmp_path, native, cpu_device):
    """Wavefront scheduling (engine.h RunTasks): four independent
    branches form one dependency level and run concurrently even at
    batch=1, where row-sharding alone gives no parallelism.  Repeated
    runs must be bit-identical (races would show as instability)."""
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.package import export_workflow
    from veles_tpu.service_units import InputJoiner

    sw = _train_mlp(cpu_device, epochs=1)
    loader = sw.loader

    branches = []
    for k in range(4):
        b = All2AllTanh(sw, output_sample_shape=6 + k,
                        learning_rate=0.1)
        b.link_attrs(loader, ("input", "minibatch_data"))
        b.initialize(device=cpu_device)
        b.run()
        branches.append(b)

    joiner = InputJoiner(sw)
    joiner.link_inputs(*[(b, "output") for b in branches])
    joiner.initialize(device=cpu_device)
    joiner.run()

    head = All2AllSoftmax(sw, output_sample_shape=4, learning_rate=0.1)
    head.link_attrs(joiner, ("input", "output"))
    head.initialize(device=cpu_device)
    head.run()

    pkg = str(tmp_path / "wide.tar")
    export_workflow(sw, pkg, units=branches + [joiner, head])

    loader.minibatch_data.map_read()
    x1 = numpy.ascontiguousarray(
        loader.minibatch_data.mem[:1], numpy.float32)
    head.output.map_read()
    expected = numpy.asarray(head.output.mem[:1], numpy.float32)

    wf = native.NativeWorkflow(pkg)
    assert wf.unit_count == 6
    first = wf.run(x1).reshape(expected.shape)
    numpy.testing.assert_allclose(first, expected, rtol=1e-5, atol=1e-6)
    for _ in range(20):
        again = wf.run(x1).reshape(expected.shape)
        numpy.testing.assert_array_equal(again, first)


def test_native_arena_safe_under_wavefront_order(tmp_path, native,
                                                 cpu_device):
    """Adversarial package order A, C, B, join: topo order interleaves
    the wavefronts (A and B share level 0 but sit at topo positions 0
    and 2), so a topo-index lifetime would let the planner alias B's
    buffer over A's while both run concurrently.  Lifetimes are in
    LEVEL steps precisely so this stays correct."""
    from veles_tpu.models.all2all import All2AllTanh
    from veles_tpu.package import export_workflow
    from veles_tpu.service_units import InputJoiner

    sw = _train_mlp(cpu_device, epochs=1)
    loader = sw.loader

    def branch(width):
        b = All2AllTanh(sw, output_sample_shape=width, learning_rate=0.1)
        b.link_attrs(loader, ("input", "minibatch_data"))
        b.initialize(device=cpu_device)
        b.run()
        return b

    a = branch(8)
    b = branch(8)  # same size as A: aliasing would be attractive
    c = All2AllTanh(sw, output_sample_shape=8, learning_rate=0.1)
    c.link_attrs(a, ("input", "output"))
    c.initialize(device=cpu_device)
    c.run()
    join = InputJoiner(sw)
    join.link_inputs((c, "output"), (b, "output"))
    join.initialize(device=cpu_device)
    join.run()

    pkg = str(tmp_path / "adversarial.tar")
    export_workflow(sw, pkg, units=[a, c, b, join])

    loader.minibatch_data.map_read()
    x = numpy.ascontiguousarray(
        loader.minibatch_data.mem, numpy.float32)
    join.output.map_read()
    expected = numpy.asarray(join.output.mem, numpy.float32)

    wf = native.NativeWorkflow(pkg)
    for _ in range(10):  # repeated: an aliasing race would flake
        got = wf.run(x).reshape(expected.shape)
        numpy.testing.assert_allclose(got, expected,
                                      rtol=1e-5, atol=1e-6)


def test_native_empty_batch(tmp_path, native, cpu_device):
    """batch=0 returns an empty result instead of crashing."""
    sw = _train_mlp(cpu_device, epochs=1)
    pkg = str(tmp_path / "empty.tar")
    sw.package_export(pkg)
    wf = native.NativeWorkflow(pkg)
    out = wf.run(numpy.empty((0, wf.input_size), numpy.float32))
    assert out.size == 0
