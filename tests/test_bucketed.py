"""SPMD data plane: bucketed gradient all-reduce overlapped with the
backward (veles_tpu/parallel/bucketed.py, compiler SPMD path).

Three tiers:

- plan/partition unit tests (pure host logic, every boundary case);
- bit-equality on the virtual CPU mesh: bucketed+overlapped ==
  flat single-tensor all-reduce for bucket > pytree, bucket of one
  leaf, and a leaf straddling a bucket edge;
- the tier-1-safe ``dist`` smoke: a 2-device compile-only
  collective-bytes audit (SCALING.json methodology) proving the
  bucketed path can never silently regress to the flat all-reduce,
  plus the control-plane demotion (inline update validation) and the
  comm observability receipts.
"""

import math

import numpy
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from veles_tpu.compiler import LayerPlan, build_train_step
from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.parallel import make_mesh
from veles_tpu.parallel.analysis import parse_collective_ops
from veles_tpu.parallel.bucketed import (
    DEFAULT_BUCKET_MB, bucketed_all_reduce, comm_receipt, overlap_model,
    plan_buckets, publish_comm_receipt)
from veles_tpu.parallel.mesh import shard_map
from veles_tpu.parallel.ring import ring_all_reduce


def _sds(*shapes):
    return [jax.ShapeDtypeStruct(s, numpy.float32) for s in shapes]


def _plan_coverage(plan, leaves):
    """Every element of every leaf covered exactly once, in order."""
    for i, leaf in enumerate(leaves):
        size = int(math.prod(leaf.shape)) if leaf.shape else 1
        spans = sorted((s, e) for b in plan.buckets
                       for (j, s, e) in b.slices if j == i)
        pos = 0
        for s, e in spans:
            assert s == pos and e > s
            pos = e
        assert pos == size, "leaf %d covered %d/%d" % (i, pos, size)


# -- bucket planning (pure host logic) ------------------------------------

class TestPlanBuckets(object):

    def test_bucket_larger_than_pytree_is_flat(self):
        leaves = _sds((100, 10), (10,), (10, 4), (4,))
        for target in (float("inf"), 10 * 2 ** 20):
            plan = plan_buckets(leaves, target)
            assert len(plan.buckets) == 1
            _plan_coverage(plan, leaves)
            assert plan.total_bytes == 4 * (1000 + 10 + 40 + 4)

    def test_bucket_of_exactly_one_leaf(self):
        # target == every leaf's byte size -> one bucket per leaf
        leaves = _sds((64,), (64,), (64,))
        plan = plan_buckets(leaves, 64 * 4)
        assert len(plan.buckets) == 3
        assert all(len(b.slices) == 1 and b.elems == 64
                   for b in plan.buckets)
        _plan_coverage(plan, leaves)

    def test_leaf_straddles_bucket_edge(self):
        # 100-element leaf against a 64-element bucket: the leaf must
        # split at the exact element boundary, spanning two buckets
        leaves = _sds((100,))
        plan = plan_buckets(leaves, 64 * 4)
        assert len(plan.buckets) == 2
        assert plan.buckets[0].slices == [(0, 0, 64)]
        assert plan.buckets[1].slices == [(0, 64, 100)]
        _plan_coverage(plan, leaves)

    def test_reverse_production_order(self):
        # bucket 0 must hold the LAST leaf's gradients — the first the
        # backward pass produces — so its all-reduce can overlap the
        # rest of the backward
        leaves = _sds((8,), (8,), (8,))
        plan = plan_buckets(leaves, 8 * 4)
        assert [b.slices[0][0] for b in plan.buckets] == [2, 1, 0]

    def test_mixed_spans_fill_to_target(self):
        leaves = _sds((10,), (30,), (10,))
        plan = plan_buckets(leaves, 25 * 4)
        _plan_coverage(plan, leaves)
        assert sum(b.elems for b in plan.buckets) == 50
        # no bucket exceeds the target
        assert all(b.nbytes <= 25 * 4 for b in plan.buckets)

    def test_default_target(self):
        leaves = _sds((1000,))
        plan = plan_buckets(leaves, None)
        assert len(plan.buckets) == 1  # 4 KB << 25 MB
        assert plan.bucket_bytes == DEFAULT_BUCKET_MB * 2 ** 20

    def test_invalid_target_rejected(self):
        with pytest.raises(ValueError):
            plan_buckets(_sds((8,)), 0)


# -- bit-equality on the virtual mesh -------------------------------------

def _mlp_state(rng, dims):
    out = []
    for fi, fo in zip(dims[:-1], dims[1:]):
        out.append({
            "weights": rng.randn(fi, fo).astype(numpy.float32) * 0.1,
            "bias": numpy.zeros(fo, numpy.float32),
            "accum_weights": numpy.zeros((fi, fo), numpy.float32),
            "accum_bias": numpy.zeros(fo, numpy.float32),
            "accum2_weights": None, "accum2_bias": None})
    return out


def _plans(lr=0.1):
    hyper = {"learning_rate": lr, "gradient_moment": 0.9}
    return [LayerPlan(All2AllTanh, hyper=hyper),
            LayerPlan(All2AllSoftmax, hyper=hyper)]


def _batch(rng, n=64, fan_in=16, classes=4):
    labels = (numpy.arange(n) % classes).astype(numpy.int32)
    centers = rng.randn(classes, fan_in).astype(numpy.float32) * 2
    x = (centers[labels] +
         rng.randn(n, fan_in).astype(numpy.float32) * 0.2)
    return x, labels


def _run_steps(step, state, x, labels, n_steps=3):
    for _ in range(n_steps):
        state, metrics = step(state, x, labels, numpy.float32(len(x)))
    return state, metrics


def _assert_bit_equal(sa, sb):
    la = jax.tree_util.tree_leaves(sa)
    lb = jax.tree_util.tree_leaves(sb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert numpy.array_equal(numpy.asarray(a), numpy.asarray(b)), \
            "bucketed result is not bit-identical to the flat all-reduce"


# grad pytree here: 16x32 w (2048 B), 32 b (128 B), 32x4 w (512 B),
# 4 b (16 B) -> 2704 bytes total.  The parametrized targets hit every
# boundary case from the issue checklist.
_BUCKET_CASES = {
    "bucket_gt_pytree": 1.0,                    # 1 MB >> 2.7 KB: flat
    "bucket_of_one_leaf": 2048 / 2.0 ** 20,     # largest leaf alone
    "leaf_straddles_edge": 1000 / 2.0 ** 20,    # splits both weights
    "one_bucket_per_element_ish": 64 / 2.0 ** 20,
}


@pytest.mark.dist
@pytest.mark.parametrize("case", sorted(_BUCKET_CASES))
def test_bucketed_bit_identical_to_flat(case):
    """Acceptance: the bucketed+overlapped all-reduce produces the
    same update as the flat single-tensor all-reduce, bit for bit,
    for every bucket-size boundary case, over several chained steps."""
    rng = numpy.random.RandomState(5)
    state = _mlp_state(rng, (16, 32, 4))
    x, labels = _batch(rng)
    mesh = make_mesh({"data": 8})

    flat = build_train_step(_plans(), mesh=mesh,
                            grad_bucket_mb=float("inf"), donate=False)
    buck = build_train_step(_plans(), mesh=mesh,
                            grad_bucket_mb=_BUCKET_CASES[case],
                            donate=False)
    sf, mf = _run_steps(flat, [dict(s) for s in state], x, labels)
    sb, mb = _run_steps(buck, [dict(s) for s in state], x, labels)
    _assert_bit_equal(sf, sb)
    assert float(mf["loss"]) == float(mb["loss"])
    assert int(mf["n_err"]) == int(mb["n_err"])


@pytest.mark.dist
def test_spmd_step_matches_single_device_and_pjit():
    """The SPMD shard_map plane agrees with the single-device step and
    the pjit annotation path (same math, different collectives)."""
    from veles_tpu.parallel import (auto_mesh, batch_sharding,
                                    mlp_state_shardings)
    rng = numpy.random.RandomState(7)
    state = _mlp_state(rng, (16, 32, 4))
    x, labels = _batch(rng)

    ref_step = build_train_step(_plans(), donate=False)
    sr, mr = _run_steps(ref_step, [dict(s) for s in state], x, labels)

    mesh = auto_mesh()
    spmd = build_train_step(_plans(), mesh=mesh, grad_bucket_mb=0.001,
                            donate=False)
    sb, mb = _run_steps(spmd, [dict(s) for s in state], x, labels)

    pjit_step = build_train_step(
        _plans(), mesh=mesh,
        state_shardings=mlp_state_shardings(mesh, state),
        batch_sharding=batch_sharding(mesh), donate=False)
    sp, mp = _run_steps(pjit_step, [dict(s) for s in state], x, labels)

    for a, b, c in zip(jax.tree_util.tree_leaves(sr),
                       jax.tree_util.tree_leaves(sb),
                       jax.tree_util.tree_leaves(sp)):
        numpy.testing.assert_allclose(numpy.asarray(a), numpy.asarray(b),
                                      rtol=1e-4, atol=1e-6)
        numpy.testing.assert_allclose(numpy.asarray(b), numpy.asarray(c),
                                      rtol=1e-4, atol=1e-6)
    assert abs(float(mr["loss"]) - float(mb["loss"])) < 1e-5
    assert abs(float(mp["loss"]) - float(mb["loss"])) < 1e-5


@pytest.mark.dist
def test_short_minibatch_mse_mask_is_global():
    """A short (padded) minibatch's masked tail lives in the LAST
    shard under SPMD; the mse mask must key on GLOBAL row indices or
    the pad rows of every shard but the first would leak into the
    loss.  Equality vs the single-device step proves it."""
    from veles_tpu.models.all2all import All2AllTanh as Tanh
    plans = [LayerPlan(Tanh, hyper={"learning_rate": 0.1})]
    rng = numpy.random.RandomState(9)
    state = [{"weights": rng.randn(8, 8).astype(numpy.float32) * 0.1,
              "bias": numpy.zeros(8, numpy.float32),
              "accum_weights": numpy.zeros((8, 8), numpy.float32),
              "accum_bias": numpy.zeros(8, numpy.float32),
              "accum2_weights": None, "accum2_bias": None}]
    x = rng.randn(16, 8).astype(numpy.float32)
    t = rng.randn(16, 8).astype(numpy.float32)
    # only 11 of 16 rows are real; rows 11.. are loader padding
    bs = numpy.float32(11)

    ref = build_train_step(plans, loss="mse", donate=False)
    sr, mr = ref([dict(s) for s in state], x, t, bs)

    mesh = make_mesh({"data": 8})
    spmd = build_train_step(plans, loss="mse", mesh=mesh,
                            grad_bucket_mb=0.001, donate=False)
    sb, mb = spmd([dict(s) for s in state], x, t, bs)
    numpy.testing.assert_allclose(
        numpy.asarray(sr[0]["weights"]), numpy.asarray(sb[0]["weights"]),
        rtol=1e-5, atol=1e-7)
    assert abs(float(mr["mse_sum"]) - float(mb["mse_sum"])) < 1e-4


@pytest.mark.dist
def test_ring_all_reduce_matches_sum():
    """The explicit ppermute ring (reduce-scatter + all-gather) sums
    correctly, including lengths not divisible by the ring size."""
    mesh = make_mesh({"data": 8})
    rng = numpy.random.RandomState(2)
    for length in (1000, 1001, 7):  # pad path and tiny vectors
        rows = rng.randn(8, length).astype(numpy.float32)

        fn = shard_map(
            lambda v: ring_all_reduce(v.reshape(-1), "data", 8),
            mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_vma=False)
        got = numpy.asarray(fn(rows))
        numpy.testing.assert_allclose(got, rows.sum(axis=0),
                                      rtol=1e-5, atol=1e-5)


@pytest.mark.dist
def test_ring_impl_step_close_to_psum():
    """impl='ring' changes summation order (ULP-close, not bit-equal);
    the trained step still agrees to float tolerance."""
    rng = numpy.random.RandomState(11)
    state = _mlp_state(rng, (16, 32, 4))
    x, labels = _batch(rng)
    mesh = make_mesh({"data": 8})
    psum_step = build_train_step(_plans(), mesh=mesh,
                                 grad_bucket_mb=0.001, donate=False)
    ring_step = build_train_step(_plans(), mesh=mesh,
                                 grad_bucket_mb=0.001,
                                 grad_allreduce_impl="ring",
                                 donate=False)
    sp, _ = _run_steps(psum_step, [dict(s) for s in state], x, labels)
    sr, _ = _run_steps(ring_step, [dict(s) for s in state], x, labels)
    for a, b in zip(jax.tree_util.tree_leaves(sp),
                    jax.tree_util.tree_leaves(sr)):
        numpy.testing.assert_allclose(numpy.asarray(a), numpy.asarray(b),
                                      rtol=1e-4, atol=1e-6)


# -- bf16 compression + health gating -------------------------------------

@pytest.mark.dist
@pytest.mark.health
def test_bf16_compression_trains_and_skips_poison_bit_exactly():
    rng = numpy.random.RandomState(13)
    state = _mlp_state(rng, (16, 32, 4))
    x, labels = _batch(rng)
    mesh = make_mesh({"data": 8})
    step = build_train_step(_plans(), mesh=mesh, grad_bucket_mb=0.001,
                            grad_compress="bf16", donate=False)
    s1, m1 = step([dict(s) for s in state], x, labels,
                  numpy.float32(64))
    assert bool(m1["finite"])
    assert numpy.isfinite(float(m1["loss"]))
    # compressed grads still descend
    ref = build_train_step(_plans(), donate=False)
    sr, _ = ref([dict(s) for s in state], x, labels, numpy.float32(64))
    numpy.testing.assert_allclose(
        numpy.asarray(s1[0]["weights"]), numpy.asarray(sr[0]["weights"]),
        rtol=2e-2, atol=2e-3)

    # a poisoned step under compression is SKIPPED bit-exactly: psum
    # spreads the NaN to every replica, the guard refuses the update
    s2, m2 = step([dict(s) for s in state], x, labels,
                  numpy.float32(64), None, numpy.float32(numpy.nan))
    assert not bool(m2["finite"]) and int(m2["skipped"]) == 1
    for before, after in zip(jax.tree_util.tree_leaves(state),
                             jax.tree_util.tree_leaves(s2)):
        assert numpy.array_equal(numpy.asarray(before),
                                 numpy.asarray(after))


@pytest.mark.health
def test_trainer_compression_fallback_on_health_sync():
    """FusedTrainer.on_health_sync: fresh skips while bf16 compression
    is on -> drop the compiled step and fall back to f32 (the PR 3
    watchdog gate riding the existing class-end sync)."""
    from veles_tpu.models.fused import FusedTrainer
    from veles_tpu.observe.metrics import registry

    trainer = FusedTrainer.__new__(FusedTrainer)
    trainer.grad_compress = "bf16"
    trainer._compress_skips_seen_ = 0
    trainer._step_fn = object()
    trainer._state = None  # sync() is a no-op without live fused state
    trainer._comm_published_ = True
    trainer.warning = lambda *a, **k: None
    before = registry.counter("comm.compress_fallbacks").value

    trainer.on_health_sync(skips=0, consec=0)   # no skips: no change
    assert trainer.grad_compress == "bf16"
    trainer.on_health_sync(skips=2, consec=1)   # fresh skips: fall back
    assert trainer.grad_compress is None
    assert trainer._step_fn is None
    assert not trainer._comm_published_
    assert registry.counter("comm.compress_fallbacks").value == before + 1
    trainer.on_health_sync(skips=2, consec=0)   # stale count: no-op
    assert trainer._step_fn is None


# -- the tier-1 dist smoke: compile-only collective-bytes audit -----------

@pytest.mark.dist
def test_two_device_spmd_smoke_collective_bytes():
    """Tier-1-safe 2-device virtual-CPU SPMD smoke (SCALING.json
    methodology, compile-only): the bucketed step's optimized HLO must
    carry one all-reduce PER BUCKET, their sizes must match the plan,
    and their sum must equal the flat path's single gradient
    all-reduce — so the overlap path can never silently regress to
    the flat monolith."""
    rng = numpy.random.RandomState(3)
    state = _mlp_state(rng, (16, 32, 4))
    x, labels = _batch(rng, n=16)
    mesh = make_mesh({"data": 2}, jax.devices()[:2])
    args = (state, x, labels, numpy.float32(16))

    grad_bytes = 4 * (16 * 32 + 32 + 32 * 4 + 4)  # 2704
    bucket_mb = 1024 / 2.0 ** 20                  # 1 KB buckets

    grads_like = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
    plan = plan_buckets(jax.tree_util.tree_leaves(grads_like),
                        1024)
    assert len(plan.buckets) >= 3

    def grad_ops(step):
        hlo = step.lower(*args).compile().as_text()
        return [op["bytes"] for op in parse_collective_ops(hlo)
                if op["kind"] == "all-reduce" and op["bytes"] >= 512]

    buck = build_train_step(_plans(), mesh=mesh,
                            grad_bucket_mb=bucket_mb, donate=False)
    flat = build_train_step(_plans(), mesh=mesh,
                            grad_bucket_mb=float("inf"), donate=False)
    bucket_ops = grad_ops(buck)
    flat_ops = grad_ops(flat)

    assert len(flat_ops) == 1 and flat_ops[0] == grad_bytes
    assert len(bucket_ops) == len(plan.buckets), \
        "bucketed step regressed: %d collective(s) for %d buckets" % (
            len(bucket_ops), len(plan.buckets))
    assert sum(bucket_ops) == grad_bytes
    assert sorted(bucket_ops) == sorted(b.nbytes for b in plan.buckets)


# -- overlap model + comm receipts ----------------------------------------

class TestOverlapModel(object):

    def test_no_step_time_credits_nothing(self):
        m = overlap_model(250e6, 10, 8, step_seconds=None)
        assert m["overlap_pct"] == 0.0
        assert m["t_comm_exposed_s"] == m["t_comm_s"]

    def test_single_bucket_cannot_hide(self):
        m = overlap_model(250e6, 1, 8, step_seconds=1.0)
        assert m["overlap_pct"] == 0.0

    def test_more_buckets_more_overlap_until_window_bound(self):
        prev = -1.0
        for buckets in (2, 5, 10):
            m = overlap_model(250e6, buckets, 8, step_seconds=0.015)
            assert m["overlap_pct"] >= prev
            prev = m["overlap_pct"]
        # the tail bucket is never hidable
        assert m["t_comm_exposed_s"] >= m["t_comm_s"] / 10 - 1e-12

    def test_window_bound(self):
        # tiny step: the backward window, not the bucket count, limits
        # the hidable fraction
        m = overlap_model(250e6, 10, 8, step_seconds=1e-4,
                          bwd_fraction=0.5)
        assert m["t_comm_hidden_s"] <= 0.5 * 1e-4 * 0.9 + 1e-12


def test_comm_receipt_publishes_gauges_and_bucket_spans():
    from veles_tpu.observe.metrics import MetricsRegistry
    from veles_tpu.observe.trace import SpanTracer

    leaves = _sds((1000, 100), (100,))
    receipt = comm_receipt(leaves, 8, bucket_bytes=100 * 1000,
                           step_seconds=0.02)
    assert receipt["allreduce_bytes"] == 4 * (100000 + 100)
    assert len(receipt["bucket_bytes"]) == len(
        plan_buckets(leaves, 100 * 1000).buckets)

    reg = MetricsRegistry()
    tr = SpanTracer()
    tr.start()
    publish_comm_receipt(receipt, tracer=tr, registry=reg)
    tr.stop()
    assert reg.peek("comm.allreduce_bytes").value == \
        receipt["allreduce_bytes"]
    assert reg.peek("comm.buckets").value == len(receipt["bucket_bytes"])
    assert reg.peek("comm.overlap_pct").value == \
        receipt["model"]["overlap_pct"]
    spans = [e for e in tr.events
             if e.get("name") == "comm.bucket" and e.get("ph") == "X"]
    assert len(spans) == len(receipt["bucket_bytes"])
    assert [s["args"]["index"] for s in spans] == \
        list(range(len(spans)))
    assert all(s["args"]["modeled"] for s in spans)
    assert any(e.get("name") == "comm.receipt" for e in tr.events)


# -- control-plane demotion: single-traversal update validation ----------

class _RecordingUnit(object):
    def __init__(self, name):
        self.name = name
        self.applied = []

    def apply_data_from_slave(self, part, slave=None):
        self.applied.append(part)


class _StubControlWorkflow(object):
    """Bare workflow-contract stand-in exposing the pieces the inline
    validator touches."""
    update_validation = "inline"

    def __init__(self, units):
        self.units = units
        self._method_timers = {}

    def _distributed_units(self):
        return self.units

    # borrow the REAL implementations under test
    from veles_tpu.workflow import Workflow as _W
    apply_update_validated = _W.apply_update_validated
    apply_data_from_slave = _W.apply_data_from_slave
    _timed_method = _W._timed_method


def test_apply_update_validated_single_pass_and_poison_stops():
    from veles_tpu.health import PoisonedUpdate

    units = [_RecordingUnit("a"), _RecordingUnit("b"),
             _RecordingUnit("c")]
    wf = _StubControlWorkflow(units)
    ok = [numpy.arange(4, dtype=numpy.float32),
          {"n": 3, "loss": 0.5},
          None]
    assert wf.apply_update_validated(ok, None) is True
    assert units[0].applied and units[1].applied
    assert not units[2].applied  # None part skipped

    poisoned = [numpy.arange(4, dtype=numpy.float32),
                {"delta": numpy.array([1.0, numpy.nan])},
                {"n": 1}]
    units2 = [_RecordingUnit("a"), _RecordingUnit("b"),
              _RecordingUnit("c")]
    wf2 = _StubControlWorkflow(units2)
    with pytest.raises(PoisonedUpdate) as err:
        wf2.apply_update_validated(poisoned, None)
    # the poisoned part never applied, nor anything after it; the
    # finite part BEFORE it did (control records: recovered by the
    # drop/requeue path, docs/distributed.md)
    assert units2[0].applied
    assert not units2[1].applied
    assert not units2[2].applied
    assert "_RecordingUnit" in str(err.value)


def test_server_quarantines_inline_poisoned_update(cpu_device):
    """End-to-end over the real Server/Client sockets: a workflow in
    inline-validation mode (the SPMD control plane) still quarantines
    a poisoned update — single traversal, same drop + TTL-blacklist
    semantics (counted via server.quarantined and the blacklist)."""
    import time as _time

    from veles_tpu.jobfarm import JobFarm

    farm = JobFarm("bucketed-inline", blacklist_ttl=0.4)

    calls = []

    def runner(spec):
        calls.append(spec)
        if spec == "poison" and calls.count("poison") == 1:
            return {"delta": numpy.array([numpy.nan], numpy.float32)}
        return {"delta": numpy.array([float(len(calls))],
                                     numpy.float32)}

    farm.start(runner=runner, local_slaves=1)
    try:
        # flip the farm master to the inline single-traversal mode:
        # results are control-record dicts here, so the demoted
        # validation path applies
        farm._master.update_validation = "inline"
        results = farm.submit(["ok1", "poison", "ok2"], timeout=30)
        assert len(results) == 3
        # the poisoned result was dropped and its job re-run after the
        # quarantine TTL, so every slot holds a finite value
        for r in results:
            assert numpy.isfinite(r["delta"]).all()
        assert farm.server.quarantined == 1
    finally:
        farm.shutdown()
        _time.sleep(0)


def test_legacy_prewalk_unchanged_all_or_nothing():
    """Workflows that still ship per-step deltas keep the
    all-or-nothing prewalk (update_validation default)."""
    from veles_tpu.workflow import Workflow
    assert Workflow.update_validation == "prewalk"
    from veles_tpu.jobfarm import _FarmMaster
    assert _FarmMaster.update_validation == "prewalk"


# -- e2e: SPMD fused workflow + demoted control plane + merged trace ------

def _blobs_workflow(seed_name, mesh=None, bucket=None, compress=None,
                    device=None, max_epochs=3):
    from tests.test_models import BlobsLoader
    from veles_tpu import prng
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    prng.get().seed(7)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=32,
            prng=RandomGenerator(seed_name, seed=3)),
        decision_config=dict(max_epochs=max_epochs))
    sw.fuse(mesh=mesh, grad_bucket_mb=bucket, grad_compress=compress)
    sw.initialize(device=device)
    return sw


@pytest.mark.dist
def test_fused_spmd_workflow_trains_and_publishes_comm(cpu_device):
    """The whole stack: StandardWorkflow.fuse(mesh=...) runs the SPMD
    bucketed inner loop, matches the single-device fused run, demotes
    the protocol (inline validation), and publishes the comm
    receipt."""
    from veles_tpu.observe.metrics import registry
    from veles_tpu.parallel import auto_mesh

    registry.reset()
    ref = _blobs_workflow("dist_e2e", device=cpu_device)
    ref.run()
    ref.fused_trainer.sync()

    mesh = auto_mesh()
    got = _blobs_workflow("dist_e2e", mesh=mesh, bucket=0.001,
                          device=cpu_device)
    assert got.update_validation == "inline"
    assert ref.update_validation == "prewalk"
    got.run()
    got.fused_trainer.sync()

    assert bool(ref.decision.complete) and bool(got.decision.complete)
    for fr, fg in zip(ref.forwards, got.forwards):
        fr.weights.map_read()
        fg.weights.map_read()
        numpy.testing.assert_allclose(fr.weights.mem, fg.weights.mem,
                                      rtol=1e-4, atol=1e-6)
    assert registry.peek("comm.allreduce_bytes").value > 0
    assert registry.peek("comm.buckets").value >= 2
    assert registry.peek("comm.overlap_pct").value is not None


@pytest.mark.dist
def test_spmd_mesh_survives_pickle_resume(cpu_device):
    """A Mesh holds live device handles, so snapshots carry its AXES;
    initialize() must rebuild it on resume instead of silently
    degrading the resumed run to a single-device step."""
    from veles_tpu.models.fused import FusedTrainer
    from veles_tpu.parallel import auto_mesh

    sw = _blobs_workflow("dist_resume", mesh=auto_mesh(), bucket=0.001,
                         device=cpu_device, max_epochs=1)
    state = sw.fused_trainer.__getstate__()
    assert state["mesh"] is None
    assert state["_spmd_axes_"] == {"data": 8}

    def bare(axes):
        t = FusedTrainer.__new__(FusedTrainer)
        t.mesh = None
        t._spmd_axes_ = axes
        t.warning = lambda *a, **k: None
        return t

    resumed = bare({"data": 8})
    resumed._restore_mesh()
    assert resumed.mesh is not None
    assert dict(resumed.mesh.shape) == {"data": 8}

    # a pure-DP mesh that no longer fits re-spans the current devices
    refit = bare({"data": 16})
    refit._restore_mesh()
    assert dict(refit.mesh.shape) == {"data": 8}

    # a multi-axis shape that cannot be rebuilt fails LOUDLY
    with pytest.raises(ValueError, match="re-fuse"):
        bare({"data": 5, "model": 3})._restore_mesh()


@pytest.mark.dist
@pytest.mark.chaos
def test_two_node_chaos_merged_trace_carries_comm_spans(
        cpu_device, tmp_path):
    """Acceptance: a 2-process-track chaos run (in-proc master +
    slave, injected poisoned update) produces a merged Perfetto trace
    in which the SPMD data plane's per-bucket comm spans and the
    ``comm.overlap_pct`` gauge are visible alongside the control
    plane's protocol events."""
    from tests.test_network import _build, _start_server
    from veles_tpu import chaos
    from veles_tpu.chaos import FaultPlan
    from veles_tpu.client import Client
    from veles_tpu.observe.merge import merge_run
    from veles_tpu.observe.metrics import registry
    from veles_tpu.observe.trace import tracer, validate_trace
    from veles_tpu.parallel import auto_mesh

    registry.reset()
    tracer.start()
    tracer.label = "master"
    try:
        # the master's data plane: an SPMD bucketed run records the
        # per-bucket comm spans on the master track while the control
        # plane serves jobs below
        spmd = _blobs_workflow("dist_chaos_spmd", mesh=auto_mesh(),
                               bucket=0.001, device=cpu_device,
                               max_epochs=2)
        spmd.run()

        master = _build("master", "dist_chaos_m", cpu_device)
        slave = _build("slave", "dist_chaos_s", cpu_device)
        server, _ = _start_server(master, blacklist_ttl=0.6)
        client = Client("127.0.0.1:%d" % server.port, slave,
                        trace_scope="threads")
        plan = chaos.install(FaultPlan().add("net.update", "nan",
                                             nth=2))
        try:
            client.run()
        finally:
            chaos.uninstall()
        assert server._done.wait(15)
        assert plan.fired("net.update") == 1
        assert server.quarantined == 1

        import json as _json
        trace_path = str(tmp_path / "master.json")
        tracer.save(trace_path)
        with open(trace_path) as fin:
            master_doc = _json.load(fin)
        merged = merge_run(master_doc, server.trace_collector,
                           trace_id=server.trace_id)
        validate_trace(merged)
    finally:
        tracer.stop()

    events = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    buckets = [e for e in events if e["name"] == "comm.bucket"]
    assert len(buckets) >= 2, \
        "per-bucket comm spans missing from the merged trace"
    assert {b["args"]["index"] for b in buckets} >= {0, 1}
    assert any(e["name"] == "comm.receipt" for e in events)
    assert any(e["name"] == "proto.quarantine" for e in events)
    assert registry.peek("comm.overlap_pct").value is not None
    assert registry.peek("comm.allreduce_bytes").value > 0
