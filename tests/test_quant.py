"""Int8 quantized-inference tests (docs/serving.md "Quantized
ladder"): the int8 Pallas matmul/conv bit-exactness contract vs the
jitted interpret-mode reference, the post-training quantization pass
(per-channel symmetric scales, percentile calibration, zero-channel /
saturating-outlier edge cases, spec round-trip bit-stability), the
f32-vs-int8 model-digest separation, the quantized AOTEngine
(accuracy parity, warm-restart 0-compile receipt, serve_snapshot
flag), the ``matmul_int8`` schedule-cache family, and the
quantized-candidate canary e2e through ``CanaryCutover``."""

import numpy
import pytest

from veles_tpu.backends import Device
from veles_tpu.compiler import LayerPlan
from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.quant import (
    build_quantized_forward, calibrate_activations, is_quantized_params,
    quantize_model_spec, quantize_weights)
from veles_tpu.serve.engine import (
    AOTEngine, engine_digest_extra, model_digest)
from tests.test_serve import _mlp_spec

pytestmark = pytest.mark.quant


def _quantized_mlp(seed=5, fan_in=16, hidden=32, classes=4,
                   n_calib=256):
    rng = numpy.random.RandomState(seed)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": (rng.randn(fan_in, hidden) * 0.3).astype(
            numpy.float32),
         "bias": (rng.randn(hidden) * 0.1).astype(numpy.float32)},
        {"weights": (rng.randn(hidden, classes) * 0.3).astype(
            numpy.float32),
         "bias": (rng.randn(classes) * 0.1).astype(numpy.float32)},
    ]
    samples = rng.rand(n_calib, fan_in).astype(numpy.float32)
    qparams, calib = quantize_model_spec(plans, params, samples)
    return plans, params, qparams, calib


# -- (a) int8 Pallas kernel bit-exactness ------------------------------------


def test_int8_matmul_bitexact_vs_reference():
    """The acceptance anchor: the tiled int8 Pallas matmul (interpret
    mode on CPU) matches the JITTED untiled reference bit-exactly —
    integer accumulation is exact under any tile grouping and the
    dequant epilogue is the same FMA-contracted f32 expression.
    Shapes exercise padding on every axis and multi-block K walks."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.matmul_int8 import (matmul_int8,
                                           matmul_int8_reference)

    rng = numpy.random.RandomState(3)
    ref = jax.jit(matmul_int8_reference)
    for m, k, n, blocks in [(37, 91, 53, (64, 128, 128)),
                            (300, 500, 260, (64, 128, 128)),
                            (8, 1024, 128, (32, 128, 128)),
                            (129, 257, 385, None)]:
        a = jnp.asarray(rng.randint(-127, 128, (m, k)), jnp.int8)
        b = jnp.asarray(rng.randint(-127, 128, (k, n)), jnp.int8)
        scale = jnp.asarray(rng.rand(n).astype(numpy.float32) * 0.01)
        bias = jnp.asarray(rng.randn(n).astype(numpy.float32))
        out = matmul_int8(a, b, scale, bias, blocks=blocks)
        want = ref(a, b, scale, bias)
        assert out.dtype == jnp.float32
        assert (numpy.asarray(out) == numpy.asarray(want)).all(), \
            (m, k, n, blocks)
    # scalar scale, no bias — the other epilogue arity
    a = jnp.asarray(rng.randint(-127, 128, (40, 200)), jnp.int8)
    b = jnp.asarray(rng.randint(-127, 128, (200, 70)), jnp.int8)
    out = matmul_int8(a, b, jnp.float32(0.005), blocks=(32, 128, 128))
    want = jax.jit(lambda a, b, s: matmul_int8_reference(a, b, s))(
        a, b, jnp.float32(0.005))
    assert (numpy.asarray(out) == numpy.asarray(want)).all()


def test_int8_matmul_rejects_non_int8():
    import jax.numpy as jnp

    from veles_tpu.ops.matmul_int8 import matmul_int8
    with pytest.raises(TypeError):
        matmul_int8(jnp.zeros((4, 4), jnp.float32),
                    jnp.zeros((4, 4), jnp.int8), 1.0)


def test_int8_conv_matches_dequantized_f32_conv():
    """conv2d_int8 == the f32 conv of the dequantized integers (the
    patches are pure data movement, the contraction is exact int32):
    agreement to f32 rounding noise across stride/padding configs."""
    import jax.numpy as jnp
    from jax import lax

    from veles_tpu.ops.matmul_int8 import conv2d_int8

    rng = numpy.random.RandomState(7)
    for padding, sliding in [((0, 0, 0, 0), (1, 1)),
                             ((1, 1, 1, 1), (2, 2)),
                             ((2, 1, 0, 1), (1, 2))]:
        x = jnp.asarray(rng.randint(-127, 128, (2, 9, 11, 3)),
                        jnp.int8)
        w = jnp.asarray(rng.randint(-127, 128, (3, 3, 3, 5)),
                        jnp.int8)
        scale = jnp.asarray(rng.rand(5).astype(numpy.float32) * 0.01)
        bias = jnp.asarray(rng.randn(5).astype(numpy.float32))
        got = conv2d_int8(x, w, scale, bias, padding=padding,
                          sliding=sliding)
        left, top, right, bottom = padding
        sx, sy = sliding
        zf = lax.conv_general_dilated(
            x.astype(jnp.float32), w.astype(jnp.float32), (sy, sx),
            ((top, bottom), (left, right)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        want = zf * scale[None, None, None, :] + bias[None, None,
                                                      None, :]
        assert got.shape == want.shape
        assert numpy.allclose(numpy.asarray(got), numpy.asarray(want),
                              rtol=1e-5, atol=1e-4), (padding, sliding)


# -- (b) the quantization pass -----------------------------------------------


def test_quantize_weights_per_channel_edges():
    """Zero-point-free symmetric edge cases: an all-zero channel gets
    scale 1.0 and zero codes (no div-by-zero, exact dequant); the
    largest magnitude in every channel lands exactly on +/-127; values
    beyond a channel's own max cannot exist by construction."""
    w = numpy.zeros((4, 3), numpy.float32)
    w[:, 0] = [1.0, -2.0, 0.5, 2.0]       # symmetric-ish channel
    w[:, 1] = 0.0                          # all-zero channel
    w[:, 2] = [1e-3, -1e-3, 5e-4, 1e-3]    # tiny channel
    q, scales = quantize_weights(w)
    assert q.dtype == numpy.int8 and scales.shape == (3,)
    assert scales[1] == 1.0 and (q[:, 1] == 0).all()
    assert abs(q[:, 0]).max() == 127
    assert abs(q[:, 2]).max() == 127  # per-channel: tiny channel keeps
    #                                   its full 8-bit resolution
    # round-trip error bounded by half a step per channel
    deq = q.astype(numpy.float32) * scales[None, :]
    assert numpy.abs(deq - w).max() <= (scales.max() / 2 + 1e-9)


def test_calibration_percentile_clips_saturating_outliers():
    """Percentile calibration deliberately clips the outlier tail: the
    scale stays near the bulk of the distribution, the clip fraction
    is recorded (and rides the serve.quant.clip_fraction gauge), and
    the quantized forward stays finite through saturation."""
    import jax.numpy as jnp

    from veles_tpu.observe.metrics import registry

    rng = numpy.random.RandomState(9)
    plans = [LayerPlan(All2AllTanh)]
    params = [{"weights": (rng.randn(8, 4) * 0.3).astype(numpy.float32),
               "bias": numpy.zeros(4, numpy.float32)}]
    samples = rng.rand(512, 8).astype(numpy.float32)
    samples[::97] *= 1e3  # saturating outlier rows
    minmax = calibrate_activations(plans, params, samples,
                                   mode="minmax")
    pct = calibrate_activations(plans, params, samples,
                                mode="percentile", percentile=99.0)
    assert pct.layers[0]["act_scale"] < minmax.layers[0]["act_scale"]
    assert minmax.layers[0]["clip_fraction"] == 0.0
    assert pct.layers[0]["clip_fraction"] > 0.0
    gauge = registry.peek("serve.quant.clip_fraction")
    assert gauge is not None and gauge.value == round(
        pct.clip_fraction, 6)
    # saturation stays finite end to end
    qparams, _ = quantize_model_spec(plans, params, calibration=pct)
    fwd = build_quantized_forward(plans)
    out = fwd([{k: jnp.asarray(v) for k, v in qparams[0].items()}],
              jnp.asarray(samples[:8]))
    assert bool(jnp.isfinite(out).all())


def test_per_channel_beats_per_tensor_on_skewed_mlp():
    """A weight matrix with a 100x inter-channel magnitude skew: one
    per-tensor scale crushes the small channels' resolution; the
    per-channel pass keeps every channel's full 8-bit grid, so its
    output error must be strictly smaller."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.compiler import build_forward

    rng = numpy.random.RandomState(11)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    w0 = (rng.randn(16, 32) * 0.02).astype(numpy.float32)
    w0[:, ::4] *= 100.0  # channel skew
    params = [
        {"weights": w0,
         "bias": numpy.zeros(32, numpy.float32)},
        {"weights": (rng.randn(32, 4) * 0.3).astype(numpy.float32),
         "bias": numpy.zeros(4, numpy.float32)},
    ]
    samples = rng.rand(256, 16).astype(numpy.float32)
    x = jnp.asarray(rng.rand(64, 16).astype(numpy.float32))
    ref = jax.jit(build_forward(plans))(
        [{k: jnp.asarray(v) for k, v in e.items()} for e in params], x)
    errs = {}
    for gran in ("channel", "tensor"):
        qp, _ = quantize_model_spec(plans, params, samples,
                                    weight_granularity=gran)
        out = jax.jit(build_quantized_forward(plans))(
            [{k: jnp.asarray(v) for k, v in e.items()} for e in qp], x)
        errs[gran] = float(jnp.max(jnp.abs(out - ref)))
    assert errs["channel"] < errs["tensor"], errs


def test_quantized_spec_roundtrip_bit_stable(tmp_path):
    """The quantized spec round-trips through export_model_spec /
    import_file with bit-identical serving: scales and int8 codes
    survive the pickle byte-for-byte, the restored engine shares the
    original's digest, and re-quantizing the same params with the same
    calibration reproduces the identical artifacts."""
    from veles_tpu.serve.freshness import export_model_spec
    from veles_tpu.snapshotter import SnapshotterBase

    plans, params, qparams, calib = _quantized_mlp()
    path = str(tmp_path / "qspec.pickle")
    export_model_spec(path, plans, qparams, (16,))
    restored = SnapshotterBase.import_file(path, fallback=False)
    rparams = [dict(e) for e in restored["params"]]
    for orig, back in zip(qparams, rparams):
        assert sorted(orig) == sorted(back)
        for key in orig:
            assert (numpy.asarray(orig[key])
                    == numpy.asarray(back[key])).all()
            assert numpy.asarray(orig[key]).dtype \
                == numpy.asarray(back[key]).dtype
    # determinism: same params + same calibration -> identical pass
    qparams2, _ = quantize_model_spec(plans, params, calibration=calib)
    for a, b in zip(qparams, qparams2):
        for key in a:
            assert (numpy.asarray(a[key]) == numpy.asarray(b[key])).all()
    # and the restored spec serves bit-identically
    eng = AOTEngine(plans, qparams, (16,), ladder=(8,),
                    device=Device(backend="cpu"))
    eng.compile()
    eng2 = AOTEngine(list(restored["plans"]), rparams,
                     tuple(restored["sample_shape"]), ladder=(8,),
                     device=Device(backend="cpu"))
    eng2.compile()
    assert eng2.digest == eng.digest
    x = numpy.random.RandomState(4).rand(8, 16).astype(numpy.float32)
    assert (eng.infer(x) == eng2.infer(x)).all()


# -- (c) digest separation ---------------------------------------------------


def test_model_digest_f32_int8_collision_impossible():
    """The satellite regression: a quantized spec and its f32 source
    have identical topology and weight SHAPES — param dtypes and the
    quantization artifacts must still separate the digests, or the two
    engines would share one persistent compile cache entry and one
    freshness last-good identity.  The engine input dtype rides the
    digest too (f32-in vs bf16-in is a different compiled program)."""
    plans, params, qparams, _ = _quantized_mlp()
    extra = engine_digest_extra(numpy.float32)
    d_f32 = model_digest(plans, params, (16,), extra=extra)
    d_int8 = model_digest(plans, qparams, (16,), extra=extra)
    assert d_f32 != d_int8
    # engines agree with the module-level recipe
    e_f32 = AOTEngine(plans, params, (16,), device=Device(backend="cpu"))
    e_int8 = AOTEngine(plans, qparams, (16,),
                       device=Device(backend="cpu"))
    assert e_f32.digest == d_f32 and e_int8.digest == d_int8
    assert e_int8.quantized and not e_f32.quantized
    # input-dtype separation (same params, different ladder input)
    assert model_digest(plans, params, (16,),
                        extra=engine_digest_extra("float32")) != \
        model_digest(plans, params, (16,),
                     extra=engine_digest_extra("bfloat16"))


# -- (d) the quantized engine ------------------------------------------------


def test_quantized_engine_parity_and_snapshot_flag():
    """A quantized engine beside its f32 source: sub-percent top-1
    disagreement and small probability divergence on a seeded stream
    (random-weight MLPs have near-tie rows, so the bound is loose
    compared to the trained-zoo QUANT.json receipt), and the
    serve_snapshot/healthz quantized flag flips with the engine."""
    from veles_tpu.observe.metrics import registry
    from veles_tpu.serve.batcher import serve_snapshot

    plans, params, qparams, _ = _quantized_mlp(fan_in=16, hidden=32,
                                               classes=10)
    f32 = AOTEngine(plans, params, (16,), ladder=(8, 32),
                    device=Device(backend="cpu"))
    f32.compile()
    assert registry.peek("serve.quantized").value == 0
    q = AOTEngine(plans, qparams, (16,), ladder=(8, 32),
                  device=Device(backend="cpu"))
    receipt = q.compile()
    assert receipt["quantized"] is True
    assert registry.peek("serve.quantized").value == 1
    assert serve_snapshot().get("quantized") == 1
    x = numpy.random.RandomState(2).rand(128, 16).astype(numpy.float32)
    y32, y8 = f32.infer(x), q.infer(x)
    assert float((y32.argmax(1) != y8.argmax(1)).mean()) <= 0.05
    assert float(numpy.abs(y32 - y8).max()) < 0.05


def test_quantized_warm_restart_zero_compiles(tmp_path):
    """Acceptance: warm restart of a quantized engine = 0 new backend
    compiles — the int8 Pallas forward persists in the digest-keyed
    compile cache like any other program."""
    import jax

    plans, _params, qparams, _ = _quantized_mlp()
    root = str(tmp_path / "qserve_cache")
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    try:
        cold = AOTEngine(plans, qparams, (16,), ladder=(8, 32),
                         device=Device(backend="cpu"), cache_root=root)
        cold_receipt = cold.compile()
        assert cold_receipt["new_compiles"] >= 2
        warm = AOTEngine(plans, qparams, (16,), ladder=(8, 32),
                         device=Device(backend="cpu"), cache_root=root)
        warm_receipt = warm.compile()
        assert warm_receipt["new_compiles"] == 0, warm_receipt
        assert warm_receipt["cache_hits"] >= 2
        x = numpy.random.RandomState(4).rand(8, 16).astype(
            numpy.float32)
        assert (warm.infer(x) == cold.infer(x)).all()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_floor)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prev_size)
        from jax._src import compilation_cache
        compilation_cache.reset_cache()


# -- (e) schedule-cache family -----------------------------------------------


def test_schedule_cache_serves_int8_family():
    """A planted matmul_int8 entry is consulted by blocks=None calls
    (counted as a tune.cache_hit) and — schedules change scheduling,
    never math — serves BIT-identical results to the static default;
    the int8 family's digest can never collide with the f32 matmul's
    for the same raw shape."""
    import jax.numpy as jnp

    from veles_tpu.observe.metrics import registry
    from veles_tpu.ops.matmul_int8 import matmul_int8
    from veles_tpu.tune.cache import cache_for, schedule_key
    from veles_tpu.tune.spec import matmul_int8_spec, matmul_spec

    m, k, n = 48, 300, 200
    spec = matmul_int8_spec(m, k, n)
    digest, payload = schedule_key(
        spec["op"], spec["shape"], spec["dtype"],
        spec["precision_level"], "cpu", spec["extra"])
    f32_spec = matmul_spec(m, k, n, "float32", 0)
    f32_digest, _ = schedule_key(
        f32_spec["op"], f32_spec["shape"], f32_spec["dtype"],
        f32_spec["precision_level"], "cpu", f32_spec["extra"])
    assert digest != f32_digest
    cache = cache_for()
    cache.put(digest, payload, {"blocks": [32, 128, 128]},
              source="test")
    rng = numpy.random.RandomState(6)
    a = jnp.asarray(rng.randint(-127, 128, (m, k)), jnp.int8)
    b = jnp.asarray(rng.randint(-127, 128, (k, n)), jnp.int8)
    scale = jnp.asarray(rng.rand(n).astype(numpy.float32) * 0.01)
    hits_before = registry.counter("tune.cache_hits").value
    tuned = matmul_int8(a, b, scale)          # consults the cache
    static = matmul_int8(a, b, scale, blocks=(256, 512, 512))
    assert registry.counter("tune.cache_hits").value > hits_before
    assert (numpy.asarray(tuned) == numpy.asarray(static)).all()


def test_int8_family_quantization_and_feasibility():
    """MXU legality for int8: genes snap to sublane-32/lane-128
    multiples, and the feasibility gate rejects VMEM-overflow tiles
    before any compile."""
    from veles_tpu.tune.spec import (TUNE_VMEM_BUDGET_BYTES,
                                     family_for, matmul_int8_spec)

    family = family_for("matmul_int8")
    spec = matmul_int8_spec(1000, 1000, 1000)
    sched = family.quantize(spec, {"bm": 100, "bn": 200, "bk": 300})
    bm, bn, bk = sched["blocks"]
    assert bm % 32 == 0 and bn % 128 == 0 and bk % 128 == 0
    assert family.validate(sched) is not None
    assert family.validate({"blocks": [8, 128, 128]}) is None  # f32 tile
    assert family.feasible(spec, {"blocks": [32, 128, 128]})
    huge = {"blocks": [1024, 2048, 2048]}
    footprint = (1024 * 2048 + 2048 * 2048 + 2 * 1024 * 2048 * 4
                 + 2 * 2048 * 4)
    assert footprint > TUNE_VMEM_BUDGET_BYTES
    assert not family.feasible(spec, huge)


# -- (f) freshness / canary --------------------------------------------------


def test_watcher_accepts_quantized_spec(tmp_path):
    """A published quantized model spec is 'just another digest' to the
    freshness watcher: manifest-verified, finite-gated (int8 arrays are
    vacuously finite) and handed over as a candidate — never escalated
    as poisoned."""
    from veles_tpu.health import all_finite
    from veles_tpu.observe.metrics import registry
    from veles_tpu.serve import SnapshotWatcher, export_model_spec
    from veles_tpu.snapshotter import publish_snapshot

    plans, _params, qparams, _ = _quantized_mlp()
    assert all_finite(qparams)  # the controller's finite gate passes
    path = str(tmp_path / "qspec.pickle")
    export_model_spec(path, plans, qparams, (16,))
    pub = str(tmp_path / "pub")
    publish_snapshot(path, pub)
    poisoned_before = registry.counter(
        "serve.freshness.poisoned_rejected").value
    got = []
    watcher = SnapshotWatcher(pub, callback=got.append)
    cand = watcher.poll_once()
    assert cand is not None and got and got[0] is cand
    assert is_quantized_params(cand.params)
    assert tuple(cand.sample_shape) == (16,)
    assert registry.counter(
        "serve.freshness.poisoned_rejected").value == poisoned_before


def test_quantized_candidate_canary_promote_then_divergence_rollback(
        tmp_path):
    """The satellite e2e: an int8-quantized candidate is canaried
    against the f32 fleet under mirrored traffic and PROMOTED (its
    divergence sits far inside the bound); a scale-corrupted quantized
    candidate — finite, loads fine, answers garbage — breaches the
    divergence bound and is auto-ROLLED BACK with zero new compiles."""
    import threading
    import time

    from veles_tpu.serve import value_digest
    from veles_tpu.snapshotter import publish_snapshot
    from tests.test_freshness import (_controller, _pool, _spec_path)

    pool = _pool(tmp_path, replicas=3, seed=11)
    # quantize the fleet's OWN model — the production scenario: the
    # candidate is the serving weights at the int8 level, calibrated
    # on the same distribution the clients drive
    calib = numpy.random.RandomState(1).rand(256, 16).astype(
        numpy.float32)
    qparams, _ = quantize_model_spec(pool.engine.plans,
                                     pool.engine.params, calib)
    pool.start()
    controller = _controller(pool, tmp_path, divergence_limit=0.2,
                             invalid_ttl_s=1.0)
    controller.start()
    errors = []
    stop = threading.Event()

    def client(k):
        rng = numpy.random.RandomState(40 + k)
        x = rng.rand(16).astype(numpy.float32)
        while not stop.is_set():
            try:
                pool.infer(x, timeout=15.0)
            except Exception as exc:
                errors.append(exc)
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(3)]
    for t in threads:
        t.start()
    try:
        def publish(name, p):
            return publish_snapshot(
                _spec_path(tmp_path, name, p, pool.engine.plans),
                str(tmp_path / "publish"))

        def wait_cycle(ordinal, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                for entry in controller.history:
                    if entry["ordinal"] == ordinal:
                        return entry
                time.sleep(0.02)
            raise TimeoutError("no verdict for #%d" % ordinal)

        # the quantized candidate promotes: the fleet cuts over to the
        # int8 digest (per-replica AOT warm — new digest, new engines)
        entry = wait_cycle(publish("quant.pickle", qparams)["ordinal"])
        assert entry["verdict"] == "promoted", entry
        want = value_digest(qparams)
        for rep in pool.replicas:
            assert rep.engine.quantized
            assert value_digest(rep.engine.params) == want

        # a finite-but-garbage quantized candidate: the output classes
        # permuted (weights/bias/scales rolled together) — loads,
        # warms, quantization artifacts all self-consistent, answers
        # the WRONG question confidently; the mirrored divergence
        # bound is exactly what catches it
        garbage = [dict(e) for e in qparams]
        garbage[-1] = dict(
            garbage[-1],
            weights=numpy.roll(garbage[-1]["weights"], 1, axis=1),
            weights_scale=numpy.roll(garbage[-1]["weights_scale"], 1),
            bias=numpy.roll(garbage[-1]["bias"], 1))
        entry = wait_cycle(publish("qbad.pickle", garbage)["ordinal"])
        assert entry["verdict"] == "rolled_back", entry
        assert entry["new_compiles"] == 0, entry
        for rep in pool.replicas:
            assert value_digest(rep.engine.params) == want
        assert pool.cutover.state == "idle"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        controller.stop()
        pool.stop()
    assert not errors, errors[:3]


def test_rejected_quantized_canary_restores_process_flags(tmp_path):
    """The quantized flag and MFU-ceiling dtype are process-global and
    a canary's warm-up compile flips them; rollback is swap-backs with
    ZERO compiles by construction, so it must republish from the live
    fleet anchor — a rejected int8 candidate cannot leave an f32 fleet
    branded quantized (and rating MFU against the int8 peak) forever."""
    from veles_tpu.observe import xla_introspect
    from veles_tpu.observe.metrics import registry
    from tests.test_freshness import _pool

    pool = _pool(tmp_path, replicas=2, seed=7)
    assert registry.peek("serve.quantized").value == 0
    assert xla_introspect.step_dtype() == "bf16"
    calib = numpy.random.RandomState(1).rand(128, 16).astype(
        numpy.float32)
    qparams, _ = quantize_model_spec(pool.engine.plans,
                                     pool.engine.params, calib)
    pool.start()
    try:
        candidate = AOTEngine(pool.engine.plans, qparams, (16,),
                              ladder=pool.engine.ladder,
                              device=pool.replicas[-1].device)
        candidate.compile()  # the warm-up flips the process globals
        assert registry.peek("serve.quantized").value == 1
        assert xla_introspect.step_dtype() == "int8"
        pool.cutover.begin(candidate)
        receipt = pool.cutover.rollback(reason="test rejection")
        assert receipt["new_compiles"] == 0
        # the restored f32 fleet owns the flags again
        assert registry.peek("serve.quantized").value == 0
        assert xla_introspect.step_dtype() == "bf16"
    finally:
        pool.stop()


# -- (g) MFU ceiling + bench machinery ---------------------------------------


def test_peak_tables_and_step_dtype(monkeypatch):
    """The int8 peak table doubles bf16 where the hardware does
    (v5e/v5p/v6) and never undercuts it; set_step_dtype drives the
    ceiling mfu_snapshot divides by (via peak_flops' dtype default)
    and the step-dtype gauge."""
    from veles_tpu.observe import xla_introspect as xi
    from veles_tpu.observe.metrics import registry

    bf16 = dict(xi.PEAK_BF16_TFLOPS)
    int8 = dict(xi.PEAK_INT8_TFLOPS)
    assert set(bf16) == set(int8)
    for kind in bf16:
        assert int8[kind] >= bf16[kind]
    for kind in ("v5", "v5p", "v6"):
        assert int8[kind] == 2 * bf16[kind]
    prev = xi.step_dtype()
    try:
        xi.set_step_dtype("int8")
        assert xi.step_dtype() == "int8"
        assert registry.peek("xla.step_dtype_int8").value == 1
        # the env override applies to whatever dtype is asked for
        monkeypatch.setenv("VELES_PEAK_TFLOPS", "123.5")
        xi._peak_cache.pop(("peak", "int8"), None)
        assert xi.peak_flops() == 123.5e12
        xi._peak_cache.pop(("peak", "int8"), None)
        with pytest.raises(ValueError):
            xi.set_step_dtype("fp4")
    finally:
        xi.set_step_dtype(prev)


def test_bench_quant_ab_smoke():
    """The bench section's CPU mode: parity + receipts, green."""
    from bench import bench_quant_ab

    result = bench_quant_ab(True)
    assert result["pallas_bitexact"] is True
    assert result["top1_delta_pct"] <= 5.0
    assert result["digests"]["f32"] != result["digests"]["int8"]
    assert result["compiles"]["int8"] >= 1
    assert "note" in result  # CPU rows never claim a speedup
