"""Ring / Ulysses sequence-parallel attention vs the single-device
oracle, on the virtual 8-device CPU mesh."""

import numpy
import pytest

import jax

from veles_tpu.parallel.mesh import make_mesh
from veles_tpu.parallel.ring import (
    attention_reference, ring_attention, ulysses_attention)


def _qkv(rng, batch=2, seq=64, heads=8, depth=16):
    shape = (batch, seq, heads, depth)
    return (rng.randn(*shape).astype(numpy.float32),
            rng.randn(*shape).astype(numpy.float32),
            rng.randn(*shape).astype(numpy.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(causal):
    rng = numpy.random.RandomState(0)
    q, k, v = _qkv(rng)
    mesh = make_mesh({"seq": 8})
    want = numpy.asarray(attention_reference(q, k, v, causal=causal))
    got = numpy.asarray(ring_attention(q, k, v, mesh, causal=causal))
    numpy.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(causal):
    rng = numpy.random.RandomState(1)
    q, k, v = _qkv(rng)
    mesh = make_mesh({"seq": 8})
    want = numpy.asarray(attention_reference(q, k, v, causal=causal))
    got = numpy.asarray(
        ulysses_attention(q, k, v, mesh, causal=causal))
    numpy.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_2d_mesh_with_dp():
    """seq parallel composes with data parallel on a 2D mesh."""
    rng = numpy.random.RandomState(2)
    q, k, v = _qkv(rng, batch=4, seq=32, heads=4)
    mesh = make_mesh({"data": 2, "seq": 4})
    want = numpy.asarray(attention_reference(q, k, v, causal=True))

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P("data", "seq"))
    qd, kd, vd = (jax.device_put(t, sharding) for t in (q, k, v))
    got = numpy.asarray(ring_attention(qd, kd, vd, mesh, causal=True))
    numpy.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_attention_data_axis_shards_batch(fn):
    """data_axis= shards the batch over a second mesh axis (the dp x sp
    layout the 64-device dryrun runs pod-shaped) and stays exact."""
    rng = numpy.random.RandomState(4)
    q, k, v = _qkv(rng, batch=4, seq=32, heads=4)
    mesh = make_mesh({"data": 2, "seq": 4})
    want = numpy.asarray(attention_reference(q, k, v, causal=True))
    got = numpy.asarray(fn(q, k, v, mesh, causal=True,
                           data_axis="data"))
    numpy.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_flow():
    rng = numpy.random.RandomState(3)
    q, k, v = _qkv(rng, batch=1, seq=32, heads=2, depth=8)
    mesh = make_mesh({"seq": 8})

    def loss(q_, k_, v_):
        import jax.numpy as jnp
        return jnp.sum(ring_attention(q_, k_, v_, mesh) ** 2)

    def loss_ref(q_, k_, v_):
        import jax.numpy as jnp
        return jnp.sum(attention_reference(q_, k_, v_) ** 2)

    g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    numpy.testing.assert_allclose(numpy.asarray(g),
                                  numpy.asarray(g_ref), rtol=1e-3,
                                  atol=1e-4)


def test_ring_attention_grad_matches_oracle():
    """The ring is reverse-differentiable (scan + ppermute transpose):
    long-context models can TRAIN through it, not just serve."""
    rng = numpy.random.RandomState(4)
    q, k, v = _qkv(rng, batch=2, seq=32, heads=4, depth=8)
    mesh = make_mesh({"seq": 8})

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        numpy.testing.assert_allclose(
            numpy.asarray(g), numpy.asarray(w), rtol=2e-3, atol=2e-4)


def test_ring_attention_3d_mesh_dp_sp_tp():
    """batch->data, seq->ring, heads->model: the 3-axis composition
    (dp x sp x tp) is exact — heads are embarrassingly parallel, so
    the tensor-parallel axis adds zero communication to the ring."""
    rng = numpy.random.RandomState(5)
    q, k, v = _qkv(rng, batch=4, seq=16, heads=2, depth=8)
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    want = numpy.asarray(attention_reference(q, k, v, causal=True))

    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("data", "seq", "model", None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))
    got = numpy.asarray(ring_attention(
        qs, ks, vs, mesh, causal=True, data_axis="data",
        head_axis="model"))
    numpy.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_bf16_matches_oracle():
    """bf16 inputs (the long-context serving dtype): the ring's f32
    online-softmax accumulators keep it within bf16 tolerance."""
    import jax.numpy as jnp
    rng = numpy.random.RandomState(6)
    q, k, v = _qkv(rng, batch=2, seq=32, heads=4, depth=8)
    qb, kb, vb = (jnp.asarray(t, jnp.bfloat16) for t in (q, k, v))
    mesh = make_mesh({"seq": 8})
    want = numpy.asarray(attention_reference(
        qb, kb, vb, causal=True).astype(jnp.float32))
    got = numpy.asarray(ring_attention(
        qb, kb, vb, mesh, causal=True).astype(jnp.float32))
    numpy.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
