"""Round-2 service depth: PDF publishing, forge upload auth, sqlite
snapshot sink, WebHDFS loader (in-process fake namenode), audio
loader on real WAV files."""

import http.server
import json
import sqlite3
import threading
import wave

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.prng import RandomGenerator


# ---------------------------------------------------------------- pdf


def test_pdf_publishing_backend(tmp_path, cpu_device):
    from tests.test_native import _train_mlp
    from veles_tpu.publishing import PDFBackend, Publisher

    sw = _train_mlp(cpu_device, epochs=1)
    publisher = Publisher(sw, backends=[PDFBackend(str(tmp_path))])
    publisher.run()
    pdf = tmp_path / "report.pdf"
    assert pdf.exists()
    head = pdf.read_bytes()[:5]
    assert head == b"%PDF-"
    assert pdf.stat().st_size > 1000


# -------------------------------------------------------------- forge


def test_forge_upload_token_auth(tmp_path):
    import urllib.error

    from veles_tpu.forge import ForgeServer, list_packages, upload

    server = ForgeServer(str(tmp_path / "store"), upload_token="tok123")
    server.start_background()
    url = "http://127.0.0.1:%d" % server.port
    pkg = tmp_path / "p.tar"
    pkg.write_bytes(b"payload")
    try:
        # no token -> 401, nothing stored
        with pytest.raises(urllib.error.HTTPError) as err:
            upload(url, "pkg", "1.0.0", str(pkg), token="")
        assert err.value.code == 401
        # wrong token -> 401
        with pytest.raises(urllib.error.HTTPError) as err:
            upload(url, "pkg", "1.0.0", str(pkg), token="nope")
        assert err.value.code == 401
        assert list_packages(url) == []
        # right token -> stored
        upload(url, "pkg", "1.0.0", str(pkg), token="tok123")
        assert len(list_packages(url)) == 1
    finally:
        server.stop()


# ----------------------------------------------------- snapshot db sink


def test_snapshot_sqlite_sink(tmp_path, cpu_device):
    from tests.test_native import _train_mlp
    from veles_tpu.snapshotter import Snapshotter

    sw = _train_mlp(cpu_device, epochs=1)
    db = str(tmp_path / "snapshots.sqlite")
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="db",
                       interval=1, time_interval=0, db_path=db)
    snap.run()
    snap.run()
    rows = sqlite3.connect(db).execute(
        "SELECT prefix, workflow, destination, bytes, best_metric "
        "FROM snapshots").fetchall()
    assert len(rows) == 2
    prefix, workflow_name, destination, nbytes, metric = rows[0]
    assert prefix == "db" and "StandardWorkflow" in workflow_name
    assert destination.startswith(str(tmp_path))
    assert nbytes > 0
    assert metric is not None


# ---------------------------------------------------------------- hdfs


class _FakeWebHdfs(http.server.BaseHTTPRequestHandler):
    """Speaks just enough WebHDFS v1 for the loader."""

    files = {
        "/data/a.txt": b"0.1 0.2 0\n0.3 0.4 1\n",
        "/data/b.txt": b"0.5 0.6 2\n0.7 0.8 0\n0.9 1.0 1\n",
    }

    def log_message(self, *args):
        pass

    def do_GET(self):
        from urllib.parse import parse_qs, urlparse
        parsed = urlparse(self.path)
        op = parse_qs(parsed.query).get("op", [""])[0]
        path = parsed.path[len("/webhdfs/v1"):]
        if op == "LISTSTATUS":
            statuses = [
                {"pathSuffix": name.rsplit("/", 1)[1], "type": "FILE",
                 "length": len(data)}
                for name, data in sorted(self.files.items())
                if name.startswith(path + "/")]
            body = json.dumps(
                {"FileStatuses": {"FileStatus": statuses}}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)
        elif op == "OPEN" and path in self.files:
            self.send_response(200)
            self.end_headers()
            self.wfile.write(self.files[path])
        else:
            self.send_response(404)
            self.end_headers()


def test_hdfs_text_loader(cpu_device):
    from veles_tpu.loader import HdfsTextLoader

    httpd = http.server.HTTPServer(("127.0.0.1", 0), _FakeWebHdfs)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        wf = DummyWorkflow()
        loader = HdfsTextLoader(
            wf.workflow,
            hdfs_url="http://127.0.0.1:%d" % httpd.server_port,
            hdfs_path="/data", suffix=".txt", validation_ratio=0.4,
            minibatch_size=2, prng=RandomGenerator("hdfs", seed=1))
        loader.initialize(device=cpu_device)
        assert loader.class_lengths[1] == 2   # 40% of 5
        assert loader.class_lengths[2] == 3
        assert loader.shape == (2,)
        loader.original_data.map_read()
        assert loader.original_data.mem.shape == (5, 2)
        assert sorted(loader.labels_mapping) == [0, 1, 2]
    finally:
        httpd.shutdown()


# --------------------------------------------------------------- audio


def _write_wav(path, freq, rate=8000, seconds=0.5):
    t = numpy.arange(int(rate * seconds)) / rate
    tone = (numpy.sin(2 * numpy.pi * freq * t) * 0.5 *
            32767).astype(numpy.int16)
    with wave.open(str(path), "wb") as wav:
        wav.setnchannels(1)
        wav.setsampwidth(2)
        wav.setframerate(rate)
        wav.writeframes(tone.tobytes())


def test_audio_loader_real_wavs(tmp_path, cpu_device):
    from veles_tpu.loader import AudioFileLoader
    from veles_tpu.loader.audio import read_audio

    for cls, freq in (("low", 200), ("high", 1200)):
        cdir = tmp_path / "train" / cls
        cdir.mkdir(parents=True)
        for i in range(2):
            _write_wav(cdir / ("t%d.wav" % i), freq + i * 10)

    data, rate = read_audio(
        str(tmp_path / "train" / "low" / "t0.wav"))
    assert rate == 8000 and abs(float(numpy.abs(data).max()) - 0.5) < 0.01

    wf = DummyWorkflow()
    loader = AudioFileLoader(
        wf.workflow, train_dir=str(tmp_path / "train"),
        window_frames=1024, minibatch_size=4,
        prng=RandomGenerator("audio", seed=1))
    loader.initialize(device=cpu_device)
    # 4000 frames per file, stride 1024 -> 3 windows * 4 files
    assert loader.class_lengths[2] == 12
    assert loader.shape == (1024,)
    assert sorted(loader.labels_mapping) == ["high", "low"]


# --------------------------------------------------------- confluence


class _FakeConfluence(http.server.BaseHTTPRequestHandler):
    """Mock of the three Confluence REST endpoints the backend speaks:
    content search by title, page create/update, attachment upload."""

    pages = {}        # id -> {title, space, body, version}
    attachments = {}  # id -> [filenames]
    next_id = [1000]
    auth = []         # records Authorization headers seen

    def log_message(self, *args):
        pass

    def _json(self, payload, code=200):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from urllib.parse import parse_qs, urlparse
        self.auth.append(self.headers.get("Authorization"))
        q = parse_qs(urlparse(self.path).query)
        title = q.get("title", [""])[0]
        hits = [
            {"id": pid, "title": p["title"],
             "version": {"number": p["version"]}}
            for pid, p in self.pages.items() if p["title"] == title]
        self._json({"results": hits})

    def do_POST(self):
        self.auth.append(self.headers.get("Authorization"))
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        if self.path.endswith("/child/attachment"):
            pid = self.path.split("/")[-3]
            fname = raw.split(b'filename="', 1)[1].split(b'"', 1)[0]
            self.attachments.setdefault(pid, []).append(fname.decode())
            self._json({"results": [{"title": fname.decode()}]})
            return
        payload = json.loads(raw)
        pid = str(self.next_id[0])
        self.next_id[0] += 1
        self.pages[pid] = {
            "title": payload["title"],
            "space": payload["space"]["key"],
            "body": payload["body"]["storage"]["value"],
            "version": 1}
        self._json({"id": pid})

    def do_PUT(self):
        self.auth.append(self.headers.get("Authorization"))
        length = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(length))
        pid = self.path.rsplit("/", 1)[1]
        self.pages[pid].update(
            body=payload["body"]["storage"]["value"],
            version=payload["version"]["number"])
        self._json({"id": pid})


def test_confluence_publishing_backend(tmp_path, cpu_device):
    from tests.test_native import _train_mlp
    from veles_tpu.publishing import ConfluenceBackend, Publisher

    _FakeConfluence.pages.clear()
    _FakeConfluence.attachments.clear()
    server = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), _FakeConfluence)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        sw = _train_mlp(cpu_device, epochs=1)
        backend = ConfluenceBackend(base, space="ML", token="sekret")
        publisher = Publisher(sw, backends=[backend])
        publisher.run()
        assert backend.url.startswith(base + "/pages/")
        pages = list(_FakeConfluence.pages.values())
        assert len(pages) == 1
        page = pages[0]
        assert page["space"] == "ML"
        assert "<h2>Metrics</h2>" in page["body"]
        assert "Unit run times" in page["body"]
        pid = next(iter(_FakeConfluence.pages))
        assert "workflow.dot" in _FakeConfluence.attachments[pid]
        assert all(a == "Bearer sekret" for a in _FakeConfluence.auth)

        # same name again: title de-duplicates like the reference
        backend2 = ConfluenceBackend(base, space="ML", token="sekret")
        Publisher(sw, backends=[backend2]).run()
        titles = sorted(p["title"]
                        for p in _FakeConfluence.pages.values())
        assert titles[1].endswith("(1)")

        # explicit page: updates in place with a version bump
        backend3 = ConfluenceBackend(base, space="ML", token="sekret",
                                     page=page["title"])
        Publisher(sw, backends=[backend3]).run()
        assert _FakeConfluence.pages[pid]["version"] == 2
    finally:
        server.shutdown()
