"""Multi-tenant QoS (veles_tpu/serve/qos.py, docs/serving.md
"Multi-tenant QoS"): token-bucket quota math including burst/refill
edges, class-ordered shedding under a full queue with the
interactive-starves-last invariant, deterministic seeded per-class
``retry_after`` jitter, per-class hedge-budget exhaustion that routes
normally (never fails a request), wire-level tenant/class labels with
per-tenant quota rejection at the binary transport, tenant metrics in
``serve_snapshot``, and the fleet canary promote/auto-rollback e2e
over in-process socketpair hosts with the 0-new-compiles swap receipt
and mirrored traffic excluded from the served counters."""

import socket
import threading
import time

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, BinaryTransportClient, BinaryTransportServer,
    ContinuousBatcher, FleetRouter, HedgeBudget, RetryJitter,
    ServeOverload, TenantQuota, normalize_class, parse_quota_spec,
    serve_snapshot)
from veles_tpu.serve.freshness import (
    FleetCanaryController, LocalHostControl)
from veles_tpu.serve.qos import TokenBucket, class_rank
from tests.test_serve import _mlp_spec
from tests.test_serve_fleet import _Hosts

pytestmark = [pytest.mark.serve, pytest.mark.qos]


def _counter(name):
    return registry.counter(name).value


class _Clock(object):
    """Injectable deterministic clock for the bucket math."""

    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


# -- token-bucket quota math --------------------------------------------------


def test_token_bucket_burst_and_refill_edges():
    clock = _Clock()
    bucket = TokenBucket(rate=2.0, burst=4.0, clock=clock)
    # starts full at burst
    assert bucket.tokens == 4.0
    for _ in range(4):
        assert bucket.try_take()
    assert not bucket.try_take(), "empty bucket must reject"
    # refill accrues at rate, capped at burst
    clock.now += 1.0
    assert bucket.tokens == pytest.approx(2.0)
    clock.now += 100.0
    assert bucket.tokens == pytest.approx(4.0), "refill must cap at burst"
    # time_until: deficit / rate, 0 when available, inf when impossible
    assert bucket.time_until(3.0) == 0.0
    assert bucket.try_take(4.0)
    assert bucket.time_until(3.0) == pytest.approx(1.5)
    assert bucket.time_until(100.0) == float("inf"), \
        "a demand above burst can never be granted"


def test_token_bucket_zero_rate_never_refills():
    clock = _Clock()
    bucket = TokenBucket(rate=0.0, burst=2.0, clock=clock)
    assert bucket.try_take() and bucket.try_take()
    clock.now += 1e6
    assert not bucket.try_take(), "rate<=0 must never refill"
    assert bucket.time_until() == float("inf")


def test_parse_quota_spec_and_tenant_quota():
    quotas = parse_quota_spec("acme=100:200,free_tier=5,*=50")
    assert quotas == {"acme": (100.0, 200.0), "free_tier": (5.0, None),
                      "*": (50.0, None)}
    with pytest.raises(ValueError):
        parse_quota_spec("missing_equals")
    clock = _Clock()
    quota = TenantQuota({"tiny": (0.0, 2.0)}, clock=clock)
    # unlisted tenants without a '*' default are UNLIMITED: quota is
    # opt-in, legacy traffic is never rejected by nobody's config
    for _ in range(100):
        assert quota.admit("anyone") is None
        assert quota.admit(None) is None
    # the listed tenant gets exactly its burst, then a wait hint
    assert quota.admit("tiny") is None
    assert quota.admit("tiny") is None
    wait = quota.admit("tiny")
    assert wait is not None and wait > 0


def test_tenant_quota_default_and_anonymous_bucket():
    clock = _Clock()
    quota = TenantQuota({"*": (0.0, 1.0)}, clock=clock)
    # each tenant gets its OWN default bucket...
    assert quota.admit("a") is None
    assert quota.admit("b") is None
    assert quota.admit("a") is not None
    # ...while all anonymous traffic shares ONE bucket
    assert quota.admit(None) is None
    assert quota.admit(None) is not None


def test_normalize_class_and_rank():
    assert normalize_class(None) == "batch"
    assert normalize_class("INTERACTIVE") == "interactive"
    assert normalize_class("best-effort") == "best_effort"
    assert normalize_class("no_such_class") == "batch"
    assert class_rank("best_effort") < class_rank("batch") < \
        class_rank("interactive")


def test_retry_jitter_distinct_and_deterministic():
    jitter = RetryJitter(seed=7, spread=0.5)
    a = jitter.apply(1.0, "interactive")
    b = jitter.apply(1.0, "interactive")
    # two clients shed with the same rejection must not re-stampede at
    # the same instant (the satellite contract)
    assert a != b
    for v in (a, b):
        assert 1.0 <= v <= 1.5
    # per-class counters are independent streams
    c = jitter.apply(1.0, "batch")
    assert c != a
    # same seed + same rejection sequence = same jitters (replayable)
    replay = RetryJitter(seed=7, spread=0.5)
    assert replay.apply(1.0, "interactive") == a
    assert replay.apply(1.0, "interactive") == b


# -- class-ordered shedding under a full queue --------------------------------


class _GateDevice(object):
    def put(self, x):
        return numpy.asarray(x)


class _GateEngine(object):
    """Duck engine whose run() blocks on a gate Event: deterministic
    queue pressure, no jax in the loop."""

    dtype = numpy.float32
    sample_shape = (4,)
    max_batch = 1
    digest = "gate"

    def __init__(self):
        self.device = _GateDevice()
        self.gate = threading.Event()

    def rung_for(self, n, cap=None):
        return 1

    def run(self, x_dev, rung):
        assert self.gate.wait(30.0), "test gate never opened"
        return numpy.asarray(x_dev) * 2.0


def _occupy_worker(batcher):
    """Park the worker inside run() so the queue holds what we put."""
    head = batcher.submit(numpy.zeros(4, numpy.float32),
                          slo_class="best_effort")
    deadline = time.monotonic() + 10.0
    while batcher._q.qsize() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert batcher._q.qsize() == 0, "worker never picked up the head"
    return head


def test_class_ordered_shedding_interactive_starves_last():
    engine = _GateEngine()
    batcher = ContinuousBatcher(engine, max_delay_s=0.0, max_queue=4,
                                retry_jitter=RetryJitter(seed=3))
    batcher.start()
    shed_be = _counter("serve.tenant.best_effort.shed")
    shed_batch = _counter("serve.tenant.batch.shed")
    shed_int = _counter("serve.tenant.interactive.shed")
    try:
        head = _occupy_worker(batcher)
        x = numpy.arange(4, dtype=numpy.float32)
        b1 = batcher.submit(x, slo_class="best_effort")
        b2 = batcher.submit(x, slo_class="best_effort")
        n1 = batcher.submit(x, slo_class="batch")
        n2 = batcher.submit(x)  # un-labelled legacy = batch
        # queue is at max_queue=4: interactive admissions evict
        # best_effort first, then batch — in that order
        i1 = batcher.submit(x, slo_class="interactive")
        assert b1.cancelled and isinstance(b1.error, ServeOverload)
        assert "eviction" in str(b1.error)
        i2 = batcher.submit(x, slo_class="interactive")
        assert b2.cancelled and isinstance(b2.error, ServeOverload)
        # victims of the same class get DISTINCT jittered retry_after
        assert b1.error.retry_after != b2.error.retry_after
        assert not n1.cancelled and not n2.cancelled
        i3 = batcher.submit(x, slo_class="interactive")
        assert n1.cancelled, "with best_effort drained, batch is next"
        # an incoming batch request finds nothing STRICTLY lower
        # pending: it is shed itself, the queued batch one survives
        with pytest.raises(ServeOverload):
            batcher.submit(x, slo_class="batch")
        assert not n2.cancelled
        with pytest.raises(ServeOverload):
            batcher.submit(x, slo_class="best_effort")
        # interactive starves LAST: nothing below it remains, so an
        # interactive admission into interactive saturation sheds the
        # INCOMING interactive request — never a queued one
        n2.cancelled = True  # leave only interactive work pending
        with pytest.raises(ServeOverload):
            batcher.submit(x, slo_class="interactive")
        for req in (i1, i2, i3):
            assert not req.cancelled
        # open the gate: every surviving request is served intact
        engine.gate.set()
        for req in (head, i1, i2, i3):
            assert req.done.wait(10.0)
            assert req.error is None
            assert (req.result == req.sample * 2.0).all()
    finally:
        engine.gate.set()
        batcher.stop()
    assert _counter("serve.tenant.best_effort.shed") - shed_be == 3
    assert _counter("serve.tenant.batch.shed") - shed_batch == 2
    assert _counter("serve.tenant.interactive.shed") - shed_int == 1


@pytest.mark.chaos
def test_tenant_flood_chaos_is_shed_as_best_effort():
    """``serve.tenant.flood`` storms the queue with synthetic
    best_effort load; an interactive admission evicts flood rows, and
    every shed the storm causes lands on best_effort."""
    engine = _GateEngine()
    batcher = ContinuousBatcher(engine, max_delay_s=0.0, max_queue=4)
    batcher.start()
    shed_int = _counter("serve.tenant.interactive.shed")
    try:
        head = _occupy_worker(batcher)
        chaos.install(chaos.FaultPlan(seed=5).add(
            "serve.tenant.flood", "storm", nth=1, param=8))
        x = numpy.ones(4, numpy.float32)
        req = batcher.submit(x, slo_class="interactive")
        assert not req.cancelled
        engine.gate.set()
        assert req.done.wait(10.0) and req.error is None
        assert head.done.wait(10.0)
    finally:
        chaos.uninstall()
        engine.gate.set()
        batcher.stop()
    assert _counter("serve.tenant.best_effort.shed") > 0
    assert _counter("serve.tenant.interactive.shed") == shed_int


# -- tenant metrics in serve_snapshot ----------------------------------------


def test_tenant_metrics_in_serve_snapshot_exclude_shadow():
    plans, params = _mlp_spec(seed=9)
    engine = AOTEngine(plans, params, (16,), ladder=(8, 32),
                       device=Device(backend="cpu"))
    engine.compile()
    batcher = ContinuousBatcher(engine, max_delay_s=0.001).start()
    served_int = _counter("serve.tenant.interactive.requests")
    served_batch = _counter("serve.tenant.batch.requests")
    try:
        rng = numpy.random.RandomState(0)
        x = rng.rand(16).astype(numpy.float32)
        ref = engine.infer(x[None])[0]
        assert (batcher.infer(x) == ref).all()  # legacy -> batch
        out = batcher.submit(x, slo_class="interactive")
        assert out.done.wait(10.0) and (out.result == ref).all()
        # shadow/mirror traffic NEVER lands in the tenant counters
        shadow = batcher.submit_shadow(x)
        assert shadow.done.wait(10.0)
        assert (shadow.result == ref).all()
    finally:
        batcher.stop()
    assert _counter("serve.tenant.interactive.requests") \
        - served_int == 1
    assert _counter("serve.tenant.batch.requests") - served_batch == 1
    block = serve_snapshot()
    tenants = block["tenants"]
    for cls in ("interactive", "batch"):
        assert tenants[cls]["requests"] >= 1
        assert tenants[cls]["latency_ms"]["p99"] >= \
            tenants[cls]["latency_ms"]["p50"] >= 0


# -- wire-level labels + quota at the binary transport ------------------------


@pytest.mark.transport
def test_transport_tenant_quota_and_class_labels():
    plans, params = _mlp_spec(seed=2)
    engine = AOTEngine(plans, params, (16,), ladder=(8, 32),
                       device=Device(backend="cpu"))
    engine.compile()
    batcher = ContinuousBatcher(engine, max_delay_s=0.001).start()
    quota = TenantQuota({"metered": (0.0, 2.0)})
    server = BinaryTransportServer(batcher, port=None, quota=quota,
                                   retry_jitter=RetryJitter(seed=1))
    server.start_background()
    clients = []

    def connect(**kwargs):
        ours, theirs = socket.socketpair()
        server.serve_socket(ours)
        client = BinaryTransportClient(sock=theirs, shm=False, **kwargs)
        clients.append(client)
        return client

    try:
        rng = numpy.random.RandomState(1)
        x = rng.rand(16).astype(numpy.float32)
        ref = engine.infer(x[None])
        # un-labelled legacy client: served unchanged (class batch)
        legacy = connect()
        assert (legacy.infer(x) == ref).all()
        # hello-labelled connection; burst of 2, then 503 + jittered
        # retry_after — distinct across consecutive rejections
        metered = connect(tenant="metered", slo_class="interactive")
        assert (metered.infer(x) == ref).all()
        assert (metered.infer(x) == ref).all()
        with pytest.raises(ServeOverload) as exc1:
            metered.infer(x)
        with pytest.raises(ServeOverload) as exc2:
            metered.infer(x)
        assert exc1.value.retry_after > 0
        assert exc1.value.retry_after != exc2.value.retry_after
        # per-frame tenant override rides one frame only: the legacy
        # connection charged as "metered" is rejected too...
        with pytest.raises(ServeOverload):
            legacy.infer(x, tenant="metered")
        # ...and reverts to its (unlimited) connection default after
        assert (legacy.infer(x) == ref).all()
    finally:
        for client in clients:
            client.close()
        server.stop()
        batcher.stop()


# -- per-class hedge budgets in the fleet router ------------------------------


@pytest.mark.fleet
@pytest.mark.chaos
def test_hedge_budget_exhaustion_routes_normally_never_fails():
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    budget = HedgeBudget({cls: (0.0, 0.0) for cls in
                          ("interactive", "batch", "best_effort")})
    router = FleetRouter(hedge_factor=1.2, hedge_floor_s=0.01,
                         hedge_tick_s=0.01, hedge_warmup=2,
                         hedge_budget=budget).start()
    for i in range(2):
        hosts.connect(router, i)
    try:
        rng = numpy.random.RandomState(4)
        x = rng.rand(4, 16).astype(numpy.float32)
        ref = hosts.entries[0][0].infer(x)
        for i in range(router.hedge_warmup):  # arm the watchdog
            router.infer(x[i % 4], timeout=15.0)
        fired = _counter("serve.hedge.fired")
        exhausted = _counter("serve.hedge.budget_exhausted")
        chaos.install(chaos.FaultPlan(seed=1).add(
            "serve.host.stall", "stall", times=2, param=0.4))
        try:
            # stalled requests age past the hedge threshold; the
            # zero-token budget denies every hedge — the request rides
            # out the stall on its primary copy and still completes
            for i in range(4):
                out = router.infer(x[i], timeout=15.0,
                                   slo_class="interactive")
                assert (out == ref[i]).all()
        finally:
            chaos.uninstall()
        assert _counter("serve.hedge.fired") == fired, \
            "an exhausted budget must suppress the hedge entirely"
        assert _counter("serve.hedge.budget_exhausted") > exhausted
    finally:
        router.stop()
        hosts.stop()


@pytest.mark.fleet
@pytest.mark.chaos
def test_fleet_front_class_aware_inflight_bound():
    """Past ``max_inflight`` the fleet front evicts a STRICTLY lower
    class (shed on the victim), so the interactive request proceeds."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge=False, max_inflight=2).start()
    for i in range(2):
        hosts.connect(router, i)
    try:
        rng = numpy.random.RandomState(6)
        x = rng.rand(16).astype(numpy.float32)
        ref = hosts.entries[0][0].infer(x[None])[0]
        shed_be = _counter("serve.tenant.best_effort.shed")
        chaos.install(chaos.FaultPlan(seed=2).add(
            "serve.host.stall", "stall", times=2, param=0.6))
        try:
            victims = [router.submit(x, slo_class="best_effort")
                       for _ in range(2)]
            out = router.infer(x, timeout=15.0,
                               slo_class="interactive")
            assert (out == ref).all()
        finally:
            chaos.uninstall()
        evicted = [v for v in victims
                   if isinstance(v.error, ServeOverload)]
        assert len(evicted) == 1, \
            "exactly one lower-class victim makes room"
        assert _counter("serve.tenant.best_effort.shed") - shed_be == 1
        # the surviving best_effort entry still completes
        survivor = [v for v in victims if v not in evicted][0]
        assert survivor.done.wait(15.0)
        if survivor.error is None:
            assert (survivor.result == ref).all()
    finally:
        router.stop()
        hosts.stop()


# -- fleet canary: promote / auto-rollback e2e --------------------------------


class _Traffic(object):
    """Closed-loop interactive client thread driving the fleet front;
    counts failures and checks bit-identity against the reference."""

    def __init__(self, router, samples, reference):
        self.router = router
        self.samples = samples
        self.reference = reference
        self.served = 0
        self.failed = 0
        self.mismatched = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="qos-traffic")

    def _loop(self):
        i = 0
        while not self._stop.is_set():
            k = i % len(self.samples)
            i += 1
            try:
                out = self.router.infer(self.samples[k], timeout=15.0,
                                        slo_class="interactive")
            except Exception:
                self.failed += 1
                continue
            self.served += 1
            if not (out == self.reference[k]).all():
                self.mismatched += 1

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=30)


@pytest.mark.fleet
@pytest.mark.freshness
def test_fleet_canary_promotes_good_and_rolls_back_poison():
    plans, good = _mlp_spec(seed=3)
    # the poison: same shapes/digest (it MUST pass the structural swap
    # gate — the canary exists for what static checks cannot see), but
    # the output classes permuted, so mirrored evidence diverges
    poison = [dict(p) for p in good]
    poison[1] = dict(poison[1],
                     weights=numpy.ascontiguousarray(
                         good[1]["weights"][:, ::-1]),
                     bias=numpy.ascontiguousarray(good[1]["bias"][::-1]))
    hosts = _Hosts(2, plans, good)
    router = FleetRouter(hedge=False).start()
    for i in range(2):
        hosts.connect(router, i)
    host_ids = sorted(router.snapshot()["hosts"])
    controls = {hid: LocalHostControl(hosts.entries[i][1])
                for i, hid in enumerate(host_ids)}
    controller = FleetCanaryController(
        router, controls, mirror_fraction=1.0, min_mirrors=4,
        divergence_limit=1e-4, breach_budget=2, verdict_timeout_s=30.0,
        seed=7)
    rng = numpy.random.RandomState(8)
    x = rng.rand(6, 16).astype(numpy.float32)
    reference = hosts.entries[0][0].infer(x)
    canary_host = host_ids[0]
    mirrors = _counter("serve.fleet.canary.mirrors")
    try:
        # -- promote: a good candidate (same values -> divergence 0)
        with _Traffic(router, x, reference) as traffic:
            receipt = controller.run(good, canary_host)
        assert receipt["verdict"] == "promote"
        assert receipt["new_compiles"] == 0, \
            "canary staging is swap-only: 0 new compiles"
        assert receipt["mirrors"] >= 4
        assert receipt["max_divergence"] == 0.0
        assert traffic.failed == 0, \
            "0 failed interactive requests through a promote cycle"
        assert traffic.mismatched == 0 and traffic.served > 0
        assert _counter("serve.fleet.canary.mirrors") > mirrors
        # -- rollback: the class-permuted poison diverges on real
        # mirrored evidence and the whole fleet auto-rolls back
        with _Traffic(router, x, reference) as traffic:
            receipt = controller.run(poison, canary_host)
        assert receipt["verdict"] == "rolled_back"
        assert receipt["new_compiles"] == 0
        assert "divergence" in receipt["reason"]
        assert traffic.failed == 0, \
            "0 failed interactive requests through a rollback cycle"
        assert traffic.mismatched == 0, \
            "the poison must never answer a primary request"
        # the fleet is whole again and still serves the good model
        snap = router.snapshot()
        assert snap["hosts_live"] == 2 and snap["canary"] is None
        for i in range(6):
            assert (router.infer(x[i], timeout=15.0)
                    == reference[i]).all()
        assert _counter("serve.fleet.canary.promotions") >= 1
        assert _counter("serve.fleet.canary.rollbacks") >= 1
    finally:
        router.stop()
        hosts.stop()


@pytest.mark.fleet
def test_canary_poison_never_served_and_mirrors_not_counted():
    """While a poison is staged on the canary host, primary traffic is
    bit-identical to the good model, and mirrored shadow frames are
    excluded from the tenant served counters."""
    plans, good = _mlp_spec(seed=3)
    poison = [dict(p) for p in good]
    poison[0] = dict(poison[0], weights=numpy.ascontiguousarray(
        good[0]["weights"] * 50.0))
    hosts = _Hosts(2, plans, good)
    router = FleetRouter(hedge=False).start()
    for i in range(2):
        hosts.connect(router, i)
    host_ids = sorted(router.snapshot()["hosts"])
    controls = {hid: LocalHostControl(hosts.entries[i][1])
                for i, hid in enumerate(host_ids)}
    rng = numpy.random.RandomState(9)
    x = rng.rand(4, 16).astype(numpy.float32)
    reference = hosts.entries[0][0].infer(x)
    try:
        pairs = []
        slice_ = router.begin_canary_slice(
            host_ids[0], fraction=1.0, seed=1,
            on_pair=lambda *pair: pairs.append(pair))
        controls[host_ids[0]].stage(poison)
        slice_.armed = True
        served_int = _counter("serve.tenant.interactive.requests")
        n = 24
        for i in range(n):
            out = router.infer(x[i % 4], timeout=15.0,
                               slo_class="interactive")
            assert (out == reference[i % 4]).all(), \
                "primary traffic must never see the staged poison"
        deadline = time.monotonic() + 10.0
        while len(pairs) < n and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(pairs) == n, "fraction=1.0 mirrors every single"
        # the shadow leg really ran the poison (evidence is real)...
        assert any(not numpy.array_equal(p, s) for p, s, _, _ in pairs)
        # ...but mirrors are EXCLUDED from tenant served accounting:
        # only the n primary requests count
        assert _counter("serve.tenant.interactive.requests") \
            - served_int == n
        controls[host_ids[0]].revert()
        stats = router.end_canary_slice()
        assert stats["mirrored"] == n and stats["pairs"] == n
        assert stats["shadow_errors"] == 0
        for i in range(4):
            assert (router.infer(x[i], timeout=15.0)
                    == reference[i]).all()
    finally:
        router.stop()
        hosts.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_qos_soak_receipt(tmp_path):
    """Acceptance (ISSUE 17): scripts/qos_soak.py floods real
    subprocess hosts with a 3x best-effort storm under seeded stalls —
    interactive p99 within the SLO budget, 0 interactive sheds, every
    shed attributed to best_effort — then the fleet canary promotes a
    good snapshot and rolls back a class-permuted poison with 0 failed
    interactive requests and 0 new compiles.  The committed QOS.json
    is this driver at full size."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "QOS.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "qos_soak.py"),
         "--out", str(out), "--fast"],
        cwd=repo, timeout=900, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    receipt = json.loads(out.read_text())
    assert receipt["passed"] is True
    assert receipt["flood"]["interactive_sheds"] == 0
    assert receipt["flood"]["counters"][
        "serve.tenant.interactive.shed"] == 0
    assert receipt["canary"]["promote"]["verdict"] == "promote"
    assert receipt["canary"]["rollback"]["verdict"] == "rolled_back"
    assert receipt["canary"]["rollback"]["new_compiles"] == 0
    assert receipt["canary"]["interactive_failed"] == 0
