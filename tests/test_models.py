"""Model layer tests: forward/backward math vs jax.grad oracle, and the
standard workflow end-to-end on synthetic classification data
(the MNIST-784 shape in miniature; SURVEY.md section 7 minimum slice)."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader import FullBatchLoader
from veles_tpu.models.all2all import (
    All2All, All2AllTanh, All2AllSoftmax)
from veles_tpu.models.evaluator import EvaluatorSoftmax
from veles_tpu.models.gd import GradientDescent, GDTanh
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator


# ----------------------------------------------------------- math vs autodiff

def test_gd_matches_jax_autodiff():
    """One GD step must equal -lr * dL/dW from jax.grad for a quadratic
    surrogate loss L = sum(y * err_output_const)."""
    import jax
    import jax.numpy as jnp

    rng = numpy.random.RandomState(0)
    x = rng.randn(8, 5).astype(numpy.float32)
    W = rng.randn(5, 3).astype(numpy.float32)
    b = rng.randn(3).astype(numpy.float32)
    err_const = rng.randn(8, 3).astype(numpy.float32)

    def loss(params):
        y = All2AllTanh.apply(params, x)
        return jnp.sum(y * err_const)

    grads = jax.grad(loss)({"weights": W, "bias": b})

    y = numpy.asarray(All2AllTanh.apply({"weights": W, "bias": b}, x))
    state = {"weights": W, "bias": b,
             "accum_weights": numpy.zeros_like(W),
             "accum_bias": numpy.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    err_input, new_state = GDTanh.backward(
        state, hyper, x, y, err_const, solver="momentum",
        include_bias=True, need_err_input=True)

    numpy.testing.assert_allclose(
        numpy.asarray(new_state["weights"]),
        W - 0.1 * numpy.asarray(grads["weights"]), rtol=1e-4, atol=1e-5)
    numpy.testing.assert_allclose(
        numpy.asarray(new_state["bias"]),
        b - 0.1 * numpy.asarray(grads["bias"]), rtol=1e-4, atol=1e-5)

    # err_input = dL/dx
    def loss_x(xv):
        y2 = All2AllTanh.apply({"weights": W, "bias": b}, xv)
        return jnp.sum(y2 * err_const)
    gx = numpy.asarray(jax.grad(loss_x)(x))
    numpy.testing.assert_allclose(
        numpy.asarray(err_input), gx, rtol=1e-4, atol=1e-5)


def test_softmax_ce_gradient_matches_autodiff():
    """evaluator err_output chained through GDSoftmax equals the autodiff
    gradient of mean cross-entropy wrt the pre-softmax logits."""
    import jax
    import jax.numpy as jnp

    rng = numpy.random.RandomState(1)
    x = rng.randn(6, 4).astype(numpy.float32)
    W = rng.randn(4, 3).astype(numpy.float32)
    b = numpy.zeros(3, numpy.float32)
    labels = rng.randint(0, 3, 6).astype(numpy.int32)

    def ce(params):
        z = x @ params["weights"] + params["bias"]
        logp = jax.nn.log_softmax(z)
        return -jnp.mean(logp[jnp.arange(6), labels])

    grads = jax.grad(ce)({"weights": W, "bias": b})

    probs = numpy.asarray(
        All2AllSoftmax.apply({"weights": W, "bias": b}, x))
    err, n_err, conf = EvaluatorSoftmax.compute(
        probs, labels, numpy.float32(6), 3)
    state = {"weights": W, "bias": b,
             "accum_weights": numpy.zeros_like(W),
             "accum_bias": numpy.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 1.0, "learning_rate_bias": 1.0,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    from veles_tpu.models.gd import GDSoftmax
    _, new_state = GDSoftmax.backward(
        state, hyper, x, probs, numpy.asarray(err), solver="momentum",
        include_bias=True, need_err_input=False)
    dW = W - numpy.asarray(new_state["weights"])
    numpy.testing.assert_allclose(
        dW, numpy.asarray(grads["weights"]), rtol=1e-4, atol=1e-5)


def test_solver_updates():
    import jax.numpy as jnp
    from veles_tpu.models.nn_units import GradientDescentBase as G
    p = jnp.ones(4)
    g = jnp.full(4, 2.0)
    acc = jnp.zeros(4)
    # momentum: v = 0.9*0 + 0.1*2 = 0.2
    new_p, v, _ = G.solver_update("momentum", p, g, acc, None, 0.1, 0.9,
                                  0.95, 1e-6)
    numpy.testing.assert_allclose(numpy.asarray(new_p), 0.8, rtol=1e-6)
    # adagrad: a = 4; p - 0.1*2/sqrt(4) = 1 - 0.1 = 0.9
    new_p, a, _ = G.solver_update("adagrad", p, g, acc, None, 0.1, 0.0,
                                  0.95, 1e-6)
    numpy.testing.assert_allclose(numpy.asarray(new_p), 0.9, rtol=1e-4)
    # adadelta smoke: moves in -grad direction
    new_p, a, a2 = G.solver_update("adadelta", p, g, acc, acc, 1.0, 0.0,
                                   0.95, 1e-6)
    assert (numpy.asarray(new_p) < 1.0).all()


# ------------------------------------------------------------- end-to-end

class BlobsLoader(FullBatchLoader):
    """Deterministic 4-class Gaussian blobs, learnable to ~0 error."""

    def load_data(self):
        self.class_lengths[:] = [0, 64, 256]
        self._calc_class_end_offsets()
        self.create_originals((16,))
        rng = numpy.random.RandomState(99)
        centers = rng.randn(4, 16) * 2.0
        for i in range(self.total_samples):
            label = i % 4
            self.original_data.mem[i] = (
                centers[label] + rng.randn(16) * 0.3)
            self.original_labels[i] = label


def build_mnist_like(device, layers=None, **decision):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,  # the DummyLauncher
        layers=layers or [
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("blobs", seed=7)),
        decision_config=dict(max_epochs=10, **decision),
    )
    sw.initialize(device=device)
    return sw


def test_standard_workflow_builds_and_links(cpu_device):
    sw = build_mnist_like(cpu_device)
    assert len(sw.forwards) == 2
    assert len(sw.gds) == 2
    assert sw.forwards[0].weights.shape == (16, 32)
    assert sw.forwards[1].weights.shape == (32, 4)
    # gd shares the very same Array objects with its forward
    assert sw.gds[0].weights is sw.forwards[0].weights
    assert sw.gds[1].weights is sw.forwards[1].weights


def test_mnist_like_trains_to_low_error(cpu_device):
    sw = build_mnist_like(cpu_device)
    sw.run()
    assert bool(sw.decision.complete)
    # validation error after 10 epochs on blobs must be tiny
    assert sw.decision.epoch_metrics[1] is not None
    assert sw.decision.epoch_metrics[1] < 5.0, \
        "validation error %.2f%%" % sw.decision.epoch_metrics[1]
    assert sw.decision.epoch_metrics[2] < 5.0


def test_numpy_backend_parity(numpy_device, cpu_device):
    """Same seeds -> numpy pseudo-device and XLA path converge alike."""
    sw_np = build_mnist_like(numpy_device)
    sw_np.run()
    sw_dev = build_mnist_like(cpu_device)
    sw_dev.run()
    assert abs(sw_np.decision.epoch_metrics[1] -
               sw_dev.decision.epoch_metrics[1]) < 3.0


def test_adagrad_and_adadelta_train(cpu_device):
    for solver, lr in (("adagrad", 0.05), ("adadelta", 1.0)):
        wf = DummyWorkflow()
        sw = StandardWorkflow(
            wf.workflow,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": lr, "solver": solver},
                {"type": "softmax", "output_sample_shape": 4,
                 "learning_rate": lr, "solver": solver},
            ],
            loader_factory=lambda w: BlobsLoader(
                w, minibatch_size=64,
                prng=RandomGenerator("blobs2", seed=11)),
            decision_config=dict(max_epochs=6),
        )
        sw.initialize(device=cpu_device)
        sw.run()
        assert sw.decision.epoch_metrics[1] < 25.0, (
            solver, sw.decision.epoch_metrics[1])
