"""Hand-scheduled backward kernels (docs/kernels.md), interpret-mode
parity on CPU: the fused conv-VJP family (``ops/conv_vjp.py``), the
pool select-and-scatter backward (``ops/pool_bwd.py``), the compiler's
backward-decongestion hints (barrier chain / remat — bit-identical by
contract), and the ``VELES_PALLAS_BWD`` knob's autodiff-fallback
bit-equality.  Every test runs the kernels through the Pallas
interpreter (``JAX_PLATFORMS=cpu``), same numerics as Mosaic."""

import numpy
import pytest

pytestmark = pytest.mark.pallas

NAN = float("nan")


@pytest.fixture
def pallas_on(monkeypatch):
    """Force the hand-scheduled backward on (the CPU default is off);
    the env was read once at import, so tests flip the module flag."""
    from veles_tpu.ops import common
    monkeypatch.setattr(common, "PALLAS_BWD_ENV", "1")


@pytest.fixture
def pallas_off(monkeypatch):
    from veles_tpu.ops import common
    monkeypatch.setattr(common, "PALLAS_BWD_ENV", "0")


def _conv_reference(x, w, y, dy, activation, padding, sliding):
    """The stock formulation: activation backward (via the forward
    output, like the gd units), then jax.vjp of the pure conv."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.models.conv import Conv
    from veles_tpu.ops.conv_vjp import activation_grad

    err = activation_grad(activation, y.astype(jnp.float32),
                          dy.astype(jnp.float32)).astype(x.dtype)

    def lin(w_, x_):
        return Conv.apply({"weights": w_, "bias": None}, x_,
                          padding=padding, sliding=sliding,
                          pallas_bwd=False)

    _, vjp = jax.vjp(lin, w, x)
    gw, gx = vjp(err)
    gb = err.astype(jnp.float32).sum(axis=(0, 1, 2))
    return gx, gw.astype(jnp.float32), gb


def _max_rel(a, b):
    a = numpy.asarray(a, numpy.float64)
    b = numpy.asarray(b, numpy.float64)
    return float(numpy.abs(a - b).max() /
                 max(numpy.abs(b).max(), 1e-12))


def _conv_case(shape, co, kyx, padding, sliding, activation, dtype,
               seed=0):
    import jax.numpy as jnp

    from veles_tpu.models.conv import Conv
    from veles_tpu.ops.conv_vjp import _forward_act

    rng = numpy.random.RandomState(seed)
    n, h, w_sp, ci = shape
    ky, kx = kyx
    x = jnp.asarray(rng.randn(n, h, w_sp, ci), dtype)
    w = jnp.asarray(rng.randn(ky, kx, ci, co) * 0.1, dtype)
    z = Conv.apply({"weights": w, "bias": None}, x, padding=padding,
                   sliding=sliding, pallas_bwd=False)
    y = _forward_act(activation)(z.astype(jnp.float32)).astype(dtype)
    dy = jnp.asarray(rng.randn(*y.shape), dtype)
    return x, w, y, dy


# -- conv-VJP parity ---------------------------------------------------------


@pytest.mark.parametrize("activation,padding,sliding", [
    ("linear", (0, 0, 0, 0), (1, 1)),
    ("strict_relu", (1, 1, 1, 1), (2, 2)),
    ("relu_log", (0, 0, 0, 0), (1, 1)),
    ("tanh", (2, 1, 2, 1), (2, 3)),
    ("sigmoid", (1, 1, 1, 1), (1, 1)),
])
def test_conv_vjp_parity_f32(activation, padding, sliding):
    """Fused wgrad/bias/err vs the autodiff reference, f32 level 1
    (true-f32 products + Kahan): within the documented ~1e-6 rel band
    for the tile-parallel contraction; dgrad BIT-exact (it is the same
    lhs-dilated lax conv XLA's transpose rule emits)."""
    from veles_tpu.ops.conv_vjp import fused_conv_vjp
    import jax.numpy as jnp

    x, w, y, dy = _conv_case((2, 9, 10, 4), 8, (3, 3), padding,
                             sliding, activation, jnp.float32)
    gx, gw, gb = fused_conv_vjp(
        x, w, y, dy, activation=activation, padding=padding,
        sliding=sliding, precision_level=1)
    rgx, rgw, rgb = _conv_reference(x, w, y, dy, activation, padding,
                                    sliding)
    assert _max_rel(gw, rgw) < 1e-5
    assert _max_rel(gb, rgb) < 1e-5
    # dgrad consumes the kernel's fused err; activation backwards that
    # are exact in f32 (linear/strict_relu) stay bit-exact end to end
    if activation in ("linear", "strict_relu"):
        numpy.testing.assert_array_equal(numpy.asarray(gx),
                                         numpy.asarray(rgx))
    else:
        assert _max_rel(gx, rgx) < 1e-5


def test_conv_vjp_bit_exact_on_representable():
    """On exactly-representable operands (small integers) every f32
    product and sum is exact, so tile order cannot matter: the fused
    wgrad/bias/dgrad must be BIT-identical to autodiff."""
    import jax.numpy as jnp

    from veles_tpu.ops.conv_vjp import fused_conv_vjp

    rng = numpy.random.RandomState(3)
    x = jnp.asarray(rng.randint(-4, 5, (2, 8, 8, 3)), jnp.float32)
    w = jnp.asarray(rng.randint(-3, 4, (3, 3, 3, 8)), jnp.float32)
    y = jnp.zeros((2, 6, 6, 8), jnp.float32)  # linear epilogue: unused
    dy = jnp.asarray(rng.randint(-4, 5, (2, 6, 6, 8)), jnp.float32)
    gx, gw, gb = fused_conv_vjp(
        x, w, y, dy, activation="linear", padding=(0, 0, 0, 0),
        sliding=(1, 1), precision_level=1)
    rgx, rgw, rgb = _conv_reference(x, w, y, dy, "linear",
                                    (0, 0, 0, 0), (1, 1))
    numpy.testing.assert_array_equal(numpy.asarray(gw),
                                     numpy.asarray(rgw))
    numpy.testing.assert_array_equal(numpy.asarray(gb),
                                     numpy.asarray(rgb))
    numpy.testing.assert_array_equal(numpy.asarray(gx),
                                     numpy.asarray(rgx))


def test_conv_vjp_bf16x3_ulp_bound():
    """Level 0's bf16x3 decomposition: f32-class products (~5e-7 rel)
    plus tile-order accumulation — the documented bound is 1e-5 rel vs
    the true-f32 reference (docs/kernels.md)."""
    import jax.numpy as jnp

    from veles_tpu.ops.conv_vjp import fused_conv_vjp

    x, w, y, dy = _conv_case((2, 8, 8, 3), 16, (3, 3), (0, 0, 0, 0),
                             (1, 1), "linear", jnp.float32, seed=7)
    _, gw0, gb0 = fused_conv_vjp(
        x, w, y, dy, activation="linear", padding=(0, 0, 0, 0),
        sliding=(1, 1), precision_level=0)
    _, rgw, rgb = _conv_reference(x, w, y, dy, "linear", (0, 0, 0, 0),
                                   (1, 1))
    assert _max_rel(gw0, rgw) < 1e-5
    assert _max_rel(gb0, rgb) < 1e-5


def test_conv_vjp_bf16():
    """bf16 operands take single-pass MXU products with f32
    accumulation; parity vs autodiff is bounded by the reference's own
    bf16 output rounding (eps ~7.8e-3)."""
    import jax.numpy as jnp

    from veles_tpu.ops.conv_vjp import fused_conv_vjp

    x, w, y, dy = _conv_case((2, 8, 8, 4), 16, (3, 3), (1, 1, 1, 1),
                             (1, 1), "strict_relu", jnp.bfloat16)
    gx, gw, gb = fused_conv_vjp(
        x, w, y, dy, activation="strict_relu", padding=(1, 1, 1, 1),
        sliding=(1, 1), precision_level=1)
    rgx, rgw, rgb = _conv_reference(x, w, y, dy, "strict_relu",
                                     (1, 1, 1, 1), (1, 1))
    assert _max_rel(gw, rgw) < 1.6e-2
    assert _max_rel(gb, rgb) < 1.6e-2
    assert _max_rel(gx, rgx) < 1.6e-2


def test_conv_vjp_many_taps_falls_back():
    """Kernels past MAX_FUSED_TAPS (AlexNet's 11x11) keep the stock
    autodiff VJP — bit-identical to the reference, same call-site
    contract."""
    import jax.numpy as jnp

    from veles_tpu.ops.conv_vjp import MAX_FUSED_TAPS, fused_conv_vjp

    ky = kx = 6
    assert ky * kx > MAX_FUSED_TAPS
    x, w, y, dy = _conv_case((1, 14, 14, 2), 4, (ky, kx),
                             (0, 0, 0, 0), (2, 2), "strict_relu",
                             jnp.float32, seed=5)
    gx, gw, gb = fused_conv_vjp(
        x, w, y, dy, activation="strict_relu", padding=(0, 0, 0, 0),
        sliding=(2, 2), precision_level=0)
    rgx, rgw, rgb = _conv_reference(x, w, y, dy, "strict_relu",
                                     (0, 0, 0, 0), (2, 2))
    numpy.testing.assert_array_equal(numpy.asarray(gw),
                                     numpy.asarray(rgw))
    numpy.testing.assert_array_equal(numpy.asarray(gx),
                                     numpy.asarray(rgx))
    numpy.testing.assert_array_equal(numpy.asarray(gb),
                                     numpy.asarray(rgb))


# -- pool select-and-scatter backward ---------------------------------------


def _pool_reference(x, dy, window, sliding):
    import jax

    from veles_tpu.models.pooling import MaxPooling

    def pool(x_):
        return MaxPooling.apply({}, x_, window=window, sliding=sliding,
                                pallas_bwd=False)

    _, vjp = jax.vjp(pool, x)
    (ref,) = vjp(dy.astype(x.dtype))
    return ref


@pytest.mark.parametrize("shape,window,sliding,exact", [
    ((2, 8, 8, 3), (2, 2), (2, 2), True),     # VGG-style non-overlap
    ((2, 9, 9, 3), (3, 3), (2, 2), False),    # AlexNet overlap + ceil
    ((1, 5, 5, 2), (2, 2), (2, 2), True),     # odd input, ceil tail
    ((2, 6, 6, 130), (2, 2), (2, 2), True),   # channels past one lane
    ((1, 4, 4, 1), (4, 4), (4, 4), True),     # window == input
    ((2, 7, 7, 5), (3, 3), (1, 1), False),    # dense overlap
])
def test_pool_bwd_parity(shape, window, sliding, exact):
    """Routed scatter vs jax.vjp(reduce_window): bit-exact for
    non-overlapping windows (each input cell receives at most one
    contribution); OVERLAPPING windows agree within ~1 ULP where >= 2
    selected contributions sum into one cell in a different order
    (docs/kernels.md)."""
    import jax.numpy as jnp

    from veles_tpu.models.pooling import MaxPooling
    from veles_tpu.ops.pool_bwd import max_pool_bwd

    rng = numpy.random.RandomState(11)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    y = MaxPooling.apply({}, x, window=window, sliding=sliding,
                         pallas_bwd=False)
    dy = jnp.asarray(rng.randn(*y.shape), jnp.float32)
    out = max_pool_bwd(x, y, dy, window=window, sliding=sliding)
    ref = _pool_reference(x, dy, window, sliding)
    assert out.shape == x.shape
    if exact:
        numpy.testing.assert_array_equal(numpy.asarray(out),
                                         numpy.asarray(ref))
    else:
        numpy.testing.assert_allclose(
            numpy.asarray(out), numpy.asarray(ref), rtol=1e-6,
            atol=1e-6)


def test_pool_bwd_ties_bit_exact():
    """All-equal windows: the kernel's first-match tie-break must
    reproduce XLA's select-and-scatter routing exactly."""
    import jax.numpy as jnp

    from veles_tpu.models.pooling import MaxPooling
    from veles_tpu.ops.pool_bwd import max_pool_bwd

    rng = numpy.random.RandomState(2)
    x = jnp.ones((1, 6, 6, 2), jnp.float32)
    y = MaxPooling.apply({}, x, window=(3, 3), sliding=(2, 2),
                         pallas_bwd=False)
    dy = jnp.asarray(rng.randn(*y.shape), jnp.float32)
    out = max_pool_bwd(x, y, dy, window=(3, 3), sliding=(2, 2))
    ref = _pool_reference(x, dy, (3, 3), (2, 2))
    numpy.testing.assert_array_equal(numpy.asarray(out),
                                     numpy.asarray(ref))


def test_pool_bwd_w_tiling_and_vmem_fallback(monkeypatch):
    """Shrinking POOL_VMEM_BUDGET_BYTES (a) tiles the W axis for
    non-overlapping windows and (b) falls back to autodiff for
    overlapping ones — both bit-exact vs the reference."""
    import jax.numpy as jnp

    from veles_tpu.models.pooling import MaxPooling
    from veles_tpu.ops import pool_bwd

    rng = numpy.random.RandomState(4)

    # (a) non-overlap: find a budget that forces > 1 W tile
    x = jnp.asarray(rng.randn(1, 6, 64, 3), jnp.float32)
    y = MaxPooling.apply({}, x, window=(2, 2), sliding=(2, 2),
                         pallas_bwd=False)
    dy = jnp.asarray(rng.randn(*y.shape), jnp.float32)
    full = pool_bwd._plan_blocks(6, 64, 3, y.shape[1], y.shape[2],
                                 (2, 2), (2, 2), 4)
    assert full == (1, y.shape[2])
    budget = pool_bwd.POOL_VMEM_BUDGET_BYTES
    while True:
        budget //= 2
        monkeypatch.setattr(pool_bwd, "POOL_VMEM_BUDGET_BYTES", budget)
        plan = pool_bwd._plan_blocks(6, 64, 3, y.shape[1], y.shape[2],
                                     (2, 2), (2, 2), 4)
        assert plan is not None, "non-overlap must always tile"
        if plan[0] > 1:
            break
    out = pool_bwd.max_pool_bwd(x, y, dy, window=(2, 2),
                                sliding=(2, 2))
    ref = _pool_reference(x, dy, (2, 2), (2, 2))
    numpy.testing.assert_array_equal(numpy.asarray(out),
                                     numpy.asarray(ref))

    # (b) overlapping window + impossible budget -> autodiff fallback
    monkeypatch.setattr(pool_bwd, "POOL_VMEM_BUDGET_BYTES", 1)
    x2 = jnp.asarray(rng.randn(1, 9, 9, 2), jnp.float32)
    y2 = MaxPooling.apply({}, x2, window=(3, 3), sliding=(2, 2),
                          pallas_bwd=False)
    dy2 = jnp.asarray(rng.randn(*y2.shape), jnp.float32)
    out2 = pool_bwd.max_pool_bwd(x2, y2, dy2, window=(3, 3),
                                 sliding=(2, 2))
    ref2 = _pool_reference(x2, dy2, (3, 3), (2, 2))
    numpy.testing.assert_array_equal(numpy.asarray(out2),
                                     numpy.asarray(ref2))


# -- custom_vjp wrappers: forward bit-identity + end-to-end grads -----------


def test_knob_forward_bit_identical():
    """The knob must never change the forward: conv_act / max_pool
    custom_vjp forwards are the SAME composition as the stock apply."""
    import jax.numpy as jnp

    from veles_tpu.models.conv import ConvStrictRELU, ConvTanh
    from veles_tpu.models.pooling import MaxPooling

    rng = numpy.random.RandomState(6)
    x = jnp.asarray(rng.randn(2, 8, 8, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 8) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(8) * 0.1, jnp.float32)
    for cls in (ConvStrictRELU, ConvTanh):
        on = cls.apply({"weights": w, "bias": b}, x,
                       padding=(1, 1, 1, 1), sliding=(1, 1),
                       pallas_bwd=True)
        off = cls.apply({"weights": w, "bias": b}, x,
                        padding=(1, 1, 1, 1), sliding=(1, 1),
                        pallas_bwd=False)
        numpy.testing.assert_array_equal(numpy.asarray(on),
                                         numpy.asarray(off))
    p_on = MaxPooling.apply({}, x, window=(2, 2), sliding=(2, 2),
                            pallas_bwd=True)
    p_off = MaxPooling.apply({}, x, window=(2, 2), sliding=(2, 2),
                             pallas_bwd=False)
    numpy.testing.assert_array_equal(numpy.asarray(p_on),
                                     numpy.asarray(p_off))


def test_wrapper_grads_match_autodiff():
    """jax.grad through the knob-on custom_vjp composition (conv ->
    pool -> scalar loss) matches the stock path within the kernel
    band — the end-to-end cascade, not just per-op parity."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.models.conv import ConvStrictRELU
    from veles_tpu.models.pooling import MaxPooling

    rng = numpy.random.RandomState(9)
    x = jnp.asarray(rng.randn(2, 8, 8, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 8) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(8) * 0.1, jnp.float32)

    def loss(w_, b_, pallas_bwd):
        h = ConvStrictRELU.apply(
            {"weights": w_, "bias": b_}, x, padding=(1, 1, 1, 1),
            sliding=(1, 1), pallas_bwd=pallas_bwd)
        h = MaxPooling.apply({}, h, window=(2, 2), sliding=(2, 2),
                             pallas_bwd=pallas_bwd)
        return (h * h).sum()

    g_on = jax.grad(loss, argnums=(0, 1))(w, b, True)
    g_off = jax.grad(loss, argnums=(0, 1))(w, b, False)
    assert _max_rel(g_on[0], g_off[0]) < 1e-5
    assert _max_rel(g_on[1], g_off[1]) < 1e-5


# -- compiler scheduling hints: bit-identical by contract -------------------


def _conv_step_fixture(loss="softmax"):
    """A conv+pool+conv+pool+softmax fused-step setup on synthetic
    images — the smallest model exercising every new kernel."""
    from veles_tpu.models.zoo import build_plans_and_state

    specs = [
        {"type": "conv_str", "n_kernels": 4, "kx": 3, "ky": 3,
         "padding": 1, "learning_rate": 0.05, "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "conv_tanh", "n_kernels": 4, "kx": 3, "ky": 3,
         "padding": 1, "learning_rate": 0.05, "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "softmax", "output_sample_shape": 5,
         "learning_rate": 0.05, "gradient_moment": 0.9},
    ]
    plans, state, _ = build_plans_and_state(specs, (12, 12, 3), seed=2)
    rng = numpy.random.RandomState(1)
    batches = [(rng.randn(16, 12, 12, 3).astype(numpy.float32),
                rng.randint(0, 5, 16).astype(numpy.int32))
               for _ in range(4)]
    return plans, state, batches


def _run_steps(step, state, batches, indices, **kwargs):
    out = state
    m = None
    for i in indices:
        out, m = step(out, batches[i][0], batches[i][1],
                      numpy.float32(16), **kwargs)
    return out, m


def _assert_states_equal(sa, sb):
    for ea, eb in zip(sa, sb):
        for key in ea:
            if ea[key] is None:
                assert eb[key] is None
                continue
            numpy.testing.assert_array_equal(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]))


def test_barrier_chain_is_identity():
    """_chain_grad_barriers is a scheduling hint ONLY: values out ==
    values in, leaf for leaf."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.compiler import _chain_grad_barriers

    rng = numpy.random.RandomState(0)
    grads = [
        {"weights": jnp.asarray(rng.randn(4, 3), jnp.float32),
         "bias": jnp.asarray(rng.randn(3), jnp.float32)},
        {},  # a param-less layer (pooling) must pass through
        {"weights": jnp.asarray(rng.randn(3, 2), jnp.float32),
         "bias": None},
    ]
    chained = _chain_grad_barriers(grads)
    assert len(chained) == len(grads)
    for orig, out in zip(grads, chained):
        assert set(orig) == set(out)
        for leaves in (jax.tree_util.tree_leaves(orig),
                       jax.tree_util.tree_leaves(out)):
            pass
        for ka in orig:
            if orig[ka] is None:
                assert out[ka] is None
            else:
                numpy.testing.assert_array_equal(
                    numpy.asarray(orig[ka]), numpy.asarray(out[ka]))


def test_step_bwd_schedule_and_remat_bit_identical(pallas_off):
    """The decongestion hints (optimization_barrier chain, per-layer
    remat) change the SCHEDULE, never the values: 3 chained steps are
    bit-identical with and without them."""
    from veles_tpu.compiler import build_train_step

    plans, state, batches = _conv_step_fixture()
    base = build_train_step(plans, donate=False, bwd_schedule=False)
    hinted = build_train_step(plans, donate=False, bwd_schedule=True)
    remat = build_train_step(plans, donate=False, bwd_schedule=True,
                             bwd_remat=True)
    s_base, _ = _run_steps(base, state, batches, (0, 1, 2))
    s_hint, _ = _run_steps(hinted, state, batches, (0, 1, 2))
    s_remat, _ = _run_steps(remat, state, batches, (0, 1, 2))
    _assert_states_equal(s_base, s_hint)
    _assert_states_equal(s_base, s_remat)


# -- the VELES_PALLAS_BWD knob end to end -----------------------------------


def test_env_knob_resolution(monkeypatch):
    from veles_tpu.ops import common

    for env, expect_cpu in (("0", False), ("1", True), ("on", True),
                            ("", False), ("auto", False)):
        monkeypatch.setattr(common, "PALLAS_BWD_ENV", env)
        # CPU backend: ""/"auto" resolve off (TPU-only default)
        assert common.pallas_bwd_enabled() is expect_cpu


def test_fused_step_knob_parity(pallas_on):
    """The whole fused train step with the hand-scheduled backward:
    losses bit-identical to autodiff (same forward), updated state
    within the documented kernel band over chained steps."""
    from veles_tpu.compiler import build_train_step
    from veles_tpu.ops import common

    plans, state, batches = _conv_step_fixture()
    step_on = build_train_step(plans, donate=False)
    s_on, m_on = _run_steps(step_on, state, batches, (0, 1, 2))

    common.PALLAS_BWD_ENV = "0"
    step_off = build_train_step(plans, donate=False)
    s_off, m_off = _run_steps(step_off, state, batches, (0, 1, 2))

    # first-step forward is identical => first loss identical; after
    # the first update states differ within the kernel parity band
    assert numpy.isfinite(float(m_on["loss"]))
    for ea, eb in zip(s_on, s_off):
        for key in ea:
            if ea[key] is None:
                assert eb[key] is None
                continue
            assert _max_rel(ea[key], eb[key]) < 1e-4, key


def test_poisoned_step_skips_bit_exactly_through_fused_bwd(pallas_on):
    """PR 3's guard contract survives the hand-scheduled backward: a
    NaN-poisoned step leaves params AND solver accumulators
    bit-identical to never having served that minibatch."""
    import math

    from veles_tpu.compiler import build_train_step

    plans, state, batches = _conv_step_fixture()
    step = build_train_step(plans, donate=False)

    ref, m = _run_steps(step, state, batches, (0, 1, 3))
    assert bool(m["finite"]) and int(m["skipped"]) == 0

    got, _ = _run_steps(step, state, batches, (0, 1))
    got, m = _run_steps(step, got, batches, (2,),
                        grad_poison=numpy.float32(NAN))
    assert not bool(m["finite"]) and int(m["skipped"]) == 1
    assert not math.isfinite(float(m["grad_norm"]))
    got, _ = _run_steps(step, got, batches, (3,))
    _assert_states_equal(ref, got)


def test_knob_off_never_calls_kernels(pallas_off, monkeypatch):
    """The tier-1 fallback smoke: with VELES_PALLAS_BWD=0 the fused
    step must take the stock autodiff path — the Pallas kernels are
    poisoned to raise, and the result matches an unpatched knob-off
    run bit-exactly (the fallback IS the stock code path)."""
    from veles_tpu.compiler import build_train_step
    from veles_tpu.ops import conv_vjp, pool_bwd

    plans, state, batches = _conv_step_fixture()
    baseline = build_train_step(plans, donate=False)
    s_ref, _ = _run_steps(baseline, state, batches, (0, 1))

    def boom(*args, **kwargs):
        raise AssertionError("VELES_PALLAS_BWD=0 must not reach the "
                             "Pallas backward kernels")

    monkeypatch.setattr(conv_vjp, "fused_conv_vjp", boom)
    monkeypatch.setattr(conv_vjp, "conv_act", boom)
    monkeypatch.setattr(pool_bwd, "max_pool_bwd", boom)
    monkeypatch.setattr(pool_bwd, "max_pool", boom)
    step = build_train_step(plans, donate=False)
    s_got, _ = _run_steps(step, state, batches, (0, 1))
    _assert_states_equal(s_ref, s_got)


def test_gd_units_route_through_kernels(pallas_on):
    """The per-unit gd chain (non-fused path) takes the same kernels:
    GDConv/GDMaxPooling backwards match their stock formulations."""
    import jax.numpy as jnp

    from veles_tpu.models.gd_conv import GDConvStrictRELU
    from veles_tpu.models.gd_pooling import GDMaxPooling
    from veles_tpu.models.conv import ConvStrictRELU
    from veles_tpu.models.pooling import MaxPooling
    from veles_tpu.ops import common

    rng = numpy.random.RandomState(8)
    x = jnp.asarray(rng.randn(2, 8, 8, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 4) * 0.1, jnp.float32)
    b = jnp.zeros((4,), jnp.float32)
    state = {"weights": w, "bias": b,
             "accum_weights": jnp.zeros_like(w),
             "accum_bias": jnp.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.9,
             "gradient_moment_bias": 0.9, "adadelta_rho": 0.9,
             "solver_epsilon": 1e-8}
    y = ConvStrictRELU.apply({"weights": w, "bias": b}, x,
                             padding=(1, 1, 1, 1), sliding=(1, 1),
                             pallas_bwd=False)
    dy = jnp.asarray(rng.randn(*y.shape), jnp.float32)
    err_on, new_on = GDConvStrictRELU.backward(
        state, hyper, x, y, dy, solver="momentum", include_bias=True,
        need_err_input=True, padding=(1, 1, 1, 1), sliding=(1, 1))

    common.PALLAS_BWD_ENV = "0"
    err_off, new_off = GDConvStrictRELU.backward(
        state, hyper, x, y, dy, solver="momentum", include_bias=True,
        need_err_input=True, padding=(1, 1, 1, 1), sliding=(1, 1))
    assert _max_rel(err_on, err_off) < 1e-5
    for key in new_on:
        if new_on[key] is None:
            assert new_off[key] is None
            continue
        assert _max_rel(new_on[key], new_off[key]) < 1e-5, key

    # pooling: routing is value-exact, so bit-equality holds
    common.PALLAS_BWD_ENV = "1"
    yp = MaxPooling.apply({}, x, window=(2, 2), sliding=(2, 2),
                          pallas_bwd=False)
    dyp = jnp.asarray(rng.randn(*yp.shape), jnp.float32)
    p_on, _ = GDMaxPooling.backward(
        {}, hyper, x, yp, dyp, solver="momentum", include_bias=False,
        need_err_input=True, window=(2, 2), sliding=(2, 2))
    common.PALLAS_BWD_ENV = "0"
    p_off, _ = GDMaxPooling.backward(
        {}, hyper, x, yp, dyp, solver="momentum", include_bias=False,
        need_err_input=True, window=(2, 2), sliding=(2, 2))
    numpy.testing.assert_array_equal(numpy.asarray(p_on),
                                     numpy.asarray(p_off))


# -- observe: live fwd/bwd attribution --------------------------------------


def test_bwd_snapshot_attribution():
    """bwd.step_ms / bwd.mfu_pct derive from the existing step
    histograms + the two flops gauges, and ride health_snapshot so
    heartbeats and web_status carry the split (docs/kernels.md)."""
    from veles_tpu.observe.metrics import MetricsRegistry, health_snapshot
    from veles_tpu.observe import xla_introspect as xla

    reg = MetricsRegistry()
    # missing inputs -> None, never a crash
    assert xla.bwd_snapshot(reg) is None
    train = reg.histogram("step.train_s")
    ev = reg.histogram("step.eval_s")
    assert xla.bwd_snapshot(reg) is None  # histograms empty
    for _ in range(8):
        train.observe(0.016)
        ev.observe(0.004)
    out = xla.bwd_snapshot(reg)
    assert out == {"bwd_step_ms": 12.0}  # no flops yet: time only

    reg.gauge("xla.step_flops").set(1.5e12)
    reg.gauge("xla.fwd_flops").set(0.5e12)
    out = xla.bwd_snapshot(reg)
    assert out["bwd_step_ms"] == 12.0
    assert out["bwd_mfu_pct"] > 0
    health = health_snapshot(reg)
    assert health["bwd_step_ms"] == 12.0
    assert health["bwd_mfu_pct"] == out["bwd_mfu_pct"]

    # eval slower than train (mis-ordered windows) -> attribution
    # withheld rather than a negative time published
    reg2 = MetricsRegistry()
    t2, e2 = reg2.histogram("step.train_s"), reg2.histogram("step.eval_s")
    for _ in range(4):
        t2.observe(0.002)
        e2.observe(0.004)
    assert xla.bwd_snapshot(reg2) is None


def test_bench_bwd_ab_smoke():
    """The compile-only A/B harness runs on CPU: both legs compile,
    forward losses bit-identical, states within the kernel band."""
    import bench

    res = bench.bench_bwd_ab(small=True)
    assert res["loss_bit_identical"] is True
    assert res["parity_ok"] is True
    assert res["state_max_rel_diff"] < 1e-4
    # CPU leg carries compile+parity only — no timing claims
    assert "note" in res or "speedup" in res


def test_spread_filters_jitter_passes():
    """bench._spread / _filter_passes: the published median discards
    non-positive (jitter-dominated) passes, records passes_used and
    the raw per-pass slopes (the MFU.json weather_note, automated)."""
    from bench import _filter_passes, _spread

    samples = [0.016, 0.017, -0.038, 0.016, 0.018]
    spread = _spread(samples)
    assert spread["passes"] == 5
    assert spread["passes_used"] == 4
    assert spread["median"] == pytest.approx(0.0165)
    assert spread["min"] == pytest.approx(-0.038)  # raw extremes kept
    assert spread["slopes"] == [pytest.approx(s) for s in samples]
    # all passes jitter-dominated: raw list returned, caller's floor
    # (not the filter) rejects the measurement
    assert _filter_passes([-1.0, -2.0]) == [-1.0, -2.0]
