"""Async double-buffered input pipeline (veles_tpu/pipeline_input.py):
parity with the synchronous serve, short-tail handling, clean shutdown,
the Array staging/prefetch dirty-bit machinery, and per-run stats."""

import io
import re
import threading

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.prng import RandomGenerator
from tests.test_models import BlobsLoader


def _build_fused(device, pipeline, max_epochs=4, on_device=True,
                 loader_cls=BlobsLoader, minibatch_size=64):
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    prng.get().seed(1234)  # identical layer-init streams across builds
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: loader_cls(
            w, minibatch_size=minibatch_size, on_device=on_device,
            prng=RandomGenerator("pipe", seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.fuse(pipeline=pipeline)
    sw.initialize(device=device)
    return sw


@pytest.mark.parametrize("on_device", [True, False],
                         ids=["device-gather", "host-fill"])
def test_pipeline_bit_identical_to_sync(cpu_device, on_device):
    """Epoch metrics AND final weights must match the synchronous path
    bit for bit: the pipeline serves the same minibatches in the same
    order, staged through the same device_put bytes."""
    sync = _build_fused(cpu_device, pipeline=False, on_device=on_device)
    sync.run()
    pipe = _build_fused(cpu_device, pipeline=True, on_device=on_device)
    assert pipe.fused_trainer._prefetcher is not None
    pipe.run()

    assert sync.decision.epoch_metrics == pipe.decision.epoch_metrics
    assert sync.fused_trainer.run_calls == pipe.fused_trainer.run_calls
    sync.fused_trainer.sync()
    pipe.fused_trainer.sync()
    for fwd_s, fwd_p in zip(sync.forwards, pipe.forwards):
        fwd_s.weights.map_read()
        fwd_p.weights.map_read()
        numpy.testing.assert_array_equal(fwd_s.weights.mem,
                                         fwd_p.weights.mem)
    # workers joined at run end: nothing non-daemon left behind
    assert not [t for t in threading.enumerate()
                if t.name.startswith("prefetch")]


class TailBlobsLoader(BlobsLoader):
    """Class sizes deliberately NOT divisible by the minibatch size:
    validation 10 (tail 10), train 70 (tails 32+32+6)."""

    def load_data(self):
        self.class_lengths[:] = [0, 10, 70]
        self._calc_class_end_offsets()
        self.create_originals((16,))
        rng = numpy.random.RandomState(99)
        centers = rng.randn(4, 16) * 2.0
        for i in range(self.total_samples):
            label = i % 4
            self.original_data.mem[i] = (
                centers[label] + rng.randn(16) * 0.3)
            self.original_labels[i] = label


@pytest.mark.parametrize("on_device", [True, False],
                         ids=["device-gather", "host-fill"])
def test_pipeline_short_tail_minibatches(cpu_device, on_device):
    """Short-tail minibatches (size < max) keep the exact synchronous
    sequence of (class, size, offset, flags) and the same zero/-1
    padding semantics."""
    def serve_sequence(pipeline, steps=12):
        sw = _build_fused(cpu_device, pipeline=pipeline, on_device=on_device,
                          loader_cls=TailBlobsLoader, minibatch_size=32)
        loader, trainer = sw.loader, sw.fused_trainer
        seq = []
        for _ in range(steps):
            loader.run()
            pf = trainer._prefetcher
            if pf is not None:
                x = numpy.asarray(pf.current.data)
                y = numpy.asarray(pf.current.labels)
            else:
                x = numpy.asarray(
                    loader.minibatch_data.device_array(trainer.device))
                y = numpy.asarray(
                    loader.minibatch_labels.device_array(trainer.device))
            seq.append((loader.minibatch_class, loader.minibatch_size,
                        loader.minibatch_offset,
                        bool(loader.last_minibatch),
                        bool(loader.epoch_ended), loader.epoch_number,
                        x.tobytes(), y.tobytes()))
            trainer.run()
        sw.stop()
        return seq

    sync_seq = serve_sequence(False)
    pipe_seq = serve_sequence(True)
    assert sync_seq == pipe_seq
    sizes = [s[1] for s in pipe_seq]
    assert 6 in sizes and 10 in sizes  # the short tails really occurred
    # tail padding: beyond minibatch_size the batch is zeroed / -1
    for cls, size, _off, _lmb, _ee, _en, xb, yb in pipe_seq:
        if size == 6:
            x = numpy.frombuffer(xb, numpy.float32).reshape(32, 16)
            y = numpy.frombuffer(yb, numpy.int32)
            assert not x[6:].any()
            assert (y[6:] == -1).all()


def test_pipeline_stop_mid_epoch_joins_worker(cpu_device):
    """Workflow.stop() mid-epoch must leave no live worker threads, and
    a later run must restart the pipeline cleanly."""
    sw = _build_fused(cpu_device, pipeline=True)
    loader, trainer = sw.loader, sw.fused_trainer
    for _ in range(3):  # mid-epoch: train class not finished
        loader.run()
        trainer.run()
    prefetcher = trainer._prefetcher
    assert prefetcher._pool is not None
    sw.stop()
    assert prefetcher._pool is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith("prefetch") and t.is_alive()]
    # restart: a full run completes and joins its fresh worker again
    sw.run()
    assert bool(sw.decision.complete)
    assert prefetcher._pool is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith("prefetch") and t.is_alive()]


def test_pipeline_never_drops_served_ahead_minibatches(cpu_device):
    """Serving ahead must not lose data: the not-yet-consumed serve
    keeps its pending record, a mid-run snapshot requeues it into
    failed_minibatches, and shutdown does the same in-process."""
    import time

    sw = _build_fused(cpu_device, pipeline=True)
    loader, trainer = sw.loader, sw.fused_trainer
    for _ in range(3):
        loader.run()
        trainer.run()
    # wait for the served-ahead minibatch to land in the results queue
    # (its pending record is appended during the serve)
    prefetcher = trainer._prefetcher
    deadline = time.time() + 10.0
    while prefetcher._results.empty() and time.time() < deadline:
        time.sleep(0.01)
    # depth 1: exactly one serve is ahead and unconsumed
    state = loader.__getstate__()  # the snapshotter's view, mid-run
    assert len(state["failed_minibatches"]) == 1
    sw.stop()
    assert len(loader.failed_minibatches) == 1
    offset, size, mb_class = loader.failed_minibatches[0][:3]
    assert size > 0 and mb_class in (0, 1, 2)
    # consume-time accounting: only CONSUMED samples were counted, so a
    # replay of the requeued record cannot double-count
    assert loader.samples_served == 3 * 64


def test_loader_setstate_migrates_legacy_serving_fields(cpu_device):
    """Snapshots written when minibatch_class/epoch_number were plain
    attributes must still restore now that they are properties."""
    sw = _build_fused(cpu_device, pipeline=False)
    loader = sw.loader
    state = loader.__getstate__()
    # simulate a pre-property snapshot
    state["minibatch_class"] = 2
    state["epoch_number"] = 5
    state.pop("_minibatch_class", None)
    state.pop("_epoch_number", None)
    restored = object.__new__(type(loader))
    restored.__setstate__(state)
    assert restored.minibatch_class == 2
    assert restored.epoch_number == 5


def test_pipeline_worker_failure_propagates(cpu_device):
    """A crash inside the worker's serve must surface in the graph
    thread (not hang the run), and still wind the worker down."""
    sw = _build_fused(cpu_device, pipeline=True)
    loader = sw.loader
    loader.run()  # primes the pipeline
    sw.fused_trainer.run()

    def boom():
        raise RuntimeError("fill exploded")
    loader.fill_indices = lambda *a: boom()
    with pytest.raises(RuntimeError, match="fill exploded"):
        for _ in range(4):  # inflight items may drain first
            loader.run()
    assert sw.fused_trainer._prefetcher._pool is None


# -- memory.Array staging + prefetch dirty-bit machinery -------------------


def test_array_staging_ping_pong(cpu_device):
    arr = Array(numpy.zeros((4, 3), numpy.float32))
    arr.stage_init(2)
    assert arr.staged
    bufs = arr._stage_bufs_
    assert bufs[0] is arr.mem

    arr.stage_begin(0)
    arr.mem[:] = 1.0
    dev0 = arr.stage_put(cpu_device)
    arr.stage_begin(1)
    assert arr.mem is bufs[1]
    arr.mem[:] = 2.0
    dev1 = arr.stage_put(cpu_device)
    # refilling slot 0 must not corrupt the already-transferred batch
    arr.stage_begin(0)
    arr.mem[:] = 3.0
    numpy.testing.assert_array_equal(numpy.asarray(dev0), 1.0)
    numpy.testing.assert_array_equal(numpy.asarray(dev1), 2.0)
    # while staged, host state is authoritative: map_read cannot
    # replace the slot buffer with a device fetch mid-fill
    arr.map_read()
    assert arr.mem is bufs[0]

    # a wholesale buffer swap drops the staging slots
    arr.mem = numpy.zeros((2, 2), numpy.float32)
    assert not arr.staged


def test_array_staged_capture_prefers_device_path(cpu_device):
    arr = Array(numpy.zeros(3, numpy.float32))
    dev = cpu_device.put(numpy.arange(3, dtype=numpy.float32))
    arr.set_device_array(dev, cpu_device)
    assert arr.staged_capture(cpu_device) is dev  # adopted, no re-put
    arr.detach_device()
    arr.mem = numpy.full(3, 7.0, numpy.float32)
    out = numpy.asarray(arr.staged_capture(cpu_device))
    numpy.testing.assert_array_equal(out, 7.0)  # falls back to a put


class _PlainDevArray(object):
    """Device-array stand-in WITHOUT copy_to_host_async."""

    def __init__(self, value):
        self._value = value
        self.shape = value.shape
        self.dtype = value.dtype

    def __array__(self, dtype=None):
        return (self._value if dtype is None
                else self._value.astype(dtype))


def test_prefetch_host_eager_fallback_dirty_bits():
    """Satellite: prefetch_host on a backend array without
    copy_to_host_async must fetch eagerly (state -> IN_SYNC with the
    device bytes local), not silently no-op."""
    from veles_tpu import memory
    arr = Array(numpy.zeros(4, numpy.float32))
    fake = _PlainDevArray(numpy.arange(4, dtype=numpy.float32))
    arr.set_device_array(fake)
    assert arr._state_ == memory._DEVICE_DIRTY
    arr.prefetch_host()
    assert arr._state_ == memory._IN_SYNC  # eager fetch happened NOW
    numpy.testing.assert_array_equal(
        arr.mem, numpy.arange(4, dtype=numpy.float32))
    arr.map_read()  # no-op, stays in sync
    assert arr._state_ == memory._IN_SYNC

    # detach after prefetch: host stays authoritative and readable
    arr.detach_device()
    assert arr._devmem_ is None
    numpy.testing.assert_array_equal(
        arr.mem, numpy.arange(4, dtype=numpy.float32))


def test_prefetch_host_async_path_keeps_device_dirty(cpu_device):
    """With copy_to_host_async available the state must STAY
    device-dirty (the async copy is a hint, map_read still syncs)."""
    from veles_tpu import memory
    arr = Array(numpy.zeros(3, numpy.float32))
    arr.set_device_array(
        cpu_device.put(numpy.arange(3, dtype=numpy.float32)), cpu_device)
    arr.prefetch_host()
    assert arr._state_ == memory._DEVICE_DIRTY
    arr.map_read()
    assert arr._state_ == memory._IN_SYNC
    numpy.testing.assert_array_equal(
        arr.mem, numpy.arange(3, dtype=numpy.float32))


def test_cpu_device_put_owns_its_buffer(cpu_device):
    """Regression: XLA:CPU device_put adopts aligned host buffers
    zero-copy without keeping them valid, which made training over
    recycled gather-window/minibatch buffers nondeterministic.
    CPUDevice.put must return an XLA-owned array."""
    buf = numpy.ones((64, 16), numpy.float32)
    dev = cpu_device.put(buf)
    buf[:] = 3.0
    numpy.testing.assert_array_equal(numpy.asarray(dev), 1.0)


def test_stage_put_decouples_from_host_buffer(cpu_device):
    """Regression: XLA:CPU device_put adopts aligned host buffers
    zero-copy (immutable semantics), so refilling a staging slot
    silently corrupted the already-'transferred' minibatch.
    stage_put must return an array decoupled from the host buffer."""
    arr = Array(numpy.ones((64, 16), numpy.float32))
    arr.stage_init(2)
    arr.stage_begin(0)
    arr.mem[:] = 1.0
    dev = arr.stage_put(cpu_device)
    dev.block_until_ready()
    arr.mem[:] = 3.0  # refill the same slot buffer
    numpy.testing.assert_array_equal(numpy.asarray(dev), 1.0)


# -- per-run workflow stats (print_stats deltas) ---------------------------


def _stats_runs(sw, unit_name, **kwargs):
    buf = io.StringIO()
    sw.print_stats(out=buf, **kwargs)
    text = buf.getvalue()
    match = re.search(r"%s \((\d+) runs\)" % unit_name, text)
    assert match, text
    return int(match.group(1)), text


def test_print_stats_reports_per_run_deltas(cpu_device):
    sw = _build_fused(cpu_device, pipeline=False, max_epochs=2)
    sw.run()
    first_runs, _ = _stats_runs(sw, "FusedTrainer")
    total_after_first = sw.fused_trainer.run_calls
    assert first_runs == total_after_first

    sw.decision.complete <<= False
    sw.decision.max_epochs = 4
    sw.run()
    second_runs, text = _stats_runs(sw, "FusedTrainer")
    # per-run delta: ONLY the second run's calls, not the accumulation
    assert second_runs == sw.fused_trainer.run_calls - total_after_first
    assert "(this run)" in text
    cumulative_runs, text = _stats_runs(sw, "FusedTrainer",
                                        cumulative=True)
    assert cumulative_runs == sw.fused_trainer.run_calls
    assert "(this run)" not in text


def test_print_stats_surfaces_pipeline_stage_timers(cpu_device):
    sw = _build_fused(cpu_device, pipeline=True, max_epochs=2,
                      on_device=False)
    sw.run()
    buf = io.StringIO()
    sw.print_stats(out=buf)
    text = buf.getvalue()
    assert "pipeline_fill" in text
    assert "pipeline_h2d" in text
    assert "depth 1" in text
