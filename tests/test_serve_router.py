"""Multi-replica router tests (docs/serving.md): per-replica results
bit-identical to single-replica, least-loaded routing around a stalled
replica, overload cascade then fleet-wide 503, cross-replica metrics
aggregation, the warm fleet-restart zero-compile receipt, and snapshot
hot-reload under closed-loop load (same digest = 0 new backend
compiles, zero dropped requests; new digest = background warm-up +
atomic cutover)."""

import threading
import time

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, ReplicaPool, ServeOverload, ServeService)
from veles_tpu.serve.batcher import serve_snapshot
from tests.test_serve import _mlp_spec, _restore_jax_cache_config  # noqa: F401

pytestmark = pytest.mark.serve


def _pool(replicas=3, ladder=(8, 32), seed=11, **kwargs):
    plans, params = _mlp_spec(seed=seed)
    pool = ReplicaPool(plans, params, (16,), replicas=replicas,
                       ladder=ladder, **kwargs)
    pool.compile()
    return pool


def test_replicas_bit_identical_to_single_replica():
    """Every replica — and the router over them — returns results bit
    for bit equal to the single-replica sequential reference; the
    replicas really live on distinct devices (the 8-device test
    mesh)."""
    pool = _pool(replicas=3)
    assert len({str(rep.engine.device.jax_device)
                for rep in pool.replicas}) == 3
    assert pool.compile_receipt["replicas"] == 3
    pool.start()
    try:
        rng = numpy.random.RandomState(1)
        x = rng.rand(9, 16).astype(numpy.float32)
        ref = pool.engine.infer(x)
        for rep in pool.replicas:
            out = numpy.stack([rep.batcher.infer(x[i])
                               for i in range(len(x))])
            assert (out == ref).all(), \
                "replica %d diverged" % rep.index
        routed = numpy.stack([pool.infer(x[i]) for i in range(len(x))])
        assert (routed == ref).all()
        block = pool.infer_block(numpy.ascontiguousarray(x[:8]))
        assert (block == ref[:8]).all()
    finally:
        pool.stop()


@pytest.mark.chaos
def test_least_loaded_pick_avoids_stalled_replica():
    """With replica 0's worker stalled (chaos serve.stall) and its
    queue backed up, the router sends new work to an idle sibling."""
    pool = _pool(replicas=2, max_delay_s=0.0)
    chaos.install(chaos.FaultPlan(seed=1).add("serve.stall", "stall",
                                              param=0.4))
    pool.start()
    rep0 = pool.replicas[0]
    try:
        zeros = numpy.zeros(16, numpy.float32)
        stalled = [rep0.batcher.submit(zeros)]
        time.sleep(0.08)  # rep0's worker pops it and stalls 0.4s
        stalled += [rep0.batcher.submit(zeros) for _ in range(2)]
        assert rep0.batcher._q.qsize() >= 2
        routed = pool.submit(numpy.ones(16, numpy.float32))
        # the router picked the idle sibling, not the backed-up replica
        assert routed not in list(rep0.batcher._q.queue)
        assert routed.done.wait(10)
        assert routed.error is None
        for req in stalled:
            assert req.done.wait(10)
    finally:
        pool.stop()
        chaos.uninstall()


@pytest.mark.chaos
def test_overload_cascades_then_503():
    """An overloaded replica cascades the request to its siblings;
    only when EVERY replica sheds does the pool 503 — with the
    smallest retry_after any replica offered."""
    pool = _pool(replicas=2)
    pool.start()
    zeros = numpy.zeros(16, numpy.float32)
    try:
        before = registry.counter("serve.router.cascades").value
        chaos.install(chaos.FaultPlan(seed=1).add("serve.drop", "drop",
                                                  nth=1))
        out = pool.infer(zeros)  # first pick sheds, sibling serves
        assert out.shape == (4,)
        assert registry.counter("serve.router.cascades").value \
            == before + 1
        chaos.uninstall()
        chaos.install(chaos.FaultPlan(seed=1).add("serve.drop",
                                                  "drop"))
        with pytest.raises(ServeOverload) as info:
            pool.submit(zeros)
        assert info.value.retry_after > 0
    finally:
        pool.stop()
        chaos.uninstall()


def test_metrics_aggregate_across_replicas():
    """Counters are process-shared (totals sum across replicas by
    construction); gauges are per-replica and the serve snapshot
    carries the replica block with the aggregate queue depth."""
    requests_before = registry.counter("serve.requests").value
    pool = _pool(replicas=2)
    pool.start()
    try:
        rng = numpy.random.RandomState(3)
        for i in range(12):
            pool.infer(rng.rand(16).astype(numpy.float32))
    finally:
        pool.stop()
    assert registry.counter("serve.requests").value \
        >= requests_before + 12
    assert registry.peek("serve.replica.0.queue_depth") is not None
    assert registry.peek("serve.replica.1.queue_depth") is not None
    snap = serve_snapshot()
    assert snap["replicas"] == 2
    assert len(snap["replica_queue_depths"]) == 2
    assert snap["queue_depth"] == sum(snap["replica_queue_depths"])


def test_warm_fleet_restart_zero_compiles(
        tmp_path, _restore_jax_cache_config):  # noqa: F811
    """A restarted 2-replica fleet against the warm digest-keyed cache
    performs 0 new backend compiles ACROSS ALL replicas (jax's cache
    key includes the device assignment, so the cold start wrote one
    entry set per device and the restart deserializes them all)."""
    plans, params = _mlp_spec(seed=13)
    root = str(tmp_path / "fleet_cache")
    cold = ReplicaPool(plans, params, (16,), replicas=2, ladder=(8,),
                       cache_root=root)
    cold_receipt = cold.compile()
    assert cold_receipt["new_compiles"] >= 2  # >= one per device
    warm = ReplicaPool(plans, params, (16,), replicas=2, ladder=(8,),
                       cache_root=root)
    warm_receipt = warm.compile()
    assert warm_receipt["new_compiles"] == 0, warm_receipt
    assert warm_receipt["cache_hits"] >= 2
    rng = numpy.random.RandomState(4)
    x = rng.rand(3, 16).astype(numpy.float32)
    assert (warm.engine.infer(x) == cold.engine.infer(x)).all()


def _closed_loop(pool, errors, stop, clients=4):
    def worker(k):
        rng = numpy.random.RandomState(k)
        x = rng.rand(16).astype(numpy.float32)
        while not stop.is_set():
            try:
                pool.infer(x, timeout=10.0)
            except Exception as exc:  # EVERY failure counts
                errors.append(exc)
    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(clients)]
    for t in threads:
        t.start()
    return threads


def test_hot_reload_under_load_zero_drops():
    """The acceptance receipt: closed-loop clients hammer the pool
    while (a) a same-digest snapshot reload swaps weights with 0 new
    backend compiles, then (b) a new-digest reload warm-compiles in
    the background and cuts over atomically — zero dropped or failed
    requests through both, and post-reload results match a fresh
    reference engine for the new weights."""
    plans, params = _mlp_spec(seed=17)
    pool = ReplicaPool(plans, params, (16,), replicas=2,
                       ladder=(8, 32), max_delay_s=0.001,
                       max_queue=4096)
    pool.compile()
    pool.start()
    errors, stop = [], threading.Event()
    threads = _closed_loop(pool, errors, stop)
    try:
        time.sleep(0.2)
        # (a) same digest: retrained weights, identical architecture
        _, params2 = _mlp_spec(seed=99)
        receipt = pool.reload(params2)
        assert receipt["mode"] == "params"
        assert receipt["new_compiles"] == 0, receipt
        assert receipt["digest"] == receipt["previous_digest"]
        time.sleep(0.2)
        probe = numpy.random.RandomState(5).rand(16).astype(
            numpy.float32)
        ref2 = pool.engine.infer(probe)[0]
        for rep in pool.replicas:
            assert (rep.batcher.infer(probe) == ref2).all()
        # (b) new digest: wider hidden layer -> full engine cutover
        plans3, params3 = _mlp_spec(seed=5, hidden=24)
        receipt3 = pool.reload(params3, plans=plans3)
        assert receipt3["mode"] == "engine"
        assert receipt3["new_compiles"] >= 1
        assert receipt3["digest"] != receipt3["previous_digest"]
        deadline = time.time() + 5.0
        while time.time() < deadline and any(
                rep.batcher.engine.digest != receipt3["digest"]
                for rep in pool.replicas):
            time.sleep(0.05)  # cutover lands between batches
        for rep in pool.replicas:
            assert rep.batcher.engine.digest == receipt3["digest"]
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        pool.stop()
    assert not errors, errors[:3]
    ref_engine = AOTEngine(plans3, params3, (16,), ladder=(8, 32),
                           device=Device(backend="cpu"))
    ref_engine.compile()
    probe = numpy.random.RandomState(6).rand(3, 16).astype(
        numpy.float32)
    assert (pool.engine.infer(probe)
            == ref_engine.infer(probe)).all()
    assert registry.counter("serve.reloads").value >= 2


def test_service_reload_single_engine():
    """The single-engine service mirrors the pool's reload semantics:
    params swap with 0 compiles on the same digest, engine cutover on
    a new one — through the public ServeService surface."""
    plans, params = _mlp_spec(seed=23)
    engine = AOTEngine(plans, params, (16,), ladder=(8,),
                       device=Device(backend="cpu"))
    engine.compile()
    svc = ServeService(engine, max_delay_s=0.001)
    svc.start_background()
    try:
        _, params2 = _mlp_spec(seed=24)
        receipt = svc.reload(params2)
        assert receipt["mode"] == "params"
        assert receipt["new_compiles"] == 0, receipt
        probe = numpy.random.RandomState(7).rand(16).astype(
            numpy.float32)
        answer = svc.infer_payload(probe)
        expect = svc.engine.infer(probe)[0]
        assert (numpy.asarray(answer["probabilities"][0],
                              numpy.float32) == expect).all()
        plans3, params3 = _mlp_spec(seed=25, hidden=24)
        receipt3 = svc.reload(params3, plans=plans3)
        assert receipt3["mode"] == "engine"
        assert svc.engine.digest == receipt3["digest"]
        deadline = time.time() + 5.0
        while time.time() < deadline and \
                svc.batcher.engine.digest != receipt3["digest"]:
            time.sleep(0.05)
        assert svc.batcher.engine.digest == receipt3["digest"]
        assert svc.last_reload is receipt3
    finally:
        svc.stop()


def test_service_over_pool_healthz_and_infer():
    """ServeService drives a whole pool: requests ride the router and
    /healthz carries the per-replica block."""
    import json
    import urllib.request

    pool = _pool(replicas=2, seed=29)
    svc = ServeService(pool, labels_mapping={0: "a", 1: "b", 2: "c",
                                             3: "d"})
    svc.start_background()
    try:
        base = "http://127.0.0.1:%d" % svc.port
        rng = numpy.random.RandomState(8)
        batch = rng.rand(3, 16).astype(numpy.float32)
        req = urllib.request.Request(
            base + "/infer",
            data=json.dumps({"input": batch.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            answer = json.loads(resp.read())
        ref = pool.engine.infer(batch)
        assert (numpy.asarray(answer["probabilities"],
                              numpy.float32) == ref).all()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["replicas"]["replicas"] == 2
        assert health["model_digest"] == pool.digest
        assert health["compile"]["replicas"] == 2
    finally:
        svc.stop()
