"""Real multi-PROCESS distributed tests (SURVEY §4 implication (b):
the JAX analog of the reference's in-process master+slave socket tests
is multi-process jax.distributed on localhost).

Each test spawns N fresh interpreters; every process pins itself to 2
virtual CPU devices, joins the cluster through the same
VELES_COORDINATOR/VELES_NUM_PROCESSES/VELES_PROCESS_ID contract the
launcher's init_multihost reads, and runs real cross-process
collectives on the 2N-device global mesh.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import json, os, sys
pid = int(os.environ["VELES_PROCESS_ID"])
n = int(os.environ["VELES_NUM_PROCESSES"])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from veles_tpu.launcher import Launcher
Launcher.init_multihost()

import numpy
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from veles_tpu.compiler import build_train_step
from veles_tpu.models.zoo import build_plans_and_state
from veles_tpu.parallel import (batch_sharding, replicate,
                                shard_host_batch)

mesh = Mesh(numpy.array(jax.devices()).reshape(-1), ("data",))
specs = [{"type": "all2all_tanh", "output_sample_shape": 16,
          "learning_rate": 0.1, "gradient_moment": 0.9},
         {"type": "softmax", "output_sample_shape": 4,
          "learning_rate": 0.1, "gradient_moment": 0.9}]
plans, state, _ = build_plans_and_state(specs, (8,), seed=7)
with mesh:
    state = replicate(mesh, state)
    step = build_train_step(
        plans, mesh=mesh,
        batch_sharding=batch_sharding(mesh),
        donate=False)
    # every process loads ITS OWN slice (what a per-host Loader window
    # serves); shard_host_batch stitches the global batch
    rng = numpy.random.RandomState(100 + pid)
    local_x = rng.rand(8, 8).astype(numpy.float32)
    local_y = rng.randint(0, 4, 8).astype(numpy.int32)
    x = shard_host_batch(mesh, local_x)
    y = shard_host_batch(mesh, local_y)
    new_state, metrics = step(state, x, y, numpy.float32(8 * n))
    loss = float(metrics["loss"])
    # parameter fingerprint must be IDENTICAL across processes: the
    # gradient all-reduce is the reference's parameter-server merge
    w = new_state[0]["weights"]
    fingerprint = float(jnp.sum(jnp.abs(w)))
print(json.dumps({"pid": pid,
                  "global_devices": len(jax.devices()),
                  "local_devices": len(jax.local_devices()),
                  "loss": loss, "fingerprint": fingerprint}))
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_cluster(n_procs, script):
    port = _free_port()
    procs = []
    for pid in range(n_procs):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "VELES_COORDINATOR": "127.0.0.1:%d" % port,
            "VELES_NUM_PROCESSES": str(n_procs),
            "VELES_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    import time
    outs = []
    try:
        # shared deadline + poll so ONE crashed worker surfaces its
        # stderr immediately instead of the others' barrier timeout
        deadline = time.time() + 240
        while any(p.poll() is None for p in procs):
            if any(p.poll() not in (None, 0) for p in procs):
                break
            if time.time() > deadline:
                raise AssertionError("cluster workers timed out")
            time.sleep(0.3)
        for proc in procs:
            try:
                out, err = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, err = proc.communicate()
                raise AssertionError(
                    "worker hung; peer stderr follows:\n" + err[-2000:])
            assert proc.returncode == 0, err[-2000:]
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a worker that failed or timed out must not orphan the rest
        # at the coordinator barrier; reap after kill so no zombies or
        # open pipes outlive the test
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            try:
                proc.communicate(timeout=10)
            except Exception:
                pass
    return outs


@pytest.mark.slow
def test_two_process_dp_train_step():
    """2 processes x 2 virtual devices: cluster forms a 4-device global
    mesh, each process feeds its local batch slice, one fused DP train
    step runs a REAL cross-process gradient all-reduce, and both
    processes end with bit-identical parameters."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _spawn_cluster(2, _WORKER % {"repo": repo})
    assert [o["global_devices"] for o in outs] == [4, 4]
    assert [o["local_devices"] for o in outs] == [2, 2]
    assert outs[0]["loss"] == outs[1]["loss"]
    assert outs[0]["fingerprint"] == outs[1]["fingerprint"]


_RING_WORKER = r"""
import json, os, sys
pid = int(os.environ["VELES_PROCESS_ID"])
n = int(os.environ["VELES_NUM_PROCESSES"])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from veles_tpu.launcher import Launcher
Launcher.init_multihost()

import numpy
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from veles_tpu.parallel.ring import ring_attention

mesh = Mesh(numpy.array(jax.devices()).reshape(-1), ("seq",))
B, T, H, D = 2, 4 * len(jax.devices()), 2, 8
rng = numpy.random.RandomState(11)  # same on every process
q, k, v = (rng.randn(B, T, H, D).astype(numpy.float32)
           for _ in range(3))
sharding = NamedSharding(mesh, P(None, "seq"))
# identical full arrays on every process -> device_put is legal
qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
with mesh:
    out = ring_attention(qs, ks, vs, mesh, causal=True)
    got = numpy.asarray(
        jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(out))

# oracle: plain causal attention on the full sequence
scale = 1.0 / numpy.sqrt(D)
logits = numpy.einsum("bqhd,bkhd->bhqk", q, k) * scale
mask = numpy.tril(numpy.ones((T, T), bool))
logits = numpy.where(mask[None, None], logits, -1e30)
w = numpy.exp(logits - logits.max(-1, keepdims=True))
w /= w.sum(-1, keepdims=True)
ref = numpy.einsum("bhqk,bkhd->bqhd", w, v)
err = float(numpy.abs(got - ref).max())
print(json.dumps({"pid": pid, "err": err,
                  "devices": len(jax.devices())}))
"""


@pytest.mark.slow
def test_two_process_ring_attention():
    """Ring attention's ppermute hops cross PROCESS boundaries on a
    2-process x 2-device seq mesh and still matches the single-host
    oracle exactly — the long-context sequence-parallel path is
    genuinely multi-host."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    outs = _spawn_cluster(2, _RING_WORKER % {"repo": repo})
    assert [o["devices"] for o in outs] == [4, 4]
    for o in outs:
        assert o["err"] < 2e-5, o
