"""InputJoiner / MeanDispNormalizer / Avatar unit tests across
backends."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.memory import Array
from veles_tpu.service_units import Avatar, InputJoiner, \
    MeanDispNormalizer, Shell


@pytest.mark.parametrize("backend", ["cpu", "numpy"])
def test_input_joiner(backend):
    from veles_tpu.backends import Device
    device = Device(backend=backend)
    wf = DummyWorkflow()
    rng = numpy.random.RandomState(0)
    a = Array(rng.rand(6, 4).astype(numpy.float32))
    b = Array(rng.rand(6, 3).astype(numpy.float32))
    joiner = InputJoiner(wf, inputs=[a, b])
    joiner.initialize(device=device)
    joiner.run()
    joiner.output.map_read()
    numpy.testing.assert_allclose(
        joiner.output.mem,
        numpy.concatenate([a.mem, b.mem], axis=1), rtol=1e-6)


@pytest.mark.parametrize("backend", ["cpu", "numpy"])
def test_mean_disp_normalizer_unit(backend):
    from veles_tpu.backends import Device
    device = Device(backend=backend)
    wf = DummyWorkflow()
    rng = numpy.random.RandomState(1)
    data = rng.rand(8, 5).astype(numpy.float32) * 10
    mean = data.mean(axis=0)
    rdisp = 1.0 / (data.max(axis=0) - data.min(axis=0))
    unit = MeanDispNormalizer(wf)
    unit.input = Array(data)
    unit.mean = mean
    unit.rdisp = rdisp
    unit.initialize(device=device)
    unit.run()
    unit.output.map_read()
    numpy.testing.assert_allclose(
        unit.output.mem, (data - mean) * rdisp, rtol=1e-5)


def test_avatar_clones(cpu_device):
    wf = DummyWorkflow()
    from veles_tpu.dummy import DummyUnit
    src = DummyUnit(wf, output=Array(numpy.ones(4, numpy.float32)))
    avatar = Avatar(wf).clone(src, "output")
    avatar.initialize(device=cpu_device)
    avatar.run()
    avatar.output.map_read()
    numpy.testing.assert_array_equal(avatar.output.mem, numpy.ones(4))
    # mutating the clone leaves the source untouched
    avatar.output.map_write()
    avatar.output.mem[:] = 7
    src.output.map_read()
    numpy.testing.assert_array_equal(src.output.mem, numpy.ones(4))


def test_shell_noop_without_tty():
    wf = DummyWorkflow()
    shell = Shell(wf)
    shell.initialize()
    shell.run()  # stdin is not a tty under pytest: must not block
