"""Request-scoped serve tracing (veles_tpu/observe/requests.py,
docs/observability.md "Request tracing"): trace-id minting and
normalization at the serve port's never-unpickle trust boundary, id
propagation across the HTTP front, the binary front (hello default +
per-frame override) and the pipelined fleet link, the hedged two-leg
stitch under ONE id over socketpair hosts (validate_trace nesting +
the observe/merge.py offset-corrected round-trip), tail-exemplar ring
bounds with shadow/mirror exclusion, SLO-violation flight dumps that
carry the offending timeline, arrival-anchored end-to-end latency
under chaos requeue, and the ``python -m veles_tpu.observe requests``
critical-path analyzer CLI."""

import json
import socket
import time
import urllib.request

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.observe import requests as reqtrace
from veles_tpu.observe.trace import tracer, validate_trace
from veles_tpu.serve import (
    AOTEngine, BinaryTransportServer, ContinuousBatcher, FleetRouter,
    ServeService)
from veles_tpu.serve.transport import BinaryTransportClient
from tests.test_serve import _mlp_spec
from tests.test_serve_fleet import _Hosts, _counter, _wait_for

pytestmark = [pytest.mark.serve, pytest.mark.reqtrace]


def _engine(seed=0):
    plans, params = _mlp_spec(seed=seed)
    eng = AOTEngine(plans, params, (16,), ladder=(8, 32),
                    device=Device(backend="cpu"))
    eng.compile()
    return eng


# -- id contract (trust boundary) -------------------------------------------


def test_mint_and_normalize_trace_ids():
    """Minted ids are unique, short, and pass their own normalization;
    anything that crossed the wire is accepted only as a bounded plain
    string (the serve port never unpickles — ids do not change that)."""
    a, b = reqtrace.mint_trace_id(), reqtrace.mint_trace_id()
    assert a != b
    assert reqtrace.normalize_trace_id(a) == a
    assert reqtrace.normalize_trace_id("  cli-1.2:x_y-Z  ") == \
        "cli-1.2:x_y-Z"
    for bad in (None, 17, b"bytes", "", "has space", "semi;colon",
                "x" * 65, {"trace": "dict"}, ["list"]):
        assert reqtrace.normalize_trace_id(bad) is None


def test_sampling_is_deterministic_in_the_id():
    """Keep/drop hashes the id, no RNG: the two legs of one hedged
    request — different hosts, different processes — make the SAME
    decision, which is what lets them stitch under one id."""
    ids = ["req-%d" % i for i in range(400)]
    first = [reqtrace.sampled(t, rate=0.5) for t in ids]
    assert first == [reqtrace.sampled(t, rate=0.5) for t in ids]
    kept = sum(first)
    assert 0 < kept < len(ids)  # rate actually partitions
    assert all(reqtrace.sampled(t, rate=1.0) for t in ids)
    assert not any(reqtrace.sampled(t, rate=0.0) for t in ids)
    assert not reqtrace.sampled(None, rate=1.0)


# -- tail-exemplar ring ------------------------------------------------------


def test_exemplar_ring_bound_and_shadow_exclusion():
    """The ring is bounded, keeps over-budget timelines, and never
    keeps shadow/mirror traffic no matter how slow it ran."""
    ring = reqtrace.ExemplarRing(capacity=4, window=16, min_samples=4)
    marks = [("queue", 10.0, 0.001), ("device", 10.001, 0.040)]
    # shadow traffic is excluded outright
    assert not ring.note("shadow-1", 9.9, marks=marks, t0=10.0,
                         budget_s=0.1, shadow=True)
    assert ring.kept == 0
    # over-budget requests are kept with their full timeline
    for i in range(10):
        assert ring.note("slow-%d" % i, 0.5, marks=marks, t0=10.0,
                         slo_class="interactive", budget_s=0.1,
                         kind="host", extra={"hedges": 0})
    snap = ring.snapshot()
    assert snap["capacity"] == 4
    assert len(snap["entries"]) == 4  # bounded: oldest evicted
    assert snap["kept"] == 10
    assert snap["seen"] == 10  # shadow notes are not even counted
    entry = snap["entries"][-1]
    assert entry["trace"] == "slow-9"
    assert entry["over"] == "budget"
    assert [m["seg"] for m in entry["timeline"]] == ["queue", "device"]
    assert entry["timeline"][1]["dur_s"] == pytest.approx(0.040)
    assert entry["hedges"] == 0
    # fast traffic under budget and under the rolling p99 is not kept
    assert not ring.note("fast", 0.001, marks=marks, t0=10.0,
                         budget_s=0.1)
    ring.clear()
    assert ring.snapshot()["entries"] == []


# -- HTTP front --------------------------------------------------------------


def test_http_front_propagates_and_echoes_trace_id():
    """A client id rides the body or the X-Trace-Id header and is
    echoed back; an id that fails normalization is REPLACED by a
    server-minted one — never trusted, never erred on."""
    svc = ServeService(_engine(seed=11), max_delay_s=0.002)
    svc.start_background()
    try:
        base = "http://127.0.0.1:%d" % svc.port
        row = numpy.zeros(16, numpy.float32).tolist()

        def post(body, headers=()):
            req = urllib.request.Request(
                base + "/infer", data=json.dumps(body).encode(),
                headers=dict({"Content-Type": "application/json"},
                             **dict(headers)))
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read())

        answer = post({"input": row, "trace": "cli-http-1"})
        assert answer["trace"] == "cli-http-1"
        answer = post({"input": row},
                      headers={"X-Trace-Id": "hdr-trace-2"})
        assert answer["trace"] == "hdr-trace-2"
        # malformed wire id: minted server-side instead
        answer = post({"input": row, "trace": "bad id!"})
        assert answer["trace"] != "bad id!"
        assert reqtrace.normalize_trace_id(answer["trace"])
        # no id offered: one is still minted while tracing is enabled
        answer = post({"input": row})
        assert reqtrace.normalize_trace_id(answer["trace"])
    finally:
        svc.stop()


# -- binary front ------------------------------------------------------------


@pytest.mark.transport
def test_binary_front_hello_default_and_per_frame_override():
    """``trace=True`` in the hello makes the server mint an id per
    frame; an explicit ``infer(..., trace=...)`` overrides it; the
    reply echoes the id plus the per-segment breakdown the host
    batcher stamped (queue/assemble/h2d/device/d2h at minimum)."""
    batcher = ContinuousBatcher(_engine(seed=12),
                                max_delay_s=0.002).start()
    server = BinaryTransportServer(batcher, port=None,
                                   host_meta={"host_id": "h0"})
    server.start_background()
    client = None
    try:
        ours, theirs = socket.socketpair()
        server.serve_socket(ours)
        client = BinaryTransportClient(sock=theirs, shm=False,
                                       trace=True)
        x = numpy.zeros(16, numpy.float32)
        out = client.infer(x)
        assert out.shape == (1, 4)
        minted = client.last_trace
        assert reqtrace.normalize_trace_id(minted)
        client.infer(x, trace="cli-bin.7")
        assert client.last_trace == "cli-bin.7"
        segs = client.last_segments
        assert isinstance(segs, dict)
        for seg in ("queue", "assemble", "h2d", "device", "d2h"):
            assert seg in segs and segs[seg] >= 0.0
        assert set(segs) <= set(reqtrace.SEGMENTS)
        # a malformed per-frame id falls back to the hello default
        client.infer(x, trace="not ok!")
        assert client.last_trace != "not ok!"
        assert reqtrace.normalize_trace_id(client.last_trace)
    finally:
        if client is not None:
            client.close()
        server.stop()
        batcher.stop()


# -- SLO-violation dump ------------------------------------------------------


@pytest.mark.chaos
def test_slo_violation_dump_carries_exemplar_timeline(tmp_path):
    """A chaos-stalled request breaches the SLO watch: the ENTER-edge
    flight dump must carry the tail exemplars, timeline included, so
    the violation arrives WITH the offending request's breakdown."""
    batcher = ContinuousBatcher(_engine(seed=13), max_delay_s=0.002,
                                slo_p99_ms=1.0,
                                slo_check_every=1).start()
    # the stall must push the request PAST its 100 ms interactive
    # budget (qos.DEFAULT_SLO_BUDGETS_S) or the ring keeps nothing
    chaos.install(chaos.FaultPlan(seed=1).add(
        "serve.stall", "stall", nth=1, param=0.15))
    try:
        req = batcher.submit(numpy.zeros(16, numpy.float32),
                             slo_class="interactive",
                             trace="slo-breach-1")
        assert req.done.wait(10) and req.error is None

        def dumped():
            return list(tmp_path.glob(
                "veles_flight.serve.slo_violation.*.json"))

        _wait_for(lambda: bool(dumped()), what="SLO-violation dump")
    finally:
        chaos.uninstall()
        batcher.stop()
    doc = json.loads(dumped()[0].read_text())
    assert doc["kind"] == "flight"
    assert doc["reason"] == "serve.slo_violation"
    entries = doc["exemplars"]["entries"]
    assert entries, "dump carries no exemplar timelines"
    mine = [e for e in entries if e["trace"] == "slo-breach-1"]
    assert mine, "the breaching request is not among the exemplars"
    segs = {m["seg"] for m in mine[0]["timeline"]}
    assert {"queue", "device"} <= segs
    assert mine[0]["class"] == "interactive"
    # and the analyzer folds the dump directly
    report = reqtrace.analyze_files([str(dumped()[0])])
    assert report["exemplars"] >= 1
    assert "device" in report["segments"]


# -- critical-path attribution ----------------------------------------------


@pytest.mark.chaos
def test_analyzer_attributes_tail_to_device_stall(tmp_path):
    """The e2e attribution receipt: among fast requests, ONE rides a
    chaos device-edge stall; the analyzer's tail block names that
    request as worst and attributes its latency to the ``device``
    segment — the question aggregate histograms cannot answer."""
    batcher = ContinuousBatcher(_engine(seed=14),
                                max_delay_s=0.002).start()
    tracer.start()
    try:
        x = numpy.zeros(16, numpy.float32)
        for i in range(12):
            req = batcher.submit(x, trace="fast-%d" % i)
            assert req.done.wait(10) and req.error is None
        chaos.install(chaos.FaultPlan(seed=2).add(
            "serve.device.stall", "stall", nth=1, param=0.25))
        try:
            req = batcher.submit(x, trace="tail-dev-1")
            assert req.done.wait(10) and req.error is None
        finally:
            chaos.uninstall()
    finally:
        tracer.stop()
        batcher.stop()
    path = tracer.save(str(tmp_path / "serve_trace.json"))
    validate_trace(json.loads(open(path).read()))
    report = reqtrace.analyze_files([path])
    assert report["requests"] == 13
    assert report["segments"]["device"]["max_ms"] >= 200.0
    worst = report["tail"]["worst"]
    assert worst["trace"] == "tail-dev-1"
    assert worst["dominant"] == "device"
    assert report["tail"]["dominant"].get("device", 0) >= 1


# -- fleet: hedged two-leg stitch under one id -------------------------------


@pytest.mark.chaos
@pytest.mark.fleet
def test_hedged_two_leg_stitch_under_one_id(tmp_path):
    """The tentpole receipt: a chaos-stalled primary forces a hedge;
    the merged timeline shows BOTH legs under ONE request id — the
    fleet-tier parent, one leg span per dispatch on two distinct
    hosts, and the winning host's own segment spans — and the
    analyzer folds the two per-process files into one record via the
    merge.py offset-corrected stitch."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge_factor=1.5, hedge_floor_s=0.05,
                         hedge_tick_s=0.01).start()
    try:
        for i in range(2):
            hosts.connect(router, i)
        x = numpy.random.RandomState(4).rand(
            3, 16).astype(numpy.float32)
        for i in range(router.hedge_warmup):
            router.infer(x[i % 2], timeout=15.0)
        sampled = _counter("serve.reqtrace.sampled")
        tracer.start()
        chaos.install(chaos.FaultPlan(seed=1).add(
            "serve.host.stall", "stall", nth=1, param=2.0))
        try:
            out = router.infer(x[2], timeout=15.0, trace="stitch-1")
            assert out.shape == (4,)
            # the fleet parent emits on the reader thread after
            # done.set(); the winning host emitted before its reply
            _wait_for(lambda: _counter("serve.reqtrace.sampled")
                      >= sampled + 2, what="request-span emission")
        finally:
            chaos.uninstall()
            tracer.stop()
    finally:
        router.stop()
        hosts.stop()

    events = tracer.events
    validate_trace({"traceEvents": events})
    named = lambda e: (e.get("args") or {})
    fleet_req = [e for e in events
                 if e.get("name") == reqtrace.REQUEST_SPAN
                 and named(e).get("tier") == "fleet"]
    assert len(fleet_req) == 1
    assert named(fleet_req[0])["trace"] == "stitch-1"
    assert named(fleet_req[0])["hedges"] >= 1
    legs = [e for e in events if e.get("name") == reqtrace.LEG_SPAN]
    assert len(legs) >= 2
    assert len({named(e).get("host") for e in legs}) == 2
    host_req = [e for e in events
                if e.get("name") == reqtrace.REQUEST_SPAN
                and named(e).get("tier") == "host"]
    assert host_req, "the winning host emitted no request span"
    assert all(named(e)["trace"] == "stitch-1" for e in host_req)
    assert all(named(e).get("host") in ("h0", "h1") for e in host_req)

    # split into per-process files (front vs host) and round-trip the
    # analyzer through the offset-corrected merge stitch
    saved = json.loads(open(tracer.save(
        str(tmp_path / "all.json"))).read())
    other = saved["otherData"]
    front, host = [], []
    for e in events:
        if e.get("ph") == "i" or named(e).get("tier") == "fleet" or \
                e.get("name") == reqtrace.LEG_SPAN:
            front.append(e)
        elif e.get("cat") == "req":
            host.append(e)
    paths = []
    for label, evts in (("front", front), ("host0", host)):
        doc = {"traceEvents": evts,
               "otherData": dict(other, label=label)}
        path = tmp_path / (label + ".json")
        path.write_text(json.dumps(doc))
        paths.append(str(path))
    report = reqtrace.analyze_files(paths)
    assert report["files"] == ["front", "host0"]
    assert report["requests"] == 1  # both legs fold under ONE id
    assert report["legs"] >= 3  # 2 front leg spans + the host leg
    assert report["hedge"]["fired"] >= 1
    assert report["hedge"]["hedged_requests"] == 1
    assert report["tail"]["worst"]["trace"] == "stitch-1"
    assert "device" in report["segments"]


# -- arrival-anchored latency under requeue ----------------------------------


@pytest.mark.chaos
@pytest.mark.fleet
def test_requeue_latency_anchored_at_original_arrival():
    """Satellite regression: end-to-end latency is measured from the
    ORIGINAL front-door arrival.  Requests wedged on a host that dies
    are requeued to the survivor; a requeue must never restart the
    latency clock, so the reported latency covers the wedge, not just
    the survivor's quick service."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge=False).start()  # isolate the requeue
    try:
        for i in range(2):
            hosts.connect(router, i)
        x = numpy.random.RandomState(5).rand(
            6, 16).astype(numpy.float32)
        ref = hosts.entries[0][0].infer(x)
        requeues_before = _counter("serve.fleet.requeues")
        # wedge ONLY h0 (the host-scoped chaos point) so survivors
        # answer fast and the wedge time is attributable
        chaos.install(chaos.FaultPlan(seed=2).add(
            "serve.host.stall:h0", "stall", times=8, param=5.0))
        try:
            t0 = time.perf_counter()
            reqs = [router.submit(row, trace="rq-%d" % i)
                    for i, row in enumerate(x)]
            time.sleep(0.3)  # the wedged requests age on h0
            hosts.stop(0)
            for req in reqs:
                assert req.done.wait(20), "request dropped"
                assert req.error is None, req.error
            elapsed = time.perf_counter() - t0
        finally:
            chaos.uninstall()
        for req, want in zip(reqs, ref):
            assert (req.result == want).all()
        requeued = [r for r in reqs if r.requeues >= 1]
        assert requeued, "no request was requeued off the dead host"
        assert _counter("serve.fleet.requeues") > requeues_before
        for req in requeued:
            # anchored at arrival: the 0.3 s wedge is part of the
            # latency; a clock restarted at requeue would report only
            # the survivor's few-ms service time
            assert req.latency >= 0.28, req.latency
            assert req.latency <= elapsed + 0.05
    finally:
        router.stop()
        hosts.stop(1)


# -- analyzer CLI ------------------------------------------------------------


def test_observe_requests_cli_roundtrip(tmp_path, capsys):
    """``python -m veles_tpu.observe requests`` renders the digest
    from a recorded SLO dump, ``--json`` emits the machine report, and
    the ``summary`` command appends the per-request-segment digest."""
    from veles_tpu.observe.__main__ import main
    from veles_tpu.observe.flight import flight
    # earlier serve tests left request spans in the process-shared
    # flight ring; the analyzer would fold them into this dump too
    flight.clear()
    ring = reqtrace.ExemplarRing(capacity=8, window=8, min_samples=2)
    marks = [("queue", 5.0, 0.002), ("device", 5.002, 0.120),
             ("d2h", 5.122, 0.001)]
    for i in range(3):
        ring.note("cli-%d" % i, 0.123 + i * 0.01, marks=marks, t0=5.0,
                  slo_class="interactive", budget_s=0.1)
    path = str(tmp_path / "slo_dump.json")
    assert ring.dump(path=path) == path

    assert main(["requests", path]) == 0
    text = capsys.readouterr().out
    assert "request digest: 3 requests" in text
    assert "device" in text and "tail" in text

    assert main(["requests", path, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["kind"] == "requests"
    assert report["exemplars"] == 3
    assert report["segments"]["device"]["count"] == 3
    assert report["tail"]["worst"]["dominant"] == "device"

    assert main(["summary", path]) == 0
    text = capsys.readouterr().out
    assert "request segments: 3 requests" in text
