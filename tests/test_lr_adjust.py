"""LR policies + rollback unit tests."""

import numpy

from veles_tpu.dummy import DummyWorkflow, DummyUnit
from veles_tpu.memory import Array
from veles_tpu.models.lr_adjust import (
    LearningRateAdjust, Rollback, exp_policy, fixed_policy, inv_policy,
    step_exp_policy)
from veles_tpu.mutable import Bool


def test_policies():
    assert fixed_policy(0.1)(100) == 0.1
    assert abs(step_exp_policy(0.1, 0.5, 10)(25) - 0.025) < 1e-12
    assert abs(exp_policy(1.0, 0.9)(2) - 0.81) < 1e-12
    assert abs(inv_policy(1.0, 1.0, 1.0)(1) - 0.5) < 1e-12


def test_lr_adjust_applies_to_gds():
    wf = DummyWorkflow()
    gd = DummyUnit(wf, learning_rate=1.0, learning_rate_bias=1.0)
    adj = LearningRateAdjust(wf, lr_policy=exp_policy(1.0, 0.5))
    adj.add_gd_unit(gd)
    adj._is_initialized_ = True
    adj.run()
    assert gd.learning_rate == 0.5
    adj.run()
    assert gd.learning_rate == 0.25


def test_rollback_restores_best():
    wf = DummyWorkflow()
    w = Array(numpy.ones(4, numpy.float32))
    gd = DummyUnit(wf, weights=w, learning_rate=1.0,
                   learning_rate_bias=1.0)
    improved = Bool(True)
    rb = Rollback(wf, lr_cut=0.5)
    rb.improved = improved
    rb.add_gd_unit(gd)
    rb.initialize()
    rb.run()  # snapshot of ones
    w.map_write()
    w.mem[:] = 99.0
    improved <<= False
    rb.run()  # slip -> restore
    numpy.testing.assert_array_equal(w.mem, numpy.ones(4))
    assert gd.learning_rate == 0.5
