"""Snapshot -> resume end-to-end (reference: snapshotter.py:522 +
workflow.py:338-340 + SURVEY.md section 3.4): training state, RNG, and
epoch counters survive the pickle round-trip and training continues."""

import os
import pickle

import numpy
import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator
from veles_tpu.snapshotter import Snapshotter, SnapshotterBase
from tests.test_models import BlobsLoader


def _build(device, max_epochs):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("snap", seed=9)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.initialize(device=device)
    return sw


def test_snapshot_resume_continues_training(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=2)
    sw.run()
    assert bool(sw.decision.complete)
    epoch_before = sw.decision.epoch_number
    sw.forwards[0].weights.map_read()
    weights_before = numpy.array(sw.forwards[0].weights.mem)

    blob = pickle.dumps(sw, protocol=pickle.HIGHEST_PROTOCOL)
    restored = pickle.loads(blob)

    # reattach to a fresh launcher and continue for 2 more epochs
    restored.workflow = DummyLauncher()
    restored.restored_from_snapshot_ = True
    restored.decision.max_epochs = 4
    restored.decision.complete <<= False
    restored.initialize(device=cpu_device)

    # weights survived the round trip
    restored.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        restored.forwards[0].weights.mem, weights_before)
    # epoch counter continued, not reset
    assert restored.loader.epoch_number == epoch_before

    restored.run()
    assert bool(restored.decision.complete)
    assert restored.decision.epoch_number >= 4
    assert restored.decision.epoch_metrics[1] < 5.0


def test_snapshotter_unit_writes_and_imports(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="t",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    sw.run()
    snap.run()
    assert snap.destination and os.path.exists(snap.destination)
    # _current symlink maintained (reference :388-409)
    link = os.path.join(str(tmp_path), "t_current")
    assert os.path.islink(link)

    restored = SnapshotterBase.import_file(snap.destination)
    assert type(restored).__name__ == "StandardWorkflow"
    restored.workflow = DummyLauncher()
    restored.initialize(device=cpu_device)
    restored.forwards[0].weights.map_read()
    sw.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        restored.forwards[0].weights.mem, sw.forwards[0].weights.mem)


def test_snapshotter_codecs(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    for codec in ("", "gz", "bz2", "xz"):
        snap = Snapshotter(sw, directory=str(tmp_path),
                           prefix="c%s" % (codec or "raw"), interval=1,
                           time_interval=0, compression=codec)
        snap.initialize()
        snap.export()
        restored = SnapshotterBase.import_file(snap.destination)
        assert restored is not None


def test_slave_never_snapshots(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    sw.workflow.workflow_mode = "slave"
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="s",
                       interval=1, time_interval=0)
    snap.initialize()
    snap.run()
    assert snap.destination is None
