"""Snapshot -> resume end-to-end (reference: snapshotter.py:522 +
workflow.py:338-340 + SURVEY.md section 3.4): training state, RNG, and
epoch counters survive the pickle round-trip and training continues;
plus the crash-consistency layer — atomic writes, sidecar manifests,
verification + previous-good fallback, retention, run gating, and the
snapshot-db failure path (ISSUE 2)."""

import gzip
import os
import pickle
import time

import numpy
import pytest

from veles_tpu.config import root
from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator
from veles_tpu.snapshotter import (
    MANIFEST_SUFFIX, SnapshotError, Snapshotter, SnapshotterBase)
from tests.test_models import BlobsLoader


def _build(device, max_epochs):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("snap", seed=9)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.initialize(device=device)
    return sw


def test_snapshot_resume_continues_training(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=2)
    sw.run()
    assert bool(sw.decision.complete)
    epoch_before = sw.decision.epoch_number
    sw.forwards[0].weights.map_read()
    weights_before = numpy.array(sw.forwards[0].weights.mem)

    blob = pickle.dumps(sw, protocol=pickle.HIGHEST_PROTOCOL)
    restored = pickle.loads(blob)

    # reattach to a fresh launcher and continue for 2 more epochs
    restored.workflow = DummyLauncher()
    restored.restored_from_snapshot_ = True
    restored.decision.max_epochs = 4
    restored.decision.complete <<= False
    restored.initialize(device=cpu_device)

    # weights survived the round trip
    restored.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        restored.forwards[0].weights.mem, weights_before)
    # epoch counter continued, not reset
    assert restored.loader.epoch_number == epoch_before

    restored.run()
    assert bool(restored.decision.complete)
    assert restored.decision.epoch_number >= 4
    assert restored.decision.epoch_metrics[1] < 5.0


def test_snapshotter_unit_writes_and_imports(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="t",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    sw.run()
    snap.run()
    assert snap.destination and os.path.exists(snap.destination)
    # _current symlink maintained (reference :388-409)
    link = os.path.join(str(tmp_path), "t_current")
    assert os.path.islink(link)

    restored = SnapshotterBase.import_file(snap.destination)
    assert type(restored).__name__ == "StandardWorkflow"
    restored.workflow = DummyLauncher()
    restored.initialize(device=cpu_device)
    restored.forwards[0].weights.map_read()
    sw.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        restored.forwards[0].weights.mem, sw.forwards[0].weights.mem)


def test_snapshotter_codecs(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    for codec in ("", "gz", "bz2", "xz"):
        snap = Snapshotter(sw, directory=str(tmp_path),
                           prefix="c%s" % (codec or "raw"), interval=1,
                           time_interval=0, compression=codec)
        snap.initialize()
        snap.export()
        restored = SnapshotterBase.import_file(snap.destination)
        assert restored is not None


def test_slave_never_snapshots(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    sw.workflow.workflow_mode = "slave"
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="s",
                       interval=1, time_interval=0)
    snap.initialize()
    snap.run()
    assert snap.destination is None


# -- run gating (interval / time_interval / skip) -------------------------


class _RecordingSnapshotter(SnapshotterBase):
    """Counts exports without paying for a real workflow pickle."""

    def __init__(self, *args, **kwargs):
        super(_RecordingSnapshotter, self).__init__(*args, **kwargs)
        self.exports = 0

    def export(self):
        self.exports += 1
        self.destination = os.path.join(
            self.directory, "%s_fake%d" % (self.prefix, self.exports))


def test_run_gating_interval(tmp_path):
    snap = _RecordingSnapshotter(
        DummyWorkflow(), directory=str(tmp_path), interval=2,
        time_interval=0)
    snap.initialize()
    snap.run()
    assert snap.exports == 0, "counter 1 is not a multiple of 2"
    snap.run()
    assert snap.exports == 1
    snap.run()
    snap.run()
    assert snap.exports == 2


def test_run_gating_time_interval_first_snapshot_exempt(tmp_path):
    """The throttle only applies to REPEAT snapshots: a short run (or
    an early crash) must still leave one snapshot on disk."""
    snap = _RecordingSnapshotter(
        DummyWorkflow(), directory=str(tmp_path), interval=1,
        time_interval=3600)
    snap.initialize()
    snap.run()
    assert snap.exports == 1, "first snapshot must ignore time_interval"
    snap.run()
    assert snap.exports == 1, "repeat within time_interval throttled"


def test_run_gating_skip_bool(tmp_path):
    snap = _RecordingSnapshotter(
        DummyWorkflow(), directory=str(tmp_path), interval=1,
        time_interval=0)
    snap.initialize()
    snap.skip <<= True
    snap.run()
    snap.run()
    assert snap.exports == 0
    snap.skip <<= False
    snap.run()
    assert snap.exports == 1


def test_run_gating_disable_config(tmp_path):
    snap = _RecordingSnapshotter(
        DummyWorkflow(), directory=str(tmp_path), interval=1,
        time_interval=0)
    snap.initialize()
    root.common.disable.update({"snapshotting": True})
    try:
        snap.run()
        assert snap.exports == 0
    finally:
        root.common.disable.update({"snapshotting": False})
    snap.run()
    assert snap.exports == 1


# -- import_file: codec sniffing on damaged files -------------------------


def test_import_file_zero_byte(tmp_path):
    path = tmp_path / "empty.pickle"
    path.write_bytes(b"")
    with pytest.raises(SnapshotError) as err:
        SnapshotterBase.import_file(str(path))
    assert "no usable snapshot" in str(err.value)


def test_import_file_truncated_gz(tmp_path):
    blob = gzip.compress(pickle.dumps({"k": list(range(1000))}))
    path = tmp_path / "cut.pickle.gz"
    path.write_bytes(blob[:len(blob) // 2])  # valid magic, torn body
    with pytest.raises(SnapshotError):
        SnapshotterBase.import_file(str(path))


def test_import_file_truncated_plain_pickle(tmp_path):
    blob = pickle.dumps({"k": 1})
    path = tmp_path / "cut.pickle"
    path.write_bytes(blob[:-3])
    with pytest.raises(SnapshotError):
        SnapshotterBase.import_file(str(path))


def test_import_file_sniffs_extensionless(tmp_path):
    """The _current symlink carries no extension: the codec must come
    from the magic bytes."""
    path = tmp_path / "no_extension"
    path.write_bytes(gzip.compress(pickle.dumps({"ok": 42})))
    assert SnapshotterBase.import_file(str(path)) == {"ok": 42}


# -- manifest / atomicity / retention -------------------------------------


def test_export_writes_verified_manifest(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    sw.run()
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="m",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    snap.export()
    dest = snap.destination
    assert os.path.exists(dest + MANIFEST_SUFFIX)
    assert not os.path.exists(dest + ".tmp"), "tmp residue after export"
    ok, manifest = SnapshotterBase.verify_snapshot(dest)
    assert ok is True
    assert manifest["nbytes"] == os.path.getsize(dest)
    assert manifest["codec"] == "gz"
    assert manifest["workflow"] == "StandardWorkflow"
    assert manifest["checksum"] == sw.checksum
    # the _current link verifies through to the same manifest
    link = os.path.join(str(tmp_path), "m_current")
    ok, _ = SnapshotterBase.verify_snapshot(link)
    assert ok is True


def test_verify_snapshot_detects_truncation_and_corruption(
        tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="v",
                       interval=1, time_interval=0, compression="")
    snap.initialize()
    snap.export()
    dest = snap.destination
    original = open(dest, "rb").read()
    # truncation -> size mismatch
    with open(dest, "wb") as fout:
        fout.write(original[:-10])
    ok, reason = SnapshotterBase.verify_snapshot(dest)
    assert ok is False and "size mismatch" in reason
    # same-size corruption -> sha mismatch
    with open(dest, "wb") as fout:
        fout.write(original[:-1] + bytes([original[-1] ^ 0xFF]))
    ok, reason = SnapshotterBase.verify_snapshot(dest)
    assert ok is False and "sha256" in reason
    # restored bytes verify again
    with open(dest, "wb") as fout:
        fout.write(original)
    assert SnapshotterBase.verify_snapshot(dest)[0] is True


def test_legacy_snapshot_without_manifest_still_imports(tmp_path,
                                                        cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="l",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    snap.export()
    os.remove(snap.destination + MANIFEST_SUFFIX)
    ok, reason = SnapshotterBase.verify_snapshot(snap.destination)
    assert ok is None and reason == "no manifest"
    assert SnapshotterBase.import_file(snap.destination) is not None


def test_retention_keeps_newest_and_current(tmp_path, cpu_device):
    sw = _build(cpu_device, max_epochs=1)
    sw.run()
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="r",
                       interval=1, time_interval=0, compression="gz",
                       keep=2)
    snap.initialize()
    for i in range(5):
        snap.suffix = "e%d" % i
        snap.export()
        time.sleep(0.02)  # distinct mtimes for the retention sort
    pickles = sorted(f for f in os.listdir(str(tmp_path))
                     if ".pickle" in f and not f.endswith(MANIFEST_SUFFIX)
                     and not f.endswith(".tmp"))
    # keep=2 (+ best-by-metric may add one more)
    assert len(pickles) <= 3
    assert any("e4" in f for f in pickles), "newest must survive"
    assert any("e3" in f for f in pickles)
    link = os.path.join(str(tmp_path), "r_current")
    target = os.path.realpath(link)
    assert os.path.exists(target), "_current target must never be pruned"
    # manifests of pruned snapshots are pruned with them
    manifests = [f for f in os.listdir(str(tmp_path))
                 if f.endswith(MANIFEST_SUFFIX)]
    assert len(manifests) == len(pickles)


def test_resolve_resume(tmp_path, cpu_device):
    assert SnapshotterBase.resolve_resume("") is None
    assert SnapshotterBase.resolve_resume(
        "auto", directory=str(tmp_path / "missing")) is None
    with pytest.raises(SnapshotError):
        SnapshotterBase.resolve_resume(str(tmp_path / "nope.pickle"))
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="a",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()
    snap.suffix = "one"
    snap.export()
    resolved = SnapshotterBase.resolve_resume(
        "auto", directory=str(tmp_path))
    assert resolved == os.path.realpath(
        os.path.join(str(tmp_path), "a_current"))
    # explicit path resolves to itself
    assert SnapshotterBase.resolve_resume(snap.destination) == \
        snap.destination


# -- satellite regressions ------------------------------------------------


def test_record_in_db_failure_warns_not_raises(tmp_path, cpu_device,
                                               caplog):
    """A locked/readonly/unopenable snapshot DB must never abort the
    training step after a successful snapshot write."""
    sw = _build(cpu_device, max_epochs=1)
    bad_db = os.path.join(str(tmp_path), "no_such_dir", "snap.sqlite")
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="db",
                       interval=1, time_interval=0, compression="gz",
                       db_path=bad_db)
    snap.initialize()
    snap.export()  # must not raise
    assert snap.destination and os.path.exists(snap.destination)
    assert any("snapshot db record failed" in r.message
               for r in caplog.records)


def test_failed_current_link_flip_warns(tmp_path, cpu_device,
                                        monkeypatch, caplog):
    """A failed _current flip silently strands resume on an OLD
    snapshot — it must at least be visible in the log."""
    sw = _build(cpu_device, max_epochs=1)
    snap = Snapshotter(sw, directory=str(tmp_path), prefix="ln",
                       interval=1, time_interval=0, compression="gz")
    snap.initialize()

    def broken_symlink(*args, **kwargs):
        raise OSError("symlinks unavailable")

    monkeypatch.setattr(os, "symlink", broken_symlink)
    snap.export()  # must not raise
    assert snap.destination and os.path.exists(snap.destination)
    assert any("failed to update snapshot link" in r.message
               for r in caplog.records)


class OtherWorkflow(StandardWorkflow):
    """A second model snapshotting into the same directory."""

    hide_from_registry = True


def test_fallback_never_crosses_workflows(tmp_path, cpu_device, caplog):
    """A shared snapshot directory holds several models' histories; a
    corrupted snapshot must fall back to ITS OWN workflow's previous
    good snapshot, never to a newer snapshot of a different one."""
    sw = _build(cpu_device, max_epochs=1)
    mine = Snapshotter(sw, directory=str(tmp_path), prefix="mine",
                       interval=1, time_interval=0, compression="gz")
    mine.initialize()
    mine.suffix = "old"
    mine.export()
    my_old = mine.destination
    time.sleep(0.02)
    mine.suffix = "new"
    mine.export()
    my_new = mine.destination

    time.sleep(0.02)
    other_sw = _build(cpu_device, max_epochs=1)
    object.__setattr__(other_sw, "__class__", OtherWorkflow)
    other = Snapshotter(other_sw, directory=str(tmp_path),
                        prefix="other", interval=1, time_interval=0,
                        compression="gz")
    other.initialize()
    other.export()  # newest file in the directory, wrong workflow

    with open(my_new, "r+b") as fout:  # corrupt my newest
        fout.seek(os.path.getsize(my_new) // 2)
        byte = fout.read(1)
        fout.seek(-1, os.SEEK_CUR)
        fout.write(bytes([byte[0] ^ 0xFF]))

    restored = SnapshotterBase.import_file(
        os.path.join(str(tmp_path), "mine_current"))
    assert type(restored).__name__ == "StandardWorkflow", \
        "fell back to a different workflow's snapshot"
    assert any(os.path.basename(my_old) in r.message and
               "previous-good" in r.message for r in caplog.records)
