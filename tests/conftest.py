"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
sharding/collective tests run anywhere (SURVEY.md section 4 implication b)."""

import os

# Force CPU even when the host pins JAX_PLATFORMS (e.g. axon): the suite
# must run on the virtual 8-device mesh.  Set VELES_TEST_TPU=1 to opt back
# into real-chip runs.  The env var alone is not enough on hosts whose
# sitecustomize re-pins the platform, so also update jax.config directly.
if not os.environ.get("VELES_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("VELES_BACKEND", "cpu")

import jax  # noqa: E402

if not os.environ.get("VELES_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def _open_shm_channels():
    """Not-yet-closed ShmChannel segments, without importing the module
    into tests that never touched the network layer."""
    import sys
    mod = sys.modules.get("veles_tpu.network_common")
    if mod is None:
        return set()
    return mod.ShmChannel.open_channels()


@pytest.fixture(autouse=True)
def _no_resource_leaks():
    """Fail any test leaking a live NON-daemon thread (it outlives
    pytest and hangs CI) or an open ShmChannel shared-memory segment
    (an abandoned creator-side segment survives as a /dev/shm file
    past process death).  Guards the input-pipeline prefetch worker,
    every thread_pool.py user, and the control plane's same-host
    payload bypass — resources must be released by the code under
    test, not abandoned."""
    import threading
    import time

    before = set(threading.enumerate())
    shm_before = _open_shm_channels()
    yield
    deadline = time.time() + 3.0
    leaked = []
    leaked_shm = []
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive() and not t.daemon]
        leaked_shm = [c for c in _open_shm_channels()
                      if c not in shm_before]
        if not leaked and not leaked_shm:
            return
        time.sleep(0.05)  # give wind-downs in progress a moment
    problems = []
    if leaked:
        problems.append("non-daemon thread(s): %s" %
                        ", ".join(sorted(t.name for t in leaked)))
    if leaked_shm:
        # close them so one leak does not cascade into later tests
        names = sorted(c.name for c in leaked_shm)
        for chan in leaked_shm:
            chan.close()
        problems.append("ShmChannel segment(s): %s" % ", ".join(names))
    pytest.fail("leaked " + "; ".join(problems))


@pytest.fixture(autouse=True)
def _no_chaos_bleed():
    """A fault plan left installed by a failing chaos test must never
    inject faults into unrelated tests."""
    yield
    import sys
    mod = sys.modules.get("veles_tpu.chaos")
    if mod is not None and mod.plan is not None:
        mod.uninstall()


@pytest.fixture(autouse=True)
def _flight_dumps_to_tmp(tmp_path, monkeypatch):
    """The always-on flight recorder dumps on divergence / rollback /
    quarantine — which many chaos/health tests trigger on purpose.
    Those dumps must land in the test's tmp dir, not litter the
    repository cwd."""
    from veles_tpu.observe.flight import flight
    monkeypatch.setattr(flight, "base_path",
                        str(tmp_path / "veles_flight"))


@pytest.fixture(autouse=True)
def _schedule_cache_to_tmp(tmp_path, monkeypatch):
    """The kernels consult the tuned schedule cache on every
    ``blocks=None`` call (ops/matmul.py, conv_vjp.py, pool_bwd.py,
    matmul_int8.py, and the attention family in ops/attention.py) —
    a developer's real cache under ~/.cache would silently change the
    tiles (and thus the f32 accumulation grouping — for attention,
    the online-softmax rescale grouping) every numeric parity test
    runs with.  Tests always see a private empty cache; the ones that
    WANT entries plant them here."""
    monkeypatch.setenv("VELES_SCHEDULE_CACHE",
                       str(tmp_path / "schedule_cache"))


@pytest.fixture(autouse=True)
def _exemplar_ring_reset():
    """The tail-exemplar ring (observe/requests.py) is a process
    singleton fed by every batcher completion — one serve test's tail
    timelines must never leak into another's ring-bound or
    SLO-violation-dump assertions.  (Its dumps already land in tmp via
    _flight_dumps_to_tmp.)"""
    import sys
    yield
    mod = sys.modules.get("veles_tpu.observe.requests")
    if mod is not None:
        mod.exemplars.clear()


@pytest.fixture(autouse=True)
def _telemetry_plane_reset():
    """The global series ring (observe/timeseries.py) and alert
    manager (observe/alerts.py) are process singletons fed by every
    metrics tick and rule sweep — one test's closed buckets or
    edge-triggered firing state must never leak into another's
    rollup, burn-rate, or zero-alerts assertions."""
    import sys
    yield
    ts_mod = sys.modules.get("veles_tpu.observe.timeseries")
    if ts_mod is not None:
        ts_mod.series.clear()
    al_mod = sys.modules.get("veles_tpu.observe.alerts")
    if al_mod is not None:
        al_mod.alerts.clear()


@pytest.fixture(autouse=True)
def _calibration_to_tmp(tmp_path, monkeypatch):
    """The post-training quantization pass writes a calibration
    sidecar JSON on every quantize (veles_tpu/quant/ptq.py) — those
    artifacts must land in the test's tmp dir, never in a developer's
    real ~/.cache where they would accumulate one file per quantizing
    test forever."""
    monkeypatch.setenv("VELES_QUANT_CALIB",
                       str(tmp_path / "quant_calib"))


@pytest.fixture(autouse=True)
def _publish_dir_to_tmp(tmp_path):
    """The freshness loop's publish directory config
    (root.common.freshness.publish_dir, the trainer's --publish-dir /
    the watcher's --watch-dir default) must always point at test-local
    tmp: a developer's site config (~/.veles_tpu) setting a real
    publish dir must never leak into — or be watched by — the suite.
    Deliberate side effect: every default-config Snapshotter in the
    suite actually exercises the publish path (verify + copy into
    tmp); the whole-suite cost is noise next to the export itself and
    buys the publish hook coverage on every snapshotting test."""
    from veles_tpu.config import root
    prev = root.common.freshness.get("publish_dir")
    root.common.freshness.update(
        {"publish_dir": str(tmp_path / "publish")})
    yield
    root.common.freshness.update({"publish_dir": prev})


@pytest.fixture
def cpu_device():
    from veles_tpu.backends import Device
    return Device(backend="cpu")


@pytest.fixture
def numpy_device():
    from veles_tpu.backends import Device
    return Device(backend="numpy")
