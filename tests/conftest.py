"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
sharding/collective tests run anywhere (SURVEY.md section 4 implication b)."""

import os

# Force CPU even when the host pins JAX_PLATFORMS (e.g. axon): the suite
# must run on the virtual 8-device mesh.  Set VELES_TEST_TPU=1 to opt back
# into real-chip runs.  The env var alone is not enough on hosts whose
# sitecustomize re-pins the platform, so also update jax.config directly.
if not os.environ.get("VELES_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("VELES_BACKEND", "cpu")

import jax  # noqa: E402

if not os.environ.get("VELES_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _no_nondaemon_thread_leaks():
    """Fail any test leaking a live NON-daemon thread: such a thread
    outlives pytest and hangs CI.  Guards the input-pipeline prefetch
    worker and every other thread_pool.py user — worker pools must be
    shut down (joined) by the code under test, not abandoned."""
    import threading
    import time

    before = set(threading.enumerate())
    yield
    deadline = time.time() + 3.0
    leaked = []
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive() and not t.daemon]
        if not leaked:
            return
        time.sleep(0.05)  # give wind-downs in progress a moment
    pytest.fail("leaked non-daemon thread(s): %s" %
                ", ".join(sorted(t.name for t in leaked)))


@pytest.fixture
def cpu_device():
    from veles_tpu.backends import Device
    return Device(backend="cpu")


@pytest.fixture
def numpy_device():
    from veles_tpu.backends import Device
    return Device(backend="numpy")
