"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
sharding/collective tests run anywhere (SURVEY.md section 4 implication b)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("VELES_BACKEND", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def cpu_device():
    from veles_tpu.backends import Device
    return Device(backend="cpu")


@pytest.fixture
def numpy_device():
    from veles_tpu.backends import Device
    return Device(backend="numpy")
