"""Forge hub tests over real HTTP (reference test model: forge
server+client HTTP tests): upload a trained package, list, fetch,
and run native inference on the fetched copy."""

import numpy
import pytest

from veles_tpu.forge import ForgeServer, details, fetch, list_packages, \
    upload


@pytest.fixture()
def forge(tmp_path):
    server = ForgeServer(str(tmp_path / "store"))
    server.start_background()
    yield server
    server.stop()


def test_forge_upload_list_fetch(forge, tmp_path, cpu_device):
    from tests.test_native import _train_mlp
    sw = _train_mlp(cpu_device, epochs=1)
    pkg = str(tmp_path / "m.veles.tar")
    sw.package_export(pkg)

    url = "http://127.0.0.1:%d" % forge.port
    upload(url, "blobs-mlp", "1.0.0", pkg,
           metadata={"workflow": "StandardWorkflow"})
    upload(url, "blobs-mlp", "1.1.0", pkg)

    packages = list_packages(url)
    assert len(packages) == 1
    assert packages[0]["version"] == "1.1.0"

    info = details(url, "blobs-mlp")
    assert info["versions"] == ["1.0.0", "1.1.0"]

    out = str(tmp_path / "fetched.tar")
    path, version = fetch(url, "blobs-mlp", out)
    assert version == "1.1.0"
    assert open(path, "rb").read() == open(pkg, "rb").read()


def test_forge_fetched_package_runs_natively(forge, tmp_path,
                                             cpu_device):
    from tests.test_native import _train_mlp
    from veles_tpu import native as native_mod
    try:
        native_mod.build_native()
    except Exception as exc:
        pytest.skip("native build unavailable: %s" % exc)

    sw = _train_mlp(cpu_device, epochs=1)
    pkg = str(tmp_path / "m.veles.tar")
    sw.package_export(pkg)
    url = "http://127.0.0.1:%d" % forge.port
    upload(url, "mlp", "0.1", pkg)
    out, _ = fetch(url, "mlp", str(tmp_path / "f.tar"))
    nwf = native_mod.NativeWorkflow(out)
    probs = nwf.run(numpy.random.RandomState(0).rand(4, 16))
    assert numpy.allclose(probs.sum(axis=1), 1.0, atol=1e-4)


def test_forge_unknown_package_404(forge):
    import urllib.error
    url = "http://127.0.0.1:%d" % forge.port
    with pytest.raises(urllib.error.HTTPError):
        fetch(url, "nope", "/tmp/x.tar")


def test_forge_rejects_path_traversal(forge, tmp_path):
    """Upload with traversal components must 400 and write nothing
    outside the store root (advisor finding, round 1)."""
    import urllib.error
    import urllib.request

    url = ("http://127.0.0.1:%d/upload?name=pkg&version=..%%2F..%%2Fevil"
           % forge.port)
    req = urllib.request.Request(url, data=b"payload", method="POST")
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req)
    assert exc_info.value.code == 400
    # nothing escaped the store directory
    assert not (tmp_path / "evil").exists()
    assert not (tmp_path.parent / "evil").exists()

    with pytest.raises(ValueError):
        forge.store("../pkg", "1.0.0", b"x")
    with pytest.raises(ValueError):
        forge.store("pkg", "../../1.0.0", b"x")


def test_git_backed_forge_roundtrip(tmp_path):
    """git_backed=True (reference forge_server.py kept one git repo
    per package): uploads commit + tag, every historical version stays
    fetchable byte-exact, duplicates are refused, and the HTTP surface
    is unchanged."""
    import json
    import shutil
    import urllib.error
    import urllib.request
    if shutil.which("git") is None:
        pytest.skip("git unavailable")
    from veles_tpu.forge.server import ForgeServer

    server = ForgeServer(str(tmp_path / "hub"), git_backed=True)
    server.start_background()
    base = "http://127.0.0.1:%d" % server.port
    try:
        v1 = b"PKG-v1" * 100
        v2 = b"PKG-v2" * 100
        for version, payload in (("1.0.0", v1), ("1.1.0", v2)):
            req = urllib.request.Request(
                base + "/upload?name=demo&version=%s" % version,
                data=payload)
            assert json.loads(urllib.request.urlopen(req).read())[
                "result"] == "ok"
        # duplicate version refused with 400
        req = urllib.request.Request(
            base + "/upload?name=demo&version=1.0.0", data=v1)
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(req)
        assert excinfo.value.code == 400

        with urllib.request.urlopen(
                base + "/service?query=details&name=demo") as resp:
            details_ = json.loads(resp.read())
        assert details_["versions"] == ["1.0.0", "1.1.0"]
        assert details_["metadata"]["version"] == "1.1.0"
        # historical version comes back byte-exact from git
        with urllib.request.urlopen(
                base + "/fetch?name=demo&version=1.0.0") as resp:
            assert resp.read() == v1
        with urllib.request.urlopen(base + "/fetch?name=demo") as resp:
            assert resp.headers["X-Package-Version"] == "1.1.0"
            assert resp.read() == v2
        # storage really is a git repo with one tag per version
        assert (tmp_path / "hub" / "demo" / ".git").is_dir()
    finally:
        server.stop()


def test_git_backed_forge_out_of_order_uploads(tmp_path):
    """Backfilling an older version after a newer one must not change
    what "latest" serves: payload, X-Package-Version, details, and
    index all keep agreeing on the numerically greatest version
    (advisor finding, round 2)."""
    import shutil
    if shutil.which("git") is None:
        pytest.skip("git unavailable")
    from veles_tpu.forge.server import ForgeServer

    server = ForgeServer(str(tmp_path / "hub"), git_backed=True)
    v110 = b"NEW" * 50
    v101 = b"OLD-BACKFILL" * 50
    server.store("demo", "1.1.0", v110)
    server.store("demo", "1.0.1", v101)  # worktree now holds 1.0.1

    payload, version = server.load("demo", "latest")
    assert version == "1.1.0"
    assert payload == v110
    assert server.index()[0]["version"] == "1.1.0"
    # the backfilled version is still fetchable byte-exact
    payload, version = server.load("demo", "1.0.1")
    assert (payload, version) == (v101, "1.0.1")


def test_forge_versions_sort_numerically(tmp_path):
    """'1.10.0' must beat '1.9.0' for latest (advisor finding: naive
    lexicographic sort breaks at two-digit components), on both the
    plain-directory and git-backed paths."""
    import shutil
    from veles_tpu.forge.server import ForgeServer

    plain = ForgeServer(str(tmp_path / "plain"))
    plain.store("p", "1.9.0", b"nine")
    plain.store("p", "1.10.0", b"ten")
    assert plain.versions("p") == ["1.9.0", "1.10.0"]
    assert plain.load("p", "latest") == (b"ten", "1.10.0")

    if shutil.which("git") is None:
        pytest.skip("git unavailable")
    hub = ForgeServer(str(tmp_path / "hub"), git_backed=True)
    hub.store("p", "1.10.0", b"ten")
    hub.store("p", "1.9.0", b"nine")
    assert hub.versions("p") == ["1.9.0", "1.10.0"]
    assert hub.load("p", "latest") == (b"ten", "1.10.0")
