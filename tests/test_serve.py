"""Serving-subsystem tests (docs/serving.md): AOT ladder dispatch,
continuous-batching bit-equality with sequential inference (including
padded-tail masking), the warm persistent-cache zero-compile receipt,
SLO tripwires under an injected stall, overload shedding with the
503/retry_after protocol, OOM ladder degradation, and the RESTfulAPI
compatibility front."""

import json
import urllib.error
import urllib.request

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.compiler import LayerPlan
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, ContinuousBatcher, ServeOverload, ServeService,
    model_digest, serve_snapshot)

pytestmark = pytest.mark.serve


def _mlp_spec(seed=0, fan_in=16, hidden=16, classes=4):
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    rng = numpy.random.RandomState(seed)
    plans = [LayerPlan(All2AllTanh), LayerPlan(All2AllSoftmax)]
    params = [
        {"weights": rng.rand(fan_in, hidden).astype(numpy.float32),
         "bias": rng.rand(hidden).astype(numpy.float32)},
        {"weights": rng.rand(hidden, classes).astype(numpy.float32),
         "bias": rng.rand(classes).astype(numpy.float32)},
    ]
    return plans, params


@pytest.fixture(scope="module")
def engine():
    """Shared AOT ladder over a random-parameter MLP.  The ladder
    starts at 8 ON PURPOSE: XLA:CPU lowers the rung-1 program to a
    different vector-matrix kernel whose rows differ from the batched
    rungs by ~1 ulp, while every rung >= the vector width produces
    bit-identical rows (measured; see serve/engine.py docstring) — the
    bit-equality contract below holds within such a ladder."""
    plans, params = _mlp_spec()
    eng = AOTEngine(plans, params, (16,), ladder=(8, 32),
                    device=Device(backend="cpu"))
    eng.compile()
    return eng


def _co_batch(batcher, samples, timeout=30.0):
    """Submit every sample inside ONE collect window (the batcher's
    queue-delay makes the worker wait for them), then gather results in
    submission order — a deterministic stand-in for concurrent
    clients."""
    requests = [batcher.submit(s) for s in samples]
    results, errors = [], []
    for i, req in enumerate(requests):
        if not req.done.wait(timeout):
            errors.append((i, TimeoutError("request %d timed out" % i)))
            results.append(None)
        elif req.error is not None:
            errors.append((i, req.error))
            results.append(None)
        else:
            results.append(req.result)
    return results, errors


# -- (a) batching correctness ------------------------------------------------


def test_batched_bit_identical_to_sequential(engine):
    """Continuously-batched results == sequential single-sample
    inference, bit for bit, including a padded tail (13 requests on an
    8/32 ladder co-batch into a 32-rung with 19 padding rows)."""
    rng = numpy.random.RandomState(1)
    samples = rng.rand(13, 16).astype(numpy.float32)
    sequential = numpy.stack(
        [engine.infer(samples[i])[0] for i in range(len(samples))])

    hist = registry.histogram("serve.batch_size")
    hist.reset()
    batcher = ContinuousBatcher(engine, max_delay_s=0.5).start()
    try:
        results, errors = _co_batch(batcher, list(samples))
    finally:
        batcher.stop()
    assert not errors, errors
    batched = numpy.stack(results)
    assert batched.shape == sequential.shape
    assert (batched == sequential).all(), \
        numpy.abs(batched - sequential).max()
    # the equality must have been proven ON a co-batched path, not 13
    # singleton batches racing through
    assert hist.count >= 1
    assert max(hist.window_values()) > 1


def test_padded_tail_never_leaks(engine):
    """Padding rows cannot influence real rows: the same 5 samples
    dispatched on the 8-rung with zero padding and with garbage
    padding produce identical real rows (no cross-row reduction in the
    forward; the per-row softmax stays per-row)."""
    rng = numpy.random.RandomState(2)
    x = rng.rand(5, 16).astype(numpy.float32)
    zeros = numpy.zeros((8, 16), numpy.float32)
    zeros[:5] = x
    garbage = (rng.rand(8, 16).astype(numpy.float32) * 1e3)
    garbage[:5] = x
    out_zeros = numpy.asarray(
        engine.run(engine.device.put(zeros), 8))[:5]
    out_garbage = numpy.asarray(
        engine.run(engine.device.put(garbage), 8))[:5]
    assert (out_zeros == out_garbage).all()


def test_engine_sequential_shapes(engine):
    """infer() accepts a bare sample and a batch; an overflowing batch
    chunks through the top rung."""
    rng = numpy.random.RandomState(3)
    one = engine.infer(rng.rand(16).astype(numpy.float32))
    assert one.shape == (1, 4)
    big = rng.rand(70, 16).astype(numpy.float32)  # > max rung 32
    out = engine.infer(big)
    assert out.shape == (70, 4)
    ref = numpy.stack([engine.infer(big[i])[0] for i in range(70)])
    # chunking pads the 6-row tail to the 8-rung; still bit-equal
    assert (out == ref).all()


# -- (b) warm persistent cache ----------------------------------------------


@pytest.fixture
def _restore_jax_cache_config():
    import jax
    before = (jax.config.jax_compilation_cache_dir,
              jax.config.jax_persistent_cache_min_compile_time_secs,
              jax.config.jax_persistent_cache_min_entry_size_bytes)
    yield
    jax.config.update("jax_compilation_cache_dir", before[0])
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", before[1])
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes", before[2])
    # unbind the digest dir the engines bound (the singleton would
    # otherwise keep writing there for the rest of the suite)
    from jax._src import compilation_cache
    compilation_cache.reset_cache()


def test_warm_cache_reports_zero_new_compiles(
        tmp_path, _restore_jax_cache_config):
    """A second engine start against the warm digest-keyed persistent
    cache performs 0 new backend compiles: every compile request is
    answered by a cache hit (asserted via the xla_introspect
    compile.count / compile.cache_hits counters that feed the
    receipt)."""
    from veles_tpu.observe import xla_introspect

    plans, params = _mlp_spec(seed=7)
    root = str(tmp_path / "serve_cache")
    cold = AOTEngine(plans, params, (16,), ladder=(8, 32),
                     device=Device(backend="cpu"), cache_root=root)
    cold_receipt = cold.compile()
    assert cold_receipt["new_compiles"] >= 2  # one per rung, cold
    assert cold_receipt["cache_dir"].startswith(root)

    before = xla_introspect.compile_snapshot()
    warm = AOTEngine(plans, params, (16,), ladder=(8, 32),
                     device=Device(backend="cpu"), cache_root=root)
    warm_receipt = warm.compile()
    after = xla_introspect.compile_snapshot()
    assert warm_receipt["new_compiles"] == 0, warm_receipt
    assert warm_receipt["cache_hits"] >= 2
    # the raw counters agree: every backend-compile request during the
    # warm start was served from the cache
    assert (after["count"] - before["count"]
            == after["cache_hits"] - before["cache_hits"])
    # same architecture, new weights -> same digest (the cache must
    # survive retraining); new topology -> different digest
    from veles_tpu.serve.engine import engine_digest_extra
    extra = engine_digest_extra(numpy.float32)
    plans2, params2 = _mlp_spec(seed=8)
    assert model_digest(plans2, params2, (16,),
                        extra=extra) == warm.digest
    plans3, params3 = _mlp_spec(seed=7, hidden=32)
    assert model_digest(plans3, params3, (16,),
                        extra=extra) != warm.digest

    rng = numpy.random.RandomState(4)
    x = rng.rand(3, 16).astype(numpy.float32)
    assert (warm.infer(x) == cold.infer(x)).all()


# -- (c) SLO tripwires under an injected stall -------------------------------


@pytest.mark.chaos
def test_slo_violations_fire_under_stall(engine):
    """serve.stall chaos makes every batch ~60 ms; with a 10 ms p99
    budget the SLO watch must trip the counter and record the
    trace/flight instant."""
    from veles_tpu.observe.trace import tracer

    before = registry.counter("serve.slo_violations").value
    chaos.install(chaos.FaultPlan(seed=1).add(
        "serve.stall", "stall", param=0.06))
    tracer.start()
    batcher = ContinuousBatcher(
        engine, max_delay_s=0.001, slo_p99_ms=10.0, slo_check_every=1)
    batcher.start()
    try:
        for _ in range(3):
            batcher.infer(numpy.zeros(16, numpy.float32))
    finally:
        batcher.stop()
        chaos.uninstall()
        tracer.stop()
    assert registry.counter("serve.slo_violations").value > before
    names = [e["name"] for e in tracer.events]
    assert "serve.slo_violation" in names
    snap = serve_snapshot()
    assert snap["slo_violations"] > 0
    assert snap["p99_ms"] > 10.0


# -- overload + degradation --------------------------------------------------


@pytest.mark.chaos
def test_overload_sheds_with_retry_after(engine):
    """Past max_queue pending requests submit() sheds with a transient
    ServeOverload instead of growing the queue; chaos serve.drop sheds
    deterministically."""
    chaos.install(chaos.FaultPlan(seed=1).add(
        "serve.stall", "stall", param=0.2))
    batcher = ContinuousBatcher(engine, max_delay_s=0.0, max_queue=2)
    batcher.start()
    shed = []
    try:
        for i in range(30):
            try:
                batcher.submit(numpy.zeros(16, numpy.float32))
            except ServeOverload as exc:
                shed.append(exc)
    finally:
        batcher.stop()
        chaos.uninstall()
    assert shed, "queue grew without bound"
    assert all(exc.retry_after > 0 for exc in shed)

    chaos.install(chaos.FaultPlan(seed=1).add("serve.drop", "drop",
                                              nth=1))
    batcher = ContinuousBatcher(engine).start()
    try:
        with pytest.raises(ServeOverload):
            batcher.submit(numpy.zeros(16, numpy.float32))
        # only the first submit was armed; the second serves fine
        assert batcher.infer(
            numpy.zeros(16, numpy.float32)).shape == (4,)
    finally:
        batcher.stop()
        chaos.uninstall()


@pytest.mark.chaos
def test_oom_degrades_ladder_and_replays(engine):
    """A RESOURCE_EXHAUSTED dispatch caps the ladder below the failing
    rung and replays the batch in chunks: every request still gets its
    bit-exact answer, only slower."""
    rng = numpy.random.RandomState(5)
    samples = rng.rand(13, 16).astype(numpy.float32)
    sequential = numpy.stack(
        [engine.infer(samples[i])[0] for i in range(len(samples))])
    chaos.install(chaos.FaultPlan(seed=1).add("serve.oom", "oom",
                                              nth=1))
    batcher = ContinuousBatcher(engine, max_delay_s=0.5).start()
    try:
        # 13 requests inside one collect window -> the 32-rung, whose
        # dispatch the armed fault kills
        results, errors = _co_batch(batcher, list(samples))
        assert not errors, errors
        assert (numpy.stack(results) == sequential).all()
        assert batcher._rung_cap == 8  # capped below the 32-rung
        assert registry.gauge("serve.rung_cap").value == 8
    finally:
        batcher.stop()
        chaos.uninstall()


# -- HTTP front + compatibility ---------------------------------------------


def test_service_http_roundtrip_and_healthz(engine):
    svc = ServeService(engine, labels_mapping={0: "a", 1: "b", 2: "c",
                                               3: "d"},
                       max_delay_s=0.002)
    svc.start_background()
    try:
        base = "http://127.0.0.1:%d" % svc.port
        rng = numpy.random.RandomState(6)
        batch = rng.rand(3, 16).astype(numpy.float32)
        req = urllib.request.Request(
            base + "/infer",
            data=json.dumps({"input": batch.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            answer = json.loads(resp.read())
        assert len(answer["result"]) == 3
        assert set(answer["result"]) <= {"a", "b", "c", "d"}
        assert len(answer["probabilities"]) == 3
        ref = engine.infer(batch)
        # float32 -> json -> float32 is lossless: the HTTP answer is
        # bit-identical to the in-process engine
        assert (numpy.asarray(answer["probabilities"],
                              numpy.float32) == ref).all()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["compile"]["rungs"] == [8, 32]
        assert "queue_depth" in health["serve"]
        assert health["model_digest"] == engine.digest
        with urllib.request.urlopen(base + "/metrics.json",
                                    timeout=10) as r:
            metrics = json.loads(r.read())
        assert "serve.latency_s" in metrics["histograms"]
        assert "http.request_s" in metrics["histograms"]
    finally:
        svc.stop()


@pytest.mark.chaos
def test_service_answers_503_on_shed(engine):
    chaos.install(chaos.FaultPlan(seed=1).add("serve.drop", "drop"))
    svc = ServeService(engine)
    svc.start_background()
    try:
        req = urllib.request.Request(
            "http://127.0.0.1:%d/infer" % svc.port,
            data=json.dumps(
                {"input": [0.0] * 16}).encode())
        with pytest.raises(urllib.error.HTTPError) as info:
            urllib.request.urlopen(req, timeout=10)
        assert info.value.code == 503
        body = json.loads(info.value.read())
        assert body["retry_after"] > 0
        assert info.value.headers.get("Retry-After") is not None
    finally:
        svc.stop()
        chaos.uninstall()


def test_format_result_vectorized_contract():
    """format_result keeps the REST contract after the per-batch
    vectorization: one tolist over the whole (viewed, never re-copied)
    block, scalar result for single-row payloads, mapped labels when a
    mapping exists and plain ints (vectorized box) when not."""
    from veles_tpu.serve import format_result

    probs = numpy.array([[0.1, 0.9], [0.8, 0.2]], numpy.float32)
    out = format_result(probs, {0: "a", 1: "b"})
    assert out["result"] == ["b", "a"]
    assert out["probabilities"] == probs.tolist()
    unmapped = format_result(probs)
    assert unmapped["result"] == [1, 0]
    assert all(isinstance(label, int) for label in unmapped["result"])
    single = format_result(probs[0])
    assert single["result"] == 1
    assert single["probabilities"] == [probs[0].tolist()]
    one_row = format_result(probs[:1], {0: "a", 1: "b"})
    assert one_row["result"] == "b"
    # list payloads (the RESTful compat front) still work
    assert format_result(probs.tolist())["result"] == [1, 0]


def test_restful_api_delegates_to_engine():
    """The compatibility unit serves the old contract through the AOT
    engine: programmatic infer() without a started server uses the
    sequential engine path, and the engine mirrors the trained
    workflow's forward exactly."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.restful_api import RESTfulAPI
    from tests.test_models import BlobsLoader

    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("serve-rest", seed=21)),
        decision_config=dict(max_epochs=2),
    )
    sw.initialize(device=Device(backend="cpu"))
    sw.run()
    api = RESTfulAPI(sw, ladder=(1, 8))
    api.initialize()
    try:
        x = sw.loader.original_data.mem[0]
        answer = api.infer(x.tolist())
        assert answer["result"] == sw.loader.original_labels[0]
        assert abs(sum(answer["probabilities"][0]) - 1.0) < 1e-3
        assert api.requests_served == 1
        assert api.engine.compile_receipt is not None
    finally:
        api.stop()
