"""GA + ensemble tests (reference test model: veles/tests around
genetics and wine_ensemble.json)."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.ensemble import EnsembleTester, EnsembleTrainer
from veles_tpu.genetics import (
    GeneticsOptimizer, Population, Tune, apply_values, extract_tunes,
    gray_decode, gray_encode)
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator
from tests.test_models import BlobsLoader


def test_gray_roundtrip():
    for value in (0.0, 0.25, 0.7, 1.0):
        code = gray_encode(value, 0.0, 1.0, 12)
        back = gray_decode(code, 0.0, 1.0, 12)
        assert abs(back - value) < 1e-3


def test_tune_extract_and_apply():
    spec = {"layers": [
        {"type": "tanh", "lr": Tune(0.05, 0.001, 0.5),
         "units": Tune(32, 8, 64)},
        {"type": "softmax", "lr": Tune(0.05, 0.001, 0.5)},
    ]}
    tunes = extract_tunes(spec)
    assert len(tunes) == 3
    candidate = apply_values(spec, tunes, [0.1, 16.4, 0.2])
    # int Tune stays int
    assert candidate["layers"][0]["units"] == 16
    assert isinstance(candidate["layers"][0]["units"], int)
    # original untouched
    assert isinstance(spec["layers"][0]["units"], Tune)


def test_population_converges_on_sphere():
    """GA must find the maximum of -(x-0.3)^2-(y+0.2)^2."""
    rng = RandomGenerator("ga", seed=11)
    pop = Population([-1, -1], [1, 1], size=24, rng=rng,
                     mutation="gaussian", mutation_rate=0.3)
    for _ in range(15):
        for c in pop.unevaluated():
            c.fitness = -((c.values[0] - 0.3) ** 2 +
                          (c.values[1] + 0.2) ** 2)
        best = pop.best
        pop.evolve()
    assert abs(best.values[0] - 0.3) < 0.15
    assert abs(best.values[1] + 0.2) < 0.15


def test_binary_mutation_stays_in_bounds():
    rng = RandomGenerator("gab", seed=3)
    pop = Population([0], [10], size=8, rng=rng, binary_bits=8,
                     mutation="binary", mutation_rate=0.2)
    for _ in range(5):
        for c in pop.unevaluated():
            c.fitness = -abs(c.values[0] - 7)
        pop.evolve()
    for c in pop.chromosomes:
        assert 0 <= c.values[0] <= 10


def test_genetics_optimizer_on_analytic_fitness():
    spec = {"x": Tune(0.0, -2.0, 2.0), "y": Tune(0.0, -2.0, 2.0)}

    def fitness(candidate):
        return -((candidate["x"] - 1.0) ** 2 + (candidate["y"] - 0.5) ** 2)

    opt = GeneticsOptimizer(
        spec, fitness, generations=10, population=20,
        rng=RandomGenerator("gopt", seed=21), mutation_rate=0.3)
    best_spec, best_fitness = opt.run()
    assert best_fitness > -0.05
    assert abs(best_spec["x"] - 1.0) < 0.25
    assert len(opt.history) == 10


def _member_factory(member, seed):
    wf = DummyWorkflow()
    return StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("ens%d" % member, seed=seed)),
        decision_config=dict(max_epochs=3),
    )


def test_optimizer_farms_over_control_plane():
    """One GA generation evaluated as control-plane jobs: a farm
    master + 2 in-process slave workers (reference
    genetics/optimization_workflow.py:186-221 farmed chromosome
    evaluations to slaves)."""
    spec = {"x": Tune(0.0, -2.0, 2.0), "y": Tune(0.0, -2.0, 2.0)}

    def fitness(candidate):
        return -((candidate["x"] - 1.0) ** 2
                 + (candidate["y"] - 0.5) ** 2)

    opt = GeneticsOptimizer(
        spec, fitness, generations=1, population=8, farm_slaves=2,
        rng=RandomGenerator("gfarm", seed=5))
    best_spec, best_fitness = opt.run()
    # every chromosome came back evaluated through the farm
    assert all(c.fitness is not None for c in opt.population.chromosomes)
    assert best_fitness == max(
        c.fitness for c in opt.population.chromosomes)
    assert -9.0 < best_fitness <= 0.0


def test_ensemble_trains_distributed_over_control_plane(
        tmp_path, cpu_device):
    """4-member ensemble farmed as jobs through a master + 2
    in-process slaves (reference ensemble/base_workflow.py:135-153
    distributed member training the same way)."""
    trainer = EnsembleTrainer(
        _member_factory, size=4, directory=str(tmp_path),
        device=cpu_device, farm_slaves=2)
    results_path = trainer.run()
    assert [e["id"] for e in trainer.results] == [0, 1, 2, 3]
    assert all(e["metrics"][1] is not None for e in trainer.results)

    tester = EnsembleTester(results_path, device=cpu_device)
    wf = DummyWorkflow()
    loader = BlobsLoader(wf, minibatch_size=64,
                         prng=RandomGenerator("enstest2", seed=78))
    loader.initialize(device=None)
    x = loader.original_data.mem[64:128]
    labels = numpy.array(
        [loader.labels_mapping[loader.original_labels[i]]
         for i in range(64, 128)])
    err = tester.error_rate(x, labels)
    assert err < 10.0, "ensemble error %.1f%%" % err


def test_ensemble_test_farms_member_evaluation(tmp_path, cpu_device):
    """--ensemble-test as control-plane jobs (reference
    ensemble/test_workflow.py reran snapshots as jobs): farmed
    predictions must equal in-process predictions exactly."""
    trainer = EnsembleTrainer(
        _member_factory, size=3, directory=str(tmp_path),
        device=cpu_device)
    results_path = trainer.run()

    wf = DummyWorkflow()
    loader = BlobsLoader(wf, minibatch_size=64,
                         prng=RandomGenerator("enstest3", seed=79))
    loader.initialize(device=None)
    x = loader.original_data.mem[:32]

    inproc = EnsembleTester(results_path, device=cpu_device)
    farmed = EnsembleTester(results_path, device=cpu_device,
                            farm_slaves=2)
    numpy.testing.assert_allclose(
        farmed.predict(x), inproc.predict(x), rtol=1e-5, atol=1e-6)


def test_ensemble_remote_worker_entrypoint(tmp_path, cpu_device):
    """Remote-only farming: EnsembleTrainer with an explicit address
    and NO local slaves; a worker joins via trainer.worker() — the
    farm_enabled gate must start the master for this setup."""
    import socket
    import threading

    # remote-only means a REAL address (the "127.0.0.1:0" default
    # signals no farming); reserve a free port the usual way
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    master = EnsembleTrainer(
        _member_factory, size=2, directory=str(tmp_path),
        device=cpu_device, farm_address="127.0.0.1:%d" % port)
    assert master.farm_enabled

    worker = EnsembleTrainer(
        _member_factory, size=2, directory=str(tmp_path),
        device=cpu_device)

    # the master logs/binds its port only once run() starts; poll the
    # farm tag's server through a patched JobFarm.start is overkill —
    # instead run the master in a thread and join the worker against
    # the address it publishes via the trainer attribute
    from veles_tpu import jobfarm

    started = threading.Event()
    address = {}
    orig_start = jobfarm.JobFarm.start

    def start_and_publish(self, **kwargs):
        out = orig_start(self, **kwargs)
        address["addr"] = self.address
        started.set()
        return out

    jobfarm.JobFarm.start = start_and_publish
    try:
        run_thread = threading.Thread(target=master.run, daemon=True)
        run_thread.start()
        assert started.wait(30)
        n = worker.worker(address["addr"])
        run_thread.join(60)
        assert not run_thread.is_alive()
    finally:
        jobfarm.JobFarm.start = orig_start
    assert n == 2  # the remote worker trained both members
    assert [e["id"] for e in master.results] == [0, 1]


def test_ensemble_train_ratio_reaches_three_arg_factories(tmp_path,
                                                          cpu_device):
    """--ensemble-train N:r semantics: factories that accept a third
    parameter receive the per-member train fraction; two-arg
    factories keep working."""
    seen = []

    def factory3(member, seed, train_ratio):
        seen.append((member, train_ratio))
        return _member_factory(member, seed)

    trainer = EnsembleTrainer(
        factory3, size=2, directory=str(tmp_path),
        train_ratio=0.5, device=cpu_device)
    trainer.run()
    assert seen == [(0, 0.5), (1, 0.5)]

    # a **kwargs-only third "parameter" must NOT be fed a positional
    kw_calls = []

    def factory_kw(member, seed, **opts):
        kw_calls.append(opts)
        return _member_factory(member, seed)

    EnsembleTrainer(factory_kw, size=1, directory=str(tmp_path),
                    train_ratio=0.5, device=cpu_device).run()
    assert kw_calls == [{}]


def test_ensemble_train_and_test(tmp_path, cpu_device):
    trainer = EnsembleTrainer(
        _member_factory, size=3, directory=str(tmp_path),
        device=cpu_device)
    results_path = trainer.run()
    assert len(trainer.results) == 3

    tester = EnsembleTester(results_path, device=cpu_device)
    # evaluate on freshly generated blobs (same generator as training)
    wf = DummyWorkflow()
    loader = BlobsLoader(wf, minibatch_size=64,
                         prng=RandomGenerator("enstest", seed=77))
    loader.initialize(device=None)
    x = loader.original_data.mem[64:128]
    labels = numpy.array(
        [loader.labels_mapping[loader.original_labels[i]]
         for i in range(64, 128)])
    err = tester.error_rate(x, labels)
    assert err < 10.0, "ensemble error %.1f%%" % err
