"""Serve binary-transport tests (docs/serving.md wire format):
tensor-codec safety (no pickle, hostile headers refused), framed
round-trips over in-process socketpair duplex streams (no real port
binds — the `transport` marker contract), the same-host ShmChannel
payload bypass with stale-channel fallback, HMAC rejection, the
overload protocol over the wire, and the batcher's zero-staging block
fast path the transport feeds."""

import socket

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.network_common import ProtocolError
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, BinaryTransportClient, BinaryTransportServer,
    ContinuousBatcher, ServeOverload, decode_tensor, encode_tensor)
from tests.test_serve import _mlp_spec

pytestmark = [pytest.mark.serve, pytest.mark.transport]


@pytest.fixture(scope="module")
def engine():
    plans, params = _mlp_spec(seed=3)
    eng = AOTEngine(plans, params, (16,), ladder=(8, 32),
                    device=Device(backend="cpu"))
    eng.compile()
    return eng


@pytest.fixture
def served(engine):
    """Started batcher + transport server + socketpair client factory
    (tier-1 never binds a TCP port: ``port=None`` + serve_socket)."""
    batcher = ContinuousBatcher(engine, max_delay_s=0.002).start()
    server = BinaryTransportServer(batcher, port=None)
    server.start_background()
    clients = []

    def connect(**kwargs):
        ours, theirs = socket.socketpair()
        server.serve_socket(ours)
        cli = BinaryTransportClient(sock=theirs, **kwargs)
        clients.append(cli)
        return cli

    yield engine, batcher, server, connect
    for cli in clients:
        cli.close()
    server.stop()
    batcher.stop()


# -- tensor codec ------------------------------------------------------------


def test_tensor_codec_roundtrip_bit_exact():
    rng = numpy.random.RandomState(0)
    arrays = (
        rng.rand(4, 7).astype(numpy.float32),
        rng.rand(2, 3, 4),                      # float64
        rng.randint(-5, 90, (3, 2)).astype(numpy.int64),
        (rng.rand(8) > 0.5),                    # bool
        numpy.arange(6, dtype=numpy.uint8).reshape(2, 3),
    )
    for arr in arrays:
        for codec in ("none", "gzip"):
            meta, raw = encode_tensor(arr, codec)
            out = decode_tensor(meta, raw)
            assert out.dtype == arr.dtype
            assert out.shape == arr.shape
            assert (out == arr).all()
    # the "none" decode is ZERO-COPY: a view over the received bytes
    meta, raw = encode_tensor(rng.rand(4, 4).astype(numpy.float32))
    out = decode_tensor(meta, raw)
    assert not out.flags["OWNDATA"]


def test_tensor_codec_refuses_hostile_frames():
    """The serve port never unpickles: object dtypes are refused on
    both ends, and malformed headers (negative/oversized shapes,
    length mismatches, unknown codecs) raise ProtocolError before any
    attacker-sized allocation."""
    with pytest.raises(ValueError):
        encode_tensor(numpy.array([object()], dtype=object))
    _, raw = encode_tensor(numpy.zeros(4, numpy.float32))
    hostile = (
        {"dtype": "|O", "shape": [1], "codec": "none"},
        {"dtype": "V8", "shape": [1], "codec": "none"},
        {"dtype": "nope", "shape": [4], "codec": "none"},
        {"dtype": "<f4", "shape": [-1], "codec": "none"},
        {"dtype": "<f4", "shape": [1 << 40], "codec": "none"},
        {"dtype": "<f4", "shape": [3], "codec": "none"},  # len mismatch
        {"dtype": "<f4", "shape": [4], "codec": "evil"},
        {"shape": [4], "codec": "none"},                  # no dtype
    )
    for meta in hostile:
        with pytest.raises(ProtocolError):
            decode_tensor(meta, raw)


# -- framed round-trips ------------------------------------------------------


def test_binary_roundtrip_inline(served):
    """A batch and a single sample over the socket (shm off) come back
    bit-identical to the in-process engine; byte counters show the
    payloads actually rode the socket."""
    engine, _, _, connect = served
    cli = connect(shm=False)
    assert cli.server_digest == engine.digest
    assert cli.sample_shape == (16,)
    rng = numpy.random.RandomState(1)
    x = rng.rand(5, 16).astype(numpy.float32)
    ref = engine.infer(x)
    out = cli.infer(x)
    assert out.dtype == ref.dtype
    assert (out == ref).all()
    one = cli.infer(x[0])
    assert one.shape == (1, 4)
    assert (one[0] == ref[0]).all()
    assert cli.socket_tx_bytes == x.nbytes + x[0].nbytes
    assert cli.socket_rx_bytes == ref.nbytes + ref[0:1].nbytes
    assert cli.shm_tx_bytes == 0 and cli.shm_rx_bytes == 0
    assert cli.ping()


def test_binary_overflow_batch_chunks_through_ladder(served):
    """A block wider than the top rung (70 rows on the 8/32 ladder)
    chunks server-side and still matches the sequential reference."""
    engine, _, _, connect = served
    cli = connect(shm=False)
    rng = numpy.random.RandomState(9)
    x = rng.rand(70, 16).astype(numpy.float32)
    ref = engine.infer(x)
    out = cli.infer(x)
    assert out.shape == ref.shape
    assert (out == ref).all()


def test_shm_bypass_and_stale_fallback(served):
    """Same-host payload bytes ride shared memory — the socket-byte
    counters prove the bypass — and a stale/closed segment falls back
    to inline payloads instead of failing the request."""
    engine, _, _, connect = served
    sock_rx_before = registry.counter(
        "serve.transport.socket_rx_bytes").value
    shm_rx_before = registry.counter(
        "serve.transport.shm_rx_bytes").value
    cli = connect(shm=True)
    assert cli.shm_active
    rng = numpy.random.RandomState(2)
    x = rng.rand(6, 16).astype(numpy.float32)
    ref = engine.infer(x)
    out = cli.infer(x)
    assert (out == ref).all()
    # payload bytes took the shm road; zero payload bytes on the socket
    assert cli.shm_tx_bytes == x.nbytes
    assert cli.socket_tx_bytes == 0
    assert cli.shm_rx_bytes > 0
    assert cli.socket_rx_bytes == 0
    # the server-side read-path counters agree
    assert registry.counter(
        "serve.transport.shm_rx_bytes").value - shm_rx_before == x.nbytes
    assert registry.counter(
        "serve.transport.socket_rx_bytes").value == sock_rx_before
    # kill the client->server segment under the client: the next infer
    # falls back to the socket, serves correctly, and drops the channel
    cli._chan_out.close()
    out2 = cli.infer(x)
    assert (out2 == ref).all()
    assert cli._chan_out is None
    assert cli.socket_tx_bytes == x.nbytes


def test_oversized_shm_segment_refused_downgrades_to_inline(served):
    """The server attaches only client-created segments bounded by the
    frame ceiling; a client offering an oversized one is downgraded to
    inline payloads at HANDSHAKE time (shm_ok=False acked back) and
    still serves correctly — the server never commits to a road it
    refused."""
    engine, _, _, connect = served
    cli = connect(shm=True, shm_slot_mb=80.0)  # > MAX_FRAME_BYTES slot
    assert not cli.shm_active
    x = numpy.random.RandomState(7).rand(4, 16).astype(numpy.float32)
    out = cli.infer(x)
    assert (out == engine.infer(x)).all()
    assert cli.socket_tx_bytes == x.nbytes  # inline road
    assert cli.shm_tx_bytes == 0


def test_hostile_length_prefix_drops_connection(served):
    """A length prefix past the serve port's 64 MiB frame ceiling (but
    under the control plane's 1 GiB one) kills the connection at the
    prefix — the reader must never park buffering bytes that will
    never arrive — and the server keeps serving its other clients."""
    import struct

    _, _, server, connect = served
    healthy = connect(shm=False)
    ours, theirs = socket.socketpair()
    server.serve_socket(ours)
    theirs.settimeout(5.0)
    theirs.sendall(struct.pack("!IIB", 1 << 29, 1 << 29, 32))
    assert theirs.recv(64) == b""  # dropped, no reply, no parking
    theirs.close()
    out = healthy.infer(numpy.zeros(16, numpy.float32))
    assert out.shape == (1, 4)


def test_hmac_rejects_wrong_secret(engine):
    batcher = ContinuousBatcher(engine, max_delay_s=0.001).start()
    server = BinaryTransportServer(batcher, port=None, secret=b"sesame")
    server.start_background()
    try:
        ours, theirs = socket.socketpair()
        server.serve_socket(ours)
        cli = BinaryTransportClient(sock=theirs, secret=b"sesame",
                                    shm=False)
        out = cli.infer(numpy.zeros(16, numpy.float32))
        assert out.shape == (1, 4)
        cli.close()
        # wrong secret: the server rejects the hello BEFORE parsing it
        # and drops the connection — the client never gets a reply
        ours2, theirs2 = socket.socketpair()
        server.serve_socket(ours2)
        with pytest.raises((ProtocolError, ConnectionError, OSError)):
            BinaryTransportClient(sock=theirs2, secret=b"wrong",
                                  shm=False, timeout=5.0)
        theirs2.close()
    finally:
        server.stop()
        batcher.stop()


@pytest.mark.chaos
def test_transport_overload_is_transient(served):
    """A shed request crosses the wire as the transient error frame
    and resurfaces client-side as ServeOverload with retry_after —
    the 503 protocol, minus the HTTP."""
    _, _, _, connect = served
    cli = connect(shm=False)
    chaos.install(chaos.FaultPlan(seed=1).add("serve.drop", "drop",
                                              nth=1))
    try:
        with pytest.raises(ServeOverload) as info:
            cli.infer(numpy.zeros(16, numpy.float32))
        assert info.value.retry_after > 0
        # only the first dispatch was armed; the connection survives
        out = cli.infer(numpy.zeros(16, numpy.float32))
        assert out.shape == (1, 4)
    finally:
        chaos.uninstall()


# -- the zero-copy block path the transport feeds ----------------------------


def test_submit_block_skips_staging(engine):
    """A rung-exact contiguous block dispatches without ever touching
    the ping-pong staging buffers (Device.put gets the caller's buffer
    — the XLA:CPU-hazard-safe copy); a non-aligned block falls back to
    a vectorized staging fill.  Both bit-match the sequential path."""
    batcher = ContinuousBatcher(engine, max_delay_s=0.0).start()
    try:
        rng = numpy.random.RandomState(4)
        x = numpy.ascontiguousarray(
            rng.rand(8, 16).astype(numpy.float32))
        ref = engine.infer(x)
        req = batcher.submit_block(x)
        assert req.done.wait(10)
        assert req.error is None
        assert (req.result == ref).all()
        assert 8 not in batcher._stage, \
            "rung-exact block went through staging"
        req2 = batcher.submit_block(numpy.ascontiguousarray(x[:5]))
        assert req2.done.wait(10) and req2.error is None
        assert (req2.result == ref[:5]).all()
        assert 8 in batcher._stage  # padded tail staged normally
        with pytest.raises(ValueError):
            batcher.submit_block(rng.rand(33, 16).astype(numpy.float32))
        with pytest.raises(ValueError):
            batcher.submit_block(rng.rand(4, 7).astype(numpy.float32))
    finally:
        batcher.stop()


def test_blocks_cobatch_with_rows_bit_exact(engine):
    """Blocks and single rows inside one collect window share a rung
    and every result matches the sequential reference."""
    rng = numpy.random.RandomState(5)
    x = rng.rand(7, 16).astype(numpy.float32)
    ref = engine.infer(x)
    hist = registry.histogram("serve.batch_size")
    hist.reset()
    batcher = ContinuousBatcher(engine, max_delay_s=0.5).start()
    try:
        reqs = [batcher.submit_block(numpy.ascontiguousarray(x[:3])),
                batcher.submit(x[3]),
                batcher.submit(x[4]),
                batcher.submit_block(numpy.ascontiguousarray(x[5:7]))]
        for req in reqs:
            assert req.done.wait(10)
            assert req.error is None, req.error
        assert (reqs[0].result == ref[:3]).all()
        assert (reqs[1].result == ref[3]).all()
        assert (reqs[2].result == ref[4]).all()
        assert (reqs[3].result == ref[5:7]).all()
        # proven on a co-batched dispatch, not four singleton batches
        assert max(hist.window_values()) >= 7
    finally:
        batcher.stop()
