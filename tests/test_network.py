"""Distributed control-plane tests: in-process master+slave over real
localhost sockets (reference test model: veles/tests/test_network.py:
111-137), payload codecs, checksum rejection, drop/requeue, chaos."""

import threading
import time

import numpy
import pytest

from veles_tpu.client import Client
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.network_common import decode_payload, encode_payload
from veles_tpu.prng import RandomGenerator
from veles_tpu.server import Server
from tests.test_models import BlobsLoader


def test_payload_codecs_roundtrip():
    obj = {"x": numpy.arange(1000), "s": "hello", "n": None}
    for codec in ("none", "gzip"):
        blob = encode_payload(obj, codec)
        back = decode_payload(blob)
        numpy.testing.assert_array_equal(back["x"], obj["x"])
        assert back["s"] == "hello" and back["n"] is None


def _build(mode, seed_key, device, max_epochs=3):
    wf = DummyWorkflow()
    wf.workflow.workflow_mode = mode
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator(seed_key, seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.initialize(device=device)
    return sw


def _start_server(master_sw, **kwargs):
    server = Server("127.0.0.1:0", master_sw, **kwargs)
    master_sw.workflow.on_workflow_finished = server.on_workflow_finished
    thread = server.start_background()
    deadline = time.time() + 5
    while server.port == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert server.port != 0
    return server, thread


@pytest.mark.parametrize("async_slave", [False, True])
def test_master_slave_full_cycle(cpu_device, async_slave):
    master = _build("master", "net_m", cpu_device)
    slave = _build("slave", "net_s", cpu_device)
    server, sthread = _start_server(master)

    client = Client("127.0.0.1:%d" % server.port, slave,
                    async_slave=async_slave)
    client.run()  # blocks until the master says stop

    server._done.wait(10)
    assert client.jobs_done > 0
    assert server.jobs_dispatched >= client.jobs_done
    assert server.updates_applied > 0
    # the master's decision saw the whole run and stopped it
    assert bool(master.decision.complete)
    assert master.decision.epoch_metrics[1] is not None
    # training actually converged through the delta-merge protocol
    assert master.decision.epoch_metrics[1] < 15.0, \
        "validation error %s%%" % master.decision.epoch_metrics[1]
    # master's canonical weights match what the slave ended up with
    # (sync mode: the last update came from the slave)
    master.forwards[0].weights.map_read()
    assert numpy.isfinite(master.forwards[0].weights.mem).all()


def test_checksum_mismatch_rejected(cpu_device):
    master = _build("master", "net_m2", cpu_device)
    slave = _build("slave", "net_s2", cpu_device)
    # a DIFFERENT workflow class => different checksum (the digest mixes
    # source file + class name, workflow.py checksum property)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    object.__setattr__(client, "workflow", _ChecksumProxy(slave))
    try:
        client.run()
    finally:
        server.stop()
    assert client.jobs_done == 0
    assert client._stopping  # gave up after the reject


class _ChecksumProxy(object):
    """Wraps a workflow but lies about its checksum."""

    def __init__(self, workflow):
        self._wf = workflow

    checksum = "bogus"

    def __getattr__(self, name):
        return getattr(self._wf, name)


def test_slave_death_requeues_jobs(cpu_device):
    """Chaos: the slave dies mid-run with injected faults; the master
    requeues its pending minibatches and a healthy slave finishes."""
    master = _build("master", "net_m3", cpu_device)
    server, _ = _start_server(master)

    # doomed slave (dies almost immediately, reconnects also die)
    doomed = _build("slave", "net_s3", cpu_device)
    doomed_client = Client("127.0.0.1:%d" % server.port, doomed,
                           death_probability=1.0, reconnect_limit=1)
    doomed_client.run()
    assert doomed_client.jobs_done == 0

    deadline = time.time() + 5
    while not master.loader.failed_minibatches and time.time() < deadline:
        time.sleep(0.02)
    assert master.loader.total_failed >= 1

    healthy = _build("slave", "net_s4", cpu_device)
    healthy_client = Client("127.0.0.1:%d" % server.port, healthy)
    healthy_client.run()
    server._done.wait(10)
    assert bool(master.decision.complete)
    assert healthy_client.jobs_done > 0


def test_frame_auth_full_cycle(cpu_device):
    """Matched shared secrets: HMAC-authenticated frames, run completes."""
    master = _build("master", "net_m5", cpu_device, max_epochs=2)
    slave = _build("slave", "net_s5", cpu_device, max_epochs=2)
    server, _ = _start_server(master, secret=b"sesame")
    client = Client("127.0.0.1:%d" % server.port, slave, secret=b"sesame")
    client.run()
    server._done.wait(10)
    assert client.jobs_done > 0
    assert bool(master.decision.complete)


def test_frame_auth_mismatch_rejected(cpu_device):
    """A peer without the right secret is dropped before any unpickling."""
    master = _build("master", "net_m6", cpu_device)
    slave = _build("slave", "net_s6", cpu_device)
    server, _ = _start_server(master, secret=b"right")
    client = Client("127.0.0.1:%d" % server.port, slave,
                    secret=b"wrong", reconnect_limit=1)
    try:
        client.run()
    finally:
        server.stop()
        server._done.wait(5)
    assert client.jobs_done == 0
    assert server.updates_applied == 0


def test_checksum_reject_reason(cpu_device):
    master = _build("master", "net_m7", cpu_device)
    slave = _build("slave", "net_s7", cpu_device)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    object.__setattr__(client, "workflow", _ChecksumProxy(slave))
    try:
        client.run()
    finally:
        server.stop()
    assert client.reject_reason == "checksum mismatch"


def test_pause_resume(cpu_device):
    """Server pause parks connected slaves (no job flow); resume releases
    the parked requests and the run completes (reference
    server.py:734-745)."""
    master = _build("master", "net_m8", cpu_device, max_epochs=2)
    slave = _build("slave", "net_s8", cpu_device, max_epochs=2)
    server, _ = _start_server(master)
    server.pause()

    client = Client("127.0.0.1:%d" % server.port, slave)
    cthread = client.start_background()

    deadline = time.time() + 5
    while client.sid is None and time.time() < deadline:
        time.sleep(0.01)
    assert client.sid is not None, "handshake should succeed while paused"
    time.sleep(0.5)
    assert client.jobs_done == 0, "no jobs must flow while paused"
    assert client.paused
    assert server.paused

    server.resume()
    cthread.join(20)
    server._done.wait(10)
    assert client.jobs_done > 0
    assert bool(master.decision.complete)


def test_all_codecs_roundtrip():
    from veles_tpu.network_common import (
        available_codecs, pack_payload, unpack_payload)
    obj = {"x": numpy.arange(2000, dtype=numpy.float32), "s": "веles"}
    codecs = available_codecs()
    assert {"none", "gzip", "bz2", "xz"} <= set(codecs)
    for codec in codecs:
        back = unpack_payload(pack_payload(obj, codec), codec)
        numpy.testing.assert_array_equal(back["x"], obj["x"])
        assert back["s"] == obj["s"]
    with pytest.raises(ValueError):
        pack_payload(obj, "brotli")


def test_shm_channel_slots():
    """Two-slot alternating shared-memory channel (SharedIO analog,
    reference txzmq/sharedio.py:44)."""
    from veles_tpu.network_common import ProtocolError, ShmChannel
    chan = ShmChannel.create(1 << 12)
    try:
        peer = ShmChannel.attach(chan.name)
        try:
            a = chan.write(b"first")
            b = chan.write(b"second")
            assert a[0] != b[0], "slots must alternate"
            assert peer.read(*a) == b"first"
            assert peer.read(*b) == b"second"
            # a third write lands back in the first slot
            c = chan.write(b"third")
            assert c[0] == a[0]
            assert peer.read(*c) == b"third"
            # oversized payloads fall back to inline (None)
            assert chan.write(b"x" * (1 << 12)) is None
            with pytest.raises(ProtocolError):
                peer.read(1 << 11, 1 << 12)
        finally:
            peer.close()
    finally:
        chan.close()


def test_shm_bypass_engaged_same_host(cpu_device):
    """Same-machine master+slave: payloads ride shared memory (the
    frame carries only descriptors), both directions, run completes."""
    master = _build("master", "net_m9", cpu_device, max_epochs=2)
    slave = _build("slave", "net_s9", cpu_device, max_epochs=2)
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    client.run()
    server._done.wait(10)
    assert client.jobs_done > 0
    assert bool(master.decision.complete)
    assert server.shm_sends > 0, "job payloads should ride shm"
    assert client.shm_sends > 0, "update payloads should ride shm"


def test_shm_bypass_disabled(cpu_device):
    master = _build("master", "net_m10", cpu_device, max_epochs=2)
    slave = _build("slave", "net_s10", cpu_device, max_epochs=2)
    server, _ = _start_server(master, use_shm=False)
    client = Client("127.0.0.1:%d" % server.port, slave)
    client.run()
    server._done.wait(10)
    assert client.jobs_done > 0
    assert server.shm_sends == 0
    assert client.shm_sends == 0
