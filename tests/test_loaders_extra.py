"""Extended loader family tests: images+augmentation, HDF5, pickles,
minibatch saver/replayer, queue/zmq feeds, ensemble loader,
downloader."""

import gzip
import json
import os
import pickle
import tarfile

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.base import TRAIN
from veles_tpu.prng import RandomGenerator


def _write_images(root_dir, split, classes=2, per_class=6):
    import cv2
    rng = numpy.random.RandomState(0)
    paths = []
    for label in range(classes):
        d = os.path.join(root_dir, split, "class%d" % label)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = (rng.rand(12, 12, 3) * 255).astype(numpy.uint8)
            img[:, :, label] = 255  # class-colored channel
            path = os.path.join(d, "img%02d.png" % i)
            cv2.imwrite(path, img)
            paths.append(path)
    return paths


def test_file_image_loader_and_augmentation(tmp_path):
    from veles_tpu.loader.image import (
        FileImageLoader, ImageAugmentation)
    _write_images(str(tmp_path), "train", per_class=8)
    _write_images(str(tmp_path), "valid", per_class=4)
    wf = DummyWorkflow()
    loader = FileImageLoader(
        wf, minibatch_size=8,
        validation_dir=os.path.join(str(tmp_path), "valid"),
        train_dir=os.path.join(str(tmp_path), "train"),
        augmentation=ImageAugmentation(
            scale=(8, 8), prng=RandomGenerator("aug", seed=1)),
        prng=RandomGenerator("img_l", seed=2))
    loader.initialize(device=None)
    assert loader.class_lengths == [0, 8, 16]
    assert loader.shape == (8, 8, 3)
    assert loader.unique_labels_count == 2
    loader.run()
    assert loader.minibatch_data.mem.max() <= 1.0


def test_augmentation_ops():
    from veles_tpu.loader.image import ImageAugmentation
    img = numpy.zeros((10, 10, 3), numpy.uint8)
    img[:, :5] = 255
    aug = ImageAugmentation(mirror="always",
                            prng=RandomGenerator("aug2", seed=1))
    out = aug.apply(img)
    assert out[:, :5].sum() == 0 and out[:, 5:].sum() > 0
    aug2 = ImageAugmentation(crop=(4, 4),
                             prng=RandomGenerator("aug3", seed=1))
    assert aug2.apply(img).shape == (4, 4, 3)
    aug3 = ImageAugmentation(color_space="GRAY",
                             prng=RandomGenerator("aug4", seed=1))
    assert aug3.apply(img).ndim == 2


def test_hdf5_loader(tmp_path, cpu_device):
    import h5py
    rng = numpy.random.RandomState(1)
    for split, n in (("train", 32), ("valid", 16)):
        with h5py.File(str(tmp_path / ("%s.h5" % split)), "w") as f:
            f["data"] = rng.rand(n, 6).astype(numpy.float32)
            f["labels"] = (numpy.arange(n) % 3).astype(numpy.int64)
    from veles_tpu.loader.hdf5 import FullBatchHDF5Loader
    wf = DummyWorkflow()
    loader = FullBatchHDF5Loader(
        wf, minibatch_size=16,
        validation_path=str(tmp_path / "valid.h5"),
        train_path=str(tmp_path / "train.h5"),
        prng=RandomGenerator("h5", seed=3))
    loader.initialize(device=cpu_device)
    assert loader.class_lengths == [0, 16, 32]
    assert loader.unique_labels_count == 3
    loader.run()
    assert loader.minibatch_size == 16


def test_pickles_loader(tmp_path):
    rng = numpy.random.RandomState(2)
    train = {"data": rng.rand(20, 4).astype(numpy.float32),
             "labels": list(numpy.arange(20) % 2)}
    with open(str(tmp_path / "train.pickle"), "wb") as f:
        pickle.dump(train, f)
    from veles_tpu.loader.pickles import PicklesLoader
    wf = DummyWorkflow()
    loader = PicklesLoader(
        wf, minibatch_size=10, train_path=str(tmp_path / "train.pickle"),
        prng=RandomGenerator("pk", seed=4))
    loader.initialize(device=None)
    assert loader.class_lengths == [0, 0, 20]
    loader.run()
    numpy.testing.assert_allclose(
        loader.minibatch_data.mem[:10],
        train["data"][loader.minibatch_indices.mem[:10]], rtol=1e-6)


def test_minibatch_saver_and_replay(tmp_path):
    from tests.test_models import BlobsLoader
    from veles_tpu.loader.saver import (
        MinibatchesLoader, MinibatchesSaver)
    wf = DummyWorkflow()
    loader = BlobsLoader(wf, minibatch_size=64,
                         prng=RandomGenerator("sv", seed=5))
    loader.initialize(device=None)
    saver = MinibatchesSaver(wf, path=str(tmp_path / "mb.gz"))
    saver.loader = loader
    saver.initialize()
    served = []
    for _ in range(6):
        loader.run()
        saver.run()
        served.append(numpy.array(
            loader.minibatch_data.mem[:loader.minibatch_size]))
    saver.close()

    wf2 = DummyWorkflow()
    replay = MinibatchesLoader(wf2, path=str(tmp_path / "mb.gz"),
                               prng=RandomGenerator("sv2", seed=6))
    replay.initialize(device=None)
    assert replay.class_lengths == loader.class_lengths
    for i in range(6):
        replay.run()
        numpy.testing.assert_allclose(
            replay.minibatch_data.mem[:replay.minibatch_size],
            served[i], rtol=1e-6)


def test_queue_loader_feeds():
    from veles_tpu.loader.feeds import InteractiveLoader
    wf = DummyWorkflow()
    loader = InteractiveLoader(wf, sample_shape=(4,), minibatch_size=1,
                               prng=RandomGenerator("q", seed=7))
    loader.initialize(device=None)
    loader.feed([1.0, 2.0, 3.0, 4.0])
    loader.run()
    numpy.testing.assert_array_equal(
        loader.minibatch_data.mem[0], [1, 2, 3, 4])
    assert loader.minibatch_size == 1


def test_zmq_loader_roundtrip():
    import zmq
    from veles_tpu.loader.feeds import ZeroMQLoader
    wf = DummyWorkflow()
    loader = ZeroMQLoader(wf, sample_shape=(3,), minibatch_size=1,
                          prng=RandomGenerator("z", seed=8))
    loader.initialize(device=None)
    context = zmq.Context.instance()
    sock = context.socket(zmq.DEALER)
    sock.connect(loader.endpoint)
    sock.send(pickle.dumps(numpy.array([9.0, 8.0, 7.0])))
    loader.run()
    numpy.testing.assert_array_equal(
        loader.minibatch_data.mem[0], [9, 8, 7])
    assert sock.recv() == b"ok"
    sock.close(0)
    loader.stop()


def test_ensemble_loader(tmp_path):
    from veles_tpu.loader.feeds import EnsembleLoader
    results = {"models": [
        {"id": 0, "snapshot": "a.pickle", "EvaluationFitness": -1.0},
        {"id": 1, "snapshot": "b.pickle", "EvaluationFitness": -2.0},
    ]}
    path = str(tmp_path / "ens.json")
    with open(path, "w") as f:
        json.dump(results, f)
    wf = DummyWorkflow()
    loader = EnsembleLoader(wf, results_path=path, minibatch_size=1,
                            prng=RandomGenerator("el", seed=9))
    loader.initialize(device=None)
    assert loader.class_lengths == [2, 0, 0]
    loader.run()
    assert loader.current_model["snapshot"] == "a.pickle"


def test_downloader_file_url(tmp_path):
    from veles_tpu.downloader import Downloader
    payload_dir = tmp_path / "payload"
    payload_dir.mkdir()
    (payload_dir / "dataset.txt").write_text("hello")
    archive = str(tmp_path / "ds.tar")
    with tarfile.open(archive, "w") as tar:
        tar.add(str(payload_dir / "dataset.txt"), arcname="dataset.txt")
    wf = DummyWorkflow()
    target = str(tmp_path / "out")
    dl = Downloader(wf, url="file://" + archive, directory=target,
                    files=["dataset.txt"])
    dl.initialize()
    assert (tmp_path / "out" / "dataset.txt").read_text() == "hello"
    # second initialize: already satisfied, no refetch needed
    dl2 = Downloader(wf, url="file:///nonexistent", directory=target,
                     files=["dataset.txt"])
    dl2.initialize()
