"""Fused train step + sharding tests on the virtual 8-device CPU mesh
(SURVEY.md section 4 implication b)."""

import numpy
import pytest

import jax

from veles_tpu.compiler import (
    LayerPlan, adopt_state, build_forward, build_train_step, extract_state,
    workflow_plan)
from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
from veles_tpu.parallel import (
    auto_mesh, batch_sharding, make_mesh, mlp_state_shardings, replicate,
    shard_batch)


def _state(rng, dims):
    out = []
    for fi, fo in zip(dims[:-1], dims[1:]):
        out.append({
            "weights": rng.randn(fi, fo).astype(numpy.float32) * 0.1,
            "bias": numpy.zeros(fo, numpy.float32),
            "accum_weights": numpy.zeros((fi, fo), numpy.float32),
            "accum_bias": numpy.zeros(fo, numpy.float32),
            "accum2_weights": None, "accum2_bias": None})
    return out


def _plans(lr=0.1):
    hyper = {"learning_rate": lr, "gradient_moment": 0.9}
    return [LayerPlan(All2AllTanh, hyper=hyper),
            LayerPlan(All2AllSoftmax, hyper=hyper)]


def _batch(rng, n=32, fan_in=16, classes=4):
    labels = (numpy.arange(n) % classes).astype(numpy.int32)
    centers = rng.randn(classes, fan_in).astype(numpy.float32) * 2
    x = (centers[labels] +
         rng.randn(n, fan_in).astype(numpy.float32) * 0.2)
    return x, labels


def test_fused_step_decreases_loss():
    rng = numpy.random.RandomState(0)
    state = _state(rng, (16, 32, 4))
    step = build_train_step(_plans())
    x, labels = _batch(rng)
    losses = []
    for _ in range(20):
        state, metrics = step(state, x, labels, numpy.float32(32))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5


def test_fused_step_matches_unit_graph():
    """The compiler path and the per-unit GD path must produce the same
    parameters after a step (same math, fused)."""
    from tests.test_models import build_mnist_like
    from veles_tpu.backends import Device
    dev = Device(backend="cpu")

    sw = build_mnist_like(dev)
    plans = workflow_plan(sw)
    state0 = jax.tree.map(lambda v: None if v is None else numpy.array(v),
                          extract_state(sw), is_leaf=lambda v: v is None)

    # one minibatch through the unit graph (TRAIN class comes 3rd; run
    # loader until a train minibatch is served)
    loader = sw.loader
    while True:
        loader.run()
        if loader.minibatch_class == 2:
            break
    for fwd in sw.forwards:
        fwd.run()
    sw.evaluator.run()
    for gd in reversed(sw.gds):
        gd.run()
    unit_state = extract_state(sw)

    step = build_train_step(plans, donate=False)
    x = numpy.asarray(loader.minibatch_data.devmem)
    labels = numpy.asarray(loader.minibatch_labels.devmem)
    fused_state, _ = step(state0, x, labels,
                          numpy.float32(loader.minibatch_size))

    for us, fs in zip(unit_state, fused_state):
        for key in ("weights", "bias"):
            numpy.testing.assert_allclose(
                numpy.asarray(us[key]), numpy.asarray(fs[key]),
                rtol=1e-4, atol=1e-6)


def test_dp_sharded_step_matches_single_device():
    rng = numpy.random.RandomState(3)
    state = _state(rng, (16, 32, 4))
    x, labels = _batch(rng, n=64)

    ref_step = build_train_step(_plans(), donate=False)
    ref_state, ref_metrics = ref_step(
        jax.tree.map(lambda v: None if v is None else numpy.array(v),
                     state, is_leaf=lambda v: v is None),
        x, labels, numpy.float32(64))

    mesh = auto_mesh()
    shardings = mlp_state_shardings(mesh, state)
    bsh = batch_sharding(mesh)
    step = build_train_step(_plans(), mesh=mesh, state_shardings=shardings,
                            batch_sharding=bsh, donate=False)
    dstate = jax.tree.map(lambda l, s: None if l is None else jax.device_put(l, s),
                          state, shardings, is_leaf=lambda v: v is None)
    dx = jax.device_put(x, bsh)
    dlabels = jax.device_put(labels, bsh)
    new_state, metrics = step(dstate, dx, dlabels, numpy.float32(64))

    assert abs(float(metrics["loss"]) - float(ref_metrics["loss"])) < 1e-5
    for rs, ns in zip(ref_state, new_state):
        numpy.testing.assert_allclose(
            numpy.asarray(rs["weights"]), numpy.asarray(ns["weights"]),
            rtol=1e-4, atol=1e-6)


def test_tp_dp_mesh_step_matches_single_device():
    rng = numpy.random.RandomState(4)
    state = _state(rng, (16, 32, 4))
    x, labels = _batch(rng, n=64)

    ref_step = build_train_step(_plans(), donate=False)
    ref_state, _ = ref_step(
        jax.tree.map(lambda v: None if v is None else numpy.array(v),
                     state, is_leaf=lambda v: v is None),
        x, labels, numpy.float32(64))

    mesh = make_mesh({"data": 4, "model": 2})
    shardings = mlp_state_shardings(mesh, state, model_axis="model")
    bsh = batch_sharding(mesh)
    step = build_train_step(_plans(), mesh=mesh, state_shardings=shardings,
                            batch_sharding=bsh, donate=False)
    dstate = jax.tree.map(lambda l, s: None if l is None else jax.device_put(l, s),
                          state, shardings, is_leaf=lambda v: v is None)
    new_state, _ = step(dstate, jax.device_put(x, bsh),
                        jax.device_put(labels, bsh), numpy.float32(64))
    for rs, ns in zip(ref_state, new_state):
        numpy.testing.assert_allclose(
            numpy.asarray(rs["weights"]), numpy.asarray(ns["weights"]),
            rtol=1e-3, atol=1e-5)


def test_graft_entry_single_chip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (128, 10)
    assert numpy.allclose(numpy.asarray(out).sum(axis=1), 1.0, atol=1e-3)


def test_graft_entry_dryrun_multichip():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)
    mod.dryrun_multichip(3)


def test_collective_bytes_analysis():
    """parse_collective_bytes finds the dp gradient all-reduce and its
    volume matches the parameter bytes (scaling.py's honest input)."""
    import jax
    import numpy
    from jax.sharding import NamedSharding, PartitionSpec as P

    from veles_tpu.compiler import build_train_step, LayerPlan
    from veles_tpu.models.all2all import All2AllSoftmax, All2AllTanh
    from veles_tpu.parallel import make_mesh
    from veles_tpu.parallel.analysis import (
        collective_bytes, parse_collective_bytes)

    # direct parser check, incl. tuple results
    hlo = """
  ar0 = f32[100]{0} all-reduce(x), replica_groups={}
  ar1 = (f32[2,3]{1,0}, bf16[4]{0}) all-reduce(y, z)
  other = f32[8]{0} add(a, b)
"""
    parsed = parse_collective_bytes(hlo)
    assert parsed["all-reduce"] == 400 + 24 + 8
    assert parsed["total"] == parsed["all-reduce"]

    n = 4
    mesh = make_mesh({"data": n}, jax.devices()[:n])
    plans = [LayerPlan(All2AllTanh, hyper={"learning_rate": 0.1}),
             LayerPlan(All2AllSoftmax, hyper={"learning_rate": 0.1})]
    rng = numpy.random.RandomState(0)
    state = [
        {"weights": rng.rand(16, 8).astype(numpy.float32),
         "bias": numpy.zeros(8, numpy.float32),
         "accum_weights": numpy.zeros((16, 8), numpy.float32),
         "accum_bias": numpy.zeros(8, numpy.float32),
         "accum2_weights": None, "accum2_bias": None},
        {"weights": rng.rand(8, 4).astype(numpy.float32),
         "bias": numpy.zeros(4, numpy.float32),
         "accum_weights": numpy.zeros((8, 4), numpy.float32),
         "accum_bias": numpy.zeros(4, numpy.float32),
         "accum2_weights": None, "accum2_bias": None},
    ]
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("data"))
    state_sh = jax.tree.map(lambda leaf: None if leaf is None else repl,
                            state, is_leaf=lambda x: x is None)
    step = build_train_step(plans, mesh=mesh, data_axis="data",
                            state_shardings=state_sh,
                            batch_sharding=bsh, donate=False)
    x = jax.device_put(rng.rand(8, 16).astype(numpy.float32), bsh)
    y = jax.device_put(rng.randint(0, 4, 8).astype(numpy.int32), bsh)
    state = jax.tree.map(
        lambda leaf: None if leaf is None else jax.device_put(leaf, repl),
        state, is_leaf=lambda v: v is None)
    traffic = collective_bytes(
        jax.jit(step), state, x, y, numpy.float32(8), None)
    param_bytes = 4 * (16 * 8 + 8 + 8 * 4 + 4)
    # the grad all-reduce must move at least the parameter gradients
    # (XLA may add small scalar reductions for the loss/n_err metrics)
    assert traffic["all-reduce"] >= param_bytes
    assert traffic["all-reduce"] <= param_bytes + 4096
