"""Schedule autotuner (veles_tpu/tune/, docs/kernels.md "Autotuning"):
cache key semantics, corrupt/stale fallback, planted-entry consults in
all four kernel families, tuned-vs-static bit-equality through the
Pallas interpreter, the GA fitness memo, quantization/feasibility
gates, the learned-cost-model fitness mode end to end, the fleet
schedule bank (export/merge/publish/watcher pickup), the fused-step
walk and the CLI round trip.

Every test sees a PRIVATE empty schedule cache (the conftest autouse
fixture redirects ``VELES_SCHEDULE_CACHE`` to tmp) — tests that want
entries plant them."""

import importlib
import json
import logging
import os

import numpy
import pytest

pytestmark = pytest.mark.tune

#: the module, not the function ``veles_tpu.ops``'s __init__ re-exports
#: under the same name
matmul_mod = importlib.import_module("veles_tpu.ops.matmul")


def _ints(rng, shape, lo=-3, hi=4):
    """Exactly-representable f32 operands: every precision level and
    tile order accumulates them without rounding, so tuned-vs-static
    comparisons can demand BIT equality."""
    import jax.numpy as jnp
    return jnp.asarray(rng.randint(lo, hi, shape).astype(numpy.float32))


def _plant(spec, schedule, source="test"):
    """Write one schedule-cache entry for ``spec`` keyed exactly the
    way the kernels' consults will look it up."""
    from veles_tpu.tune.cache import cache_for, device_kind, schedule_key
    digest, payload = schedule_key(
        spec["op"], spec["shape"], spec["dtype"],
        spec["precision_level"], device_kind(), spec["extra"])
    cache_for().put(digest, payload, schedule, source=source)
    return digest


# -- cache keys ---------------------------------------------------------------


def test_schedule_key_invariance_and_sensitivity():
    """Same spec -> same digest; every coordinate (shape, dtype,
    precision level, device kind, kernel version) changes it."""
    from veles_tpu.tune.cache import schedule_key
    base = ("matmul", (64, 128, 128), "float32", 0, "cpu",
            {"kernel_version": 2})
    d0, payload = schedule_key(*base)
    d1, _ = schedule_key(*base)
    assert d0 == d1
    assert payload["shape"] == [64, 128, 128]
    variants = [
        ("matmul", (64, 128, 256), "float32", 0, "cpu",
         {"kernel_version": 2}),
        ("matmul", (64, 128, 128), "bfloat16", 0, "cpu",
         {"kernel_version": 2}),
        ("matmul", (64, 128, 128), "float32", 1, "cpu",
         {"kernel_version": 2}),
        ("matmul", (64, 128, 128), "float32", 0, "TPU v5e",
         {"kernel_version": 2}),
        ("matmul", (64, 128, 128), "float32", 0, "cpu",
         {"kernel_version": 3}),
        ("conv_vjp", (64, 128, 128), "float32", 0, "cpu",
         {"kernel_version": 2}),
    ]
    digests = {schedule_key(*v)[0] for v in variants}
    assert d0 not in digests and len(digests) == len(variants)


def test_cache_roundtrip_and_len(tmp_path):
    from veles_tpu.tune.cache import ScheduleCache
    cache = ScheduleCache(str(tmp_path / "s.json"))
    assert len(cache) == 0 and cache.get("nope") is None
    cache.put("d1", {"op": "matmul"}, {"blocks": [8, 128, 128]},
              fitness=-0.5, evals=3)
    # a fresh instance reads the persisted file
    reloaded = ScheduleCache(str(tmp_path / "s.json"))
    entry = reloaded.get("d1")
    assert entry["schedule"] == {"blocks": [8, 128, 128]}
    assert entry["fitness"] == -0.5 and entry["evals"] == 3
    assert len(reloaded) == 1


def test_put_merges_concurrent_writers(tmp_path):
    """put() re-reads the file before its read-modify-write: a second
    writer's entries persisted after our lazy load survive our save
    (the fleet pre-tune must not be wiped by a later local sweep)."""
    from veles_tpu.tune.cache import ScheduleCache
    path = str(tmp_path / "s.json")
    ours = ScheduleCache(path)
    assert len(ours) == 0  # lazy load happens now, file absent
    theirs = ScheduleCache(path)
    for i in range(3):
        theirs.put("fleet-%d" % i, {"op": "matmul"},
                   {"blocks": [8, 128, 128]})
    ours.put("local", {"op": "matmul"}, {"blocks": [16, 128, 128]})
    merged = ScheduleCache(path)
    assert len(merged) == 4
    assert merged.get("fleet-2") is not None
    assert merged.get("local")["schedule"]["blocks"] == [16, 128, 128]


def test_provenance_rejects_invalid_entry_like_the_consult(caplog):
    """An entry the kernel consult would reject (MXU-illegal blocks)
    must not be attributed as "tuned" in MFU rows — provenance runs
    the same structural validation."""
    from veles_tpu.tune.cache import provenance
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(40, 40, 40, "float32", 0)
    args = (spec["op"], spec["shape"], spec["dtype"],
            spec["precision_level"], spec["extra"])
    _plant(spec, {"blocks": [5, 99, 1]})  # MXU-illegal
    with caplog.at_level(logging.WARNING, logger="veles_tpu.tune"):
        assert provenance(*args) == "static"


def test_corrupt_cache_file_warns_and_serves_static(caplog):
    """A garbage cache file is a WARNING and a miss — the matmul call
    still runs on the static tables, bit-identical to a no-cache run."""
    from veles_tpu.ops.matmul import matmul
    cache_dir = os.environ["VELES_SCHEDULE_CACHE"]
    os.makedirs(cache_dir, exist_ok=True)
    with open(os.path.join(cache_dir, "schedules.json"), "w") as fout:
        fout.write("{this is not json")
    rng = numpy.random.RandomState(0)
    a, b = _ints(rng, (16, 24)), _ints(rng, (24, 32))
    with caplog.at_level(logging.WARNING, logger="veles_tpu.tune"):
        out = matmul(a, b)
    assert any("unreadable" in r.getMessage() for r in caplog.records)
    ref = matmul(a, b, blocks=(16, 128, 128))
    numpy.testing.assert_array_equal(numpy.asarray(out),
                                     numpy.asarray(ref))


def test_malformed_entry_warns_and_serves_static(caplog):
    """A structurally broken schedule (wrong multiples / not a dict)
    falls back to the static tables with a warning, never a crash."""
    from veles_tpu.ops.matmul import matmul
    from veles_tpu.tune.spec import matmul_spec
    rng = numpy.random.RandomState(1)
    a, b = _ints(rng, (16, 24)), _ints(rng, (24, 32))
    ref = numpy.asarray(matmul(a, b))

    spec = matmul_spec(16, 24, 32, "float32", 0)
    _plant(spec, {"blocks": [7, 100, 3]})  # MXU-illegal multiples
    with caplog.at_level(logging.WARNING, logger="veles_tpu.tune"):
        out = matmul(a, b)
    assert any("malformed" in r.getMessage() for r in caplog.records)
    numpy.testing.assert_array_equal(numpy.asarray(out), ref)


def test_stale_kernel_version_is_a_miss(monkeypatch):
    """An entry keyed to an older kernel version never serves the new
    algorithm: bumping the version turns the planted hit into a miss."""
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(16, 24, 32, "float32", 0)
    _plant(spec, {"blocks": [8, 128, 128]})
    seen = []
    real = matmul_mod._matmul_jit

    def spy(a, b, pl, blocks, od, interp):
        seen.append(blocks)
        return real(a, b, pl, blocks, od, interp)

    monkeypatch.setattr(matmul_mod, "_matmul_jit", spy)
    rng = numpy.random.RandomState(2)
    a, b = _ints(rng, (16, 24)), _ints(rng, (24, 32))
    matmul_mod.matmul(a, b)
    assert seen[-1] == (8, 128, 128)  # hit on the current version
    monkeypatch.setattr(matmul_mod, "MATMUL_KERNEL_VERSION",
                        matmul_mod.MATMUL_KERNEL_VERSION + 1)
    matmul_mod.matmul(a, b)
    assert seen[-1] is None  # stale version: static tables


# -- planted-entry consults + bit-equality ------------------------------------


def test_planted_entry_serves_matmul_bit_equal(monkeypatch):
    """matmul() demonstrably loads tuned blocks from a planted cache
    entry, and the tuned result is BIT-identical to the static-table
    result on representable operands (tiles change schedules, never
    math)."""
    rng = numpy.random.RandomState(3)
    a, b = _ints(rng, (24, 40)), _ints(rng, (40, 48))
    base = numpy.asarray(matmul_mod.matmul(a, b))

    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(24, 40, 48, "float32", 0)
    _plant(spec, {"blocks": [8, 128, 128]})

    seen = []
    real = matmul_mod._matmul_jit

    def spy(a_, b_, pl, blocks, od, interp):
        seen.append(blocks)
        return real(a_, b_, pl, blocks, od, interp)

    monkeypatch.setattr(matmul_mod, "_matmul_jit", spy)
    tuned = numpy.asarray(matmul_mod.matmul(a, b))
    assert seen == [(8, 128, 128)]
    numpy.testing.assert_array_equal(tuned, base)


def test_planted_entry_serves_conv_vjp_bit_equal(monkeypatch):
    """fused_conv_vjp consults the cache for its wgrad tiles; the
    tuned schedule's gradients are bit-identical on representable
    operands."""
    from veles_tpu.ops import conv_vjp as conv_mod
    rng = numpy.random.RandomState(4)
    import jax.numpy as jnp
    x = _ints(rng, (2, 6, 6, 3))
    w = _ints(rng, (3, 3, 3, 4), -2, 3)
    dy = _ints(rng, (2, 6, 6, 4))
    y = jnp.zeros((2, 6, 6, 4), jnp.float32)  # linear epilogue: unused

    def run():
        _, gw, gb = conv_mod.fused_conv_vjp(
            x, w, y, dy, activation="linear", padding=(1, 1, 1, 1),
            sliding=(1, 1), need_err_input=False)
        return numpy.asarray(gw), numpy.asarray(gb)

    gw0, gb0 = run()

    from veles_tpu.tune.spec import conv_vjp_spec
    spec = conv_vjp_spec(x.shape, 3, 3, 4, (6, 6), "float32", 0)
    _plant(spec, {"blocks": [128, 128, 8]})

    seen = []
    real = conv_mod._fused_wgrad_jit

    def spy(x_, y_, dy_, act, ky, kx, out_hw, padding, sliding, pl,
            blocks, interp):
        seen.append(blocks)
        return real(x_, y_, dy_, act, ky, kx, out_hw, padding,
                    sliding, pl, blocks, interp)

    monkeypatch.setattr(conv_mod, "_fused_wgrad_jit", spy)
    gw1, gb1 = run()
    assert seen == [(128, 128, 8)]
    numpy.testing.assert_array_equal(gw1, gw0)
    numpy.testing.assert_array_equal(gb1, gb0)


def test_planted_entry_serves_pool_bwd_bit_equal(monkeypatch):
    """max_pool_bwd consults the cache for its W tiling; a tuned
    owb routes bit-identically (select-and-scatter is value-exact)."""
    import jax.numpy as jnp

    from veles_tpu.models.pooling import MaxPooling
    from veles_tpu.ops import pool_bwd as pool_mod
    rng = numpy.random.RandomState(5)
    x = _ints(rng, (2, 8, 8, 3), -5, 6)
    y = MaxPooling.apply({}, x, window=(2, 2), sliding=(2, 2),
                         pallas_bwd=False)
    dy = _ints(rng, (2,) + tuple(y.shape[1:]))
    base = numpy.asarray(pool_mod.max_pool_bwd(
        x, y, dy, window=(2, 2), sliding=(2, 2)))

    from veles_tpu.tune.spec import pool_bwd_spec
    spec = pool_bwd_spec(x.shape, (4, 4), (2, 2), (2, 2), "float32")
    _plant(spec, {"owb": 2})

    seen = []
    real = pool_mod._max_pool_bwd_jit

    def spy(x_, y_, dy_, window, sliding, interp, owb=None):
        seen.append(owb)
        return real(x_, y_, dy_, window, sliding, interp, owb)

    monkeypatch.setattr(pool_mod, "_max_pool_bwd_jit", spy)
    tuned = numpy.asarray(pool_mod.max_pool_bwd(
        x, y, dy, window=(2, 2), sliding=(2, 2)))
    assert seen == [2]
    numpy.testing.assert_array_equal(tuned, base)
    assert jnp.asarray(dy).dtype == jnp.float32


# -- measurement discipline ---------------------------------------------------


def test_filter_passes_is_the_shared_definition():
    """bench.py's _filter_passes IS tune.measure.filter_passes — one
    jitter policy, no drift."""
    import bench
    from veles_tpu.tune.measure import filter_passes
    assert bench._filter_passes is filter_passes
    assert filter_passes([-1.0, 2.0, 3.0]) == [2.0, 3.0]
    # all-jitter: raw list unchanged, caller's floor rejects
    assert filter_passes([-1.0, -2.0]) == [-1.0, -2.0]


def test_rank_positive_majority_discipline():
    """A candidate with a positive MINORITY of passes is rejected even
    if its surviving samples are tiny — the jitter-swamped-tile
    crowning the matmul autotuner documents."""
    from veles_tpu.tune.measure import rank
    meds = rank({"honest": [1.0, 1.1, 0.9],
                 "jitter_swamped": [-1.0, -1.0, 0.001],
                 "all_jitter": [-1.0, -2.0, -3.0]})
    assert meds["honest"] == 1.0
    assert meds["jitter_swamped"] is None
    assert meds["all_jitter"] is None


# -- GA memoization + quantization/feasibility --------------------------------


def test_duplicate_genomes_memoized_invocation_count():
    """Crossover/elitism duplicates are FREE: fitness_fn runs at most
    once per distinct genome across all generations."""
    from veles_tpu.genetics import GeneticsOptimizer, Tune
    from veles_tpu.prng import RandomGenerator

    calls = []

    def fitness(spec):
        calls.append(spec["x"])
        return -(spec["x"] - 0.7) ** 2

    opt = GeneticsOptimizer(
        {"x": Tune(0.0, 0.0, 1.0)}, fitness, generations=5,
        population=6, rng=RandomGenerator("memo", seed=5),
        binary_bits=1, mutation="binary", mutation_rate=0.5)
    opt.run()
    # binary_bits=1 collapses mutated genes onto {0.0, 1.0}: plenty of
    # duplicate genomes across 5 generations — every one memoized
    assert len(calls) == len(set(calls))
    assert all(c.fitness is not None
               for c in opt.population.chromosomes)


def test_batch_fitness_path_evaluates_generations_together():
    """batch_fitness_fn sees each generation's (deduplicated) pending
    specs as ONE list — the interleaved-measurement hook."""
    from veles_tpu.genetics import GeneticsOptimizer, Tune
    from veles_tpu.prng import RandomGenerator

    batches = []

    def boom(spec):  # the serial path must NOT be used
        raise AssertionError("serial fitness path used")

    def batch(specs):
        batches.append(len(specs))
        return [-(s["x"] - 0.5) ** 2 for s in specs]

    opt = GeneticsOptimizer(
        {"x": Tune(0.0, 0.0, 1.0)}, boom, generations=3, population=5,
        rng=RandomGenerator("batch", seed=9), batch_fitness_fn=batch)
    opt.run()
    # generation 0 evaluates the full population in ONE batch; later
    # generations only ship genomes the values-keyed memo hasn't seen
    # (a fully-duplicated generation ships nothing at all)
    assert batches and batches[0] == 5
    assert len(batches) <= 3 and sum(batches) <= 15
    assert all(c.fitness is not None
               for c in opt.population.chromosomes)


def test_quantization_lands_on_mxu_multiples():
    from veles_tpu.tune.spec import FAMILIES, matmul_spec
    family = FAMILIES["matmul"]
    spec = matmul_spec(300, 300, 300, "float32", 0)
    sched = family.quantize(spec, {"bm": 13.7, "bn": 200.2,
                                   "bk": 510.9})
    bm, bn, bk = sched["blocks"]
    assert bm % 8 == 0 and bn % 128 == 0 and bk % 128 == 0
    # clamped into the padded-shape box
    assert bm <= 304 and bn <= 384 and bk <= 384
    assert family.validate(sched) is not None


def test_infeasible_candidate_rejected_before_compile(monkeypatch):
    """A VMEM-overflowing candidate is PENALTY'd without ever building
    a runner (= without paying a compile)."""
    from veles_tpu.tune import spec as spec_mod
    from veles_tpu.tune.autotune import PENALTY, evaluate_candidate
    from veles_tpu.tune.spec import matmul_spec

    spec = matmul_spec(4096, 4096, 4096, "float32", 0)
    big = {"blocks": [1024, 2048, 2048]}
    assert not spec_mod.FAMILIES["matmul"].feasible(spec, big)

    def boom(self, *a):
        raise AssertionError("compile paid for an infeasible tile")

    monkeypatch.setattr(spec_mod.MatmulFamily, "build_runner", boom)
    fitness = evaluate_candidate({
        "family": "matmul", "spec": spec,
        "genes": {"bm": 1024, "bn": 2048, "bk": 2048},
        "fitness_mode": "compile"})
    assert fitness == PENALTY


# -- the tuner end to end -----------------------------------------------------


def test_tuner_ga_then_cache_hit():
    """First tune: GA runs (compile fitness), persists.  Second tune of
    the same spec: pure cache hit, ZERO evaluations."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec

    spec = matmul_spec(16, 32, 48, "float32", 0)

    def tuner():
        return ScheduleTuner(spec, generations=2, population=4,
                             fitness="compile",
                             rng=RandomGenerator("t", seed=3))

    first = tuner().tune()
    assert first["source"] == "ga" and first["evals"] >= 1
    # "evals" counts compiles PAID; "genomes" distinct genomes
    # dispatched — memo/feasibility savings show as genomes >= evals
    assert first["genomes"] >= first["evals"]
    blocks = first["schedule"]["blocks"]
    assert (blocks[0] % 8 == 0 and blocks[1] % 128 == 0
            and blocks[2] % 128 == 0)
    second = tuner().tune()
    assert second["source"] == "cache" and second["evals"] == 0
    assert second["schedule"] == first["schedule"]


def test_autotune_matmul_migrates_shipped_device_info_entry():
    """A shipped devices/device_infos.json winner (the OLD persistence
    path) serves instantly on a fresh schedule cache AND is migrated
    into it — a fresh host never re-pays the headline sweep."""
    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops.matmul import (MATMUL_KERNEL_VERSION,
                                      autotune_matmul)
    from veles_tpu.tune.cache import cache_for

    info = DeviceInfo("legacy-chip")
    info.table["matmul:v%d:float32:pl0:s256" %
               MATMUL_KERNEL_VERSION] = [768, 512, 512]
    assert autotune_matmul(info, size=256) == (768, 512, 512)
    # migrated: a second call hits the schedule cache directly
    entries = cache_for().entries()
    assert any(e.get("source") == "device_info"
               for e in entries.values())


def test_tuner_invalid_cache_hit_retunes():
    """An entry the kernels' consult would reject must be a MISS for
    the tuner too — it retunes and overwrites instead of reporting
    source='cache' forever while static tiles actually serve."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec

    spec = matmul_spec(16, 32, 48, "float32", 0)
    _plant(spec, {"blocks": [5, 99, 1]})  # MXU-illegal
    row = ScheduleTuner(spec, generations=1, population=4,
                        fitness="compile",
                        rng=RandomGenerator("rt", seed=2)).tune()
    assert row["source"] == "ga"
    blocks = row["schedule"]["blocks"]
    assert blocks[0] % 8 == 0 and blocks[1] % 128 == 0


def test_put_does_not_revert_concurrent_retune(tmp_path):
    """Fresher disk state wins per digest: another process's re-tune
    of digest X survives our later put of digest Y."""
    from veles_tpu.tune.cache import ScheduleCache
    path = str(tmp_path / "s.json")
    ours = ScheduleCache(path)
    ours.put("X", {"op": "matmul"}, {"blocks": [8, 128, 128]})
    theirs = ScheduleCache(path)
    theirs.put("X", {"op": "matmul"}, {"blocks": [16, 256, 256]})
    ours.put("Y", {"op": "matmul"}, {"blocks": [8, 128, 128]})
    final = ScheduleCache(path)
    assert final.get("X")["schedule"]["blocks"] == [16, 256, 256]
    assert final.get("Y") is not None


def test_f32_winner_seeds_survive_small_populations():
    """The dtype-specific measured winners seed FIRST so a default
    population of 8 cannot truncate them away."""
    from veles_tpu.tune.spec import FAMILIES, matmul_spec
    seeds = FAMILIES["matmul"].seeds(
        matmul_spec(3001, 3001, 3001, "float32", 0))
    assert seeds[0]["blocks"] == [768, 512, 512]
    # bf16 has no dtype-specific tiles: generic list unchanged
    bf16 = FAMILIES["matmul"].seeds(
        matmul_spec(3001, 3001, 3001, "bfloat16", 0))
    assert bf16[0]["blocks"] == [256, 256, 256]


def test_snap_collapses_clamp_identical_genomes():
    """Genomes that quantize to the same schedule snap to bit-equal
    values — so the GA's values-keyed memo dedupes them on EVERY
    evaluator path (workers/farm children share no schedule memo)."""
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(512, 512, 512, "float32", 0)
    tuner = ScheduleTuner(spec, fitness="compile")
    snap = tuner._snap_genome(tuner.family.space(spec))
    # gene order is the GA's sorted-path order: (bk, bm, bn)
    a = snap([130.2, 254.0, 260.0])
    b = snap([127.9, 253.1, 270.1])
    numpy.testing.assert_array_equal(a, b)
    numpy.testing.assert_array_equal(a, [128.0, 256.0, 256.0])


def test_pool_footprint_formula_is_shared():
    """tune.spec's pool feasibility calls the kernel planner's OWN
    footprint helper — one formula, no drift."""
    from veles_tpu.ops.pool_bwd import (POOL_VMEM_BUDGET_BYTES,
                                        pool_block_footprint)
    from veles_tpu.tune.spec import FAMILIES, pool_bwd_spec
    spec = pool_bwd_spec((2, 8, 8, 3), (4, 4), (2, 2), (2, 2),
                         "float32")
    family = FAMILIES["pool_bwd"]
    assert family.feasible(spec, {"owb": 2})
    assert (pool_block_footprint(8, 3, 4, 2, (2, 2), (2, 2), 4)
            <= POOL_VMEM_BUDGET_BYTES)


def test_tuner_untunable_pool_shape():
    """Overlapping pool windows admit no halo-free W tiling: the tuner
    reports 'untunable' and persists nothing."""
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.cache import cache_for
    from veles_tpu.tune.spec import pool_bwd_spec

    spec = pool_bwd_spec((2, 9, 9, 3), (4, 4), (3, 3), (2, 2),
                         "float32")
    row = ScheduleTuner(spec, fitness="compile").tune()
    assert row["source"] == "untunable" and row["schedule"] is None
    assert len(cache_for()) == 0


def test_provenance_and_counters():
    from veles_tpu.tune.cache import provenance, tune_counters
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(16, 24, 32, "float32", 0)
    args = (spec["op"], spec["shape"], spec["dtype"],
            spec["precision_level"], spec["extra"])
    assert provenance(*args) == "static"
    _plant(spec, {"blocks": [8, 128, 128]})
    assert provenance(*args) == "tuned"
    counters = tune_counters()
    assert counters["entries"] == 1


# -- the walk + CLI -----------------------------------------------------------


def test_walk_collects_conv_pool_and_matmul_specs():
    """One lowering of a conv+pool+softmax fused step yields specs for
    all three kernel families (conv/pool from the recorded consults,
    matmul from the dot_general harvest)."""
    from veles_tpu.models.zoo import build_plans_and_state
    from veles_tpu.tune.walk import collect_specs

    layer_specs = [
        {"type": "conv_str", "n_kernels": 4, "kx": 3, "ky": 3,
         "padding": 1, "learning_rate": 0.05, "gradient_moment": 0.9},
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "softmax", "output_sample_shape": 5,
         "learning_rate": 0.05, "gradient_moment": 0.9},
    ]
    plans, state, _ = build_plans_and_state(layer_specs, (8, 8, 3),
                                            seed=2)
    specs = collect_specs(plans, state, 4, (8, 8, 3))
    ops = {spec["op"] for spec in specs}
    assert {"conv_vjp", "pool_bwd", "matmul"} <= ops
    digests = [spec["digest"] for spec in specs]
    assert len(digests) == len(set(digests))  # deduplicated
    conv = next(s for s in specs if s["op"] == "conv_vjp")
    assert conv["shape"][0] == 9  # 3x3 taps
    assert conv["raw"]["x_shape"] == [4, 8, 8, 3]


def test_cli_tune_receipt_and_second_run_hits(tmp_path, capsys):
    """python -m veles_tpu.tune round trip: first run tunes and writes
    TUNE.json + the persisted cache; the second run is ALL cache hits
    with zero evaluations."""
    from veles_tpu.tune.__main__ import main

    out1 = str(tmp_path / "TUNE1.json")
    out2 = str(tmp_path / "TUNE2.json")
    argv = ["--model", "mlp", "--hidden", "16", "--batch", "8",
            "--fitness", "compile", "--generations", "1",
            "--population", "4", "--ops", "matmul",
            "--max-specs", "2", "--out", out1]
    assert main(argv) == 0
    receipt = json.load(open(out1))
    assert receipt["counts"].get("ga", 0) >= 1
    assert receipt["evals"] >= 1
    assert os.path.exists(receipt["cache_path"])
    for row in receipt["specs"]:
        assert row["op"] == "matmul"

    assert main(argv[:-1] + [out2]) == 0
    second = json.load(open(out2))
    assert second["counts"] == {"cache": len(second["specs"])}
    assert second["evals"] == 0
    capsys.readouterr()  # swallow the CLI's progress prints


# -- the attention family -----------------------------------------------------


def test_attention_family_space_quantize_feasibility():
    """The attention gene box tracks the padded grid (bq rides the
    sublane quantum, bk the lane quantum), quantization lands on legal
    multiples inside the caps, and the feasibility gate uses the
    kernel's own VMEM footprint."""
    from veles_tpu.tune.spec import (FAMILIES, TUNE_VMEM_BUDGET_BYTES,
                                     attention_spec)
    fam = FAMILIES["attention"]
    spec = attention_spec(2, 192, 32, "float32", 0)
    # shape = (B, ceil8(T), ceil128(T), ceil128(dh)) — grid coords
    assert spec["shape"] == [2, 192, 256, 128]
    space = fam.space(spec)
    assert (space["bq"].min, space["bq"].max) == (8, 192)
    assert (space["bk"].min, space["bk"].max) == (128, 256)
    sched = fam.quantize(spec, {"bq": 61.7, "bk": 200.0})
    assert sched["blocks"][0] % 8 == 0 and sched["blocks"][1] % 128 == 0
    assert sched["blocks"][0] <= 192 and sched["blocks"][1] <= 256
    assert fam.feasible(spec, sched)
    assert fam.footprint(spec, {"blocks": [8, 128]}) <= \
        TUNE_VMEM_BUDGET_BYTES
    # validate mirrors the consult: MXU-illegal or malformed -> None
    assert fam.validate({"blocks": [64, 256]}) == {"blocks": [64, 256]}
    assert fam.validate({"blocks": [60, 256]}) is None
    assert fam.validate({"blocks": [64, 200]}) is None
    assert fam.validate({"blocks": [64]}) is None
    assert fam.genes_of({"blocks": [64, 256]}) == {"bq": 64, "bk": 256}


def test_planted_entry_serves_attention_bit_equal(monkeypatch):
    """flash_attention() demonstrably loads tuned (bq, bk) from a
    planted cache entry: the consult run is BIT-identical to passing
    the planted blocks explicitly (same program, so the cache changed
    nothing but the schedule), and stays within the single-k-tile ULP
    contract of the default-blocks run (a bq-only change repartitions
    q rows; XLA's vectorized transcendentals may round the same row
    differently across tile layouts — test_transformer's bound)."""
    from veles_tpu.ops import attention as att_mod

    rng = numpy.random.RandomState(7)
    q = _ints(rng, (2, 192, 32))
    k = _ints(rng, (2, 192, 32))
    v = _ints(rng, (2, 192, 32))

    seen = []
    real = att_mod._flash_fn

    def spy(scale, level, blocks):
        seen.append(blocks)
        return real(scale, level, blocks)

    monkeypatch.setattr(att_mod, "_flash_fn", spy)
    base = numpy.asarray(att_mod.flash_attention(q, k, v))
    assert seen == [att_mod._DEFAULT_BLOCKS]  # empty cache -> static
    explicit = numpy.asarray(
        att_mod.flash_attention(q, k, v, blocks=(64, 256)))

    from veles_tpu.tune.spec import attention_spec
    _plant(attention_spec(2, 192, 32, "float32", 0),
           {"blocks": [64, 256]})
    seen.clear()
    tuned = numpy.asarray(att_mod.flash_attention(q, k, v))
    assert seen == [(64, 256)]
    numpy.testing.assert_array_equal(tuned, explicit)
    assert float(numpy.abs(tuned - base).max()) < 1e-5


def test_attention_tuner_ga_then_cache_hit():
    """Attention joins the tune-once contract: the first tune runs the
    GA (compile fitness over the full fwd+bwd custom_vjp step) and
    persists; the SECOND run of the same spec is all cache hits with
    ZERO evaluations."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import attention_spec

    spec = attention_spec(2, 64, 16, "float32", 0)

    def tuner():
        return ScheduleTuner(spec, generations=1, population=3,
                             fitness="compile",
                             rng=RandomGenerator("att", seed=5))

    first = tuner().tune()
    assert first["source"] == "ga" and first["evals"] >= 1
    blocks = first["schedule"]["blocks"]
    assert blocks[0] % 8 == 0 and blocks[1] % 128 == 0
    second = tuner().tune()
    assert second["source"] == "cache" and second["evals"] == 0
    assert second["schedule"] == first["schedule"]


# -- fitness="model" ----------------------------------------------------------


def test_model_fitness_thin_data_falls_back_to_base():
    """fitness='model' with an empty measurement sidecar degrades to
    the base mode and SAYS SO: the receipt row carries the fallback
    reason, and the tune still lands a valid persisted winner."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec

    spec = matmul_spec(16, 32, 48, "float32", 0)
    row = ScheduleTuner(spec, generations=1, population=4,
                        fitness="model", model_base="compile",
                        rng=RandomGenerator("mf", seed=9)).tune()
    assert row["source"] == "ga" and row["evals"] >= 1
    assert row["model"]["fallback"] == "thin-data"
    assert row["model"]["predicted"] == 0
    assert row["schedule"]["blocks"][0] % 8 == 0


def test_model_fitness_pool_run_degrades_to_base(caplog):
    """Model ranking is in-process only: asking for workers (or farm
    slaves) degrades fitness='model' to the base mode up front instead
    of mis-ranking across children that share no model."""
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec

    with caplog.at_level(logging.WARNING):
        tuner = ScheduleTuner(matmul_spec(16, 32, 48, "float32", 0),
                              fitness="model", model_base="compile",
                              workers=2)
    assert tuner.fitness_mode == "compile"
    assert any("in-process only" in r.message for r in caplog.records)


def test_model_fitness_e2e_tunes_with_fewer_compiles_and_serves():
    """The headline loop end to end on real compiles: a measured base
    leg builds the sidecar, then a fitness='model' re-tune trains the
    stump model, compiles only the top-ranked slice (predicted >= 1,
    evals below the base leg's), and its MEASURED winner both persists
    and serves the actual matmul consult bit-identically."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.spec import matmul_spec

    rng = numpy.random.RandomState(11)
    a, b = _ints(rng, (64, 512)), _ints(rng, (512, 512))
    base_out = numpy.asarray(matmul_mod.matmul(a, b))  # static tiles

    # base leg: compile-fitness GA over two specs -> measurement
    # triples in >= 2 spec groups (leave-one-spec-out needs a held-out
    # group to validate against).  population 14 so the seeded initial
    # generation carries enough DISTINCT schedules for the model leg's
    # top-decile cut to actually skip some (floor is 2 per generation)
    spec = matmul_spec(64, 512, 512, "float32", 0)
    side = matmul_spec(128, 512, 512, "float32", 0)
    base_row = ScheduleTuner(spec, generations=2, population=14,
                             fitness="compile",
                             rng=RandomGenerator("mb", seed=13)).tune()
    ScheduleTuner(side, generations=1, population=10,
                  fitness="compile",
                  rng=RandomGenerator("ms", seed=14)).tune()
    assert base_row["source"] == "ga" and base_row["evals"] >= 3

    model_row = ScheduleTuner(
        spec, generations=2, population=14, fitness="model",
        model_base="compile", model_min_triples=6, model_trust=10.0,
        rng=RandomGenerator("mb", seed=13)).tune(force=True)
    info = model_row["model"]
    assert info["fallback"] is None and info["trusted"]
    assert info["triples"] >= 6 and info["groups"] >= 2
    # the receipt: predictions replaced compiles
    assert info["predicted"] >= 1
    assert model_row["evals"] < base_row["evals"]
    # the winner is a real MEASUREMENT, never a prediction
    assert model_row["source"] == "ga"
    assert model_row["fitness"] is not None
    winner = model_row["schedule"]["blocks"]

    seen = []
    real = matmul_mod._matmul_jit

    def spy(a_, b_, pl, blocks, od, interp):
        seen.append(blocks)
        return real(a_, b_, pl, blocks, od, interp)

    import pytest as _pytest
    mp = _pytest.MonkeyPatch()
    try:
        mp.setattr(matmul_mod, "_matmul_jit", spy)
        tuned_out = numpy.asarray(matmul_mod.matmul(a, b))
    finally:
        mp.undo()
    assert seen == [tuple(winner)]
    numpy.testing.assert_array_equal(tuned_out, base_out)


# -- the fleet schedule bank --------------------------------------------------


def test_bank_merge_into_fresh_cache_serves_with_zero_local_evals(
        monkeypatch):
    """The fleet contract: host A tunes and exports; host B (a FRESH
    empty cache) merges the bank and immediately serves the identical
    schedule — consult bit-equal, re-tune all cache hits, ZERO local
    evaluations paid."""
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.tune.autotune import ScheduleTuner
    from veles_tpu.tune.cache import cache_for
    from veles_tpu.tune.spec import matmul_spec

    rng = numpy.random.RandomState(17)
    a, b = _ints(rng, (16, 32)), _ints(rng, (32, 48))
    base_out = numpy.asarray(matmul_mod.matmul(a, b))

    spec = matmul_spec(16, 32, 48, "float32", 0)
    first = ScheduleTuner(spec, generations=2, population=4,
                          fitness="compile",
                          rng=RandomGenerator("bk", seed=3)).tune()
    assert first["source"] == "ga"

    import tempfile
    bank_path = os.path.join(tempfile.mkdtemp(prefix="veles_bank"),
                             "bank.json")
    assert cache_for().export_bank(bank_path) == 1

    # host B: point the env at a fresh directory — cache_for() is
    # path-keyed, so this is a brand-new empty cache
    fresh_dir = tempfile.mkdtemp(prefix="veles_fresh")
    monkeypatch.setenv("VELES_SCHEDULE_CACHE",
                       os.path.join(fresh_dir, "schedule_cache"))
    assert len(cache_for()) == 0
    counts = cache_for().merge_bank(bank_path)
    assert counts["adopted"] == 1 and counts["total"] == 1

    seen = []
    real = matmul_mod._matmul_jit

    def spy(a_, b_, pl, blocks, od, interp):
        seen.append(blocks)
        return real(a_, b_, pl, blocks, od, interp)

    monkeypatch.setattr(matmul_mod, "_matmul_jit", spy)
    merged_out = numpy.asarray(matmul_mod.matmul(a, b))
    assert seen == [tuple(first["schedule"]["blocks"])]
    numpy.testing.assert_array_equal(merged_out, base_out)

    retune = ScheduleTuner(spec, generations=2, population=4,
                           fitness="compile",
                           rng=RandomGenerator("bk", seed=3)).tune()
    assert retune["source"] == "cache" and retune["evals"] == 0
    assert retune["schedule"] == first["schedule"]


def test_publish_schedule_bank_channel_and_watcher_pickup(tmp_path):
    """The publish channel end to end: publish_schedule_bank writes a
    manifest-verified schedule_bank.json beside the snapshots; the
    serve watcher's _maybe_merge_bank adopts it into the LOCAL cache,
    consumes the (mtime, size) stamp, and a mid-replace corruption is
    retried (stamp NOT consumed) instead of half-merged."""
    from veles_tpu.serve.freshness import SnapshotWatcher
    from veles_tpu.snapshotter import publish_schedule_bank
    from veles_tpu.tune.cache import (BANK_FILE_NAME, ScheduleCache,
                                      cache_for, device_kind,
                                      schedule_key)

    pub = str(tmp_path / "pub")
    # nothing to share is not an error
    empty = ScheduleCache(str(tmp_path / "empty" / "schedules.json"))
    assert publish_schedule_bank(pub, cache=empty) is None

    # the trainer-side cache with one real keyed winner
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    producer = ScheduleCache(str(tmp_path / "prod" / "schedules.json"))
    digest, payload = schedule_key(
        "matmul", [16, 128, 128], "float32", 0, device_kind(),
        {"kernel_version": MATMUL_KERNEL_VERSION})
    producer.put(digest, payload, {"blocks": [8, 128, 128]},
                 fitness=-1e-3, evals=4)
    res = publish_schedule_bank(pub, cache=producer)
    assert res["entries"] == 1
    assert os.path.basename(res["bank"]) == BANK_FILE_NAME

    watcher = SnapshotWatcher(pub, poll_s=30.0)
    counts = watcher._maybe_merge_bank()
    assert counts["adopted"] == 1 and counts["total"] == 1
    entry = cache_for().get(digest)  # the conftest-private local cache
    assert entry["schedule"]["blocks"] == [8, 128, 128]
    assert entry["host"]  # provenance survives the trip
    # stamp consumed: the unchanged bank is not re-merged every poll
    assert watcher._maybe_merge_bank() is None

    # publisher mid-replace: bank bytes no longer match the manifest —
    # skip WITHOUT consuming the stamp so the next poll retries
    bank_file = os.path.join(pub, BANK_FILE_NAME)
    stamp_before = watcher._bank_stamp
    with open(bank_file, "a") as fout:
        fout.write("\n")
    assert watcher._maybe_merge_bank() is None
    assert watcher._bank_stamp == stamp_before

    # the publisher finishes its replace: the retry adopts the update
    producer.put(digest, payload, {"blocks": [16, 128, 128]},
                 fitness=-5e-4, evals=4)
    publish_schedule_bank(pub, cache=producer)
    counts = watcher._maybe_merge_bank()
    assert counts["adopted"] == 1
    assert cache_for().get(digest)["schedule"]["blocks"] == \
        [16, 128, 128]
