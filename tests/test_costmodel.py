"""Learned cost model + measurement sidecar + fleet schedule bank
(veles_tpu/tune/costmodel.py, tune/cache.py; docs/kernels.md
"Autotuning").

Everything here is pure numpy/JSON — NO jax compile anywhere — so the
``costmodel`` marker doubles as the fast CI tier:
``python -m pytest -m costmodel``.

Every test sees a PRIVATE empty schedule cache + sidecar (the conftest
autouse fixture redirects ``VELES_SCHEDULE_CACHE`` to tmp; the
measurement log lives beside ``schedules.json``, so the same redirect
isolates it)."""

import json
import os

import numpy
import pytest

pytestmark = pytest.mark.costmodel


def _matmul_rows(shapes, schedules, slope_fn, mode="measure"):
    """Synthetic measurement-log rows keyed EXACTLY like the tuner
    writes them (schedule_key payload + digest), so the current-version
    staleness filters accept them."""
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    from veles_tpu.tune.cache import device_kind, schedule_key
    rows = []
    for shape in shapes:
        digest, payload = schedule_key(
            "matmul", shape, "float32", 0, device_kind(),
            {"kernel_version": MATMUL_KERNEL_VERSION})
        for schedule in schedules:
            rows.append({"digest": digest, "payload": payload,
                         "schedule": dict(schedule),
                         "slope": slope_fn(shape, schedule),
                         "mode": mode})
    return rows


#: matmul schedules spanning the gene space (MXU-legal: bm%8, bn/bk%128)
_SCHEDULES = [{"blocks": [bm, bn, bk]}
              for bm in (8, 64, 256)
              for bn in (128, 512)
              for bk in (128, 256)]

_SHAPES = [(512, 512, 512), (1024, 1024, 1024), (512, 1024, 2048),
           (2048, 512, 1024)]


def _grid_slope(shape, schedule):
    """A learnable synthetic cost: grid steps times a per-step cost
    that rewards big bm tiles (monotone in the features)."""
    m, k, n = shape
    bm, bn, bk = schedule["blocks"]
    grid = (-(-m // bm)) * (-(-n // bn)) * (-(-k // bk))
    return grid * (1.0 + 64.0 / bm) * 1e-6


# -- featurize / spearman -----------------------------------------------------


def test_featurize_fixed_length_and_deterministic():
    from veles_tpu.tune.costmodel import featurize
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(512, 512, 512, "float32", 0)
    a = featurize(spec, {"blocks": [64, 128, 128]})
    b = featurize(spec, {"blocks": [64, 128, 128]})
    c = featurize(spec, {"blocks": [256, 512, 128]})
    # 3 shape dims + 3 tile dims + 5 derived features
    assert a.shape == (11,) and c.shape == (11,)
    numpy.testing.assert_array_equal(a, b)
    assert not numpy.array_equal(a, c)


def test_featurize_attention_family():
    """The attention family featurizes through the same path (its
    footprint/grid formulas, not matmul's)."""
    from veles_tpu.tune.costmodel import featurize
    from veles_tpu.tune.spec import attention_spec
    spec = attention_spec(4, 256, 64, "float32", 0)
    a = featurize(spec, {"blocks": [128, 128]})
    b = featurize(spec, {"blocks": [256, 256]})
    # 4 shape dims + 2 tile dims + 5 derived
    assert a.shape == (11,) and not numpy.array_equal(a, b)


def test_spearman_sanity():
    from veles_tpu.tune.costmodel import spearman
    assert spearman([1, 2, 3, 4], [10, 20, 30, 40]) == pytest.approx(1.0)
    assert spearman([1, 2, 3, 4], [40, 30, 20, 10]) == pytest.approx(-1.0)
    # monotone transform changes nothing (rank correlation)
    assert spearman([1, 2, 3, 4], [1, 100, 10000, 1e6]) \
        == pytest.approx(1.0)
    # no rank variance on either side reads 0, not NaN
    assert spearman([1, 1, 1], [1, 2, 3]) == 0.0


# -- fit / predict ------------------------------------------------------------


def test_fit_is_deterministic():
    """Same triples in -> same stumps, same base, same ranking out —
    the fleet-wide reproducibility contract (no RNG anywhere)."""
    from veles_tpu.tune.costmodel import CostModel
    from veles_tpu.tune.spec import matmul_spec
    rows = _matmul_rows(_SHAPES, _SCHEDULES, _grid_slope)
    m1 = CostModel("matmul").fit(rows)
    m2 = CostModel("matmul").fit(list(rows))
    assert m1.base == m2.base
    assert m1.stumps == m2.stumps
    spec = matmul_spec(768, 768, 768, "float32", 0)
    assert m1.predict_rank(spec, _SCHEDULES) \
        == m2.predict_rank(spec, _SCHEDULES)


def test_model_recovers_synthetic_ordering():
    """Trained on a learnable synthetic cost, the held-out-shape
    ranking must correlate strongly with the true ordering."""
    from veles_tpu.tune.costmodel import CostModel, spearman
    from veles_tpu.tune.spec import matmul_spec
    rows = _matmul_rows(_SHAPES, _SCHEDULES, _grid_slope)
    model = CostModel("matmul").fit(rows)
    spec = matmul_spec(1536, 1536, 1536, "float32", 0)
    pred = model.predict_seconds(spec, _SCHEDULES)
    actual = [_grid_slope((1536, 1536, 1536), s) for s in _SCHEDULES]
    assert spearman(pred, actual) > 0.8
    val = model.validate()
    assert val["groups"] >= 3
    assert val["error"] is not None and val["error"] < 0.5


def test_predict_rank_ties_break_on_lower_index():
    """A constant model (no variance in y) must produce the identity
    ranking, not an arbitrary one."""
    from veles_tpu.tune.costmodel import CostModel
    from veles_tpu.tune.spec import matmul_spec
    rows = _matmul_rows(_SHAPES[:2], _SCHEDULES[:4],
                        lambda shape, s: 1e-3)
    model = CostModel("matmul").fit(rows)
    spec = matmul_spec(512, 512, 512, "float32", 0)
    assert model.predict_rank(spec, _SCHEDULES[:4]) == [0, 1, 2, 3]


def test_fit_empty_rows_raises():
    from veles_tpu.tune.costmodel import CostModel
    with pytest.raises(ValueError):
        CostModel("matmul").fit([])


# -- trust gates --------------------------------------------------------------


def test_train_for_thin_data_fallback():
    """Below min_triples the model is not even trained."""
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.costmodel import train_for
    log = tune_cache.measurement_log()
    for row in _matmul_rows(_SHAPES[:1], _SCHEDULES[:3], _grid_slope):
        log.append(row["digest"], row["payload"], row["schedule"],
                   row["slope"], mode=row["mode"])
    model, info = train_for("matmul", mode="measure")
    assert model is None
    assert info["fallback"] == "thin-data"
    assert info["triples"] == 3 and not info["trusted"]


def test_train_for_untrusted_on_noise():
    """Feature-independent slopes: held-out Spearman ~0, validation
    error above the gate -> (None, 'untrusted')."""
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.costmodel import train_for
    rng = numpy.random.RandomState(7)
    noise = {}

    def random_slope(shape, schedule):
        key = (tuple(shape), json.dumps(schedule, sort_keys=True))
        if key not in noise:
            noise[key] = float(rng.uniform(1e-6, 1e-3))
        return noise[key]

    log = tune_cache.measurement_log()
    for row in _matmul_rows(_SHAPES, _SCHEDULES, random_slope):
        log.append(row["digest"], row["payload"], row["schedule"],
                   row["slope"], mode=row["mode"])
    model, info = train_for("matmul", mode="measure")
    assert model is None
    assert info["fallback"] == "untrusted"
    assert info["error"] is not None and info["error"] > 0.5


def test_train_for_unvalidatable_reads_untrusted():
    """Plenty of rows but no spec group with 3+ distinct schedules:
    validation has nothing to score, and an UNVALIDATABLE model must
    read as untrusted, not as perfect."""
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.costmodel import train_for
    shapes = [(8 * i, 128 * i, 128 * i) for i in range(1, 20)]
    log = tune_cache.measurement_log()
    for row in _matmul_rows(shapes, _SCHEDULES[:2], _grid_slope):
        log.append(row["digest"], row["payload"], row["schedule"],
                   row["slope"], mode=row["mode"])
    model, info = train_for("matmul", mode="measure")
    assert model is None
    assert info["fallback"] == "untrusted"
    assert info["error"] is None and info["groups"] == 0


def test_train_for_trusts_learnable_data():
    from veles_tpu.tune import cache as tune_cache
    from veles_tpu.tune.costmodel import train_for
    log = tune_cache.measurement_log()
    for row in _matmul_rows(_SHAPES, _SCHEDULES, _grid_slope):
        log.append(row["digest"], row["payload"], row["schedule"],
                   row["slope"], mode=row["mode"])
    model, info = train_for("matmul", mode="measure")
    assert model is not None
    assert info["trusted"] and info["fallback"] is None
    assert info["error"] < 0.5 and info["groups"] >= 3


# -- the measurement sidecar --------------------------------------------------


def test_measurement_log_roundtrip_and_filters(tmp_path):
    from veles_tpu.tune.cache import MeasurementLog
    log = MeasurementLog(str(tmp_path / "m.jsonl"))
    rows = _matmul_rows(_SHAPES[:2], _SCHEDULES[:2], _grid_slope)
    for row in rows:
        log.append(row["digest"], row["payload"], row["schedule"],
                   row["slope"], mode=row["mode"])
    log.append(rows[0]["digest"], rows[0]["payload"],
               rows[0]["schedule"], 2e-3, mode="compile")
    got = log.rows(op="matmul", mode="measure")
    assert len(got) == 4
    assert all(r["mode"] == "measure" for r in got)
    assert log.rows(mode="compile")[0]["slope"] == 2e-3
    counts = log.count_by_family()
    assert counts == {"matmul": 5}


def test_measurement_log_strands_stale_rows(tmp_path):
    """The staleness contract: rows from another jax version, another
    device kind, an old kernel version, or with a digest that no
    longer recomputes are filtered from training data — exactly like
    stale cache entries MISS."""
    from veles_tpu.tune.cache import MeasurementLog
    log = MeasurementLog(str(tmp_path / "m.jsonl"))
    good = _matmul_rows(_SHAPES[:1], _SCHEDULES[:1], _grid_slope)[0]
    log.append(good["digest"], good["payload"], good["schedule"],
               good["slope"])
    # (a) foreign jax version; (b) foreign device kind; (c) kernel
    # version bump — each with its digest left UNFIXED, and (d) a
    # tampered payload under the original digest
    for mutate in ({"jax": "0.0.0"}, {"device_kind": "TPU v9"},
                   {"kernel_version": -1}, {"shape": [8, 128, 128]}):
        payload = dict(good["payload"])
        payload.update(mutate)
        log.append(good["digest"], payload, good["schedule"], 1e-3)
    assert len(log.rows()) == 1
    assert len(log.rows(current_only=False)) == 5


def test_measurement_log_recomputed_digest_gate(tmp_path):
    """A consistent-looking row whose digest does not recompute from
    its payload (hand-edited/corrupted sidecar) is stranded."""
    from veles_tpu.tune.cache import MeasurementLog
    log = MeasurementLog(str(tmp_path / "m.jsonl"))
    good = _matmul_rows(_SHAPES[:1], _SCHEDULES[:1], _grid_slope)[0]
    log.append("deadbeef" * 8, good["payload"], good["schedule"], 1e-3)
    assert log.rows() == []
    assert len(log.rows(current_only=False)) == 1


def test_measurement_log_skips_garbage_lines(tmp_path, caplog):
    from veles_tpu.tune.cache import MeasurementLog
    path = tmp_path / "m.jsonl"
    good = _matmul_rows(_SHAPES[:1], _SCHEDULES[:1], _grid_slope)[0]
    log = MeasurementLog(str(path))
    log.append(good["digest"], good["payload"], good["schedule"], 1e-3)
    with open(str(path), "a") as fout:
        fout.write("not json\n")
        fout.write(json.dumps({"digest": "x"}) + "\n")
    with caplog.at_level("WARNING"):
        assert len(log.rows()) == 1
    assert any("unparseable" in r.message for r in caplog.records)


def test_measurement_log_compaction_bound(tmp_path, monkeypatch):
    """An append past the size cap compacts to the newest KEEP rows —
    the sidecar is bounded, not append-forever."""
    from veles_tpu.tune import cache as tune_cache
    monkeypatch.setattr(tune_cache, "_MEASUREMENTS_MAX_BYTES", 2048)
    monkeypatch.setattr(tune_cache, "_MEASUREMENTS_KEEP", 5)
    log = tune_cache.MeasurementLog(str(tmp_path / "m.jsonl"))
    good = _matmul_rows(_SHAPES[:1], _SCHEDULES[:1], _grid_slope)[0]
    for i in range(40):
        log.append(good["digest"], good["payload"], good["schedule"],
                   1e-6 * (i + 1))
    rows = log.rows()
    # steady state oscillates between KEEP and the next compaction
    # trigger — bounded well below the 40 appended rows either way
    assert len(rows) <= 8
    # newest rows survive (the tail of the append order)
    assert rows[-1]["slope"] == pytest.approx(1e-6 * 40)
    assert os.path.getsize(str(tmp_path / "m.jsonl")) <= 4096


def test_record_measurement_never_raises(monkeypatch, caplog):
    from veles_tpu.tune import cache as tune_cache

    def boom(*args, **kwargs):
        raise OSError("read-only cache dir")

    monkeypatch.setattr(tune_cache.MeasurementLog, "append", boom)
    with caplog.at_level("WARNING"):
        tune_cache.record_measurement("d", {"op": "matmul"},
                                      {"blocks": [8, 128, 128]}, 1e-3)
    assert any("triple dropped" in r.message for r in caplog.records)


# -- the fleet schedule bank --------------------------------------------------


def _planted_cache(tmp_path, name, fitness=-1e-3):
    """A cache with one REAL keyed matmul entry (digest recomputes)."""
    from veles_tpu.ops.matmul import MATMUL_KERNEL_VERSION
    from veles_tpu.tune.cache import (ScheduleCache, device_kind,
                                      schedule_key)
    digest, payload = schedule_key(
        "matmul", (512, 512, 512), "float32", 0, device_kind(),
        {"kernel_version": MATMUL_KERNEL_VERSION})
    cache = ScheduleCache(str(tmp_path / name))
    cache.put(digest, payload, {"blocks": [64, 512, 512]},
              fitness=fitness, evals=4, source="ga")
    return cache, digest


def test_bank_export_merge_roundtrip(tmp_path):
    from veles_tpu.tune.cache import ScheduleCache, load_bank
    ours, digest = _planted_cache(tmp_path, "a.json")
    bank_path = str(tmp_path / "bank.json")
    assert ours.export_bank(bank_path) == 1
    bank = load_bank(bank_path)
    assert bank["kind"] == "schedule_bank"
    assert bank["entries"][digest]["host"]  # provenance stamped
    theirs = ScheduleCache(str(tmp_path / "b.json"))
    counts = theirs.merge_bank(bank_path)
    assert counts == {"adopted": 1, "kept": 0, "stale": 0,
                      "invalid": 0, "total": 1}
    got = theirs.get(digest)
    assert got["schedule"] == {"blocks": [64, 512, 512]}
    assert got["fitness"] == -1e-3
    # idempotent: a re-merge of the same bank adopts nothing
    assert theirs.merge_bank(bank_path)["adopted"] == 0


def test_bank_merge_conflict_resolution(tmp_path):
    """Disk wins except on strictly-better measured fitness; an
    unmeasured challenger never displaces; an unmeasured incumbent
    yields to any measured challenger."""
    from veles_tpu.tune.cache import ScheduleCache
    ours, digest = _planted_cache(tmp_path, "a.json", fitness=-2e-3)

    def bank_with(fitness, blocks):
        donor, _ = _planted_cache(tmp_path, "donor.json",
                                  fitness=fitness)
        donor.put(digest, {k: v for k, v in
                           donor.entries()[digest].items()
                           if k not in ("schedule", "source", "fitness",
                                        "evals", "host")},
                  {"blocks": blocks}, fitness=fitness, source="ga")
        path = str(tmp_path / "bank.json")
        donor.export_bank(path)
        os.remove(str(tmp_path / "donor.json"))
        return path

    # worse fitness: local entry kept
    counts = ours.merge_bank(bank_with(-5e-3, [8, 128, 128]))
    assert counts["kept"] == 1 and counts["adopted"] == 0
    assert ours.get(digest)["schedule"] == {"blocks": [64, 512, 512]}
    # strictly better fitness: adopted
    counts = ours.merge_bank(bank_with(-1e-3, [256, 512, 512]))
    assert counts["adopted"] == 1
    assert ours.get(digest)["schedule"] == {"blocks": [256, 512, 512]}
    # unmeasured challenger (fitness None) never displaces
    counts = ours.merge_bank(bank_with(None, [8, 128, 128]))
    assert counts["kept"] == 1
    assert ours.get(digest)["schedule"] == {"blocks": [256, 512, 512]}


def test_bank_merge_rejects_stale_digest_and_invalid(tmp_path):
    """A bank entry whose digest does not recompute from its key
    coordinates (another jax/kernel version, a tampered entry) is
    rejected as stale; a structurally-invalid schedule as invalid."""
    from veles_tpu.tune.cache import (SCHEDULE_CACHE_SCHEMA,
                                      ScheduleCache)
    ours, digest = _planted_cache(tmp_path, "a.json")
    entry = dict(ours.entries()[digest])
    bank = {"schema": SCHEDULE_CACHE_SCHEMA, "kind": "schedule_bank",
            "host": "donor", "jax": "x",
            "entries": {
                # digest that does not recompute
                "deadbeef" * 8: dict(entry),
                # good digest, MXU-illegal schedule.  NOT the blocks
                # test_tune's malformed-entry test plants: the consult
                # warning dedupes on (op, schedule) PROCESS-wide, so
                # sharing its value here would swallow that test's
                # warning when both run in one session
                digest: dict(entry, schedule={"blocks": [9, 130, 2]}),
            }}
    fresh = ScheduleCache(str(tmp_path / "b.json"))
    counts = fresh.merge_bank(bank)
    assert counts["stale"] == 1 and counts["invalid"] == 1
    assert counts["adopted"] == 0 and len(fresh) == 0


def test_load_bank_rejects_non_banks(tmp_path):
    from veles_tpu.tune.cache import load_bank
    path = str(tmp_path / "junk.json")
    with open(path, "w") as fout:
        json.dump({"schema": 1, "entries": {}}, fout)
    with pytest.raises(ValueError):
        load_bank(path)


def test_bank_counters_tick(tmp_path):
    from veles_tpu.observe.metrics import registry
    from veles_tpu.tune.cache import ScheduleCache, tune_counters
    ours, _ = _planted_cache(tmp_path, "a.json")
    bank_path = str(tmp_path / "bank.json")
    ours.export_bank(bank_path)
    before = (tune_counters().get("bank_merged", 0),
              tune_counters().get("bank_entries", 0))
    fresh = ScheduleCache(str(tmp_path / "b.json"))
    fresh.merge_bank(bank_path)
    after = tune_counters()
    assert after.get("bank_merged", 0) == before[0] + 1
    assert after.get("bank_entries", 0) == before[1] + 1
    assert registry.peek("tune.bank_merged") is not None
