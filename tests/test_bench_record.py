"""The driver's byte-limited tail must always capture one complete
machine-parseable bench record line (VERDICT r04: BENCH_r03/r04 both
ended ``parsed: null`` because the only JSON line had grown past the
tail window).  bench.py now prints a compact sibling line after every
full record; these tests pin its size and its survival through a
literal ``tail -c 2000``."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench


def _rich_extras():
    """Extras shaped like a full real run (every section present)."""
    return {
        "sections_s": {"matmul_pass1": 9.1, "mnist": 31.0,
                       "alexnet_b128": 64.2, "alexnet_b256_bfloat16":
                       88.0, "native_inference": 12.4,
                       "matmul_pass2": 11.0, "matmul_f32_level1": 70.2,
                       "alexnet_b128_bfloat16": 61.0,
                       "alexnet_b256_float32": 120.9},
        "shed": [],
        "matmul": {
            "float32": {"seconds": 0.000768, "tflops": 70.3,
                        "passes": [0.001129, 0.000768]},
            "bfloat16": {"seconds": 0.0005, "tflops": 108.1,
                         "passes": [0.00052, 0.0005]},
            "float32_level1": {"seconds": 0.0024, "tflops": 22.8,
                               "blocks": [512, 512, 512]},
            "device_kind": "TPU v5e",
        },
        "mnist_784_100_10": {
            "step_seconds": 0.00025, "samples_per_sec": 400000.0,
            "scan_step_seconds": 1.57e-05,
            "scan_samples_per_sec": 6369426.8,
            "epoch_seconds_projected": 0.15, "batch": 100,
        },
        "alexnet": {
            "batch": 128,
            "float32": {"images_per_sec": 9300.0},
            "bfloat16": {"images_per_sec": 12000.0, "mfu_pct": 37.0},
            "batch_256": {"bfloat16": {"images_per_sec": 14036.0,
                                       "mfu_pct": 43.2},
                          "float32": {"images_per_sec": 9500.0}},
        },
        "native_inference": {"batch_1_rows_per_sec": 61000.0,
                             "batch_256_rows_per_sec": 1250000.0},
        "wall_s": 286.4,
    }


def test_compact_record_is_small_and_complete():
    rec = bench._compact_record(0.000768, False, _rich_extras())
    line = json.dumps(rec)
    assert len(line) < 500, "compact record must fit any tail window"
    # the required headline quadruple
    assert rec["metric"] == "matmul_3001x3001_f32_avg_time"
    assert rec["value"] == 0.000768
    assert rec["unit"] == "s"
    assert rec["vs_baseline"] == round(0.1642 / 0.000768, 2)
    # every BASELINE.md-row scalar rides along
    for key in ("mnist_step_s", "mnist_scan_step_s",
                "alexnet_b256_bf16_img_s", "alexnet_b256_bf16_mfu_pct",
                "native_batch_1_rows_per_sec",
                "native_batch_256_rows_per_sec",
                "bf16_tflops", "f32_level1_tflops", "wall_s"):
        assert key in rec, key


def test_compact_record_survives_partial_run():
    # after pass 1 only: no mnist/alexnet/native keys yet
    rec = bench._compact_record(
        0.0012, False,
        {"sections_s": {}, "shed": [],
         "matmul": {"float32": {"seconds": 0.0012}}})
    assert rec["vs_baseline"] == round(0.1642 / 0.0012, 2)
    assert "mnist_step_s" not in rec
    # small mode reports no vs_baseline (different problem size)
    small = bench._compact_record(0.0003, True, {})
    assert small["vs_baseline"] is None
    assert small["metric"] == "matmul_512x512_f32_avg_time"


def test_last_line_parses_through_tail_c_2000():
    """Reproduce the driver's capture: full record lines (which by the
    final section exceed 4 KB) followed by the compact line, piped
    through a literal ``tail -c 2000`` — the last complete line must
    json-parse and carry the headline quadruple."""
    extras = _rich_extras()
    # pad the way the real record grows: spreads, pass lists, notes
    extras["alexnet"]["precision_note"] = "x" * 400
    for row in ("float32", "bfloat16"):
        extras["matmul"][row]["passes"] = [0.001] * 40
    full = {"metric": "matmul_3001x3001_f32_avg_time",
            "value": 0.000768, "unit": "s", "vs_baseline": 213.8,
            "extras": extras}
    compact = bench._compact_record(0.000768, False, extras)
    stream = ""
    for _ in range(6):  # emit() after every section
        stream += json.dumps(full) + "\n"
        stream += json.dumps(compact) + "\n"
    assert len(json.dumps(full)) > 2000, "full line must model the overflow"
    tail = subprocess.run(["tail", "-c", "2000"],
                          input=stream.encode(), stdout=subprocess.PIPE,
                          check=True).stdout.decode()
    last = [l for l in tail.splitlines() if l.strip()][-1]
    parsed = json.loads(last)
    assert parsed["metric"] == "matmul_3001x3001_f32_avg_time"
    assert parsed["value"] == 0.000768
    assert parsed["unit"] == "s"
    assert parsed["vs_baseline"] == 213.8
    assert parsed["alexnet_b256_bf16_img_s"] == 14036.0
