"""Pallas kernel tests (reference analogs: OCLBLAS, matrix kernels,
random bitstream, fullbatch gather).  Run in interpreter mode on CPU;
the same code compiles via Mosaic on TPU."""

import jax.numpy as jnp
import numpy
import pytest

from veles_tpu.ops import (gather_minibatch, gemm, join,
                           matmul, mean_disp_normalize,
                           reduce_cols, reduce_rows)
from veles_tpu.ops import random as vrandom


RS = numpy.random.RandomState(42)


class TestMatmul:
    @pytest.mark.parametrize("shape", [
        (64, 32, 48), (128, 128, 128), (100, 77, 33), (8, 300, 120)])
    def test_matches_numpy(self, shape):
        m, k, n = shape
        a = RS.rand(m, k).astype(numpy.float32)
        b = RS.rand(k, n).astype(numpy.float32)
        out = numpy.asarray(matmul(jnp.asarray(a), jnp.asarray(b),
                                   blocks=(32, 128, 128)))
        numpy.testing.assert_allclose(out, a @ b, rtol=1e-5)

    @pytest.mark.parametrize("level", [0, 1, 2])
    def test_precision_levels(self, level):
        a = RS.rand(32, 256).astype(numpy.float32)
        b = RS.rand(256, 32).astype(numpy.float32)
        out = numpy.asarray(matmul(
            jnp.asarray(a), jnp.asarray(b), precision_level=level,
            blocks=(32, 128, 128)))
        oracle = (a.astype(numpy.float64) @ b.astype(numpy.float64))
        numpy.testing.assert_allclose(out, oracle, rtol=1e-5)

    def test_precision_level_accuracy_ladder(self):
        """Adversarial accumulation (large alternating terms): higher
        precision levels must not be worse than level 0 against the f64
        oracle — the property the reference's precise kernels buy
        (ocl/matrix_multiplication_precise.cl:36-41)."""
        k = 4096
        a = numpy.where(numpy.arange(k) % 2 == 0, 1e6, 1.0).astype(
            numpy.float32).reshape(1, k)
        a = numpy.repeat(a, 8, axis=0)
        b = numpy.where(numpy.arange(k) % 2 == 0, 1.0, -1e-3).astype(
            numpy.float32).reshape(k, 1)
        b = numpy.repeat(b, 8, axis=1)
        oracle = a.astype(numpy.float64) @ b.astype(numpy.float64)
        errs = []
        for level in (0, 1, 2):
            out = numpy.asarray(matmul(
                jnp.asarray(a), jnp.asarray(b), precision_level=level,
                blocks=(8, 128, 256)))
            errs.append(numpy.abs(out - oracle).max())
        assert errs[1] <= errs[0] * 1.001
        assert errs[2] <= errs[1] * 1.001

    def test_bfloat16_inputs(self):
        a = RS.rand(32, 64).astype(numpy.float32)
        b = RS.rand(64, 32).astype(numpy.float32)
        out = numpy.asarray(matmul(
            jnp.asarray(a, jnp.bfloat16), jnp.asarray(b, jnp.bfloat16),
            blocks=(32, 128, 128), out_dtype=jnp.float32))
        numpy.testing.assert_allclose(out, a @ b, rtol=2e-2)

    def test_shape_mismatch(self):
        with pytest.raises(ValueError):
            matmul(jnp.zeros((4, 5)), jnp.zeros((6, 4)))


class TestGemm:
    def test_alpha_beta(self):
        a = RS.rand(16, 24).astype(numpy.float32)
        b = RS.rand(24, 8).astype(numpy.float32)
        c = RS.rand(16, 8).astype(numpy.float32)
        out = numpy.asarray(gemm(jnp.asarray(a), jnp.asarray(b),
                                 jnp.asarray(c), alpha=2.0, beta=0.5))
        numpy.testing.assert_allclose(out, 2.0 * (a @ b) + 0.5 * c,
                                      rtol=1e-5)

    def test_transposes(self):
        a = RS.rand(24, 16).astype(numpy.float32)
        b = RS.rand(8, 24).astype(numpy.float32)
        out = numpy.asarray(gemm(jnp.asarray(a), jnp.asarray(b),
                                 trans_a=True, trans_b=True))
        numpy.testing.assert_allclose(out, a.T @ b.T, rtol=1e-5)


class TestReduce:
    def test_cols(self):
        x = RS.rand(300, 70).astype(numpy.float32)
        out = numpy.asarray(reduce_cols(jnp.asarray(x), block=64))
        numpy.testing.assert_allclose(out, x.sum(0, keepdims=True),
                                      rtol=1e-4)

    def test_rows(self):
        x = RS.rand(100, 500).astype(numpy.float32)
        out = numpy.asarray(reduce_rows(jnp.asarray(x), block=128))
        numpy.testing.assert_allclose(out, x.sum(1, keepdims=True),
                                      rtol=1e-4)


class TestGather:
    def test_gather_with_cast(self):
        data = (RS.rand(50, 12) * 255).astype(numpy.uint8)
        idx = RS.permutation(50)[:16].astype(numpy.int32)
        out = numpy.asarray(gather_minibatch(
            jnp.asarray(data), jnp.asarray(idx), out_dtype=jnp.float32))
        numpy.testing.assert_array_equal(out, data[idx].astype(
            numpy.float32))

    def test_gather_multidim(self):
        data = RS.rand(20, 4, 6).astype(numpy.float32)
        idx = numpy.array([3, 1, 19], numpy.int32)
        out = numpy.asarray(gather_minibatch(jnp.asarray(data),
                                             jnp.asarray(idx)))
        numpy.testing.assert_array_equal(out, data[idx])


class TestNormalize:
    def test_mean_disp(self):
        x = (RS.rand(30, 50) * 255).astype(numpy.uint8)
        mean = x.mean(0).astype(numpy.float32)
        disp = numpy.ptp(x.astype(numpy.float32), axis=0) + 1.0
        rdisp = (1.0 / disp).astype(numpy.float32)
        out = numpy.asarray(mean_disp_normalize(
            jnp.asarray(x), jnp.asarray(mean), jnp.asarray(rdisp),
            block=32))
        oracle = (x.astype(numpy.float32) - mean) * rdisp
        numpy.testing.assert_allclose(out, oracle, rtol=1e-5, atol=1e-6)


class TestJoin:
    def test_two(self):
        a = RS.rand(10, 3).astype(numpy.float32)
        b = RS.rand(10, 5).astype(numpy.float32)
        out = numpy.asarray(join(jnp.asarray(a), jnp.asarray(b)))
        numpy.testing.assert_array_equal(
            out, numpy.concatenate([a, b], axis=1))

    def test_three_multidim(self):
        a = RS.rand(4, 2, 3).astype(numpy.float32)
        b = RS.rand(4, 7).astype(numpy.float32)
        c = RS.rand(4, 1).astype(numpy.float32)
        out = numpy.asarray(join(jnp.asarray(a), jnp.asarray(b),
                                 jnp.asarray(c)))
        oracle = numpy.concatenate(
            [a.reshape(4, -1), b, c], axis=1)
        numpy.testing.assert_array_equal(out, oracle)


class TestXorshift:
    def test_128plus_bit_exact(self):
        """JAX u32-pair emulation matches the u64 numpy oracle."""
        streams = 4
        hi = RS.randint(0, 2 ** 31, (2, streams)).astype(numpy.uint32)
        lo = RS.randint(0, 2 ** 31, (2, streams)).astype(numpy.uint32)
        state = numpy.stack([hi, lo], axis=1)  # (2, 2, S)
        jstate, jbits = vrandom.xorshift128plus(jnp.asarray(state), 16)
        _, oracle = vrandom.numpy_xorshift128plus(state, 16)
        jax_u64 = (numpy.asarray(jbits[:, 0]).astype(numpy.uint64) <<
                   numpy.uint64(32)) | numpy.asarray(
                       jbits[:, 1]).astype(numpy.uint64)
        numpy.testing.assert_array_equal(jax_u64, oracle)

    def test_1024star_bit_exact(self):
        streams = 3
        state64 = RS.randint(1, 2 ** 62, (16, streams)).astype(
            numpy.uint64)
        hi = (state64 >> numpy.uint64(32)).astype(numpy.uint32)
        lo = (state64 & numpy.uint64(0xffffffff)).astype(numpy.uint32)
        _, _, _, jbits = vrandom.xorshift1024star(
            jnp.asarray(hi), jnp.asarray(lo), jnp.int32(0), 12)
        _, _, oracle = vrandom.numpy_xorshift1024star(state64, 0, 12)
        jax_u64 = (numpy.asarray(jbits[:, 0]).astype(numpy.uint64) <<
                   numpy.uint64(32)) | numpy.asarray(
                       jbits[:, 1]).astype(numpy.uint64)
        numpy.testing.assert_array_equal(jax_u64, oracle)

    def test_uniform_from_bits_range(self):
        bits = jnp.asarray(RS.randint(0, 2 ** 31, (1000,)),
                           jnp.uint32)
        u = numpy.asarray(vrandom.uniform_from_bits(bits, -2.0, 3.0))
        assert (u >= -2.0).all() and (u < 3.0).all()

    def test_hardware_uniform_cpu_fallback(self):
        u = numpy.asarray(vrandom.hardware_uniform(7, (64, 128)))
        assert u.shape == (64, 128)
        assert (u >= 0).all() and (u < 1).all()
        u2 = numpy.asarray(vrandom.hardware_uniform(7, (64, 128)))
        numpy.testing.assert_array_equal(u, u2)  # deterministic per seed


def _matmul_256_digest():
    """The schedule-cache key autotune_matmul uses for size=256 on the
    test chip kind — built through the SAME spec builder the consult
    path uses, so the test can't drift from the implementation."""
    from veles_tpu.tune.cache import schedule_key
    from veles_tpu.tune.spec import matmul_spec
    spec = matmul_spec(256, 256, 256, "float32", 0)
    return schedule_key(spec["op"], spec["shape"], spec["dtype"],
                        spec["precision_level"], "test-chip-kind",
                        spec["extra"])


def test_autotune_matmul_round_robin_picks_and_persists():
    """The autotuner measures candidates round-robin (congestion drift
    hits every tile equally), picks a majority-positive-median winner,
    and persists it in the digest-keyed ScheduleCache — or falls back
    to the defaults WITHOUT persisting when timing jitter swamps every
    tile.  (The conftest autouse fixture gives this test a private
    empty cache.)"""
    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops.matmul import _DEFAULT_BLOCKS, autotune_matmul
    from veles_tpu.tune.cache import cache_for

    info = DeviceInfo("test-chip-kind")
    blocks = autotune_matmul(info, size=256)
    assert len(blocks) == 3 and all(b > 0 for b in blocks)
    digest, _ = _matmul_256_digest()
    entry = cache_for().get(digest)
    if entry is not None:  # a tile was ranked
        assert tuple(entry["schedule"]["blocks"]) == tuple(blocks)
        assert entry["source"] == "sweep"
    else:  # all-jitter fallback: defaults, deliberately unpersisted
        assert blocks == _DEFAULT_BLOCKS


def test_autotune_matmul_cache_hit_skips_measurement():
    """A persisted entry is served verbatim — no timing runs."""
    from veles_tpu.backends import DeviceInfo
    from veles_tpu.ops.matmul import autotune_matmul
    from veles_tpu.tune.cache import cache_for

    info = DeviceInfo("test-chip-kind")
    digest, payload = _matmul_256_digest()
    sentinel = [128, 128, 128]  # not a real candidate: proves the
    cache_for().put(digest, payload,  # value came from the cache
                    {"blocks": sentinel}, source="test")
    assert autotune_matmul(info, size=256) == tuple(sentinel)


def test_estimate_computing_power_positive():
    from veles_tpu.ops.benchmark import estimate_computing_power
    power = estimate_computing_power(size=128, repeats=2)
    assert power > 0
