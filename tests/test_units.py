"""Graph engine tests (reference analogs: test_units, test_workflow)."""

import pickle

import pytest

from veles_tpu.dummy import DummyLauncher, DummyWorkflow
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


class CountingUnit(Unit):
    def __init__(self, workflow, **kwargs):
        super(CountingUnit, self).__init__(workflow, **kwargs)
        self.count = 0

    def run(self):
        self.count += 1


class TestGraph:
    def test_linear_chain(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        a.link_from(wf.start_point)
        b.link_from(a)
        wf.end_point.link_from(b)
        wf.initialize()
        wf.run()
        assert a.count == 1 and b.count == 1

    def test_and_gate(self):
        """A unit with two predecessors runs only after both fire."""
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        joined = CountingUnit(wf, name="join")
        a.link_from(wf.start_point)
        b.link_from(wf.start_point)
        joined.link_from(a, b)
        wf.end_point.link_from(joined)
        wf.initialize()
        wf.run()
        assert joined.count == 1

    def test_gate_block(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        blocked = CountingUnit(wf, name="blocked")
        a.link_from(wf.start_point)
        blocked.link_from(a)
        blocked.gate_block = Bool(True)
        wf.end_point.link_from(a)
        wf.initialize()
        wf.run()
        assert blocked.count == 0

    def test_gate_skip(self):
        """Skipped unit doesn't run but propagates control."""
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        skipped = CountingUnit(wf, name="skipped")
        after = CountingUnit(wf, name="after")
        a.link_from(wf.start_point)
        skipped.link_from(a)
        after.link_from(skipped)
        skipped.gate_skip = Bool(True)
        wf.end_point.link_from(after)
        wf.initialize()
        wf.run()
        assert skipped.count == 0 and after.count == 1

    def test_repeater_loop(self):
        """Iterate N times through a Repeater, then exit via gates."""
        wf = DummyWorkflow()
        repeater = Repeater(wf)
        body = CountingUnit(wf, name="body")
        done = Bool(False)

        class Decision(CountingUnit):
            def run(self):
                super(Decision, self).run()
                if self.count >= 5:
                    self.complete <<= True

        decision = Decision(wf, name="decision")
        decision.complete = done
        repeater.link_from(wf.start_point)
        body.link_from(repeater)
        decision.link_from(body)
        repeater.link_from(decision)
        repeater.gate_block = done
        wf.end_point.link_from(decision)
        wf.end_point.gate_block = ~done
        wf.initialize()
        wf.run()
        assert body.count == 5
        assert decision.count == 5

    def test_link_attrs(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        a.output = 42
        b.link_attrs(a, ("input", "output"))
        assert b.input == 42
        a.output = 43
        assert b.input == 43

    def test_demand_fails_init(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        a.demand("missing_thing")
        a.link_from(wf.start_point)
        wf.end_point.link_from(a)
        with pytest.raises(RuntimeError):
            wf.initialize()

    def test_demand_deferred_init(self):
        """A unit whose demand is satisfied by an earlier unit's
        initialize gets re-queued and succeeds (partial-init requeue)."""
        wf = DummyWorkflow()

        class Producer(CountingUnit):
            def initialize(self, **kwargs):
                self.produced = 7
                return super(Producer, self).initialize(**kwargs)

        producer = Producer(wf, name="p")
        consumer = CountingUnit(wf, name="c")
        consumer.demand("needed")
        producer.link_from(wf.start_point)
        consumer.link_from(producer)
        wf.end_point.link_from(consumer)

        # consumer links the attr at first successful producer init
        orig_init = producer.initialize

        def init_then_link(**kwargs):
            result = orig_init(**kwargs)
            consumer.needed = producer.produced
            return result
        producer.initialize = init_then_link

        wf.initialize()
        assert consumer.needed == 7

    def test_stop_halts_loop(self):
        wf = DummyWorkflow()
        repeater = Repeater(wf)
        body = CountingUnit(wf, name="body")

        class Stopper(CountingUnit):
            def run(self):
                super(Stopper, self).run()
                if self.count >= 3:
                    wf.stop()

        stopper = Stopper(wf, name="stopper")
        repeater.link_from(wf.start_point)
        body.link_from(repeater)
        stopper.link_from(body)
        repeater.link_from(stopper)
        wf.initialize()
        wf.run()
        assert stopper.count == 3

    def test_timers_accumulate(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        a.link_from(wf.start_point)
        wf.end_point.link_from(a)
        wf.initialize()
        wf.run()
        assert a.run_calls == 1
        assert a.timers["run"] >= 0

    def test_graphviz(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="alpha")
        a.link_from(wf.start_point)
        wf.end_point.link_from(a)
        dot = wf.generate_graph()
        assert "digraph" in dot and "alpha" in dot

    def test_checksum_stable(self):
        wf1, wf2 = DummyWorkflow(), DummyWorkflow()
        assert wf1.checksum == wf2.checksum

    def test_unlink(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        b.link_from(a)
        assert a in b.links_from
        b.unlink_from(a)
        assert a not in b.links_from and b not in a.links_to

    def test_dependency_order(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        a.link_from(wf.start_point)
        b.link_from(a)
        wf.end_point.link_from(b)
        order = wf.units_in_dependency_order
        assert order.index(a) < order.index(b)


class TestWorkflowPickle:
    def test_workflow_pickles(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        a.link_from(wf.start_point)
        wf.end_point.link_from(a)
        wf.initialize()
        wf.run()
        blob = pickle.dumps(wf)
        restored = pickle.loads(blob)
        units = {u.name for u in restored.units}
        assert "a" in units
        # restored graph is runnable again after re-init
        restored.workflow = DummyLauncher()
        restored.initialize()
        restored.run()
        assert restored["a"].count == 2

    def test_link_attrs_survive_pickle(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        b = CountingUnit(wf, name="b")
        a.output = 42
        b.link_attrs(a, ("input", "output"))
        restored = pickle.loads(pickle.dumps(wf))
        ra, rb = restored["a"], restored["b"]
        assert rb.input == 42
        ra.output = 99
        assert rb.input == 99  # alias still live, pointing at restored a

    def test_stripped_pickle_drops_links(self):
        wf = DummyWorkflow()
        a = CountingUnit(wf, name="a")
        a.link_from(wf.start_point)
        a.stripped_pickle = True
        restored = pickle.loads(pickle.dumps(a))
        assert restored.links_from == {}


class TestDistributedContract:
    def test_job_roundtrip(self):
        """Master generates a job; slave applies, runs, returns update."""
        wf_master = DummyWorkflow()

        class Worker(CountingUnit):
            job_payload = None

            def generate_data_for_slave(self, slave=None):
                return {"job": 1}

            def apply_data_from_master(self, data):
                self.job_payload = data

            def generate_data_for_master(self):
                return {"done": self.count}

            def apply_data_from_slave(self, data, slave=None):
                self.merged = data

        m_unit = Worker(wf_master, name="w")
        m_unit.link_from(wf_master.start_point)
        wf_master.end_point.link_from(m_unit)
        wf_master.initialize()

        job = wf_master.generate_data_for_slave("slave-1")
        assert any(part == {"job": 1} for part in job)

        wf_slave = DummyWorkflow()
        s_unit = Worker(wf_slave, name="w")
        s_unit.link_from(wf_slave.start_point)
        wf_slave.end_point.link_from(s_unit)
        wf_slave.initialize()

        updates = []
        wf_slave.do_job(job, None, updates.append)
        assert s_unit.job_payload == {"job": 1}
        assert s_unit.count == 1
        assert updates and any(p == {"done": 1} for p in updates[0])

        wf_master.apply_data_from_slave(updates[0], "slave-1")
        assert m_unit.merged == {"done": 1}

    def test_not_ready_sync_point(self):
        wf = DummyWorkflow()

        class NotReady(CountingUnit):
            def generate_data_for_slave(self, slave=None):
                return False

        NotReady(wf, name="nr").link_from(wf.start_point)
        wf.initialize()
        assert wf.generate_data_for_slave("s") is False


def test_run_after_finish_raises(cpu_device):
    """Broken control links surface loudly (reference units.py:823-839
    RunAfterStopError): a unit driven through the scheduler wrapper
    after the workflow finished — with no stop requested — raises
    instead of silently doing nothing."""
    from veles_tpu.units import RunAfterStopError
    from tests.test_models import BlobsLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[{"type": "softmax", "output_sample_shape": 4,
                 "learning_rate": 0.1, "gradient_moment": 0.9}],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("ras", seed=1)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    assert sw.finished and not sw.stop_requested
    with pytest.raises(RunAfterStopError):
        sw.loader._timed_run()
    # an explicit stop() is NOT an error: suppressed quietly
    sw.stop()
    assert sw.loader._timed_run() is False


def test_workflow_leaves_no_uncollectable_garbage(cpu_device):
    """Reference-cycle hygiene (the reference converted back-links to
    weakrefs so dropped workflows free): the unit graph is cyclic by
    design, so the teeth here are the weakref — a built+run+dropped
    workflow must actually be reclaimed by gc.collect()."""
    import gc
    import weakref

    from tests.test_models import BlobsLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[{"type": "softmax", "output_sample_shape": 4,
                 "learning_rate": 0.1, "gradient_moment": 0.9}],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("gcw", seed=2)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    ref = weakref.ref(sw)
    wf.workflow.del_ref(sw)
    del sw
    del wf
    gc.collect()
    assert ref() is None, "workflow survived del + gc.collect"


def test_stopped_workflow_reruns(cpu_device):
    """stop() then run() executes the graph again (per-job reruns on
    slaves depend on this): the units' own stop flags reset, so the
    second run is real, not a silently suppressed phantom."""
    from tests.test_models import BlobsLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[{"type": "softmax", "output_sample_shape": 4,
                 "learning_rate": 0.1, "gradient_moment": 0.9}],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("rr", seed=3)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    sw.stop()
    runs_before = sw.loader.run_calls
    sw.run()
    assert sw.loader.run_calls > runs_before, "phantom run after stop"
