"""FusedTrainer: the single-dispatch training path inside the standard
workflow loop."""

import numpy
import pytest

from veles_tpu.prng import RandomGenerator
from tests.test_models import BlobsLoader, build_mnist_like


def _build_fused(device, max_epochs=10):
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("fused", seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )
    sw.fuse()
    sw.initialize(device=device)
    return sw


def test_fused_workflow_trains(cpu_device):
    sw = _build_fused(cpu_device)
    sw.run()
    assert bool(sw.decision.complete)
    assert sw.decision.epoch_metrics[1] is not None
    assert sw.decision.epoch_metrics[1] < 5.0
    assert sw.fused_trainer.run_calls > 0
    # forwards/gds left the control graph
    assert sw.forwards[0].run_calls == 0
    assert sw.gds[0].run_calls == 0


def test_fused_matches_unit_path_quality(cpu_device):
    fused = _build_fused(cpu_device)
    fused.run()
    unit = build_mnist_like(cpu_device, )
    unit.decision.max_epochs = 10
    unit.run()
    # same architecture/task: both reach ~0 validation error
    assert fused.decision.epoch_metrics[1] <= \
        unit.decision.epoch_metrics[1] + 3.0


def test_fused_snapshot_roundtrip(cpu_device):
    import pickle

    from veles_tpu.dummy import DummyLauncher
    sw = _build_fused(cpu_device, max_epochs=2)
    sw.run()
    sw.fused_trainer.sync()
    sw.forwards[0].weights.map_read()
    w_before = numpy.array(sw.forwards[0].weights.mem)
    assert numpy.abs(w_before).sum() > 0

    blob = pickle.dumps(sw)
    restored = pickle.loads(blob)
    restored.workflow = DummyLauncher()
    restored.restored_from_snapshot_ = True
    restored.decision.max_epochs = 4
    restored.decision.complete <<= False
    restored.initialize(device=cpu_device)
    restored.forwards[0].weights.map_read()
    numpy.testing.assert_array_equal(
        restored.forwards[0].weights.mem, w_before)
    restored.run()
    assert restored.decision.epoch_metrics[1] < 5.0


def test_fused_sync_survives_donation(cpu_device):
    """sync() mid-training must not leave unit Arrays referencing
    buffers the next fused step donates (advisor finding, round 3):
    after sync -> more steps, the Arrays' host AND device sides stay
    usable.  (CPU donation is lenient; on the real TPU the pre-fix
    code reproducibly raised "Array has been deleted" here — verified
    on-chip both ways.)"""
    sw = _build_fused(cpu_device, max_epochs=2)
    trainer = sw.fused_trainer
    loader = sw.loader

    sw.run()                       # trains to max_epochs
    trainer.sync()                 # stage params out (snapshot path)
    before = numpy.array(sw.forwards[0].weights.mem)

    # keep stepping the fused trainer directly: donates the state
    # buffers sync() just adopted from
    loader.run()
    trainer.run()
    loader.run()
    trainer.run()

    # host side readable and device side re-attachable, no
    # "Array has been deleted"
    trainer.sync()
    sw.forwards[0].weights.map_read()
    after = numpy.array(sw.forwards[0].weights.mem)
    assert numpy.isfinite(after).all()
    assert not numpy.array_equal(before, after)  # training moved on
    dev_arr = sw.forwards[0].weights.device_array(cpu_device)
    assert numpy.isfinite(numpy.asarray(dev_arr)).all()


def _build_unfused(max_epochs=3):
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.models.nn_workflow import StandardWorkflow
    wf = DummyWorkflow()
    return StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("fused", seed=7)),
        decision_config=dict(max_epochs=max_epochs),
    )


def test_auto_fuse_on_tpu_backend(cpu_device):
    """A device resolving to the TPU backend auto-fuses at initialize
    (the per-unit loop is the opt-in debug path on TPU)."""
    sw = _build_unfused()
    cpu_device.BACKEND = "tpu"  # instance attr: claims tpu backend
    sw.initialize(device=cpu_device)
    assert sw.fused_trainer is not None
    sw.run()
    assert bool(sw.decision.complete)
    assert sw.fused_trainer.run_calls > 0
    assert sw.forwards[0].run_calls == 0


def test_auto_fuse_opt_out(cpu_device):
    from veles_tpu.config import root
    sw = _build_unfused()
    cpu_device.BACKEND = "tpu"
    root.common.engine.auto_fuse = False
    try:
        sw.initialize(device=cpu_device)
    finally:
        root.common.engine.auto_fuse = True
    assert getattr(sw, "fused_trainer", None) is None
    sw.run()
    assert sw.forwards[0].run_calls > 0


def test_no_auto_fuse_on_cpu(cpu_device):
    """CPU keeps the per-unit default: reference-parity semantics."""
    sw = _build_unfused()
    sw.initialize(device=cpu_device)
    assert getattr(sw, "fused_trainer", None) is None
    sw.run()
    assert sw.forwards[0].run_calls > 0


@pytest.mark.slow
def test_fused_snapshot_resume_on_real_tpu():
    """Round-3 verdict item 9: snapshot/restore round trip ON THE CHIP
    under the fused (auto-fuse default) path — train, snapshot
    mid-training, restore, train on.  Donation + detach interactions
    ("Array has been deleted") only reproduce on real TPU, where the
    fused step donates its state buffers.  Subprocess because conftest
    pins this process to the virtual CPU mesh."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "VELES_BACKEND")}
    env["XLA_FLAGS"] = ""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(int(bool(d) and d[0].platform != 'cpu'))"],
            env=env, capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU probe timed out (runtime unresponsive)")
    if probe.returncode != 0 or probe.stdout.strip() != "1":
        pytest.skip("no real TPU attached")

    code = """
import pickle
import sys
sys.path.insert(0, %r)

from tests.test_fused import _build_unfused
from veles_tpu.dummy import DummyLauncher

sw = _build_unfused(max_epochs=3)
sw.initialize(device="tpu")      # auto-fuses (TPU default path)
assert sw.fused_trainer is not None, "expected auto-fuse on TPU"
sw.run()
err_before = float(sw.decision.epoch_metrics[1])

# snapshot mid-training on the chip: sync pulls the donated device
# state back into the unit Arrays (prefetch_host sweep), then pickle
sw.fused_trainer.sync()
blob = pickle.dumps(sw)

restored = pickle.loads(blob)
restored.workflow = DummyLauncher()
restored.restored_from_snapshot_ = True
restored.decision.max_epochs = 6
restored.decision.complete <<= False
restored.initialize(device="tpu")   # re-adopts state; auto-fuse again
assert restored.fused_trainer is not None
restored.run()
err_after = float(restored.decision.epoch_metrics[1])
assert err_after <= err_before + 1.0, (err_before, err_after)
print("RESUME_OK", err_before, err_after)
""" % repo
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=900, cwd=repo)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "RESUME_OK" in proc.stdout, proc.stdout[-2000:]


def test_step_compiler_options_gated_on_device_db(monkeypatch):
    """step_compiler_options returns None on untuned device kinds
    (the CPU test mesh) and the XLA flag dict when the device DB
    carries a tuned scoped-VMEM entry."""
    from veles_tpu import backends
    from veles_tpu.compiler import step_compiler_options

    assert step_compiler_options() is None  # cpu: no tuned entry

    class TunedInfo(object):
        def __init__(self, kind):
            self.kind = kind

        def get(self, key, default=None):
            return 98304 if key == "train_step:scoped_vmem_kib" \
                else default

    monkeypatch.setattr(backends, "DeviceInfo", TunedInfo)
    assert step_compiler_options() == {
        "xla_tpu_scoped_vmem_limit_kib": "98304"}
