"""Kernel sanitizer sweep — the TPU analog of the reference's
``doubling_reset`` GPU OOB NaN-guard (veles/tests/doubling_reset.py:
41-66): every Pallas op is exercised on lane/tile-UNALIGNED shapes that
force internal padding, and the result must (a) match the numpy oracle
and (b) contain no NaN leaking from padded regions."""

import numpy
import pytest

import jax

from veles_tpu import ops


def _check(out, oracle, rtol=1e-4):
    out = numpy.asarray(out)
    assert numpy.isfinite(out).all(), "NaN/inf leaked from padding"
    numpy.testing.assert_allclose(out, oracle, rtol=rtol, atol=1e-5)


@pytest.mark.parametrize("shape", [(3, 5, 7), (17, 129, 33),
                                   (1, 1, 1), (130, 257, 5)])
def test_matmul_odd_shapes(shape):
    m, k, n = shape
    rng = numpy.random.RandomState(hash(shape) % 2**31)
    a = rng.rand(m, k).astype(numpy.float32)
    b = rng.rand(k, n).astype(numpy.float32)
    _check(ops.matmul(a, b), a @ b)


@pytest.mark.parametrize("width", [1, 7, 127, 129, 200])
def test_gather_odd_widths(width):
    rng = numpy.random.RandomState(width)
    data = rng.rand(50, width).astype(numpy.float32)
    idx = rng.randint(0, 50, 13).astype(numpy.int32)
    _check(ops.gather_minibatch(data, idx), data[idx])


@pytest.mark.parametrize("widths", [(1, 1), (3, 130), (127, 5, 64)])
def test_join_odd_widths(widths):
    rng = numpy.random.RandomState(sum(widths))
    parts = [rng.rand(9, w).astype(numpy.float32) for w in widths]
    _check(ops.join(*parts), numpy.concatenate(parts, axis=1))


@pytest.mark.parametrize("shape", [(1, 1), (7, 3), (33, 129)])
def test_reduce_odd_shapes(shape):
    rng = numpy.random.RandomState(shape[0])
    x = rng.rand(*shape).astype(numpy.float32)
    _check(ops.reduce_rows(x).ravel(), x.sum(axis=1))
    _check(ops.reduce_cols(x).ravel(), x.sum(axis=0))


@pytest.mark.parametrize("width", [1, 5, 127, 300])
def test_normalize_odd_widths(width):
    rng = numpy.random.RandomState(width)
    x = rng.rand(11, width).astype(numpy.float32)
    mean = rng.rand(width).astype(numpy.float32)
    rdisp = rng.rand(width).astype(numpy.float32) + 0.5
    _check(ops.mean_disp_normalize(x, mean, rdisp), (x - mean) * rdisp)


def test_nan_in_real_data_is_preserved_not_amplified():
    """NaN already IN the declared data must flow through (no masking
    bugs hiding real NaNs)."""
    a = numpy.ones((4, 4), numpy.float32)
    a[1, 2] = numpy.nan
    b = numpy.ones((4, 4), numpy.float32)
    out = numpy.asarray(ops.matmul(a, b))
    assert numpy.isnan(out[1]).all()
    assert numpy.isfinite(out[0]).all()
