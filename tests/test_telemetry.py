"""Fleet telemetry plane (veles_tpu/observe/timeseries.py, alerts.py,
baseline.py; docs/observability.md "Fleet telemetry"): series-ring
bucket semantics (counter deltas/rates over ACTUAL elapsed time,
gauge last-write, mergeable log-binned latency digests), the
take_chunk ship cursor and FleetTelemetry's seq-dedup'd
offset-corrected rollups with kind-true merge semantics (counters
sum, gauges max, digests merge bin-wise), NTP probe offset estimation
(min-delay wins), the multi-window burn-rate truth table (fast AND
slow must both burn; thin windows abstain), EMA spike rules,
edge-triggered alert lifecycle with the flight-recorder + tail-
exemplar evidence dump, heartbeat schema v2/v3 validation and the
JSONL digest, the perf-baseline regression gate, and the
``observe fleet`` / ``observe regress`` CLI round-trips."""

import json
import math

import pytest

from veles_tpu.observe import baseline
from veles_tpu.observe.alerts import (AlertManager, BurnRateRule,
                                      EmaSpikeRule, default_rules,
                                      rule_from_spec)
from veles_tpu.observe.metrics import MetricsRegistry
from veles_tpu.observe.timeseries import (DIGEST_BASE, FleetTelemetry,
                                          SERIES_SCHEMA_VERSION,
                                          SeriesRing, digest_percentiles,
                                          digest_values, fleet_summary,
                                          merge_digests)

pytestmark = [pytest.mark.observe, pytest.mark.telemetry]


# -- digests ----------------------------------------------------------------


def test_digest_values_shape_and_nan_safety():
    """A digest carries exact count/sum/min/max plus log-binned
    counts; non-finite observations are skipped, non-positive ones
    land in the zero bin."""
    d = digest_values([0.010, 0.020, 0.040, float("nan"),
                       float("inf"), 0.0, -1.0])
    assert d["count"] == 5          # nan/inf skipped, 0.0 and -1 kept
    assert d["min"] == -1.0 and d["max"] == 0.040
    assert d["bins"].get("z") == 2  # the two non-positive values
    assert sum(d["bins"].values()) == d["count"]
    assert d["sum"] == pytest.approx(0.010 + 0.020 + 0.040 - 1.0)


def test_digest_percentiles_bounded_by_bin_width():
    """A recovered percentile answers with its bin's UPPER edge:
    pessimistic, but by at most one bin width (~19% relative), and
    always clamped into the digest's exact [min, max]."""
    values = [0.001 * (i + 1) for i in range(1000)]
    pcts = digest_percentiles(digest_values(values))
    for p, exact in (("p50", 0.500), ("p95", 0.950), ("p99", 0.990)):
        assert exact <= pcts[p] <= exact * DIGEST_BASE * 1.0001
    one = digest_values([0.123])
    assert digest_percentiles(one)["p99"] == 0.123  # clamped to max
    assert digest_percentiles({"bins": {}}) == {}


def test_merge_digests_is_a_mixture():
    """Bin-wise merge: counts add, the merged percentile lies within
    the component envelope (the property averaged per-host
    percentiles can never have), malformed entries are skipped."""
    fast = digest_values([0.010] * 90 + [0.020] * 10)
    slow = digest_values([0.200] * 90 + [0.400] * 10)
    merged = merge_digests([fast, None, "junk", slow])
    assert merged["count"] == fast["count"] + slow["count"]
    assert merged["min"] == fast["min"]
    assert merged["max"] == slow["max"]
    m, f, s = (digest_percentiles(d) for d in (merged, fast, slow))
    for p in ("p50", "p99"):
        assert min(f[p], s[p]) <= m[p] <= max(f[p], s[p])


# -- series ring ------------------------------------------------------------


def test_series_ring_bucket_semantics():
    """First tick primes (no since-boot rate); then counters report
    {delta, rate-over-ACTUAL-elapsed}, gauges their last finite
    value, histograms a digest of exactly the new observations."""
    reg = MetricsRegistry()
    ring = SeriesRing(interval_s=1.0, registry=reg)
    reg.counter("req").inc(100)
    reg.gauge("depth").set(3)
    reg.histogram("lat").observe(0.010)
    assert ring.tick(now=10.0, wall=1000.0) is None  # priming
    reg.counter("req").inc(8)
    reg.gauge("depth").set(7)
    reg.histogram("lat").observe(0.020)
    reg.histogram("lat").observe(0.040)
    bucket = ring.tick(now=14.0, wall=1004.0)
    assert bucket["seq"] == 0 and bucket["ts"] == 1004.0
    assert bucket["counters"]["req"] == {"delta": 8, "rate": 2.0}
    assert bucket["gauges"]["depth"] == 7
    hist = bucket["hists"]["lat"]
    assert hist["count"] == 2            # pre-prime 0.010 NOT counted
    assert hist["min"] == 0.020 and hist["max"] == 0.040
    # an idle interval publishes a zero-delta counter and no digest
    bucket = ring.tick(now=15.0, wall=1005.0)
    assert bucket["counters"]["req"] == {"delta": 0, "rate": 0.0}
    assert "lat" not in bucket["hists"]


def test_series_ring_counter_reset_and_maybe_tick_cadence():
    """A registry reset between ticks (bench A/B legs) must not
    publish a negative delta; maybe_tick honors the interval."""
    reg = MetricsRegistry()
    ring = SeriesRing(interval_s=1.0, registry=reg)
    reg.counter("req").inc(50)
    ring.tick(now=0.0, wall=100.0)
    reg.reset()
    reg.counter("req").inc(3)            # reborn smaller than before
    bucket = ring.tick(now=2.0, wall=102.0)
    assert bucket["counters"]["req"]["delta"] == 3
    assert ring.maybe_tick(now=2.5) is None       # interval not up
    assert ring.maybe_tick(now=3.1) is not None


def test_take_chunk_cursor_and_fleet_dedup():
    """take_chunk pops only never-shipped buckets; a re-shipped
    overlap (snapshot-mode producers) dedups by seq on the receiving
    FleetTelemetry, and malformed chunks are counted, not raised."""
    reg = MetricsRegistry()
    ring = SeriesRing(interval_s=1.0, registry=reg)
    reg.counter("req").inc(1)
    ring.tick(now=0.0, wall=100.0)
    for i in range(3):
        reg.counter("req").inc(1)
        ring.tick(now=1.0 + i, wall=101.0 + i)
    chunk = ring.take_chunk(label="h0")
    assert chunk["schema"] == SERIES_SCHEMA_VERSION
    assert [b["seq"] for b in chunk["buckets"]] == [0, 1, 2]
    assert ring.take_chunk() is None     # drained
    fleet = FleetTelemetry(interval_s=1.0)
    assert fleet.add_chunk("h0", chunk)
    assert not fleet.add_chunk("h0", ring.snapshot(label="h0"))  # overlap
    assert len(fleet.host_buckets("h0")) == 3
    assert fleet.dropped == 0
    assert not fleet.add_chunk("h0", {"schema": 99, "buckets": []})
    assert not fleet.add_chunk("h0", "garbage")
    assert fleet.dropped == 2


def _host_chunk(host, wall0, latencies, reqs=10):
    """One host's two-bucket chunk with a known clock origin."""
    reg = MetricsRegistry()
    ring = SeriesRing(interval_s=1.0, registry=reg)
    ring.tick(now=0.0, wall=wall0)
    reg.counter("req").inc(reqs)
    reg.gauge("depth").set(reqs)
    for value in latencies:
        reg.histogram("lat").observe(value)
    ring.tick(now=1.0, wall=wall0 + 1.0)
    return ring.take_chunk(label=host)


def test_fleet_rollup_offset_corrected_merge():
    """Rollup cells land per LOCAL clock (ts + offset): counters sum
    across hosts, gauges take the max, digests merge — and the
    fleet_summary table recovers count-conserving percentiles."""
    fleet = FleetTelemetry(interval_s=1.0)
    # h1's wall clock runs 500 s ahead; its offset maps it back
    fleet.add_chunk("h0", _host_chunk("h0", 1000.0, [0.010] * 20,
                                     reqs=10))
    fleet.add_chunk("h1", _host_chunk("h1", 1500.0, [0.200] * 20,
                                      reqs=30))
    fleet.set_offset("h1", -500.0)
    cells = fleet.rollup()
    assert len(cells) == 1               # same corrected cell
    cell = cells[0]
    assert cell["hosts"] == ["h0", "h1"]
    assert cell["counters"]["req"]["delta"] == 40
    assert cell["gauges"]["depth"] == 30
    assert cell["hists"]["lat"]["count"] == 40
    table = fleet_summary(cells)
    assert table["hists"]["lat"]["count"] == 40
    assert 0.010 <= table["hists"]["lat"]["p50"] <= 0.200 * DIGEST_BASE
    # without the offset the buckets land 500 cells apart
    fleet.set_offset("h1", 0.0)
    assert len(fleet.rollup()) == 2


def test_add_probe_min_delay_offset_estimate():
    """The NTP discipline: among piggybacked (t0, t1, t2, t3) probes
    the MINIMUM-delay exchange wins — queueing noise only ever
    inflates delay, never deflates it."""
    fleet = FleetTelemetry()
    # true offset +5 s; a noisy probe (0.5 s RTT, asymmetric) first
    fleet.add_probe("h0", (100.0, 105.2, 105.3, 100.6))
    noisy = fleet.offset("h0")
    fleet.add_probe("h0", (200.0, 205.05, 205.06, 200.11))
    assert fleet.offset("h0") == pytest.approx(5.0, abs=1e-9)
    assert abs(fleet.offset("h0") - 5.0) <= abs(noisy - 5.0)
    fleet.add_probe("h0", ("junk",))           # ignored, not raised
    fleet.add_probe("h0", (1.0, float("nan"), 2.0, 3.0))
    assert fleet.offset("h0") == pytest.approx(5.0, abs=1e-9)


# -- alert rules ------------------------------------------------------------


def _lat_bucket(ts, values):
    return {"ts": ts, "dur_s": 1.0, "counters": {}, "gauges": {},
            "hists": {"lat": digest_values(values)}}


def test_burn_rate_truth_table():
    """The multi-window pair: fires only when the fast AND slow
    windows BOTH burn the error budget at >= factor; a window under
    min_count abstains (an idle series neither fires nor resolves)."""
    rule = BurnRateRule("burn", "lat", 0.100, objective=0.9,
                        fast_buckets=1, slow_buckets=4, factor=3.0,
                        min_count=5)
    over = [0.500] * 10
    under = [0.010] * 10
    # all windows burning: over-fraction 1.0 / allowed 0.1 = 10x
    assert rule.evaluate([_lat_bucket(t, over) for t in range(4)])
    # steady: nothing over budget
    assert rule.evaluate(
        [_lat_bucket(t, under) for t in range(4)]) is None
    # fast recovered, slow still polluted -> no fire (fast gate)
    hist = [_lat_bucket(t, over) for t in range(3)] + \
        [_lat_bucket(3, under)]
    assert rule.evaluate(hist) is None
    # fast burning but slow diluted to 2.5x < factor 3 -> no fire
    fresh = [_lat_bucket(t, under) for t in range(3)] + \
        [_lat_bucket(3, over)]
    assert rule.evaluate(fresh) is None
    # thin window abstains entirely
    assert rule.evaluate([_lat_bucket(0, [0.500])]) is None
    assert rule.window_burn([_lat_bucket(0, [0.500] * 4)]) is None


def test_burn_rate_spec_round_trip():
    rule = BurnRateRule("burn", "lat", 0.100, objective=0.95,
                        fast_buckets=2, slow_buckets=8, factor=4.0,
                        min_count=7)
    clone = rule_from_spec(rule.spec())
    assert clone.spec() == rule.spec()
    with pytest.raises(ValueError):
        rule_from_spec({"kind": "astrology"})


def test_ema_spike_rule_consumes_buckets_once():
    """A spike against the EMA baseline breaches on the newest bucket
    and is NOT folded into the baseline; already-seen buckets (by ts)
    are not re-consumed."""
    rule = EmaSpikeRule("errs", "err", spike_factor=10.0,
                        spike_floor=1.0, beta=0.5)

    def bucket(ts, rate):
        return {"ts": ts, "counters": {"err": {"delta": rate,
                                               "rate": rate}},
                "gauges": {}, "hists": {}}

    steady = [bucket(float(t), 2.0) for t in range(6)]
    assert rule.evaluate(steady) is None
    assert rule.evaluate(steady + [bucket(6.0, 200.0)])
    # breach persists until a NEW calm bucket arrives
    assert rule.evaluate(steady + [bucket(6.0, 200.0)])
    assert rule.evaluate(steady + [bucket(6.0, 200.0),
                                   bucket(7.0, 2.0)]) is None


def test_default_rules_tenant_vs_fleet_scope():
    """The stock set: one burn pair per budgeted QoS class plus the
    EMA anomaly rules; fleet scope points the burn rules at the
    front-door end-to-end histograms (the ones that see transport
    stalls) under distinct names."""
    tenant = {r.name: r for r in default_rules()}
    assert "slo_burn.interactive" in tenant
    assert tenant["slo_burn.interactive"].hist == \
        "serve.tenant.interactive.latency_s"
    assert "queue_depth_spike" in tenant
    assert "fleet_failures_spike" in tenant
    fleet = {r.name: r for r in default_rules(scope="fleet")}
    assert fleet["slo_burn.fleet.interactive"].hist == \
        "serve.fleet.interactive.latency_s"


# -- alert manager ----------------------------------------------------------


def test_alert_manager_edge_triggered_lifecycle(tmp_path):
    """One breach = one firing (however long it persists), with the
    evidence trail: the firing's flight dump carries the alert record
    and the tail-exemplar ring; recovery lands a resolved record."""
    from veles_tpu.observe.flight import flight
    prev_enabled = flight.enabled
    flight.enabled = True
    flight.base_path = str(tmp_path / "flight")
    try:
        manager = AlertManager([BurnRateRule(
            "burn", "lat", 0.100, objective=0.9, fast_buckets=1,
            slow_buckets=2, factor=2.0, min_count=5)])
        burning = [_lat_bucket(t, [0.500] * 10) for t in range(2)]
        fired = manager.evaluate(burning, wall=100.0,
                                 context={"scope": "test"})
        assert [r["alert"] for r in fired] == ["burn"]
        assert fired[0]["context"] == {"scope": "test"}
        assert manager.evaluate(burning, wall=101.0) == []  # persists
        assert manager.snapshot()["active"] == ["burn"]
        dump = fired[0].get("flight_dump")
        assert dump
        with open(dump) as fh:
            doc = json.load(fh)
        assert doc["alert"]["alert"] == "burn"
        assert "exemplars" in doc
        calm = [_lat_bucket(t, [0.010] * 10) for t in range(2)]
        assert manager.evaluate(calm, wall=102.0) == []
        states = [(r["alert"], r["state"]) for r in manager.history()]
        assert states == [("burn", "firing"), ("burn", "resolved")]
        snap = manager.snapshot()
        assert snap["fired_total"] == 1 and snap["active"] == []
        # re-breach after resolve is a NEW edge
        assert len(manager.evaluate(burning, wall=103.0)) == 1
    finally:
        flight.enabled = prev_enabled


def test_alert_manager_broken_rule_abstains():
    """A rule that raises must never take down the sweep — it simply
    abstains while the healthy rules keep evaluating."""

    class Broken(BurnRateRule):
        def evaluate(self, buckets):
            raise RuntimeError("boom")

    manager = AlertManager([
        Broken("broken", "lat", 0.1),
        BurnRateRule("burn", "lat", 0.100, objective=0.9,
                     fast_buckets=1, slow_buckets=2, factor=2.0,
                     min_count=5)])
    burning = [_lat_bucket(t, [0.500] * 10) for t in range(2)]
    fired = manager.evaluate(burning, dump=False)
    assert [r["alert"] for r in fired] == ["burn"]


# -- heartbeat schema v3 ----------------------------------------------------


def test_heartbeat_v3_line_carries_telemetry_blocks(tmp_path):
    """A live line is schema 3 with the ``series`` + ``alerts``
    blocks and passes its own validator."""
    from veles_tpu.observe.profile import (HEARTBEAT_SCHEMA_VERSION,
                                           Heartbeat,
                                           validate_heartbeat)
    hb = Heartbeat(str(tmp_path / "hb.jsonl"),
                   registry=MetricsRegistry())
    record = validate_heartbeat(hb.line())
    assert record["schema"] == HEARTBEAT_SCHEMA_VERSION == 3
    assert "schema" in record["series"]
    assert set(record["alerts"]) >= {"active", "firing",
                                     "fired_total", "history"}
    json.dumps(record)  # json-serializable end to end


def test_heartbeat_v2_stays_readable_and_v3_is_enforced(tmp_path):
    """Pre-telemetry v2 lines (no series/alerts blocks) still
    validate; a line CLAIMING v3 without the blocks is rejected."""
    from veles_tpu.observe.profile import Heartbeat, validate_heartbeat
    hb = Heartbeat(str(tmp_path / "hb.jsonl"),
                   registry=MetricsRegistry())
    v2 = hb.line()
    v2["schema"] = 2
    v2.pop("series")
    v2.pop("alerts")
    assert validate_heartbeat(v2)["schema"] == 2
    v3 = hb.line()
    v3.pop("series")
    with pytest.raises(ValueError, match="series"):
        validate_heartbeat(v3)
    with pytest.raises(ValueError, match="schema"):
        bad = hb.line()
        bad["schema"] = 99
        validate_heartbeat(bad)


def test_summarize_heartbeats_mixed_schemas(tmp_path):
    """The JSONL digest reads v2 and v3 lines side by side: schema
    census, steady-state rates from consecutive cumulative counters,
    and the set of alerts the file recorded as firing."""
    from veles_tpu.observe.profile import Heartbeat
    from veles_tpu.observe.summary import summarize
    reg = MetricsRegistry()
    hb = Heartbeat(str(tmp_path / "hb.jsonl"), registry=reg)
    records = []
    for i in range(5):
        reg.counter("train.steps").inc(10)
        line = hb.line()
        line["ts"] = 1000.0 + i          # deterministic 1 s cadence
        if i == 0:
            line["schema"] = 2
            line.pop("series")
            line.pop("alerts")
        elif i == 4:
            line["alerts"]["history"] = [
                {"alert": "slo_burn.interactive", "state": "firing",
                 "ts": line["ts"]}]
        records.append(line)
    records.append({"kind": "junk"})     # invalid line is counted
    digest = summarize({"kind": "heartbeats", "records": records})
    assert digest["events"] == 5 and digest["invalid"] == 1
    assert digest["schemas"] == {2: 1, 3: 4}
    assert digest["rates"]["train.steps"] == pytest.approx(10.0)
    assert digest["alerts_fired"] == ["slo_burn.interactive"]


# -- perf-regression sentinel -----------------------------------------------


def _write_baseline(path, metrics):
    path.write_text(json.dumps(
        {"schema": 1, "source": "test", "metrics": metrics}))
    return str(path)


def test_baseline_gate_directions_and_tolerance(tmp_path):
    """``direction`` names which way is BETTER: a higher-is-better
    metric fails by dropping past tolerance, a lower-is-better one by
    rising; in-tolerance drift and improvements pass."""
    base = _write_baseline(tmp_path / "PERF_BASELINE.json", {
        "tflops": {"value": 100.0, "direction": "higher",
                   "tolerance_pct": 10.0},
        "p99_ms": {"value": 20.0, "direction": "lower",
                   "tolerance_pct": 10.0}})
    ok, report = baseline.gate({"tflops": 95.0, "p99_ms": 21.0},
                               baseline_path=base)
    assert ok and report["status"] == "ok"
    ok, report = baseline.gate({"tflops": 85.0, "p99_ms": 19.0},
                               baseline_path=base)
    assert not ok and report["regressed"] == ["tflops"]
    ok, report = baseline.gate({"tflops": 120.0, "p99_ms": 26.0},
                               baseline_path=base)
    assert not ok and report["regressed"] == ["p99_ms"]
    statuses = {r["metric"]: r["status"] for r in report["results"]}
    assert statuses["tflops"] == "improved"
    assert any("REGRESSED" in line
               for line in baseline.render_report(report))


def test_baseline_gate_missing_metric_and_no_baseline(tmp_path):
    """A baselined metric the run did not cover reports ``missing``
    without failing; a missing baseline passes as ``no_baseline`` —
    first runs must never be red."""
    base = _write_baseline(tmp_path / "PERF_BASELINE.json", {
        "tflops": {"value": 100.0, "direction": "higher"}})
    ok, report = baseline.gate({"other": 1.0}, baseline_path=base)
    assert ok
    assert report["results"][0]["status"] == "missing"
    ok, report = baseline.gate(
        {"tflops": 1.0}, baseline_path=str(tmp_path / "absent.json"))
    assert ok and report["status"] == "no_baseline"
    assert baseline.load_baseline(str(tmp_path / "absent.json")) is None


def test_baseline_headline_metric_folding(tmp_path):
    """The compact record's headline {metric, value} pair is folded
    in under its own metric name (bench.py's last line shape)."""
    base = _write_baseline(tmp_path / "PERF_BASELINE.json", {
        "bf16_tflops": {"value": 100.0, "direction": "higher",
                        "tolerance_pct": 10.0}})
    ok, _ = baseline.gate({"metric": "bf16_tflops", "value": 99.0},
                          baseline_path=base)
    assert ok
    ok, report = baseline.gate({"metric": "bf16_tflops", "value": 50.0},
                               baseline_path=base)
    assert not ok and report["regressed"] == ["bf16_tflops"]


def test_steady_state_rates_filters_warmup(tmp_path):
    """Heartbeat-derived rates follow the measure.py filter-passes
    discipline: warmup/drain zero-rate buckets measure the weather,
    not the program."""

    def bucket(rate):
        return {"counters": {"req": {"delta": rate, "rate": rate}}}

    rates = baseline.steady_state_rates(
        [bucket(0.0), bucket(0.0)] +
        [bucket(r) for r in (95.0, 100.0, 105.0, 98.0, 102.0)])
    assert 90.0 <= rates["req.rate"] <= 110.0


# -- CLI --------------------------------------------------------------------


def test_observe_fleet_cli_round_trip(tmp_path, capsys):
    """``observe fleet`` merges saved per-host snapshots into the
    offset-corrected rollup table (and evaluates the stock rules with
    ``--rules``) — the offline twin of the router's live plane."""
    from veles_tpu.observe.__main__ import main
    a, b = tmp_path / "h0.json", tmp_path / "h1.json"
    a.write_text(json.dumps(_host_chunk("h0", 1000.0,
                                        [0.010] * 20, reqs=10)))
    b.write_text(json.dumps(_host_chunk("h1", 1500.0,
                                        [0.200] * 20, reqs=30)))
    rc = main(["fleet", str(a), str(b), "--offset", "h1=-500",
               "--rules", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["summary"]["hosts"] == ["h0", "h1"]
    assert out["summary"]["counters"]["req"]["delta"] == 40
    assert out["summary"]["hists"]["lat"]["count"] == 40
    assert sorted(out["fleet"]["hosts"]) == ["h0", "h1"]
    assert out["alerts"] == []           # no serve histograms here
    # human rendering exercises the same rollup
    assert main(["fleet", str(a), str(b)]) == 0
    assert "fleet rollup" in capsys.readouterr().out


def test_observe_regress_cli_exit_codes(tmp_path, capsys):
    """``observe regress`` is the sentinel's CLI front: exit 0 on a
    clean record, exit 1 naming the regressed metric."""
    from veles_tpu.observe.__main__ import main
    base = _write_baseline(tmp_path / "PERF_BASELINE.json", {
        "tflops": {"value": 100.0, "direction": "higher",
                   "tolerance_pct": 10.0}})
    good, bad = tmp_path / "good.json", tmp_path / "bad.json"
    good.write_text(json.dumps({"tflops": 101.0}))
    bad.write_text(json.dumps({"tflops": 70.0}))
    assert main(["regress", str(good), "--baseline", base]) == 0
    assert "perf gate: ok" in capsys.readouterr().out
    assert main(["regress", str(bad), "--baseline", base]) == 1
    assert "REGRESSED" in capsys.readouterr().out
    assert main(["regress", str(bad), "--baseline", base,
                 "--json"]) == 1
    assert json.loads(capsys.readouterr().out)["regressed"] == \
        ["tflops"]
