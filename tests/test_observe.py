"""Unified telemetry layer (veles_tpu/observe/): span tracer validity
and zero-overhead-when-disabled, metrics registry semantics, heartbeat
schema, print_stats baseline-vs-cumulative semantics, and the --trace
smoke run over a small fused workflow."""

import io
import json
import re
import threading
import time

import pytest

from veles_tpu.observe.metrics import (MetricsRegistry, health_snapshot,
                                       percentiles, registry)
from veles_tpu.observe.profile import (Heartbeat, ProfilerHook,
                                       validate_heartbeat)
from veles_tpu.observe.trace import SpanTracer, validate_trace

pytestmark = pytest.mark.observe


# -- span tracer -----------------------------------------------------------


def test_disabled_tracer_emits_nothing_and_stays_cheap():
    tracer = SpanTracer()
    start = time.perf_counter()
    for _ in range(20000):
        with tracer.span("x"):
            pass
        tracer.instant("y")
        tracer.complete("z", 0.0, 1.0)
        tracer.counter("c", 1)
    elapsed = time.perf_counter() - start
    assert tracer.events == []
    assert tracer.dropped == 0
    # 80k disabled calls: generous bound, but a host sync or lock on
    # the disabled path would blow straight through it
    assert elapsed < 2.0


def test_spans_nest_and_trace_parses(tmp_path):
    tracer = SpanTracer().start()
    with tracer.span("outer", cat="test", level=1):
        with tracer.span("inner", cat="test"):
            time.sleep(0.001)
        tracer.instant("marker", note="hello")
        tracer.counter("depth", 3)
    tracer.stop()
    path = tracer.save(str(tmp_path / "trace.json"))
    with open(path) as fin:
        doc = json.load(fin)
    validate_trace(doc)  # parses, known phases, spans nest
    events = doc["traceEvents"]
    spans = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(spans) == {"outer", "inner"}
    outer, inner = spans["outer"], spans["inner"]
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= \
        outer["ts"] + outer["dur"] + 1.0
    assert outer["args"] == {"level": 1}
    # per-thread track metadata is present
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)
    assert any(e["ph"] == "i" and e["name"] == "marker"
               for e in events)
    assert any(e["ph"] == "C" and e["args"] == {"value": 3}
               for e in events)


def test_validate_trace_rejects_overlapping_spans():
    doc = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 100.0,
         "pid": 1, "tid": 1},
        {"name": "b", "ph": "X", "ts": 50.0, "dur": 100.0,
         "pid": 1, "tid": 1},
    ]}
    with pytest.raises(ValueError, match="does not nest"):
        validate_trace(doc)


def test_traced_decorator_and_threads_get_own_tracks():
    tracer = SpanTracer().start()

    @tracer.traced(cat="test")
    def work():
        time.sleep(0.001)

    work()
    thread = threading.Thread(target=work, name="observe-worker")
    thread.start()
    thread.join()
    tracer.stop()
    spans = [e for e in tracer.events if e["ph"] == "X"]
    assert len(spans) == 2
    assert all("work" in e["name"] for e in spans)
    assert len({e["tid"] for e in spans}) == 2
    names = [e["args"]["name"] for e in tracer.events
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert "observe-worker" in names


def test_tracer_bounded_memory():
    tracer = SpanTracer(max_events=3)
    tracer.start()
    for i in range(10):
        tracer.instant("e%d" % i)
    # slot 1 holds the thread_name metadata; e0/e1 fill the rest,
    # e2..e9 count as dropped instead of growing the buffer
    events = tracer.events
    assert len(events) == 3
    assert events[0]["name"] == "thread_name"
    assert tracer.dropped == 8


# -- metrics registry ------------------------------------------------------


def test_percentiles_nearest_rank():
    assert percentiles([]) == {}
    out = percentiles(list(range(1, 101)))
    # true nearest-rank: index ceil(p/100 * n) - 1
    assert out["p50"] == 50
    assert out["p95"] == 95
    assert out["p99"] == 99
    small = percentiles([3.0, 1.0, 2.0])
    assert small["p50"] == 2.0
    assert small["p99"] == 3.0
    assert percentiles([1.0, 2.0])["p50"] == 1.0


def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("jobs").inc()
    reg.counter("jobs").inc(4)
    reg.gauge("depth").set(7)
    hist = reg.histogram("lat_s")
    for value in range(1, 101):
        hist.observe(value / 100.0)
    snap = reg.snapshot()
    assert snap["counters"]["jobs"] == 5
    assert snap["gauges"]["depth"] == 7
    lat = snap["histograms"]["lat_s"]
    assert lat["count"] == 100
    assert lat["min"] == 0.01 and lat["max"] == 1.0
    assert abs(lat["mean"] - 0.505) < 1e-9
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    # same name must keep its kind
    with pytest.raises(TypeError):
        reg.counter("depth")
    # peek never creates
    assert reg.peek("nope") is None
    reg.reset()
    assert reg.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}


def test_histogram_window_and_reset():
    reg = MetricsRegistry()
    hist = reg.histogram("w", window=4)
    for value in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
        hist.observe(value)
    assert hist.count == 6  # lifetime count survives the window
    assert sorted(hist.window_values()) == [3.0, 4.0, 5.0, 6.0]
    hist.reset()
    assert hist.count == 0 and hist.window_values() == []


def test_health_snapshot_reads_only_published_keys():
    reg = MetricsRegistry()
    assert health_snapshot(reg) == {}
    reg.gauge("health.skip_count").set(3)
    reg.gauge("health.consecutive_skips").set(2)
    reg.gauge("health.rollbacks_remaining").set(1)
    reg.gauge("server.blacklist_size").set(4)
    reg.counter("server.quarantined").inc()
    assert health_snapshot(reg) == {
        "skip_count": 3, "consecutive_skips": 2,
        "rollbacks_remaining": 1, "blacklist_size": 4,
        "quarantined": 1}


# -- profiler hook ---------------------------------------------------------


def test_profiler_hook_window_accounting(monkeypatch, tmp_path):
    calls = []

    class FakeProfiler(object):
        @staticmethod
        def start_trace(logdir):
            calls.append(("start", logdir))

        @staticmethod
        def stop_trace():
            calls.append(("stop", None))

    import jax
    monkeypatch.setattr(jax, "profiler", FakeProfiler)
    logdir = str(tmp_path / "prof")
    hook = ProfilerHook(logdir, start_step=2, stop_step=4)
    for _ in range(10):
        hook.step()
    assert hook.state == "done"
    assert calls == [("start", logdir), ("stop", None)]
    hook.stop()  # idempotent
    assert calls[-1] == ("stop", None) and len(calls) == 2


def test_profiler_hook_env_window(monkeypatch, tmp_path):
    monkeypatch.setenv("VELES_PROFILE", str(tmp_path))
    monkeypatch.setenv("VELES_PROFILE_WINDOW", "7:9")
    hook = ProfilerHook.from_env()
    assert hook.logdir == str(tmp_path)
    assert (hook.start_step, hook.stop_step) == (7, 9)
    monkeypatch.delenv("VELES_PROFILE")
    assert ProfilerHook.from_env() is None


# -- heartbeat -------------------------------------------------------------


def test_heartbeat_lines_validate(tmp_path):
    reg = MetricsRegistry()
    reg.counter("train.samples").inc(640)
    reg.histogram("step.train_s").observe(0.01)
    reg.gauge("health.skip_count").set(0)
    path = str(tmp_path / "hb.jsonl")
    heartbeat = Heartbeat(path, interval=0.05, registry=reg)
    heartbeat.start()
    time.sleep(0.2)
    reg.counter("train.samples").inc(640)
    heartbeat.stop()
    with open(path) as fin:
        lines = [json.loads(line) for line in fin if line.strip()]
    assert len(lines) >= 2  # periodic lines + the final one
    for record in lines:
        validate_heartbeat(record)
    assert lines[-1]["counters"]["train.samples"] == 1280
    assert lines[-1]["health"] == {"skip_count": 0}
    assert "step.train_s" in lines[-1]["histograms"]
    assert any("throughput_sps" in record for record in lines)


def test_heartbeat_stays_strict_json_under_nan(tmp_path):
    """A diverging run (NaN metric) must not poison the JSONL: bare
    NaN tokens are not RFC-8259 JSON and break non-Python consumers."""
    reg = MetricsRegistry()
    reg.gauge("metric.train").set(float("nan"))
    reg.histogram("step.train_s").observe(0.01)

    class FakeDecision(object):
        epoch_number = 1
        epoch_metrics = [None, float("nan"), 2.0]

    class FakeWorkflow(object):
        decision = FakeDecision()

    path = str(tmp_path / "nan_hb.jsonl")
    heartbeat = Heartbeat(path, interval=60, registry=reg,
                          workflow=FakeWorkflow())
    heartbeat.write_line()
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    record = json.loads(raw)
    validate_heartbeat(record)
    assert record["gauges"]["metric.train"] is None
    assert record["metrics"] == [None, None, 2.0]


def test_decision_never_publishes_nonfinite_metric_gauge():
    from veles_tpu.observe.metrics import registry as global_registry
    from veles_tpu.models.decision import DecisionGD
    from veles_tpu.dummy import DummyWorkflow

    global_registry.reset()
    decision = DecisionGD(DummyWorkflow(), watchdog=False)
    decision.class_lengths = [0, 0, 10]
    decision.epoch_n_err = [0, 0, float("nan")]
    decision._record_class_metric(2)  # TRAIN
    assert decision.epoch_metrics[2] != decision.epoch_metrics[2]  # NaN
    assert global_registry.peek("metric.train") is None
    decision.epoch_n_err = [0, 0, 2]
    decision._record_class_metric(2)
    assert global_registry.peek("metric.train").value == 20.0


def test_validate_heartbeat_rejects_malformed():
    with pytest.raises(ValueError):
        validate_heartbeat([])
    with pytest.raises(ValueError, match="missing"):
        validate_heartbeat({"kind": "heartbeat"})


# -- print_stats baseline-vs-cumulative semantics --------------------------


def _two_run_workflow():
    from veles_tpu.dummy import DummyUnit, DummyWorkflow
    wf = DummyWorkflow()
    unit = DummyUnit(wf)
    unit.name = "Worker"
    unit.link_from(wf.start_point)
    wf.end_point.link_from(unit)
    wf.initialize()
    return wf, unit


def test_print_stats_baseline_vs_cumulative_run_counts():
    wf, unit = _two_run_workflow()
    wf.run()
    wf.run()
    # distributed-method timers participate in the same delta logic
    wf.generate_data_for_master()

    def stats(**kwargs):
        buf = io.StringIO()
        wf.print_stats(out=buf, **kwargs)
        return buf.getvalue()

    per_run = stats()
    assert "(this run)" in per_run
    match = re.search(r"Worker \((\d+) runs\)", per_run)
    assert match and int(match.group(1)) == 1  # only the LAST run
    cumulative = stats(cumulative=True)
    assert "(this run)" not in cumulative
    match = re.search(r"Worker \((\d+) runs\)", cumulative)
    assert match and int(match.group(1)) == 2  # lifetime total
    assert "generate_data_for_master" in cumulative


def test_print_stats_method_timer_deltas_reset_per_run():
    wf, unit = _two_run_workflow()
    wf.generate_data_for_master()  # before any run: baseline-less
    wf.run()
    # nothing distributed happened DURING this run, so the per-run view
    # must not re-attribute the pre-run call
    buf = io.StringIO()
    wf.print_stats(out=buf)
    assert "generate_data_for_master" not in buf.getvalue()


# -- smoke: trace + heartbeat over a real fused workflow -------------------


def _trace_smoke_run(cpu_device, tmp_path, pipeline):
    """2-epoch fused run through the LAUNCHER with --trace semantics:
    returns (trace doc, heartbeat lines)."""
    from veles_tpu import prng
    from veles_tpu.launcher import Launcher
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from tests.test_models import BlobsLoader

    trace_path = str(tmp_path / "run_trace.json")
    hb_path = str(tmp_path / "run_hb.jsonl")
    prng.get().seed(321)
    launcher = Launcher(trace=trace_path, metrics_interval=0.05,
                        metrics_path=hb_path)
    StandardWorkflow(
        launcher,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=32, on_device=False,
            prng=RandomGenerator("observe", seed=11)),
        decision_config=dict(max_epochs=2),
    ).fuse(pipeline=pipeline)
    launcher.initialize(device=cpu_device)
    launcher.run()
    with open(trace_path) as fin:
        doc = json.load(fin)
    with open(hb_path) as fin:
        lines = [json.loads(line) for line in fin if line.strip()]
    return doc, lines


def test_smoke_trace_and_heartbeat_schema(cpu_device, tmp_path):
    registry.reset()
    doc, lines = _trace_smoke_run(cpu_device, tmp_path, pipeline=True)
    validate_trace(doc)  # Perfetto-loadable, spans nest per track
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    # unit-run spans, fused-step spans, prefetcher-stage spans
    assert "FusedTrainer" in names
    assert "fused.train_step" in names
    assert {"pipeline.fill", "pipeline.h2d", "pipeline.wait"} <= names
    assert any(name.endswith(".run") for name in names)  # workflow span
    # worker-thread stages live on their own track
    graph_tids = {e["tid"] for e in doc["traceEvents"]
                  if e["ph"] == "X" and e["name"] == "fused.train_step"}
    fill_tids = {e["tid"] for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["name"] == "pipeline.fill"}
    assert graph_tids and fill_tids and not (graph_tids & fill_tids)
    # heartbeat: at least the final line, every line schema-valid
    assert lines
    for record in lines:
        validate_heartbeat(record)
    final = lines[-1]
    assert final["counters"]["train.steps"] > 0
    assert final["counters"]["train.samples"] > 0
    assert final["histograms"]["step.train_s"]["count"] > 0
    assert final["epoch"] >= 2
    assert final["workflow"] == "StandardWorkflow"
    # health counters rode the decision's class-end sync into the line
    assert final["health"].get("skip_count") == 0


def test_tracing_disabled_leaves_no_events_in_step_path(cpu_device):
    """The acceptance check's cheap proxy for 'no added host syncs':
    with tracing off, a fused run records nothing into the global
    tracer and the instrumented sites never build event payloads."""
    from veles_tpu.observe.trace import tracer
    from tests.test_pipeline_input import _build_fused

    registry.reset()
    assert not tracer.enabled
    before = len(tracer.events)
    sw = _build_fused(cpu_device, pipeline=False, max_epochs=2)
    sw.run()
    assert len(tracer.events) == before
    # the metrics side still collected (always-on, plain-host floats)
    assert registry.counter("train.steps").value > 0
    snap = registry.histogram("step.train_s").snapshot()
    assert snap["count"] > 0 and snap["p50"] > 0.0
