"""Pipeline + expert parallelism vs sequential oracles (8-device CPU
mesh)."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.parallel.mesh import make_mesh
from veles_tpu.parallel.moe import (
    init_moe_params, moe_apply, moe_reference, shard_moe_params)
from veles_tpu.parallel.pipeline import (
    pipeline_forward, stack_stage_params, stage_param_sharding)


def _stage_fn(params, x):
    return jnp.tanh(jnp.dot(x, params["w"],
                            preferred_element_type=jnp.float32) +
                    params["b"]).astype(x.dtype)


def _stages(rng, n_stages, width):
    return [{"w": (rng.randn(width, width) * 0.3).astype(numpy.float32),
             "b": numpy.zeros(width, numpy.float32)}
            for _ in range(n_stages)]


@pytest.mark.parametrize("microbatches", [4, 8])
def test_pipeline_matches_sequential(microbatches):
    rng = numpy.random.RandomState(0)
    width, n_stages = 16, 8
    stages = _stages(rng, n_stages, width)
    x = rng.randn(32, width).astype(numpy.float32)

    want = x
    for s in stages:
        want = numpy.asarray(_stage_fn(s, want))

    mesh = make_mesh({"pipe": n_stages})
    stacked = stage_param_sharding(mesh, stack_stage_params(stages))
    got = numpy.asarray(pipeline_forward(
        _stage_fn, stacked, x, mesh, microbatches=microbatches))
    numpy.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    rng = numpy.random.RandomState(1)
    width, n_stages = 8, 4
    stages = _stages(rng, n_stages, width)
    x = rng.randn(16, width).astype(numpy.float32)
    mesh = make_mesh({"pipe": n_stages, "rest": 2})
    stacked = stack_stage_params(stages)

    def loss_pipe(params):
        return jnp.sum(pipeline_forward(
            _stage_fn, params, x, mesh, microbatches=4) ** 2)

    def loss_seq(params_list):
        h = x
        for i in range(n_stages):
            h = _stage_fn(jax.tree.map(lambda l: l[i], params_list), h)
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for key in ("w", "b"):
        numpy.testing.assert_allclose(
            numpy.asarray(g_pipe[key]), numpy.asarray(g_seq[key]),
            rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("top_k", [1, 2, 8])
def test_moe_matches_reference(top_k):
    rng = numpy.random.RandomState(2)
    params = init_moe_params(rng, n_experts=8, features=12, hidden=16,
                             out_features=6)
    x = rng.randn(10, 12).astype(numpy.float32)
    want = numpy.asarray(moe_reference(params, x, top_k=top_k))
    mesh = make_mesh({"expert": 8})
    sharded = shard_moe_params(mesh, params)
    got = numpy.asarray(moe_apply(sharded, x, mesh, top_k=top_k))
    numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_pipeline_data_axis_shards_batch():
    """dp x pp: each data row runs its own wavefront; result matches
    the sequential oracle (the layout the 64-device dryrun runs)."""
    rng = numpy.random.RandomState(5)
    width, n_stages = 16, 4
    stages = _stages(rng, n_stages, width)
    x = rng.randn(16, width).astype(numpy.float32)
    want = x
    for s in stages:
        want = numpy.asarray(_stage_fn(s, want))
    mesh = make_mesh({"data": 2, "pipe": n_stages})
    stacked = stage_param_sharding(mesh, stack_stage_params(stages))
    got = numpy.asarray(pipeline_forward(
        _stage_fn, stacked, x, mesh, microbatches=4,
        data_axis="data"))
    numpy.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_moe_data_axis_shards_tokens():
    """dp x ep: tokens shard over data, combine psums over expert
    only; exact vs the oracle."""
    rng = numpy.random.RandomState(6)
    params = init_moe_params(rng, n_experts=4, features=8, hidden=8,
                             out_features=8)
    x = rng.randn(16, 8).astype(numpy.float32)
    want = numpy.asarray(moe_reference(params, x, top_k=2))
    mesh = make_mesh({"data": 2, "expert": 4})
    sharded = shard_moe_params(mesh, params)
    got = numpy.asarray(moe_apply(sharded, x, mesh, top_k=2,
                                  data_axis="data"))
    numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_moe_composes_with_dp_mesh():
    rng = numpy.random.RandomState(3)
    params = init_moe_params(rng, n_experts=4, features=8, hidden=8,
                             out_features=8)
    x = rng.randn(16, 8).astype(numpy.float32)
    want = numpy.asarray(moe_reference(params, x, top_k=2))
    mesh = make_mesh({"data": 2, "expert": 4})
    sharded = shard_moe_params(mesh, params)
    got = numpy.asarray(moe_apply(sharded, x, mesh, top_k=2))
    numpy.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
