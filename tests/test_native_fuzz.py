"""Fuzz the native package loader (tar + json + npy) with mutated
packages: every hostile input must surface as a clean Python exception
from the C API (capi.cc catches std::exception), never a crash.

Reference robustness surface: libVeles WorkflowLoader::Load consumed
forge-fetched archives (workflow_loader.cc:41); this build's loader
reads the same roles (tar member table, contents.json schema, npy
payloads) and a malformed package can arrive through the forge fetch
path here too.
"""

import io
import json
import struct
import tarfile

import numpy
import pytest


@pytest.fixture(scope="module")
def native():
    from veles_tpu import native as native_mod
    try:
        native_mod.build_native()
    except Exception as exc:
        pytest.skip("native build unavailable: %s" % exc)
    return native_mod


def _npy_bytes(arr):
    buf = io.BytesIO()
    numpy.save(buf, arr)
    return buf.getvalue()


def _make_package(path, contents, members):
    """Write a tar with contents.json + named npy members."""
    with tarfile.open(path, "w") as tout:
        payload = json.dumps(contents).encode()
        info = tarfile.TarInfo("contents.json")
        info.size = len(payload)
        tout.addfile(info, io.BytesIO(payload))
        for name, data in members.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tout.addfile(info, io.BytesIO(data))


def _unit(name, uuid, inputs, weights, bias, out_shape,
          wname, bname):
    return {
        "class": name, "name": name, "uuid": uuid,
        "inputs": inputs,
        "arrays": {"weights": wname, "bias": bname},
        "properties": {"include_bias": True,
                       "output_sample_shape": [out_shape]},
    }


UUID_TANH = "5a51b268-0002-4000-8000-76656c6573aa"
UUID_SOFTMAX = "5a51b268-0006-4000-8000-76656c6573aa"


def _valid_contents():
    return {
        "format": 2, "input_shape": [16], "precision": "float32",
        "units": [
            _unit("A", UUID_TANH, ["__input__"], 16, 8, 8,
                  "w0.npy", "b0.npy"),
            _unit("B", UUID_SOFTMAX, ["A"], 8, 4, 4,
                  "w1.npy", "b1.npy"),
        ],
    }


def _valid_members():
    rng = numpy.random.RandomState(0)
    return {
        "w0.npy": _npy_bytes(rng.rand(16, 8).astype(numpy.float32)),
        "b0.npy": _npy_bytes(numpy.zeros(8, numpy.float32)),
        "w1.npy": _npy_bytes(rng.rand(8, 4).astype(numpy.float32)),
        "b1.npy": _npy_bytes(numpy.zeros(4, numpy.float32)),
    }


def test_valid_baseline_package_loads(tmp_path, native):
    """The hand-built package the mutations start from must load and
    run — otherwise the fuzz cases prove nothing."""
    pkg = str(tmp_path / "ok.tar")
    _make_package(pkg, _valid_contents(), _valid_members())
    wf = native.NativeWorkflow(pkg)
    out = wf.run(numpy.random.RandomState(1).rand(3, 16))
    assert out.shape == (3, 4)
    assert numpy.allclose(out.sum(axis=1), 1.0, atol=1e-4)


def _schema_mutations():
    """name -> mutate(contents_dict) for hostile contents.json."""
    def m(fn):
        def wrap(c):
            fn(c)
            return c
        return wrap

    return {
        "units_not_array": m(lambda c: c.update(units={})),
        "no_units": m(lambda c: c.update(units=[])),
        "unknown_uuid": m(lambda c: c["units"][0].update(
            uuid="00000000-dead-4000-8000-000000000000")),
        "missing_uuid": m(lambda c: c["units"][0].pop("uuid")),
        "missing_properties": m(
            lambda c: c["units"][0].pop("properties")),
        "duplicate_names": m(
            lambda c: c["units"][1].update(name="A")),
        "cycle": m(lambda c: c["units"][0].update(inputs=["B"])),
        "unknown_input": m(
            lambda c: c["units"][1].update(inputs=["nope"])),
        "multiple_outputs": m(
            lambda c: c["units"][1].update(inputs=["__input__"])),
        "missing_array_member": m(
            lambda c: c["units"][0]["arrays"].update(
                weights="missing.npy")),
        "huge_output_shape": m(
            lambda c: c["units"][0]["properties"].update(
                output_sample_shape=[1 << 40])),
        "negative_output_shape": m(
            lambda c: c["units"][0]["properties"].update(
                output_sample_shape=[-8])),
        "input_shape_string": m(
            lambda c: c.update(input_shape="wide")),
    }


@pytest.mark.parametrize("name", sorted(_schema_mutations()))
def test_hostile_contents_schema(tmp_path, native, name):
    contents = _valid_contents()
    _schema_mutations()[name](contents)
    pkg = str(tmp_path / (name + ".tar"))
    _make_package(pkg, contents, _valid_members())
    try:
        wf = native.NativeWorkflow(pkg)
        # a mutation the loader tolerates must still run bounded and
        # cleanly (huge shapes may legitimately fail at arena time)
        wf.run(numpy.random.RandomState(1).rand(2, 16))
    except (RuntimeError, ValueError, MemoryError):
        pass


_RAW_JSON = {
    "not_json": b"definitely not json",
    "truncated": b'{"units": [',
    "trailing_garbage": b'{"units": []} extra',
    "unterminated_string": b'{"units": ["abc',
    "bad_escape": b'{"units": ["\\',
    "deep_nesting": b"[" * 5000,
    "deep_object_nesting": b'{"a":' * 5000,
    "empty": b"",
}


@pytest.mark.parametrize("name", sorted(_RAW_JSON))
def test_hostile_raw_json(tmp_path, native, name):
    """Raw malformed contents.json — including 5000-deep nesting that
    must hit the parser's depth cap, not the C stack."""
    pkg = str(tmp_path / (name + ".tar"))
    with tarfile.open(pkg, "w") as tout:
        info = tarfile.TarInfo("contents.json")
        info.size = len(_RAW_JSON[name])
        tout.addfile(info, io.BytesIO(_RAW_JSON[name]))
    with pytest.raises(RuntimeError):
        native.NativeWorkflow(pkg)


def test_hostile_tar_structures(tmp_path, native):
    """Malformed archives at the tar layer."""
    cases = {}

    cases["empty_file"] = b""
    cases["end_marker_only"] = b"\0" * 1024
    cases["truncated_header"] = b"x" * 100

    # size field claims 8 GB (larger than the archive)
    block = bytearray(512)
    block[0:12] = b"contents.jso"
    block[124:136] = b"77777777777\0"  # octal size
    block[156] = ord("0")
    cases["oversized_member"] = bytes(block)

    # size says 1000 but the file ends after the header
    block2 = bytearray(512)
    block2[0:12] = b"contents.jso"
    block2[124:136] = b"00000001750\0"  # 1000 octal
    block2[156] = ord("0")
    cases["truncated_member"] = bytes(block2)

    # non-octal size field
    block3 = bytearray(512)
    block3[0:8] = b"cont.txt"
    block3[124:136] = b"zzzzzzzzzzz\0"
    block3[156] = ord("0")
    cases["garbage_size_field"] = bytes(block3)

    for name, payload in cases.items():
        path = str(tmp_path / (name + ".tar"))
        with open(path, "wb") as fout:
            fout.write(payload)
        with pytest.raises(RuntimeError):
            native.NativeWorkflow(path)


def test_hostile_npy_members(tmp_path, native):
    """npy-layer mutations beyond the existing header-length case."""
    mutations = {
        "bad_magic": lambda d: b"\x00NOPE" + d[5:],
        "truncated_payload": lambda d: d[: len(d) // 2],
        "object_dtype": lambda d: d.replace(b"<f4", b"|O8"),
        "header_len_overrun": lambda d: (
            d[:8] + struct.pack("<H", 0xFFFF) + d[10:]),
    }
    for name, mutate in mutations.items():
        members = _valid_members()
        members["w0.npy"] = mutate(members["w0.npy"])
        pkg = str(tmp_path / (name + ".tar"))
        _make_package(pkg, _valid_contents(), members)
        with pytest.raises(RuntimeError):
            native.NativeWorkflow(pkg)


def test_random_byte_flips_never_crash(tmp_path, native):
    """20 random single-byte corruptions of a valid package: each must
    either still load+run or raise cleanly."""
    pkg = str(tmp_path / "base.tar")
    _make_package(pkg, _valid_contents(), _valid_members())
    base = open(pkg, "rb").read()
    rng = numpy.random.RandomState(42)
    survived, rejected = 0, 0
    for i in range(20):
        data = bytearray(base)
        pos = int(rng.randint(0, len(data)))
        data[pos] ^= int(rng.randint(1, 256))
        path = str(tmp_path / ("flip%02d.tar" % i))
        with open(path, "wb") as fout:
            fout.write(bytes(data))
        try:
            wf = native.NativeWorkflow(path)
            out = wf.run(numpy.random.RandomState(1).rand(2, 16))
            assert out.shape[0] == 2
            survived += 1
        except (RuntimeError, ValueError, MemoryError):
            rejected += 1
    assert survived + rejected == 20
