"""Control-plane job farming (veles_tpu.jobfarm): the task-parallel
plane the reference drove through its master-slave protocol for
genetics evaluations and ensemble member training (reference:
ensemble/base_workflow.py:135-153,
genetics/optimization_workflow.py:186-221)."""

import threading
import time

import pytest

from veles_tpu.jobfarm import (FarmJobError, JobFarm, _FarmMaster,
                               _UNSET)
from veles_tpu.server import SlaveDescription


def test_farm_two_local_slaves_all_results_in_order():
    seen = []
    lock = threading.Lock()

    def runner(spec):
        with lock:
            seen.append(spec)
        return spec * spec

    results = JobFarm("sq").run(range(10), runner=runner,
                                local_slaves=2, timeout=60)
    assert results == [i * i for i in range(10)]
    assert sorted(set(seen)) == list(range(10))


def test_farm_runner_error_fails_loudly():
    def runner(spec):
        if spec == 3:
            raise ValueError("boom")
        return spec

    with pytest.raises(FarmJobError, match=r"job 3.*boom"):
        JobFarm("errs").run(range(5), runner=runner,
                            local_slaves=2, timeout=60)


def test_farm_remote_style_worker_joins():
    """No local slaves: a worker connects the way a remote host would
    (same tag, address learned from the bound server)."""
    def start_worker(server):
        threading.Thread(
            target=JobFarm("remote").worker,
            args=("127.0.0.1:%d" % server.port, lambda s: s + 1),
            daemon=True).start()

    results = JobFarm("remote").run(
        range(6), on_listening=start_worker, timeout=60)
    assert results == [1, 2, 3, 4, 5, 6]


def test_farm_persistent_batches_reuse_workers():
    """start/submit/submit/shutdown: one server, several batches —
    the GA-per-generation pattern."""
    farm = JobFarm("persist").start(runner=lambda s: s * 2,
                                    local_slaves=2)
    try:
        assert farm.submit(range(5), timeout=60) == [0, 2, 4, 6, 8]
        assert farm.submit(range(3), timeout=60) == [0, 2, 4]
        assert farm.submit([], timeout=60) == []
    finally:
        farm.shutdown()


def test_remote_worker_survives_between_batches():
    """A remote-style worker connected once must serve EVERY batch
    (round-4 verdict: a server torn down per generation silently
    lost all remote capacity after generation 0)."""
    farm = JobFarm("persist2").start()
    jobs_done = {}

    def work():
        jobs_done["n"] = JobFarm("persist2").worker(
            farm.address, lambda s: s + 1)

    thread = threading.Thread(target=work, daemon=True)
    thread.start()
    try:
        assert farm.submit(range(4), timeout=60) == [1, 2, 3, 4]
        assert farm.submit(range(4, 8), timeout=60) == [5, 6, 7, 8]
    finally:
        farm.shutdown()
    thread.join(10)
    assert jobs_done["n"] == 8


def test_watchdog_speculation_rescues_wedged_job():
    """Clients park passively (no wait-poll), so the straggler
    threshold must be re-evaluated by the server's watchdog tick:
    a wedged job's backup copy reaches the parked idle worker with
    NO update traffic to trigger a release."""
    calls = {"slow": 0}
    lock = threading.Lock()
    wedge = threading.Event()

    def runner(spec):
        if spec == "slow":
            with lock:
                calls["slow"] += 1
                first = calls["slow"] == 1
            if first:
                wedge.wait(30)  # wedged until the test releases it
            return "rescued"
        return spec

    farm = JobFarm("wedge", speculation_factor=1.0,
                   min_speculation_s=0.6).start(runner=runner,
                                                local_slaves=2)
    try:
        res = farm.submit(["a", "b", "slow"], timeout=15)
    finally:
        wedge.set()
        farm.shutdown()
    assert res == ["a", "b", "rescued"]
    assert calls["slow"] == 2  # the backup copy actually ran


def test_farm_timeout_reports_unfinished():
    with pytest.raises(FarmJobError, match="2/2 jobs unfinished"):
        JobFarm("idle").run([1, 2], timeout=0.5)  # nobody works


def test_farm_bind_failure_raises_instead_of_hanging():
    farm = JobFarm("bind1").start()
    try:
        with pytest.raises(RuntimeError, match="failed to bind"):
            JobFarm("bind2").start(
                address="127.0.0.1:%d" % farm.server.port)
    finally:
        farm.shutdown()


def _slave(sid):
    return SlaveDescription(sid, "mid", 0, 1.0)


def _master(jobs, **kwargs):
    m = _FarmMaster("c", **kwargs)
    m.reset(jobs)
    return m


def test_master_speculates_only_past_straggler_threshold():
    m = _master(["a", "b"], speculation_factor=2.0,
                min_speculation_s=2.0)
    e = m.epoch
    s1, s2 = _slave("s1"), _slave("s2")
    assert m.generate_data_for_slave(s1) == (e, 0, "a")
    assert m.generate_data_for_slave(s2) == (e, 1, "b")
    m.apply_data_from_slave((e, 1, ("ok", "B")), s2)
    # completed durations exist but job 0 only just started: a fresh
    # job is NOT re-issued...
    m._durations.clear()
    m._durations.append(1.0)
    assert m.generate_data_for_slave(s2) is False
    # ...but once it straggles past the threshold, an idle slave
    # shadows it (backup task)
    m._outstanding[0][s1.id] = time.perf_counter() - 100.0
    assert m.generate_data_for_slave(s2) == (e, 0, "a")
    # never a second copy for the same slave
    assert m.generate_data_for_slave(s2) is False
    # first result wins; the straggler's late duplicate is ignored
    m.apply_data_from_slave((e, 0, ("ok", "from_s2")), s2)
    assert m.done.is_set()
    m.apply_data_from_slave((e, 0, ("ok", "late")), s1)
    assert m.results == [("ok", "from_s2"), ("ok", "B")]


def test_master_ignores_stale_epoch_updates():
    """A duplicate surviving from a PREVIOUS batch must not land in
    the current batch's slot (measured failure mode: a six-batch-old
    result surfacing in a later submit)."""
    m = _master(["a"])
    s1 = _slave("s1")
    old = m.epoch
    assert m.generate_data_for_slave(s1) == (old, 0, "a")
    m.apply_data_from_slave((old, 0, ("ok", "old")), s1)
    m.reset(["a2"])
    assert m.generate_data_for_slave(s1) == (old + 1, 0, "a2")
    # the late duplicate from the previous epoch is dropped
    m.apply_data_from_slave((old, 0, ("ok", "stale")), s1)
    assert not m.done.is_set()
    assert m.results == [_UNSET]
    m.apply_data_from_slave((old + 1, 0, ("ok", "fresh")), s1)
    assert m.results == [("ok", "fresh")]


def test_master_never_speculates_without_completed_durations():
    m = _master(["a", "b"])
    e = m.epoch
    s1, s2 = _slave("s1"), _slave("s2")
    m.generate_data_for_slave(s1)
    m._outstanding[0][s1.id] = time.perf_counter() - 1e6  # ancient straggler
    # no completed job yet -> no credible mean -> no backup copies
    assert m.generate_data_for_slave(s2) == (e, 1, "b")
    assert m.generate_data_for_slave(s2) is False


def test_master_requeues_when_every_copy_dies():
    m = _master(["a"])
    e = m.epoch
    s1, s2 = _slave("s1"), _slave("s2")
    assert m.generate_data_for_slave(s1) == (e, 0, "a")
    m.drop_slave(s1)
    assert not m.done.is_set()
    # the orphaned job is served again to the next requester
    assert m.generate_data_for_slave(s2) == (e, 0, "a")
    m.apply_data_from_slave((e, 0, ("ok", 1)), s2)
    assert m.done.is_set()


def test_master_keeps_job_with_surviving_backup():
    m = _master(["a"], speculation_factor=2.0, min_speculation_s=2.0)
    e = m.epoch
    s1, s2 = _slave("s1"), _slave("s2")
    m.generate_data_for_slave(s1)
    m._durations.append(0.001)
    m._outstanding[0][s1.id] = time.perf_counter() - 100.0
    assert m.generate_data_for_slave(s2) == (e, 0, "a")  # backup copy
    m.drop_slave(s1)
    # not requeued: s2 still runs its copy
    assert not m._pending
    m.apply_data_from_slave((e, 0, ("ok", 1)), s2)
    assert m.done.is_set()
