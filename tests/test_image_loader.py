"""Image loader tests on REAL image files (reference test model:
golden-artifact loader tests, SURVEY section 4): PNGs written to disk,
cv2 read/augment path, distortion composition, MSE pairs, and the
distributed minibatch contract over image data."""

import numpy
import pytest

cv2 = pytest.importorskip("cv2")

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader.image import (
    FileImageLoader, FileImageLoaderMSE, FullBatchImageLoader,
    FullBatchImageLoaderMSE, ImageAugmentation, distortion_stages,
    scan_image_tree)
from veles_tpu.prng import RandomGenerator


def _write_tree(base, classes=("circle", "square"), per_class=6,
                size=16):
    """Writes a directory-per-class tree of real PNGs; returns base."""
    rng = numpy.random.RandomState(0)
    for ci, cls in enumerate(classes):
        cdir = base / cls
        cdir.mkdir(parents=True, exist_ok=True)
        for i in range(per_class):
            img = (rng.rand(size, size, 3) * 60).astype(numpy.uint8)
            if cls == "circle":
                cv2.circle(img, (size // 2, size // 2), size // 3,
                           (255, 255, 255), -1)
            else:
                cv2.rectangle(img, (3, 3), (size - 4, size - 4),
                              (255, 255, 255), -1)
            assert cv2.imwrite(str(cdir / ("img%02d.png" % i)), img)
    return base


def test_scan_and_file_loader_real_pngs(tmp_path, cpu_device):
    train = _write_tree(tmp_path / "train")
    valid = _write_tree(tmp_path / "valid", per_class=2)
    assert len(scan_image_tree(str(train))) == 12

    wf = DummyWorkflow()
    loader = FileImageLoader(
        wf.workflow, train_dir=str(train), validation_dir=str(valid),
        minibatch_size=4, prng=RandomGenerator("img1", seed=1))
    loader.initialize(device=cpu_device)
    assert loader.class_lengths[1] == 4
    assert loader.class_lengths[2] == 12
    assert loader.shape == (16, 16, 3)
    assert sorted(loader.labels_mapping) == ["circle", "square"]
    # data really came from the PNGs: bright object pixels present
    loader.original_data.map_read()
    assert loader.original_data.mem.max() > 0.9


def test_augmentation_path_real_files(tmp_path, cpu_device):
    train = _write_tree(tmp_path / "train", size=24)
    wf = DummyWorkflow()
    aug = ImageAugmentation(scale=(12, 12), color_space="GRAY",
                            prng=RandomGenerator("aug", seed=2))
    loader = FileImageLoader(
        wf.workflow, train_dir=str(train), augmentation=aug,
        minibatch_size=4, prng=RandomGenerator("img2", seed=1))
    loader.initialize(device=cpu_device)
    # grayscale + resized through the real cv2 pipeline
    assert loader.shape == (12, 12, 1)


def test_distortion_composition_inflates_train(tmp_path, cpu_device):
    """mirror + rotations materialize every combination for TRAIN
    (reference DistortionIterator, fullbatch_image.py:63-80)."""
    train = _write_tree(tmp_path / "train", per_class=3)
    valid = _write_tree(tmp_path / "valid", per_class=2)
    assert distortion_stages(True, (0, 15)) == [
        (False, 0), (True, 0), (False, 15), (True, 15)]

    wf = DummyWorkflow()
    loader = FileImageLoader(
        wf.workflow, train_dir=str(train), validation_dir=str(valid),
        mirror=True, rotations=(0, 15), minibatch_size=4,
        prng=RandomGenerator("img3", seed=1))
    assert loader.samples_inflation == 4
    loader.initialize(device=cpu_device)
    assert loader.class_lengths[2] == 6 * 4   # train inflated
    assert loader.class_lengths[1] == 4       # validation untouched
    # mirrored copy differs from the original but shares its label
    loader.original_data.map_read()
    base = loader.original_data.mem[4]
    mirrored = loader.original_data.mem[5]
    numpy.testing.assert_allclose(base[:, ::-1], mirrored, atol=1e-6)


def test_colorspace_matches_cv2_oracle():
    """The numpy conversions follow cv2's conventions exactly, so
    either backend yields interchangeable tensors."""
    from veles_tpu.loader import colorspace

    rng = numpy.random.RandomState(3)
    u8 = (rng.rand(9, 11, 3) * 255).astype(numpy.uint8)
    for dst, code in (("RGB", cv2.COLOR_BGR2RGB),
                      ("GRAY", cv2.COLOR_BGR2GRAY),
                      ("YCR_CB", cv2.COLOR_BGR2YCrCb)):
        ours = colorspace.convert(u8, "BGR", dst)
        want = cv2.cvtColor(u8, code)
        assert ours.dtype == numpy.uint8
        assert ours.shape == want.shape
        diff = numpy.abs(ours.astype(int) - want.astype(int))
        assert diff.max() <= 1, (dst, diff.max())
    # HSV: hue is circular (0 == 180 in uint8 encoding)
    ours = colorspace.convert(u8, "BGR", "HSV")
    want = cv2.cvtColor(u8, cv2.COLOR_BGR2HSV)
    dh = numpy.abs(ours[..., 0].astype(int) - want[..., 0].astype(int))
    assert numpy.minimum(dh, 180 - dh).max() <= 1
    assert numpy.abs(ours[..., 1:].astype(int)
                     - want[..., 1:].astype(int)).max() <= 1
    # float path round-trips through every 3-channel space
    f32 = rng.rand(7, 5, 3).astype(numpy.float32)
    for space in ("HSV", "YCR_CB", "BGR"):
        there = colorspace.convert(f32, "RGB", space)
        back = colorspace.convert(there, space, "RGB")
        numpy.testing.assert_allclose(back, f32, atol=1e-5)
    # the hub makes indirect pairs work too (no direct cv2 code)
    gray_hsv = colorspace.convert(
        (rng.rand(4, 4) * 255).astype(numpy.uint8), "GRAY", "HSV")
    assert gray_hsv.shape == (4, 4, 3)
    assert (gray_hsv[..., 1] == 0).all()  # gray pixels have S == 0


def test_loader_color_tree_roundtrips_in_two_spaces(tmp_path,
                                                    cpu_device):
    """The same color tree loaded in two color spaces (reference
    loader/image.py:111-125 color_space kwarg): converting the HSV
    tensors back to RGB reproduces the RGB load."""
    from veles_tpu.loader import colorspace

    train = _write_tree(tmp_path / "train")

    def load(space):
        wf = DummyWorkflow()
        loader = FileImageLoader(
            wf.workflow, train_dir=str(train), color_space=space,
            minibatch_size=4,
            prng=RandomGenerator("col_%s" % space, seed=1))
        loader.initialize(device=cpu_device)
        loader.original_data.map_read()
        return loader.original_data.mem.copy()

    rgb = load("RGB")
    hsv = load("HSV")
    assert rgb.shape == hsv.shape
    assert not numpy.allclose(rgb, hsv)  # genuinely different spaces
    # loaders store uint8/255; undo that, convert HSV -> RGB, compare
    # (uint8 HSV quantizes hue to 2-degree steps -> small tolerance)
    for i in range(len(rgb)):
        back = colorspace.convert(
            (hsv[i] * 255).round().astype(numpy.uint8), "HSV", "RGB")
        numpy.testing.assert_allclose(
            back / 255.0, rgb[i], atol=0.04)


def test_image_mse_class_targets(tmp_path, cpu_device):
    """class_target_paths: one target image per label (the reference's
    class_targets mapping, fullbatch_image.py:200-222)."""
    train = _write_tree(tmp_path / "train")
    targets = tmp_path / "targets"
    targets.mkdir()
    for name, value in (("circle", 200), ("square", 60)):
        img = numpy.full((16, 16, 3), value, numpy.uint8)
        assert cv2.imwrite(str(targets / ("%s.png" % name)), img)

    wf = DummyWorkflow()
    loader = FullBatchImageLoaderMSE(
        wf.workflow,
        train_paths=scan_image_tree(str(train)),
        class_target_paths={
            "circle": str(targets / "circle.png"),
            "square": str(targets / "square.png")},
        minibatch_size=4, prng=RandomGenerator("img4", seed=1))
    loader.initialize(device=cpu_device)
    loader.original_targets.map_read()
    assert loader.original_targets.shape == (12, 16, 16, 3)
    # first train sample is class "circle" -> its target is the
    # uniform 200/255 image
    idx = loader.original_labels.index("circle")
    numpy.testing.assert_allclose(
        loader.original_targets.mem[idx],
        numpy.full((16, 16, 3), 200 / 255.0), atol=1e-2)


def test_image_mse_per_sample_targets(tmp_path, cpu_device):
    """target_dir: one target per source basename (reference
    image_mse.py:129-158), pairs aligned through distortion."""
    train = _write_tree(tmp_path / "train", classes=("circle",),
                        per_class=4)
    tdir = tmp_path / "targets"
    tdir.mkdir()
    for path, _label in scan_image_tree(str(train)):
        img = 255 - cv2.imread(path)  # target = inverted input
        import os
        assert cv2.imwrite(str(tdir / os.path.basename(path)), img)

    wf = DummyWorkflow()
    loader = FileImageLoaderMSE(
        wf.workflow, train_dir=str(train), target_dir=str(tdir),
        mirror=True, minibatch_size=2,
        prng=RandomGenerator("img5", seed=1))
    loader.initialize(device=cpu_device)
    loader.original_data.map_read()
    loader.original_targets.map_read()
    assert (loader.original_targets.shape ==
            loader.original_data.shape)
    # inversion holds for every (possibly mirrored) pair
    numpy.testing.assert_allclose(
        loader.original_targets.mem,
        1.0 - loader.original_data.mem, atol=2e-2)


def test_distributed_contract_over_images(tmp_path, cpu_device):
    """Master/slave minibatch farming over a real-file image loader
    (VERDICT round-1 weak #6)."""
    import time

    from veles_tpu.client import Client
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from tests.test_network import _start_server

    def build(mode, key):
        train = _write_tree(tmp_path / ("train_%s" % key))
        valid = _write_tree(tmp_path / ("valid_%s" % key), per_class=2)
        wf = DummyWorkflow()
        wf.workflow.workflow_mode = mode
        sw = StandardWorkflow(
            wf.workflow,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader_factory=lambda w: FileImageLoader(
                w, train_dir=str(train), validation_dir=str(valid),
                minibatch_size=4,
                prng=RandomGenerator("imgnet_%s" % key, seed=2)),
            decision_config=dict(max_epochs=2),
        )
        sw.initialize(device=cpu_device)
        return sw

    master = build("master", "m")
    slave = build("slave", "s")
    server, _ = _start_server(master)
    client = Client("127.0.0.1:%d" % server.port, slave)
    client.run()
    server._done.wait(10)
    assert client.jobs_done > 0
    assert bool(master.decision.complete)
    assert master.decision.epoch_metrics[1] is not None
