"""CLI tests (reference test model: veles/tests/test_velescli.py):
full run via the module protocol, dump-graph, overrides, --optimize."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

_WF = textwrap.dedent('''
    import numpy
    from veles_tpu.loader import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.config import root


    class CliBlobs(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 32, 96]
            self._calc_class_end_offsets()
            self.create_originals((8,))
            rng = numpy.random.RandomState(1)
            centers = rng.randn(3, 8) * 2
            for i in range(self.total_samples):
                label = i % 3
                self.original_data.mem[i] = (
                    centers[label] + rng.randn(8) * 0.2)
                self.original_labels[i] = label


    def build(launcher):
        return StandardWorkflow(
            launcher,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 3,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader_factory=lambda w: CliBlobs(
                w, minibatch_size=32,
                prng=RandomGenerator("cli", seed=4)),
            decision_config=dict(
                max_epochs=root.cli_test.get("max_epochs", 2)),
            result_file=root.common.get("result_file"),
        )


    def run(load, main):
        wf, snapshotted = load(build)
        main(device="cpu")


    # --optimize hooks
    def tunable_spec():
        from veles_tpu.genetics import Tune
        return {"x": Tune(0.0, -1.0, 1.0)}


    def fitness(spec):
        return -(spec["x"] - 0.5) ** 2
''')


@pytest.fixture(scope="module")
def wf_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("cli") / "cli_workflow.py"
    path.write_text(_WF)
    return str(path)


def _run_cli(*args, timeout=240):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", VELES_BACKEND="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + list(args),
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd="/root/repo")


def test_cli_trains_workflow(wf_file, tmp_path):
    result_file = str(tmp_path / "results.json")
    proc = _run_cli(wf_file, "-", "-d", "cpu",
                    "--result-file", result_file,
                    "root.cli_test.max_epochs=2")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.exists(result_file)


def test_cli_dump_graph(wf_file, tmp_path):
    dot = str(tmp_path / "graph.dot")
    proc = _run_cli(wf_file, "-", "--dump-graph", dot)
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = open(dot).read()
    assert "digraph" in text and "CliBlobs" in text


def test_cli_optimize(wf_file, tmp_path):
    result_file = str(tmp_path / "opt.json")
    proc = _run_cli(wf_file, "-", "--optimize", "4:10",
                    "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    best = json.load(open(result_file))
    assert abs(best["spec"]["x"] - 0.5) < 0.3
