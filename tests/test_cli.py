"""CLI tests (reference test model: veles/tests/test_velescli.py):
full run via the module protocol, dump-graph, overrides, --optimize."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

_WF = textwrap.dedent('''
    import numpy
    from veles_tpu.loader import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from veles_tpu.config import root


    class CliBlobs(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 32, 96]
            self._calc_class_end_offsets()
            self.create_originals((8,))
            rng = numpy.random.RandomState(1)
            centers = rng.randn(3, 8) * 2
            for i in range(self.total_samples):
                label = i % 3
                self.original_data.mem[i] = (
                    centers[label] + rng.randn(8) * 0.2)
                self.original_labels[i] = label


    def build(launcher):
        return StandardWorkflow(
            launcher,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 3,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader_factory=lambda w: CliBlobs(
                w, minibatch_size=32,
                prng=RandomGenerator("cli", seed=4)),
            decision_config=dict(
                max_epochs=root.cli_test.get("max_epochs", 2)),
            result_file=root.common.get("result_file"),
        )


    def run(load, main):
        wf, snapshotted = load(build)
        main(device="cpu")


    # --optimize hooks
    def tunable_spec():
        from veles_tpu.genetics import Tune
        return {"x": Tune(0.0, -1.0, 1.0)}


    def fitness(spec):
        return -(spec["x"] - 0.5) ** 2


    # --ensemble-train / --ensemble-test hooks
    def member_factory(member, seed):
        from veles_tpu.dummy import DummyWorkflow
        wf = DummyWorkflow()
        return build(wf.workflow)


    def ensemble_test_data():
        from veles_tpu.dummy import DummyWorkflow
        wf = DummyWorkflow()
        loader = CliBlobs(wf, minibatch_size=32,
                          prng=RandomGenerator("etd", seed=9))
        loader.initialize(device=None)
        x = loader.original_data.mem[:32]
        labels = numpy.array(
            [loader.labels_mapping[loader.original_labels[i]]
             for i in range(32)])
        return x, labels
''')


@pytest.fixture(scope="module")
def wf_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("cli") / "cli_workflow.py"
    path.write_text(_WF)
    return str(path)


def _run_cli(*args, timeout=240):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", VELES_BACKEND="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + list(args),
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd="/root/repo")


def test_cli_trains_workflow(wf_file, tmp_path):
    result_file = str(tmp_path / "results.json")
    proc = _run_cli(wf_file, "-", "-d", "cpu",
                    "--result-file", result_file,
                    "root.cli_test.max_epochs=2")
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.exists(result_file)


def test_cli_dump_graph(wf_file, tmp_path):
    dot = str(tmp_path / "graph.dot")
    proc = _run_cli(wf_file, "-", "--dump-graph", dot)
    assert proc.returncode == 0, proc.stderr[-2000:]
    text = open(dot).read()
    assert "digraph" in text and "CliBlobs" in text


def test_cli_ensemble_train_then_farmed_test(wf_file, tmp_path):
    """--ensemble-train then --ensemble-test with farmed member
    evaluation through the CLI (the reference's two-phase ensemble
    flow, cmdline.py:182-204)."""
    ens_dir = str(tmp_path / "ens")
    proc = _run_cli(wf_file, "-", "--ensemble-train", "2",
                    "--ensemble-dir", ens_dir,
                    "root.cli_test.max_epochs=2")
    assert proc.returncode == 0, proc.stderr[-2000:]
    results = os.path.join(ens_dir, "ensemble.json")
    assert os.path.exists(results)

    result_file = str(tmp_path / "enstest.json")
    proc = _run_cli(wf_file, "-", "--ensemble-test", results,
                    "--farm-slaves", "2",
                    "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ensemble error rate" in proc.stdout
    report = json.load(open(result_file))
    assert report["members"] == 2
    assert 0.0 <= report["ensemble_error_pct"] <= 100.0


def test_cli_optimize(wf_file, tmp_path):
    result_file = str(tmp_path / "opt.json")
    proc = _run_cli(wf_file, "-", "--optimize", "4:10",
                    "--result-file", result_file)
    assert proc.returncode == 0, proc.stderr[-2000:]
    best = json.load(open(result_file))
    assert abs(best["spec"]["x"] - 0.5) < 0.3


def test_callable_module_notebook_style(cpu_device):
    """import veles_tpu; veles_tpu(WorkflowCls, config) drives a full
    training run in-process (reference veles/__init__.py:126,142)."""
    import veles_tpu
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator
    from tests.test_models import BlobsLoader

    wf = veles_tpu(
        StandardWorkflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("callmod", seed=3)),
        decision_config=dict(max_epochs=3),
        device="cpu",
    )
    assert bool(wf.decision.complete)
    assert wf.decision.epoch_metrics[1] is not None


def test_plugin_discovery(tmp_path):
    """Packages with a .veles_tpu marker import + register their units
    (reference veles/__init__.py:294-306)."""
    import sys
    import textwrap

    import veles_tpu
    from veles_tpu.units import UnitRegistry

    pkg = tmp_path / "demo_plugin_pkg"
    pkg.mkdir()
    (pkg / ".veles_tpu").write_text("")
    (pkg / "__init__.py").write_text(textwrap.dedent("""
        from veles_tpu.units import Unit

        class DemoPluginUnit(Unit):
            def run(self):
                pass
    """))
    (tmp_path / "not_a_plugin").mkdir()

    sys.path.insert(0, str(tmp_path))
    try:
        mods = veles_tpu.load_plugins(paths=[str(tmp_path)])
        assert any(m.__name__ == "demo_plugin_pkg" for m in mods)
        assert any(cls.__name__ == "DemoPluginUnit"
                   for cls in UnitRegistry.units)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("demo_plugin_pkg", None)


def test_per_class_cli_registry():
    """Units/services contribute their own flags via the registry
    (reference cmdline.py:61-84): Snapshotter (a Unit), Server, Client,
    Launcher flags all land in one parser and apply_parsed_args fans
    them back into config."""
    import veles_tpu.client  # noqa: F401  (registers contributors)
    import veles_tpu.server  # noqa: F401
    import veles_tpu.snapshotter  # noqa: F401
    from veles_tpu.cmdline import apply_parsed_args, build_parser
    from veles_tpu.config import root

    parser = build_parser()
    text = parser.format_help()
    for flag in ("--snapshot-dir", "--job-timeout", "--async-slave",
                 "--listen-address", "--death-probability"):
        assert flag in text, flag

    args = parser.parse_args([
        "--snapshot-dir", "/tmp/snapx", "--snapshot-interval", "7",
        "--job-timeout", "123.5", "--async-slave",
        "--listen-address", "0.0.0.0:9999"])
    apply_parsed_args(args)
    assert root.common.snapshot.get("dir") == "/tmp/snapx"
    assert root.common.snapshot.get("interval") == 7
    assert root.common.network.get("job_timeout") == 123.5
    assert root.common.network.get("async_slave") is True
    assert root.common.launcher.get("listen_address") == "0.0.0.0:9999"

    # constructors consult the applied config
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.server import Server
    from veles_tpu.snapshotter import Snapshotter
    try:
        sw = DummyWorkflow()
        snap = Snapshotter(sw.workflow, prefix="t")
        assert snap.directory == "/tmp/snapx" and snap.interval == 7
        server = Server("127.0.0.1:0", None)
        assert server.job_timeout == 123.5
    finally:
        # reset shared config for other tests
        root.common.snapshot.update(
            {"dir": None, "interval": 1, "time_interval": 15})
        root.common.network.update(
            {"job_timeout": 60.0, "async_slave": False})
        root.common.launcher.update({"listen_address": ""})


def test_frontend_composer_serves_and_launches(tmp_path):
    """Web command composer (reference __main__.py:258-332): the form
    is generated from the registered CLI args, /run launches only
    ``-m veles_tpu`` commands, /status reports the child."""
    import json
    import time
    import urllib.request

    from veles_tpu.__main__ import Main
    from veles_tpu.frontend import FrontendServer

    server = FrontendServer(Main().init_parser())
    server.start_background()
    base = "http://127.0.0.1:%d" % server.port
    try:
        page = urllib.request.urlopen(base + "/").read().decode()
        assert "--snapshot" in page and "--sync-run" in page
        assert "--ensemble-train" in page  # registry-aggregated flag
        token = page.split('TOKEN = "')[1].split('"')[0]

        def post(argv, token=token):
            req = urllib.request.Request(
                base + "/run",
                data=json.dumps({"argv": argv,
                                 "token": token}).encode())
            return json.loads(urllib.request.urlopen(req).read())

        # missing token (e.g. a cross-origin POST) is refused
        assert "error" in post(["-m", "veles_tpu", "--help"], token="x")
        # non-veles commands are refused
        refused = post(["-c", "print('pwned')"])
        assert "error" in refused
        # a composed dry run executes
        started = post(["-m", "veles_tpu", "--help"])
        assert "pid" in started
        for _ in range(50):
            status = json.loads(urllib.request.urlopen(
                base + "/status").read())
            if not status["running"]:
                break
            time.sleep(0.2)
        assert status["returncode"] == 0
    finally:
        server.stop()


def test_dump_unit_attributes():
    """--dump-unit-attributes prints the per-unit attribute table
    (reference __main__.py:663) after initialize, without running."""
    out = _run_cli("examples/digits.py", "-", "-d", "cpu",
                   "--dump-unit-attributes")
    assert out.returncode == 0, out.stderr[-1500:]
    assert "DigitsLoader" in out.stdout
    assert "class_lengths" in out.stdout
    # positive no-training signal: every unit still has zero runs
    runs = [line for line in out.stdout.splitlines()
            if " run_calls " in line]
    assert runs and all(line.rstrip().endswith(" 0")
                        for line in runs), runs[:5]



def test_cli_snapshot_and_crash_resume(wf_file, tmp_path):
    """--snapshot-dir auto-wires a Snapshotter into StandardWorkflow
    (the reference put one in every standard workflow); killing the
    process mid-training and restoring the _current symlink with -w
    resumes and finishes the remaining epochs."""
    import time

    snaps = tmp_path / "snaps"
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", VELES_BACKEND="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(
        [sys.executable, "-m", "veles_tpu", wf_file, "-", "-d", "cpu",
         "root.cli_test.max_epochs=60",
         "--snapshot-dir", str(snaps)],
        env=env, cwd="/root/repo",
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        # wait for the first checkpoint, then crash the trainer
        deadline = time.time() + 180
        current = None
        while time.time() < deadline:
            if snaps.is_dir():
                found = [p for p in snaps.iterdir()
                         if "current" in p.name]
                if found:
                    current = found[0]
                    break
            time.sleep(0.5)
        assert current is not None, "no snapshot appeared"
    finally:
        proc.kill()
        proc.wait()

    resumed = _run_cli(wf_file, "-", "-d", "cpu", "-w", str(current),
                       "--result-file", str(tmp_path / "r2.json"))
    assert resumed.returncode == 0, resumed.stderr[-1500:]
    r2 = json.loads((tmp_path / "r2.json").read_text())
    # the resumed session trained on to the snapshot's own stopping
    # criterion — far past wherever the crash landed
    assert r2["Total epochs"] == 60, r2
    assert r2["Best metric"] is not None
