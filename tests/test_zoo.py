"""Model-zoo tests: autoencoder (MSE), deconv/depool oracle checks,
RNN/LSTM vs autodiff, Kohonen convergence, RBM reconstruction,
AlexNet/VGG construction + one fused step on tiny shapes."""

import os
import sys

import numpy
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "examples"))

import jax
import jax.numpy as jnp

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader import FullBatchLoaderMSE
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator


# ------------------------------------------------------------ autoencoder

class AutoencoderLoader(FullBatchLoaderMSE):
    """targets = inputs (reconstruction)."""

    def load_data(self):
        self.class_lengths[:] = [0, 32, 128]
        self._calc_class_end_offsets()
        self.create_originals((12,), labels=False)
        rng = numpy.random.RandomState(3)
        base = rng.rand(4, 12).astype(numpy.float32)
        for i in range(self.total_samples):
            self.original_data.mem[i] = (
                base[i % 4] + rng.randn(12) * 0.05)
        self.original_targets.mem = numpy.array(self.original_data.mem)


def test_autoencoder_trains(cpu_device):
    from veles_tpu.models.zoo import autoencoder_layers
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=autoencoder_layers(bottleneck=4, hidden=16,
                                  out_features=12, lr=0.02),
        loader_factory=lambda w: AutoencoderLoader(
            w, minibatch_size=32, prng=RandomGenerator("ae", seed=2)),
        loss="mse",
        decision_config=dict(max_epochs=15),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    rmse = sw.decision.epoch_metrics[1]
    assert rmse is not None and rmse < 0.6, "val RMSE %s" % rmse


# ---------------------------------------------------------- deconv/depool

def test_deconv_inverts_conv_shape():
    from veles_tpu.models.deconv import Deconv
    rng = numpy.random.RandomState(0)
    x = rng.randn(2, 4, 4, 3).astype(numpy.float32)
    W = rng.randn(3, 3, 5, 3).astype(numpy.float32)  # (ky,kx,out,in)
    y = numpy.asarray(Deconv.apply(
        {"weights": W, "bias": None}, x, padding=(0, 0, 0, 0),
        sliding=(1, 1)))
    assert y.shape == (2, 6, 6, 5)


def test_gd_deconv_matches_autodiff():
    from veles_tpu.models.deconv import Deconv, GDDeconv
    rng = numpy.random.RandomState(1)
    x = rng.randn(2, 4, 4, 2).astype(numpy.float32)
    W = (rng.randn(3, 3, 3, 2) * 0.3).astype(numpy.float32)
    y = numpy.asarray(Deconv.apply(
        {"weights": W, "bias": None}, x, padding=(0, 0, 0, 0),
        sliding=(1, 1)))
    err = rng.randn(*y.shape).astype(numpy.float32)

    def loss(W_, x_):
        return jnp.sum(Deconv.apply(
            {"weights": W_, "bias": None}, x_, padding=(0, 0, 0, 0),
            sliding=(1, 1)) * err)

    gw, gx = jax.grad(loss, argnums=(0, 1))(W, x)
    state = {"weights": W, "bias": None,
             "accum_weights": numpy.zeros_like(W), "accum_bias": None,
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    err_input, new_state = GDDeconv.backward(
        state, hyper, x, y, err, solver="momentum", include_bias=False,
        need_err_input=True, padding=(0, 0, 0, 0), sliding=(1, 1))
    numpy.testing.assert_allclose(
        numpy.asarray(new_state["weights"]),
        W - 0.1 * numpy.asarray(gw), rtol=1e-3, atol=1e-4)
    numpy.testing.assert_allclose(numpy.asarray(err_input),
                                  numpy.asarray(gx), rtol=1e-3,
                                  atol=1e-4)


def test_depooling_upsamples():
    from veles_tpu.models.deconv import Depooling
    x = numpy.arange(4, dtype=numpy.float32).reshape(1, 2, 2, 1)
    y = numpy.asarray(Depooling.apply({}, x, window=(2, 2)))
    assert y.shape == (1, 4, 4, 1)
    assert (y[0, :2, :2, 0] == 0).all()
    assert (y[0, 2:, 2:, 0] == 3).all()


# ------------------------------------------------------------- recurrent

def test_rnn_lstm_forward_shapes():
    from veles_tpu.models.rnn import LSTM, RNN
    rng = numpy.random.RandomState(2)
    x = rng.randn(3, 7, 5).astype(numpy.float32)
    w_rnn = rng.randn(5 + 4, 4).astype(numpy.float32) * 0.2
    y = numpy.asarray(RNN.apply(
        {"weights": w_rnn, "bias": numpy.zeros(4, numpy.float32)}, x))
    assert y.shape == (3, 7, 4)
    assert numpy.abs(y).max() <= 1.0
    w_lstm = rng.randn(5 + 4, 16).astype(numpy.float32) * 0.2
    y2 = numpy.asarray(LSTM.apply(
        {"weights": w_lstm, "bias": numpy.zeros(16, numpy.float32)}, x,
        return_sequences=False))
    assert y2.shape == (3, 4)


def test_gd_lstm_matches_autodiff():
    from veles_tpu.models.rnn import GDLSTM, LSTM
    rng = numpy.random.RandomState(4)
    x = rng.randn(2, 5, 3).astype(numpy.float32)
    W = (rng.randn(3 + 4, 16) * 0.3).astype(numpy.float32)
    b = numpy.zeros(16, numpy.float32)
    y = numpy.asarray(LSTM.apply({"weights": W, "bias": b}, x))
    err = rng.randn(*y.shape).astype(numpy.float32)

    def loss(W_, b_):
        return jnp.sum(LSTM.apply({"weights": W_, "bias": b_}, x) * err)

    gw, gb = jax.grad(loss, argnums=(0, 1))(W, b)
    state = {"weights": W, "bias": b,
             "accum_weights": numpy.zeros_like(W),
             "accum_bias": numpy.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 1.0, "learning_rate_bias": 1.0,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    _, new_state = GDLSTM.backward(
        state, hyper, x, y, err, solver="momentum", include_bias=True,
        need_err_input=False)
    numpy.testing.assert_allclose(
        W - numpy.asarray(new_state["weights"]), numpy.asarray(gw),
        rtol=1e-3, atol=1e-4)


def test_rnn_workflow_trains_sequence_classification(cpu_device):
    """Classify which of 2 frequencies dominates a sequence."""
    from veles_tpu.loader import FullBatchLoader

    class SeqLoader(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 32, 96]
            self._calc_class_end_offsets()
            self.create_originals((16, 2))
            rng = numpy.random.RandomState(7)
            t = numpy.arange(16)
            for i in range(self.total_samples):
                label = i % 2
                freq = 0.2 if label == 0 else 0.8
                sig = numpy.sin(freq * t)[:, None].repeat(2, 1)
                self.original_data.mem[i] = (
                    sig + rng.randn(16, 2) * 0.1)
                self.original_labels[i] = label

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "lstm", "hidden_size": 8,
             "return_sequences": False, "learning_rate": 0.05,
             "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: SeqLoader(
            w, minibatch_size=32, prng=RandomGenerator("seq", seed=5)),
        decision_config=dict(max_epochs=10),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    assert sw.decision.epoch_metrics[1] < 15.0


# ---------------------------------------------------------------- kohonen

def test_kohonen_organizes(cpu_device):
    from veles_tpu.memory import Array
    from veles_tpu.models.kohonen import KohonenForward, KohonenTrainer
    wf = DummyWorkflow()
    rng = numpy.random.RandomState(6)
    centers = numpy.array([[0, 0], [1, 1], [0, 1], [1, 0]],
                          numpy.float32)
    data = numpy.concatenate([
        centers[i] + rng.randn(50, 2).astype(numpy.float32) * 0.05
        for i in range(4)])
    trainer = KohonenTrainer(wf, shape=(4, 4),
                             prng=RandomGenerator("koh", seed=4))
    trainer.input = Array(data)
    trainer.initialize(device=cpu_device)
    for _ in range(40):
        trainer.run()
    fwd = KohonenForward(wf, shape=(4, 4))
    fwd.input = Array(data)
    fwd.weights = trainer.weights
    fwd.initialize(device=cpu_device)
    fwd.run()
    winners = fwd.output.mem
    # each cluster maps to a (mostly) distinct dominant neuron
    dominant = set()
    for i in range(4):
        counts = numpy.bincount(winners[i * 50:(i + 1) * 50],
                                minlength=16)
        dominant.add(int(counts.argmax()))
    assert len(dominant) >= 3


# -------------------------------------------------------------------- rbm

def test_rbm_reduces_reconstruction_error(cpu_device):
    from veles_tpu.memory import Array
    from veles_tpu.models.rbm import RBM
    wf = DummyWorkflow()
    rng = numpy.random.RandomState(8)
    patterns = (rng.rand(4, 20) > 0.5).astype(numpy.float32)
    data = patterns[rng.randint(0, 4, 128)]
    rbm = RBM(wf, hidden_size=12, learning_rate=0.2,
              prng=RandomGenerator("rbm", seed=6))
    rbm.input = Array(data)
    rbm.initialize(device=cpu_device)
    errors = []
    for _ in range(200):
        rbm.run()
        errors.append(rbm.reconstruction_error)
    assert errors[-1] < errors[0] * 0.6, (errors[0], errors[-1])


# ------------------------------------------------------------ alexnet/vgg

def test_alexnet_vgg_fused_step_tiny():
    """Full AlexNet/VGG specs compile + execute one fused train step on
    scaled-down input (the real shapes run in bench.py on TPU)."""
    from veles_tpu.compiler import build_train_step
    from veles_tpu.models.zoo import (
        alexnet_layers, build_plans_and_state, vgg_layers)

    rng = numpy.random.RandomState(0)
    for name, specs, input_shape in (
            ("alexnet", alexnet_layers(classes=10), (67, 67, 3)),
            ("vgg11", vgg_layers(classes=10, config="A"), (32, 32, 3))):
        plans, state, out_shape = build_plans_and_state(
            specs, input_shape, seed=1)
        assert out_shape == (10,), name
        step = build_train_step(plans, donate=False)
        x = rng.rand(2, *input_shape).astype(numpy.float32)
        labels = rng.randint(0, 10, 2).astype(numpy.int32)
        new_state, metrics = step(
            state, x, labels, numpy.float32(2),
            jax.random.PRNGKey(0))
        assert numpy.isfinite(float(metrics["loss"])), name


def test_alexnet_workflow_constructs(cpu_device):
    """AlexNet spec builds through StandardWorkflow (tiny input)."""
    from veles_tpu.loader import FullBatchLoader
    from veles_tpu.models.zoo import alexnet_layers

    class TinyImages(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 4, 8]
            self._calc_class_end_offsets()
            self.create_originals((67, 67, 3))
            rng = numpy.random.RandomState(1)
            for i in range(self.total_samples):
                self.original_data.mem[i] = rng.rand(67, 67, 3)
                self.original_labels[i] = i % 2

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=alexnet_layers(classes=2, lr=0.01),
        loader_factory=lambda w: TinyImages(
            w, minibatch_size=4, prng=RandomGenerator("ax", seed=3)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    assert len(sw.forwards) == 13
    assert sw.forwards[0].weights.shape == (11, 11, 3, 96)


def test_kohonen_example_workflow(cpu_device):
    """The SOM example drives the real graph engine loop
    (repeater -> trainer -> counter gate) on real digits and reaches
    useful unsupervised structure (winner purity well above the 10%
    chance level)."""
    import importlib
    module = importlib.import_module("kohonen")
    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher
    saved_epochs = root.kohonen.epochs
    root.kohonen.epochs = 40  # keep the test fast; purity ~70%
    try:
        launcher = Launcher()
        wf = module.KohonenWorkflow(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        assert wf.purity is not None and wf.purity > 0.5, wf.purity
    finally:
        root.kohonen.epochs = saved_epochs


def test_rbm_example_workflow(cpu_device):
    """The RBM example pretrains on real digits through the graph
    engine loop and reconstructs held-out digits well below the
    untrained error."""
    import importlib
    module = importlib.import_module("rbm")
    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher
    saved = root.rbm.epochs
    root.rbm.epochs = 25
    try:
        launcher = Launcher()
        wf = module.RBMWorkflow(launcher)
        untrained = None
        launcher.initialize(device=cpu_device)
        untrained = wf.rbm.reconstruct_error(wf.valid_x)
        launcher.run()
        assert wf.holdout_error is not None
        assert wf.holdout_error < untrained * 0.7, (
            wf.holdout_error, untrained)
    finally:
        root.rbm.epochs = saved
