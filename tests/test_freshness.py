"""Train-to-serve freshness-loop tests (docs/serving.md "Freshness
loop"): the publish contract (atomic LATEST pointer, export-ordinal
order, bounded view retention), watcher verify-before-unpickle with
skip-and-retry backoff and TTL poisoning, the canary state machine
(live-rotation exclusion, mirror-path bit-equality, shadow-excluded
served counters, promote/auto-rollback with the zero-recompile
rollback receipt), the EMA-spike comparator, and the chaos-soak
smoke behind FRESH.json."""

import json
import logging
import os
import threading
import time

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.health import EmaSpikeWatch
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, CanaryComparator, FreshnessController, ReplicaPool,
    ServeOverload, SnapshotWatcher, export_model_spec, value_digest)
from veles_tpu.snapshotter import (
    LATEST_NAME, MANIFEST_SUFFIX, SnapshotError, publish_snapshot,
    read_latest)
from tests.test_serve import _mlp_spec

pytestmark = pytest.mark.freshness


def _spec_path(tmp_path, name, params, plans=None, shape=(16,)):
    if plans is None:
        plans, _ = _mlp_spec()
    path = str(tmp_path / name)
    export_model_spec(path, plans, params, shape)
    return path


def _pool(tmp_path, replicas=3, ladder=(8,), seed=11, **kwargs):
    plans, params = _mlp_spec(seed=seed)
    pool = ReplicaPool(plans, params, (16,), replicas=replicas,
                       ladder=ladder, max_delay_s=0.001,
                       max_queue=4096,
                       cache_root=str(tmp_path / "cache"), **kwargs)
    pool.compile()
    return pool


def _controller(pool, tmp_path, **kwargs):
    kwargs.setdefault("poll_s", 0.02)
    kwargs.setdefault("min_mirrors", 4)
    kwargs.setdefault("mirror_fraction", 1.0)
    kwargs.setdefault("breach_budget", 2)
    kwargs.setdefault("verdict_timeout_s", 15.0)
    return FreshnessController(pool, str(tmp_path / "publish"),
                               **kwargs)


def _perturb(params, scale=0.05, seed=3):
    rng = numpy.random.RandomState(seed)
    return [{k: v + scale * rng.randn(*v.shape).astype(v.dtype)
             for k, v in entry.items()} for entry in params]


def _drive(pool, n=40, seed=5, sleep=0.0):
    """Closed-loop traffic; returns (samples, results) in order."""
    rng = numpy.random.RandomState(seed)
    samples = [rng.rand(16).astype(numpy.float32) for _ in range(n)]
    results = []
    for x in samples:
        results.append(numpy.array(pool.infer(x, timeout=15.0)))
        if sleep:
            time.sleep(sleep)
    return samples, results


# -- publish contract --------------------------------------------------------


def test_publish_contract_ordinals_latest_retention(tmp_path):
    plans, params = _mlp_spec(seed=1)
    pub = str(tmp_path / "pub")
    receipts = []
    for i in range(5):
        path = _spec_path(tmp_path, "s%d.pickle" % i,
                          _perturb(params, seed=i), plans)
        receipts.append(publish_snapshot(path, pub, keep=3))
    assert [r["ordinal"] for r in receipts] == [1, 2, 3, 4, 5]
    latest = read_latest(pub)
    assert latest["ordinal"] == 5
    assert latest["snapshot"].startswith("000005_")
    assert latest["sha256"] == receipts[-1]["sha256"]
    # bounded view: keep=3 newest ordinals survive, each with its
    # manifest; the LATEST target is among them by construction
    published = sorted(f for f in os.listdir(pub)
                       if f[0].isdigit() and
                       not f.endswith(MANIFEST_SUFFIX))
    assert [f.split("_")[0] for f in published] == \
        ["000003", "000004", "000005"]
    for f in published:
        assert os.path.exists(os.path.join(pub, f + MANIFEST_SUFFIX))
    assert os.path.exists(os.path.join(pub, latest["snapshot"]))


def test_publish_refuses_unverifiable(tmp_path):
    plans, params = _mlp_spec(seed=2)
    path = _spec_path(tmp_path, "good.pickle", params, plans)
    # corrupt the data after the manifest was written
    with open(path, "r+b") as fout:
        fout.write(b"\x00\x00garbage")
    with pytest.raises(SnapshotError):
        publish_snapshot(path, str(tmp_path / "pub"))
    bare = str(tmp_path / "bare.pickle")
    import pickle
    with open(bare, "wb") as fout:
        pickle.dump({"plans": plans, "params": params,
                     "sample_shape": (16,)}, fout)
    with pytest.raises(SnapshotError):  # no manifest -> unverifiable
        publish_snapshot(bare, str(tmp_path / "pub"))


def test_snapshotter_unit_publishes_real_workflow(tmp_path,
                                                  cpu_device):
    """The trainer-side hook end-to-end: a real Snapshotter with
    publish_dir pushes its manifest-verified workflow snapshot, and
    the watcher extracts a servable plans/params spec from it.  The
    publish dir is a retention-EXEMPT view: the train dir's keep=N
    does not govern it."""
    from veles_tpu.snapshotter import Snapshotter
    from tests.test_snapshot import _build
    sw = _build(cpu_device, max_epochs=1)
    sw.run()
    pub = str(tmp_path / "pub")
    snap = Snapshotter(sw, directory=str(tmp_path / "train"),
                       prefix="fw", interval=1, time_interval=0,
                       compression="gz", keep=1, publish_dir=pub)
    snap.initialize()
    for i in range(3):
        snap.suffix = "e%d" % i
        snap.export()
        time.sleep(0.02)
    # train dir keep=1 pruned history; the publish view kept all 3
    published = [f for f in os.listdir(pub) if f[0].isdigit() and
                 not f.endswith(MANIFEST_SUFFIX)]
    assert len(published) == 3
    assert read_latest(pub)["ordinal"] == 3
    watcher = SnapshotWatcher(pub, default_sample_shape=(16,))
    cand = watcher.poll_once()
    assert cand is not None and cand.ordinal == 3
    assert cand.sample_shape == (16,)
    assert len(cand.plans) == 2 and "weights" in cand.params[0]
    # the spec actually serves
    engine = AOTEngine(cand.plans, cand.params, cand.sample_shape,
                       ladder=(8,), device=Device(backend="cpu"))
    engine.compile()
    out = engine.infer(numpy.zeros((2, 16), numpy.float32))
    assert out.shape == (2, 4) and numpy.isfinite(out).all()


# -- watcher discipline ------------------------------------------------------


def test_watcher_skips_and_retries_torn_publish(tmp_path, caplog):
    """A half-written publish (chaos freshness.publish=truncate) is
    skipped and retried with backoff — at DEBUG, never a warning per
    poll tick — and the next good publish supersedes it."""
    plans, params = _mlp_spec(seed=3)
    pub = str(tmp_path / "pub")
    chaos.install(chaos.FaultPlan(seed=1).add(
        "freshness.publish", "truncate", nth=1))
    try:
        publish_snapshot(_spec_path(tmp_path, "a.pickle", params,
                                    plans), pub)
    finally:
        chaos.uninstall()
    watcher = SnapshotWatcher(pub, poll_s=0.01, invalid_ttl_s=60.0)
    with caplog.at_level(logging.DEBUG, logger="SnapshotWatcher"):
        for _ in range(6):
            assert watcher.poll_once() is None
            time.sleep(0.012)
    warnings = [r for r in caplog.records
                if r.levelno >= logging.WARNING]
    assert not warnings, warnings
    pend = watcher._pending
    assert pend is not None and pend["ordinal"] == 1
    assert pend["backoff"] > watcher.poll_s  # backoff actually grew
    # the re-publish supersedes the torn ordinal immediately
    publish_snapshot(_spec_path(tmp_path, "b.pickle", params, plans),
                     pub)
    cand = watcher.poll_once()
    assert cand is not None and cand.ordinal == 2
    assert watcher._pending is None


def test_watcher_ttl_rejects_stuck_invalid(tmp_path):
    plans, params = _mlp_spec(seed=4)
    pub = str(tmp_path / "pub")
    chaos.install(chaos.FaultPlan(seed=1).add(
        "freshness.publish", "truncate", nth=1))
    try:
        publish_snapshot(_spec_path(tmp_path, "a.pickle", params,
                                    plans), pub)
    finally:
        chaos.uninstall()
    before = registry.counter(
        "serve.freshness.poisoned_rejected").value
    watcher = SnapshotWatcher(pub, poll_s=0.01, invalid_ttl_s=0.05,
                              max_backoff_s=0.02)
    deadline = time.monotonic() + 5.0
    while 1 not in watcher._rejected and time.monotonic() < deadline:
        watcher.poll_once()
        time.sleep(0.015)
    assert 1 in watcher._rejected
    assert registry.counter(
        "serve.freshness.poisoned_rejected").value == before + 1
    assert watcher.poll_once() is None  # rejected ordinal stays dead


def test_watcher_push_notify_wakes_poll(tmp_path):
    plans, params = _mlp_spec(seed=5)
    pub = str(tmp_path / "pub")
    seen = []
    watcher = SnapshotWatcher(pub, callback=seen.append, poll_s=30.0)
    watcher.start()
    try:
        time.sleep(0.05)  # the poll loop is now parked for 30s
        publish_snapshot(_spec_path(tmp_path, "a.pickle", params,
                                    plans), pub)
        watcher.notify()
        deadline = time.monotonic() + 5.0
        while not seen and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        watcher.stop()
    assert seen and seen[0].ordinal == 1


# -- canary mechanics --------------------------------------------------------


def _compiled_candidate(pool, params, plans=None):
    cand_plans = plans if plans is not None else pool.engine.plans
    rep = pool._live()[-1]
    engine = AOTEngine(cand_plans, params, pool.engine.sample_shape,
                       device=rep.device, ladder=pool.engine.ladder,
                       cache_root=pool.engine.cache_root)
    engine.compile()
    return engine


def test_canary_replica_leaves_rotation_and_cascade(tmp_path):
    """Satellite fix: a canary replica is never a routing pick NOR a
    cascade target, and the fleet 503's retry_after comes from live
    replicas only."""
    pool = _pool(tmp_path, replicas=3)
    pool.start()
    try:
        candidate = _compiled_candidate(
            pool, _perturb(pool.engine.params))
        rep = pool.cutover.begin(candidate)
        assert rep is pool.replicas[-1]
        assert [r.index for r in pool._live()] == [0, 1]
        assert pool.digest == pool.replicas[0].engine.digest
        for _ in range(12):
            pool.infer(numpy.zeros(16, numpy.float32))
        assert rep.batcher._q.qsize() == 0  # no routed traffic landed
        # every live replica sheds -> the canary is NOT a cascade
        # target and the 503 is computed over the 2 live replicas
        chaos.install(chaos.FaultPlan(seed=1).add("serve.drop",
                                                  "drop"))
        try:
            with pytest.raises(ServeOverload) as info:
                pool.submit(numpy.zeros(16, numpy.float32))
        finally:
            chaos.uninstall()
        assert "2 live replicas" in str(info.value)
        pool.cutover.rollback(reason="test teardown")
        assert not rep.canary
    finally:
        pool.stop()


def test_mirror_bit_equality_and_shadow_excluded_counters(tmp_path):
    """Satellite regression: a mirrored request's primary response is
    bit-identical to the unmirrored run, and the served counters
    (serve.requests, serve.latency_s) exclude shadow traffic."""
    pool = _pool(tmp_path, replicas=3)
    pool.start()
    try:
        samples, baseline = _drive(pool, n=20, seed=6)
        candidate = _compiled_candidate(
            pool, _perturb(pool.engine.params))
        pool.cutover.begin(candidate)
        shadows = []
        pool.mirror_hook = lambda sample, req: shadows.append(
            pool.cutover.shadow(numpy.array(sample, copy=True)))
        req_before = registry.counter("serve.requests").value
        lat_before = registry.histogram("serve.latency_s").count
        mirrored = [numpy.array(pool.infer(x, timeout=15.0))
                    for x in samples]
        for primary, ref in zip(mirrored, baseline):
            assert (primary == ref).all()  # bit-identical under mirror
        shadows = [s for s in shadows if s is not None]
        assert len(shadows) == len(samples)  # fraction 1.0 here
        for s in shadows:
            assert s.done.wait(10.0)
            assert s.error is None and s.latency is not None
        # EXACTLY the primary requests count as served: the shadows
        # (same number again) appear in neither counter
        assert registry.counter("serve.requests").value \
            == req_before + len(samples)
        assert registry.histogram("serve.latency_s").count \
            == lat_before + len(samples)
        # shadow results really came from the CANDIDATE model
        ref_engine = pool.cutover.canary_replica.engine
        for x, s in zip(samples, shadows):
            assert (s.result == ref_engine.infer(x)[0]).all()
        pool.mirror_hook = None
        pool.cutover.rollback(reason="test teardown")
    finally:
        pool.stop()


def test_promote_rolls_fleet_and_reload_guard(tmp_path):
    pool = _pool(tmp_path, replicas=3)
    pool.start()
    try:
        new_params = _perturb(pool.engine.params, seed=8)
        candidate = _compiled_candidate(pool, new_params)
        pool.cutover.begin(candidate)
        with pytest.raises(RuntimeError):  # reload refused mid-canary
            pool.reload(new_params)
        receipt = pool.cutover.promote()
        assert receipt["verdict"] == "promoted"
        assert receipt["new_compiles"] == 0  # same digest: params swap
        want = value_digest(new_params)
        for rep in pool.replicas:
            assert value_digest(rep.engine.params) == want
            assert not rep.canary
        assert pool.cutover.state == "idle"
        # traffic still flows and reflects the new weights everywhere
        x = numpy.random.RandomState(9).rand(16).astype(numpy.float32)
        ref = pool.engine.infer(x)[0]
        for rep in pool.replicas:
            assert (rep.batcher.infer(x) == ref).all()
    finally:
        pool.stop()


def test_rollback_restores_last_good_with_zero_compiles(tmp_path):
    """The acceptance contract: rollback is swap-backs only — zero new
    backend compiles by construction — and restores the last-good
    weights bit-exactly, including a NEW-digest candidate (wider
    hidden layer) whose canary engine replaced the replica's."""
    pool = _pool(tmp_path, replicas=2)
    pool.start()
    try:
        before = value_digest(pool.engine.params)
        x = numpy.random.RandomState(10).rand(16).astype(numpy.float32)
        ref = pool.engine.infer(x)[0]
        plans3, params3 = _mlp_spec(seed=5, hidden=24)
        candidate = _compiled_candidate(pool, params3, plans=plans3)
        canary_rep = pool.cutover.begin(candidate)
        deadline = time.monotonic() + 5.0
        while canary_rep.batcher.engine is not candidate and \
                time.monotonic() < deadline:
            pool.infer(x)  # keep batches flowing so the swap applies
        assert canary_rep.batcher.engine is candidate
        receipt = pool.cutover.rollback(reason="bad canary")
        assert receipt["verdict"] == "rolled_back"
        assert receipt["new_compiles"] == 0, receipt
        assert receipt["restored_digest"] == pool.digest
        for rep in pool.replicas:
            assert value_digest(rep.engine.params) == before
        # the rolled-back replica actually SERVES the old model again
        deadline = time.monotonic() + 5.0
        while canary_rep.batcher.engine is candidate and \
                time.monotonic() < deadline:
            pool.infer(x)
        assert (canary_rep.batcher.infer(x) == ref).all()
    finally:
        pool.stop()


# -- comparator / spike watch ------------------------------------------------


def test_ema_spike_watch_matches_decision_discipline():
    watch = EmaSpikeWatch(spike_factor=3.0, spike_floor=0.1, beta=0.5)
    assert watch.update(1.0) is None          # first value: no EMA yet
    assert watch.ema == 1.0
    assert watch.update(1.2) is None
    assert watch.ema == pytest.approx(1.1)
    reason = watch.update(100.0)
    assert reason is not None and "spiked" in reason
    assert watch.ema == pytest.approx(1.1)    # spike NOT folded in
    watch.reset()
    assert watch.ema is None
    # the floor: a near-zero baseline doesn't turn noise into spikes
    floor = EmaSpikeWatch(spike_factor=3.0, spike_floor=1.0)
    floor.update(0.001)
    assert floor.update(0.5) is None          # < 3.0 * max(ema, 1.0)


def test_comparator_verdicts():
    good = numpy.full(4, 0.25)
    # clean pairs -> promote at min_mirrors
    comp = CanaryComparator(min_mirrors=3, breach_budget=2)
    assert comp.add(good, good + 1e-4, 0.01, 0.01) is None
    assert comp.add(good, good - 1e-4, 0.01, 0.01) is None
    assert comp.add(good, good, 0.01, 0.01) == "promote"
    # non-finite canary output -> instant rollback
    comp = CanaryComparator(min_mirrors=3)
    bad = numpy.array([0.5, numpy.nan, 0.2, 0.1])
    assert comp.add(good, bad, 0.01, 0.01) == "rolled_back"
    assert "non-finite" in comp.reason()
    # divergence bound -> breaches -> rollback
    comp = CanaryComparator(min_mirrors=8, divergence_limit=0.5,
                            breach_budget=2)
    onehot = numpy.array([1.0, 0.0, 0.0, 0.0])
    assert comp.add(good, onehot, 0.01, 0.01) is None
    assert comp.add(good, onehot, 0.01, 0.01) == "rolled_back"
    assert "divergence" in comp.reason()
    # latency: live latencies prime the EMA, a slow canary spikes it
    comp = CanaryComparator(min_mirrors=8, latency_spike_factor=3.0,
                            latency_floor_s=0.01, breach_budget=2)
    for _ in range(4):
        assert comp.add(good, good, 0.01, 0.012) is None
    assert comp.add(good, good, 0.01, 5.0) is None   # breach 1
    assert comp.add(good, good, 0.01, 5.0) == "rolled_back"
    assert "latency" in comp.reason()


# -- controller end-to-end ---------------------------------------------------


def test_controller_cycle_promote_then_poison_then_rollback(tmp_path):
    """The loop end-to-end, one thread of truth: a good publish is
    canaried under mirrored closed-loop traffic and PROMOTED; a
    NaN-params publish dies at the finite gate; a finite-but-garbage
    publish (invisible to the gate) is canaried and auto-ROLLED BACK
    with zero new compiles; the fleet serves the promoted weights
    bit-exactly throughout, with zero failed requests."""
    pool = _pool(tmp_path, replicas=3)
    pool.start()
    controller = _controller(pool, tmp_path, invalid_ttl_s=1.0)
    controller.start()
    errors = []
    stop = threading.Event()

    def client(k):
        rng = numpy.random.RandomState(40 + k)
        x = rng.rand(16).astype(numpy.float32)
        while not stop.is_set():
            try:
                pool.infer(x, timeout=15.0)
            except Exception as exc:
                errors.append(exc)
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(3)]
    for t in threads:
        t.start()
    plans = pool.engine.plans
    pub = tmp_path  # publish dir is tmp_path/"publish" via _controller
    try:
        def publish(name, params):
            return publish_snapshot(
                _spec_path(pub, name, params, plans),
                str(tmp_path / "publish"))

        def wait_cycle(ordinal, timeout=30.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                for entry in controller.history:
                    if entry["ordinal"] == ordinal:
                        return entry
                time.sleep(0.02)
            raise TimeoutError("no verdict for #%d" % ordinal)

        good = _perturb(pool.engine.params, seed=21)
        entry = wait_cycle(publish("good.pickle", good)["ordinal"])
        assert entry["verdict"] == "promoted", entry
        assert entry["mirrors"] >= 4
        want = value_digest(good)
        for rep in pool.replicas:
            assert value_digest(rep.engine.params) == want

        nan_params = [{k: numpy.full_like(v, numpy.nan)
                       for k, v in e.items()} for e in good]
        entry = wait_cycle(publish("nan.pickle", nan_params)["ordinal"])
        assert entry["verdict"] == "poisoned"
        for rep in pool.replicas:  # never warmed, never served
            assert value_digest(rep.engine.params) == want

        # finite-but-wrong: the output classes permuted — a model that
        # confidently answers the WRONG question, invisible to every
        # static gate, exactly what the mirrored canary exists for
        garbage = [dict(e) for e in good]
        garbage[-1] = {
            "weights": numpy.roll(good[-1]["weights"], 1, axis=1),
            "bias": numpy.roll(good[-1]["bias"], 1)}
        entry = wait_cycle(publish("bad.pickle", garbage)["ordinal"])
        assert entry["verdict"] == "rolled_back", entry
        assert entry["new_compiles"] == 0, entry
        for rep in pool.replicas:
            assert value_digest(rep.engine.params) == want
        assert pool.cutover.state == "idle"
        snap = controller.snapshot()
        assert snap["promotions"] >= 1 and snap["rollbacks"] >= 1
        assert snap["poisoned_rejected"] >= 1
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        controller.stop()
        pool.stop()
    assert not errors, errors[:3]


def test_single_replica_falls_back_to_direct_reload(tmp_path):
    pool = _pool(tmp_path, replicas=1)
    pool.start()
    controller = _controller(pool, tmp_path)
    try:
        good = _perturb(pool.engine.params, seed=31)
        publish_snapshot(
            _spec_path(tmp_path, "solo.pickle", good,
                       pool.engine.plans),
            str(tmp_path / "publish"))
        cand = controller.watcher.poll_once()  # runs the cycle inline
        assert cand is not None
        assert controller.history[-1]["verdict"] == "reloaded"
        assert value_digest(pool.engine.params) == value_digest(good)
    finally:
        controller.stop()
        pool.stop()


def test_service_publish_endpoint_and_healthz(tmp_path):
    import urllib.request

    from veles_tpu.serve import ServeService
    pool = _pool(tmp_path, replicas=2)
    controller = _controller(pool, tmp_path, poll_s=30.0)
    controller.start()
    svc = ServeService(pool, freshness=controller)
    svc.start_background()
    try:
        base = "http://127.0.0.1:%d" % svc.port
        good = _perturb(pool.engine.params, seed=41)
        receipt = publish_snapshot(
            _spec_path(tmp_path, "push.pickle", good,
                       pool.engine.plans),
            str(tmp_path / "publish"))
        req = urllib.request.Request(
            base + "/publish",
            data=json.dumps({"snapshot": receipt["snapshot"]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            answer = json.loads(resp.read())
        assert answer["status"] == "notified"
        deadline = time.monotonic() + 20.0
        while not controller.history and time.monotonic() < deadline:
            time.sleep(0.05)  # the push, not the 30s poll, woke it
        assert controller.history, "push never woke the watcher"
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["freshness"]["last_ordinal"] == 1
        assert health["freshness"]["cycles"] >= 1
    finally:
        svc.stop()
        controller.stop()
        pool.stop()


def test_watcher_retries_when_cycle_fails_transiently(tmp_path):
    """A transient controller failure (e.g. the candidate warm-up ran
    out of memory) must not consume the ordinal — the publish is
    retried with backoff — and, because the publish itself VERIFIED,
    it is never TTL-branded poisoned no matter how long the failures
    last: a healthy model must not be rejected because the serve side
    had a bad minute."""
    plans, params = _mlp_spec(seed=6)
    pub = str(tmp_path / "pub")
    publish_snapshot(_spec_path(tmp_path, "a.pickle", params, plans),
                     pub)
    poisoned = registry.counter("serve.freshness.poisoned_rejected")
    before = poisoned.value
    calls = []

    def flaky(cand):
        calls.append(cand.ordinal)
        if len(calls) <= 2:
            raise RuntimeError("transient warm-up failure")

    watcher = SnapshotWatcher(pub, callback=flaky, poll_s=0.01,
                              invalid_ttl_s=0.02, max_backoff_s=0.02)
    assert watcher.poll_once() is None  # failed cycle: NOT consumed
    assert watcher.last_ordinal == 0
    time.sleep(0.05)  # past the TTL: must NOT escalate to poisoned
    assert watcher.poll_once() is None
    assert 1 not in watcher._rejected
    assert poisoned.value == before
    time.sleep(0.05)
    cand = watcher.poll_once()  # failure cleared: third try lands
    assert cand is not None and cand.ordinal == 1
    assert calls == [1, 1, 1]


def test_idle_fleet_self_probes_to_a_verdict(tmp_path):
    """Zero client traffic: the controller self-probes (shadow pairs
    on BOTH sides — never counted as served) and still reaches a real
    verdict — a good candidate promotes, a class-permuted one rolls
    back — instead of timing out into a verdict nobody earned."""
    pool = _pool(tmp_path, replicas=2)
    pool.start()
    controller = _controller(pool, tmp_path, probe_idle_s=0.02)
    plans = pool.engine.plans
    try:
        req_before = registry.counter("serve.requests").value
        good = _perturb(pool.engine.params, seed=51)
        publish_snapshot(_spec_path(tmp_path, "g.pickle", good, plans),
                         str(tmp_path / "publish"))
        assert controller.watcher.poll_once() is not None
        entry = controller.history[-1]
        assert entry["verdict"] == "promoted", entry
        assert entry["mirrors"] >= 4  # real probe evidence, not a bye
        bad = [dict(e) for e in good]
        bad[-1] = {
            "weights": numpy.roll(good[-1]["weights"], 1, axis=1),
            "bias": numpy.roll(good[-1]["bias"], 1)}
        publish_snapshot(_spec_path(tmp_path, "b.pickle", bad, plans),
                         str(tmp_path / "publish"))
        assert controller.watcher.poll_once() is not None
        entry = controller.history[-1]
        assert entry["verdict"] == "rolled_back", entry
        assert entry["new_compiles"] == 0
        assert value_digest(pool.engine.params) == value_digest(good)
        # probes are shadows end to end: nothing was "served"
        assert registry.counter("serve.requests").value == req_before
    finally:
        controller.stop()
        pool.stop()


# -- the soak receipt --------------------------------------------------------


@pytest.mark.chaos
def test_freshness_soak_smoke(tmp_path):
    """Tier-1 smoke of the FRESH.json receipt: the fast profile —
    publish->canary->promote cycles under trainer crash + torn publish
    + replica stalls, a NaN and a garbage snapshot both contained,
    zero dropped requests, rollback with zero new compiles."""
    import scripts.freshness_soak as soak
    out = str(tmp_path / "FRESH.json")
    receipt = soak.run_soak(good_cycles=2, replicas=3, clients=3,
                            fast=True, out=out)
    assert receipt["passed"], receipt["checks"]
    assert receipt["checks"]["promote_cycles"] >= 2
    assert receipt["checks"]["zero_dropped_requests"]
    assert receipt["checks"]["poison_never_promoted"]
    assert receipt["checks"]["rollback_zero_new_compiles"]
    assert receipt["chaos"]["trainer_crashes"] >= 1
    assert receipt["chaos"]["torn_publishes_rejected"] >= 1
    with open(out) as fin:
        assert json.load(fin)["passed"]


@pytest.mark.chaos
@pytest.mark.slow
def test_freshness_soak_full(tmp_path):
    """The committed-receipt profile: >= 5 promote cycles plus both
    poison shapes under the full chaos plan."""
    import scripts.freshness_soak as soak
    receipt = soak.run_soak(good_cycles=6, replicas=3, clients=4,
                            fast=False,
                            out=str(tmp_path / "FRESH.json"))
    assert receipt["passed"], receipt["checks"]
    assert receipt["checks"]["promote_cycles"] >= 5
