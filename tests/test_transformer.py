"""Transformer workload: flash-attention kernel parity, the
LayerNorm/MultiHeadAttention/TransformerBlock unit chain, the fused
train step, and model sharding beyond data-parallel (tensor-parallel
head sharding + pipeline-parallel stage split) — docs/kernels.md "The
attention kernel", docs/distributed.md "Model parallelism"."""

import numpy
import pytest

pytestmark = pytest.mark.transformer

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from veles_tpu.ops import common as _ops_common  # noqa: E402
from veles_tpu.ops.attention import (  # noqa: E402
    attention_reference, flash_attention)


def _qkv(rng, b, t, dh, dtype=numpy.float32, scale=1.0):
    return tuple(jnp.asarray(rng.randn(b, t, dh) * scale, dtype)
                 for _ in range(3))


def _maxrel(a, b):
    a, b = numpy.asarray(a, numpy.float64), numpy.asarray(
        b, numpy.float64)
    return float(numpy.abs(a - b).max() / max(numpy.abs(a).max(),
                                              1e-9))


# -- kernel parity ----------------------------------------------------------


@pytest.mark.parametrize("level", [0, 1, 2])
def test_flash_bit_exact_on_single_tile_shapes(level):
    """One (bq, bk) tile = the kernel executes the reference's exact
    op sequence (same shared mxu_partial_dot products): bit-exact."""
    rng = numpy.random.RandomState(0)
    q, k, v = _qkv(rng, 3, 16, 8)
    ref = attention_reference(q, k, v, precision_level=level)
    out = flash_attention(q, k, v, precision_level=level,
                          blocks=(256, 256))
    numpy.testing.assert_array_equal(numpy.asarray(ref),
                                     numpy.asarray(out))


def test_flash_padding_boundary_pinned():
    """The bit-exact claim's measured boundary: zero-padding a length
    to the 128 lane width keeps XLA's reduce grouping for T <= 32 and
    multiples of 64 (bit-exact), and regroups it in between (~2e-7)
    — docs/kernels.md states exactly this."""
    rng = numpy.random.RandomState(9)
    for t, exact in ((32, True), (64, True), (40, False)):
        q, k, v = _qkv(rng, 2, t, 8)
        a = numpy.asarray(flash_attention(q, k, v, precision_level=1,
                                          blocks=(256, 256)))
        b = numpy.asarray(attention_reference(q, k, v,
                                              precision_level=1))
        if exact:
            numpy.testing.assert_array_equal(a, b, err_msg="T=%d" % t)
        else:
            assert float(numpy.abs(a - b).max()) < 1e-6


@pytest.mark.parametrize("level,bound", [(1, 5e-6), (0, 1e-5)])
def test_flash_ulp_bound_on_multi_tile_shapes(level, bound):
    """Multi-tile shapes differ only by the online rescale's
    accumulation order: ULP-bounded (measured ~3e-7 level 1 / ~2e-6
    level 0 on this shape)."""
    rng = numpy.random.RandomState(1)
    q, k, v = _qkv(rng, 2, 300, 16)
    ref = attention_reference(q, k, v, precision_level=level)
    out = flash_attention(q, k, v, precision_level=level,
                          blocks=(64, 128))
    assert _maxrel(ref, out) < bound
    assert bool(jnp.isfinite(out).all())


def test_flash_backward_matches_stock_autodiff():
    """The Pallas backward pair vs jax.grad through the reference —
    including padded rows/columns (T=37 forces both paddings), whose
    contributions must be EXACT zeros, not NaN."""
    rng = numpy.random.RandomState(2)
    q, k, v = _qkv(rng, 2, 37, 8)

    def loss(fn):
        def f(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_) ** 2)
        return f

    flash = loss(lambda *a: flash_attention(
        *a, precision_level=1, blocks=(16, 128)))
    ref = loss(lambda *a: attention_reference(*a, precision_level=1))
    gf = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert bool(jnp.isfinite(a).all())
        assert _maxrel(b, a) < 5e-6


def test_flash_bf16_operands():
    rng = numpy.random.RandomState(3)
    q, k, v = _qkv(rng, 2, 24, 8, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, blocks=(256, 256))
    ref = attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    numpy.testing.assert_allclose(
        numpy.asarray(out, numpy.float32),
        numpy.asarray(ref, numpy.float32), rtol=0.05, atol=0.05)


def test_knob_off_runs_stock_reference_bit_exactly(monkeypatch):
    """VELES_PALLAS_BWD=0: the model layer's attention IS
    attention_reference (stock autodiff), bit-exact by construction."""
    from veles_tpu.models.transformer import MultiHeadAttention
    rng = numpy.random.RandomState(4)
    d, heads = 8, 2
    x = jnp.asarray(rng.randn(3, 5, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, 4 * d) * 0.3, jnp.float32)
    b = jnp.asarray(rng.randn(4 * d) * 0.1, jnp.float32)
    monkeypatch.setattr(_ops_common, "PALLAS_BWD_ENV", "0")
    off = MultiHeadAttention.apply({"weights": w, "bias": b}, x,
                                   heads=heads)
    monkeypatch.setattr(_ops_common, "PALLAS_BWD_ENV", "1")
    on = MultiHeadAttention.apply({"weights": w, "bias": b}, x,
                                  heads=heads)
    # the stock path twice = bit-stable; flash vs stock stays in band
    monkeypatch.setattr(_ops_common, "PALLAS_BWD_ENV", "0")
    off2 = MultiHeadAttention.apply({"weights": w, "bias": b}, x,
                                    heads=heads)
    numpy.testing.assert_array_equal(numpy.asarray(off),
                                     numpy.asarray(off2))
    assert _maxrel(off, on) < 1e-5


def test_debug_nonfinite_guard(monkeypatch):
    monkeypatch.setattr(_ops_common, "DEBUG_NONFINITE", True)
    rng = numpy.random.RandomState(5)
    q, k, v = _qkv(rng, 1, 8, 8)
    q = q.at[0, 0, 0].set(jnp.nan)
    with pytest.raises(FloatingPointError):
        flash_attention(q, k, v, blocks=(256, 256))


# -- schedule-cache family --------------------------------------------------


@pytest.mark.tune
def test_attention_schedule_cache_consult_loads_tuned_blocks():
    """A planted cache entry demonstrably changes the tiles a
    blocks=None call runs — with BIT-equal results in interpret mode
    when the planted tile covers the whole shape."""
    from veles_tpu.tune.cache import cache_for, schedule_key
    from veles_tpu.tune.spec import attention_spec
    rng = numpy.random.RandomState(6)
    q, k, v = _qkv(rng, 2, 48, 8)
    spec = attention_spec(2, 48, 8, "float32", 1)
    kind = jax.devices()[0].device_kind
    digest, payload = schedule_key(
        spec["op"], spec["shape"], spec["dtype"],
        spec["precision_level"], kind, spec["extra"])
    cache = cache_for()
    cache.put(digest, payload, {"blocks": [16, 128]}, source="test")
    consulted = flash_attention(q, k, v, precision_level=1)
    explicit = flash_attention(q, k, v, precision_level=1,
                               blocks=(16, 128))
    numpy.testing.assert_array_equal(numpy.asarray(consulted),
                                     numpy.asarray(explicit))
    # malformed entry degrades to the static default, never crashes
    cache.put(digest, payload, {"blocks": [7, 100, 3]}, source="test")
    fallback = flash_attention(q, k, v, precision_level=1)
    default = flash_attention(q, k, v, precision_level=1,
                              blocks=(256, 256))
    numpy.testing.assert_array_equal(numpy.asarray(fallback),
                                     numpy.asarray(default))


@pytest.mark.tune
def test_attention_family_quantization_and_feasibility():
    from veles_tpu.tune.spec import attention_spec, family_for
    family = family_for("attention")
    spec = attention_spec(4, 513, 64, "float32", 0)
    sched = family.quantize(spec, {"bq": 100, "bk": 300})
    bq, bk = sched["blocks"]
    assert bq % 8 == 0 and bk % 128 == 0
    assert family.feasible(spec, {"blocks": [128, 256]})
    assert not family.feasible(spec, {"blocks": [1024, 2048]})
    assert family.validate({"blocks": [8, 128]})
    assert family.validate({"blocks": [7, 128]}) is None
    assert family.space(spec) is not None


# -- the unit chain ---------------------------------------------------------


def test_layer_norm_apply_and_gd_matches_autodiff():
    from veles_tpu.models.transformer import GDLayerNorm, LayerNorm
    rng = numpy.random.RandomState(7)
    x = jnp.asarray(rng.randn(4, 6, 8), jnp.float32)
    gamma = jnp.asarray(rng.rand(8) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(8) * 0.1, jnp.float32)
    y = LayerNorm.apply({"weights": gamma, "bias": beta}, x)
    xn = (numpy.asarray(y) - numpy.asarray(beta)) / numpy.asarray(
        gamma)
    numpy.testing.assert_allclose(xn.mean(-1), 0.0, atol=1e-5)
    numpy.testing.assert_allclose(xn.std(-1), 1.0, atol=1e-3)

    err = jnp.asarray(rng.randn(4, 6, 8), jnp.float32)

    def loss(g_, b_):
        return jnp.sum(LayerNorm.apply(
            {"weights": g_, "bias": b_}, x) * err)

    gw, gb = jax.grad(loss, argnums=(0, 1))(gamma, beta)
    state = {"weights": gamma, "bias": beta,
             "accum_weights": jnp.zeros_like(gamma),
             "accum_bias": jnp.zeros_like(beta),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 1.0, "learning_rate_bias": 1.0,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    _, new_state = GDLayerNorm.backward(
        state, hyper, x, y, err, solver="momentum", include_bias=True,
        need_err_input=False, eps=1e-5)
    numpy.testing.assert_allclose(
        numpy.asarray(gamma) - numpy.asarray(new_state["weights"]),
        numpy.asarray(gw), rtol=1e-4, atol=1e-5)
    numpy.testing.assert_allclose(
        numpy.asarray(beta) - numpy.asarray(new_state["bias"]),
        numpy.asarray(gb), rtol=1e-4, atol=1e-5)


def test_transformer_block_shapes_and_gd_guard():
    """Block keeps (B, T, D); a poisoned cotangent skips the update
    bit-exactly and cascades a non-finite err_input upstream."""
    from veles_tpu.models.transformer import (GDTransformerBlock,
                                              TransformerBlock,
                                              init_block_params)
    rng = numpy.random.RandomState(8)
    d, hidden = 8, 16
    w, b = init_block_params(d, hidden, rng)
    x = jnp.asarray(rng.randn(3, 5, d), jnp.float32)
    y = TransformerBlock.apply({"weights": w, "bias": b}, x, heads=2,
                               hidden=hidden)
    assert y.shape == x.shape

    state = {"weights": jnp.asarray(w), "bias": jnp.asarray(b),
             "accum_weights": jnp.zeros_like(jnp.asarray(w)),
             "accum_bias": jnp.zeros_like(jnp.asarray(b)),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    err = jnp.full(y.shape, jnp.nan, jnp.float32)
    err_input, new_state = GDTransformerBlock.backward(
        state, hyper, x, y, err, solver="momentum", include_bias=True,
        need_err_input=True, heads=2, hidden=hidden)
    assert int(new_state.pop("skipped")) == 1
    numpy.testing.assert_array_equal(
        numpy.asarray(new_state["weights"]), numpy.asarray(w))
    assert not bool(jnp.isfinite(err_input).all())


def test_workflow_trains_per_unit_chain(cpu_device):
    """The unit chain end to end (per-unit jit path) on digit-row-like
    synthetic sequences: error drops well below chance."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.prng import RandomGenerator

    class SeqLoader(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 32, 96]
            self._calc_class_end_offsets()
            self.create_originals((8, 8))
            rng = numpy.random.RandomState(7)
            t = numpy.arange(8)
            for i in range(self.total_samples):
                label = i % 2
                freq = 0.3 if label == 0 else 0.9
                sig = numpy.sin(freq * t)[:, None].repeat(8, 1)
                self.original_data.mem[i] = (
                    sig + rng.randn(8, 8) * 0.1)
                self.original_labels[i] = label

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "transformer", "heads": 2, "hidden": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: SeqLoader(
            w, minibatch_size=32,
            prng=RandomGenerator("tfm", seed=5)),
        decision_config=dict(max_epochs=8),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    assert sw.decision.epoch_metrics[1] < 25.0


def test_workflow_trains_fused_with_mfu_attribution(cpu_device):
    """StandardWorkflow.fuse over the transformer chain: the fused
    step trains AND publishes its cost-model FLOPs, so mfu_snapshot /
    bwd_snapshot attribute the new workload like conv/MLP."""
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.loader import FullBatchLoader
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.observe import xla_introspect
    from veles_tpu.observe.metrics import registry as _registry
    from veles_tpu.prng import RandomGenerator

    class SeqLoader(FullBatchLoader):
        def load_data(self):
            self.class_lengths[:] = [0, 16, 48]
            self._calc_class_end_offsets()
            self.create_originals((8, 8))
            rng = numpy.random.RandomState(9)
            for i in range(self.total_samples):
                label = i % 2
                base = numpy.full((8, 8), label, numpy.float32)
                self.original_data.mem[i] = (
                    base + rng.randn(8, 8) * 0.2)
                self.original_labels[i] = label

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "transformer", "heads": 2, "hidden": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: SeqLoader(
            w, minibatch_size=16,
            prng=RandomGenerator("tfm-fused", seed=6)),
        decision_config=dict(max_epochs=3),
    )
    trainer = sw.fuse()
    sw.initialize(device=cpu_device)
    sw.run()
    assert sw.decision.epoch_metrics[1] is not None
    assert trainer._step_flops_ is not None
    if trainer._step_flops_ > 0:  # cost analysis available on this jax
        assert _registry.peek("xla.step_flops").value > 0
        # fwd flops from the eval lowering -> bwd attribution feeds
        snap = xla_introspect.bwd_snapshot()
        assert snap is None or "bwd_step_ms" in snap
