"""Numerics health watchdog (docs/health.md), unit level: the in-graph
skip-step guards (fused step + per-unit gd backward), NaN-safe decision
metrics, the divergence detector, the payload finiteness walker, the
server's TTL blacklist / per-slave respawn backoff, and the matmul
non-finite debug guard.  End-to-end chaos runs (rollback, quarantine)
live in tests/test_chaos.py."""

import math

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.dummy import DummyWorkflow
from veles_tpu.health import DivergenceError, all_finite, is_finite_metric
from veles_tpu.loader.base import TRAIN, VALID
from veles_tpu.models.decision import DecisionGD, DecisionMSE
from veles_tpu.models.evaluator import EvaluatorSoftmax, lazy_consec
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator
from tests.test_models import BlobsLoader

pytestmark = pytest.mark.health

NAN = float("nan")


# -- the fused step's in-graph guard --------------------------------------


def _fused_step_fixture(cpu_device):
    from veles_tpu.compiler import (
        build_train_step, extract_state, workflow_plan)
    prng.get().seed(4242)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("health_fused", seed=7)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    plans = workflow_plan(sw)
    state = extract_state(sw)
    step = build_train_step(plans, loss="softmax", donate=False)
    rng = numpy.random.RandomState(0)
    batches = [(rng.randn(64, 16).astype(numpy.float32),
                rng.randint(0, 4, 64).astype(numpy.int32))
               for _ in range(4)]
    return step, state, batches


def _assert_states_equal(sa, sb):
    for ea, eb in zip(sa, sb):
        for key in ea:
            if ea[key] is None:
                assert eb[key] is None
                continue
            numpy.testing.assert_array_equal(
                numpy.asarray(ea[key]), numpy.asarray(eb[key]))


def test_fused_step_nan_grad_skips_bit_exactly(cpu_device):
    """Acceptance: a NaN gradient at step k leaves the state (params
    AND solver accumulators) bit-identical to never having served that
    minibatch — the run with the poisoned step matches the fault-free
    run after the same number of *applied* steps."""
    step, state, batches = _fused_step_fixture(cpu_device)
    bs = numpy.float32(64)

    ref = state  # applied steps: 0, 1, 3
    for i in (0, 1, 3):
        ref, m = step(ref, batches[i][0], batches[i][1], bs)
        assert bool(m["finite"]) and int(m["skipped"]) == 0
        assert math.isfinite(float(m["grad_norm"]))

    got = state  # same, plus a poisoned (skipped) step 2 in between
    for i in (0, 1):
        got, _ = step(got, batches[i][0], batches[i][1], bs)
    got, m = step(got, batches[2][0], batches[2][1], bs,
                  grad_poison=numpy.float32(NAN))
    assert not bool(m["finite"]) and int(m["skipped"]) == 1
    assert not math.isfinite(float(m["grad_norm"]))
    got, _ = step(got, batches[3][0], batches[3][1], bs)

    _assert_states_equal(ref, got)


def test_fused_step_loss_poison_skips(cpu_device):
    """The guard also covers a non-finite LOSS with finite gradients
    (the loss leg of the isfinite reduction)."""
    step, state, batches = _fused_step_fixture(cpu_device)
    bs = numpy.float32(64)
    new, m = step(state, batches[0][0], batches[0][1], bs,
                  loss_poison=numpy.float32(NAN))
    assert int(m["skipped"]) == 1
    assert not math.isfinite(float(m["loss"]))
    # gradients themselves were finite — the skip came from the loss
    assert math.isfinite(float(m["grad_norm"]))
    _assert_states_equal(state, new)


def test_train_epoch_counts_skipped_steps(cpu_device):
    """build_train_epoch surfaces the guard's per-epoch skip count, and
    one poisoned minibatch never contaminates the epoch's state."""
    import jax.numpy as jnp
    from veles_tpu.compiler import (
        build_train_epoch, extract_state, workflow_plan)
    prng.get().seed(4242)
    sw = StandardWorkflow(
        DummyWorkflow().workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("health_epoch", seed=7)),
        decision_config=dict(max_epochs=1),
    )
    sw.initialize(device=cpu_device)
    rng = numpy.random.RandomState(0)
    dataset = rng.randn(256, 16).astype(numpy.float32)
    labels = rng.randint(0, 4, 256).astype(numpy.int32)
    # poison one sample: its minibatch's gradients go non-finite; the
    # scan must skip exactly that one step and report it
    dataset[70, 3] = NAN
    epoch = build_train_epoch(workflow_plan(sw), batch=64,
                              loss="softmax", donate=False)
    order = jnp.arange(256, dtype=jnp.int32)
    new_state, totals = epoch(extract_state(sw), dataset, labels, order)
    assert int(totals["skipped"]) == 1
    for entry in new_state:
        for key, value in entry.items():
            if value is not None:
                assert bool(jnp.isfinite(value).all()), key


# -- the per-unit gd guard ------------------------------------------------


def test_gd_backward_nan_err_skips_update():
    from veles_tpu.models.all2all import All2AllTanh
    from veles_tpu.models.gd import GDTanh

    rng = numpy.random.RandomState(1)
    W = rng.randn(5, 3).astype(numpy.float32)
    b = rng.randn(3).astype(numpy.float32)
    x = rng.randn(8, 5).astype(numpy.float32)
    y = numpy.asarray(All2AllTanh.apply({"weights": W, "bias": b}, x))
    err = rng.randn(8, 3).astype(numpy.float32)
    state = {"weights": W, "bias": b,
             "accum_weights": numpy.zeros_like(W),
             "accum_bias": numpy.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.9,
             "gradient_moment_bias": 0.9, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}

    # finite gradients apply normally and report skipped=0
    _, applied = GDTanh.backward(
        state, hyper, x, y, err, solver="momentum", include_bias=True,
        need_err_input=True)
    assert int(numpy.asarray(applied["skipped"])) == 0
    assert not numpy.array_equal(numpy.asarray(applied["weights"]), W)

    # one NaN in err_output: update skipped, err_input still propagates
    # the poison upstream so the whole chain skips the step
    poisoned = err.copy()
    poisoned[2, 1] = NAN
    err_input, skipped = GDTanh.backward(
        state, hyper, x, y, poisoned, solver="momentum",
        include_bias=True, need_err_input=True)
    assert int(numpy.asarray(skipped["skipped"])) == 1
    for key in ("weights", "bias", "accum_weights", "accum_bias"):
        numpy.testing.assert_array_equal(
            numpy.asarray(skipped[key]), numpy.asarray(state[key]))
    assert not numpy.isfinite(numpy.asarray(err_input)).all()


def test_lazy_consec_counter():
    assert lazy_consec(0, 1) == 1
    assert lazy_consec(1, 1) == 2
    assert lazy_consec(5, 0) == 0
    import jax.numpy as jnp
    assert int(lazy_consec(jnp.int32(3), jnp.int32(1))) == 4
    assert int(lazy_consec(jnp.int32(3), jnp.int32(0))) == 0


# -- NaN-safe decision metrics --------------------------------------------


def _decision(cls=DecisionMSE, **kwargs):
    wf = DummyWorkflow()
    decision = cls(wf, **kwargs)
    decision.class_lengths = [0, 64, 256]
    decision.epoch_number = 0
    decision.last_minibatch = False
    decision.epoch_ended = False
    decision.minibatch_class = TRAIN
    return decision


def test_nan_validation_metric_never_recorded_as_best():
    """`NaN < best` is False, but `best is None or NaN < best` would
    crown NaN the FIRST best — after which nothing ever improves."""
    decision = _decision()
    decision.epoch_metrics[VALID] = NAN
    decision._on_class_ended(VALID)
    assert decision.best_metric is None
    assert not bool(decision.improved)

    decision.epoch_metrics[VALID] = 3.5
    decision._on_class_ended(VALID)
    assert decision.best_metric == 3.5
    assert bool(decision.improved)

    for bad in (NAN, float("inf"), None):
        decision.epoch_metrics[VALID] = bad
        decision._on_class_ended(VALID)
        assert decision.best_metric == 3.5, bad
        assert not bool(decision.improved), bad


def test_nan_train_metric_never_improves_train_best():
    decision = _decision(watchdog=False)
    decision.epoch_metrics[TRAIN] = NAN
    decision._on_class_ended(TRAIN)
    assert decision.best_train_metric is None
    assert not bool(decision.train_improved)
    decision.epoch_metrics[TRAIN] = 1.25
    decision._on_class_ended(TRAIN)
    assert decision.best_train_metric == 1.25


def test_evaluator_softmax_nan_probs_metrics_stay_finite():
    """NaN probabilities must not leak NaN into the (integer) n_err /
    confusion metrics the decision accumulates."""
    probs = numpy.full((6, 3), NAN, numpy.float32)
    labels = numpy.array([0, 1, 2, 0, 1, -1], numpy.int32)
    err, n_err, confusion = EvaluatorSoftmax.compute(
        probs, labels, numpy.float32(6), 3)
    assert int(n_err) >= 0 and int(n_err) <= 5
    assert numpy.issubdtype(numpy.asarray(n_err).dtype, numpy.integer)
    assert numpy.asarray(confusion).sum() == 5  # only valid labels


# -- the divergence detector ----------------------------------------------


class _HealthStub(object):
    def __init__(self, skip_count=0, consecutive_skips=0):
        self.skip_count = skip_count
        self.consecutive_skips = consecutive_skips


class _RecordingWorkflow(object):
    """Duck-typed owner for a decision under test: records divergence
    callbacks instead of rolling back."""

    workflow_mode = "standalone"

    def __init__(self):
        self.divergences = []

    def on_divergence(self, reason):
        self.divergences.append(reason)


def test_consecutive_skip_budget_trips_watchdog():
    decision = _decision(cls=DecisionGD, skip_budget=4)
    recorder = _RecordingWorkflow()
    decision._workflow = recorder
    decision.health_sources = [_HealthStub(skip_count=4,
                                           consecutive_skips=4)]
    decision.epoch_metrics[TRAIN] = 10.0
    decision._check_divergence()
    assert len(recorder.divergences) == 1
    assert "consecutive" in recorder.divergences[0]
    assert bool(decision.diverged)


def test_skips_below_budget_warn_but_do_not_trip():
    decision = _decision(cls=DecisionGD, skip_budget=4)
    recorder = _RecordingWorkflow()
    decision._workflow = recorder
    decision.health_sources = [_HealthStub(skip_count=2,
                                           consecutive_skips=1)]
    decision.epoch_metrics[TRAIN] = 10.0
    decision._check_divergence()
    assert not recorder.divergences
    assert not bool(decision.diverged)


def test_ema_spike_trips_watchdog():
    decision = _decision(cls=DecisionGD, spike_factor=3.0,
                         spike_floor=1.0)
    recorder = _RecordingWorkflow()
    decision._workflow = recorder
    decision.health_sources = []
    for metric in (8.0, 7.0, 6.5):  # healthy declining history
        decision.epoch_metrics[TRAIN] = metric
        decision._check_divergence()
    assert not recorder.divergences
    decision.epoch_metrics[TRAIN] = 80.0  # blow-up
    decision._check_divergence()
    assert len(recorder.divergences) == 1
    assert "spiked" in recorder.divergences[0]


def test_nonfinite_train_metric_trips_watchdog():
    decision = _decision(cls=DecisionMSE)
    recorder = _RecordingWorkflow()
    decision._workflow = recorder
    decision.health_sources = []
    decision.epoch_metrics[TRAIN] = NAN
    decision._check_divergence()
    assert len(recorder.divergences) == 1
    assert "non-finite train metric" in recorder.divergences[0]


def test_divergence_without_handler_raises_loudly():
    decision = _decision(cls=DecisionGD, skip_budget=1)

    class _NoHook(object):
        workflow_mode = "standalone"
    decision._workflow = _NoHook()
    decision.health_sources = [_HealthStub(skip_count=2,
                                           consecutive_skips=2)]
    decision.epoch_metrics[TRAIN] = 10.0
    with pytest.raises(DivergenceError):
        decision._check_divergence()


def test_reset_divergence_restarts_window():
    decision = _decision(cls=DecisionGD, skip_budget=2)
    recorder = _RecordingWorkflow()
    decision._workflow = recorder
    source = _HealthStub(skip_count=3, consecutive_skips=3)
    decision.health_sources = [source]
    decision.epoch_metrics[TRAIN] = 10.0
    decision._check_divergence()
    assert len(recorder.divergences) == 1
    # the workflow's recovery hook zeroes counters + resets the window
    source.skip_count = source.consecutive_skips = 0
    decision.reset_divergence()
    assert not bool(decision.diverged)
    decision._check_divergence()
    assert len(recorder.divergences) == 1  # no re-trip on stale state


# -- payload finiteness walker --------------------------------------------


def test_all_finite_walker():
    good = [{"n_err": [1, 2, 3]},
            {"weights": numpy.ones((4, 4), numpy.float32),
             "bias": numpy.zeros(4)},
            None, "text", 7, 3.5, (1.0, 2.0),
            numpy.arange(5),  # int array: vacuously finite
            numpy.float64(2.5)]
    assert all_finite(good)
    assert not all_finite(NAN)
    assert not all_finite(float("inf"))
    assert not all_finite([{"weights": numpy.array([1.0, NAN])}])
    assert not all_finite({"a": {"b": [numpy.float32(NAN)]}})
    assert not all_finite((1.0, float("-inf")))
    # non-numeric leaves never fail the check
    assert all_finite({"s": b"bytes", "flag": True, "none": None})


def test_is_finite_metric():
    assert is_finite_metric(0.0) and is_finite_metric(5)
    assert not is_finite_metric(None)
    assert not is_finite_metric(NAN)
    assert not is_finite_metric(float("inf"))
    assert not is_finite_metric("nope")


# -- server: TTL blacklist + per-slave respawn backoff --------------------


class _StubMasterWorkflow(object):
    checksum = "stub"


def test_blacklist_ttl_expires():
    from veles_tpu.server import Server
    server = Server("127.0.0.1:0", _StubMasterWorkflow(),
                    blacklist_ttl=30.0)
    server._blacklist("m1")
    assert server._blacklisted("m1")
    assert not server._blacklisted("m2")
    # force-expire: the slave becomes eligible again and the entry is
    # dropped (no unbounded growth over a long run)
    server.blacklist["m1"] = 0.0
    assert not server._blacklisted("m1")
    assert "m1" not in server.blacklist


def test_respawn_backoff_is_per_slave():
    from veles_tpu.server import Server
    server = Server("127.0.0.1:0", _StubMasterWorkflow())
    # consecutive failures of ONE slave back off exponentially...
    assert server._respawn_delay("a") == 2.0
    assert server._respawn_delay("a") == 4.0
    assert server._respawn_delay("a") == 8.0
    # ...without inflating an unrelated slave's first delay (the old
    # formula used the GLOBAL blacklist size)
    assert server._respawn_delay("b") == 2.0
    for _ in range(10):
        delay = server._respawn_delay("a")
    assert delay == 30.0  # capped
    # a productive update resets the per-slave counter
    server._respawn_attempts.pop("a", None)
    assert server._respawn_delay("a") == 2.0


# -- matmul non-finite debug guard ----------------------------------------


def test_matmul_debug_guard_raises_with_stats(monkeypatch):
    import importlib
    # veles_tpu.ops re-exports the matmul FUNCTION; fetch the module
    matmul_mod = importlib.import_module("veles_tpu.ops.matmul")
    a = numpy.ones((8, 16), numpy.float32)
    a[3, 2] = numpy.inf
    b = numpy.ones((16, 8), numpy.float32)
    # guard off (default): non-finite output passes through silently
    out = matmul_mod.matmul(a, b)
    assert not numpy.isfinite(numpy.asarray(out)).all()
    # guard on: raises with operand stats naming the non-finite count
    # (the flag lives in ops.common — the kernels' one env contract)
    common_mod = importlib.import_module("veles_tpu.ops.common")
    monkeypatch.setattr(common_mod, "DEBUG_NONFINITE", True)
    with pytest.raises(FloatingPointError) as excinfo:
        matmul_mod.matmul(a, b)
    message = str(excinfo.value)
    assert "lhs" in message and "1 non-finite" in message


def test_matmul_debug_guard_names_bf16_domain(monkeypatch):
    """Finite-but-huge f32 operands land outside the level-0 bf16x3
    domain; the guard must say so (and point at precision_level>=1)."""
    import importlib
    # veles_tpu.ops re-exports the matmul FUNCTION; fetch the module
    matmul_mod = importlib.import_module("veles_tpu.ops.matmul")
    # f32 max exceeds bf16 max (~3.39e38): finite f32, inf as bf16
    big = float(numpy.finfo(numpy.float32).max)
    a = numpy.full((8, 16), big, numpy.float32)
    b = numpy.full((16, 8), 1.0, numpy.float32)
    out = matmul_mod.matmul(a, b)
    if numpy.isfinite(numpy.asarray(out)).all():
        pytest.skip("interpret-mode decomposition stayed finite here")
    common_mod = importlib.import_module("veles_tpu.ops.common")
    monkeypatch.setattr(common_mod, "DEBUG_NONFINITE", True)
    with pytest.raises(FloatingPointError) as excinfo:
        matmul_mod.matmul(a, b)
    assert "bf16x3 domain" in str(excinfo.value)
