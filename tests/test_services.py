"""Service layer tests: plotters (render golden PNGs), graphics
pub/sub transport, web status HTTP, RESTful serving, publisher reports
(reference test model: plotter PNG goldens in veles/tests/res, web
status + forge HTTP tests)."""

import json
import os
import urllib.request

import numpy
import pytest

from veles_tpu.dummy import DummyUnit, DummyWorkflow
from veles_tpu.memory import Array
from veles_tpu.plotting_units import (
    AccumulatingPlotter, Histogram, ImagePlotter, MatrixPlotter,
    MultiHistogram, SlaveStats, TableMaxMin)
from veles_tpu.prng import RandomGenerator


@pytest.fixture(scope="module")
def trained(request):
    from veles_tpu.backends import Device
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from tests.test_models import BlobsLoader
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64, prng=RandomGenerator("svc", seed=12)),
        decision_config=dict(max_epochs=3),
    )
    sw.initialize(device=Device(backend="cpu"))
    sw.run()
    return sw


def test_plotters_render_pngs(tmp_path):
    from veles_tpu.graphics_client import render_plot
    wf = DummyWorkflow()
    rng = numpy.random.RandomState(0)

    acc = AccumulatingPlotter(wf, label="err")
    acc.input = 5.0
    for v in (5.0, 3.0, 2.0, 1.5):
        acc.input = v
        acc.capture()

    mat = MatrixPlotter(wf)
    mat.input = Array(rng.randint(0, 50, (4, 4)).astype(numpy.int32))
    mat.capture()

    img = ImagePlotter(wf)
    img.input = Array(rng.rand(2, 8, 8).astype(numpy.float32))
    img.capture()

    hist = Histogram(wf)
    hist.input = Array(rng.randn(500).astype(numpy.float32))
    hist.capture()

    multi = MultiHistogram(wf)
    multi.inputs = [Array(rng.randn(100).astype(numpy.float32)),
                    Array(rng.randn(100).astype(numpy.float32))]
    multi.capture()

    table = TableMaxMin(wf)
    table.names = ["w0", "b0"]
    table.inputs = [Array(rng.randn(10).astype(numpy.float32)),
                    Array(rng.randn(5).astype(numpy.float32))]
    table.capture()

    stats = SlaveStats(wf)
    stats.capture()

    for plot in (acc, mat, img, hist, multi, table, stats):
        path = render_plot(plot, str(tmp_path))
        assert os.path.getsize(path) > 500, type(plot).__name__


def test_graphics_pubsub_roundtrip(tmp_path):
    import zmq

    from veles_tpu.graphics_server import GraphicsServer
    from veles_tpu import plotter as plotter_module

    server = GraphicsServer()
    context = zmq.Context.instance()
    sub = context.socket(zmq.SUB)
    sub.connect(server.endpoints["tcp"])
    sub.setsockopt(zmq.SUBSCRIBE, b"")
    import time
    time.sleep(0.2)  # PUB/SUB join

    wf = DummyWorkflow()
    acc = AccumulatingPlotter(wf, label="loss")
    acc.values = [3.0, 2.0, 1.0]
    server.publish(acc)

    assert sub.poll(3000), "no plot frame received"
    plot = plotter_module.loads(sub.recv())
    assert isinstance(plot, AccumulatingPlotter)
    assert plot.values == [3.0, 2.0, 1.0]
    sub.close(0)
    server.shutdown()


def test_web_status_roundtrip(trained):
    from veles_tpu.web_status import StatusReporter, WebStatusServer
    server = WebStatusServer()
    server.start_background()
    try:
        reporter = StatusReporter(
            "http://127.0.0.1:%d" % server.port, "sess1", trained)
        assert reporter.post()["result"] == "ok"
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/status.json" % server.port) as r:
            sessions = json.loads(r.read())
        assert len(sessions) == 1
        assert sessions[0]["id"] == "sess1"
        assert sessions[0]["epoch"] == 3
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/" % server.port) as r:
            page = r.read().decode()
        assert "sess1" in page
        # live JS dashboard (reference web/ frontend role): the detail
        # page embeds the client and the static file serves
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/session/sess1" % server.port) as r:
            detail = r.read().decode()
        assert 'data-sid="sess1"' in detail
        assert "/static/live.js" in detail
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/static/live.js" % server.port) as r:
            js = r.read().decode()
        assert "extractSeries" in js and "crosshair" in js
    finally:
        server.stop()


def test_restful_api_serves_inference(trained):
    from veles_tpu.restful_api import RESTfulAPI
    api = RESTfulAPI(trained)
    api.initialize()
    api.start_background()
    try:
        loader = trained.loader
        x = loader.original_data.mem[0]
        req = urllib.request.Request(
            "http://127.0.0.1:%d/api" % api.port,
            data=json.dumps({"input": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            answer = json.loads(resp.read())
        assert answer["result"] == loader.original_labels[0]
        assert abs(sum(answer["probabilities"][0]) - 1.0) < 1e-3
        assert api.requests_served == 1
    finally:
        api.stop()


def test_publisher_markdown_and_html(tmp_path, trained):
    from veles_tpu.publishing import HTMLBackend, MarkdownBackend, \
        Publisher
    pub = Publisher(trained, backends=[
        MarkdownBackend(str(tmp_path)), HTMLBackend(str(tmp_path))])
    pub.initialize()
    pub.run()
    md = open(os.path.join(str(tmp_path), "report.md")).read()
    assert "Training report: StandardWorkflow" in md
    assert "validation" in md
    assert "| BlobsLoader |" in md
    html = open(os.path.join(str(tmp_path), "report.html")).read()
    assert "StandardWorkflow" in html


def test_standard_workflow_plotters(tmp_path):
    """link_plotters wires epoch-curve/confusion/histogram plotters into
    the training loop and they render after a real run."""
    from veles_tpu.backends import Device
    from veles_tpu.graphics_client import render_plot
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from tests.test_models import BlobsLoader

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("plotwf", seed=13)),
        decision_config=dict(max_epochs=3),
    )
    plotters = sw.link_plotters()
    sw.initialize(device=Device(backend="cpu"))
    sw.run()
    curves = plotters[0]
    assert len(curves.values) == 3  # one point per epoch
    for plot in plotters:
        import os as _os
        path = render_plot(plot, str(tmp_path))
        assert _os.path.getsize(path) > 500


def test_gather_results(tmp_path):
    """Loader + decision contribute IResultProvider metrics."""
    import json as _json
    from veles_tpu.backends import Device
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from tests.test_models import BlobsLoader

    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 4,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: BlobsLoader(
            w, minibatch_size=64,
            prng=RandomGenerator("results", seed=14)),
        decision_config=dict(max_epochs=2),
    )
    sw.initialize(device=Device(backend="cpu"))
    sw.run()
    results = sw.gather_results()
    assert results["Total epochs"] == 2
    assert results["Errors"]["validation"] is not None
    path = str(tmp_path / "r.json")
    sw.write_results(path)
    assert "Errors" in _json.load(open(path))


def test_web_status_history_events_and_sqlite(tmp_path, trained):
    """Deepened web status (reference web_status.py:113 Mongo roles):
    per-session status history + event log, sqlite persistence that
    survives a server restart, dashboard detail page with sparkline."""
    from veles_tpu.web_status import StatusReporter, WebStatusServer
    db = str(tmp_path / "status.sqlite")
    server = WebStatusServer(db_path=db)
    server.start_background()
    try:
        base = "http://127.0.0.1:%d" % server.port
        reporter = StatusReporter(base, "sess-h", trained)
        for _ in range(3):
            assert reporter.post()["result"] == "ok"
        assert reporter.post_event("epoch 1 done")["result"] == "ok"
        with urllib.request.urlopen(base + "/session/sess-h.json") as r:
            history = json.loads(r.read())
        assert len(history) == 3
        with urllib.request.urlopen(base + "/events/sess-h.json") as r:
            events = json.loads(r.read())
        assert events and events[0][1] == "epoch 1 done"
        with urllib.request.urlopen(base + "/session/sess-h") as r:
            page = r.read().decode()
        assert "epoch 1 done" in page
    finally:
        server.stop()

    # restart on the same sqlite file: sessions + events come back
    server2 = WebStatusServer(db_path=db)
    server2.start_background()
    try:
        base = "http://127.0.0.1:%d" % server2.port
        with urllib.request.urlopen(base + "/status.json") as r:
            sessions = json.loads(r.read())
        assert [s["id"] for s in sessions] == ["sess-h"]
        with urllib.request.urlopen(base + "/session/sess-h.json") as r:
            assert len(json.loads(r.read())) == 3
        with urllib.request.urlopen(base + "/events/sess-h.json") as r:
            assert json.loads(r.read())[0][1] == "epoch 1 done"
    finally:
        server2.stop()


def test_web_status_sparkline_rendering():
    from veles_tpu.web_status import _metric_history, _sparkline
    history = [{"metrics": {"err_pct": v}} for v in (9.0, 5.0, 3.0, 2.5)]
    points = _metric_history(history)
    assert points == [9.0, 5.0, 3.0, 2.5]
    svg = _sparkline(points)
    assert svg.startswith("<svg") and "polyline" in svg
    assert "2.5" in svg  # last-value direct label
    assert _sparkline([1.0]) == ""  # too short: no chart
    # list-shaped metrics (StatusReporter ships epoch_metrics as
    # [test, validation, train]) key by index; bools never hijack
    lists = [{"metrics": [None, v, v + 1]} for v in (4.0, 2.0)]
    assert _metric_history(lists) == [4.0, 2.0]
    bools = [{"metrics": {"done": False, "err": v}} for v in (3.0, 1.0)]
    assert _metric_history(bools) == [3.0, 1.0]


def test_launcher_posts_status_periodically(tmp_path):
    """Launcher wiring (reference launcher.py:852-885): with
    web_status set, the session posts periodic status while running
    and a final post after the run ends."""
    from veles_tpu.backends import Device
    from veles_tpu.dummy import DummyWorkflow
    from veles_tpu.launcher import Launcher
    from veles_tpu.models.nn_workflow import StandardWorkflow
    from veles_tpu.web_status import WebStatusServer
    from tests.test_models import BlobsLoader

    server = WebStatusServer()
    server.start_background()
    try:
        launcher = Launcher(
            web_status="http://127.0.0.1:%d" % server.port,
            # small enough that even a fully compile-warm in-suite run
            # (later tests pre-warm these exact layer shapes) spans at
            # least one periodic post before the final one
            notification_interval=0.02)
        sw = StandardWorkflow(
            launcher,
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 4,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
            ],
            loader_factory=lambda w: BlobsLoader(
                w, minibatch_size=64,
                prng=RandomGenerator("wsl", seed=7)),
            decision_config=dict(max_epochs=3),
        )
        launcher.initialize(device=Device(backend="cpu"))
        launcher.run()
        sessions = server.store.list_sessions()
        assert len(sessions) == 1
        post = sessions[0]
        assert post["workflow"] == "StandardWorkflow"
        assert post["epoch"] == 3  # the final post reflects the end state
        assert post["mode"] == "standalone"
        # PERIODIC posting, not just the final flush: the run must
        # leave more than one history entry
        assert len(server.store.get_history(post["id"])) > 1
        # Logger.event records reach the dashboard's event log too
        events = server.store.get_events(post["id"])
        assert events, "no events forwarded"
        assert any('"name": "run"' in text for _, text in events), events
    finally:
        server.stop()
