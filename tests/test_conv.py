"""Conv/pooling/dropout tests: backward-vs-autodiff oracles and a
LeNet-style conv workflow end-to-end on synthetic images."""

import numpy
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader import FullBatchLoader
from veles_tpu.models.conv import Conv, ConvTanh
from veles_tpu.models.gd_conv import GDConvTanh
from veles_tpu.models.pooling import AvgPooling, MaxPooling
from veles_tpu.models.gd_pooling import GDAvgPooling, GDMaxPooling
from veles_tpu.models.nn_workflow import StandardWorkflow
from veles_tpu.prng import RandomGenerator


def test_conv_forward_shape_and_value():
    rng = numpy.random.RandomState(0)
    x = rng.randn(2, 8, 8, 3).astype(numpy.float32)
    W = rng.randn(3, 3, 3, 5).astype(numpy.float32)
    b = rng.randn(5).astype(numpy.float32)
    y = numpy.asarray(Conv.apply(
        {"weights": W, "bias": b}, x, padding=(1, 1, 1, 1),
        sliding=(1, 1)))
    assert y.shape == (2, 8, 8, 5)
    # spot-check one output against a manual dot product
    patch = numpy.zeros((3, 3, 3), numpy.float32)
    patch[:, :, :] = x[0, 0:3, 0:3, :]
    manual = (patch[..., None] * W).sum((0, 1, 2)) + b
    numpy.testing.assert_allclose(y[0, 1, 1], manual, rtol=1e-4)


def test_gd_conv_matches_autodiff():
    rng = numpy.random.RandomState(1)
    x = rng.randn(4, 6, 6, 2).astype(numpy.float32)
    W = (rng.randn(3, 3, 2, 4) * 0.5).astype(numpy.float32)
    b = numpy.zeros(4, numpy.float32)
    y = numpy.asarray(ConvTanh.apply(
        {"weights": W, "bias": b}, x, padding=(0, 0, 0, 0),
        sliding=(1, 1)))
    err_const = rng.randn(*y.shape).astype(numpy.float32)

    def loss(params, xv):
        out = ConvTanh.apply(params, xv, padding=(0, 0, 0, 0),
                             sliding=(1, 1))
        return jnp.sum(out * err_const)

    grads = jax.grad(loss, argnums=(0, 1))({"weights": W, "bias": b}, x)

    state = {"weights": W, "bias": b,
             "accum_weights": numpy.zeros_like(W),
             "accum_bias": numpy.zeros_like(b),
             "accum2_weights": None, "accum2_bias": None}
    hyper = {"learning_rate": 0.1, "learning_rate_bias": 0.1,
             "weights_decay": 0.0, "weights_decay_bias": 0.0,
             "l1_vs_l2": 0.0, "gradient_moment": 0.0,
             "gradient_moment_bias": 0.0, "adadelta_rho": 0.95,
             "solver_epsilon": 1e-6}
    err_input, new_state = GDConvTanh.backward(
        state, hyper, x, y, err_const, solver="momentum",
        include_bias=True, need_err_input=True,
        padding=(0, 0, 0, 0), sliding=(1, 1))

    numpy.testing.assert_allclose(
        numpy.asarray(new_state["weights"]),
        W - 0.1 * numpy.asarray(grads[0]["weights"]), rtol=1e-3,
        atol=1e-4)
    numpy.testing.assert_allclose(
        numpy.asarray(err_input), numpy.asarray(grads[1]), rtol=1e-3,
        atol=1e-4)


@pytest.mark.parametrize("pool_cls,gd_cls", [
    (MaxPooling, GDMaxPooling), (AvgPooling, GDAvgPooling)])
def test_gd_pooling_matches_autodiff(pool_cls, gd_cls):
    rng = numpy.random.RandomState(2)
    x = rng.randn(3, 6, 6, 2).astype(numpy.float32)
    y = numpy.asarray(pool_cls.apply({}, x, window=(2, 2), sliding=(2, 2)))
    assert y.shape == (3, 3, 3, 2)
    err_const = rng.randn(*y.shape).astype(numpy.float32)

    def loss(xv):
        return jnp.sum(pool_cls.apply({}, xv, window=(2, 2),
                                      sliding=(2, 2)) * err_const)

    gx = numpy.asarray(jax.grad(loss)(x))
    err_input, _ = gd_cls.backward(
        {"weights": None}, {}, x, y, err_const, solver="momentum",
        include_bias=False, need_err_input=True, window=(2, 2),
        sliding=(2, 2))
    numpy.testing.assert_allclose(numpy.asarray(err_input), gx, rtol=1e-4,
                                  atol=1e-5)


def test_pooling_ceil_mode_covers_input():
    x = numpy.arange(25, dtype=numpy.float32).reshape(1, 5, 5, 1)
    y = numpy.asarray(MaxPooling.apply({}, x, window=(2, 2),
                                       sliding=(2, 2)))
    assert y.shape == (1, 3, 3, 1)
    assert y[0, 2, 2, 0] == 24  # bottom-right partial window


# ------------------------------------------------------------- end-to-end

class TinyImageLoader(FullBatchLoader):
    """8x8 synthetic 3-class images: class = which quadrant is bright."""

    def load_data(self):
        self.class_lengths[:] = [0, 48, 192]
        self._calc_class_end_offsets()
        self.create_originals((8, 8, 1))
        rng = numpy.random.RandomState(5)
        for i in range(self.total_samples):
            label = i % 3
            img = rng.rand(8, 8, 1).astype(numpy.float32) * 0.3
            r, c = divmod(label, 2)
            img[r * 4:(r + 1) * 4, c * 4:(c + 1) * 4, 0] += 1.0
            self.original_data.mem[i] = img
            self.original_labels[i] = label


def test_lenet_style_workflow_trains(cpu_device):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "conv_tanh", "n_kernels": 8, "kx": 3, "ky": 3,
             "padding": 1, "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_tanh", "output_sample_shape": 24,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 3,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: TinyImageLoader(
            w, minibatch_size=48, prng=RandomGenerator("img", seed=3)),
        decision_config=dict(max_epochs=8),
    )
    sw.initialize(device=cpu_device)
    assert sw.forwards[0].weights.shape == (3, 3, 1, 8)
    assert sw.forwards[1].output.shape == (48, 4, 4, 8)
    sw.run()
    assert sw.decision.epoch_metrics[1] is not None
    assert sw.decision.epoch_metrics[1] < 10.0, \
        "validation error %.2f%%" % sw.decision.epoch_metrics[1]


def test_dropout_workflow_trains(cpu_device):
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 32,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "dropout", "dropout_ratio": 0.2},
            {"type": "softmax", "output_sample_shape": 3,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader_factory=lambda w: TinyImageLoader(
            w, minibatch_size=48, prng=RandomGenerator("img2", seed=4)),
        decision_config=dict(max_epochs=8),
    )
    sw.initialize(device=cpu_device)
    sw.run()
    assert sw.decision.epoch_metrics[1] < 15.0


def test_fused_conv_workflow_matches(cpu_device):
    """compiler fuses conv+pooling plans too."""
    from veles_tpu.compiler import build_train_step, workflow_plan
    wf = DummyWorkflow()
    sw = StandardWorkflow(
        wf.workflow,
        layers=[
            {"type": "conv_tanh", "n_kernels": 4, "kx": 3, "ky": 3,
             "learning_rate": 0.1},
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 3,
             "learning_rate": 0.1},
        ],
        loader_factory=lambda w: TinyImageLoader(
            w, minibatch_size=48, prng=RandomGenerator("img3", seed=5)),
        decision_config=dict(max_epochs=2),
    )
    sw.initialize(device=cpu_device)
    plans = workflow_plan(sw)
    step = build_train_step(plans, donate=False)
    from veles_tpu.compiler import extract_state
    state = extract_state(sw)
    rng = numpy.random.RandomState(0)
    x = rng.rand(48, 8, 8, 1).astype(numpy.float32)
    labels = rng.randint(0, 3, 48).astype(numpy.int32)
    new_state, metrics = step(state, x, labels, numpy.float32(48))
    assert numpy.isfinite(float(metrics["loss"]))
    assert new_state[0]["weights"].shape == (3, 3, 1, 4)
