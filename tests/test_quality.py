"""Real-data model quality (reference test model: the Znicz sample
workflows pinned to the quality table in
manualrst_veles_algorithms.rst:31,50).

Offline anchor: sklearn's bundled real handwritten digits through the
FULL loader->workflow->decision->snapshotter graph.  MNIST/CIFAR runs
execute when their datasets are cached (no network in CI)."""

import gzip
import os
import struct
import sys

import numpy
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples"))

from veles_tpu.datasets import (
    DatasetNotFound, DigitsLoader, digits_arrays, load_idx, mnist_arrays)


def test_load_idx_roundtrip(tmp_path):
    arr = numpy.arange(24, dtype=numpy.uint8).reshape(2, 3, 4)
    raw = struct.pack(">HBB", 0, 0x08, 3)
    raw += struct.pack(">III", 2, 3, 4) + arr.tobytes()
    p = tmp_path / "t.idx"
    p.write_bytes(raw)
    numpy.testing.assert_array_equal(load_idx(str(p)), arr)
    gz = tmp_path / "t.idx.gz"
    gz.write_bytes(gzip.compress(raw))
    numpy.testing.assert_array_equal(load_idx(str(gz)), arr)
    # int32 big-endian payload
    arr32 = numpy.array([[1, -2], [300000, 4]], dtype=">i4")
    raw32 = struct.pack(">HBB", 0, 0x0C, 2) + struct.pack(
        ">II", 2, 2) + arr32.tobytes()
    p32 = tmp_path / "t32.idx"
    p32.write_bytes(raw32)
    numpy.testing.assert_array_equal(load_idx(str(p32)), arr32)


def _write_idx(path, arr, dtype_code=0x08):
    raw = struct.pack(">HBB", 0, dtype_code, arr.ndim)
    raw += struct.pack(">" + "I" * arr.ndim, *arr.shape) + arr.tobytes()
    path.write_bytes(gzip.compress(raw) if str(path).endswith(".gz")
                     else raw)


def _write_stl10_drop(data_dir, rng):
    """Canonical-shaped synthetic STL-10 binaries under data_dir."""
    base = data_dir / "stl10_binary"
    base.mkdir(exist_ok=True)
    for x_name, y_name, count in (("train_X.bin", "train_y.bin", 5000),
                                  ("test_X.bin", "test_y.bin", 8000)):
        (base / x_name).write_bytes(
            rng.randint(0, 256, count * 3 * 96 * 96,
                        dtype=numpy.uint8).tobytes())
        (base / y_name).write_bytes(
            rng.randint(1, 11, count, dtype=numpy.uint8).tobytes())
    return base


def _write_cifar10_drop(data_dir, rng):
    """Canonical-shaped synthetic CIFAR-10 python batches."""
    import pickle
    base = data_dir / "cifar-10-batches-py"
    base.mkdir(exist_ok=True)
    for name in ["data_batch_%d" % i for i in range(1, 6)] + [
            "test_batch"]:
        with open(base / name, "wb") as fout:
            pickle.dump({
                b"data": rng.randint(0, 256, (10000, 3072),
                                     dtype=numpy.uint8),
                b"labels": rng.randint(0, 10, 10000).tolist(),
            }, fout)
    return base


def _write_mnist_drop(data_dir, rng=None):
    """Canonical-shaped synthetic MNIST idx files (uncompressed names;
    _fetch accepts the .gz name minus .gz).  ``rng=None`` writes
    all-zero files — same shapes, much faster for ingest tests."""
    from veles_tpu.datasets import MNIST_FILES
    for key, filename in MNIST_FILES.items():
        count = 60000 if key.startswith("train") else 10000
        shape = (count, 28, 28) if key.endswith("images") else (count,)
        if rng is None:
            arr = numpy.zeros(shape, numpy.uint8)
        elif key.endswith("images"):
            arr = rng.randint(0, 256, shape, dtype=numpy.uint8)
        else:
            arr = rng.randint(0, 10, count, dtype=numpy.uint8)
        _write_idx(data_dir / filename[:-3], arr)


def test_mnist_selfcheck_rejects_wrong_drop(tmp_path):
    """A data drop with non-canonical shapes must fail the self-check
    with a clear message, not surface as a training-time shape error
    (round-3 verdict item 5)."""
    from veles_tpu.datasets import MNIST_FILES
    wrong = numpy.zeros((5, 28, 28), numpy.uint8)
    labels = numpy.zeros(5, numpy.uint8)
    for key, filename in MNIST_FILES.items():
        _write_idx(tmp_path / filename,
                   wrong if key.endswith("images") else labels)
    with pytest.raises(DatasetNotFound, match="self-check failed"):
        mnist_arrays(str(tmp_path))


def test_cifar_selfcheck_rejects_truncated_drop(tmp_path):
    """Truncated CIFAR batches fail the shape self-check loudly."""
    import pickle
    from veles_tpu.datasets import cifar10_arrays
    base = tmp_path / "cifar-10-batches-py"
    base.mkdir()
    batch = {b"data": numpy.zeros((7, 3072), numpy.uint8),
             b"labels": [0] * 7}
    for name in ["data_batch_%d" % i for i in range(1, 6)] + [
            "test_batch"]:
        with open(base / name, "wb") as fout:
            pickle.dump(batch, fout)
    with pytest.raises(DatasetNotFound, match="self-check failed"):
        cifar10_arrays(str(tmp_path))


def test_selfcheck_reports_missing_when_no_drop(tmp_path):
    from veles_tpu.datasets import selfcheck
    report = selfcheck(str(tmp_path))
    assert report["mnist"]["status"] == "missing"
    assert report["cifar10"]["status"] == "missing"
    assert report["stl10"]["status"] == "missing"


def test_ingest_stages_drop_and_selfchecks(tmp_path):
    """The one-command data drop (VERDICT r04 task 3): canonical-format
    files anywhere under a directory land in the cache, parse, and
    come back checksummed in the report."""
    import pickle

    from veles_tpu.datasets import ingest, mnist_arrays

    drop = tmp_path / "drop" / "nested"
    drop.mkdir(parents=True)
    cache = tmp_path / "cache"
    cache.mkdir()
    _write_mnist_drop(drop)
    cdir = drop / "cifar-10-batches-py"
    cdir.mkdir()
    batch = {b"data": numpy.zeros((10000, 3072), numpy.uint8),
             b"labels": [0] * 10000}
    for name in ["data_batch_%d" % i for i in range(1, 6)] + [
            "test_batch"]:
        with open(cdir / name, "wb") as fout:
            pickle.dump(batch, fout)

    report = ingest(str(tmp_path / "drop"), str(cache))
    assert report["mnist"]["status"] == "ok"
    assert report["cifar10"]["status"] == "ok"
    assert report["stl10"]["status"] == "missing"
    assert len(report["cifar10"]["files"]) == 6  # checksummed
    assert len(report["ingested"]["files"]) == 10
    # the staged data actually trains: arrays load from the cache
    tx, ty, vx, vy = mnist_arrays(str(cache))
    assert tx.shape == (60000, 784) and vx.shape == (10000, 784)


def test_ingest_cli_command(tmp_path):
    """python -m veles_tpu.datasets ingest <dir> prints the JSON
    report and exits 0 when something validated."""
    import json
    import subprocess
    import sys

    drop = tmp_path / "drop"
    drop.mkdir()
    cache = tmp_path / "cache"
    cache.mkdir()
    _write_mnist_drop(drop)
    env = dict(os.environ, JAX_PLATFORMS="cpu", VELES_BACKEND="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "veles_tpu.datasets", "ingest",
         str(drop), "--data-dir", str(cache)],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-1500:]
    report = json.loads(proc.stdout)
    assert report["mnist"]["status"] == "ok"
    assert report["mnist"]["source"] == "idx"


@pytest.mark.slow
def test_stl10_drop_parses_and_selfchecks(tmp_path):
    """A canonical-shaped STL-10 drop parses (channel-major,
    column-major layout; 1-indexed labels) and passes the self-check;
    wrong sizes fail loudly.  (slow: writes + reloads a full-size
    360 MB drop)"""
    from veles_tpu.datasets import stl10_arrays

    base = _write_stl10_drop(tmp_path, numpy.random.RandomState(0))

    tx, ty, vx, vy = stl10_arrays(str(tmp_path))
    assert tx.shape == (5000, 96, 96, 3) and vx.shape == (8000, 96, 96, 3)
    assert 0.0 <= tx.min() and tx.max() <= 1.0
    assert ty.min() >= 0 and ty.max() <= 9  # rebased from 1..10

    # layout: byte b of image 0 channel 0 lands at [col, row] transposed
    raw = numpy.fromfile(base / "train_X.bin", numpy.uint8)
    img0 = raw[:3 * 96 * 96].reshape(3, 96, 96)
    numpy.testing.assert_allclose(
        tx[0, 5, 7, 2], img0[2, 7, 5] / 255.0, rtol=1e-6)

    # truncated drop fails the self-check with a clear message
    (base / "test_X.bin").write_bytes(b"\0" * 1000)
    with pytest.raises(DatasetNotFound, match="self-check failed"):
        stl10_arrays(str(tmp_path))


def test_digits_arrays_deterministic_real_data():
    tx, ty, vx, vy = digits_arrays()
    assert tx.shape == (1437, 64) and vx.shape == (360, 64)
    assert tx.dtype == numpy.float32 and ty.dtype == numpy.int32
    assert 0.0 <= tx.min() and tx.max() <= 1.0
    assert set(numpy.unique(vy)) <= set(range(10))
    tx2, ty2, _, _ = digits_arrays()
    numpy.testing.assert_array_equal(tx, tx2)
    numpy.testing.assert_array_equal(ty, ty2)


def test_digits_loader_contract(cpu_device):
    from veles_tpu.dummy import DummyWorkflow
    wf = DummyWorkflow()
    loader = DigitsLoader(wf.workflow, minibatch_size=48)
    loader.initialize(device=cpu_device)
    assert loader.class_lengths[1] == 360
    assert loader.class_lengths[2] == 1437
    assert loader.shape == (64,)


@pytest.mark.slow
def test_digits_quality_via_full_graph(cpu_device):
    """The committed QUALITY.json number stays reached: <= 2.5 %
    validation error on real digits through the full graph (measured
    1.39 % — see scripts/quality.py)."""
    import digits as digits_example
    from veles_tpu.launcher import Launcher

    launcher = Launcher()
    workflow = digits_example.build(launcher)
    launcher.initialize(device="cpu")
    launcher.run()
    best = workflow.decision.best_metric
    assert best is not None and best <= 2.5, \
        "digits validation error regressed: %s%%" % best


@pytest.mark.slow
def test_mnist_quality_via_full_graph():
    """BASELINE parity: 784-100-10 to the reference's 1.48 % table value
    (manualrst_veles_algorithms.rst:31).  Runs only where the MNIST idx
    files are cached or downloadable (no network in CI)."""
    try:
        mnist_arrays()
    except DatasetNotFound:
        pytest.skip("MNIST dataset unavailable offline")
    import mnist as mnist_example
    from veles_tpu.launcher import Launcher

    launcher = Launcher()
    workflow = mnist_example.build(launcher)
    launcher.initialize(device=os.environ.get("VELES_BACKEND", "cpu"))
    launcher.run()
    best = workflow.decision.best_metric
    # 1.48 is the table value; allow seed variance headroom
    assert best is not None and best <= 1.8, \
        "MNIST validation error %s%% (reference table: 1.48%%)" % best


@pytest.mark.slow
def test_digits_conv_classification_quality(cpu_device):
    """Conv *classification* anchor (round-3 verdict: conv quality was
    pinned only by reconstruction RMSE): digits through the conv/pool
    stack reach the committed QUALITY.json error."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher

    module = importlib.import_module("digits_conv")
    saved = root.digits_conv.max_epochs
    root.digits_conv.max_epochs = 40  # converges ~1.7 % at epoch 36
    try:
        launcher = Launcher()
        wf = module.build(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        best = wf.decision.best_metric
        assert best is not None and best <= 2.5, \
            "digits_conv validation error regressed: %s%%" % best
    finally:
        root.digits_conv.max_epochs = saved


@pytest.mark.slow
def test_mnist_drop_rehearsal(tmp_path, cpu_device):
    """A canonical-shaped MNIST drop starts the parity workflow with
    ZERO code changes (round-3 verdict item 5): synthesize idx files
    with the real shapes (random pixels — quality is meaningless,
    execution is the point), point the datasets dir at them, and run
    the real examples/mnist.py workflow end to end."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.datasets import selfcheck
    from veles_tpu.launcher import Launcher

    _write_mnist_drop(tmp_path, numpy.random.RandomState(0))
    report = selfcheck(str(tmp_path))
    assert report["mnist"]["status"] == "ok"
    # synthetic files are structurally canonical but not THE files
    # (uncompressed names have no published md5 -> canonical None)
    assert all(f["canonical"] is not True
               for f in report["mnist"]["files"].values())

    saved_dir = root.common.dirs.datasets
    module = importlib.import_module("mnist")
    saved_epochs = root.mnist.max_epochs
    root.common.dirs.datasets = str(tmp_path)
    root.mnist.max_epochs = 1
    try:
        launcher = Launcher()
        wf = module.build(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        # random labels: anything finite proves the pipeline ran
        assert wf.decision.best_metric is not None
        assert 0.0 <= wf.decision.best_metric <= 100.0
        assert int(wf.loader.epoch_number) >= 1
    finally:
        root.common.dirs.datasets = saved_dir
        root.mnist.max_epochs = saved_epochs


@pytest.mark.slow
def test_stl10_and_mnist_ae_drop_rehearsal(tmp_path, cpu_device):
    """The dataset-gated parity configs (CIFAR-10 17.21 %, STL-10
    35.10 %, MNIST AE RMSE 0.5478) execute end to end on
    canonical-shaped synthetic drops: one fused eval + train step
    each through the real example workflows."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.loader.base import TRAIN

    rng = numpy.random.RandomState(0)
    _write_stl10_drop(tmp_path, rng)
    _write_mnist_drop(tmp_path, rng)
    _write_cifar10_drop(tmp_path, rng)

    saved_dir = root.common.dirs.datasets
    root.common.dirs.datasets = str(tmp_path)
    try:
        for module_name in ("cifar10", "stl10", "mnist_autoencoder"):
            module = importlib.import_module(module_name)
            from veles_tpu.launcher import Launcher
            launcher = Launcher()
            sw = module.build(launcher)
            sw.fuse()
            sw.initialize(device=cpu_device)
            # one eval dispatch on the first served minibatch, then
            # rehearse the TRAIN program on the same batch (walking
            # the whole 8k-image validation epoch at 96px on CPU
            # would take tens of minutes and prove nothing extra)
            sw.loader.run()
            sw.fused_trainer.run()
            sw.loader.minibatch_class = TRAIN
            sw.fused_trainer.run()
            loss = float(sw.fused_trainer.last_loss)
            assert numpy.isfinite(loss), (module_name, loss)
    finally:
        root.common.dirs.datasets = saved_dir


@pytest.mark.slow
def test_digits_quality_on_real_tpu():
    """On-chip end-to-end proof (round-3 verdict item 2): the FULL
    unit-graph product (loader -> per-unit jitted forwards/GD ->
    decision -> snapshot path) trains to the same quality on the real
    TPU as on CPU.  Subprocess because conftest pins this process to
    the virtual CPU mesh.  Skipped when no TPU is attached."""
    import json
    import subprocess
    import sys

    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "VELES_BACKEND")}
    env["XLA_FLAGS"] = ""  # no virtual-device forcing in the child
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(int(bool(d) and d[0].platform != 'cpu'))"],
            env=env, capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU probe timed out (runtime unresponsive)")
    if probe.returncode != 0 or probe.stdout.strip() != "1":
        pytest.skip("no real TPU attached")

    # run the maintained harness, not a re-implementation: the same
    # path that records QUALITY.json rows (incl. the snapshot-restore
    # proof for digits).  --fuse: one compiled program (~75 s on the
    # tunneled chip) instead of the remote-compile-bound per-unit walk
    out = os.path.join(tempfile.mkdtemp(prefix="quality_tpu_"),
                       "q.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "quality.py"),
         "--backend", "tpu", "--anchors", "digits", "--fuse",
         "--out", out],
        env=env, capture_output=True, text=True, timeout=1800,
        cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.load(open(out))["results_tpu_fused"]["digits"]
    assert row.get("snapshot_restored"), row
    # same bar as the CPU anchor (measured 1.39% on both backends)
    assert row["best_error_pct"] <= 2.5, row


@pytest.mark.slow
def test_autoencoder_reconstructs_digits(cpu_device):
    """Autoencoder quality anchor (reference MNIST AE RMSE 0.5478,
    manualrst_veles_algorithms.rst:69; offline stand-in reconstructs
    the 8x8 digits): the committed QUALITY.json RMSE stays reached."""
    import importlib

    module = importlib.import_module("autoencoder")
    from veles_tpu.launcher import Launcher
    launcher = Launcher()
    workflow = module.build(launcher)
    launcher.initialize(device=cpu_device)
    launcher.run()
    best = workflow.decision.best_metric
    assert best is not None
    # measured 0.1256 on plain CPU; generous headroom for backend and
    # mesh-size numeric drift, still far under the reference MNIST 0.5478
    assert best < 0.2, best


@pytest.mark.slow
def test_lstm_sequence_classification(cpu_device):
    """LSTM over digit-row sequences (the reference shipped RNN/LSTM
    untested; this pins our recurrent training path on real data)."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher

    module = importlib.import_module("sequence")
    saved = root.sequence.max_epochs
    root.sequence.max_epochs = 25
    try:
        launcher = Launcher()
        wf = module.build(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        best = wf.decision.best_metric
        assert best is not None and best < 5.0, best
    finally:
        root.sequence.max_epochs = saved


@pytest.mark.slow
@pytest.mark.transformer
def test_transformer_sequence_classification(cpu_device):
    """Transformer over digit-row sequences (examples/transformer.py):
    the pre-LN block chain + flash-attention path trained end to end
    through the unit graph into the receipted accuracy band (measured
    1.67 % best validation error at 25 epochs — the LSTM anchor's
    band)."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher

    module = importlib.import_module("transformer")
    saved = root.transformer.max_epochs
    root.transformer.max_epochs = 25
    try:
        launcher = Launcher()
        wf = module.build(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        best = wf.decision.best_metric
        assert best is not None and best < 5.0, best
    finally:
        root.transformer.max_epochs = saved


@pytest.mark.slow
def test_conv_autoencoder_reconstructs_digits(cpu_device):
    """Convolutional autoencoder (reference family: conv autoencoders):
    conv encode + deconv decode on real digits, pinned well below the
    MLP autoencoder's RMSE."""
    import importlib

    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher

    module = importlib.import_module("conv_autoencoder")
    saved = root.conv_ae.max_epochs
    root.conv_ae.max_epochs = 15
    try:
        launcher = Launcher()
        wf = module.build(launcher)
        launcher.initialize(device=cpu_device)
        launcher.run()
        best = wf.decision.best_metric
        # 4x spatial bottleneck: measured 0.114 at full epochs
        assert best is not None and best < 0.2, best
    finally:
        root.conv_ae.max_epochs = saved
