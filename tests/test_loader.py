"""Data layer tests (reference test model: veles/tests/test_loader.py,
SURVEY.md section 4): normalizers, minibatch contract, fullbatch device
gather parity across backends, distributed index-window protocol."""

import numpy
import pytest

from veles_tpu.dummy import DummyWorkflow
from veles_tpu.loader import (
    FullBatchLoader, FullBatchLoaderMSE, TEST, VALID, TRAIN)
from veles_tpu.normalization import NormalizerRegistry


# ---------------------------------------------------------------- normalizers

def test_normalizer_registry_knows_all_mappings():
    for name in ("none", "linear", "range_linear", "mean_disp", "exp",
                 "pointwise", "external_mean", "internal_mean"):
        assert name in NormalizerRegistry.normalizers


def test_mean_disp_normalizer_roundtrip():
    n = NormalizerRegistry.get("mean_disp")
    data = numpy.random.RandomState(7).rand(100, 12).astype(numpy.float32)
    n.analyze(data)
    normalized = n.normalize(data.copy())
    assert abs(normalized.mean()) < 0.1
    restored = n.denormalize(normalized.copy())
    assert numpy.allclose(restored, data, atol=1e-5)


def test_range_linear_normalizer_interval():
    n = NormalizerRegistry.get("range_linear", interval=(0, 1))
    data = numpy.random.RandomState(3).rand(50, 4) * 9 - 3
    n.analyze(data)
    out = n.normalize(data.copy())
    assert out.min() >= -1e-9 and out.max() <= 1 + 1e-9
    back = n.denormalize(out.copy())
    assert numpy.allclose(back, data, atol=1e-9)


def test_pointwise_normalizer():
    n = NormalizerRegistry.get("pointwise")
    data = numpy.random.RandomState(5).rand(40, 6) * 10
    n.analyze(data)
    out = n.normalize(data.copy())
    assert out.min() >= -1 - 1e-9 and out.max() <= 1 + 1e-9
    back = n.denormalize(out.copy())
    assert numpy.allclose(back, data, atol=1e-9)


def test_external_mean_normalizer():
    mean = numpy.full(8, 2.0, numpy.float32)
    n = NormalizerRegistry.get("external_mean", mean_source=mean)
    n.analyze(None)
    data = numpy.full((3, 8), 5.0, numpy.float32)
    out = n.normalize(data.copy())
    assert numpy.allclose(out, 3.0)


def test_internal_mean_normalizer():
    n = NormalizerRegistry.get("internal_mean")
    data = numpy.random.RandomState(1).rand(30, 5)
    n.analyze(data)
    out = n.normalize(data.copy())
    assert numpy.allclose(out.mean(axis=0), 0, atol=1e-9)


# ---------------------------------------------------------------- the loader

class SyntheticLoader(FullBatchLoader):
    """10-class blobs: deterministic, learnable; 3-class split."""

    def __init__(self, workflow, n_test=32, n_valid=32, n_train=128,
                 features=16, classes=4, **kwargs):
        self._counts = (n_test, n_valid, n_train)
        self._features = features
        self._classes = classes
        super(SyntheticLoader, self).__init__(workflow, **kwargs)

    def load_data(self):
        self.class_lengths[:] = self._counts
        self._calc_class_end_offsets()
        self.create_originals((self._features,))
        rng = numpy.random.RandomState(42)
        centers = rng.rand(self._classes, self._features) * 4
        for i in range(self.total_samples):
            label = i % self._classes
            self.original_data.mem[i] = (
                centers[label] + rng.randn(self._features) * 0.1)
            self.original_labels[i] = "class%d" % label


def make_loader(device=None, **kwargs):
    from veles_tpu.prng import RandomGenerator
    wf = DummyWorkflow()
    kwargs.setdefault("prng", RandomGenerator("test_loader", seed=1234))
    loader = SyntheticLoader(wf, minibatch_size=32, **kwargs)
    loader.initialize(device=device)
    return loader


def test_loader_initialize_host():
    loader = make_loader(device=None)
    assert loader.total_samples == 192
    assert loader.class_end_offsets == [32, 64, 192]
    assert loader.has_labels
    assert loader.unique_labels_count == 4
    assert loader.minibatch_data.shape == (32, 16)


def test_loader_epoch_iteration_host():
    loader = make_loader(device=None)
    classes_seen = []
    epoch_ended_at = []
    for i in range(6):  # 32/32 + 32/32 + 128/32=4 -> 6 minibatches/epoch
        loader.run()
        classes_seen.append(loader.minibatch_class)
        if bool(loader.epoch_ended):
            epoch_ended_at.append(i)
        assert loader.minibatch_size == 32
    assert classes_seen == [TEST, VALID, TRAIN, TRAIN, TRAIN, TRAIN]
    # reference semantics (loader/base.py:861-869): epoch_ended fires when
    # the VALIDATION class completes (eval done), train_ended after TRAIN
    assert epoch_ended_at == [1]
    assert bool(loader.train_ended)
    assert loader.epoch_number == 1


def test_loader_minibatch_content_matches_indices_host():
    loader = make_loader(device=None)
    loader.run()
    idx = loader.minibatch_indices.mem[:loader.minibatch_size]
    loader.original_data.map_read()
    expected = loader.original_data.mem[idx]
    numpy.testing.assert_allclose(
        loader.minibatch_data.mem[:loader.minibatch_size], expected,
        rtol=1e-6)


def test_loader_device_gather_parity(cpu_device):
    host = make_loader(device=None)
    dev = make_loader(device=cpu_device)
    for _ in range(6):
        host.run()
        dev.run()
        dev.minibatch_data.map_read()
        numpy.testing.assert_allclose(
            dev.minibatch_data.mem[:dev.minibatch_size],
            host.minibatch_data.mem[:host.minibatch_size], rtol=1e-5)
        dev.minibatch_labels.map_read()
        numpy.testing.assert_array_equal(
            dev.minibatch_labels.mem[:dev.minibatch_size],
            host.minibatch_labels.mem[:host.minibatch_size])


def test_loader_train_shuffled_between_epochs():
    loader = make_loader(device=None)
    first = None
    for _ in range(6):
        loader.run()
    first = loader.shuffled_indices.mem[64:].copy()
    for _ in range(6):
        loader.run()
    second = loader.shuffled_indices.mem[64:]
    assert not numpy.array_equal(first, second)
    # test/valid windows never shuffled
    numpy.testing.assert_array_equal(
        loader.shuffled_indices.mem[:64], numpy.arange(64))


def test_loader_normalization_applied_to_originals():
    loader = make_loader(device=None, normalization_type="mean_disp")
    data = loader.original_data.mem
    train = data[loader.class_end_offsets[VALID]:]
    assert abs(train.mean()) < 0.2


# ------------------------------------------------- distributed index protocol

class _FakeSlave(object):
    def __init__(self, sid):
        self.id = sid


def test_master_slave_index_window_protocol():
    master = make_loader(device=None)
    master.workflow.workflow.workflow_mode = "master"
    slave = make_loader(device=None)
    slave.workflow.workflow.workflow_mode = "slave"

    s = _FakeSlave("s1")
    job = master.generate_data_for_slave(s)
    assert job["minibatch_size"] == 32
    assert master.pending_minibatches_count == 1

    slave.apply_data_from_master(job)
    slave.serve_next_minibatch(None)
    numpy.testing.assert_array_equal(
        slave.minibatch_indices.mem[:32], job["indices"])
    # slave filled its minibatch from its local copy of the dataset
    expected = slave.original_data.mem[job["indices"]]
    numpy.testing.assert_allclose(
        slave.minibatch_data.mem[:32], expected, rtol=1e-6)

    master.apply_data_from_slave(True, s)
    assert master.pending_minibatches_count == 0
    assert master.samples_served == 32


def test_drop_slave_requeues_failed_minibatches():
    master = make_loader(device=None)
    master.workflow.workflow.workflow_mode = "master"
    s = _FakeSlave("dead")
    job = master.generate_data_for_slave(s)
    assert master.pending_minibatches_count == 1
    master.drop_slave(s)
    assert master.pending_minibatches_count == 0
    assert len(master.failed_minibatches) == 1
    assert master.total_failed == 1
    # next serve must re-serve the failed window first
    s2 = _FakeSlave("alive")
    job2 = master.generate_data_for_slave(s2)
    assert job2["minibatch_offset"] == job["minibatch_offset"]
    numpy.testing.assert_array_equal(job2["indices"], job["indices"])


def test_pickle_moves_pending_to_failed():
    import pickle
    master = make_loader(device=None)
    master.workflow.workflow.workflow_mode = "master"
    master.generate_data_for_slave(_FakeSlave("s1"))
    state = master.__getstate__()
    assert len(state["failed_minibatches"]) == 1


# ------------------------------------------------------------------- MSE

class SyntheticMSELoader(FullBatchLoaderMSE):
    def load_data(self):
        self.class_lengths[:] = [0, 16, 64]
        self._calc_class_end_offsets()
        self.create_originals((8,), labels=False)
        rng = numpy.random.RandomState(0)
        self.original_data.mem[:] = rng.rand(80, 8)
        self.original_targets.mem = (
            self.original_data.mem @ rng.rand(8, 3)).astype(numpy.float32)


def test_mse_loader_targets(cpu_device):
    wf = DummyWorkflow()
    loader = SyntheticMSELoader(wf, minibatch_size=16)
    loader.initialize(device=cpu_device)
    loader.run()
    loader.minibatch_targets.map_read()
    idx = loader.minibatch_indices.mem[:16]
    loader.original_targets.map_read()
    numpy.testing.assert_allclose(
        loader.minibatch_targets.mem[:16],
        loader.original_targets.mem[idx], rtol=1e-5)
