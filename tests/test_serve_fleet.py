"""Multi-host serve tier (veles_tpu/serve/fleet.py, docs/serving.md
"Multi-host tier"): membership epochs over the pipelined binary link,
throughput-weighted least-loaded routing, request hedging with
first-result-wins bit-identity, the exactly-once duplicate-rejection
fence (chaos ``serve.hedge.lose_race``), host-kill requeue with zero
dropped requests, host-granular cascade-then-503 with the
fleet-minimum ``retry_after``, and the rejoin-re-warm 0-new-compiles
receipt.  Hosts are in-process socketpair adoptions (the ``transport``
marker pattern — tier-1 never binds a real port); the multi-process
SIGKILL soak lives in scripts/fleet_soak.py → HEDGE.json (slow)."""

import socket
import threading
import time

import numpy
import pytest

from veles_tpu import chaos
from veles_tpu.backends import Device
from veles_tpu.observe.metrics import registry
from veles_tpu.serve import (
    AOTEngine, BinaryTransportServer, ContinuousBatcher, FleetRouter,
    ServeOverload, serve_snapshot)
from veles_tpu.serve.batcher import ServeOverload as _Overload
from tests.test_serve import _mlp_spec

pytestmark = [pytest.mark.serve, pytest.mark.fleet]


class _Hosts(object):
    """N in-process serve hosts (engine + batcher + transport server)
    sharing ONE spec, plus socketpair plumbing into a router."""

    def __init__(self, n, plans, params, cache_root=None):
        self.entries = []
        for i in range(n):
            kwargs = {}
            if cache_root is not None:
                kwargs["cache_root"] = cache_root
            engine = AOTEngine(plans, params, (16,), ladder=(8, 32),
                               device=Device(backend="cpu"), **kwargs)
            engine.compile()
            batcher = ContinuousBatcher(engine,
                                        max_delay_s=0.002).start()
            server = BinaryTransportServer(
                batcher, port=None, host_meta={"host_id": "h%d" % i})
            server.start_background()
            self.entries.append([engine, batcher, server])

    def connect(self, router, i):
        ours, theirs = socket.socketpair()
        self.entries[i][2].serve_socket(ours)
        return router.add_host(sock=theirs)

    def stop(self, i=None):
        which = self.entries if i is None else [self.entries[i]]
        for engine, batcher, server in which:
            server.stop()
            batcher.stop()


@pytest.fixture
def fleet():
    """Two-host fleet behind a hedging router, plus the sequential
    reference engine for bit-identity checks."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge_factor=1.5, hedge_floor_s=0.05,
                         hedge_tick_s=0.01).start()
    for i in range(2):
        hosts.connect(router, i)
    yield hosts, router, hosts.entries[0][0]
    router.stop()
    hosts.stop()


def _counter(name):
    metric = registry.counter(name)
    return metric.value


def _wait_for(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError("timed out waiting for " + what)


def test_fleet_routes_bit_identical_with_membership_epochs(fleet):
    """Routed singles and blocks come back bit-identical to the
    sequential engine wherever they land; joins bumped the membership
    epoch once each; the serve_snapshot/web-status block carries the
    fleet keys."""
    hosts, router, engine = fleet
    rng = numpy.random.RandomState(1)
    x = rng.rand(6, 16).astype(numpy.float32)
    ref = engine.infer(x)
    for row, want in zip(x, ref):
        out = router.infer(row, timeout=15.0)
        assert (out == want).all()
    out = router.infer_block(numpy.ascontiguousarray(x), timeout=15.0)
    assert (out == ref).all()
    assert router.fleet.membership_epoch == 2
    snap = router.snapshot()
    assert snap["hosts_live"] == 2
    assert snap["digest"] == engine.digest
    block = serve_snapshot()
    assert block["hosts_live"] == 2
    assert block["fleet_membership_epoch"] == 2
    # routing observed real throughput for at least one host
    assert any(h["throughput_ema"] != 1.0
               for h in snap["hosts"].values())


@pytest.mark.chaos
def test_hedged_first_result_wins_bit_identity(fleet):
    """An induced ``serve.host.stall`` straggler: the hedge fires past
    the threshold, the sibling's result answers the client well under
    the stall, bit-identical to the sequential reference — and the
    loser's cancel means no duplicate ever surfaces."""
    hosts, router, engine = fleet
    rng = numpy.random.RandomState(2)
    x = rng.rand(3, 16).astype(numpy.float32)
    ref = engine.infer(x)
    # seed the hedge_warmup window: a cold router deliberately never
    # hedges (no latency evidence = no threshold worth trusting)
    for i in range(router.hedge_warmup):
        router.infer(x[i % 2], timeout=15.0)
    fired = _counter("serve.hedge.fired")
    wins = _counter("serve.hedge.wins")
    chaos.install(chaos.FaultPlan(seed=1).add(
        "serve.host.stall", "stall", nth=1, param=2.0))
    try:
        t0 = time.perf_counter()
        out = router.infer(x[2], timeout=15.0)
        elapsed = time.perf_counter() - t0
    finally:
        chaos.uninstall()
    assert (out == ref[2]).all()
    assert elapsed < 1.5, \
        "hedge must beat the 2 s straggler (took %.2fs)" % elapsed
    assert _counter("serve.hedge.fired") == fired + 1
    assert _counter("serve.hedge.wins") == wins + 1


@pytest.mark.chaos
def test_lose_race_duplicate_result_rejected(fleet):
    """Chaos ``serve.hedge.lose_race`` skips the loser's wire cancel:
    the losing copy completes, its late result hits the exactly-once
    fence — rejected as a duplicate, the client's answer unchanged."""
    hosts, router, engine = fleet
    rng = numpy.random.RandomState(4)
    x = rng.rand(16).astype(numpy.float32)
    ref = engine.infer(x)
    for _ in range(router.hedge_warmup):  # arm the hedge watchdog
        router.infer(x, timeout=15.0)
    dups = _counter("serve.hedge.duplicates_dropped")
    chaos.install(chaos.FaultPlan(seed=1)
                  .add("serve.host.stall", "stall", nth=1, param=0.4)
                  .add("serve.hedge.lose_race", "skip"))
    try:
        out = router.infer(x, timeout=15.0)
        assert (out == ref[0]).all()
        # the stalled loser finishes ~0.4s later; its result must be
        # dropped at the fence, never re-answer the request
        _wait_for(lambda: _counter("serve.hedge.duplicates_dropped")
                  > dups, what="duplicate rejection")
    finally:
        chaos.uninstall()


@pytest.mark.chaos
def test_host_kill_requeues_in_flight_zero_drops():
    """A host severed mid-stream with requests wedged on it: membership
    epoch bumps, every in-flight request on the dead link is requeued
    to the survivor, and EVERY request completes bit-identical — zero
    failed requests, the tentpole's headline contract."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge=False).start()  # isolate the requeue
    try:
        for i in range(2):
            hosts.connect(router, i)
        rng = numpy.random.RandomState(5)
        x = rng.rand(6, 16).astype(numpy.float32)
        ref = hosts.entries[0][0].infer(x)
        requeues = _counter("serve.fleet.requeues")
        epoch_before = router.fleet.membership_epoch
        # wedge EVERY initial dispatch host-side so the kill lands
        # while the requests are provably in flight
        chaos.install(chaos.FaultPlan(seed=2).add(
            "serve.host.stall", "stall", times=6, param=0.5))
        try:
            reqs = [router.submit(row) for row in x]
            # both hosts hold wedged work; sever host 0 abruptly
            hosts.stop(0)
            for req in reqs:
                assert req.done.wait(20), "request dropped on the floor"
                assert req.error is None, req.error
        finally:
            chaos.uninstall()
        for req, want in zip(reqs, ref):
            assert (req.result == want).all()
        assert router.fleet.membership_epoch == epoch_before + 1
        assert _counter("serve.fleet.requeues") > requeues
        assert router.snapshot()["hosts_live"] == 1
    finally:
        router.stop()
        hosts.stop(1)


def test_cascade_then_503_with_fleet_minimum_retry_after(fleet):
    """Every live host shedding: the fleet sheds ONCE with the
    smallest retry_after any host offered (its best promise), after
    cascading through both."""
    hosts, router, engine = fleet

    def shedding(retry_after):
        def _admit(slo_class=None):
            raise _Overload("test shed", retry_after=retry_after)
        return _admit

    saved = [entry[1]._admit for entry in hosts.entries]
    hosts.entries[0][1]._admit = shedding(0.7)
    hosts.entries[1][1]._admit = shedding(0.3)
    try:
        req = router.submit(numpy.zeros(16, numpy.float32))
        assert req.done.wait(10)
        assert isinstance(req.error, ServeOverload)
        assert req.error.retry_after == pytest.approx(0.3)
    finally:
        for entry, admit in zip(hosts.entries, saved):
            entry[1]._admit = admit
    # the fleet recovered: the same request now serves
    out = router.infer(numpy.zeros(16, numpy.float32), timeout=15.0)
    assert out.shape == (4,)


def test_rejoin_rewarm_zero_new_compiles_receipt(tmp_path):
    """A host restarting against the shared digest-keyed persistent
    cache re-warms with new_compiles == 0, and its rejoin hello
    carries that receipt to the router before it re-enters rotation."""
    plans, params = _mlp_spec(seed=6)
    cache_root = str(tmp_path / "fleet_cache")
    hosts = _Hosts(2, plans, params, cache_root=cache_root)
    router = FleetRouter(hedge=False).start()
    try:
        h0 = hosts.connect(router, 0)
        hosts.connect(router, 1)
        out = router.infer(numpy.zeros(16, numpy.float32),
                           timeout=15.0)
        assert out.shape == (4,)
        # "restart" host 0: same spec, same shared cache directory
        hosts.stop(0)
        _wait_for(lambda: router.snapshot()["hosts_live"] == 1,
                  what="host loss")
        engine = AOTEngine(plans, params, (16,), ladder=(8, 32),
                           device=Device(backend="cpu"),
                           cache_root=cache_root)
        receipt = engine.compile()
        assert receipt["new_compiles"] == 0, \
            "the restart must deserialize its ladder from the cache"
        batcher = ContinuousBatcher(engine, max_delay_s=0.002).start()
        server = BinaryTransportServer(
            batcher, port=None, host_meta={"host_id": "h0"})
        server.start_background()
        hosts.entries[0] = [engine, batcher, server]
        epoch = router.fleet.membership_epoch
        rejoined = hosts.connect(router, 0)
        assert rejoined == h0
        snap = router.snapshot()
        assert snap["hosts"][rejoined]["new_compiles"] == 0, \
            "the rejoin hello must carry the re-warm receipt"
        assert router.fleet.membership_epoch == epoch + 1
        assert snap["hosts_live"] == 2
        out = router.infer(numpy.zeros(16, numpy.float32),
                           timeout=15.0)
        assert out.shape == (4,)
    finally:
        router.stop()
        hosts.stop()


def test_idle_link_keepalive_does_not_retire_healthy_hosts():
    """An idle fleet must not lose its hosts: the reader's socket
    timeout at a frame BOUNDARY is a keepalive ping, not a death —
    several silent keepalive intervals later the membership is
    untouched and the fleet still serves (regression: the first cut
    retired every host after one idle link_timeout)."""
    plans, params = _mlp_spec(seed=3)
    hosts = _Hosts(2, plans, params)
    router = FleetRouter(hedge=False, keepalive_s=0.2).start()
    try:
        for i in range(2):
            hosts.connect(router, i)
        x = numpy.zeros(16, numpy.float32)
        router.infer(x, timeout=15.0)
        epoch = router.fleet.membership_epoch
        time.sleep(1.0)  # ~5 keepalive intervals of silence
        assert router.snapshot()["hosts_live"] == 2
        assert router.fleet.membership_epoch == epoch
        assert router.infer(x, timeout=15.0).shape == (4,)
    finally:
        router.stop()
        hosts.stop()


def test_digest_mismatch_refused(fleet):
    """One fleet serves ONE digest: routed and hedged copies must be
    bit-identical wherever they land, so a host with a different
    architecture is refused at the handshake."""
    hosts, router, engine = fleet
    plans, params = _mlp_spec(seed=9, hidden=8)  # different shapes
    other = _Hosts(1, plans, params)
    try:
        with pytest.raises(ValueError, match="mixed fleet"):
            other.connect(router, 0)
        assert router.snapshot()["hosts_live"] == 2
    finally:
        other.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_soak_sigkill_receipt(tmp_path):
    """Acceptance (ISSUE 15): scripts/fleet_soak.py SIGKILLs a real
    serve-host subprocess mid-stream — zero failed requests, bounded
    p99, membership epochs bumped, every re-answered request
    bit-identical — and the hedging A/B under an induced straggler
    cuts p99.  The committed HEDGE.json is this driver at full size."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "HEDGE.json"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "fleet_soak.py"),
         "--out", str(out), "--fast"],
        cwd=repo, timeout=900, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    receipt = json.loads(out.read_text())
    assert receipt["passed"] is True
    assert receipt["kill"]["failed_requests"] == 0
    assert receipt["kill"]["bit_identical"] is True
    assert receipt["hedge_ab"]["p99_cut_pct"] > 0
