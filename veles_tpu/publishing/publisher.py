"""Publisher unit: gathers a training report and hands it to backends.

Reference veles/publishing/publisher.py collected workflow name, config,
image of the workflow graph, plots, and result metrics, then rendered
through Confluence/Markdown/PDF backends.  The info dict here carries
the same material; Confluence/PDF need network/latex (absent) and are
explicit unsupported-backend errors rather than silent stubs.
"""

import time

from veles_tpu.units import Unit

__all__ = ["Publisher"]


class Publisher(Unit):
    def __init__(self, workflow, **kwargs):
        super(Publisher, self).__init__(workflow, **kwargs)
        self.backends = list(kwargs.get("backends", ()))
        self.plots_dir = kwargs.get("plots_dir")
        self.reports = []

    def gather_info(self):
        sw = self.workflow
        decision = getattr(sw, "decision", None)
        loader = getattr(sw, "loader", None)
        info = {
            "name": type(sw).__name__,
            "checksum": sw.checksum,
            "date": time.strftime("%Y-%m-%d %H:%M:%S"),
            "epochs": getattr(decision, "epoch_number", None),
            "metrics": {
                "test": getattr(decision, "epoch_metrics",
                                [None] * 3)[0],
                "validation": getattr(decision, "epoch_metrics",
                                      [None] * 3)[1],
                "train": getattr(decision, "epoch_metrics",
                                 [None] * 3)[2],
                "best": getattr(decision, "best_metric", None),
            },
            "dataset": {
                "test": loader.class_lengths[0] if loader else 0,
                "validation": loader.class_lengths[1] if loader else 0,
                "train": loader.class_lengths[2] if loader else 0,
            },
            "units": [
                {"name": u.name, "runs": u.run_calls,
                 "time": round(u.timers.get("run", 0.0), 4)}
                for u in sw.units if u is not sw],
            "graph_dot": sw.generate_graph(),
            "plots_dir": self.plots_dir,
        }
        results = sw.gather_results()
        if results:
            info["results"] = results
        return info

    def run(self):
        if self.workflow is not None and \
                self.workflow.workflow_mode == "slave":
            return
        info = self.gather_info()
        for backend in self.backends:
            self.reports.append(backend.render(info))
