"""Publishing backends: Markdown and HTML report writers
(reference backend.py / confluence_backend.py / jinja templates)."""

import json
import os

__all__ = ["MarkdownBackend", "HTMLBackend"]


class BackendBase(object):
    def __init__(self, output_dir):
        self.output_dir = output_dir

    def render(self, info):
        raise NotImplementedError


class MarkdownBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        lines = [
            "# Training report: %s" % info["name"],
            "",
            "- date: %s" % info["date"],
            "- checksum: `%s`" % info["checksum"],
            "- epochs: %s" % info["epochs"],
            "",
            "## Metrics",
            "",
            "| split | value |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train", "best"):
            lines.append("| %s | %s |" % (split,
                                          info["metrics"].get(split)))
        lines += [
            "",
            "## Dataset",
            "",
            "| split | samples |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train"):
            lines.append("| %s | %s |" % (split,
                                          info["dataset"].get(split)))
        lines += ["", "## Unit run times", "",
                  "| unit | runs | seconds |", "|---|---|---|"]
        for u in info["units"]:
            lines.append("| %s | %d | %.4f |" % (u["name"], u["runs"],
                                                 u["time"]))
        if info.get("results"):
            lines += ["", "## Results", "", "```json",
                      json.dumps(info["results"], indent=1,
                                 default=repr),
                      "```"]
        path = os.path.join(self.output_dir, "report.md")
        with open(path, "w") as fout:
            fout.write("\n".join(lines) + "\n")
        return path


class HTMLBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        rows = "".join(
            "<tr><td>%s</td><td>%s</td></tr>" % (k, info["metrics"][k])
            for k in ("test", "validation", "train", "best"))
        units = "".join(
            "<tr><td>%s</td><td>%d</td><td>%.4f</td></tr>" %
            (u["name"], u["runs"], u["time"]) for u in info["units"])
        html = (
            "<html><head><title>%s</title></head><body>"
            "<h1>%s</h1><p>%s — epochs: %s</p>"
            "<h2>Metrics</h2><table border=1>%s</table>"
            "<h2>Units</h2><table border=1>"
            "<tr><th>unit</th><th>runs</th><th>s</th></tr>%s</table>"
            "</body></html>" % (
                info["name"], info["name"], info["date"],
                info["epochs"], rows, units))
        path = os.path.join(self.output_dir, "report.html")
        with open(path, "w") as fout:
            fout.write(html)
        return path
