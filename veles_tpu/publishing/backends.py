"""Publishing backends: Markdown and HTML report writers
(reference backend.py / confluence_backend.py / jinja templates)."""

import json
import os

__all__ = ["MarkdownBackend", "HTMLBackend", "PDFBackend"]


class BackendBase(object):
    def __init__(self, output_dir):
        self.output_dir = output_dir

    def render(self, info):
        raise NotImplementedError


class MarkdownBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        lines = [
            "# Training report: %s" % info["name"],
            "",
            "- date: %s" % info["date"],
            "- checksum: `%s`" % info["checksum"],
            "- epochs: %s" % info["epochs"],
            "",
            "## Metrics",
            "",
            "| split | value |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train", "best"):
            lines.append("| %s | %s |" % (split,
                                          info["metrics"].get(split)))
        lines += [
            "",
            "## Dataset",
            "",
            "| split | samples |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train"):
            lines.append("| %s | %s |" % (split,
                                          info["dataset"].get(split)))
        lines += ["", "## Unit run times", "",
                  "| unit | runs | seconds |", "|---|---|---|"]
        for u in info["units"]:
            lines.append("| %s | %d | %.4f |" % (u["name"], u["runs"],
                                                 u["time"]))
        if info.get("results"):
            lines += ["", "## Results", "", "```json",
                      json.dumps(info["results"], indent=1,
                                 default=repr),
                      "```"]
        path = os.path.join(self.output_dir, "report.md")
        with open(path, "w") as fout:
            fout.write("\n".join(lines) + "\n")
        return path


class HTMLBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        rows = "".join(
            "<tr><td>%s</td><td>%s</td></tr>" % (k, info["metrics"][k])
            for k in ("test", "validation", "train", "best"))
        units = "".join(
            "<tr><td>%s</td><td>%d</td><td>%.4f</td></tr>" %
            (u["name"], u["runs"], u["time"]) for u in info["units"])
        html = (
            "<html><head><title>%s</title></head><body>"
            "<h1>%s</h1><p>%s — epochs: %s</p>"
            "<h2>Metrics</h2><table border=1>%s</table>"
            "<h2>Units</h2><table border=1>"
            "<tr><th>unit</th><th>runs</th><th>s</th></tr>%s</table>"
            "</body></html>" % (
                info["name"], info["name"], info["date"],
                info["epochs"], rows, units))
        path = os.path.join(self.output_dir, "report.html")
        with open(path, "w") as fout:
            fout.write(html)
        return path


class PDFBackend(BackendBase):
    """PDF report via matplotlib's PdfPages (the reference rendered
    PDF through its jinja/confluence stack; matplotlib is already this
    framework's plotting engine)."""

    def render(self, info):
        import matplotlib
        matplotlib.use("Agg", force=False)
        from matplotlib.backends.backend_pdf import PdfPages
        import matplotlib.pyplot as plt

        os.makedirs(self.output_dir, exist_ok=True)
        path = os.path.join(self.output_dir, "report.pdf")
        with PdfPages(path) as pdf:
            fig = plt.figure(figsize=(8.27, 11.69))  # A4
            fig.text(0.5, 0.95, "Training report: %s" % info["name"],
                     ha="center", size=16, weight="bold")
            fig.text(0.1, 0.90, "date: %s" % info["date"], size=10)
            fig.text(0.1, 0.88, "checksum: %s" % info["checksum"],
                     size=8, family="monospace")
            fig.text(0.1, 0.86, "epochs: %s" % info["epochs"], size=10)

            ax = fig.add_axes([0.1, 0.62, 0.8, 0.20])
            ax.axis("off")
            rows = [[split, str(info["metrics"].get(split))]
                    for split in ("test", "validation", "train", "best")]
            table = ax.table(cellText=rows,
                             colLabels=["split", "metric"],
                             loc="center")
            table.scale(1, 1.4)
            ax.set_title("Metrics")

            ax2 = fig.add_axes([0.1, 0.40, 0.8, 0.16])
            ax2.axis("off")
            rows2 = [[split, str(info["dataset"].get(split))]
                     for split in ("test", "validation", "train")]
            ax2.table(cellText=rows2,
                      colLabels=["split", "samples"], loc="center")
            ax2.set_title("Dataset")

            units = info["units"][:20]
            if units:
                ax3 = fig.add_axes([0.1, 0.05, 0.8, 0.30])
                ax3.axis("off")
                rows3 = [[u["name"], str(u["runs"]),
                          "%.4f" % u["time"]] for u in units]
                ax3.table(cellText=rows3,
                          colLabels=["unit", "runs", "seconds"],
                          loc="center")
                ax3.set_title("Unit run times")
            pdf.savefig(fig)
            plt.close(fig)

            plots_dir = info.get("plots_dir")
            if plots_dir and os.path.isdir(plots_dir):
                for fname in sorted(os.listdir(plots_dir)):
                    if not fname.endswith(".png"):
                        continue
                    img = plt.imread(os.path.join(plots_dir, fname))
                    fig = plt.figure(figsize=(8.27, 11.69))
                    ax = fig.add_axes([0.05, 0.05, 0.9, 0.9])
                    ax.imshow(img)
                    ax.axis("off")
                    ax.set_title(fname)
                    pdf.savefig(fig)
                    plt.close(fig)
        return path
